# Dev workflow (≅ the reference's root Makefile role).
SHELL := /bin/bash
.PHONY: test verify native bench smoke trace-smoke tune-smoke mem-smoke \
	serve-smoke replay-smoke overlap-smoke moe-smoke decode-smoke \
	chaos-smoke anatomy-smoke topo-smoke live-smoke fleet-smoke lint \
	lint-smoke protocol-smoke records records-check ci clean

test:
	python -m pytest tests/ -q

# the blessed tier-1 gate, verbatim from ROADMAP.md — builders and CI
# invoke this one entry point instead of hand-copying the command
verify:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

native:
	$(MAKE) -C native

bench:
	python bench.py

# CI-sized bench + entry-point checks on a 4-device CPU mesh
smoke:
	TPU_MPI_BENCH_N=128 TPU_MPI_BENCH_ITERS_SHORT=50 \
	TPU_MPI_BENCH_ITERS_LONG=1050 TPU_MPI_BENCH_FAKE_DEVICES=4 \
	python bench.py
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# timeline-pipeline smoke: a 2-fake-device daxpy run records telemetry
# JSONL and auto-merges it into a Chrome trace on exit; the check
# asserts the trace is non-empty valid JSON with placeable events
trace-smoke:
	rm -f /tmp/_tpumt_trace_smoke*.json*
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.daxpy \
		--fake-devices 2 --n 4096 --telemetry \
		--jsonl /tmp/_tpumt_trace_smoke.jsonl \
		--trace-out /tmp/_tpumt_trace_smoke.trace.json
	python -c "import json; \
		d = json.load(open('/tmp/_tpumt_trace_smoke.trace.json')); \
		evs = [e for e in d['traceEvents'] if e['ph'] != 'M']; \
		assert evs, 'trace has no placeable events'; \
		assert all('ts' in e and 'pid' in e for e in evs); \
		print('trace-smoke OK:', len(evs), 'events')"

# autotuner-pipeline smoke: a 2-fake-device stencil1d sweeps the halo
# schedule space (--staging auto --tune), persists the winner into a
# fresh cache (checked: valid JSON, non-empty), and a second invocation
# resolves as a PURE cache hit — asserted via the JSONL tune records
# (run 1: tune measurements + tune_result; run 2: tune_hit only)
tune-smoke:
	rm -f /tmp/_tpumt_tune_smoke*
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.stencil1d \
		--fake-devices 2 --n-global 65536 --staging auto \
		--tune --tune-cache /tmp/_tpumt_tune_smoke.cache.json \
		--tune-budget 300 \
		--jsonl /tmp/_tpumt_tune_smoke.r1.jsonl
	python -c "import json; \
		d = json.load(open('/tmp/_tpumt_tune_smoke.cache.json')); \
		assert d['version'] == 1 and d['entries'], 'empty cache'; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_tune_smoke.r1.jsonl')]; \
		kinds = [r.get('kind') for r in recs]; \
		assert kinds.count('tune') >= 2, kinds; \
		assert 'tune_result' in kinds, kinds; \
		print('tune-smoke sweep OK:', len(d['entries']), 'entries')"
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.stencil1d \
		--fake-devices 2 --n-global 65536 --staging auto \
		--tune --tune-cache /tmp/_tpumt_tune_smoke.cache.json \
		--jsonl /tmp/_tpumt_tune_smoke.r2.jsonl
	python -c "import json; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_tune_smoke.r2.jsonl')]; \
		kinds = [r.get('kind') for r in recs]; \
		assert 'tune_hit' in kinds, kinds; \
		assert 'tune' not in kinds and 'tune_result' not in kinds, kinds; \
		print('tune-smoke cache-hit OK')"

# memory/compile-observability smoke: a 2-fake-device daxpy with
# --memwatch + --telemetry must (a) record kind:"mem" (census-only on
# CPU — no memory_stats) and kind:"compile" JSONL records, (b) merge
# them into a trace with at least one Perfetto counter track, and
# (c) render non-empty MEMORY and COMPILE tables under tpumt-report
mem-smoke:
	rm -f /tmp/_tpumt_mem_smoke*
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.daxpy \
		--fake-devices 2 --n 4096 --telemetry --memwatch \
		--mem-interval 0.05 \
		--jsonl /tmp/_tpumt_mem_smoke.jsonl \
		--trace-out /tmp/_tpumt_mem_smoke.trace.json
	python -c "import json; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_mem_smoke.jsonl')]; \
		kinds = [r.get('kind') for r in recs]; \
		assert 'mem' in kinds and 'compile' in kinds, kinds; \
		mems = [r for r in recs if r.get('kind') == 'mem']; \
		assert any(r.get('event') == 'phase' for r in mems), mems; \
		d = json.load(open('/tmp/_tpumt_mem_smoke.trace.json')); \
		cs = [e for e in d['traceEvents'] if e['ph'] == 'C']; \
		assert cs, 'no counter track'; \
		print('mem-smoke records OK:', kinds.count('mem'), 'mem,', \
			kinds.count('compile'), 'compile,', len(cs), \
			'counter events')"
	python -m tpu_mpi_tests.instrument.aggregate \
		/tmp/_tpumt_mem_smoke.jsonl > /tmp/_tpumt_mem_smoke.report.txt
	grep -q '^MEM ' /tmp/_tpumt_mem_smoke.report.txt
	grep -q '^COMPILE ' /tmp/_tpumt_mem_smoke.report.txt
	@echo "mem-smoke report OK: MEMORY + COMPILE tables render"

# serving-pipeline smoke: a 2-fake-device open-loop Poisson run (~5 s)
# must (a) emit kind:"serve" JSONL with finite p50/p95/p99 per class,
# (b) render a non-empty SLO table under tpumt-report, (c) place
# serve:<class> request spans on the tpumt-trace timeline, and (d)
# honor the --diff exit contract across two serve runs BOTH ways,
# deterministically: the real run-vs-run diff must exit exactly as its
# own output says (1 iff a REGRESSION line printed — a p99 from ~100
# CPU requests is too tail-noisy to pin the direction in CI), and a
# synthetically degraded copy of run 2 (10x latency, 1/10 throughput)
# must always exit 1
serve-smoke:
	rm -f /tmp/_tpumt_serve_smoke*
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.serve \
		--fake-devices 2 --duration 5 --arrival poisson --rate 30 \
		--seed 7 --report-interval 1 --batch-deadline 120 \
		--workloads daxpy:4096:float32:3,allreduce:1024:float32:1,moe:256x32:float32:2 \
		--telemetry --jsonl /tmp/_tpumt_serve_smoke.r1.jsonl \
		--trace-out /tmp/_tpumt_serve_smoke.trace.json
	python -c "import json, math; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_serve_smoke.r1.jsonl')]; \
		sm = [r for r in recs if r.get('kind') == 'serve' \
			and r.get('event') == 'summary']; \
		assert len(sm) == 3, [r.get('class') for r in sm]; \
		rts = [r for r in recs if r.get('kind') == 'route']; \
		assert rts, 'moe serve traffic must land route records'; \
		assert all(r['requests'] > 0 and \
			math.isfinite(r['p50_ms']) and \
			math.isfinite(r['p95_ms']) and \
			math.isfinite(r['p99_ms']) for r in sm), sm; \
		d = json.load(open('/tmp/_tpumt_serve_smoke.trace.json')); \
		spans = [e for e in d['traceEvents'] if e['ph'] == 'X' \
			and e['name'].startswith('serve:')]; \
		assert spans, 'no serve request spans in trace'; \
		print('serve-smoke records OK:', len(sm), 'classes,', \
			len(spans), 'request spans')"
	python -m tpu_mpi_tests.instrument.aggregate \
		/tmp/_tpumt_serve_smoke.r1.jsonl \
		> /tmp/_tpumt_serve_smoke.report.txt
	grep -q '^SLO daxpy:4096:float32: ' /tmp/_tpumt_serve_smoke.report.txt
	grep -q '^SLO allreduce:1024:float32: ' \
		/tmp/_tpumt_serve_smoke.report.txt
	grep -q '^SLO moe:256x32:float32: ' /tmp/_tpumt_serve_smoke.report.txt
	grep -q '^ROUTE moe: ' /tmp/_tpumt_serve_smoke.report.txt
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.serve \
		--fake-devices 2 --duration 5 --arrival poisson --rate 30 \
		--seed 7 --report-interval 1 --batch-deadline 120 \
		--workloads daxpy:4096:float32:3,allreduce:1024:float32:1,moe:256x32:float32:2 \
		--jsonl /tmp/_tpumt_serve_smoke.r2.jsonl
	python -m tpu_mpi_tests.instrument.aggregate --diff \
		/tmp/_tpumt_serve_smoke.r1.jsonl \
		/tmp/_tpumt_serve_smoke.r2.jsonl \
		> /tmp/_tpumt_serve_smoke.diff.txt; rc=$$?; \
	if grep -q ' REGRESSION' /tmp/_tpumt_serve_smoke.diff.txt; \
		then test $$rc -eq 1; else test $$rc -eq 0; fi
	python -c "import json; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_serve_smoke.r2.jsonl')]; \
		f = open('/tmp/_tpumt_serve_smoke.bad.jsonl', 'w'); \
		[f.write(json.dumps({**r, **({k: r[k] * 10 for k in \
			('p50_ms', 'p95_ms', 'p99_ms') if k in r}), \
			**({'achieved_hz': r['achieved_hz'] / 10} \
			if 'achieved_hz' in r else {})}) + chr(10)) \
			for r in recs if r.get('kind') == 'serve']; \
		f.close()"
	python -m tpu_mpi_tests.instrument.aggregate --diff \
		/tmp/_tpumt_serve_smoke.r1.jsonl \
		/tmp/_tpumt_serve_smoke.bad.jsonl \
		> /tmp/_tpumt_serve_smoke.baddiff.txt; test $$? -eq 1
	grep -q ' REGRESSION' /tmp/_tpumt_serve_smoke.baddiff.txt
	@echo "serve-smoke OK: SLO table + request spans + diff gate"

# request-lifecycle + traffic record/replay smoke (README "Latency
# anatomy & traffic replay"): (a) record a 2-fake-device Poisson run —
# the traffic artifact lands with a fingerprint, the run logs a
# kind:"traffic" record, the SLO table renders the qd99/svc99
# decomposition columns with real values, and the trace carries req
# exemplar spans on the per-rank "requests" thread; (b) replay the
# artifact twice — both replays report the artifact's own fingerprint,
# reproduce identical per-class arrival counts, and their cross-replay
# --diff prints the fingerprints-match line under the serve-smoke rc
# contract (real clocks jitter sub-ms service times; byte-identical
# arrival determinism is pinned with a fake clock in
# tests/test_replay.py); (c) a degraded copy of a replay still trips
# the gate (rc 1); (d) traffic recorded under a different seed refuses
# to diff (rc 2, DIFF ERROR) unless --allow-traffic-mismatch.
replay-smoke:
	rm -f /tmp/_tpumt_replay*
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.serve \
		--fake-devices 2 --duration 4 --arrival poisson --rate 30 \
		--seed 7 --report-interval 1 \
		--workloads daxpy:4096:float32:3,allreduce:1024:float32:1 \
		--telemetry --record /tmp/_tpumt_replay.traffic.json \
		--jsonl /tmp/_tpumt_replay.rec.jsonl \
		--trace-out /tmp/_tpumt_replay.trace.json \
		| tee /tmp/_tpumt_replay.rec.out
	grep -q '^SERVE TRAFFIC recorded: ' /tmp/_tpumt_replay.rec.out
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.serve \
		--fake-devices 2 --replay /tmp/_tpumt_replay.traffic.json \
		--seed 7 --report-interval 1 \
		--workloads daxpy:4096:float32:3,allreduce:1024:float32:1 \
		--jsonl /tmp/_tpumt_replay.r1.jsonl \
		| tee /tmp/_tpumt_replay.r1.out
	grep -q '^SERVE TRAFFIC replayed: ' /tmp/_tpumt_replay.r1.out
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.serve \
		--fake-devices 2 --replay /tmp/_tpumt_replay.traffic.json \
		--seed 7 --report-interval 1 \
		--workloads daxpy:4096:float32:3,allreduce:1024:float32:1 \
		--jsonl /tmp/_tpumt_replay.r2.jsonl
	python -c "import json; \
		art = json.load(open('/tmp/_tpumt_replay.traffic.json')); \
		runs = [[json.loads(l) for l in open(p)] for p in \
			('/tmp/_tpumt_replay.rec.jsonl', \
			 '/tmp/_tpumt_replay.r1.jsonl', \
			 '/tmp/_tpumt_replay.r2.jsonl')]; \
		tr = [[r for r in recs if r.get('kind') == 'traffic'][-1] \
			for recs in runs]; \
		assert all(t['fingerprint'] == art['fingerprint'] \
			for t in tr), tr; \
		assert [t['event'] for t in tr] == \
			['record', 'replay', 'replay'], tr; \
		ns = [sorted((r['class'], r['requests']) for r in recs \
			if r.get('kind') == 'serve' \
			and r.get('event') == 'summary') for recs in runs]; \
		assert ns[1] == ns[2], (ns[1], ns[2]); \
		print('replay-smoke fingerprint OK:', art['fingerprint'], \
			'replayed classes:', ns[1])"
	python -c "import json; \
		d = json.load(open('/tmp/_tpumt_replay.trace.json')); \
		q = [e for e in d['traceEvents'] \
			if e.get('cat') == 'req_queue']; \
		s = [e for e in d['traceEvents'] \
			if e.get('cat') == 'req_service']; \
		m = [e for e in d['traceEvents'] if e.get('ph') == 'M' \
			and e.get('args', {}).get('name') == 'requests']; \
		assert q and s and m, (len(q), len(s), len(m)); \
		print('replay-smoke trace OK:', len(q), 'queue spans,', \
			len(s), 'service spans')"
	python -m tpu_mpi_tests.instrument.aggregate \
		/tmp/_tpumt_replay.rec.jsonl > /tmp/_tpumt_replay.report.txt
	grep -Eq '^SLO daxpy:4096:float32: .*qd99=[0-9.]+ms svc99=[0-9.]+ms' \
		/tmp/_tpumt_replay.report.txt
	grep -q '^TRAFFIC record: fingerprint=' /tmp/_tpumt_replay.report.txt
	python -m tpu_mpi_tests.instrument.aggregate --diff \
		/tmp/_tpumt_replay.r1.jsonl /tmp/_tpumt_replay.r2.jsonl \
		> /tmp/_tpumt_replay.diff.txt; rc=$$?; \
	if grep -q ' REGRESSION' /tmp/_tpumt_replay.diff.txt; \
		then test $$rc -eq 1; else test $$rc -eq 0; fi
	grep -q '^DIFF traffic fingerprints match' /tmp/_tpumt_replay.diff.txt
	python -c "import json; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_replay.r1.jsonl')]; \
		f = open('/tmp/_tpumt_replay.bad.jsonl', 'w'); \
		[f.write(json.dumps({**r, **({k: r[k] * 10 for k in \
			('p50_ms', 'p95_ms', 'p99_ms', 'qd_p99_ms', \
			'svc_p99_ms') if k in r}), \
			**({'achieved_hz': r['achieved_hz'] / 10} \
			if 'achieved_hz' in r else {})}) + chr(10)) \
			for r in recs if r.get('kind') in ('serve', 'traffic')]; \
		f.close()"
	python -m tpu_mpi_tests.instrument.aggregate --diff \
		/tmp/_tpumt_replay.r1.jsonl /tmp/_tpumt_replay.bad.jsonl \
		> /tmp/_tpumt_replay.baddiff.txt; test $$? -eq 1
	grep -q ' REGRESSION' /tmp/_tpumt_replay.baddiff.txt
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.serve \
		--fake-devices 2 --duration 4 --arrival poisson --rate 30 \
		--seed 8 --report-interval 1 \
		--workloads daxpy:4096:float32:3,allreduce:1024:float32:1 \
		--record /tmp/_tpumt_replay.trafficB.json \
		--jsonl /tmp/_tpumt_replay.b.jsonl
	python -m tpu_mpi_tests.instrument.aggregate --diff \
		/tmp/_tpumt_replay.r1.jsonl /tmp/_tpumt_replay.b.jsonl \
		> /tmp/_tpumt_replay.mm.txt 2>&1; test $$? -eq 2
	grep -q 'DIFF ERROR traffic fingerprints differ' \
		/tmp/_tpumt_replay.mm.txt
	python -m tpu_mpi_tests.instrument.aggregate --diff \
		--allow-traffic-mismatch \
		/tmp/_tpumt_replay.r1.jsonl /tmp/_tpumt_replay.b.jsonl \
		> /tmp/_tpumt_replay.mmok.txt; rc=$$?; \
	if grep -q ' REGRESSION' /tmp/_tpumt_replay.mmok.txt; \
		then test $$rc -eq 1; else test $$rc -eq 0; fi
	grep -q '^DIFF NOTE traffic fingerprints differ' \
		/tmp/_tpumt_replay.mmok.txt
	@echo "replay-smoke OK: record/replay fingerprint gate + latency anatomy columns + req spans"

# overlap-engine smoke (README "Overlap engine"): a 2-fake-device
# stencil1d pipeline run at depth 2 must (a) record kind:"overlap" with
# overlap_frac > 0, pass the bitwise seam gate (driver rc 0), and place
# depth-2 async exchange spans on the merged trace; (b) a depth-1 run
# must report overlap_frac exactly 0; (c) tpumt-report must render the
# OVERLAP table for BOTH; and (d) diffing the serialized run against
# the pipelined one must flag the re-serialization
# (overlap:halo:frac REGRESSION, exit 1) — the gate that catches a
# future PR silently de-pipelining the hot path
overlap-smoke:
	rm -f /tmp/_tpumt_ov_smoke*
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.stencil1d \
		--fake-devices 2 --n-global 65536 --overlap 2 \
		--overlap-iters 8 --telemetry \
		--jsonl /tmp/_tpumt_ov_smoke.d2.jsonl \
		--trace-out /tmp/_tpumt_ov_smoke.trace.json
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.stencil1d \
		--fake-devices 2 --n-global 65536 --overlap 1 \
		--overlap-iters 8 --telemetry \
		--jsonl /tmp/_tpumt_ov_smoke.d1.jsonl
	python -c "import json; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_ov_smoke.d2.jsonl')]; \
		ov = [r for r in recs if r.get('kind') == 'overlap']; \
		assert ov and ov[0]['depth'] == 2 \
			and ov[0]['overlap_frac'] > 0, ov; \
		recs1 = [json.loads(l) for l in \
			open('/tmp/_tpumt_ov_smoke.d1.jsonl')]; \
		ov1 = [r for r in recs1 if r.get('kind') == 'overlap']; \
		assert ov1 and ov1[0]['overlap_frac'] == 0.0, ov1; \
		d = json.load(open('/tmp/_tpumt_ov_smoke.trace.json')); \
		spans = [e for e in d['traceEvents'] if e['ph'] == 'X' \
			and e.get('args', {}).get('overlap_depth') == 2]; \
		assert spans, 'no pipelined exchange spans in trace'; \
		print('overlap-smoke records OK:', len(spans), \
			'async spans')"
	python -m tpu_mpi_tests.instrument.aggregate \
		/tmp/_tpumt_ov_smoke.d2.jsonl | grep -q '^OVERLAP halo: depth=2'
	python -m tpu_mpi_tests.instrument.aggregate \
		/tmp/_tpumt_ov_smoke.d1.jsonl \
		| grep -q '^OVERLAP halo: depth=1 frac=0.000'
	python -m tpu_mpi_tests.instrument.aggregate --diff \
		/tmp/_tpumt_ov_smoke.d2.jsonl /tmp/_tpumt_ov_smoke.d1.jsonl \
		> /tmp/_tpumt_ov_smoke.diff.txt; test $$? -eq 1
	grep -q 'overlap:halo:frac.*REGRESSION' /tmp/_tpumt_ov_smoke.diff.txt
	@echo "overlap-smoke OK: frac gate + trace spans + diff gate"

# workload-spec pillar smoke (ISSUE 8): on 2 fake devices the MoE spec
# must route → combine → verify (rc 0) with kind:"route" records whose
# overflow accounting is deterministic, the decode spec must emit
# µs/op latency rows, tpumt-report must render the ROUTE + DECODE +
# WORKLOAD tables, and --diff must gate a synthetically degraded copy
# (overflow % up, decode latency 10x) with exit 1 while the run against
# itself passes clean
moe-smoke:
	rm -f /tmp/_tpumt_moe_smoke*
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.workloads.moe \
		--fake-devices 2 --tokens 512 --d-model 32 --iters 8 \
		--capacity-factor 1.0 --telemetry \
		--jsonl /tmp/_tpumt_moe_smoke.moe.jsonl
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.workloads.decode \
		--fake-devices 2 --batches 1,8 --heads 16 --n-iter 100 \
		--jsonl /tmp/_tpumt_moe_smoke.dec.jsonl
	python -c "import json; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_moe_smoke.moe.jsonl')]; \
		rts = [r for r in recs if r.get('kind') == 'route']; \
		assert rts and all(r['overflow_pct'] > 0 for r in rts), rts; \
		assert len({(r['routed'], r['dropped']) for r in rts}) == 1, \
			'drop accounting must be deterministic across calls'; \
		dec = [json.loads(l) for l in \
			open('/tmp/_tpumt_moe_smoke.dec.jsonl')]; \
		rows = [r for r in dec if r.get('kind') == 'decode']; \
		assert len(rows) == 4 and all(r['us_per_op'] > 0 \
			for r in rows), rows; \
		print('moe-smoke records OK:', len(rts), 'route,', \
			len(rows), 'decode rows')"
	python -m tpu_mpi_tests.instrument.aggregate \
		/tmp/_tpumt_moe_smoke.moe.jsonl /tmp/_tpumt_moe_smoke.dec.jsonl \
		> /tmp/_tpumt_moe_smoke.report.txt
	grep -q '^ROUTE moe: ' /tmp/_tpumt_moe_smoke.report.txt
	grep -q '^DECODE allreduce:1x16: ' /tmp/_tpumt_moe_smoke.report.txt
	grep -q '^WORKLOAD moe:us_per_step: ' /tmp/_tpumt_moe_smoke.report.txt
	cat /tmp/_tpumt_moe_smoke.moe.jsonl /tmp/_tpumt_moe_smoke.dec.jsonl \
		> /tmp/_tpumt_moe_smoke.all.jsonl
	python -m tpu_mpi_tests.instrument.aggregate --diff \
		/tmp/_tpumt_moe_smoke.all.jsonl /tmp/_tpumt_moe_smoke.all.jsonl \
		> /dev/null
	python -c "import json; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_moe_smoke.all.jsonl')]; \
		f = open('/tmp/_tpumt_moe_smoke.bad.jsonl', 'w'); \
		[f.write(json.dumps({**r, \
			**({'overflow_pct': r['overflow_pct'] * 2 + 10} \
				if r.get('kind') == 'route' else {}), \
			**({'us_per_op': r['us_per_op'] * 10} \
				if r.get('kind') == 'decode' else {}), \
			**({'value': r['value'] * 10} \
				if r.get('kind') == 'workload' else {})}) \
			+ chr(10)) for r in recs]; \
		f.close()"
	python -m tpu_mpi_tests.instrument.aggregate --diff \
		/tmp/_tpumt_moe_smoke.all.jsonl /tmp/_tpumt_moe_smoke.bad.jsonl \
		> /tmp/_tpumt_moe_smoke.diff.txt; test $$? -eq 1
	grep -q 'route:moe:overflow_pct.*REGRESSION' \
		/tmp/_tpumt_moe_smoke.diff.txt
	grep -q 'decode:allreduce:1x16:us_per_op.*REGRESSION' \
		/tmp/_tpumt_moe_smoke.diff.txt
	@echo "moe-smoke OK: route + decode rows + ROUTE table + diff gate"

# decode-tier smoke (ISSUE 19): the fixed-cost collective tier, end to
# end on 2 fake CPU devices (the Pallas kernels execute in interpret
# mode on this backend). Leg 1 — the sweeper prices the tier: a --tune
# collbench run at a decode-class payload (1 KiB/shard) must record
# per-candidate tune records with the one-shot tier MEASURED (its
# pad-to-tile wrapper prices at every payload, where the rdma twin
# records its lane-floor error), persist the winner, and a re-run must
# resolve as a PURE cache hit (tune_hit records only). Leg 2 — the
# decode rows consume the SAME schedule: a decode run over the same
# payload must stamp the cached winner into its DECODE [variant] rows
# and records, and tpumt-report must render the DECODE table. Leg 3 —
# --diff must gate a degraded copy (10x us/op) with exit 1 naming the
# decode series.
decode-smoke:
	rm -f /tmp/_tpumt_dec_smoke*
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.collbench \
		--fake-devices 2 --collectives auto --sizes-kib 1 \
		--n-iter 20 --tune \
		--tune-cache /tmp/_tpumt_dec_smoke.cache.json \
		--jsonl /tmp/_tpumt_dec_smoke.sweep.jsonl
	python -c "import json; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_dec_smoke.sweep.jsonl')]; \
		tune = [r for r in recs if r.get('kind') == 'tune' \
			and r.get('knob') == 'coll_variant/allreduce']; \
		cands = {t['candidate'] for t in tune}; \
		assert cands == {'xla', 'rdma', 'oneshot'}, cands; \
		one = [t for t in tune if t['candidate'] == 'oneshot']; \
		assert one and all('seconds' in t for t in one), one; \
		res = [r for r in recs if r.get('kind') == 'tune_result' \
			and r.get('knob') == 'coll_variant/allreduce']; \
		assert len(res) == 1, res; \
		d = json.load(open('/tmp/_tpumt_dec_smoke.cache.json')); \
		assert d['entries'], 'empty cache'; \
		print('decode-smoke sweep OK: oneshot priced, winner', \
			res[0]['value'])"
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.collbench \
		--fake-devices 2 --collectives auto --sizes-kib 1 \
		--n-iter 20 --tune \
		--tune-cache /tmp/_tpumt_dec_smoke.cache.json \
		--jsonl /tmp/_tpumt_dec_smoke.hit.jsonl
	python -c "import json; \
		kinds = [json.loads(l).get('kind') for l in \
			open('/tmp/_tpumt_dec_smoke.hit.jsonl')]; \
		assert 'tune_hit' in kinds, kinds; \
		assert 'tune' not in kinds and 'tune_result' not in kinds, kinds; \
		print('decode-smoke cache-hit OK')"
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.workloads.decode \
		--fake-devices 2 --batches 16 --heads 16 --n-iter 100 \
		--colls allreduce \
		--tune-cache /tmp/_tpumt_dec_smoke.cache.json \
		--jsonl /tmp/_tpumt_dec_smoke.dec.jsonl
	python -c "import json; \
		sweep = [json.loads(l) for l in \
			open('/tmp/_tpumt_dec_smoke.sweep.jsonl')]; \
		win = [r for r in sweep if r.get('kind') == 'tune_result' \
			and r.get('knob') == 'coll_variant/allreduce'][0]['value']; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_dec_smoke.dec.jsonl')]; \
		dec = [r for r in recs if r.get('kind') == 'decode']; \
		assert len(dec) == 1, dec; \
		assert dec[0]['variant'] == win, (dec[0]['variant'], win); \
		assert dec[0]['shard_bytes'] == 1024, dec; \
		print('decode-smoke rows OK: DECODE stamped with the swept', \
			win, 'schedule')"
	python -m tpu_mpi_tests.instrument.aggregate \
		/tmp/_tpumt_dec_smoke.dec.jsonl > /tmp/_tpumt_dec_smoke.report.txt
	grep -q '^DECODE allreduce:16x16: ' /tmp/_tpumt_dec_smoke.report.txt
	python -c "import json; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_dec_smoke.dec.jsonl')]; \
		f = open('/tmp/_tpumt_dec_smoke.bad.jsonl', 'w'); \
		[f.write(json.dumps({**r, **({'us_per_op': r['us_per_op'] * 10} \
			if r.get('kind') == 'decode' else {})}) + chr(10)) \
			for r in recs]; \
		f.close()"
	python -m tpu_mpi_tests.instrument.aggregate --diff \
		/tmp/_tpumt_dec_smoke.dec.jsonl /tmp/_tpumt_dec_smoke.bad.jsonl \
		> /tmp/_tpumt_dec_smoke.diff.txt; test $$? -eq 1
	grep -q 'decode:allreduce:16x16:us_per_op.*REGRESSION' \
		/tmp/_tpumt_dec_smoke.diff.txt
	@echo "decode-smoke OK: sweep prices the one-shot tier + DECODE rows carry the winner + cache hit + diff gate"

# chaos-verified diagnosis smoke (README "Chaos & diagnosis"): inject
# every fault class — kill, straggler, wedge, OOM ramp, serve flood —
# and assert tpumt-doctor convicts the right CLASS and the right RANK
# from the organic telemetry alone (--expect = exactly-one-finding
# contract), while a clean run yields zero findings. The flood runs
# twice: bounded queue → shed_storm (the verdict once load drops), and
# unbounded queue → queue_ramp (the early warning BEFORE any shed) —
# the ramp run is recorded, replayed without chaos armed, and the
# ONLINE doctor (--follow) convicts the replayed storm mid-run. Multi-rank legs
# run real separate processes under the native launcher with a
# local-compute workload (this image's CPU backend has no
# cross-process collectives — the multiproc test family documents
# that); the kill leg's survivor exits via os._exit to skip the
# dead-peer distributed-shutdown barrier (~100 s heartbeat timeout).
# The disarmed-identity half of the acceptance contract (a run without
# chaos armed is byte-identical to a build without the chaos layer)
# is pinned by tests/test_chaos.py.
chaos-smoke:
	rm -f /tmp/_tpumt_chaos*
	$(MAKE) -C native tpumt_run
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.stencil1d \
		--fake-devices 2 --n-global 65536 --telemetry --memwatch \
		--mem-interval 0.05 --jsonl /tmp/_tpumt_chaos.clean.jsonl
	python -m tpu_mpi_tests.instrument.diagnose \
		/tmp/_tpumt_chaos.clean.jsonl | grep -q '^DOCTOR OK'
	python -c "import json; \
		ks = [json.loads(l).get('kind') for l in \
			open('/tmp/_tpumt_chaos.clean.jsonl')]; \
		assert 'chaos' not in ks, 'disarmed run must emit no chaos records'"
	env JAX_PLATFORMS=cpu \
		TPU_MPI_CHAOS="wedge:op=halo_exchange:after=3:stall_s=60" \
		python -m tpu_mpi_tests.drivers.stencil1d --fake-devices 2 \
		--n-global 65536 --overlap 1 --overlap-iters 12 --telemetry \
		--deadline 6 --jsonl /tmp/_tpumt_chaos.wedge.jsonl; \
		test $$? -eq 9
	python -m tpu_mpi_tests.instrument.diagnose \
		/tmp/_tpumt_chaos.wedge.jsonl --expect wedge:0
	env JAX_PLATFORMS=cpu \
		TPU_MPI_CHAOS="oom:step_mb=8:limit_mb=48:frac=0.8" \
		python -m tpu_mpi_tests.drivers.daxpy --fake-devices 2 \
		--n 1048576 --iters 20 --telemetry --memwatch \
		--mem-interval 0.05 \
		--jsonl /tmp/_tpumt_chaos.oom.jsonl; test $$? -eq 134
	python -m tpu_mpi_tests.instrument.diagnose \
		/tmp/_tpumt_chaos.oom.jsonl --expect oom:0
	env JAX_PLATFORMS=cpu \
		TPU_MPI_CHAOS="kill:rank=1:phase=kernel:after=10" \
		./native/tpumt_run -n 2 -o /tmp/_tpumt_chaos.kill.rank -- \
		python -c "import sys, os; \
			from tpu_mpi_tests.workloads.daxpy import main; \
			rc = main(sys.argv[1:]); \
			sys.stdout.flush(); sys.stderr.flush(); os._exit(rc)" \
		--fake-devices 1 --n 8388608 --iters 150 --telemetry \
		--memwatch --mem-interval 0.05 \
		--jsonl /tmp/_tpumt_chaos.kill.jsonl; test $$? -eq 137
	python -m tpu_mpi_tests.instrument.diagnose \
		/tmp/_tpumt_chaos.kill.jsonl --expect missing_rank:1
	env JAX_PLATFORMS=cpu \
		TPU_MPI_CHAOS="straggler:rank=1:delay_ms=25" \
		./native/tpumt_run -n 2 -o /tmp/_tpumt_chaos.strag.rank -- \
		python -m tpu_mpi_tests.drivers.daxpy --fake-devices 1 \
		--n 1048576 --iters 40 --telemetry --memwatch \
		--mem-interval 0.05 --jsonl /tmp/_tpumt_chaos.strag.jsonl
	python -m tpu_mpi_tests.instrument.diagnose \
		/tmp/_tpumt_chaos.strag.jsonl --expect straggler:1
	env JAX_PLATFORMS=cpu TPU_MPI_CHAOS="flood:burst=300:after=1" \
		python -m tpu_mpi_tests.drivers.serve --fake-devices 2 \
		--duration 4 --arrival poisson --rate 20 --seed 7 \
		--report-interval 1 --max-queue 32 \
		--workloads daxpy:4096:float32 --telemetry \
		--jsonl /tmp/_tpumt_chaos.flood.jsonl; test $$? -eq 1
	python -m tpu_mpi_tests.instrument.diagnose \
		/tmp/_tpumt_chaos.flood.jsonl --expect shed_storm:0
	env JAX_PLATFORMS=cpu TPU_MPI_CHAOS="flood:burst=4000:after=1" \
		python -m tpu_mpi_tests.drivers.serve --fake-devices 2 \
		--duration 4 --arrival poisson --rate 20 --seed 7 \
		--report-interval 0.5 --max-queue 100000 --max-batch 2 \
		--workloads daxpy:1048576:float32 \
		--record /tmp/_tpumt_chaos.ramp.traffic.json \
		--jsonl /tmp/_tpumt_chaos.ramp.jsonl
	python -m tpu_mpi_tests.instrument.diagnose \
		/tmp/_tpumt_chaos.ramp.jsonl --expect queue_ramp:0
	( env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.serve \
		--fake-devices 2 \
		--replay /tmp/_tpumt_chaos.ramp.traffic.json \
		--seed 7 --report-interval 0.5 --max-queue 100000 \
		--max-batch 2 --workloads daxpy:1048576:float32 \
		--jsonl /tmp/_tpumt_chaos.ramp2.jsonl \
		> /tmp/_tpumt_chaos.ramp2.out 2>&1 ) & pid=$$!; \
	sleep 1; python -m tpu_mpi_tests.instrument.diagnose \
		/tmp/_tpumt_chaos.ramp2.jsonl --follow --timeout 120 \
		--expect queue_ramp:0 | tee /tmp/_tpumt_chaos.ramp2.doc; \
	rc=$${PIPESTATUS[0]}; wait $$pid; test $$rc -eq 0
	grep -q '(live, ' /tmp/_tpumt_chaos.ramp2.doc
	python -c "import json; \
		sm = [json.loads(l) for l in \
			open('/tmp/_tpumt_chaos.ramp2.jsonl')]; \
		sm = [r for r in sm if r.get('kind') == 'serve' \
			and r.get('event') == 'summary']; \
		assert sm and all(r['shed'] == 0 for r in sm), sm; \
		print('queue_ramp convicted with zero sheds: the ramp is', \
			'the warning before the storm')"
	python -m tpu_mpi_tests.instrument.aggregate \
		/tmp/_tpumt_chaos.kill.jsonl > /tmp/_tpumt_chaos.report.txt
	grep -q '^DIAGNOSIS missing_rank: rank=1' /tmp/_tpumt_chaos.report.txt
	python -m tpu_mpi_tests.instrument.timeline \
		/tmp/_tpumt_chaos.kill.jsonl -o /tmp/_tpumt_chaos.trace.json
	python -c "import json; \
		d = json.load(open('/tmp/_tpumt_chaos.trace.json')); \
		f = [e for e in d['traceEvents'] \
			if e.get('cat') == 'finding']; \
		assert f and f[0]['pid'] == 1, f; \
		print('chaos-smoke trace FINDING marker OK')"
	@echo "chaos-smoke OK: 6 fault classes convicted (class+rank), clean run silent"

# communication-anatomy smoke (README "Communication anatomy"): over
# two REAL native-launcher processes, (a) an injected per-op chaos
# straggler must be convicted by the wait/wire decomposition — the
# ANATOMY table charges >50% of the victim op's span time to wait with
# the culprit rank alone atop the wait-share ranking, and tpumt-doctor
# cites the per-call anatomy evidence (matched-seq wait attribution +
# the culprit's worst late entry, file:line); (b) the Perfetto export
# carries the wait/wire sub-spans and the rank-pair traffic counter
# track; (c) the same command WITHOUT chaos stays near the honesty
# floor (organic skew below clock-sync uncertainty is reported
# unresolved, not fabricated); (d) --diff: a self-diff over anatomy:*
# series is clean, and clean-vs-straggler exits 1 naming
# anatomy:halo_exchange:wait_frac as the regressed series.
anatomy-smoke:
	rm -f /tmp/_tpumt_anat*
	$(MAKE) -C native tpumt_run
	env JAX_PLATFORMS=cpu \
		TPU_MPI_CHAOS="straggler:rank=1:op=halo_exchange:delay_ms=80" \
		./native/tpumt_run -n 2 -o /tmp/_tpumt_anat.strag.rank -- \
		python -m tpu_mpi_tests.drivers.stencil1d --fake-devices 1 \
		--n-global 65536 --dtype float64 --overlap 1 \
		--overlap-iters 8 --telemetry \
		--jsonl /tmp/_tpumt_anat.strag.jsonl
	python -m tpu_mpi_tests.instrument.aggregate \
		/tmp/_tpumt_anat.strag.jsonl > /tmp/_tpumt_anat.report.txt
	grep -q '^ANATOMY halo_exchange: ' /tmp/_tpumt_anat.report.txt
	grep -q '^COMMGRAPH 0->1: bytes=' /tmp/_tpumt_anat.report.txt
	grep -q '^COMMGRAPH 1->0: bytes=' /tmp/_tpumt_anat.report.txt
	python -m tpu_mpi_tests.instrument.aggregate --json \
		/tmp/_tpumt_anat.strag.jsonl > /tmp/_tpumt_anat.strag.sum.json
	python -c "import json; \
		a = json.load(open('/tmp/_tpumt_anat.strag.sum.json'))['anatomy']; \
		op = a['ops']['halo_exchange']; \
		assert op['wait_frac'] > 0.5, op; \
		assert op['wait_share'][0][0] == 1, op['wait_share']; \
		assert op['unmatched'] == 0, op; \
		print('anatomy-smoke: straggler wait_frac', \
			round(op['wait_frac'], 3), '-> culprit r1,', \
			op['calls'], 'matched calls')"
	python -m tpu_mpi_tests.instrument.diagnose \
		/tmp/_tpumt_anat.strag.jsonl --expect straggler:1 \
		> /tmp/_tpumt_anat.doc.txt
	grep -q 'anatomy: rank 1 held' /tmp/_tpumt_anat.doc.txt
	grep -q 'evidence: .*span halo_exchange seq=' /tmp/_tpumt_anat.doc.txt
	python -m tpu_mpi_tests.instrument.timeline \
		/tmp/_tpumt_anat.strag.jsonl -o /tmp/_tpumt_anat.trace.json
	python -c "import json; \
		d = json.load(open('/tmp/_tpumt_anat.trace.json')); \
		w = [e for e in d['traceEvents'] \
			if e.get('cat') == 'comm_wait']; \
		t = [e for e in d['traceEvents'] \
			if e.get('cat') == 'traffic']; \
		assert w and t, (len(w), len(t)); \
		print('anatomy-smoke trace:', len(w), 'wait sub-spans,', \
			len(t), 'traffic counter samples')"
	env JAX_PLATFORMS=cpu \
		./native/tpumt_run -n 2 -o /tmp/_tpumt_anat.clean.rank -- \
		python -m tpu_mpi_tests.drivers.stencil1d --fake-devices 1 \
		--n-global 65536 --dtype float64 --overlap 1 \
		--overlap-iters 8 --telemetry \
		--jsonl /tmp/_tpumt_anat.clean.jsonl
	python -m tpu_mpi_tests.instrument.aggregate --json \
		/tmp/_tpumt_anat.clean.jsonl > /tmp/_tpumt_anat.clean.sum.json
	python -c "import json; \
		a = json.load(open('/tmp/_tpumt_anat.clean.sum.json'))['anatomy']; \
		op = a['ops']['halo_exchange']; \
		assert op['wait_frac'] < 0.25, op; \
		print('anatomy-smoke: clean wait_frac', \
			round(op['wait_frac'], 3), \
			'(', op['unresolved'], 'of', op['calls'], \
			'below the clock-sync floor -> unresolved )')"
	python -m tpu_mpi_tests.instrument.aggregate --diff \
		/tmp/_tpumt_anat.clean.jsonl /tmp/_tpumt_anat.clean.jsonl \
		> /tmp/_tpumt_anat.selfdiff.txt
	python -m tpu_mpi_tests.instrument.aggregate --diff \
		/tmp/_tpumt_anat.clean.jsonl /tmp/_tpumt_anat.strag.jsonl \
		> /tmp/_tpumt_anat.diff.txt; test $$? -eq 1
	grep -q 'anatomy:halo_exchange:wait_frac.* REGRESSION' \
		/tmp/_tpumt_anat.diff.txt
	@echo "anatomy-smoke OK: wait/wire convicts the injected straggler, clean run holds the honesty floor, diff names the series"

# topology-observability smoke (README "Topology observability"): two
# REAL native-launcher processes form a discovered h2x1 topology (one
# rank per jax process — every cross-rank pair is inter_host), so
# (a) each rank's JSONL carries the kind:"topo" audit record and its
# comm spans the wrapper-build link/partner_link stamps; (b) the
# report renders the TOPOLOGY shape + per-link-class GB/s tables, the
# per-op ANATOMY [inter_host] split rows, the COMMGRAPH link suffix,
# and the hosts= header; (c) the Perfetto export carries the per-link
# "comm bytes by link" counter track and span link args; (d) the pack
# shape gate: importing a pack tuned on h2x4 into a cache holding
# flat-machine entries refuses (exit 3, NOTE names both shapes — no
# schedule could ever resolve) and --allow-topology-mismatch
# overrides.
topo-smoke:
	rm -f /tmp/_tpumt_topo*
	$(MAKE) -C native tpumt_run
	env JAX_PLATFORMS=cpu \
		./native/tpumt_run -n 2 -o /tmp/_tpumt_topo.rank -- \
		python -m tpu_mpi_tests.drivers.stencil1d --fake-devices 1 \
		--n-global 65536 --dtype float64 --overlap 1 \
		--overlap-iters 8 --telemetry \
		--jsonl /tmp/_tpumt_topo.jsonl
	grep -q '"kind": "topo".*"topology": "h2x1"' /tmp/_tpumt_topo.p0.jsonl
	grep -q '"link": "inter_host"' /tmp/_tpumt_topo.p0.jsonl
	grep -q '"partner_link": \["inter_host", "inter_host"\]' \
		/tmp/_tpumt_topo.p0.jsonl
	python -m tpu_mpi_tests.instrument.aggregate \
		/tmp/_tpumt_topo.p0.jsonl /tmp/_tpumt_topo.p1.jsonl \
		> /tmp/_tpumt_topo.report.txt
	grep -q '^RUN .*hosts=2x1' /tmp/_tpumt_topo.report.txt
	grep -q '^TOPOLOGY h2x1: world=2 hosts=2x1.*links=inter_host' \
		/tmp/_tpumt_topo.report.txt
	grep -q '^TOPOLOGY inter_host: calls=.*GB/s' \
		/tmp/_tpumt_topo.report.txt
	grep -q '^ANATOMY halo_exchange\[inter_host\]: ' \
		/tmp/_tpumt_topo.report.txt
	grep -q '^COMMGRAPH 0->1: bytes=.*link=inter_host' \
		/tmp/_tpumt_topo.report.txt
	python -m tpu_mpi_tests.instrument.timeline \
		/tmp/_tpumt_topo.p0.jsonl /tmp/_tpumt_topo.p1.jsonl \
		-o /tmp/_tpumt_topo.trace.json
	python -c "import json; \
		d = json.load(open('/tmp/_tpumt_topo.trace.json')); \
		cnt = [e for e in d['traceEvents'] if e.get('ph') == 'C' \
			and e['name'] == 'comm bytes by link']; \
		assert cnt and all(e['cat'] == 'traffic' for e in cnt), cnt; \
		assert all(set(e['args']) == {'inter_host'} for e in cnt); \
		sp = [e for e in d['traceEvents'] if e.get('ph') == 'X' \
			and e.get('args', {}).get('link') == 'inter_host']; \
		assert sp, 'no link-stamped spans in trace'; \
		print('topo-smoke trace:', len(cnt), 'link counter samples,', \
			len(sp), 'link-stamped spans')"
	python -c "import json; \
		from tpu_mpi_tests.tune import pack as tp; \
		from tpu_mpi_tests.tune.cache import ScheduleCache; \
		fp = 'device=v5e;hosts=2;platform=tpu;rph=4'; \
		doc = tp.make_pack({'demo/k|' + fp: {'value': 7, \
			'seconds': 0.1, 'knob': 'demo/k', 'fingerprint': fp, \
			't': 100.0}}); \
		open('/tmp/_tpumt_topo.pack.json', 'w').write( \
			json.dumps(doc)); \
		c = ScheduleCache.load('/tmp/_tpumt_topo.cache.json'); \
		c.store('demo/k', 'device=v5e;platform=tpu', 1, seconds=0.1); \
		c.save(); \
		print('topo-smoke: h2x4 pack vs flat cache staged')"
	python -m tpu_mpi_tests.tune.pack import \
		/tmp/_tpumt_topo.pack.json \
		--cache /tmp/_tpumt_topo.cache.json \
		> /tmp/_tpumt_topo.imp.txt; test $$? -eq 3
	grep -q 'NOTE topology mismatch: pack measured on h2x4' \
		/tmp/_tpumt_topo.imp.txt
	python -m tpu_mpi_tests.tune.pack import \
		/tmp/_tpumt_topo.pack.json \
		--cache /tmp/_tpumt_topo.cache.json \
		--allow-topology-mismatch > /dev/null
	@echo "topo-smoke OK: h2x1 discovered, link-class tables + trace counters rendered, mismatched pack import refused"

# live-observability smoke (README "Live observability"): (a) a serve
# run armed with --metrics-port must expose well-formed OpenMetrics at
# /metrics MID-RUN (curl'd while the loop serves) with nonzero serve
# counters, and leave the health heartbeat trail (incl. the final
# marker) in its JSONL; (b) tpumt-top renders a frame from the
# finished stream; (c) under an injected chaos straggler across two
# real processes, tpumt-doctor --follow must convict straggler:1 WHILE
# the ensemble is still executing (doctor exits 0, then kill -0 proves
# the run was still alive) and the post-mortem doctor over the SAME
# organic stream must agree — the online/offline shared-kernel
# contract, byte-level-pinned in tests/test_live.py.
live-smoke:
	rm -f /tmp/_tpumt_live*
	$(MAKE) -C native tpumt_run
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.serve \
		--fake-devices 2 --duration 10 --arrival poisson --rate 30 \
		--seed 7 --report-interval 1 --batch-deadline 120 \
		--workloads daxpy:4096:float32 \
		--metrics-port 0 --metrics-interval 0.25 \
		--jsonl /tmp/_tpumt_live.serve.jsonl \
		> /tmp/_tpumt_live.serve.out 2>&1 & \
	SERVE_PID=$$!; \
	ok=1; \
	for i in $$(seq 1 160); do \
		PORT=$$(sed -n \
			's#.*OpenMetrics at http://0.0.0.0:\([0-9]*\)/metrics.*#\1#p' \
			/tmp/_tpumt_live.serve.out 2>/dev/null | head -1); \
		if [ -n "$$PORT" ] \
		&& curl -sf http://127.0.0.1:$$PORT/metrics \
			-o /tmp/_tpumt_live.metrics.txt 2>/dev/null \
		&& awk '$$1 ~ /^tpumt_serve_requests_total/ \
			{ if ($$2+0 > 0) found=1 } END { exit !found }' \
			/tmp/_tpumt_live.metrics.txt; \
		then ok=0; break; fi; sleep 0.25; done; \
	wait $$SERVE_PID; test $$ok -eq 0
	python -c "import re; \
		lines = open('/tmp/_tpumt_live.metrics.txt').read() \
			.strip().splitlines(); \
		assert lines[-1] == '# EOF', lines[-3:]; \
		bad = [l for l in lines if not l.startswith('#') and not \
			re.match(r'^[A-Za-z_:][A-Za-z0-9_:]*(\{[^{}]*\})? \S+$$', \
			l)]; \
		assert not bad, bad; \
		assert any(l.startswith('# TYPE tpumt_serve_requests counter') \
			for l in lines), 'missing TYPE line'; \
		print('live-smoke exporter OK:', len(lines), \
			'well-formed OpenMetrics lines mid-run')"
	python -c "import json; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_live.serve.jsonl')]; \
		hb = [r for r in recs if r.get('kind') == 'health' \
			and r.get('event') == 'heartbeat']; \
		assert hb and hb[-1].get('final'), 'no heartbeat trail'; \
		assert any('queue_depth' in r for r in recs \
			if r.get('kind') == 'serve' \
			and r.get('event') == 'window'), 'no live queue depth'; \
		print('live-smoke heartbeats OK:', len(hb), 'beats')"
	python -m tpu_mpi_tests.instrument.live \
		/tmp/_tpumt_live.serve.jsonl > /tmp/_tpumt_live.top.txt
	grep -q 'daxpy:4096:float32' /tmp/_tpumt_live.top.txt
	grep -q '^BEAT ' /tmp/_tpumt_live.top.txt
	env JAX_PLATFORMS=cpu \
		TPU_MPI_CHAOS="straggler:rank=1:delay_ms=25" \
		./native/tpumt_run -n 2 -o /tmp/_tpumt_live.rank -- \
		python -m tpu_mpi_tests.drivers.daxpy --fake-devices 1 \
		--n 1048576 --iters 200 --metrics-port 0 \
		--metrics-interval 0.2 \
		--jsonl /tmp/_tpumt_live.strag.jsonl & \
	RUN_PID=$$!; \
	python -m tpu_mpi_tests.instrument.diagnose \
		/tmp/_tpumt_live.strag.jsonl --follow \
		--expect straggler:1 --interval 0.3 --timeout 120; DRC=$$?; \
	if kill -0 $$RUN_PID 2>/dev/null; then ALIVE=0; else ALIVE=1; fi; \
	wait $$RUN_PID; \
	test $$DRC -eq 0 && test $$ALIVE -eq 0
	python -m tpu_mpi_tests.instrument.diagnose \
		/tmp/_tpumt_live.strag.jsonl --expect straggler:1
	@echo "live-smoke OK: mid-run OpenMetrics + heartbeat trail + tpumt-top frame + online straggler conviction"

# fleet-tuning smoke (README "Fleet tuning"): the ISSUE-14 closed loop,
# end to end. Leg 1 — rank-0-swept, broadcast-applied multi-process
# sweep: a REAL 2-process --tune daxpy run under the native launcher
# must MEASURE (no multi-process skip note), with exactly one sweep
# (rank 0 carries the per-candidate tune records; rank 1 none),
# byte-identical tune_result records on both ranks, and one cache
# writer; then `tpumt-tune pack` → `import` into a fresh cache → the
# re-run is a pure tune_hit on BOTH ranks (the tune-once-ship-the-
# schedule contract). Leg 2 — the online controller: a serve run whose
# pre-seeded winner drifts (tpu/retune_demo.py) must latch tune_stale,
# re-sweep between windows, emit a kind:"control" tune_swap, and pull
# the post-swap SLO windows back inside the band — all asserted from
# the JSONL — with tpumt-doctor exonerating the answered latch and the
# CONTROL table rendering. Leg 3 — the same drift WITHOUT --retune
# (live plane armed, controller off) must convict stale_schedule:0.
fleet-smoke:
	rm -f /tmp/_tpumt_fleet*
	$(MAKE) -C native tpumt_run
	env JAX_PLATFORMS=cpu ./native/tpumt_run -n 2 \
		-o /tmp/_tpumt_fleet.rank -- \
		python -m tpu_mpi_tests.workloads.daxpy --fake-devices 1 \
		--n 262144 --iters 24 --tune \
		--tune-cache /tmp/_tpumt_fleet.cache.json \
		--jsonl /tmp/_tpumt_fleet.r1.jsonl
	python -c "import json; \
		recs = {r: [json.loads(l) for l in \
			open(f'/tmp/_tpumt_fleet.r1.p{r}.jsonl')] for r in (0, 1)}; \
		kinds = {r: [x.get('kind') for x in recs[r]] for r in (0, 1)}; \
		assert kinds[0].count('tune') == 3, kinds[0]; \
		assert kinds[1].count('tune') == 0, kinds[1]; \
		res = {r: [x for x in recs[r] \
			if x.get('kind') == 'tune_result'] for r in (0, 1)}; \
		assert len(res[0]) == 1 and len(res[1]) == 1, res; \
		assert all('note' not in x for x in res[0] + res[1]), \
			'sweep must MEASURE, not skip'; \
		strip = lambda x: {k: v for k, v in x.items() if k != 'rank'}; \
		assert json.dumps(strip(res[0][0]), sort_keys=True) == \
			json.dumps(strip(res[1][0]), sort_keys=True), res; \
		cache = json.load(open('/tmp/_tpumt_fleet.cache.json')); \
		assert len(cache['entries']) == 2, cache; \
		print('fleet-smoke sweep OK: rank-0 swept, both ranks applied', \
			res[0][0]['value'])"
	python -m tpu_mpi_tests.tune.pack pack \
		--cache /tmp/_tpumt_fleet.cache.json \
		-o /tmp/_tpumt_fleet.pack.json
	python -m tpu_mpi_tests.tune.pack import /tmp/_tpumt_fleet.pack.json \
		--cache /tmp/_tpumt_fleet.fresh.json
	env JAX_PLATFORMS=cpu ./native/tpumt_run -n 2 \
		-o /tmp/_tpumt_fleet.r2rank -- \
		python -m tpu_mpi_tests.workloads.daxpy --fake-devices 1 \
		--n 262144 --iters 24 --tune \
		--tune-cache /tmp/_tpumt_fleet.fresh.json \
		--jsonl /tmp/_tpumt_fleet.r2.jsonl
	python -c "import json; \
		kinds = {r: [json.loads(l).get('kind') for l in \
			open(f'/tmp/_tpumt_fleet.r2.p{r}.jsonl')] for r in (0, 1)}; \
		assert all(kinds[r].count('tune_hit') == 1 and \
			kinds[r].count('tune') == 0 and \
			kinds[r].count('tune_result') == 0 for r in (0, 1)), kinds; \
		print('fleet-smoke pack OK: import -> pure cache hits on both ranks')"
	python -c "from tpu_mpi_tests.drivers._common import force_cpu_devices; \
		force_cpu_devices(2); \
		from tpu_mpi_tests.tune.cache import ScheduleCache; \
		from tpu_mpi_tests.tune.fingerprint import device_fingerprint; \
		c = ScheduleCache.load('/tmp/_tpumt_fleet.serve.cache.json'); \
		c.store('daxpy/chunk', device_fingerprint(), 1); c.save()"
	env JAX_PLATFORMS=cpu python -m tpu.retune_demo --drift-after=8 \
		--fake-devices 2 --duration 6 --arrival closed --concurrency 1 \
		--seed 5 --report-interval 1 --workloads daxpy:4096:float32 \
		--telemetry --retune --batch-deadline 30 \
		--tune-cache /tmp/_tpumt_fleet.serve.cache.json \
		--jsonl /tmp/_tpumt_fleet.serve.jsonl
	python -c "import json; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_fleet.serve.jsonl')]; \
		stale = [r for r in recs if r.get('kind') == 'health' \
			and r.get('event') == 'tune_stale']; \
		assert len(stale) == 1 and \
			stale[0]['op'] == 'serve:daxpy:4096:float32', stale; \
		sweeps = [r for r in recs if r.get('kind') == 'tune' \
			and r.get('knob') == 'daxpy/chunk']; \
		assert len(sweeps) == 3, sweeps; \
		swap = [r for r in recs if r.get('kind') == 'control']; \
		assert len(swap) == 1 and swap[0]['event'] == 'tune_swap', swap; \
		s = swap[0]; \
		assert s['old'] == 1 and s['new'] in (8, 32), s; \
		assert s['resweep_s'] > 0 and s['sag_pct'] > 15, s; \
		wins = [(r['t_end'], r['p50_ms']) for r in recs \
			if r.get('kind') == 'serve' and r.get('event') == 'window']; \
		pre = [p for t, p in wins if t <= s['t']]; \
		post = [p for t, p in wins if t > s['t']]; \
		assert pre and max(pre) > 20, (pre, 'induced sag must show'); \
		assert len(post) >= 3 and all(p < 10 for p in post), \
			(post, 'post-swap windows must be back inside the band'); \
		print('fleet-smoke retune OK: stale -> resweep -> swap', \
			s['old'], '->', s['new'], '-> p50', max(pre), '->', max(post))"
	python -m tpu_mpi_tests.instrument.diagnose \
		/tmp/_tpumt_fleet.serve.jsonl | grep -q '^DOCTOR OK'
	python -m tpu_mpi_tests.instrument.aggregate \
		/tmp/_tpumt_fleet.serve.jsonl > /tmp/_tpumt_fleet.report.txt
	grep -q '^CONTROL tune_swap daxpy:4096:float32:' \
		/tmp/_tpumt_fleet.report.txt
	rm -f /tmp/_tpumt_fleet.serve.cache.json
	python -c "from tpu_mpi_tests.drivers._common import force_cpu_devices; \
		force_cpu_devices(2); \
		from tpu_mpi_tests.tune.cache import ScheduleCache; \
		from tpu_mpi_tests.tune.fingerprint import device_fingerprint; \
		c = ScheduleCache.load('/tmp/_tpumt_fleet.serve.cache.json'); \
		c.store('daxpy/chunk', device_fingerprint(), 1); c.save()"
	env JAX_PLATFORMS=cpu python -m tpu.retune_demo --drift-after=8 \
		--fake-devices 2 --duration 4 --arrival closed --concurrency 1 \
		--seed 5 --report-interval 1 --workloads daxpy:4096:float32 \
		--telemetry --metrics-port 0 \
		--tune-cache /tmp/_tpumt_fleet.serve.cache.json \
		--jsonl /tmp/_tpumt_fleet.noctl.jsonl > /dev/null
	python -m tpu_mpi_tests.instrument.diagnose \
		/tmp/_tpumt_fleet.noctl.jsonl --expect stale_schedule:0
	# stencil/tier fleet leg (ISSUE 15): a REAL 2-process --tune sweep
	# over the kernel-tier space through the rank-0-swept broadcast
	# path. This backend cannot execute the tiers cross-process
	# (collectives unsupported on multi-process CPU), so every
	# candidate — the fused tier included — records a VISIBLE error on
	# rank 0 (the honest-decline contract), and the assertion is the
	# fleet invariant itself: per-candidate tune records are
	# rank-0-only, while the broadcast-resolved tune_result (winner =
	# the prior, unpersisted) is byte-identical on both ranks.
	env JAX_PLATFORMS=cpu ./native/tpumt_run -n 2 \
		-o /tmp/_tpumt_fleet.tierrank -- \
		python -m tpu_mpi_tests.drivers.stencil2d --fake-devices 1 \
		--n-local 16 --n-other 32 --dtype float32 \
		--iterate-tier auto --iterate-only --iterate-iters 2 --tune \
		--tune-cache /tmp/_tpumt_fleet.tier.cache.json \
		--jsonl /tmp/_tpumt_fleet.tier.jsonl
	python -c "import json; \
		recs = {r: [json.loads(l) for l in \
			open(f'/tmp/_tpumt_fleet.tier.p{r}.jsonl')] for r in (0, 1)}; \
		tune = {r: [x for x in recs[r] if x.get('kind') == 'tune' \
			and x.get('knob') == 'stencil/tier'] for r in (0, 1)}; \
		assert {t['candidate'] for t in tune[0]} == \
			{'blocks', 'rdma-chained', 'rdma-fused', 'xla'}, tune[0]; \
		assert all('seconds' in t or 'error' in t for t in tune[0]); \
		assert tune[1] == [], 'per-candidate records are rank-0-only'; \
		res = {r: [x for x in recs[r] if x.get('kind') == 'tune_result' \
			and x.get('knob') == 'stencil/tier'] for r in (0, 1)}; \
		assert len(res[0]) == 1 and len(res[1]) == 1, res; \
		strip = lambda x: {k: v for k, v in x.items() if k != 'rank'}; \
		assert json.dumps(strip(res[0][0]), sort_keys=True) == \
			json.dumps(strip(res[1][0]), sort_keys=True), res; \
		print('fleet-smoke tier OK: broadcast-identical stencil/tier', \
			'winner', res[0][0]['value'], 'on both ranks')"
	# the fused tier's OVERLAP row: a single-process iterate-leg run
	# emits the kernel-level seam-wait record and tpumt-report renders
	# it attributed to the rdma-fused tier
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.stencil2d \
		--fake-devices 2 --n-local 16 --n-other 32 --dtype float32 \
		--iterate-tier rdma-fused --iterate-only --iterate-iters 2 \
		--jsonl /tmp/_tpumt_fleet.tierov.jsonl > /dev/null
	python -m tpu_mpi_tests.instrument.aggregate \
		/tmp/_tpumt_fleet.tierov.jsonl | \
		grep -E '^OVERLAP stencil2d_fused_rdma: .*tier=rdma-fused'
	@echo "fleet-smoke OK: rank-0 fleet sweep + pack round-trip + closed-loop retune + stale_schedule conviction + broadcast tier winners + fused OVERLAP row"

# self-clean gate: the repo's own code must raise zero tpumt-lint
# findings (stable TPMxxx codes — README "Static analysis"); unused
# suppressions are findings too, so stale ignores also fail here. The
# golden fixtures (analysis/fixtures/) are deliberately bad and are
# excluded from recursive walks by the linter itself. --jobs 2
# exercises the multiprocessing fact-extraction path on every CI run
# (ISSUE 13); warm-cache runs re-parse zero files regardless of N.
lint:
	python -m tpu_mpi_tests.analysis.cli --jobs 2 \
		tpu_mpi_tests tpu tests __graft_entry__.py bench.py

# regenerate RECORDS.md — the JSONL record-kind schema table extracted
# from the producer/consumer facts (tpu_mpi_tests/analysis/records.py);
# the TPM14xx lint family enforces the same contract
records:
	python -m tpu_mpi_tests.analysis.records

# CI staleness gate: regenerate, then fail if the committed table
# drifted from the code (the generate → git diff --exit-code pattern)
records-check:
	$(MAKE) records
	git diff --exit-code -- RECORDS.md

# lint-cache smoke (README "Static analysis"): the whole-program
# analyzer's incrementality contract, asserted via --stats counters on
# a throwaway cache — a cold run over the repo analyzes every file, a
# warm re-run of the unchanged tree re-parses ZERO files (pure cache
# hits, project rules recomputed from the serialized summaries), and
# touching one file re-analyzes exactly that file. The probe file
# lives in /tmp so the repo itself is never mutated.
lint-smoke:
	rm -rf /tmp/_tpumt_lint_smoke; mkdir -p /tmp/_tpumt_lint_smoke
	printf 'PROBE = 1\n' > /tmp/_tpumt_lint_smoke/probe.py
	python -m tpu_mpi_tests.analysis.cli \
		tpu_mpi_tests tpu tests __graft_entry__.py bench.py \
		/tmp/_tpumt_lint_smoke/probe.py \
		--cache /tmp/_tpumt_lint_smoke/cache.json \
		--stats 2> /tmp/_tpumt_lint_smoke/cold.stats
	python -c "import re; s = open('/tmp/_tpumt_lint_smoke/cold.stats').read(); \
		f, a, h = map(int, re.search( \
			r'files=(\d+) analyzed=(\d+) cache_hits=(\d+)', s).groups()); \
		assert f == a > 0 and h == 0, s; \
		print('lint-smoke cold OK:', a, 'files analyzed')"
	python -m tpu_mpi_tests.analysis.cli \
		tpu_mpi_tests tpu tests __graft_entry__.py bench.py \
		/tmp/_tpumt_lint_smoke/probe.py \
		--cache /tmp/_tpumt_lint_smoke/cache.json \
		--stats 2> /tmp/_tpumt_lint_smoke/warm.stats
	python -c "import re; s = open('/tmp/_tpumt_lint_smoke/warm.stats').read(); \
		f, a, h = map(int, re.search( \
			r'files=(\d+) analyzed=(\d+) cache_hits=(\d+)', s).groups()); \
		assert a == 0 and h == f > 0, s; \
		print('lint-smoke warm OK:', h, 'cache hits, 0 files re-parsed')"
	printf 'PROBE_TOUCHED = 2\n' >> /tmp/_tpumt_lint_smoke/probe.py
	python -m tpu_mpi_tests.analysis.cli \
		tpu_mpi_tests tpu tests __graft_entry__.py bench.py \
		/tmp/_tpumt_lint_smoke/probe.py \
		--cache /tmp/_tpumt_lint_smoke/cache.json \
		--stats 2> /tmp/_tpumt_lint_smoke/touch.stats
	python -c "import re; s = open('/tmp/_tpumt_lint_smoke/touch.stats').read(); \
		f, a, h = map(int, re.search( \
			r'files=(\d+) analyzed=(\d+) cache_hits=(\d+)', s).groups()); \
		assert a == 1 and h == f - 1, s; \
		print('lint-smoke touch OK: exactly 1 file re-analyzed')"
	python -c "import json; json.dump({'version': 1, \
		'salt': 'pre-bump-engine', 'entries': \
		{'/tmp/_tpumt_lint_smoke/probe.py': {'hash': 'stale'}}}, \
		open('/tmp/_tpumt_lint_smoke/salted.json', 'w'))"
	python -m tpu_mpi_tests.analysis.cli \
		tpu_mpi_tests tpu tests __graft_entry__.py bench.py \
		/tmp/_tpumt_lint_smoke/probe.py \
		--cache /tmp/_tpumt_lint_smoke/salted.json \
		--stats 2> /tmp/_tpumt_lint_smoke/salt_cold.stats
	python -c "import re; s = open('/tmp/_tpumt_lint_smoke/salt_cold.stats').read(); \
		f, a, h = map(int, re.search( \
			r'files=(\d+) analyzed=(\d+) cache_hits=(\d+)', s).groups()); \
		assert f == a > 0 and h == 0, s; \
		print('lint-smoke salt-bump OK: stale-engine cache invalidated once,', a, 'files re-judged')"
	python -m tpu_mpi_tests.analysis.cli \
		tpu_mpi_tests tpu tests __graft_entry__.py bench.py \
		/tmp/_tpumt_lint_smoke/probe.py \
		--cache /tmp/_tpumt_lint_smoke/salted.json \
		--stats 2> /tmp/_tpumt_lint_smoke/salt_warm.stats
	python -c "import re; s = open('/tmp/_tpumt_lint_smoke/salt_warm.stats').read(); \
		f, a, h = map(int, re.search( \
			r'files=(\d+) analyzed=(\d+) cache_hits=(\d+)', s).groups()); \
		assert a == 0 and h == f > 0, s; \
		print('lint-smoke salt-warm OK:', h, 'cache hits, 0 files re-parsed')"
	python -m tpu_mpi_tests.analysis.cli \
		tpu_mpi_tests/analysis/fixtures/tpm16_bad \
		--cache /tmp/_tpumt_lint_smoke/races.json --format json \
		> /tmp/_tpumt_lint_smoke/races_cold.json || true
	python -m tpu_mpi_tests.analysis.cli \
		tpu_mpi_tests/analysis/fixtures/tpm16_bad \
		--cache /tmp/_tpumt_lint_smoke/races.json --format json \
		--stats > /tmp/_tpumt_lint_smoke/races_warm.json \
		2> /tmp/_tpumt_lint_smoke/races_warm.stats || true
	python -c "import json, re; \
		cold = json.load(open('/tmp/_tpumt_lint_smoke/races_cold.json')); \
		warm = json.load(open('/tmp/_tpumt_lint_smoke/races_warm.json')); \
		s = open('/tmp/_tpumt_lint_smoke/races_warm.stats').read(); \
		f, a, h = map(int, re.search( \
			r'files=(\d+) analyzed=(\d+) cache_hits=(\d+)', s).groups()); \
		codes = {x['code'] for x in warm['findings']}; \
		assert a == 0 and h == f > 0, s; \
		assert warm == cold, 'warm TPM16xx findings must replay identically'; \
		assert {'TPM1601', 'TPM1602', 'TPM1603'} <= codes, codes; \
		print('lint-smoke races OK: TPM16xx recomputed from replayed concurrency facts, 0 files re-parsed')"
	@echo "lint-smoke OK: cold populate, warm zero-reparse (concurrency facts replayed), touched file re-analyzes, salt bump invalidates exactly once"

# collective-protocol smoke (README "Static analysis", ISSUE 18): the
# whole-program schedule automaton, end to end. (a) Self-clean: the
# repo's own composed schedule raises zero TPM17xx findings. (b)
# Mutation gates against a copy of the REAL tree: rank-0-guarding the
# fleet sweep's opening broadcast convicts TPM1701 as the run's SOLE
# finding, and a rank-dependent halo trip count convicts TPM1702
# (under --jobs 2, so the parallel extraction path feeds the protocol
# pass). (c) Static↔runtime conformance: a fresh 2-process
# native-launcher stencil run replays through the automaton clean
# (--conform exit 0), and a truncated copy of rank 1's stream — the
# wire-level mutant — is convicted TPM1705 (exit 1) citing the sibling
# rank's next op; the two conformance runs share one cache, so the
# second compiles its automaton from replayed per-file summaries with
# ZERO files re-parsed (asserted via --stats).
protocol-smoke:
	rm -rf /tmp/_tpumt_proto; mkdir -p /tmp/_tpumt_proto/m1 /tmp/_tpumt_proto/m2 /tmp/_tpumt_proto/trunc
	$(MAKE) -C native tpumt_run
	python -m tpu_mpi_tests.analysis.cli --select TPM17 --no-cache \
		tpu_mpi_tests tpu tests __graft_entry__.py bench.py
	@echo "protocol-smoke self-clean OK: zero TPM17xx findings"
	for m in m1 m2; do \
		cp -r tpu_mpi_tests tpu /tmp/_tpumt_proto/$$m/; \
		cp bench.py __graft_entry__.py /tmp/_tpumt_proto/$$m/; \
	done
	grep -q 'fleet.bcast({"knob": knob, "n": len(candidates)}, f"{knob}:open")' \
		/tmp/_tpumt_proto/m1/tpu_mpi_tests/tune/sweep.py
	sed -i 's/^\(        \)fleet\.bcast({"knob": knob, "n": len(candidates)}, f"{knob}:open")$$/\1if fleet.process_index() == 0:\n\1    fleet.bcast({"knob": knob, "n": len(candidates)}, f"{knob}:open")/' \
		/tmp/_tpumt_proto/m1/tpu_mpi_tests/tune/sweep.py
	python -m tpu_mpi_tests.analysis.cli --no-cache \
		/tmp/_tpumt_proto/m1/tpu_mpi_tests /tmp/_tpumt_proto/m1/tpu \
		/tmp/_tpumt_proto/m1/bench.py \
		/tmp/_tpumt_proto/m1/__graft_entry__.py \
		> /tmp/_tpumt_proto/m1.out; test $$? -eq 1
	grep -q ' TPM1701 ' /tmp/_tpumt_proto/m1.out
	test "$$(wc -l < /tmp/_tpumt_proto/m1.out)" -eq 1
	@echo "protocol-smoke mutant OK: rank-guarded handshake -> sole TPM1701"
	grep -q '^                    for _ in range(k):$$' \
		/tmp/_tpumt_proto/m2/tpu_mpi_tests/workloads/stencil1d.py
	sed -i 's/^\(                    \)for _ in range(k):$$/\1for _ in range(k - jax.process_index()):/' \
		/tmp/_tpumt_proto/m2/tpu_mpi_tests/workloads/stencil1d.py
	python -m tpu_mpi_tests.analysis.cli --no-cache --jobs 2 \
		/tmp/_tpumt_proto/m2/tpu_mpi_tests /tmp/_tpumt_proto/m2/tpu \
		/tmp/_tpumt_proto/m2/bench.py \
		/tmp/_tpumt_proto/m2/__graft_entry__.py \
		> /tmp/_tpumt_proto/m2.out; test $$? -eq 1
	grep -q ' TPM1702 ' /tmp/_tpumt_proto/m2.out
	test "$$(wc -l < /tmp/_tpumt_proto/m2.out)" -eq 1
	@echo "protocol-smoke mutant OK: rank-dependent trip count -> sole TPM1702"
	env JAX_PLATFORMS=cpu \
		./native/tpumt_run -n 2 -o /tmp/_tpumt_proto/conf.rank -- \
		python -m tpu_mpi_tests.drivers.stencil1d --fake-devices 1 \
		--n-global 65536 --dtype float64 --overlap 1 \
		--overlap-iters 8 --telemetry \
		--jsonl /tmp/_tpumt_proto/conf.jsonl
	python -m tpu_mpi_tests.analysis.cli --conform \
		--cache /tmp/_tpumt_proto/cache.json \
		/tmp/_tpumt_proto/conf.jsonl
	@echo "protocol-smoke conform OK: fresh 2-process stream replays clean"
	cp /tmp/_tpumt_proto/conf.p0.jsonl /tmp/_tpumt_proto/trunc/conf.p0.jsonl
	head -n -5 /tmp/_tpumt_proto/conf.p1.jsonl \
		> /tmp/_tpumt_proto/trunc/conf.p1.jsonl
	python -m tpu_mpi_tests.analysis.cli --conform \
		--cache /tmp/_tpumt_proto/cache.json --stats \
		/tmp/_tpumt_proto/trunc/conf.jsonl \
		> /tmp/_tpumt_proto/trunc.out \
		2> /tmp/_tpumt_proto/warm.stats; test $$? -eq 1
	grep -q ' TPM1705 ' /tmp/_tpumt_proto/trunc.out
	grep -q 'sibling rank 0' /tmp/_tpumt_proto/trunc.out
	test "$$(wc -l < /tmp/_tpumt_proto/trunc.out)" -eq 1
	python -c "import re; s = open('/tmp/_tpumt_proto/warm.stats').read(); \
		f, a, h = map(int, re.search( \
			r'files=(\d+) analyzed=(\d+) cache_hits=(\d+)', s).groups()); \
		assert a == 0 and h == f > 0, s; \
		print('protocol-smoke warm OK: automaton recompiled from', h, \
			'replayed summaries, 0 files re-parsed')"
	@echo "protocol-smoke OK: self-clean + 2 source mutants + wire mutant convicted, conform clean on the real stream"

# CI umbrella: the tier-1 gate, the timeline-pipeline smoke, the
# autotuner sweep→persist→cache-hit smoke, the memory/compile
# observability smoke, the serving-pipeline smoke, the overlap-engine
# smoke, the workload-spec pillar smoke, the decode-tier smoke (one-
# shot collective sweep → DECODE consumption → diff gate), the chaos-
# verified diagnosis smoke, the topology smoke (2-process h2x1
# discovery + link-class attribution + pack shape gate), the
# live-observability smoke (OpenMetrics
# endpoint + online doctor), the fleet-tuning smoke (rank-0 2-process
# sweep + pack round-trip + closed-loop retune), the lint self-clean
# gate, the lint-cache incrementality + engine-salt smoke, the
# collective-protocol smoke (schedule-automaton mutation gates +
# static↔runtime conformance), and the RECORDS.md staleness gate
ci: verify trace-smoke tune-smoke mem-smoke serve-smoke replay-smoke \
	overlap-smoke moe-smoke decode-smoke chaos-smoke anatomy-smoke \
	topo-smoke live-smoke fleet-smoke lint lint-smoke protocol-smoke \
	records-check

clean:
	$(MAKE) -C native clean
	rm -rf .pytest_cache tpu_mpi_tests/__pycache__
