# Dev workflow (≅ the reference's root Makefile role).
.PHONY: test native bench smoke clean

test:
	python -m pytest tests/ -q

native:
	$(MAKE) -C native

bench:
	python bench.py

# CI-sized bench + entry-point checks on a 4-device CPU mesh
smoke:
	TPU_MPI_BENCH_N=128 TPU_MPI_BENCH_ITERS_SHORT=50 \
	TPU_MPI_BENCH_ITERS_LONG=1050 TPU_MPI_BENCH_FAKE_DEVICES=4 \
	python bench.py
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

clean:
	$(MAKE) -C native clean
	rm -rf .pytest_cache tpu_mpi_tests/__pycache__
