# Dev workflow (≅ the reference's root Makefile role).
SHELL := /bin/bash
.PHONY: test verify native bench smoke trace-smoke tune-smoke mem-smoke \
	lint ci clean

test:
	python -m pytest tests/ -q

# the blessed tier-1 gate, verbatim from ROADMAP.md — builders and CI
# invoke this one entry point instead of hand-copying the command
verify:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

native:
	$(MAKE) -C native

bench:
	python bench.py

# CI-sized bench + entry-point checks on a 4-device CPU mesh
smoke:
	TPU_MPI_BENCH_N=128 TPU_MPI_BENCH_ITERS_SHORT=50 \
	TPU_MPI_BENCH_ITERS_LONG=1050 TPU_MPI_BENCH_FAKE_DEVICES=4 \
	python bench.py
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# timeline-pipeline smoke: a 2-fake-device daxpy run records telemetry
# JSONL and auto-merges it into a Chrome trace on exit; the check
# asserts the trace is non-empty valid JSON with placeable events
trace-smoke:
	rm -f /tmp/_tpumt_trace_smoke*.json*
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.daxpy \
		--fake-devices 2 --n 4096 --telemetry \
		--jsonl /tmp/_tpumt_trace_smoke.jsonl \
		--trace-out /tmp/_tpumt_trace_smoke.trace.json
	python -c "import json; \
		d = json.load(open('/tmp/_tpumt_trace_smoke.trace.json')); \
		evs = [e for e in d['traceEvents'] if e['ph'] != 'M']; \
		assert evs, 'trace has no placeable events'; \
		assert all('ts' in e and 'pid' in e for e in evs); \
		print('trace-smoke OK:', len(evs), 'events')"

# autotuner-pipeline smoke: a 2-fake-device stencil1d sweeps the halo
# schedule space (--staging auto --tune), persists the winner into a
# fresh cache (checked: valid JSON, non-empty), and a second invocation
# resolves as a PURE cache hit — asserted via the JSONL tune records
# (run 1: tune measurements + tune_result; run 2: tune_hit only)
tune-smoke:
	rm -f /tmp/_tpumt_tune_smoke*
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.stencil1d \
		--fake-devices 2 --n-global 65536 --staging auto \
		--tune --tune-cache /tmp/_tpumt_tune_smoke.cache.json \
		--tune-budget 300 \
		--jsonl /tmp/_tpumt_tune_smoke.r1.jsonl
	python -c "import json; \
		d = json.load(open('/tmp/_tpumt_tune_smoke.cache.json')); \
		assert d['version'] == 1 and d['entries'], 'empty cache'; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_tune_smoke.r1.jsonl')]; \
		kinds = [r.get('kind') for r in recs]; \
		assert kinds.count('tune') >= 2, kinds; \
		assert 'tune_result' in kinds, kinds; \
		print('tune-smoke sweep OK:', len(d['entries']), 'entries')"
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.stencil1d \
		--fake-devices 2 --n-global 65536 --staging auto \
		--tune --tune-cache /tmp/_tpumt_tune_smoke.cache.json \
		--jsonl /tmp/_tpumt_tune_smoke.r2.jsonl
	python -c "import json; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_tune_smoke.r2.jsonl')]; \
		kinds = [r.get('kind') for r in recs]; \
		assert 'tune_hit' in kinds, kinds; \
		assert 'tune' not in kinds and 'tune_result' not in kinds, kinds; \
		print('tune-smoke cache-hit OK')"

# memory/compile-observability smoke: a 2-fake-device daxpy with
# --memwatch + --telemetry must (a) record kind:"mem" (census-only on
# CPU — no memory_stats) and kind:"compile" JSONL records, (b) merge
# them into a trace with at least one Perfetto counter track, and
# (c) render non-empty MEMORY and COMPILE tables under tpumt-report
mem-smoke:
	rm -f /tmp/_tpumt_mem_smoke*
	env JAX_PLATFORMS=cpu python -m tpu_mpi_tests.drivers.daxpy \
		--fake-devices 2 --n 4096 --telemetry --memwatch \
		--mem-interval 0.05 \
		--jsonl /tmp/_tpumt_mem_smoke.jsonl \
		--trace-out /tmp/_tpumt_mem_smoke.trace.json
	python -c "import json; \
		recs = [json.loads(l) for l in \
			open('/tmp/_tpumt_mem_smoke.jsonl')]; \
		kinds = [r.get('kind') for r in recs]; \
		assert 'mem' in kinds and 'compile' in kinds, kinds; \
		mems = [r for r in recs if r.get('kind') == 'mem']; \
		assert any(r.get('event') == 'phase' for r in mems), mems; \
		d = json.load(open('/tmp/_tpumt_mem_smoke.trace.json')); \
		cs = [e for e in d['traceEvents'] if e['ph'] == 'C']; \
		assert cs, 'no counter track'; \
		print('mem-smoke records OK:', kinds.count('mem'), 'mem,', \
			kinds.count('compile'), 'compile,', len(cs), \
			'counter events')"
	python -m tpu_mpi_tests.instrument.aggregate \
		/tmp/_tpumt_mem_smoke.jsonl > /tmp/_tpumt_mem_smoke.report.txt
	grep -q '^MEM ' /tmp/_tpumt_mem_smoke.report.txt
	grep -q '^COMPILE ' /tmp/_tpumt_mem_smoke.report.txt
	@echo "mem-smoke report OK: MEMORY + COMPILE tables render"

# self-clean gate: the repo's own code must raise zero tpumt-lint
# findings (stable TPMxxx codes — README "Static analysis"); unused
# suppressions are findings too, so stale ignores also fail here. The
# golden fixtures (analysis/fixtures/) are deliberately bad and are
# excluded from recursive walks by the linter itself.
lint:
	python -m tpu_mpi_tests.analysis.cli \
		tpu_mpi_tests tpu tests __graft_entry__.py bench.py

# CI umbrella: the tier-1 gate, the timeline-pipeline smoke, the
# autotuner sweep→persist→cache-hit smoke, the memory/compile
# observability smoke, and the lint self-clean gate
ci: verify trace-smoke tune-smoke mem-smoke lint

clean:
	$(MAKE) -C native clean
	rm -rf .pytest_cache tpu_mpi_tests/__pycache__
