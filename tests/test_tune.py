"""Autotuner gates: cache round-trip + corruption fallback, fingerprint
stability across process restarts, a stubbed-timer CPU sweep that
completes under budget with a deterministic winner, the pinned-prior
parity contract (no cache + no --tune == the hand-pinned era, exactly),
and the precedence order (explicit > cached > prior) at every resolver.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from tpu_mpi_tests.tune import priors
from tpu_mpi_tests.tune import registry as tr
from tpu_mpi_tests.tune.cache import CACHE_VERSION, ScheduleCache
from tpu_mpi_tests.tune.fingerprint import (
    compose,
    device_fingerprint,
    fingerprint,
    shape_bucket,
)
from tpu_mpi_tests.tune.sweep import ensure_tuned, sweep

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _isolated_registry(monkeypatch):
    """Every test starts (and ends) unconfigured, and a developer's real
    ~/.cache/tpumt/tune.json can never leak in."""
    monkeypatch.delenv("TPU_MPI_TUNE_CACHE", raising=False)
    tr.deconfigure()
    yield
    tr.deconfigure()


# ---------------------------------------------------------------- cache


def test_cache_round_trip(tmp_path):
    """write → reload → same schedule."""
    path = tmp_path / "tune.json"
    c = ScheduleCache.load(str(path))  # missing file: empty, no error
    assert len(c) == 0
    c.store("flash_tiles/contig", "fp1", {"k_tile": 1024, "skip_tile": 0},
            seconds=0.5)
    c.store("halo/staging", "fp2", "device", seconds=0.25)
    c.save()

    c2 = ScheduleCache.load(str(path))
    assert c2.lookup("flash_tiles/contig", "fp1") == {
        "k_tile": 1024, "skip_tile": 0,
    }
    assert c2.lookup("halo/staging", "fp2") == "device"
    assert c2.lookup("halo/staging", "other-fp") is None
    doc = json.loads(path.read_text())
    assert doc["version"] == CACHE_VERSION


def test_cache_save_merges_concurrent_writers(tmp_path):
    """Two caches writing disjoint knobs to one file compose instead of
    last-writer-wins clobbering."""
    path = str(tmp_path / "tune.json")
    a, b = ScheduleCache.load(path), ScheduleCache.load(path)
    a.store("knob/a", "fp", 1)
    b.store("knob/b", "fp", 2)
    a.save()
    b.save()
    c = ScheduleCache.load(path)
    assert c.lookup("knob/a", "fp") == 1
    assert c.lookup("knob/b", "fp") == 2


@pytest.mark.parametrize("content", [
    "not json at all{{{",
    '{"version": 999, "entries": {"k|f": {"value": 7}}}',  # stale format
    '[1, 2, 3]',
    '{"version": 1, "entries": "not-a-dict"}',
])
def test_corrupted_or_stale_cache_falls_back_to_priors(tmp_path, content):
    path = tmp_path / "tune.json"
    path.write_text(content)
    c = ScheduleCache.load(str(path))
    assert len(c) == 0
    # and end-to-end: a configured-but-garbage cache resolves priors
    tr.configure(cache_path=str(path))
    from tpu_mpi_tests.comm.ring import _resolve_k_tile

    assert _resolve_k_tile(None, False) == \
        priors.MEASURED_BEST_K_TILE["contig"]


def test_malformed_cached_value_degrades_to_prior(tmp_path):
    """A hand-edited entry of the wrong shape must not crash resolution."""
    path = tmp_path / "tune.json"
    tr.configure(cache_path=str(path))
    cache = tr.configured_cache()
    from tpu_mpi_tests.comm.ring import _resolve_k_tile

    cache.store("flash_tiles/contig", device_fingerprint(), "garbage")
    assert _resolve_k_tile(None, False) == \
        priors.MEASURED_BEST_K_TILE["contig"]

    from tpu_mpi_tests.comm.halo import Staging, resolve_staging

    cache.store("halo/staging", _staging_fp(), "bogus-mode")
    assert resolve_staging("auto", _fake_zg(), 0, 2) is Staging.DIRECT
    # a cache must never silently select the host measurement mode
    cache.store("halo/staging", _staging_fp(), "host")
    assert resolve_staging("auto", _fake_zg(), 0, 2) is Staging.DIRECT


# ---------------------------------------------------------- fingerprint


def test_shape_bucket_powers_of_two():
    assert [shape_bucket(v) for v in (1, 2, 3, 4, 1000, 8192, 8193)] == \
        [1, 2, 4, 4, 1024, 8192, 16384]


def test_fingerprint_composition_is_pure_and_sorted():
    base = {"platform": "tpu", "device": "v5e", "ndev": "4", "procs": "1"}
    fp = compose(base, dtype="float32", lq=8192)
    assert fp == ("device=v5e;dtype=float32;lq=8192;ndev=4;"
                  "platform=tpu;procs=1")
    assert compose(base, lq=8192, dtype="float32") == fp  # order-free
    assert compose(base, lq=5000) == compose(base, lq=8192)  # bucketed


def test_fingerprint_stable_across_process_restarts():
    """Same inputs → same key in fresh interpreters: nothing
    process-local (id/hash randomization/time) may leak into the key,
    or a persisted winner would never be found again."""
    snippet = (
        "from tpu_mpi_tests.tune.fingerprint import compose; "
        "print(compose({'platform': 'cpu', 'device': 'cpu', 'ndev': '2',"
        " 'procs': '1'}, dtype='bfloat16', lq=4096))"
    )
    outs = {
        subprocess.run(
            [sys.executable, "-c", snippet], cwd=REPO,
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        for _ in range(2)
    }
    assert len(outs) == 1
    assert outs.pop() == ("device=cpu;dtype=bfloat16;lq=4096;ndev=2;"
                          "platform=cpu;procs=1")


def test_live_fingerprint_includes_context(tmp_path):
    fp = fingerprint(dtype="float32", lq=4096)
    assert "dtype=float32" in fp and "lq=4096" in fp
    assert "platform=" in fp and "device=" in fp
    # the device-only key is a strict prefix-set of the full one
    for field in device_fingerprint().split(";"):
        assert field in fp.split(";")


# ---------------------------------------------------------------- sweep


def test_stubbed_sweep_picks_deterministic_winner(tmp_path):
    """A CPU sweep with a stubbed timer: completes within budget, picks
    the argmin, persists it under the full AND device-only fingerprints,
    and a later resolve() serves the winner."""
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True,
                 budget_s=60.0)
    timing = {"a": 0.5, "b": 0.125, "c": 0.25}
    records = []
    winner = sweep(
        "demo/knob", lambda cand: timing[cand],
        candidates=("a", "b", "c"), emit=records.append,
        dtype="float32", lq=128,
    )
    assert winner == "b"
    kinds = [r["kind"] for r in records]
    assert kinds == ["tune", "tune", "tune", "tune_result"]
    assert records[-1]["value"] == "b"
    assert records[-1]["seconds"] == 0.125
    assert records[-1]["measured"] == 3 and records[-1]["skipped"] == 0

    cache = ScheduleCache.load(str(tmp_path / "t.json"))
    assert cache.lookup(
        "demo/knob", fingerprint(dtype="float32", lq=128)
    ) == "b"
    assert cache.lookup("demo/knob", device_fingerprint()) == "b"
    assert tr.resolve("demo/knob", prior="a", dtype="float32", lq=128) == "b"


def test_sweep_budget_skips_are_reported_not_silent(tmp_path):
    """budget_s=0: the prior is still measured (always), the rest are
    emitted as skipped — a bounded sweep must never read as exhaustive."""
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)
    records = []
    winner = sweep(
        "demo/knob2", lambda cand: 1.0,
        candidates=("prior", "x", "y"), budget_s=0.0,
        emit=records.append,
    )
    assert winner == "prior"
    skipped = [r for r in records if r.get("skipped") == "budget"]
    assert {r["candidate"] for r in skipped} == {"x", "y"}
    assert records[-1]["skipped"] == 2


def test_sweep_survives_erroring_candidate(tmp_path):
    """An infeasible candidate (e.g. an RDMA ring below its lane floor)
    records its error and loses; it must not kill the sweep."""
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)

    def measure(cand):
        if cand == "bad":
            raise ValueError("lane floor")
        return 1.0

    records = []
    winner = sweep("demo/knob3", measure, candidates=("bad", "ok"),
                   emit=records.append)
    assert winner == "ok"
    errs = [r for r in records if r.get("error")]
    assert len(errs) == 1 and "lane floor" in errs[0]["error"]


def test_sweep_all_invalid_keeps_prior_and_does_not_persist(tmp_path):
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)
    records = []
    winner = sweep(
        "demo/knob4", lambda c: float("nan"), candidates=("p", "q"),
        emit=records.append,
    )
    assert winner == "p"
    assert records[-1]["measured"] == 0
    assert ScheduleCache.load(str(tmp_path / "t.json")).lookup(
        "demo/knob4", device_fingerprint()
    ) is None


def test_ensure_tuned_hit_skips_measure_and_emits_tune_hit(tmp_path):
    """The make tune-smoke contract in-process: first call sweeps, the
    second is a pure cache hit (no measurement, a tune_hit record)."""
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)
    calls = []
    records = []

    def measure(cand):
        calls.append(cand)
        return {"slow": 1.0, "fast": 0.5}[cand]

    first = ensure_tuned("demo/knob5", measure,
                         candidates=("slow", "fast"),
                         emit=records.append, dtype="float32")
    assert first == "fast" and calls == ["slow", "fast"]

    calls.clear()
    records.clear()
    again = ensure_tuned("demo/knob5", measure,
                         candidates=("slow", "fast"),
                         emit=records.append, dtype="float32")
    assert again == "fast"
    assert calls == []  # pure cache hit: nothing measured
    assert [r["kind"] for r in records] == ["tune_hit"]


def test_ensure_tuned_disabled_returns_prior_without_measuring(tmp_path):
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=False)
    out = ensure_tuned(
        "demo/knob6", lambda c: pytest.fail("must not measure"),
        candidates=("p", "q"), prior="p",
    )
    assert out == "p"


# ------------------------------------------------- pinned-prior parity


def test_pinned_prior_parity_unconfigured():
    """With no cache and no --tune, every schedule resolves exactly as
    the hand-pinned era: the acceptance contract of the whole demotion."""
    from tpu_mpi_tests.comm.halo import Staging, resolve_staging
    from tpu_mpi_tests.comm.ring import (
        MEASURED_BEST_K_TILE,
        MEASURED_BEST_SKIP_TILE,
        _resolve_k_tile,
        _resolve_skip_tile,
    )

    assert tr.configured_cache() is None
    assert MEASURED_BEST_K_TILE == priors.MEASURED_BEST_K_TILE
    assert MEASURED_BEST_SKIP_TILE == priors.MEASURED_BEST_SKIP_TILE
    for stripe in (False, True):
        layout = "striped" if stripe else "contig"
        assert _resolve_k_tile(None, stripe) == \
            priors.MEASURED_BEST_K_TILE[layout]
        assert _resolve_skip_tile(None, stripe) == \
            priors.MEASURED_BEST_SKIP_TILE[layout]
    assert resolve_staging("direct", _fake_zg(), 0, 2) is Staging.DIRECT
    assert resolve_staging("auto", _fake_zg(), 0, 2) is Staging.DIRECT

    bench = _import_bench()
    assert bench._resolve_steps(None, n=8192, world=1) == \
        priors.BENCH_STEPS
    assert bench._resolve_blocks(None, "float32", n=8192, world=1) == \
        priors.BENCH_BLOCKS["float32"]
    assert bench._resolve_blocks(None, "bfloat16", n=8192, world=1) == \
        priors.BENCH_BLOCKS["bfloat16"]

    from tpu_mpi_tests.kernels import pallas_kernels as PK

    assert PK._STREAM_SKIP_TILE_DEFAULT == priors.STREAM_SKIP_TILE


# ---------------------------------------------------------- precedence


def test_precedence_explicit_over_cached_over_prior(tmp_path):
    """The satellite contract: attnbench --k-tile/--skip-tile and
    TPU_MPI_BENCH_BLOCKS win over a cache entry, which wins over the
    prior."""
    tr.configure(cache_path=str(tmp_path / "t.json"))
    cache = tr.configured_cache()
    from tpu_mpi_tests.comm.ring import _resolve_k_tile, _resolve_skip_tile

    cache.store("flash_tiles/contig", device_fingerprint(),
                {"k_tile": 1024, "skip_tile": 128})
    # cached beats prior (prior is 2048/0)
    assert _resolve_k_tile(None, False) == 1024
    assert _resolve_skip_tile(None, False) == 128
    # explicit beats cached
    assert _resolve_k_tile(512, False) == 512
    assert _resolve_skip_tile(0, False) == 0

    bench = _import_bench()
    cache.store("stencil/blocks",
                fingerprint(dtype="float32", n=8192, world=1), 4)
    assert bench._resolve_blocks(None, "float32", n=8192, world=1) == 4
    assert bench._resolve_blocks("8", "float32", n=8192, world=1) == 8
    cache.store("stencil/steps", fingerprint(n=8192, world=1), 2)
    assert bench._resolve_steps(None, n=8192, world=1) == 2
    assert bench._resolve_steps("8", n=8192, world=1) == 8

    from tpu_mpi_tests.comm.halo import Staging, resolve_staging

    cache.store("halo/staging", _staging_fp(), "device")
    assert resolve_staging("auto", _fake_zg(), 0, 2) is \
        Staging.DEVICE_STAGED
    # explicit staging never consults the cache
    assert resolve_staging("pallas", _fake_zg(), 0, 2) is \
        Staging.PALLAS_RDMA


def test_context_sensitive_knobs_ignore_device_slot(tmp_path):
    """A dtype-keyed knob must not inherit another context's winner via
    the device-only slot: the f32 sweep's S=2 leaking into the bf16
    resolution would override bf16's measured-best single-buffer prior."""
    tr.configure(cache_path=str(tmp_path / "t.json"))
    cache = tr.configured_cache()
    cache.store("stencil/blocks", device_fingerprint(), 2)
    bench = _import_bench()
    assert bench._resolve_blocks(None, "bfloat16", n=8192, world=1) == \
        priors.BENCH_BLOCKS["bfloat16"]
    # the flash-tile knob keeps the fallback: its in-kernel resolve
    # site is context-free by construction
    cache.store("flash_tiles/contig", device_fingerprint(),
                {"k_tile": 512, "skip_tile": 0})
    from tpu_mpi_tests.comm.ring import _resolve_k_tile

    assert _resolve_k_tile(None, False, dtype="bfloat16", lq=64) == 512


def test_fleet_sweep_rank0_measures_and_persists(tmp_path, monkeypatch):
    """ISSUE 14 tentpole a: a multi-process sweep MEASURES. On rank 0
    the fleet path runs every candidate, records them, picks the
    argmin, persists it, and emits NO multi-process skip note. (With a
    single-process jax the broadcast is the identity, which is exactly
    rank 0's view of the protocol.)"""
    import importlib

    sweep_mod = importlib.import_module("tpu_mpi_tests.tune.sweep")
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)
    monkeypatch.setattr(sweep_mod, "_process_count", lambda: 2)
    timing = {"slow": 1.0, "fast": 0.25}
    records = []
    winner = sweep(
        "demo/fleet0", lambda c: timing[c],
        candidates=("slow", "fast"), emit=records.append,
        dtype="float32",
    )
    assert winner == "fast"
    kinds = [r["kind"] for r in records]
    assert kinds == ["tune", "tune", "tune_result"]
    assert all("note" not in r for r in records), records
    assert records[-1]["value"] == "fast"
    assert records[-1]["measured"] == 2
    cache = ScheduleCache.load(str(tmp_path / "t.json"))
    assert cache.lookup("demo/fleet0",
                        fingerprint(dtype="float32")) == "fast"
    assert cache.lookup("demo/fleet0", device_fingerprint()) == "fast"


def test_fleet_sweep_rank0_budget_cutoff_is_broadcast(tmp_path,
                                                     monkeypatch):
    """Rank 0's clock decides the budget stop; the skipped candidates
    are reported exactly like the single-process sweep's."""
    import importlib

    sweep_mod = importlib.import_module("tpu_mpi_tests.tune.sweep")
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)
    monkeypatch.setattr(sweep_mod, "_process_count", lambda: 2)
    records = []
    winner = sweep(
        "demo/fleetb", lambda c: 1.0,
        candidates=("prior", "x", "y"), budget_s=0.0,
        emit=records.append,
    )
    assert winner == "prior"
    skipped = [r for r in records if r.get("skipped") == "budget"]
    assert {r["candidate"] for r in skipped} == {"x", "y"}
    assert records[-1]["skipped"] == 2


def test_fleet_sweep_nonzero_rank_applies_broadcast_winner(
        tmp_path, monkeypatch):
    """A non-zero rank measures every candidate (the collectives need
    it present) but emits ONLY the broadcast tune_result — rank 0's
    record verbatim — and never writes the cache: exactly one sweep,
    one writer, byte-identical resolved schedules."""
    from tpu_mpi_tests.tune import fleet

    sweep_mod = __import__("tpu_mpi_tests.tune.sweep",
                           fromlist=["sweep"])
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)
    monkeypatch.setattr(sweep_mod, "_process_count", lambda: 2)
    monkeypatch.setattr(fleet, "process_count", lambda: 2)
    monkeypatch.setattr(fleet, "process_index", lambda: 1)
    monkeypatch.setattr(
        fleet, "_device_bcast",
        lambda payload: (_ for _ in ()).throw(RuntimeError("no mp cpu")),
    )
    # rank 0's decision stream, served FIFO by a fake coordination
    # client (key content does not matter: the SPMD call order does)
    import json as _json

    rank0 = [
        {"knob": "demo/fleet1", "n": 2},   # open handshake
        True,                               # go candidate 0
        True,                               # go candidate 1
        {"kind": "tune_result", "knob": "demo/fleet1", "value": "b",
         "seconds": 0.125, "measured": 2, "skipped": 0,
         "fingerprint": "fp-from-rank0"},
    ]
    payloads = [_json.dumps(v) for v in rank0]

    class FakeClient:
        def blocking_key_value_get(self, key, timeout_ms):
            return payloads.pop(0)

        def key_value_set(self, key, value):  # pragma: no cover
            raise AssertionError("rank 1 must never set decisions")

    monkeypatch.setattr(fleet, "_kv_client", lambda: FakeClient())
    fleet._reset_transport_for_tests()
    measured = []
    records = []
    winner = sweep_mod.sweep(
        "demo/fleet1",
        lambda c: measured.append(c) or {"a": 0.5, "b": 0.125}[c],
        candidates=("a", "b"), emit=records.append,
    )
    fleet._reset_transport_for_tests()
    assert winner == "b"
    assert measured == ["a", "b"]  # every rank runs every candidate
    assert [r["kind"] for r in records] == ["tune_result"]
    assert records[0]["fingerprint"] == "fp-from-rank0"  # verbatim
    # one writer: rank 1 persisted nothing
    assert ScheduleCache.load(str(tmp_path / "t.json")).entries == {}


def test_fleet_sweep_without_transport_keeps_skip_contract(
        tmp_path, monkeypatch):
    """A fleet with no broadcast path degrades to the PR-4 contract on
    every rank: record the skip, resolve cached > prior."""
    from tpu_mpi_tests.tune import fleet

    sweep_mod = __import__("tpu_mpi_tests.tune.sweep",
                           fromlist=["sweep"])
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)
    monkeypatch.setattr(sweep_mod, "_process_count", lambda: 2)
    monkeypatch.setattr(fleet, "process_count", lambda: 2)
    monkeypatch.setattr(
        fleet, "_device_bcast",
        lambda payload: (_ for _ in ()).throw(RuntimeError("no mp cpu")),
    )
    monkeypatch.setattr(fleet, "_kv_client", lambda: None)
    fleet._reset_transport_for_tests()
    records = []
    winner = sweep_mod.sweep(
        "demo/mp", lambda c: pytest.fail("must not measure"),
        candidates=("p", "q"), emit=records.append,
    )
    fleet._reset_transport_for_tests()
    assert winner == "p"
    assert [r["kind"] for r in records] == ["tune_result"]
    assert "no fleet broadcast transport" in records[0]["note"]
    # a warmed cache still serves its winner
    tr.configured_cache().store("demo/mp", device_fingerprint(), "q")
    assert sweep_mod.sweep("demo/mp", lambda c: 0.0,
                           candidates=("p", "q"),
                           emit=records.append) == "q"


def test_ensure_tuned_hit_decision_is_rank0s(tmp_path, monkeypatch):
    """Per-host caches can diverge (rank 0 is the only writer): the
    hit-vs-sweep decision must be rank 0's, broadcast — a non-zero rank
    whose LOCAL cache misses must still take the hit path when rank 0
    hit, or a subset of ranks would enter the collective sweep
    handshake alone and hang the pod."""
    import json as _json

    from tpu_mpi_tests.tune import fleet

    sweep_mod = __import__("tpu_mpi_tests.tune.sweep",
                           fromlist=["ensure_tuned"])
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)
    monkeypatch.setattr(sweep_mod, "_process_count", lambda: 2)
    monkeypatch.setattr(fleet, "process_count", lambda: 2)
    monkeypatch.setattr(fleet, "process_index", lambda: 1)
    monkeypatch.setattr(
        fleet, "_device_bcast",
        lambda payload: (_ for _ in ()).throw(RuntimeError("no mp cpu")),
    )
    payloads = [_json.dumps({"hit": True, "value": "rank0-winner"})]

    class FakeClient:
        def blocking_key_value_get(self, key, timeout_ms):
            return payloads.pop(0)

    monkeypatch.setattr(fleet, "_kv_client", lambda: FakeClient())
    fleet._reset_transport_for_tests()
    records = []
    out = sweep_mod.ensure_tuned(
        "demo/fleeth",
        lambda c: pytest.fail("rank 0 hit: no rank may sweep"),
        candidates=("a", "b"), emit=records.append,
    )
    fleet._reset_transport_for_tests()
    assert out == "rank0-winner"  # not this rank's (empty) cache view
    assert [r["kind"] for r in records] == ["tune_hit"]


def test_cache_read_only_never_writes(tmp_path):
    """The single-writer contract's mechanism: a read-only cache's
    save() is a no-op (non-zero fleet ranks get one from configure)."""
    path = tmp_path / "tune.json"
    c = ScheduleCache.load(str(path))
    c.read_only = True
    c.store("knob/x", "fp", 7)
    c.save()
    assert not path.exists()
    assert c.lookup("knob/x", "fp") == 7  # in-memory view still serves


def test_configure_marks_nonzero_rank_read_only(tmp_path, monkeypatch):
    """registry.configure is where non-zero ranks lose write access: a
    2-process run produces ONE cache writer."""
    monkeypatch.setattr(tr, "_nonzero_rank", lambda: True)
    cache = tr.configure(cache_path=str(tmp_path / "t.json"))
    assert cache.read_only
    cache.store("knob/x", "fp", 1)
    cache.save()
    assert not (tmp_path / "t.json").exists()
    monkeypatch.setattr(tr, "_nonzero_rank", lambda: False)
    cache = tr.configure(cache_path=str(tmp_path / "t.json"))
    assert not cache.read_only


def test_mark_fleet_rank_applies_after_bootstrap(tmp_path, monkeypatch):
    """The real driver ordering: setup_tuning configures BEFORE
    bootstrap initializes jax.distributed — so at configure time every
    rank looks like a writer. mark_fleet_rank (called by make_reporter,
    which runs after bootstrap) applies the marking once the rank is
    actually known."""
    monkeypatch.setattr(tr, "_nonzero_rank", lambda: False)
    cache = tr.configure(cache_path=str(tmp_path / "t.json"))
    assert not cache.read_only  # pre-bootstrap: rank unknown
    monkeypatch.setattr(tr, "_nonzero_rank", lambda: True)
    tr.mark_fleet_rank()
    assert cache.read_only
    cache.store("knob/x", "fp", 1)
    cache.save()
    assert not (tmp_path / "t.json").exists()
    # unconfigured registry: a harmless no-op
    tr.deconfigure()
    tr.mark_fleet_rank()


def test_full_fingerprint_beats_device_slot(tmp_path):
    """lookup() prefers the exact-context entry over the device-only
    fallback slot when both exist."""
    tr.configure(cache_path=str(tmp_path / "t.json"))
    cache = tr.configured_cache()
    cache.store("demo/knob7", device_fingerprint(), "generic")
    cache.store("demo/knob7", fingerprint(dtype="float32"), "exact")
    assert tr.lookup("demo/knob7", dtype="float32") == "exact"
    assert tr.lookup("demo/knob7", dtype="bfloat16") == "generic"


# ------------------------------------------------------ report plumbing


def test_report_tuning_table(tmp_path):
    """tpumt-report renders a tuning table from the sweep's JSONL."""
    from tpu_mpi_tests.instrument.aggregate import summarize

    f = tmp_path / "run.jsonl"
    recs = [
        {"kind": "tune", "knob": "halo/staging", "candidate": "direct",
         "seconds": 2e-4, "fingerprint": "f"},
        {"kind": "tune", "knob": "halo/staging", "candidate": "device",
         "seconds": 1e-4, "fingerprint": "f"},
        {"kind": "tune", "knob": "halo/staging", "candidate": "pallas",
         "seconds": None, "error": "ValueError: floor",
         "fingerprint": "f"},
        {"kind": "tune", "knob": "halo/staging", "candidate": "x",
         "skipped": "budget", "fingerprint": "f"},
        # NaN measurement (seconds=null, no error): invalid, never
        # countable as measured — the table must match the raw records
        {"kind": "tune", "knob": "halo/staging", "candidate": "host",
         "seconds": None, "fingerprint": "f"},
        {"kind": "tune_result", "knob": "halo/staging",
         "value": "device", "seconds": 1e-4, "measured": 2,
         "skipped": 1, "fingerprint": "f"},
        {"kind": "tune_hit", "knob": "flash_tiles/contig",
         "value": {"k_tile": 1024, "skip_tile": 0}, "fingerprint": "f"},
    ]
    f.write_text("".join(json.dumps(r) + "\n" for r in recs))
    summary = summarize([str(f)])
    t = summary["tuning"]["halo/staging"]
    assert t == {"measured": 2, "skipped": 1, "errors": 1, "invalid": 1,
                 "hits": 0, "winner": "device", "winner_seconds": 1e-4}
    hit = summary["tuning"]["flash_tiles/contig"]
    assert hit["hits"] == 1
    assert hit["winner"] == {"k_tile": 1024, "skip_tile": 0}
    # and the whole summary stays JSON-serializable (--json path)
    json.dumps(summary)


def test_driver_flags_exist():
    """Every driver inherits --tune/--tune-cache/--tune-budget/
    --compile-cache through the shared base parser."""
    from tpu_mpi_tests.drivers._common import base_parser

    p = base_parser("t")
    args = p.parse_args([
        "--tune", "--tune-cache", "/tmp/x.json", "--tune-budget", "5",
        "--compile-cache", "/tmp/cc",
    ])
    assert args.tune and args.tune_cache == "/tmp/x.json"
    assert args.tune_budget == 5.0
    assert args.compile_cache == "/tmp/cc"
    defaults = p.parse_args([])
    assert not defaults.tune and defaults.tune_cache is None


# -------------------------------------------------------------- helpers


class _FakeZg:
    """Just enough array surface for the staging-context composer."""

    shape = (1024, 64)
    dtype = "float32"


def _fake_zg():
    return _FakeZg()


def _staging_fp():
    """The exact key resolve_staging composes for _fake_zg (the staging
    knob is context-sensitive: no device-only fallback)."""
    from tpu_mpi_tests.comm.halo import _staging_context

    return fingerprint(**_staging_context(_fake_zg(), 0, 2))


def _import_bench():
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


# ---------------------------------------------------- stencil/tier (ISSUE 15)


def test_stencil_tier_space_and_prior_parity():
    """The kernel-tier space is declared with the fused tier as a
    sweepable candidate, prior first; an unconfigured registry resolves
    the shipped "blocks" prior (pre-ISSUE-15 schedule, byte-identical)
    and malformed cache values degrade to it."""
    from tpu_mpi_tests.comm.halo import (
        STENCIL_TIERS,
        resolve_stencil_tier,
    )

    sp = tr.space("stencil/tier")
    assert sp.prior == priors.STENCIL_TIER == "blocks"
    assert "rdma-fused" in sp.candidates
    assert set(sp.candidates) == set(STENCIL_TIERS)
    assert tr.configured_cache() is None
    assert resolve_stencil_tier(None, dtype="float32", n=8192,
                                world=1) == "blocks"
    # explicit wins
    assert resolve_stencil_tier("rdma-fused", dtype="float32", n=8192,
                                world=1) == "rdma-fused"


def test_stencil_tier_cached_winner_and_malformed_degrade(tmp_path):
    from tpu_mpi_tests.comm.halo import resolve_stencil_tier

    tr.configure(cache_path=str(tmp_path / "t.json"))
    cache = tr.configured_cache()
    ctx = dict(dtype="float32", n=4096, world=2)
    cache.store("stencil/tier", fingerprint(**ctx), "rdma-fused")
    assert resolve_stencil_tier(None, **ctx) == "rdma-fused"
    # a winner tuned at one context must not leak through the
    # device-only slot (device_fallback=False)
    assert resolve_stencil_tier(None, dtype="bfloat16", n=4096,
                                world=2) == "blocks"
    # malformed cache value -> prior, never a crash
    cache.store("stencil/tier", fingerprint(**ctx), "warp-drive")
    assert resolve_stencil_tier(None, **ctx) == "blocks"


# ------------------------------------------------- ring/tier (ISSUE 19)


def test_ring_tier_space_and_prior_parity():
    """The K/V-rotation tier space is declared with the fused one-launch
    kernel as a sweepable candidate, prior first; an unconfigured
    registry resolves the shipped "pipelined" prior (pre-ISSUE-19
    schedule, byte-identical) and explicit wins."""
    from tpu_mpi_tests.comm.ring import _resolve_ring_tier

    sp = tr.space("ring/tier")
    assert sp.prior == priors.RING_TIER == "pipelined"
    assert "fused" in sp.candidates
    assert tr.configured_cache() is None
    assert _resolve_ring_tier(None, dtype="float32", lq=16) == \
        "pipelined"
    # explicit wins
    assert _resolve_ring_tier("fused", dtype="float32", lq=16) == \
        "fused"


def test_ring_tier_cached_winner_and_malformed_degrade(tmp_path):
    from tpu_mpi_tests.comm.ring import _resolve_ring_tier

    tr.configure(cache_path=str(tmp_path / "t.json"))
    cache = tr.configured_cache()
    ctx = dict(dtype="float32", lq=16)
    cache.store("ring/tier", fingerprint(**ctx), "fused")
    assert _resolve_ring_tier(None, **ctx) == "fused"
    # a winner tuned at one geometry must not leak to another via the
    # device-only slot (device_fallback=False — feasibility is
    # lq/d/dtype-dependent)
    assert _resolve_ring_tier(None, dtype="bfloat16", lq=16) == \
        "pipelined"
    # malformed cache value -> prior, never a crash
    cache.store("ring/tier", fingerprint(**ctx), "warp-drive")
    assert _resolve_ring_tier(None, **ctx) == "pipelined"


def test_coll_variant_spaces_carry_oneshot_candidate():
    """ISSUE 19 tentpole wiring contract: the one-shot in-kernel tier
    enters the EXISTING ``coll_variant/*`` spaces as a candidate — the
    prior stays "xla" (untuned runs unchanged), and the PR-4/14
    sweeper/serve machinery picks it up with zero new wiring."""
    from tpu_mpi_tests.drivers import collbench  # noqa: F401 declares

    for coll in ("allgather", "allreduce"):
        sp = tr.space(f"coll_variant/{coll}")
        assert sp.prior == priors.COLL_VARIANT == "xla"
        assert "oneshot" in sp.candidates
        assert "rdma" in sp.candidates
        assert sp.candidates[0] == "xla"


def test_decode_serve_handler_hot_swaps_cached_oneshot(tmp_path, mesh8):
    """ISSUE 19 satellite: a cached in-kernel ("oneshot") winner for a
    decode-class payload is picked up by the decode serve handler with
    zero new wiring — cached > prior through the SAME
    ``coll_variant/allreduce`` resolution the DECODE rows consume — and
    a malformed cache value degrades the rebuilt handler to the "xla"
    prior instead of crashing the class."""
    from tpu_mpi_tests.drivers import _common

    tr.configure(cache_path=str(tmp_path / "t.json"))
    cache = tr.configured_cache()
    # decode class (batch=1, heads=8) f32 on world=8: 32 B per shard —
    # below every ring floor; only the pad-to-tile one-shot tier admits it
    ctx = dict(dtype="float32", bytes=32, world=8)
    cache.store("coll_variant/allreduce", fingerprint(**ctx), "oneshot")
    step = _common.workload_factory("decode")(mesh8, (1, 8), "float32")
    assert step.tune_info["variant"] == "oneshot"
    step(2)  # the in-kernel tier actually serves traffic
    # malformed cache value: the rebuilt handler degrades to the prior
    cache.store("coll_variant/allreduce", fingerprint(**ctx), "garbage")
    rebuilt = step.tune_info["rebuild"]()
    assert rebuilt.tune_info["variant"] == "xla"
    rebuilt(2)


def test_stencil_tier_sweep_visible_degrade(tmp_path):
    """The acceptance shape (ISSUE 15): the fused tier is MEASURED and
    honestly declined when slower — its seconds land in the tune
    records (a visible-degrade record), the faster tier wins and
    persists."""
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True,
                 budget_s=60.0)
    timing = {"blocks": 0.2, "rdma-chained": 0.3, "rdma-fused": 0.5,
              "xla": 0.9}
    records = []
    winner = sweep(
        "stencil/tier", lambda cand: timing[cand],
        emit=records.append, dtype="float32", n=8192, world=1,
    )
    assert winner == "blocks"
    fused = [r for r in records
             if r["kind"] == "tune" and r["candidate"] == "rdma-fused"]
    assert len(fused) == 1 and fused[0]["seconds"] == 0.5
    assert records[-1]["kind"] == "tune_result"
    assert records[-1]["value"] == "blocks"
    # and the persisted winner resolves at the same context
    from tpu_mpi_tests.comm.halo import resolve_stencil_tier

    assert resolve_stencil_tier(None, dtype="float32", n=8192,
                                world=1) == "blocks"
