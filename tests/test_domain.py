import numpy as np
import pytest

from tpu_mpi_tests.arrays.domain import Domain1D, Domain2D
from tpu_mpi_tests.utils import TpuMtError


def x_cubed(x):
    return x**3


class TestDomain1D:
    def test_sizes(self):
        d = Domain1D(n_global=64, n_shards=4, n_bnd=2)
        assert d.n_local == 16
        assert d.n_ghosted == 20
        assert d.delta == 8.0 / 64
        assert d.scale == 64 / 8.0

    def test_divisibility_fail_fast(self):
        with pytest.raises(TpuMtError):
            Domain1D(n_global=10, n_shards=3)

    def test_coords_continuous_across_shards(self):
        d = Domain1D(n_global=64, n_shards=4)
        xs = np.concatenate([d.interior_coords(r) for r in range(4)])
        np.testing.assert_allclose(xs, np.arange(64) * d.delta)

    def test_ghost_coords_extend_grid(self):
        d = Domain1D(n_global=64, n_shards=4, n_bnd=2)
        g = d.ghosted_coords(1)
        i = d.interior_coords(1)
        np.testing.assert_allclose(g[2:-2], i)
        # ghosts continue the same grid
        np.testing.assert_allclose(g[1] - g[0], d.delta)
        # rank 1's left ghosts == rank 0's last interior points
        np.testing.assert_allclose(g[:2], d.interior_coords(0)[-2:])

    def test_init_shard_physical_ghosts(self):
        d = Domain1D(n_global=32, n_shards=4, n_bnd=2)
        s0 = d.init_shard(x_cubed, 0)
        # left physical ghosts: x = -2*delta, -delta (mpi_stencil_gt.cc:186-189)
        np.testing.assert_allclose(
            s0[:2], [(-2 * d.delta) ** 3, (-d.delta) ** 3]
        )
        s_last = d.init_shard(x_cubed, 3)
        np.testing.assert_allclose(
            s_last[-2:], [d.length**3, (d.length + d.delta) ** 3]
        )
        # interior ghosts of middle shards start zero (to be halo-filled)
        s1 = d.init_shard(x_cubed, 1)
        assert (s1[:2] == 0).all() and (s1[-2:] == 0).all()

    def test_strip_ghosts_roundtrip(self):
        d = Domain1D(n_global=32, n_shards=4, n_bnd=2)
        zg = d.init_global(x_cubed)
        assert zg.shape == (4 * 12,)
        interior = d.strip_ghosts_global(zg)
        np.testing.assert_allclose(interior, d.interior_global(x_cubed))


def z_fn(x, y):
    return x**3 + y**2


class TestDomain2D:
    @pytest.mark.parametrize("dim", [0, 1])
    def test_shapes(self, dim):
        d = Domain2D(
            n_local_deriv=8, n_global_other=6, n_shards=4, dim=dim, n_bnd=2
        )
        assert d.local_shape[dim] == 8
        assert d.local_shape[1 - dim] == 6
        assert d.ghosted_shape[dim] == 12
        assert d.global_ghosted_shape[dim] == 48
        assert d.global_interior_shape[dim] == 32

    @pytest.mark.parametrize("dim", [0, 1])
    def test_strip_ghosts_matches_interior(self, dim):
        d = Domain2D(
            n_local_deriv=8, n_global_other=6, n_shards=4, dim=dim, n_bnd=2
        )
        zg = d.init_global(z_fn)
        np.testing.assert_allclose(
            d.strip_ghosts_global(zg), d.interior_global(z_fn)
        )

    @pytest.mark.parametrize("dim", [0, 1])
    def test_edge_shard_physical_ghosts_filled(self, dim):
        d = Domain2D(
            n_local_deriv=8, n_global_other=6, n_shards=4, dim=dim, n_bnd=2
        )
        s0 = d.init_shard(z_fn, 0)
        lo = [slice(None)] * 2
        lo[dim] = slice(0, 2)
        assert (s0[tuple(lo)] != 0).any()
        s1 = d.init_shard(z_fn, 1)
        assert (s1[tuple(lo)] == 0).all()

    def test_ghost_continuity_between_shards(self):
        d = Domain2D(
            n_local_deriv=8, n_global_other=6, n_shards=4, dim=0, n_bnd=2
        )
        # what rank 1's left ghost *should* hold equals rank 0's last interior
        x1, y1 = d._coords(1, ghosted=True, dtype=np.float64)
        x0, _ = d._coords(0, ghosted=False, dtype=np.float64)
        np.testing.assert_allclose(x1[:2], x0[-2:])


def test_device_init_matches_host_blocks_1d(mesh8):
    """Traced (device_init) and host (shard_blocks) init paths must agree —
    same ghost masking, same coordinates."""
    import jax.numpy as jnp

    from tpu_mpi_tests.arrays.domain import Domain1D
    from tpu_mpi_tests.comm.collectives import device_init, shard_blocks
    from tpu_mpi_tests.kernels.stencil import analytic_pairs

    d = Domain1D(n_global=8 * 64, n_shards=8)
    f, df = analytic_pairs()["1d"]
    dev = device_init(
        mesh8, lambda r: d.init_shard_jax(f, r, jnp.float64), ndim=1
    )
    host = shard_blocks(
        mesh8,
        (8 * d.n_ghosted,),
        np.float64,
        lambda r: d.init_shard(f, r, np.float64),
    )
    assert np.allclose(np.asarray(dev), np.asarray(host), atol=1e-9)


@pytest.mark.parametrize("dim", [0, 1])
def test_device_init_matches_host_blocks_2d(mesh8, dim):
    import jax.numpy as jnp

    from tpu_mpi_tests.arrays.domain import Domain2D
    from tpu_mpi_tests.comm.collectives import device_init, shard_blocks
    from tpu_mpi_tests.kernels.stencil import analytic_pairs

    d = Domain2D(
        n_local_deriv=16, n_global_other=24, n_shards=8, dim=dim
    )
    f, _ = analytic_pairs()[f"2d_dim{dim}"]
    dev = device_init(
        mesh8, lambda r: d.init_shard_jax(f, r, jnp.float64), axis=dim
    )
    host = shard_blocks(
        mesh8,
        d.global_ghosted_shape,
        np.float64,
        lambda r: d.init_shard(f, r, np.float64),
        axis=dim,
    )
    assert np.allclose(np.asarray(dev), np.asarray(host), atol=1e-9)
