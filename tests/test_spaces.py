import jax.numpy as jnp
import numpy as np

from tpu_mpi_tests.arrays.spaces import (
    Space,
    meminfo,
    nbytes_report,
    place,
    to_device,
)


def test_space_parse():
    assert Space.parse("device") is Space.DEVICE
    assert Space.parse("MANAGED") is Space.MANAGED
    assert Space.parse(Space.HOST) is Space.HOST


def test_place_roundtrip_all_spaces():
    x = np.arange(16, dtype=np.float32)
    for space in Space:
        y = place(x, space)
        np.testing.assert_array_equal(np.asarray(y), x)


def test_to_device():
    x = jnp.arange(8.0)
    y = to_device(x)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_meminfo_reports_placement():
    x = place(np.zeros(4, np.float32), Space.DEVICE)
    s = meminfo(x)
    assert "nbytes=16" in s and "devices=" in s
    assert meminfo(np.zeros(3)).startswith("host(")


def test_nbytes_report():
    a = jnp.zeros((1024, 1024), jnp.float32)
    s = nbytes_report(a, a)
    assert "2 arrays" in s and "8.0 MiB" in s
