"""Smoke tests for the repo-root entry points the benchmark harness calls:
``bench.py`` (one JSON line) and ``__graft_entry__`` (single-chip compile +
multi-chip dryrun). Run in subprocesses because each needs its own backend
configuration."""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def run_py(code: str, env_extra: dict | None = None):
    env = dict(os.environ)
    # a clean backend per subprocess; the conftest's fake-device setup must
    # not leak in (both the platform pin and the fake-device count flag)
    env.pop("JAX_PLATFORMS", None)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(flags)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )


def test_bench_prints_one_json_line_smoke():
    r = run_py(
        "import bench; bench.main()",
        {
            "TPU_MPI_BENCH_N": "128",
            # the difference timing needs enough iterations that real work
            # dominates timer noise, or the sign can flip; fake devices
            # force the CPU backend (env JAX_PLATFORMS alone is overridden
            # by the image's sitecustomize) and exercise the sharded path
            "TPU_MPI_BENCH_ITERS_SHORT": "50",
            "TPU_MPI_BENCH_ITERS_LONG": "1050",
            "TPU_MPI_BENCH_FAKE_DEVICES": "4",
            # 2 samples: covers the samples-list schema + median bound at
            # a fraction of the real-run default of 5
            "TPU_MPI_BENCH_SAMPLES": "2",
        },
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must stay ONE line, got {lines}"
    rec = json.loads(lines[-1])
    per_dtype = {"value", "unit", "vs_baseline",
                 "vs_f64_reference_roofline", "dtype", "samples",
                 "schedule", "steps", "tier", "topology"}
    # round 5 (VERDICT r4 #3): one invocation carries BOTH dtypes — the
    # primary keeps the top-level headline fields, the secondary is a
    # same-shaped sub-object under its dtype name
    assert set(rec) == {"metric"} | per_dtype | {"bfloat16"}
    assert rec["dtype"] == "float32"
    assert rec["value"] > 0
    # the reported value is the median of the recorded (finite) samples;
    # both are independently rounded to 2 dp, so allow half-step slack
    finite = [s for s in rec["samples"] if s is not None]
    assert finite
    assert min(finite) - 0.01 <= rec["value"] <= max(finite) + 0.01
    sub = rec["bfloat16"]
    assert set(sub) == per_dtype
    assert sub["dtype"] == "bfloat16"
    assert sub["value"] > 0
    assert sub["schedule"].startswith("dim1_")
    # tier provenance (ISSUE 15): the schedule string and the JSON both
    # name the EXECUTING kernel tier — xla is the only CPU tier — and
    # the trailing token stamps the host topology (ISSUE 20:
    # unconditional, h1x<world> on a flat 4-fake-device mesh)
    assert rec["tier"] == "xla" and sub["tier"] == "xla"
    assert rec["schedule"].endswith("_xla_h1x4")
    assert sub["schedule"].endswith("_xla_h1x4")
    assert rec["topology"] == "h1x4" and sub["topology"] == "h1x4"


def test_bench_second_dtype_disable():
    r = run_py(
        "import bench; bench.main()",
        {
            "TPU_MPI_BENCH_N": "128",
            "TPU_MPI_BENCH_ITERS_SHORT": "50",
            "TPU_MPI_BENCH_ITERS_LONG": "1050",
            "TPU_MPI_BENCH_FAKE_DEVICES": "4",
            "TPU_MPI_BENCH_SAMPLES": "1",
            "TPU_MPI_BENCH_SECOND_DTYPE": "none",
        },
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(r.stdout.splitlines()[-1])
    assert "bfloat16" not in rec


def test_graft_entry_single_chip():
    # force_cpu_devices: the env var alone is overridden by the image's
    # sitecustomize, which would silently run this on the TPU tunnel
    r = run_py(
        "from tpu_mpi_tests.drivers._common import force_cpu_devices\n"
        "force_cpu_devices(1)\n"
        "import jax, __graft_entry__ as g\n"
        "fn, args = g.entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "jax.block_until_ready(out)\n"
        "print('OK', jax.tree.map(lambda x: x.shape, out))\n",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "OK" in r.stdout


def test_tpumt_trace_help():
    """The tpumt-trace console script parses --help (and pyproject maps
    the script to the module entry, so the installed binary and the
    ``python -m`` form stay one implementation)."""
    r = run_py(
        "import sys, tpu_mpi_tests.instrument.timeline as t\n"
        "try:\n"
        "    t.main(['--help'])\n"
        "except SystemExit as e:\n"
        "    sys.exit(e.code or 0)\n"
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "tpumt-trace" in r.stdout
    assert "Perfetto" in r.stdout
    pyproject = (REPO / "pyproject.toml").read_text()
    assert ('tpumt-trace = "tpu_mpi_tests.instrument.timeline:main"'
            in pyproject)


def test_tpumt_lint_runs_without_jax(tmp_path):
    """The tpumt-lint console script must import, parse --help, AND
    produce findings in a process where ``import jax`` raises — the
    same login-node guarantee tpumt-report/tpumt-trace already claim
    (the linter is pure stdlib: ast + tokenize). ISSUE 10 extends the
    golden to a WHOLE-PROGRAM run: the interprocedural pass (a
    use-after-donate through a helper in another file) and the analysis
    cache (off, cold, and warm — zero files re-parsed) must all work
    under the jax-blocking meta_path hook too."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\n"
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = jnp.sin(x)\n"
        "    return y, time.perf_counter() - t0\n"
    )
    # a cross-file finding: the helper forwards into allreduce_sum's
    # donated position, the driver reads the donated name afterwards
    pkg = tmp_path / "proj" / "dnt"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "helper.py").write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def reduce_into(buf, mesh):\n"
        "    return allreduce_sum(buf, mesh)\n"
    )
    (pkg / "driver.py").write_text(
        "from dnt.helper import reduce_into\n"
        "def step(x, mesh):\n"
        "    total = reduce_into(x, mesh)\n"
        "    return x + total\n"
    )
    proj = str(tmp_path / "proj")
    cache = str(tmp_path / "lint_cache.json")
    code = (
        "import sys\n"
        "class Block:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('jax blocked: login-node sim')\n"
        "sys.meta_path.insert(0, Block())\n"
        "from tpu_mpi_tests.analysis import cli\n"
        "from tpu_mpi_tests.analysis.core import lint_paths\n"
        "try:\n"
        "    cli.main(['--help'])\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        f"assert cli.main([{str(bad)!r}]) == 1\n"
        f"assert cli.main(['--ignore', 'TPM1', {str(bad)!r}]) == 0\n"
        f"assert cli.main(['--no-cache', {proj!r}]) == 1\n"
        f"s1 = {{}}; f1 = lint_paths([{proj!r}], cache_path={cache!r},\n"
        "                           stats=s1)\n"
        "assert [f.code for f in f1] == ['TPM1201'], f1\n"
        "assert s1['analyzed'] == 3 and s1['cache_hits'] == 0, s1\n"
        f"s2 = {{}}; f2 = lint_paths([{proj!r}], cache_path={cache!r},\n"
        "                           stats=s2)\n"
        "assert f2 == f1, f2\n"
        "assert s2['analyzed'] == 0 and s2['cache_hits'] == 3, s2\n"
        "print('LINT NOJAX WHOLE-PROGRAM OK')\n"
    )
    r = run_py(code)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "LINT NOJAX WHOLE-PROGRAM OK" in r.stdout
    assert "tpumt-lint" in r.stdout  # --help went to stdout
    assert "TPM1201" in r.stdout  # the cross-file finding printed
    pyproject = (REPO / "pyproject.toml").read_text()
    assert 'tpumt-lint = "tpu_mpi_tests.analysis.cli:main"' in pyproject


def test_tpumt_doctor_runs_without_jax(tmp_path):
    """The tpumt-doctor console script must import, parse --help, AND
    diagnose in a process where ``import jax`` raises — the login-node
    contract tpumt-report/tpumt-trace/tpumt-lint already claim (the
    doctor triages files copied OFF the pod)."""
    import json as _json

    def rec(lines, path):
        path.write_text("".join(_json.dumps(r) + "\n" for r in lines))

    span = lambda rank, t: {  # noqa: E731 — local literal builder
        "kind": "span", "op": "allreduce", "world": 2,
        "seconds": 0.01, "t_start": t, "t_end": t + 0.01, "rank": rank}
    man = lambda rank: {  # noqa: E731
        "kind": "manifest", "process_index": rank, "process_count": 2}
    rec([man(0)] + [span(0, 100.0 + i) for i in range(10)]
        + [{"kind": "telemetry_summary", "op": "x", "rank": 0},
           {"kind": "mem", "event": "final", "t": 110.0}],
        tmp_path / "run.p0.jsonl")
    rec([man(1)] + [span(1, 100.0 + i) for i in range(3)],
        tmp_path / "run.p1.jsonl")
    code = (
        "import sys\n"
        "class Block:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('jax blocked: login-node sim')\n"
        "sys.meta_path.insert(0, Block())\n"
        "from tpu_mpi_tests.instrument import diagnose\n"
        "try:\n"
        "    diagnose.main(['--help'])\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        f"base = {str(tmp_path / 'run.jsonl')!r}\n"
        "assert diagnose.main([base]) == 1\n"
        "assert diagnose.main([base, '--expect',\n"
        "                      'missing_rank:1']) == 0\n"
        "print('DOCTOR NOJAX OK')\n"
    )
    r = run_py(code)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "DOCTOR NOJAX OK" in r.stdout
    assert "FINDING missing_rank: rank=1" in r.stdout
    pyproject = (REPO / "pyproject.toml").read_text()
    assert ('tpumt-doctor = "tpu_mpi_tests.instrument.diagnose:main"'
            in pyproject)


def test_tpumt_top_runs_without_jax(tmp_path):
    """The tpumt-top console script and the OpenMetrics renderer must
    import, parse --help, render a frame over a golden JSONL tail, and
    expose well-formed OpenMetrics in a process where ``import jax``
    raises — the login-node contract of the other CLIs, applied to a
    run that has not ended yet (files tailed off a shared fs)."""
    import json as _json

    recs = [
        {"kind": "manifest", "process_index": 0, "process_count": 1,
         "platform": "cpu", "global_device_count": 2},
        {"kind": "span", "op": "halo_exchange", "nbytes": 1 << 20,
         "world": 2, "seconds": 0.01, "gbps": 0.105,
         "t_start": 100.0, "t_end": 100.01},
        {"kind": "serve", "event": "window", "class": "daxpy:64:float32",
         "arrivals": 5, "requests": 5, "errors": 0, "shed": 0,
         "queue_depth": 1, "p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": 3.0,
         "offered_hz": 5.0, "achieved_hz": 5.0, "t_end": 101.0},
        {"kind": "health", "event": "heartbeat", "rank": 0, "seq": 1,
         "t": 101.5},
    ]
    (tmp_path / "run.jsonl").write_text(
        "".join(_json.dumps(r) + "\n" for r in recs))
    code = (
        "import sys\n"
        "class Block:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('jax blocked: login-node sim')\n"
        "sys.meta_path.insert(0, Block())\n"
        "from tpu_mpi_tests.instrument import live\n"
        "from tpu_mpi_tests.instrument.export import render_openmetrics\n"
        "from tpu_mpi_tests.instrument.metrics import MetricsRegistry\n"
        "try:\n"
        "    live.main(['--help'])\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        f"base = {str(tmp_path / 'run.jsonl')!r}\n"
        "assert live.main([base]) == 0\n"
        "reg = MetricsRegistry()\n"
        "import json\n"
        "for ln in open(base):\n"
        "    reg.observe(json.loads(ln))\n"
        "text = render_openmetrics(reg)\n"
        "assert text.rstrip().endswith('# EOF'), text[-50:]\n"
        "assert 'tpumt_serve_requests_total' in text\n"
        "print('TOP NOJAX OK')\n"
    )
    r = run_py(code)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "TOP NOJAX OK" in r.stdout
    assert "halo_exchange" in r.stdout  # the rendered OPS row
    assert "daxpy:64:float32" in r.stdout  # the rendered SLO row
    pyproject = (REPO / "pyproject.toml").read_text()
    assert ('tpumt-top = "tpu_mpi_tests.instrument.live:main"'
            in pyproject)


def test_tpumt_doctor_follow_runs_without_jax(tmp_path):
    """--follow (the online doctor) shares the login-node contract:
    tail + convict with jax blocked."""
    import json as _json

    recs0 = [{"kind": "manifest", "process_index": 0,
              "process_count": 2}]
    recs1 = [{"kind": "manifest", "process_index": 1,
              "process_count": 2}]
    for i in range(1, 11):
        t = 100.0 + i
        recs0.append({"kind": "time", "event": "progress",
                      "phase": "kernel", "seconds": 0.1 * i,
                      "count": 5 * i, "t": t})
        recs1.append({"kind": "time", "event": "progress",
                      "phase": "kernel", "seconds": 0.5 * i,
                      "count": 5 * i, "t": t})
    for recs, name in ((recs0, "run.p0.jsonl"), (recs1, "run.p1.jsonl")):
        (tmp_path / name).write_text(
            "".join(_json.dumps(r) + "\n" for r in recs))
    code = (
        "import sys\n"
        "class Block:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('jax blocked: login-node sim')\n"
        "sys.meta_path.insert(0, Block())\n"
        "from tpu_mpi_tests.instrument import diagnose\n"
        f"base = {str(tmp_path / 'run.jsonl')!r}\n"
        "assert diagnose.main([base, '--follow', '--expect',\n"
        "                      'straggler:1', '--interval', '0.05',\n"
        "                      '--timeout', '20']) == 0\n"
        "print('FOLLOW NOJAX OK')\n"
    )
    r = run_py(code)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "FOLLOW NOJAX OK" in r.stdout


def test_graft_dryrun_multichip():
    r = run_py(
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(8)\n"
        "print('DRYRUN OK')\n",
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "DRYRUN OK" in r.stdout
