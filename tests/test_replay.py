"""Traffic record/replay (serve/replay.py + tpumt-serve --record/--replay).

The artifact layer (fingerprint, save/load validation, ReplayArrivals)
is pure stdlib and tested directly; the determinism contract — two
replays of one artifact are byte-identical — is pinned at the loop
level under a fake clock, because on real clocks sub-millisecond CPU
service times jitter (replay-smoke applies the serve-smoke rc contract
for exactly that reason; here the invariant holds exactly).
"""

from __future__ import annotations

import json

import pytest

from tpu_mpi_tests.serve.arrival import OpenLoopPoisson
from tpu_mpi_tests.serve.loop import ServeLoop
from tpu_mpi_tests.serve.replay import (
    TRAFFIC_FORMAT,
    TRAFFIC_VERSION,
    ReplayArrivals,
    TrafficFormatError,
    TrafficRecorder,
    load_traffic,
    save_traffic,
    traffic_fingerprint,
)
from tpu_mpi_tests.serve.workloads import parse_workload_table


EVENTS = [(0.0, "a:1:f32"), (0.25, "b:2:f32"), (0.25, "a:1:f32"),
          (1.5, "a:1:f32")]


# ---------------------------------------------------------------------------
# fingerprint
# ---------------------------------------------------------------------------


def test_fingerprint_stable_and_sensitive():
    fp = traffic_fingerprint(EVENTS, 2.0)
    assert fp == traffic_fingerprint(list(EVENTS), 2.0)
    # every component of the identity moves it: a time, a key, the
    # count, the duration
    assert fp != traffic_fingerprint(
        [(0.001, "a:1:f32")] + EVENTS[1:], 2.0)
    assert fp != traffic_fingerprint(
        [(0.0, "b:2:f32")] + EVENTS[1:], 2.0)
    assert fp != traffic_fingerprint(EVENTS[:-1], 2.0)
    assert fp != traffic_fingerprint(EVENTS, 3.0)


def test_fingerprint_robust_to_float_json_roundtrip():
    """Identity survives a JSON round-trip (the artifact is JSON): the
    microsecond rounding absorbs sub-us float noise, while a full
    microsecond of drift is a different schedule."""
    jittered = [(t + 4e-8, k) for t, k in EVENTS]
    assert traffic_fingerprint(EVENTS, 2.0) \
        == traffic_fingerprint(jittered, 2.0)
    shifted = [(EVENTS[0][0] + 1e-6, EVENTS[0][1])] + EVENTS[1:]
    assert traffic_fingerprint(EVENTS, 2.0) \
        != traffic_fingerprint(shifted, 2.0)


# ---------------------------------------------------------------------------
# recorder + artifact save/load
# ---------------------------------------------------------------------------


def _artifact(events=EVENTS, duration=2.0):
    rec = TrafficRecorder(arrival="poisson", load="test")
    for t, k in events:
        rec.add(t, k)
    return rec.finalize(duration)


def test_recorder_roundtrip(tmp_path):
    art = _artifact()
    assert art["format"] == TRAFFIC_FORMAT
    assert art["version"] == TRAFFIC_VERSION
    assert art["count"] == 4 and art["duration_s"] == 2.0
    assert art["classes"] == {"a:1:f32": 3, "b:2:f32": 1}
    assert art["fingerprint"] == traffic_fingerprint(EVENTS, 2.0)
    p = tmp_path / "t.json"
    save_traffic(str(p), art)
    assert load_traffic(str(p)) == json.loads(p.read_text())
    assert load_traffic(str(p))["fingerprint"] == art["fingerprint"]


def test_load_refuses_bad_artifacts(tmp_path):
    """Every defect class raises TrafficFormatError (the driver's
    NOTE + exit 2 path), never a crash or a silent partial replay."""
    p = tmp_path / "t.json"

    def refused(doc):
        p.write_text(doc if isinstance(doc, str) else json.dumps(doc))
        with pytest.raises(TrafficFormatError):
            load_traffic(str(p))

    with pytest.raises(TrafficFormatError):
        load_traffic(str(tmp_path / "missing.json"))
    refused("{not json")
    refused({"format": "something-else", "version": 1})
    art = _artifact()
    refused({**art, "version": TRAFFIC_VERSION + 1})
    refused({**art, "events": [[0.0], [1.0, "k"]]})
    refused({**art, "events": [["x", "k"]]})
    refused({**art, "count": art["count"] + 1})
    refused({**art, "events": [[1.0, "a:1:f32"], [0.5, "a:1:f32"]],
             "count": 2})
    # a tampered stream fails the fingerprint self-check
    tampered = {**art,
                "events": [[t, "b:2:f32"] for t, _ in art["events"]]}
    refused(tampered)


# ---------------------------------------------------------------------------
# ReplayArrivals semantics
# ---------------------------------------------------------------------------


def test_replay_arrivals_schedule_and_classes():
    r = ReplayArrivals(_artifact())
    assert r.take_due(100.0) == []  # not started yet
    r.start(10.0)
    assert r.next_event() == 10.0
    assert r.take_due(10.3) == [10.0, 10.25, 10.25]
    assert [r.draw_class() for _ in range(3)] \
        == ["a:1:f32", "b:2:f32", "a:1:f32"]
    # limit is an absolute cutoff, same as OpenLoopPoisson
    assert r.take_due(100.0, limit=11.0) == []
    assert r.next_event() == 11.5
    assert r.take_due(100.0) == [11.5]
    assert r.draw_class() == "a:1:f32"
    # exhausted: no more events, no more keys
    assert r.next_event() is None and r.take_due(100.0) == []
    assert r.draw_class() is None
    r.on_complete(3, 12.0)  # no-op: replay is open-loop by construction
    assert r.next_event() is None
    # start() rewinds both cursors
    r.start(0.0)
    assert r.take_due(0.0) == [0.0] and r.draw_class() == "a:1:f32"


# ---------------------------------------------------------------------------
# loop-level determinism under a fake clock
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def clock(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


def _loop_run(arrival, recorder=None, duration=6.0, service_s=0.001):
    clk = FakeClock()
    classes = parse_workload_table(
        "daxpy:128:float32:3,allreduce:64:float32:1")
    records = []

    def handler(n):
        clk.t += service_s * n

    loop = ServeLoop(
        classes, {c.key: handler for c in classes}, arrival,
        duration_s=duration, max_batch=8, window_s=2.0, seed=5,
        sink=records.append, recorder=recorder,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    summaries = loop.run()
    return records, summaries


def test_record_then_replay_reproduces_traffic_exactly():
    """The tentpole determinism contract, exact under a fake clock:
    record a Poisson run, replay it twice — the two replays emit
    byte-identical record streams, and re-recording DURING a replay
    reproduces the original artifact fingerprint (round-trip
    identity)."""
    rec = TrafficRecorder(arrival="poisson", load="test")
    _, rec_sum = _loop_run(OpenLoopPoisson(40.0, seed=5), recorder=rec)
    art = rec.finalize(6.0)
    assert art["count"] == sum(s["arrivals"] for s in rec_sum)

    rerec = TrafficRecorder(arrival="replay", load="test")
    r1, s1 = _loop_run(ReplayArrivals(art), recorder=rerec)
    r2, s2 = _loop_run(ReplayArrivals(art))
    assert json.dumps(r1) == json.dumps(r2)  # byte-identical streams
    # the replay serves the recorded load class-for-class
    assert {s["class"]: s["arrivals"] for s in s1} == art["classes"]
    # replay -> re-record round-trips to the same traffic identity
    assert rerec.finalize(6.0)["fingerprint"] == art["fingerprint"]


def test_two_replays_diff_clean_and_recorded_run_comparable(tmp_path):
    """tpumt-report --diff between two fake-clock replays exits 0 with
    the fingerprints-match line; a degraded copy of one still trips the
    gate (the mismatch refusal lives in test_report_cli)."""
    from tpu_mpi_tests.instrument import aggregate

    rec = TrafficRecorder(arrival="poisson", load="test")
    _loop_run(OpenLoopPoisson(40.0, seed=5), recorder=rec)
    art = rec.finalize(6.0)

    def run_file(name, degrade=1.0):
        records, _ = _loop_run(ReplayArrivals(art))
        recs = [{"kind": "manifest", "process_index": 0,
                 "process_count": 1},
                {"kind": "traffic", "event": "replay",
                 "fingerprint": art["fingerprint"],
                 "count": art["count"], "duration_s": 6.0, "rank": 0}]
        for r in records:
            if degrade != 1.0 and r.get("kind") == "serve":
                r = {**r, **{k: r[k] * degrade for k in
                             ("p50_ms", "p95_ms", "p99_ms",
                              "qd_p99_ms", "svc_p99_ms") if k in r}}
            recs.append({**r, "rank": 0})
        p = tmp_path / name
        p.write_text("".join(json.dumps(r) + "\n" for r in recs))
        return str(p)

    a, b = run_file("a.jsonl"), run_file("b.jsonl")
    assert aggregate.main(["--diff", a, b]) == 0
    bad = run_file("bad.jsonl", degrade=10.0)
    assert aggregate.main(["--diff", a, bad]) == 1
