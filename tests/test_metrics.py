"""Live observability plane (instrument/metrics.py + export.py): the
record-tee registry, rolling histograms, the tune_stale watermark rule,
OpenMetrics exposition, heartbeats, phase-progress streaming, the
Reporter tee wiring, and the disarmed byte-identity acceptance."""

import json
import os
import re
import subprocess
import sys
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from tpu_mpi_tests.instrument.export import (
    CONTENT_TYPE,
    Heartbeat,
    MetricsExporter,
    render_openmetrics,
)
from tpu_mpi_tests.instrument.metrics import (
    STALE_SAMPLES,
    MetricsRegistry,
    PhaseProgress,
    RollingHistogram,
)
from tpu_mpi_tests.instrument.report import Reporter

REPO = Path(__file__).resolve().parent.parent


def _span(op="allreduce", gbps=None, seconds=0.01, **extra):
    rec = {"kind": "span", "op": op, "nbytes": 1 << 20,
           "world": 2, "seconds": seconds}
    if gbps is not None:
        rec["gbps"] = gbps
    rec.update(extra)
    return rec


class TestRegistry:
    def test_span_records_update_series(self):
        reg = MetricsRegistry()
        for _ in range(3):
            reg.observe(_span(gbps=2.0))
        assert reg.value("tpumt_spans", (("op", "allreduce"),)) == 3
        assert reg.value("tpumt_span_bytes",
                         (("op", "allreduce"),)) == 3 * (1 << 20)
        assert reg.value("tpumt_span_gbps",
                         (("op", "allreduce"),)) == 2.0
        assert reg.value("tpumt_records", (("kind", "span"),)) == 3

    def test_async_spans_keep_their_own_row(self):
        """Dispatch-window spans must not pollute the sync op's series
        — the same [async] split tpumt-report makes."""
        reg = MetricsRegistry()
        reg.observe(_span())
        reg.observe({**_span(), "async": True})
        assert reg.value("tpumt_spans", (("op", "allreduce"),)) == 1
        assert reg.value("tpumt_spans",
                         (("op", "allreduce[async]"),)) == 1

    def test_serve_window_series(self):
        reg = MetricsRegistry()
        win = {"kind": "serve", "event": "window", "class": "c1",
               "arrivals": 10, "requests": 8, "errors": 1, "shed": 1,
               "queue_depth": 3, "queue_max": 7, "p50_ms": 1.0,
               "p95_ms": 2.0, "p99_ms": 3.0, "offered_hz": 5.0,
               "achieved_hz": 4.0}
        reg.observe(win)
        reg.observe(win)
        L = (("class", "c1"),)
        assert reg.value("tpumt_serve_arrivals", L) == 20
        assert reg.value("tpumt_serve_requests", L) == 16
        assert reg.value("tpumt_serve_shed", L) == 2
        # gauge prefers the standing backlog over the high-water mark
        assert reg.value("tpumt_serve_queue_depth", L) == 3
        assert reg.value("tpumt_serve_p99_ms", L) == 3.0

    def test_serve_window_latency_decomposition_gauges(self):
        """The PR-16 latency anatomy rides the same tee: standing
        per-class queue-delay and service p99 gauges, absent (not fake
        zero) for pre-decomposition windows."""
        reg = MetricsRegistry()
        reg.observe({"kind": "serve", "event": "window", "class": "c1",
                     "p99_ms": 3.0, "qd_p99_ms": 2.5,
                     "svc_p99_ms": 0.5})
        L = (("class", "c1"),)
        assert reg.value("tpumt_serve_queue_delay_p99_ms", L) == 2.5
        assert reg.value("tpumt_serve_service_p99_ms", L) == 0.5
        reg2 = MetricsRegistry()
        reg2.observe({"kind": "serve", "event": "window",
                      "class": "c1", "p99_ms": 3.0})
        assert reg2.value("tpumt_serve_queue_delay_p99_ms", L) is None

    def test_serve_window_queue_depth_falls_back_to_queue_max(self):
        reg = MetricsRegistry()
        reg.observe({"kind": "serve", "event": "window", "class": "c1",
                     "queue_max": 7})
        assert reg.value("tpumt_serve_queue_depth",
                         (("class", "c1"),)) == 7

    def test_unknown_kind_only_counts(self):
        reg = MetricsRegistry()
        reg.observe({"kind": "something_new", "v": 1})
        assert reg.value("tpumt_records",
                         (("kind", "something_new"),)) == 1
        assert len(reg.snapshot()) == 1

    def test_series_cap_drops_instead_of_growing(self):
        reg = MetricsRegistry(max_series=8)
        for i in range(50):
            reg.observe(_span(op=f"op{i}"))
        snap = reg.snapshot()
        total = sum(len(f["samples"]) for f in snap.values())
        assert total <= 8 + 1  # the cap plus the drop counter itself
        assert reg.value("tpumt_series_dropped", ()) > 0

    def test_observe_never_raises(self):
        reg = MetricsRegistry()
        reg.observe({"kind": "span", "op": None, "nbytes": "junk",
                     "seconds": object()})
        reg.observe({"no_kind": True})
        reg.observe({"kind": 42})


class TestRollingHistogram:
    def test_window_expiry(self):
        t = [0.0]
        h = RollingHistogram(window_s=6.0, slots=3, clock=lambda: t[0])
        h.record(0.001)
        assert h.merged().count == 1
        t[0] = 3.0
        h.record(0.002)
        assert h.merged().count == 2
        t[0] = 100.0  # far past the window: everything expired
        assert h.merged().count == 0
        h.record(0.003)
        assert h.merged().count == 1

    def test_percentiles_track_recent_window(self):
        t = [0.0]
        h = RollingHistogram(window_s=60.0, slots=6, clock=lambda: t[0])
        for _ in range(100):
            h.record(0.010)
        m = h.merged()
        assert m.percentile(50.0) == pytest.approx(0.010, rel=0.06)


class TestTuneStale:
    def _sink(self):
        out = []
        return out, out.append

    def test_sag_fires_exactly_one_health_record(self):
        out, sink = self._sink()
        reg = MetricsRegistry(health_sink=sink)
        reg.observe({"kind": "tune_hit", "knob": "halo/staging",
                     "value": "DIRECT"})
        for _ in range(STALE_SAMPLES):
            reg.observe(_span(gbps=10.0))
        # 30% below the cached winner's fresh baseline: well past the
        # 15% noise floor
        for _ in range(3 * STALE_SAMPLES):
            reg.observe(_span(gbps=7.0))
        stale = [r for r in out if r.get("event") == "tune_stale"]
        assert len(stale) == 1, out
        rec = stale[0]
        assert rec["kind"] == "health"
        assert rec["op"] == "allreduce"
        assert rec["signal"] == "gbps"
        assert rec["baseline"] == pytest.approx(10.0)
        assert rec["rolling"] == pytest.approx(7.0)
        assert rec["sag_pct"] == pytest.approx(30.0)
        assert "halo/staging" in rec["knobs"]

    def test_inside_noise_band_stays_silent(self):
        out, sink = self._sink()
        reg = MetricsRegistry(health_sink=sink)
        reg.observe({"kind": "tune_result", "knob": "halo/staging",
                     "value": "DIRECT", "seconds": 0.01})
        for _ in range(STALE_SAMPLES):
            reg.observe(_span(gbps=10.0))
        for _ in range(3 * STALE_SAMPLES):
            reg.observe(_span(gbps=9.3))  # 7% sag < the 15% floor
        assert [r for r in out if r.get("event") == "tune_stale"] == []

    def test_without_tuned_context_never_fires(self):
        out, sink = self._sink()
        reg = MetricsRegistry(health_sink=sink)
        for _ in range(STALE_SAMPLES):
            reg.observe(_span(gbps=10.0))
        for _ in range(3 * STALE_SAMPLES):
            reg.observe(_span(gbps=1.0))
        assert out == []

    def test_noisy_baseline_widens_the_band(self):
        """A baseline whose own spread exceeds 30% must not convict a
        30% sag — the band is the baseline's own noise."""
        out, sink = self._sink()
        reg = MetricsRegistry(health_sink=sink)
        reg.observe({"kind": "tune_hit", "knob": "k", "value": 1})
        for i in range(STALE_SAMPLES):
            reg.observe(_span(gbps=10.0 + (4.0 if i % 2 else -4.0)))
        for _ in range(3 * STALE_SAMPLES):
            reg.observe(_span(gbps=7.0))
        assert [r for r in out if r.get("event") == "tune_stale"] == []

    def test_roofline_signal_fires_too(self):
        out, sink = self._sink()
        reg = MetricsRegistry(health_sink=sink)
        reg.observe({"kind": "tune_hit", "knob": "k", "value": 1})
        for _ in range(STALE_SAMPLES):
            reg.observe(_span(roofline_frac=0.8))
        for _ in range(3 * STALE_SAMPLES):
            reg.observe(_span(roofline_frac=0.4))
        stale = [r for r in out if r.get("event") == "tune_stale"]
        assert len(stale) == 1
        assert stale[0]["signal"] == "roofline_frac"

    def test_standalone_registry_absorbs_its_own_firing(self):
        """tpumt-top's registry has no sink: the record must land in
        health_events + the counter instead of vanishing."""
        reg = MetricsRegistry()
        reg.observe({"kind": "tune_hit", "knob": "k", "value": 1})
        for _ in range(STALE_SAMPLES):
            reg.observe(_span(gbps=10.0))
        for _ in range(3 * STALE_SAMPLES):
            reg.observe(_span(gbps=5.0))
        assert reg.value("tpumt_health_events",
                         (("event", "tune_stale"),)) == 1
        assert any(r.get("event") == "tune_stale"
                   for r in reg.health_events)


_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? \S+$")


class TestExporter:
    def _fed_registry(self):
        reg = MetricsRegistry()
        reg.observe(_span(gbps=1.5))
        reg.observe({"kind": "serve", "event": "window",
                     "class": "daxpy:4096:float32", "arrivals": 5,
                     "requests": 4, "errors": 0, "shed": 0,
                     "queue_depth": 1, "p50_ms": 1.0, "p99_ms": 2.0,
                     "offered_hz": 5.0, "achieved_hz": 4.0})
        reg.observe({"kind": "mem", "rank": 0, "bytes_in_use": 1 << 20})
        return reg

    def test_exposition_wellformed(self):
        text = render_openmetrics(self._fed_registry())
        lines = text.strip().splitlines()
        assert lines[-1] == "# EOF"
        for ln in lines[:-1]:
            if ln.startswith("# TYPE "):
                assert re.match(r"^# TYPE \S+ (counter|gauge|summary)$",
                                ln), ln
            else:
                assert _SAMPLE_RE.match(ln), ln
        # counters expose with the _total sample suffix (OpenMetrics)
        assert "tpumt_serve_requests_total{" in text
        assert "# TYPE tpumt_serve_requests counter" in text
        # histograms expose as quantile summaries
        assert 'quantile="0.5"' in text
        assert "tpumt_latency_seconds_count" in text

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.observe(_span(op='we"ird\\op'))
        text = render_openmetrics(reg)
        assert r'op="we\"ird\\op"' in text

    def test_http_endpoint(self):
        exp = MetricsExporter(self._fed_registry(), 0).start()
        try:
            url = f"http://127.0.0.1:{exp.port}/metrics"
            with urllib.request.urlopen(url, timeout=10) as r:
                body = r.read().decode()
                assert r.headers["Content-Type"] == CONTENT_TYPE
            assert body.strip().endswith("# EOF")
            assert "tpumt_spans_total" in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/nope", timeout=10)
        finally:
            exp.stop()


class TestHeartbeat:
    def test_periodic_records_and_final_marker(self):
        reg = MetricsRegistry()
        reg.observe({"kind": "serve", "event": "window", "class": "c",
                     "queue_depth": 4})
        reg.observe({"kind": "mem", "rank": 0, "bytes_in_use": 999})
        reg.observe(_span(seconds=0.01))
        out = []
        hb = Heartbeat(reg, out.append, interval_s=0.05).start()
        import time as _time

        _time.sleep(0.25)
        hb.stop()
        assert len(out) >= 2
        assert all(r["kind"] == "health"
                   and r["event"] == "heartbeat" for r in out)
        seqs = [r["seq"] for r in out]
        assert seqs == sorted(seqs)
        last = out[-1]
        assert last.get("final") is True  # the clean-close marker
        assert last["queue_depth"] == 4
        assert last["hbm_bytes_in_use"] == 999
        assert last["p50_ms"] > 0
        assert last["records"] >= 3

    def test_sink_error_is_swallowed(self):
        reg = MetricsRegistry()

        def bad_sink(rec):
            raise RuntimeError("closed")

        hb = Heartbeat(reg, bad_sink, interval_s=0.05).start()
        import time as _time

        _time.sleep(0.1)
        hb.stop()  # no raise = pass


class TestPhaseProgress:
    def test_cumulative_snapshots_with_throttle(self):
        out = []
        t = [0.0]
        w = [100.0]
        pp = PhaseProgress(out.append, interval_s=1.0,
                           clock=lambda: t[0], wall=lambda: w[0])
        for i in range(5):
            pp("kernel", "begin")
            t[0] += 0.2
            pp("kernel", "end")
            w[0] += 0.3
        # first exit emits, then the 1 s throttle admits one more
        assert len(out) == 2
        first, second = out
        assert first["kind"] == "time" and first["event"] == "progress"
        assert first["phase"] == "kernel"
        assert first["seconds"] == pytest.approx(0.2)
        assert first["count"] == 1
        assert second["seconds"] == pytest.approx(0.2 * 5)
        assert second["count"] == 5

    def test_stop_flushes_final_snapshot(self):
        out = []
        t = [0.0]
        pp = PhaseProgress(out.append, interval_s=1e9,
                           clock=lambda: t[0], wall=lambda: t[0])
        pp("p", "begin")
        t[0] += 0.5
        pp("p", "end")
        pp("p", "begin")
        t[0] += 0.5
        pp("p", "end")
        assert out == []  # everything inside the (huge) throttle
        # stop() without start() only flushes (hook never registered
        # in this unit test — the real registration is covered below)
        from tpu_mpi_tests.instrument import timers

        timers.add_phase_hook(pp)
        pp.stop()
        assert out[-1]["seconds"] == pytest.approx(1.0)
        assert out[-1]["count"] == 2

    def test_real_phase_timer_integration(self):
        from tpu_mpi_tests.instrument.timers import PhaseTimer

        out = []
        pp = PhaseProgress(out.append, interval_s=0.0).start()
        try:
            timer = PhaseTimer()
            with timer.phase("warm"):
                pass
        finally:
            pp.stop()
        assert any(r["phase"] == "warm" and r["event"] == "progress"
                   for r in out)


class TestReporterTee:
    def test_records_tee_into_registry(self, tmp_path):
        reg = MetricsRegistry()
        rep = Reporter(jsonl_path=str(tmp_path / "o.jsonl"))
        rep.attach_metrics(reg)
        rep.jsonl(_span())
        rep.close()
        assert reg.value("tpumt_spans", (("op", "allreduce"),)) == 1
        # and the record still reached the file
        recs = [json.loads(ln)
                for ln in (tmp_path / "o.jsonl").read_text().splitlines()]
        assert recs[0]["kind"] == "span"

    def test_tee_works_without_jsonl_file(self):
        reg = MetricsRegistry()
        rep = Reporter(jsonl_path=None)
        rep.attach_metrics(reg)
        rep.jsonl(_span())
        assert reg.value("tpumt_spans", (("op", "allreduce"),)) == 1

    def test_attach_live_stops_on_close(self, tmp_path):
        class Stoppable:
            stopped = 0

            def stop(self):
                Stoppable.stopped += 1

        rep = Reporter(jsonl_path=str(tmp_path / "o.jsonl"))
        rep.attach_live(Stoppable(), Stoppable())
        rep.close()
        assert Stoppable.stopped == 2
        rep.close()  # idempotent: stoppables run once
        assert Stoppable.stopped == 2


def _run(code_or_module, args, timeout=240):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    if "\n" in code_or_module:
        cmd = [sys.executable, "-c", code_or_module, *args]
    else:
        cmd = [sys.executable, "-m", code_or_module, *args]
    return subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)


class TestDriverWiring:
    def test_metrics_armed_run_emits_live_trail(self, tmp_path):
        """A --metrics-port run must leave the whole live trail in its
        JSONL: heartbeats (incl. the final marker), per-phase progress
        snapshots, and the METRICS endpoint banner on stdout — while
        tpumt-report still renders each phase exactly once (progress
        snapshots are not double-counted)."""
        jsonl = tmp_path / "m.jsonl"
        r = _run("tpu_mpi_tests.drivers.daxpy",
                 ["--fake-devices", "2", "--n", "4096", "--iters", "3",
                  "--metrics-port", "0", "--metrics-interval", "0.05",
                  "--jsonl", str(jsonl)])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "METRICS rank 0: OpenMetrics at http://" in r.stdout
        recs = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
        hb = [x for x in recs if x.get("kind") == "health"
              and x.get("event") == "heartbeat"]
        assert hb and hb[-1].get("final") is True
        prog = [x for x in recs if x.get("kind") == "time"
                and x.get("event") == "progress"]
        assert {x["phase"] for x in prog} >= {"kernel"}
        from tpu_mpi_tests.instrument.aggregate import summarize

        s = summarize([str(jsonl)])
        assert s["phases"]["kernel"]["count"] == 1

    def test_arm_metrics_stamps_true_process_index(self, tmp_path):
        """Meshless multi-process specs pass rank=0 to make_reporter in
        EVERY process (the _arm_chaos lesson) — the live trail must
        stamp the true process index or every rank's heartbeats
        collapse onto rank 0 in the merged view."""
        from types import SimpleNamespace

        from tpu_mpi_tests.drivers import _common

        rep = Reporter(rank=0, size=1,
                       jsonl_path=str(tmp_path / "o.jsonl"),
                       proc_index=1, proc_count=2)
        args = SimpleNamespace(metrics_port=9000, metrics_interval=5.0,
                               metrics_all_ranks=False)
        _common._arm_metrics(args, rep)  # proc 1: no exporter bound
        rep.close()  # the final heartbeat flushes through the sink
        recs = [json.loads(ln) for ln in
                open(rep.jsonl_path).read().splitlines()]
        hb = [r for r in recs if r.get("kind") == "health"]
        assert hb and all(r["rank"] == 1 for r in hb)

    def test_disarmed_run_identical_to_build_without_live_modules(
        self, tmp_path
    ):
        """THE acceptance identity (the PR-9 pattern): without
        --metrics-port and with no follow consumers, masked stdout and
        the JSONL record-kind sequence are byte-identical to a build
        where the live modules cannot even be imported."""
        blocked = (
            "import sys\n"
            "class Block:\n"
            "    def find_spec(self, name, path=None, target=None):\n"
            "        if name in ('tpu_mpi_tests.instrument.metrics',\n"
            "                    'tpu_mpi_tests.instrument.export',\n"
            "                    'tpu_mpi_tests.instrument.live'):\n"
            "            raise ImportError('live plane removed')\n"
            "sys.meta_path.insert(0, Block())\n"
            "from tpu_mpi_tests.workloads.daxpy import main\n"
            "sys.exit(main(sys.argv[1:]))\n"
        )
        plain = (
            "import sys\n"
            "from tpu_mpi_tests.workloads.daxpy import main\n"
            "sys.exit(main(sys.argv[1:]))\n"
        )
        outs = []
        for code, jsonl in ((blocked, tmp_path / "a.jsonl"),
                            (plain, tmp_path / "b.jsonl")):
            r = _run(code, ["--fake-devices", "2", "--n", "4096",
                            "--telemetry", "--jsonl", str(jsonl)])
            assert r.returncode == 0, r.stderr[-2000:]
            outs.append(r.stdout)
        mask = re.compile(r"[0-9][0-9.e+-]*")

        def masked(s):
            return [mask.sub("#", ln) for ln in s.splitlines()
                    if not ln.startswith("MANIFEST")]  # git sha varies

        assert masked(outs[0]) == masked(outs[1])
        kinds = [
            [json.loads(ln).get("kind") for ln in open(p)]
            for p in (tmp_path / "a.jsonl", tmp_path / "b.jsonl")
        ]
        assert kinds[0] == kinds[1]
        assert "health" not in kinds[1]
