"""Automated multi-process (DCN-path) distributed tests.

The reference validates its distributed backend only on real cluster
allocations (``summit/``, ``jlse/``); round 1 of this framework validated the
``jax.distributed`` bootstrap only by hand. These tests close that gap: each
spawns a REAL multi-process world over localhost via the native launcher
(``native/tpumt_run``, ≅ ``mpirun -np N`` in ``jlse/run.sh:29-33``), with one
fake CPU device per process, and asserts the drivers' checksum/err_norm gates
from the combined output — so the DCN bootstrap + cross-process collective
path is green in ``make test`` with no hardware.
"""

import os
import re
import shutil
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
LAUNCHER = REPO / "native" / "tpumt_run"

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain for tpumt_run"
)


@pytest.fixture(scope="module")
def tpumt_run():
    subprocess.run(
        ["make", "-C", str(REPO / "native"), "tpumt_run"],
        capture_output=True,
        check=True,
        timeout=120,
    )
    return str(LAUNCHER)


def launch(tpumt_run, nprocs, *cmd, out_prefix=None, timeout=300):
    """Run a command under the native launcher. With ``out_prefix``, each
    rank's stdout+stderr lands in ``<out_prefix><rank>.txt`` (parallel
    children interleave a shared pipe, which corrupts parsed values)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + (
        ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    args = [tpumt_run, "-n", str(nprocs)]
    if out_prefix is not None:
        args += ["-o", str(out_prefix)]
    # own session + killpg on timeout: killing only the launcher leaves
    # grandchild ranks holding the captured pipe, and communicate() would
    # then hang forever — exactly in the distributed-deadlock case these
    # tests exist to catch
    proc = subprocess.Popen(
        args + ["--", *cmd],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=REPO,
        env=env,
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, 9)
        stdout, stderr = proc.communicate()
        pytest.fail(f"launcher timed out after {timeout}s; partial output:\n"
                    f"{stdout}\n{stderr}")
    return subprocess.CompletedProcess(
        proc.args, proc.returncode, stdout, stderr
    )


def rank_outputs(out_prefix, nprocs):
    return [Path(f"{out_prefix}{r}.txt").read_text() for r in range(nprocs)]


def test_multiproc_daxpy_allgather_checksums(tpumt_run, tmp_path):
    """2-process mpi_daxpy_nvtx: per-rank SUM, cross-process in-place
    allgather, and the driver's internal ALLSUM/GATHER-PARITY gates
    (≅ mpi_daxpy_nvtx.cc:251-310 semantics over a real 2-process world)."""
    prefix = tmp_path / "out-daxpy-"
    r = launch(
        tpumt_run, 2, sys.executable, "-m",
        "tpu_mpi_tests.drivers.mpi_daxpy_nvtx",
        "--fake-devices", "1", "--n-per-node", "65536",
        out_prefix=prefix,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    outs = rank_outputs(prefix, 2)
    per_rank_sums, per_rank_allsums = [], []
    for rank, out in enumerate(outs):
        sums = re.findall(rf"{rank}/2 SUM = ([\d.]+)", out)
        assert sums, out
        per_rank_sums.append({float(v) for v in sums})
        allsums = re.findall(rf"{rank}/2 ALLSUM = ([\d.]+)", out)
        assert len(allsums) == 1, out
        per_rank_allsums.append(float(allsums[0]))
        assert out.count("TIME gather :") == 1
    # identical shards → identical checksums on both ranks; the allgathered
    # total spans both ranks' data
    assert per_rank_sums[0] == per_rank_sums[1]
    assert per_rank_allsums[0] == per_rank_allsums[1]
    assert per_rank_allsums[0] > max(per_rank_sums[0])


def test_multiproc_stencil1d_err_norm(tpumt_run, tmp_path):
    """2-process 1-D stencil: the halo exchange crosses the process boundary
    and the analytic err_norm gate passes on every rank
    (≅ mpi_stencil_gt.cc:222-225 over a real distributed world)."""
    prefix = tmp_path / "out-stencil-"
    r = launch(
        tpumt_run, 2, sys.executable, "-m",
        "tpu_mpi_tests.drivers.stencil1d",
        "--fake-devices", "1", "--n-global", "8192", "--dtype", "float64",
        out_prefix=prefix,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    # stencil1d reports all logical ranks from the controller process
    out0 = rank_outputs(prefix, 2)[0]
    errs = re.findall(r"(\d)/2 \[\w+\] err_norm = ([\d.e+-]+)", out0)
    assert {rank for rank, _ in errs} == {"0", "1"}, out0
    assert all(float(e) < 1e-8 for _, e in errs)


def test_multiproc_2level_mesh_collectives(tpumt_run, tmp_path):
    """make_mesh_2level over a real 2-process world: the outer (dcn) axis
    spans processes, and psum over both axes reduces across the process
    boundary (≅ node-axis collectives from MPI_Comm_split_type topology,
    mpi_daxpy_nvtx.cc:72-82)."""
    script = tmp_path / "two_level.py"
    script.write_text(textwrap.dedent("""
        import os
        # one CPU device per process (the parent test env may carry an
        # 8-fake-device XLA_FLAGS; this world wants dcn=2 x ici=1)
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

        import functools
        import jax
        import numpy as np
        from jax import lax

        from tpu_mpi_tests.compat import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpu_mpi_tests.comm.mesh import bootstrap, make_mesh_2level, topology

        jax.config.update("jax_platforms", "cpu")
        bootstrap()
        topo = topology()
        assert topo.process_count == 2, topo
        mesh = make_mesh_2level()
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
            "dcn": 2, "ici": 1}, mesh

        spec = P(("dcn", "ici"))  # vary over both axes so both psums are legal

        @jax.jit
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=spec, out_specs=spec)
        def rank_psum(x):
            both = lax.psum(x, ("dcn", "ici"))
            dcn_only = lax.psum(x, "dcn")
            return both + dcn_only

        full = np.arange(2, dtype=np.float32)  # dcn rank r holds [r]
        x = jax.make_array_from_callback(
            (2,), NamedSharding(mesh, spec), lambda idx: full[idx])
        out = rank_psum(x)
        # psum over all axes = 0+1 = 1 everywhere; the dcn-only psum (ici
        # axis is size 1, so it reduces the same pair) adds another 1
        local = np.asarray(out.addressable_shards[0].data)
        assert float(local[0]) == 2.0, local
        print(f"2LEVEL OK rank={topo.process_index}")
    """))
    r = launch(tpumt_run, 2, sys.executable, str(script))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "2LEVEL OK rank=0" in r.stdout
    assert "2LEVEL OK rank=1" in r.stdout


def test_multiproc_4proc_stencil1d_and_ring(tpumt_run, tmp_path):
    """FOUR-process world (VERDICT r2 weak #7 / next #8): a 2-process ring
    makes left and right neighbor the same process, so wrong-neighbor
    sends and partial-permutation edge cases pass vacuously there. This
    world gives every rank DISTINCT neighbors: (a) the 1-D stencil's halo
    exchange must keep the analytic err gate on all 4 ranks, and (b) an
    explicit ppermute ring on the 2-level mesh must deliver exactly the
    left neighbor's rank index to each rank (a wrong-direction or
    wrong-pair permutation fails loudly), plus psum across the 4-process
    dcn axis (≅ the reference's 12-rank matrix, summit/job.lsf:9-16)."""
    prefix = tmp_path / "out-stencil4-"
    r = launch(
        tpumt_run, 4, sys.executable, "-m",
        "tpu_mpi_tests.drivers.stencil1d",
        "--fake-devices", "1", "--n-global", "16384", "--dtype", "float64",
        out_prefix=prefix, timeout=420,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out0 = rank_outputs(prefix, 4)[0]
    errs = re.findall(r"(\d)/4 \[\w+\] err_norm = ([\d.e+-]+)", out0)
    assert {rank for rank, _ in errs} == {"0", "1", "2", "3"}, out0
    assert all(float(e) < 1e-8 for _, e in errs)

    script = tmp_path / "ring4.py"
    script.write_text(textwrap.dedent("""
        import os
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

        import functools
        import jax
        import numpy as np
        from jax import lax

        from tpu_mpi_tests.compat import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpu_mpi_tests.comm.mesh import (
            bootstrap, make_mesh_2level, topology,
        )

        jax.config.update("jax_platforms", "cpu")
        bootstrap()
        topo = topology()
        assert topo.process_count == 4, topo
        mesh = make_mesh_2level()
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
            "dcn": 4, "ici": 1}, mesh

        spec = P(("dcn", "ici"))

        @jax.jit
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=spec, out_specs=spec)
        def probe(x):
            n = mesh.shape["dcn"]  # lax.axis_size needs jax >= 0.4.38
            # ring shift +1: rank r receives rank r-1's value — a
            # wrong-neighbor or wrong-direction permutation is exact-fail
            fwd = [(i, (i + 1) % n) for i in range(n)]
            from_left = lax.ppermute(x, "dcn", fwd)
            total = lax.psum(x, ("dcn", "ici"))
            return from_left * 100.0 + total

        full = np.arange(4, dtype=np.float32)  # dcn rank r holds [r]
        x = jax.make_array_from_callback(
            (4,), NamedSharding(mesh, spec), lambda idx: full[idx])
        out = probe(x)
        local = float(np.asarray(out.addressable_shards[0].data)[0])
        r = topo.process_index
        want = ((r - 1) % 4) * 100.0 + 6.0  # left neighbor + sum(0..3)
        assert local == want, (r, local, want)
        print(f"RING4 OK rank={r}")
    """))
    r = launch(tpumt_run, 4, sys.executable, str(script), timeout=420)
    assert r.returncode == 0, r.stdout + r.stderr
    for rank in range(4):
        assert f"RING4 OK rank={rank}" in r.stdout


def test_multiproc_collbench_busbw(tpumt_run, tmp_path):
    """2-process collective bandwidth sweep: every collective in the ladder
    crosses the process boundary and reports a finite nonzero busbw
    (≅ running an OSU-style sweep under mpirun; the NaN guard in
    chain_rate must not trip on a healthy world)."""
    prefix = tmp_path / "out-coll-"
    r = launch(
        tpumt_run, 2, sys.executable, "-m",
        "tpu_mpi_tests.drivers.collbench",
        # 150 base iterations (scaled to 2400 at 64 KiB): the busbw>0
        # assert needs the chain delta to clear timer noise even on a
        # loaded CI host
        "--fake-devices", "1", "--sizes-kib", "64", "--n-iter", "150",
        out_prefix=prefix,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out0 = rank_outputs(prefix, 2)[0]
    from tpu_mpi_tests.drivers.collbench import COLL_LINE_RE

    rows = [
        (m[0], m[2], m[3])
        for m in re.findall(COLL_LINE_RE, out0)
        if m[1] == "65536"
    ]
    from tpu_mpi_tests.drivers.collbench import COLLECTIVES

    assert {name for name, _, _ in rows} == set(COLLECTIVES), out0
    for name, us, busbw in rows:
        assert us != "nan" and float(us) > 0, (name, us)
        assert busbw != "nan" and float(busbw) > 0, (name, busbw)


@pytest.mark.parametrize("kernel", ["xla", "pallas"])
def test_multiproc_heat2d_grid(tpumt_run, tmp_path, kernel):
    """2-process heat mini-app: the process-grid x-axis spans the process
    boundary, so every time step's halo exchange crosses DCN; the driver
    must complete and report steps/s (the eigen gate needs addressable
    shards and is skipped multi-host — finiteness gates instead). Both
    update-body tiers run — the pallas row-streaming Laplacian consumes
    the same DCN-exchanged ghosts."""
    prefix = tmp_path / f"out-heat-{kernel}-"
    r = launch(
        tpumt_run, 2, sys.executable, "-m",
        "tpu_mpi_tests.drivers.heat2d",
        "--fake-devices", "1", "--mesh", "2,1", "--nx-local", "16",
        "--ny-local", "32", "--n-steps", "40", "--dtype", "float64",
        "--kernel", kernel,
        out_prefix=prefix,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out0 = rank_outputs(prefix, 2)[0]
    assert re.search(r"HEAT mesh:2x1 n:32x32; steps=40 [\d.]+ steps/s", out0)
    assert "HEAT FAIL" not in out0


def test_multiproc_stencil2d_rdma_tier(tpumt_run, tmp_path):
    """2-process stencil2d through the hand-written RDMA-ring exchange
    tier: in interpret mode the ring kernel's remote DMA is emulated with
    XLA collectives, which cross the process boundary like any other —
    so the hand tier's semantics get DCN CI coverage too (err gate)."""
    prefix = tmp_path / "out-rdma-"
    r = launch(
        tpumt_run, 2, sys.executable, "-m",
        "tpu_mpi_tests.drivers.stencil2d",
        "--fake-devices", "1", "--n-local", "16", "--n-other", "32",
        "--n-iter", "3", "--rdma", "--only", "0:0",
        out_prefix=prefix,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out0 = rank_outputs(prefix, 2)[0]
    assert re.search(r"TEST dim:0, device , buf:0; [\d.]+, err=", out0)
    assert "ERR_NORM FAIL" not in out0


def test_multiproc_stencil2d_managed_space(tpumt_run, tmp_path):
    """2-process stencil2d with the MANAGED space twin: on the
    multi-process CPU backend the host-memory-kind placement must
    DEGRADE (single choke point ``spaces.host_sharding``) instead of
    crashing — the round-4 on-chip job.sh matrix died here when the
    driver retargeted the sharding itself and XLA refused to reshard
    placement-annotated buffers across the multi-controller device
    order ('Side-effect ops cannot be replicated')."""
    prefix = tmp_path / "out-managed-"
    r = launch(
        tpumt_run, 2, sys.executable, "-m",
        "tpu_mpi_tests.drivers.stencil2d",
        "--fake-devices", "1", "--n-local", "16", "--n-other", "32",
        "--n-iter", "3", "--managed", "--only", "0:0",
        out_prefix=prefix,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out0 = rank_outputs(prefix, 2)[0]
    assert re.search(r"TEST dim:0, managed, buf:0; [\d.]+, err=", out0)
    assert "ERR_NORM FAIL" not in out0
