"""Serving-mode harness (tpu_mpi_tests/serve/ + drivers/serve.py).

The pure layers (arrival, histogram, batcher, loop orchestration) are
tested jax-free with injected clocks/handlers — deterministic and fast;
the end-to-end smoke drives the real tpumt-serve driver on the
fake-device mesh.
"""

from __future__ import annotations

import json
import math
import random

import pytest

from tpu_mpi_tests.serve.arrival import ClosedLoop, OpenLoopPoisson
from tpu_mpi_tests.serve.batcher import coalesce
from tpu_mpi_tests.serve.histogram import LatencyHistogram
from tpu_mpi_tests.serve.loop import Request, ServeLoop
from tpu_mpi_tests.serve.workloads import (
    WorkloadClass,
    WorkloadMix,
    parse_workload_table,
)

# ---------------------------------------------------------------------------
# arrival processes
# ---------------------------------------------------------------------------


def _drain(proc, until, step=0.1):
    out, t = [], 0.0
    while t <= until:
        out.extend(proc.take_due(t))
        t += step
    return out


def test_poisson_deterministic_under_seed():
    a = OpenLoopPoisson(100.0, seed=7)
    b = OpenLoopPoisson(100.0, seed=7)
    a.start(0.0)
    b.start(0.0)
    ta = _drain(a, 1.0)
    tb = _drain(b, 1.0)
    assert ta == tb and len(ta) > 50
    # a different seed gives a different schedule
    c = OpenLoopPoisson(100.0, seed=8)
    c.start(0.0)
    assert _drain(c, 1.0) != ta


def test_poisson_rate_and_limit():
    p = OpenLoopPoisson(1000.0, seed=3)
    p.start(0.0)
    due = p.take_due(10.0, limit=1.0)
    # ~1000 arrivals in the 1 s window (Poisson: ±4 sigma is ±~130)
    assert 800 < len(due) < 1200
    assert all(t <= 1.0 for t in due)
    # nothing past the limit ever materializes
    assert p.take_due(10.0, limit=1.0) == []
    assert p.next_event() is not None and p.next_event() > 1.0


def test_poisson_rejects_bad_rate():
    with pytest.raises(ValueError):
        OpenLoopPoisson(0.0)


def test_closed_loop_population():
    c = ClosedLoop(4)
    c.start(5.0)
    assert c.take_due(5.0) == [5.0] * 4
    assert c.take_due(6.0) == []
    assert c.next_event() is None
    c.on_complete(2, 7.0)
    assert c.next_event() == 7.0
    assert c.take_due(7.0) == [7.0, 7.0]
    # refills scheduled past the limit stay pending (the drain stops)
    c.on_complete(1, 9.0)
    assert c.take_due(10.0, limit=8.0) == []


# ---------------------------------------------------------------------------
# workload table
# ---------------------------------------------------------------------------


def test_parse_workload_table_full_and_defaults():
    classes = parse_workload_table(
        "daxpy:4096:float32:2,attn:256x64:bfloat16:0.5,halo"
    )
    assert [c.key for c in classes] == [
        "daxpy:4096:float32", "attn:256x64:bfloat16",
        "halo:65536:float32",
    ]
    assert classes[0].weight == 2 and classes[1].shape == (256, 64)
    assert classes[2].weight == 1.0  # defaults applied


@pytest.mark.parametrize("bad", [
    "nosuch:128", "daxpy:0", "daxpy:128:int8", "daxpy:128:float32:0",
    "daxpy:128:float32:1:extra", "daxpy:12x", "",
    "daxpy:128,daxpy:128",  # duplicate class
])
def test_parse_workload_table_rejects(bad):
    with pytest.raises(ValueError):
        parse_workload_table(bad)


def test_mix_draw_deterministic_and_weighted():
    classes = parse_workload_table("daxpy:128:float32:9,halo:256:float32:1")
    a = [WorkloadMix(classes, seed=5).draw().key for _ in range(1)]
    b = [WorkloadMix(classes, seed=5).draw().key for _ in range(1)]
    assert a == b
    mix = WorkloadMix(classes, seed=5)
    draws = [mix.draw().workload for _ in range(2000)]
    frac = draws.count("daxpy") / len(draws)
    assert 0.85 < frac < 0.95  # 9:1 weighting


# ---------------------------------------------------------------------------
# histogram: bounded memory + percentile correctness
# ---------------------------------------------------------------------------


def test_histogram_percentiles_vs_sorted_reference():
    rng = random.Random(11)
    h = LatencyHistogram()
    samples = [rng.lognormvariate(-6.0, 1.0) for _ in range(5000)]
    for s in samples:
        h.record(s)
    ref = sorted(samples)
    for q in (50.0, 95.0, 99.0):
        want = ref[max(0, math.ceil(q / 100 * len(ref)) - 1)]
        got = h.percentile(q)
        # log-bucket resolution: within one bucket width (~10%)
        assert abs(got - want) / want < 0.11, (q, got, want)
    assert h.min_s == min(samples) and h.max_s == max(samples)
    assert h.mean() == pytest.approx(sum(samples) / len(samples))


def test_histogram_memory_independent_of_sample_count():
    small, large = LatencyHistogram(), LatencyHistogram()
    rng = random.Random(2)
    for _ in range(10):
        small.record(rng.random())
    for _ in range(100000):
        large.record(rng.random())
    # the bounded-memory contract: identical footprint either way
    assert len(small.counts) == len(large.counts)
    assert large.count == 100000


def test_histogram_edges():
    h = LatencyHistogram()
    assert h.percentile(50) is None and h.percentiles_ms() == {}
    h.record(0.0)  # below MIN_LATENCY_S -> underflow, reads back as min
    assert h.percentile(50) == 0.0
    h2 = LatencyHistogram()
    h2.record(float("nan"))
    h2.record(-1.0)
    assert h2.count == 0  # invalid latencies never land


# ---------------------------------------------------------------------------
# batcher: class-compatible coalescing only
# ---------------------------------------------------------------------------


def _req(key_cls, t=0.0):
    return Request(key_cls, t)


def test_coalesce_never_crosses_class():
    a = WorkloadClass("daxpy", (128,), "float32")
    a16 = WorkloadClass("daxpy", (128,), "bfloat16")
    b = WorkloadClass("daxpy", (256,), "float32")
    queue = [_req(a), _req(a16), _req(b), _req(a), _req(b)]
    batch, rest = coalesce(queue, max_batch=8)
    assert [r.cls.key for r in batch] == [a.key, a.key]
    # dtype and shape siblings stay queued, order preserved
    assert [r.cls.key for r in rest] == [a16.key, b.key, b.key]


def test_coalesce_caps_and_fifo_head():
    a = WorkloadClass("daxpy", (128,), "float32")
    b = WorkloadClass("halo", (256,), "float32")
    queue = [_req(b)] + [_req(a) for _ in range(10)]
    batch, rest = coalesce(queue, max_batch=4)
    # head of queue picks the class even if a bigger batch exists behind
    assert [r.cls.key for r in batch] == [b.key]
    batch2, rest2 = coalesce(rest, max_batch=4)
    assert len(batch2) == 4 and all(r.cls.key == a.key for r in batch2)
    assert len(rest2) == 6
    assert coalesce([], 4) == ([], [])


# ---------------------------------------------------------------------------
# loop orchestration under a fake clock (jax-free)
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def clock(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


def _run_loop(rate, duration, service_s=0.001, window_s=2.0, seed=0,
              max_batch=8, classes=None, watchdog=None):
    clk = FakeClock()
    classes = classes or parse_workload_table(
        "daxpy:128:float32:3,allreduce:64:float32:1"
    )
    records = []

    def handler(n):
        clk.t += service_s * n

    loop = ServeLoop(
        classes, {c.key: handler for c in classes},
        OpenLoopPoisson(rate, seed=seed),
        duration_s=duration, max_batch=max_batch, window_s=window_s,
        seed=seed, sink=records.append, watchdog=watchdog,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    summaries = loop.run()
    return records, summaries


def test_window_records_carry_standing_queue_depth():
    """Every window record reports the STANDING backlog at emission
    time (``queue_depth``) alongside the high-water mark
    (``queue_max``) — the live serve-pressure signal the metrics tee
    forwards with the loop knowing nothing about metrics. A saturated
    run must show a nonzero standing depth in some window, and the
    standing depth can never exceed that window's high-water mark."""
    # service far slower than arrivals: the queue builds a backlog
    records, _ = _run_loop(rate=50.0, duration=10.0, service_s=0.1,
                           max_batch=1)
    windows = [r for r in records if r["event"] == "window"]
    assert windows
    assert all(isinstance(r.get("queue_depth"), int) for r in windows)
    assert all(r["queue_depth"] <= r["queue_max"] for r in windows)
    assert any(r["queue_depth"] > 0 for r in windows)
    # summaries keep their pre-live shape: no standing-depth field
    assert all("queue_depth" not in r for r in records
               if r["event"] == "summary")


def test_loop_record_count_independent_of_request_count():
    """Bounded-memory acceptance: 10x the traffic must NOT mean 10x the
    records — emission is per (class, window), never per request."""
    rec_lo, sum_lo = _run_loop(rate=20.0, duration=10.0)
    rec_hi, sum_hi = _run_loop(rate=200.0, duration=10.0)
    n_lo = sum(r["requests"] for r in sum_lo)
    n_hi = sum(r["requests"] for r in sum_hi)
    assert n_hi > 5 * n_lo  # the traffic really did scale
    assert len(rec_hi) == len(rec_lo)  # the record stream did not


def test_loop_summary_accounting_and_percentiles():
    records, summaries = _run_loop(rate=50.0, duration=10.0)
    assert {r["event"] for r in records
            if r["kind"] == "serve"} == {"window", "summary"}
    # the only other stream is the bounded kind:"req" exemplars
    assert {r["kind"] for r in records} <= {"serve", "req"}
    for s in summaries:
        assert s["kind"] == "serve" and s["event"] == "summary"
        assert s["requests"] == s["arrivals"]  # everything served
        assert s["errors"] == 0 and s["shed"] == 0
        assert s["achieved_hz"] == pytest.approx(
            s["requests"] / s["duration_s"])
        if s["requests"]:
            assert math.isfinite(s["p50_ms"])
            assert s["p50_ms"] <= s["p95_ms"] <= s["p99_ms"]
    # windows carry the wall clock (PR-2 placement contract)
    w = [r for r in records if r["event"] == "window"][0]
    assert w["t_end"] > w["t_start"]


def test_loop_deterministic_under_seed():
    _, a = _run_loop(rate=50.0, duration=10.0, seed=9)
    _, b = _run_loop(rate=50.0, duration=10.0, seed=9)
    sa = {r["class"]: (r["requests"], r["batches"]) for r in a}
    sb = {r["class"]: (r["requests"], r["batches"]) for r in b}
    assert sa == sb


def test_loop_handler_errors_counted_not_fatal():
    clk = FakeClock()
    classes = parse_workload_table("daxpy:128:float32")
    records = []

    def bad(n):
        clk.t += 0.001
        raise RuntimeError("device fell over")

    loop = ServeLoop(
        classes, {classes[0].key: bad},
        OpenLoopPoisson(50.0, seed=0),
        duration_s=5.0, window_s=2.0, sink=records.append,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    (summary,) = loop.run()
    assert summary["errors"] > 0 and summary["requests"] == 0
    assert "p50_ms" not in summary  # absent fields, never fake zeros


def test_loop_closed_persistent_failure_backs_off():
    """A dead handler under closed-loop arrivals must not busy-spin:
    the post-failure backoff bounds the error-batch rate (and, under
    an injected clock, is what keeps time advancing at all)."""
    clk = FakeClock()
    classes = parse_workload_table("daxpy:128:float32")

    def dead(n):
        raise RuntimeError("mesh lost")  # fails without consuming time

    loop = ServeLoop(
        classes, {classes[0].key: dead}, ClosedLoop(4),
        duration_s=5.0, window_s=10.0,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    (summary,) = loop.run()
    assert summary["errors"] > 0 and summary["requests"] == 0
    # ~duration / FAIL_BACKOFF_S batches, not millions
    assert summary["batches"] <= 5.0 / 0.05 + 5


def test_loop_quarantine_isolates_dead_class():
    """Graceful degradation: a handler class dead past N consecutive
    failed batches is quarantined — its arrivals shed, the OTHER class
    keeps its SLO — instead of error-spinning the whole run; the
    summary carries the episode accounting."""
    clk = FakeClock()
    classes = parse_workload_table(
        "daxpy:128:float32:1,allreduce:64:float32:1"
    )
    dead_key = classes[0].key
    records = []

    def dead(n):
        clk.t += 0.001
        raise RuntimeError("mesh lost")

    def healthy(n):
        clk.t += 0.001 * n

    loop = ServeLoop(
        classes, {classes[0].key: dead, classes[1].key: healthy},
        OpenLoopPoisson(50.0, seed=0),
        duration_s=8.0, window_s=2.0, max_queue=64,
        sink=records.append, quarantine_after=3,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    summaries = {s["class"]: s for s in loop.run()}
    quar = [r for r in records if r.get("event") == "quarantine"]
    assert len(quar) == 1 and quar[0]["class"] == dead_key
    assert quar[0]["consecutive_errors"] == 3
    # exactly 3 failed batches, then isolation: arrivals shed instead
    dead_sum = summaries[dead_key]
    assert dead_sum["errors"] > 0 and dead_sum["shed"] > 0
    assert dead_sum["requests"] == 0
    # the still-open episode is charged to the summary at run end
    assert dead_sum["quarantines"] == 1
    assert dead_sum["quarantine_s"] > 0
    # a never-recovering class's whole error/shed story is quarantine-
    # attributed (the triggering streak + quarantine sheds), so the
    # driver can forgive it all
    assert dead_sum["quar_errors"] == dead_sum["errors"]
    assert dead_sum["quar_shed"] == dead_sum["shed"]
    # the healthy class never noticed
    ok_sum = summaries[classes[1].key]
    assert ok_sum["requests"] > 0 and ok_sum["errors"] == 0
    assert "quarantines" not in ok_sum


def test_loop_quarantine_probe_readmits_recovered_class():
    """The window-boundary probe re-admits a recovered handler and the
    recover record carries the downtime; the class serves again."""
    clk = FakeClock()
    classes = parse_workload_table("daxpy:128:float32")
    records = []

    def flaky(n):  # dead until t=4, healthy after
        clk.t += 0.001
        if clk.t < 4.0:
            raise RuntimeError("transient device loss")

    loop = ServeLoop(
        classes, {classes[0].key: flaky},
        OpenLoopPoisson(50.0, seed=0),
        duration_s=10.0, window_s=2.0, max_queue=64,
        sink=records.append, quarantine_after=3,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    (summary,) = loop.run()
    rec = [r for r in records if r.get("event") == "recover"]
    assert len(rec) == 1 and rec[0]["downtime_s"] > 0
    assert summary["quarantines"] == 1
    assert summary["quarantine_s"] == pytest.approx(
        rec[0]["downtime_s"])
    assert summary["requests"] > 0  # served again after re-admission
    # clean after recovery: every error belongs to the episode
    assert summary["quar_errors"] == summary["errors"]
    assert summary["quar_shed"] == summary["shed"]


def test_loop_quarantine_attribution_excludes_later_failures():
    """One recovered quarantine is not amnesty: errors from failures
    OUTSIDE the quarantine streak (here, intermittent post-recovery
    failures that never re-quarantine) stay unattributed, so the
    driver still flags the run."""
    clk = FakeClock()
    classes = parse_workload_table("daxpy:128:float32")
    records = []
    calls = [0]

    def flaky(n):  # dead until t=4, then fails every other batch
        clk.t += 0.001
        if clk.t < 4.0:
            raise RuntimeError("transient device loss")
        calls[0] += 1
        if calls[0] % 2:
            raise RuntimeError("still sick")

    loop = ServeLoop(
        classes, {classes[0].key: flaky},
        OpenLoopPoisson(50.0, seed=0),
        duration_s=10.0, window_s=2.0, max_queue=64,
        sink=records.append, quarantine_after=3,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    (summary,) = loop.run()
    assert summary["quarantines"] == 1
    # post-recovery failures accrued errors the episode does NOT cover
    assert summary["errors"] > summary["quar_errors"] > 0


def test_loop_quarantine_off_by_default():
    """Without --quarantine-after the pre-quarantine behavior is
    untouched: a dead class error-spins (bounded by the backoff) and
    no quarantine records appear."""
    clk = FakeClock()
    classes = parse_workload_table("daxpy:128:float32")
    records = []

    def dead(n):
        clk.t += 0.001
        raise RuntimeError("mesh lost")

    loop = ServeLoop(
        classes, {classes[0].key: dead},
        OpenLoopPoisson(50.0, seed=0),
        duration_s=6.0, window_s=2.0, max_queue=64,
        sink=records.append,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    (summary,) = loop.run()
    assert not [r for r in records
                if r.get("event") in ("quarantine", "recover")]
    assert "quarantines" not in summary
    assert summary["errors"] > 0


def test_loop_sheds_beyond_max_queue():
    clk = FakeClock()
    classes = parse_workload_table("daxpy:128:float32")

    def slow(n):
        clk.t += 1.0  # 1 s per batch vs 100 req/s offered

    loop = ServeLoop(
        classes, {classes[0].key: slow},
        OpenLoopPoisson(100.0, seed=0),
        duration_s=5.0, window_s=10.0, max_queue=20, max_batch=1,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    (summary,) = loop.run()
    assert summary["shed"] > 0
    assert summary["queue_max"] <= 20


def test_latency_decomposition_reconciles_with_e2e():
    """The PR-16 latency anatomy: every completion's e2e is recorded as
    queue-delay + service EXACTLY, so the histogram means (the one
    readout that is not bucket-quantized) reconcile to float precision,
    and the bucketed percentiles reconcile within one log-bucket of
    readout tolerance. Windows and summaries both carry the qd_/svc_
    decomposition fields."""
    records, summaries = _run_loop(rate=50.0, duration=10.0)
    tol = 10 ** (1 / 24)  # one histogram bucket (24 per decade)
    for s in summaries:
        if not s["requests"]:
            continue
        assert s["qd_mean_ms"] + s["svc_mean_ms"] \
            == pytest.approx(s["mean_ms"])
        # components never exceed the whole (pointwise qd <= e2e and
        # svc <= e2e survive the percentile readout up to bucketing)
        assert s["qd_p99_ms"] <= s["p99_ms"] * tol
        assert s["svc_p99_ms"] <= s["p99_ms"] * tol
        # ... and the whole never exceeds the sum of the parts
        assert s["p99_ms"] <= (s["qd_p99_ms"] + s["svc_p99_ms"]) * tol
    windows = [r for r in records
               if r.get("event") == "window" and r["requests"]]
    assert windows
    assert all("qd_p99_ms" in r and "svc_p99_ms" in r for r in windows)


def test_req_exemplars_bounded_and_coherent():
    """The rate-capped request sampler: an overloaded run sheds
    thousands of requests but emits at most REQ_EXEMPLAR_CAP shed
    exemplars plus ONE p99-worst completion per class-window, each
    carrying a self-consistent lifecycle (arrival <= dispatch <= done,
    queue + service == e2e)."""
    from tpu_mpi_tests.serve.loop import REQ_EXEMPLAR_CAP

    clk = FakeClock()
    classes = parse_workload_table("daxpy:128:float32")
    records = []

    def slow(n):
        clk.t += 0.02 * n

    loop = ServeLoop(
        classes, {classes[0].key: slow},
        OpenLoopPoisson(200.0, seed=1),
        duration_s=10.0, window_s=2.0, max_queue=10, max_batch=1,
        sink=records.append,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    (summary,) = loop.run()
    windows = [r for r in records if r.get("event") == "window"]
    reqs = [r for r in records if r["kind"] == "req"]
    sheds = [r for r in reqs if r["event"] == "shed"]
    completes = [r for r in reqs if r["event"] == "complete"]
    assert summary["shed"] > REQ_EXEMPLAR_CAP * len(windows)
    assert sheds and completes
    assert len(sheds) <= REQ_EXEMPLAR_CAP * len(windows)
    assert len(completes) <= len(windows)
    for r in completes:
        assert r["sampled"] == "p99_worst"
        assert r["t_arrival"] <= r["t_dispatch"] <= r["t_done"]
        assert r["queue_ms"] + r["service_ms"] \
            == pytest.approx(r["e2e_ms"])
    for r in sheds:
        assert r["sampled"] == "shed"
        assert r["queue_ms"] >= 0
        assert r["t_done"] >= r["t_arrival"]


def test_req_error_exemplars_capped():
    """Failed batches surface as bounded error exemplars: at most
    REQ_EXEMPLAR_CAP per class-window, stamped with the dispatch
    lifecycle of the failed batch."""
    from tpu_mpi_tests.serve.loop import REQ_EXEMPLAR_CAP

    clk = FakeClock()
    classes = parse_workload_table("daxpy:128:float32")
    records = []

    def bad(n):
        clk.t += 0.001
        raise RuntimeError("device fell over")

    loop = ServeLoop(
        classes, {classes[0].key: bad},
        OpenLoopPoisson(50.0, seed=0),
        duration_s=5.0, window_s=2.0, sink=records.append,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    (summary,) = loop.run()
    windows = [r for r in records if r.get("event") == "window"]
    errs = [r for r in records
            if r["kind"] == "req" and r["event"] == "error"]
    assert summary["errors"] > REQ_EXEMPLAR_CAP * len(windows)
    assert errs
    assert len(errs) <= REQ_EXEMPLAR_CAP * len(windows)
    for r in errs:
        assert r["sampled"] == "error"
        assert r["t_arrival"] <= r["t_dispatch"] <= r["t_done"]
        assert r["requests"] >= 1


def test_shed_wait_accounted_in_records():
    """Shed requests get terminal accounting, not silent disappearance:
    windows that shed carry the accumulated queue time of their shed
    requests (mean + max), windows that did not shed carry neither
    field (absent, never fake zeros)."""
    clk = FakeClock()
    classes = parse_workload_table("daxpy:128:float32")
    records = []

    def slow(n):
        clk.t += 0.02 * n

    loop = ServeLoop(
        classes, {classes[0].key: slow},
        OpenLoopPoisson(200.0, seed=1),
        duration_s=10.0, window_s=2.0, max_queue=10, max_batch=1,
        sink=records.append,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    (summary,) = loop.run()
    assert summary["shed"] > 0
    assert summary["shed_wait_ms_max"] >= summary["shed_wait_ms_mean"] >= 0
    for r in (r for r in records if r.get("event") == "window"):
        if r["shed"]:
            assert r["shed_wait_ms_max"] >= r["shed_wait_ms_mean"] >= 0
        else:
            assert "shed_wait_ms_mean" not in r
            assert "shed_wait_ms_max" not in r


def test_quarantine_drops_leave_terminal_records():
    """Requests already queued when their class is quarantined are
    dropped WITH a terminal story: their waited time joins the class's
    shed-wait accounting and a bounded number surface as
    sampled="quarantine_drop" exemplars."""
    clk = FakeClock()
    classes = parse_workload_table(
        "daxpy:128:float32:1,allreduce:64:float32:1"
    )
    records = []

    def dead(n):
        # slow failures: arrivals pile up behind the dying batches, so
        # a backlog exists at the moment quarantine triggers
        clk.t += 0.1
        raise RuntimeError("mesh lost")

    def healthy(n):
        clk.t += 0.001 * n

    loop = ServeLoop(
        classes, {classes[0].key: dead, classes[1].key: healthy},
        OpenLoopPoisson(50.0, seed=0),
        duration_s=8.0, window_s=2.0, max_queue=64, max_batch=1,
        sink=records.append, quarantine_after=3,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    summaries = {s["class"]: s for s in loop.run()}
    drops = [r for r in records if r["kind"] == "req"
             and r.get("sampled") == "quarantine_drop"]
    assert drops
    assert all(r["event"] == "shed"
               and r["class"] == classes[0].key
               and r["t_done"] >= r["t_arrival"]
               and r["queue_ms"] >= 0 for r in drops)
    assert summaries[classes[0].key]["shed_wait_ms_max"] >= 0


def test_loop_saturation_visible_in_summary():
    """A saturated-but-not-shedding run must still read as saturated:
    offered is the rate over the TRAFFIC window, not diluted by the
    post-deadline drain, so offered >> achieved and the drain length
    is first-class in the record."""
    clk = FakeClock()
    classes = parse_workload_table("daxpy:128:float32")

    def slow(n):
        clk.t += 0.05 * n  # sustains ~20/s vs 100/s offered

    loop = ServeLoop(
        classes, {classes[0].key: slow},
        OpenLoopPoisson(100.0, seed=0),
        duration_s=5.0, window_s=100.0, max_batch=1,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    (s,) = loop.run()
    assert s["requests"] == s["arrivals"]  # nothing shed or errored
    assert s["offered_hz"] == pytest.approx(s["arrivals"] / 5.0)
    assert s["achieved_hz"] < 0.3 * s["offered_hz"]
    assert s["drain_s"] > 10.0  # the backlog took longer than the run


def test_loop_closed_arrival_tracks_concurrency():
    clk = FakeClock()
    classes = parse_workload_table("daxpy:128:float32")

    def handler(n):
        clk.t += 0.01 * n

    loop = ServeLoop(
        classes, {classes[0].key: handler}, ClosedLoop(3),
        duration_s=10.0, window_s=5.0, max_batch=8,
        clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
    )
    (summary,) = loop.run()
    # 3 clients, 10 ms service, batched: ~100 batch rounds x 3
    assert summary["requests"] > 100
    assert summary["queue_max"] <= 3


def test_loop_requires_handler_per_class():
    classes = parse_workload_table("daxpy:128:float32,halo:256:float32")
    with pytest.raises(ValueError):
        ServeLoop(classes, {}, OpenLoopPoisson(1.0), duration_s=1.0)


def test_loop_arms_watchdog_only_around_dispatch():
    """The serve loop drives the idle-aware arm/disarm API: armed once
    per batch, always disarmed afterwards (idle gaps uncovered)."""
    events = []

    class SpyWatchdog:
        def arm(self, phase=None):
            events.append(("arm", phase))

        def disarm(self):
            events.append(("disarm", None))

    records, summaries = _run_loop(rate=20.0, duration=5.0,
                                   watchdog=SpyWatchdog())
    batches = sum(s["batches"] for s in summaries)
    arms = [e for e in events if e[0] == "arm"]
    assert len(arms) == batches > 0
    assert len(events) == 2 * batches
    # strict alternation: never armed across an idle wait
    for i, (what, _) in enumerate(events):
        assert what == ("arm" if i % 2 == 0 else "disarm")


# ---------------------------------------------------------------------------
# end-to-end smoke on the fake-device mesh (2+ devices, real handlers)
# ---------------------------------------------------------------------------


@pytest.fixture()
def serve_env(tmp_path, monkeypatch):
    # isolate the schedule cache; keep the run off any warmed state
    monkeypatch.setenv("TPU_MPI_TUNE_CACHE",
                       str(tmp_path / "tune.json"))
    from tpu_mpi_tests.tune import registry as tr

    yield tmp_path
    tr.deconfigure()


def test_serve_driver_end_to_end(serve_env, capsys):
    """tpumt-serve on the fake-device mesh: rc 0, SERVE lines, serve
    records with finite percentiles, SLO table renders from the JSONL."""
    from tpu_mpi_tests.drivers import serve as drv
    from tpu_mpi_tests.instrument import aggregate

    jl = serve_env / "serve.jsonl"
    rc = drv.main([
        "--duration", "1.5", "--arrival", "poisson", "--rate", "40",
        "--seed", "3", "--report-interval", "0.5",
        "--workloads", "daxpy:4096:float32:3,allreduce:512:float32:1",
        "--max-batch", "4", "--batch-deadline", "120",
        "--jsonl", str(jl),
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "SERVE daxpy:4096:float32:" in out
    assert "SERVE allreduce:512:float32:" in out

    recs = [json.loads(ln) for ln in jl.read_text().splitlines()]
    assert recs[0]["kind"] == "manifest"  # self-describing result file
    serves = [r for r in recs if r.get("kind") == "serve"]
    summaries = [r for r in serves if r["event"] == "summary"]
    assert {r["class"] for r in summaries} == {
        "daxpy:4096:float32", "allreduce:512:float32"
    }
    for r in summaries:
        assert r["requests"] > 0 and math.isfinite(r["p50_ms"])
        assert r["t_end"] > r["t_start"]

    rc = aggregate.main([str(jl)])
    rep = capsys.readouterr().out
    assert rc == 0
    assert any(ln.startswith("SLO daxpy:4096:float32:")
               for ln in rep.splitlines())


def test_serve_driver_record_replay_roundtrip(serve_env, capsys):
    """tpumt-serve --record then --replay end to end: the artifact
    lands fingerprinted, the replay banner + TRAFFIC line carry the
    same fingerprint, the replay manifest is self-describing about the
    traffic that drove it, and the replayed run serves the recorded
    per-class load exactly."""
    from tpu_mpi_tests.drivers import serve as drv
    from tpu_mpi_tests.serve.replay import load_traffic

    art_path = serve_env / "traffic.json"
    jl_rec = serve_env / "rec.jsonl"
    base = [
        "--duration", "1.5", "--arrival", "poisson", "--rate", "40",
        "--seed", "3", "--report-interval", "0.5",
        "--workloads", "daxpy:4096:float32:3,allreduce:512:float32:1",
    ]
    rc = drv.main([*base, "--record", str(art_path),
                   "--jsonl", str(jl_rec)])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "SERVE TRAFFIC recorded:" in out
    art = load_traffic(str(art_path))  # validates the fingerprint

    jl_rep = serve_env / "rep.jsonl"
    rc = drv.main([*base, "--replay", str(art_path),
                   "--jsonl", str(jl_rep)])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert f"fingerprint={art['fingerprint']}" in out
    assert "SERVE TRAFFIC replayed:" in out
    recs = [json.loads(ln) for ln in jl_rep.read_text().splitlines()]
    assert recs[0]["kind"] == "manifest"
    assert recs[0]["traffic_fingerprint"] == art["fingerprint"]
    served = {r["class"]: r["arrivals"] for r in recs
              if r.get("kind") == "serve"
              and r.get("event") == "summary"}
    assert served == art["classes"]


def test_serve_driver_refuses_untrustworthy_replay(serve_env, capsys,
                                                   tmp_path):
    """Every refused-artifact path is a NOTE + exit 2, never a crash:
    corrupt JSON, a version this build does not speak, and traffic
    naming classes absent from --workloads."""
    from tpu_mpi_tests.drivers import serve as drv
    from tpu_mpi_tests.serve.replay import TrafficRecorder, save_traffic

    base = ["--workloads", "daxpy:4096:float32", "--duration", "1"]
    art_path = tmp_path / "t.json"

    art_path.write_text("{definitely not json")
    rc = drv.main([*base, "--replay", str(art_path)])
    out = capsys.readouterr().out
    assert rc == 2 and "NOTE traffic artifact refused" in out

    rec = TrafficRecorder(arrival="poisson")
    rec.add(0.0, "daxpy:4096:float32")
    art = rec.finalize(1.0)
    save_traffic(str(art_path), {**art, "version": art["version"] + 1})
    rc = drv.main([*base, "--replay", str(art_path)])
    out = capsys.readouterr().out
    assert rc == 2 and "NOTE traffic artifact refused" in out
    assert "version" in out

    rec = TrafficRecorder(arrival="poisson")
    rec.add(0.0, "stencil1d:8192:float32")
    save_traffic(str(art_path), rec.finalize(1.0))
    rc = drv.main([*base, "--replay", str(art_path)])
    out = capsys.readouterr().out
    assert rc == 2 and "absent from --workloads" in out


def test_serve_driver_record_replay_mutually_exclusive(capsys):
    """Replaying a recording while re-recording it would fork the
    traffic identity: argparse rejects the combination outright."""
    from tpu_mpi_tests.drivers import serve as drv

    with pytest.raises(SystemExit):
        drv.main(["--record", "a.json", "--replay", "b.json",
                  "--workloads", "daxpy:4096:float32"])
    assert "mutually exclusive" in capsys.readouterr().err


def test_serve_driver_quarantine_exits_clean(serve_env, capsys,
                                             monkeypatch):
    """The graceful-degradation contract end to end: one class's
    handler stays dead, --quarantine-after isolates it, the OTHER
    class keeps serving, the SERVE QUARANTINE line surfaces the
    episode, and the run exits 0 instead of rc-1-ing."""
    from tpu_mpi_tests.drivers import _common, serve as drv

    real_factory = _common.workload_factory

    def patched(name):
        if name == "daxpy":
            def build(mesh, shape, dtype):
                def dead_handler(n):
                    raise RuntimeError("handler class stayed dead")
                return dead_handler
            return build
        return real_factory(name)

    monkeypatch.setattr(_common, "workload_factory", patched)
    jl = serve_env / "quar.jsonl"
    rc = drv.main([
        "--duration", "2", "--arrival", "poisson", "--rate", "30",
        "--seed", "5", "--report-interval", "0.5",
        "--workloads", "daxpy:4096:float32:1,allreduce:512:float32:1",
        "--quarantine-after", "2", "--jsonl", str(jl),
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "SERVE QUARANTINE daxpy:4096:float32:" in out
    assert "survived by the other classes" in out
    recs = [json.loads(ln) for ln in jl.read_text().splitlines()]
    quar = [r for r in recs if r.get("kind") == "serve"
            and r.get("event") == "quarantine"]
    assert quar and quar[0]["class"] == "daxpy:4096:float32"
    # the healthy class genuinely served
    ok = [r for r in recs if r.get("kind") == "serve"
          and r.get("event") == "summary"
          and r["class"] == "allreduce:512:float32"]
    assert ok and ok[0]["requests"] > 0 and ok[0]["errors"] == 0


def test_serve_driver_rejects_bad_table(serve_env, capsys):
    from tpu_mpi_tests.drivers import serve as drv

    rc = drv.main(["--duration", "1", "--workloads", "nosuch:128"])
    out = capsys.readouterr().out
    assert rc == 2 and "ERROR" in out and "unknown workload" in out


@pytest.mark.slow
def test_serve_driver_closed_loop_all_handlers(serve_env, capsys):
    """All four registered handler families under closed-loop load on
    the 8-fake-device mesh (slow: attn/halo compile)."""
    from tpu_mpi_tests.drivers import serve as drv

    rc = drv.main([
        "--duration", "2", "--arrival", "closed", "--concurrency", "3",
        "--seed", "1", "--report-interval", "1",
        "--workloads",
        "daxpy:4096:float32,halo:65536:float32,attn:128x32:float32,"
        "allreduce:512:float32",
        "--jsonl", str(serve_env / "closed.jsonl"),
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert out.count("SERVE ") >= 4


def test_serve_main_promotes_x64_for_float64_classes():
    """A float64 workload class must arm the x64 software path (else
    jnp silently truncates to f32 and every SLO row mislabels what
    ran); malformed specs defer to run()'s ERROR reporting."""
    from tpu_mpi_tests.drivers.serve import _table_wants_x64

    assert _table_wants_x64("daxpy:256:float64")
    assert _table_wants_x64("daxpy:256:float32,halo:512:float64:2")
    assert not _table_wants_x64("daxpy:256:float32")
    assert not _table_wants_x64("definitely::malformed::")


def test_serve_main_rejects_closed_concurrency_over_queue(capsys):
    """Shed closed-loop clients are never re-armed, so a population
    larger than the queue bound would silently decay — rejected."""
    from tpu_mpi_tests.drivers import serve as drv

    with pytest.raises(SystemExit):
        drv.main(["--arrival", "closed", "--concurrency", "50",
                  "--max-queue", "10"])
    assert "--max-queue" in capsys.readouterr().err


@pytest.mark.parametrize("argv", [
    ["--batch-deadline", "-1"],  # negative Timer fires instantly
    ["--batch-deadline", "0"],
    ["--max-queue", "0"],
])
def test_serve_main_rejects_degenerate_flags(argv, capsys):
    from tpu_mpi_tests.drivers import serve as drv

    with pytest.raises(SystemExit):
        drv.main(["--duration", "1"] + argv)
    assert "must be" in capsys.readouterr().err


def test_halo_handler_recovers_after_failed_batch(mesh8, monkeypatch):
    """Donated-state contract: a batch that fails mid-flight must not
    poison the class — the handler rebuilds its (possibly consumed)
    buffers and the next batch serves normally."""
    from tpu_mpi_tests.comm import halo as H
    from tpu_mpi_tests.drivers import _common

    step = _common.workload_factory("halo")(mesh8, (4096,), "float32")
    step(2)  # healthy baseline

    def flaky(*a, **kw):
        raise RuntimeError("transient device error")

    monkeypatch.setattr(H, "halo_exchange", flaky)
    with pytest.raises(RuntimeError):
        step(2)
    monkeypatch.undo()
    step(2)  # must serve again, not fail buffer-deleted forever


def test_workload_registry_names():
    from tpu_mpi_tests.drivers import _common

    names = _common.workload_names()
    assert {"daxpy", "halo", "attn", "allreduce"} <= set(names)
    with pytest.raises(KeyError):
        _common.workload_factory("nosuch")
