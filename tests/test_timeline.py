"""tpumt-trace (instrument/timeline.py): cross-rank timeline merging —
Chrome trace-event export with clock offsets applied, the ASCII swimlane
behind ``tpumt-report --timeline``, pre-timeline JSONL compatibility, and
the driver ``--trace-out`` auto-merge."""

import json

import pytest

from tpu_mpi_tests.instrument import timeline
from tpu_mpi_tests.instrument.aggregate import (
    expand_rank_files,
    main as report_main,
    summarize,
)


def _write_jsonl(path, records):
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


@pytest.fixture()
def two_rank_run(tmp_path):
    """Two synthetic per-rank streams with KNOWN clock offsets: rank 1's
    wall clock runs 0.5 s ahead of rank 0's. After alignment both ranks'
    first all_gather starts at the same instant; the second starts 100 ms
    later on rank 1 (a true 100 ms barrier skew at step 1)."""
    _write_jsonl(tmp_path / "run.p0.jsonl", [
        {"kind": "manifest", "process_index": 0, "process_count": 2},
        {"kind": "clock_sync", "rank": 0, "offset_s": 0.0,
         "method": "barrier_echo"},
        {"kind": "span", "op": "all_gather", "nbytes": 1 << 20,
         "gbps": 4.0, "axis": "shard", "world": 2, "seconds": 0.25,
         "t_start": 100.0, "t_end": 100.25, "rank": 0},
        {"kind": "span", "op": "all_gather", "nbytes": 1 << 20,
         "seconds": 0.25, "t_start": 101.0, "t_end": 101.25, "rank": 0},
        {"kind": "time", "phase": "exchange", "seconds": 1.0,
         "t_start": 100.0, "t_end": 101.3, "rank": 0},
        {"kind": "dispatch", "note": "ring_halo_pallas(world=2)",
         "t": 100.9, "rank": 0},
    ])
    _write_jsonl(tmp_path / "run.p1.jsonl", [
        {"kind": "manifest", "process_index": 1, "process_count": 2},
        {"kind": "clock_sync", "rank": 1, "offset_s": 0.5,
         "method": "barrier_echo"},
        {"kind": "span", "op": "all_gather", "nbytes": 1 << 20,
         "seconds": 0.25, "t_start": 100.5, "t_end": 100.75, "rank": 1},
        {"kind": "span", "op": "all_gather", "nbytes": 1 << 20,
         "seconds": 0.25, "t_start": 101.6, "t_end": 101.85, "rank": 1},
        {"kind": "time", "phase": "exchange", "seconds": 1.1,
         "t_start": 100.5, "t_end": 101.9, "rank": 1},
        {"kind": "watchdog", "phase": "driver", "deadline_s": 60.0,
         "t": 101.95, "rank": 1},
    ])
    return [str(tmp_path / "run.p0.jsonl"), str(tmp_path / "run.p1.jsonl")]


class TestChromeTrace:
    def test_golden_merge_offsets_applied(self, two_rank_run):
        """The acceptance golden: valid trace-event fields, pid/tid per
        rank, ts/dur in microseconds, rank 1 shifted by its 0.5 s
        offset."""
        doc = timeline.chrome_trace(two_rank_run)
        evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        for e in evs:  # schema every viewer requires
            assert set(e) >= {"ph", "ts", "dur", "pid", "tid", "name"}
        gather = sorted(
            [e for e in evs if e["name"] == "all_gather"],
            key=lambda e: (e["ts"], e["pid"]),
        )
        assert [e["pid"] for e in gather] == [0, 1, 0, 1]
        assert all(e["tid"] == timeline.TID_COMM for e in gather)
        # offsets applied: both step-0 gathers align at ts=0 even though
        # rank 1 stamped t_start=100.5 on its (fast) local clock...
        assert gather[0]["ts"] == 0.0
        assert gather[1]["ts"] == pytest.approx(0.0, abs=1e-6)
        # ...and step 1 keeps its REAL 100 ms skew (101.6-0.5 vs 101.0)
        assert gather[2]["ts"] == pytest.approx(1.0e6)
        assert gather[3]["ts"] == pytest.approx(1.1e6)
        assert all(e["dur"] == pytest.approx(0.25e6) for e in gather)
        # span annotations survive into args
        assert gather[0]["args"]["nbytes"] == 1 << 20
        assert gather[0]["args"]["gbps"] == 4.0
        assert gather[0]["args"]["axis"] == "shard"
        # phases land on the nested phase track
        phases = [e for e in evs if e["name"] == "exchange"]
        assert {e["pid"] for e in phases} == {0, 1}
        assert all(e["tid"] == timeline.TID_PHASE for e in phases)
        assert phases[0]["dur"] == pytest.approx(1.3e6)
        # dispatch note -> thread instant; watchdog -> process instant
        inst = {e["cat"]: e for e in doc["traceEvents"] if e["ph"] == "i"}
        assert inst["dispatch"]["name"] == "ring_halo_pallas(world=2)"
        assert inst["dispatch"]["s"] == "t"
        assert inst["watchdog"]["name"] == "WATCHDOG driver"
        assert inst["watchdog"]["s"] == "p" and inst["watchdog"]["pid"] == 1
        assert inst["watchdog"]["ts"] == pytest.approx(1.45e6)
        # per-rank track metadata
        meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert {(m["name"], m["pid"]) for m in meta} >= {
            ("process_name", 0), ("process_name", 1)
        }
        assert doc["otherData"]["clock_offsets_s"] == {"0": 0.0, "1": 0.5}

    def test_write_trace_round_trips_through_json_load(
        self, two_rank_run, tmp_path
    ):
        out = tmp_path / "trace.json"
        n = timeline.write_trace(two_rank_run, str(out))
        doc = json.load(open(out))  # acceptance: json.load accepts it
        assert n == 8  # 4 comm spans + 2 phases + 1 dispatch + 1 watchdog
        assert len([e for e in doc["traceEvents"] if e["ph"] != "M"]) == n

    def test_cli_main_expands_rank_set(self, two_rank_run, tmp_path):
        base = two_rank_run[0].replace(".p0", "")
        out = tmp_path / "t.json"
        rc = timeline.main([base, "-o", str(out)])
        assert rc == 0
        doc = json.load(open(out))
        assert {e["pid"] for e in doc["traceEvents"]} == {0, 1}

    def test_cli_missing_files(self, tmp_path):
        assert timeline.main([str(tmp_path / "nope.jsonl")]) == 1


class TestPreTimelineCompat:
    """Pre-PR JSONL (no t_start/t_end, no clock_sync) must neither crash
    the trace merge nor the stats aggregation."""

    @pytest.fixture()
    def old_files(self, tmp_path):
        _write_jsonl(tmp_path / "old.p0.jsonl", [
            {"kind": "manifest", "process_index": 0},
            {"kind": "time", "phase": "exchange", "seconds": 1.0,
             "rank": 0},
            {"kind": "span", "op": "all_gather", "nbytes": 64,
             "seconds": 0.5, "gbps": 1.0, "rank": 0},
        ])
        return [str(tmp_path / "old.p0.jsonl")]

    def test_trace_valid_but_empty(self, old_files, tmp_path):
        out = tmp_path / "trace.json"
        n = timeline.write_trace(old_files, str(out))
        assert n == 0
        doc = json.load(open(out))
        assert [e for e in doc["traceEvents"] if e["ph"] != "M"] == []
        assert doc["otherData"]["unplaced_records"] == 2

    def test_report_still_aggregates(self, old_files):
        s = summarize(old_files)
        assert s["phases"]["exchange"]["mean_s"] == 1.0
        assert s["ops"]["all_gather"]["ops"] == 1

    def test_swimlane_says_no_timestamps(self, old_files):
        (line,) = timeline.ascii_swimlane(old_files)
        assert "no timestamped records" in line


class TestAsciiSwimlane:
    def test_lanes_and_skew_series(self, two_rank_run):
        lines = timeline.ascii_swimlane(two_rank_run, width=40)
        text = "\n".join(lines)
        assert lines[0].startswith("TIMELINE ranks=2")
        assert "PHASE exchange" in text
        lanes = [ln for ln in lines if ln.strip().startswith("r")]
        assert len(lanes) == 2
        assert all("|" in ln and "#" in ln for ln in lanes)
        # the known per-step skews: step0 aligned, step1 off by 100 ms
        (skew,) = [ln for ln in lines if ln.startswith("SKEW all_gather")]
        assert "over 2 steps" in skew
        assert "0 100" in skew
        assert "max 100ms @step 1" in skew

    def test_report_timeline_mode(self, two_rank_run, capsys):
        base = two_rank_run[0].replace(".p0", "")
        rc = report_main(["--timeline", "--width", "32", base])
        out = capsys.readouterr().out
        assert rc == 0
        assert out.startswith("TIMELINE ranks=2")
        assert "SKEW all_gather" in out
        # stats mode still works on the same files (both CLIs share the
        # rank-set expansion)
        assert report_main([base]) == 0
        assert "OP all_gather" in capsys.readouterr().out


def test_driver_trace_out_end_to_end(tmp_path, capsys):
    """--trace-out: the daxpy driver merges its own JSONL into a valid
    Perfetto-loadable trace on reporter close (phase spans placed, rank
    track present, clock_sync recorded with offset 0 single-process)."""
    from tpu_mpi_tests.drivers import daxpy

    jl = tmp_path / "run.jsonl"
    tr = tmp_path / "trace.json"
    rc = daxpy.main(
        ["--n", "256", "--dtype", "float32", "--telemetry",
         "--jsonl", str(jl), "--trace-out", str(tr)]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert f"TRACE {tr}" in out
    recs = [json.loads(ln) for ln in jl.read_text().splitlines()]
    sync = [r for r in recs if r.get("kind") == "clock_sync"]
    assert len(sync) == 1 and sync[0]["offset_s"] == 0.0
    assert sync[0]["method"] == "single_process"
    times = [r for r in recs if r.get("kind") == "time"]
    assert times and all(
        r["t_start"] is not None and r["t_end"] >= r["t_start"]
        for r in times
    )
    doc = json.load(open(tr))
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"copyInput", "kernel", "copyOutput"} <= names


def test_trace_out_without_jsonl_notes_and_skips(capsys, tmp_path):
    from tpu_mpi_tests.drivers import daxpy

    tr = tmp_path / "trace.json"
    rc = daxpy.main(["--n", "64", "--dtype", "float32",
                     "--trace-out", str(tr)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "--trace-out needs --jsonl" in out
    assert not tr.exists()


def _ghost_siblings(tmp_path, run_sync_us=None):
    """Two .p<i> rank files at the base path, from some OTHER run."""
    for i in range(2):
        recs = [{"kind": "manifest", "process_index": i}]
        if run_sync_us is not None:
            recs.append({"kind": "clock_sync", "rank": i, "offset_s": 0.0,
                         "run_sync_us": run_sync_us})
        recs.append({"kind": "time", "phase": "ghost", "seconds": 1.0,
                     "t_start": 50.0, "t_end": 51.0, "rank": i})
        _write_jsonl(tmp_path / f"out.p{i}.jsonl", recs)


def test_trace_out_ignores_stale_rank_siblings_by_mtime(tmp_path):
    """Siblings from an OLD run with no run-identity stamp fall to the
    mtime filter: yesterday's 2-process files at the base path must not
    become ghost rank tracks under today's single-process merge."""
    import io
    import os
    import time as _time

    from tpu_mpi_tests.instrument.report import Reporter

    _ghost_siblings(tmp_path)
    for i in range(2):
        p = tmp_path / f"out.p{i}.jsonl"
        os.utime(p, (_time.time() - 3600, _time.time() - 3600))
    tr = tmp_path / "trace.json"
    with Reporter(stream=io.StringIO(),
                  jsonl_path=str(tmp_path / "out.jsonl"),
                  trace_out=str(tr)) as r:
        r.time_line("fresh", 0.5)
    doc = json.load(open(tr))
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert names == {"fresh"}


def test_trace_out_run_identity_beats_fresh_mtimes(tmp_path):
    """Back-to-back reruns (< 5 s apart) leave stale siblings with FRESH
    mtimes, where an mtime window cannot help; the shared clock_sync
    run_sync_us stamp still tells this run's files from the ghosts —
    and still admits a true same-run sibling."""
    import io

    from tpu_mpi_tests.instrument.report import Reporter

    _ghost_siblings(tmp_path, run_sync_us=111)  # other run, fresh mtime
    # a genuine same-run sibling rank file (matching stamp)
    _write_jsonl(tmp_path / "out.p9.jsonl", [
        {"kind": "manifest", "process_index": 9},
        {"kind": "clock_sync", "rank": 9, "offset_s": 0.0,
         "run_sync_us": 222},
        {"kind": "time", "phase": "peer", "seconds": 1.0,
         "t_start": 60.0, "t_end": 61.0, "rank": 9},
    ])
    tr = tmp_path / "trace.json"
    with Reporter(stream=io.StringIO(),
                  jsonl_path=str(tmp_path / "out.jsonl"),
                  trace_out=str(tr)) as r:
        r.run_sync_us = 222
        r.jsonl({"kind": "clock_sync", "rank": 0, "offset_s": 0.0,
                 "run_sync_us": 222})
        r.time_line("fresh", 0.5)
    doc = json.load(open(tr))
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert names == {"fresh", "peer"}


def test_clock_sync_digits_survive_float32():
    """The barrier-echo handshake ships timestamps through
    process_allgather, which canonicalizes to float32 when x64 is off;
    the base-2^24 digit codec must reconstruct epoch microseconds
    exactly through that round-trip (a raw float32 epoch is only
    ~128 s-accurate)."""
    import numpy as np

    from tpu_mpi_tests.instrument.manifest import _join_us, _split_us

    for t in (1785738694.948360, 0.0, 2_000_000_000.123456):
        through_f32 = _split_us(t).astype(np.float32).astype(np.float64)
        assert _join_us(through_f32) == pytest.approx(t, abs=1e-6)
    assert abs(float(np.float32(1785738694.948360)) - 1785738694.948360) > 1


def test_cli_tools_import_and_run_without_jax(two_rank_run, tmp_path):
    """tpumt-trace / tpumt-report are advertised for login nodes with no
    jax install: both must import and run with jax BLOCKED (the package
    __init__ re-exports resolve lazily)."""
    import subprocess
    import sys
    from pathlib import Path

    base = two_rank_run[0].replace(".p0", "")
    out = str(tmp_path / "nojax_trace.json")
    code = (
        "import sys\n"
        "class Block:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('jax blocked: login-node sim')\n"
        "sys.meta_path.insert(0, Block())\n"
        "from tpu_mpi_tests.instrument import aggregate, timeline\n"
        f"assert timeline.main([{base!r}, '-o', {out!r}]) == 0\n"
        f"assert aggregate.main([{base!r}]) == 0\n"
        f"assert aggregate.main(['--timeline', {base!r}]) == 0\n"
        "print('NOJAX OK')\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "NOJAX OK" in r.stdout
    assert json.load(open(out))["traceEvents"]


def test_trace_out_rerun_appends_select_current_run_segment(tmp_path):
    """Append-mode JSONL reuse: rerunning with the same --jsonl base
    appends a second run to every rank file. The merge must (a) still
    include siblings — the current stamp is NOT the file's first — and
    (b) select only the current run's segment, not bleed run 1's events
    through run 2's clock offset."""
    import io

    from tpu_mpi_tests.instrument.report import Reporter

    sib = tmp_path / "out.p1.jsonl"
    _write_jsonl(sib, [
        {"kind": "manifest", "process_index": 1},
        {"kind": "clock_sync", "rank": 1, "offset_s": 0.0,
         "run_sync_us": 111},
        {"kind": "time", "phase": "old_phase", "seconds": 1.0,
         "t_start": 10.0, "t_end": 11.0, "rank": 1},
    ])
    with sib.open("a") as fh:  # run 2 appends
        for rec in (
            {"kind": "manifest", "process_index": 1},
            {"kind": "clock_sync", "rank": 1, "offset_s": 0.25,
             "run_sync_us": 222},
            {"kind": "time", "phase": "new_phase", "seconds": 1.0,
             "t_start": 100.25, "t_end": 101.25, "rank": 1},
        ):
            fh.write(json.dumps(rec) + "\n")
    tr = tmp_path / "trace.json"
    with Reporter(stream=io.StringIO(),
                  jsonl_path=str(tmp_path / "out.jsonl"),
                  trace_out=str(tr)) as r:
        r.run_sync_us = 222
        r.jsonl({"kind": "clock_sync", "rank": 0, "offset_s": 0.0,
                 "run_sync_us": 222})
        r.time_line("fresh", 0.5)
    doc = json.load(open(tr))
    evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in evs} == {"fresh", "new_phase"}
    # run 2's offset applied to run 2's segment only
    (new,) = [e for e in evs if e["name"] == "new_phase"]
    assert new["pid"] == 1 and new["dur"] == pytest.approx(1.0e6)


def test_rank_streams_picks_newest_segment_by_default(tmp_path):
    """Offline tpumt-trace on a multi-run file: with no run id the
    newest run's segment is used (older runs' events would be misplaced
    by the newest clock offset)."""
    p = tmp_path / "multi.jsonl"
    _write_jsonl(p, [
        {"kind": "manifest", "process_index": 3},
        {"kind": "time", "phase": "old", "seconds": 1.0,
         "t_start": 10.0, "t_end": 11.0},
        {"kind": "manifest", "process_index": 3},
        {"kind": "clock_sync", "rank": 3, "offset_s": 0.5,
         "run_sync_us": 9},
        {"kind": "time", "phase": "new", "seconds": 1.0,
         "t_start": 100.5, "t_end": 101.5},
    ])
    ((rank, offset, records),) = timeline.rank_streams([str(p)])
    assert rank == 3 and offset == 0.5
    assert [r.get("phase") for r in records
            if r.get("kind") == "time"] == ["new"]
    assert timeline.run_sync_ids(str(p)) == {9}


def test_expand_rank_files_shared_with_report(two_rank_run):
    base = two_rank_run[0].replace(".p0", "")
    assert [f.rsplit("/", 1)[-1] for f in expand_rank_files([base])] == [
        "run.p0.jsonl", "run.p1.jsonl"
    ]


class TestMemCountersAndCompileTrack:
    """PR 5: ``kind:"mem"`` records become Perfetto counter tracks and
    ``kind:"compile"`` records a compile track, both clock-aligned."""

    @pytest.fixture()
    def mem_run(self, tmp_path):
        _write_jsonl(tmp_path / "mem.p0.jsonl", [
            {"kind": "manifest", "process_index": 0, "process_count": 2},
            {"kind": "clock_sync", "rank": 0, "offset_s": 0.0},
            {"kind": "span", "op": "all_gather", "seconds": 0.1,
             "t_start": 100.0, "t_end": 100.1, "rank": 0},
            {"kind": "mem", "event": "sample", "t": 100.05, "rank": 0,
             "devices": {"0": {"bytes_in_use": 64},
                         "1": {"bytes_in_use": 32}},
             "bytes_in_use": 96},
            {"kind": "compile", "label": "daxpy", "seconds": 0.5,
             "flops": 2048.0, "bytes_accessed": 4096.0,
             "t_start": 100.2, "t_end": 100.7, "rank": 0},
        ])
        _write_jsonl(tmp_path / "mem.p1.jsonl", [
            {"kind": "manifest", "process_index": 1, "process_count": 2},
            {"kind": "clock_sync", "rank": 1, "offset_s": 0.5},
            # census-only rank (CPU degrade path): still a counter
            {"kind": "mem", "event": "sample", "t": 100.55, "rank": 1,
             "live_bytes": 4096, "live_count": 3},
        ])
        return [str(tmp_path / "mem.p0.jsonl"),
                str(tmp_path / "mem.p1.jsonl")]

    def test_counter_events_offsets_applied(self, mem_run):
        doc = timeline.chrome_trace(mem_run)
        counters = [e for e in doc["traceEvents"] if e["ph"] == "C"]
        assert len(counters) == 2
        hbm = [e for e in counters if e["name"] == "HBM bytes_in_use"]
        live = [e for e in counters if e["name"] == "live bytes"]
        assert hbm[0]["pid"] == 0
        assert hbm[0]["args"] == {"dev0": 64, "dev1": 32}
        # rank 1's 0.5 s clock offset applied: both samples land at the
        # same aligned instant (100.05 on rank 0's axis)
        assert live[0]["pid"] == 1
        assert live[0]["ts"] == pytest.approx(hbm[0]["ts"])
        assert live[0]["args"] == {"bytes": 4096}

    def test_compile_track(self, mem_run):
        doc = timeline.chrome_trace(mem_run)
        evs = [e for e in doc["traceEvents"]
               if e["ph"] == "X" and e.get("cat") == "compile"]
        (c,) = evs
        assert c["name"] == "compile daxpy"
        assert c["tid"] == timeline.TID_COMPILE and c["pid"] == 0
        assert c["dur"] == pytest.approx(0.5e6)
        assert c["args"]["flops"] == 2048.0
        # the compile thread is named, but only on ranks that compiled
        meta = {(m["pid"], m["tid"]): m["args"]["name"]
                for m in doc["traceEvents"]
                if m["ph"] == "M" and m["name"] == "thread_name"}
        assert meta[(0, timeline.TID_COMPILE)] == "compile"
        assert (1, timeline.TID_COMPILE) not in meta

    def test_req_exemplar_track(self, tmp_path):
        """kind:"req" lifecycle exemplars render as queue + service
        spans on the per-rank "requests" thread: queue from arrival to
        dispatch, service from dispatch to done; a shed exemplar (no
        dispatch) is all queue; the thread is named only on ranks that
        carry exemplars."""
        _write_jsonl(tmp_path / "run.p0.jsonl", [
            {"kind": "manifest", "process_index": 0,
             "process_count": 1},
            {"kind": "req", "event": "complete", "class": "c:1:f32",
             "sampled": "p99_worst", "t_arrival": 100.0,
             "t_dispatch": 100.4, "t_done": 100.5, "queue_ms": 400.0,
             "service_ms": 100.0, "e2e_ms": 500.0, "rank": 0},
            {"kind": "req", "event": "shed", "class": "c:1:f32",
             "sampled": "shed", "t_arrival": 101.0, "t_done": 101.2,
             "queue_ms": 200.0, "rank": 0},
        ])
        doc = timeline.chrome_trace([str(tmp_path / "run.p0.jsonl")])
        evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        q = [e for e in evs if e.get("cat") == "req_queue"]
        s = [e for e in evs if e.get("cat") == "req_service"]
        assert len(q) == 2 and len(s) == 1
        assert all(e["tid"] == timeline.TID_REQ for e in q + s)
        done = {e["name"]: e for e in q}
        assert done["queue complete c:1:f32"]["dur"] \
            == pytest.approx(0.4e6)
        # the shed exemplar queues until its terminal drop
        assert done["queue shed c:1:f32"]["dur"] == pytest.approx(0.2e6)
        assert s[0]["dur"] == pytest.approx(0.1e6)
        assert s[0]["args"]["sampled"] == "p99_worst"
        meta = {(m["pid"], m["tid"]): m["args"]["name"]
                for m in doc["traceEvents"]
                if m["ph"] == "M" and m["name"] == "thread_name"}
        assert meta[(0, timeline.TID_REQ)] == "requests"

    def test_counters_count_as_placed_events(self, mem_run, tmp_path):
        out = tmp_path / "t.json"
        n = timeline.write_trace(mem_run, str(out))
        doc = json.load(open(out))
        assert n == len(
            [e for e in doc["traceEvents"] if e["ph"] != "M"]
        )
        # 1 comm span + 1 compile span + 2 counters
        assert n == 4

    def test_mem_record_without_t_counts_unplaced(self, tmp_path):
        _write_jsonl(tmp_path / "u.p0.jsonl", [
            {"kind": "mem", "event": "sample", "live_bytes": 1},
        ])
        doc = timeline.chrome_trace([str(tmp_path / "u.p0.jsonl")])
        assert doc["otherData"]["unplaced_records"] == 1
        assert [e for e in doc["traceEvents"] if e["ph"] != "M"] == []
