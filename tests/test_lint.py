"""tpumt-lint golden tests: every rule family fails on its bad fixture,
passes on its good fixture, and respects ``# tpumt: ignore[...]``;
engine behaviors (suppressions, select/ignore, output formats, exit
codes, self-clean gate) on top.

The fixtures live in ``tpu_mpi_tests/analysis/fixtures/`` — excluded
from recursive walks (deliberately-bad code must not fail the
self-clean gate) but always linted when passed explicitly, which is
what these tests do.
"""

import json
from pathlib import Path

import pytest

from tpu_mpi_tests.analysis import cli
from tpu_mpi_tests.analysis.core import (
    collect_suppressions,
    lint_paths,
    rule_table,
)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tpu_mpi_tests" / "analysis" / "fixtures"


@pytest.fixture(autouse=True)
def _hermetic_cache(tmp_path, monkeypatch):
    """CLI invocations default the analysis cache ON — point it at a
    per-test temp file so tests never touch (or depend on) the user's
    ~/.cache/tpumt/lint.json."""
    monkeypatch.setenv(
        "TPU_MPI_LINT_CACHE", str(tmp_path / "_lintcache.json")
    )


#: (family prefix, fixture stem) for the single-file families
FILE_FAMILIES = [
    ("TPM1", "tpm1"),
    ("TPM2", "tpm2"),
    ("TPM3", "tpm3"),
    ("TPM5", "tpm5"),
    ("TPM6", "tpm6"),
    ("TPM7", "tpm7"),
    ("TPM8", "tpm8"),
    ("TPM10", "tpm10"),
]

#: (family prefix, fixture stem) for the ISSUE-10 whole-program
#: families — mini package trees, because the findings are
#: interprocedural by construction (helper in one file, hazard in
#: another)
TREE_FAMILIES = [
    ("TPM11", "tpm11"),
    ("TPM12", "tpm12"),
]


def codes_of(findings):
    return [f.code for f in findings]


@pytest.mark.parametrize("family,stem", FILE_FAMILIES)
def test_family_bad_good_suppressed(family, stem):
    bad = lint_paths([str(FIXTURES / f"{stem}_bad.py")])
    assert any(c.startswith(family) for c in codes_of(bad)), (
        f"{stem}_bad.py must raise a {family}xx finding, got {bad}"
    )

    good = lint_paths([str(FIXTURES / f"{stem}_good.py")])
    assert not any(c.startswith(family) for c in codes_of(good)), (
        f"{stem}_good.py must be clean of {family}xx, got {good}"
    )

    sup = lint_paths([str(FIXTURES / f"{stem}_suppressed.py")])
    assert not any(c.startswith(family) for c in codes_of(sup)), (
        f"suppression comment must silence {family}xx, got {sup}"
    )
    # a suppression that fired is used: no TPM900 on the same file
    assert "TPM900" not in codes_of(sup), sup


@pytest.mark.parametrize("family,stem", TREE_FAMILIES)
def test_project_family_bad_good_suppressed_trees(family, stem):
    """The whole-program families' goldens: each tree splits helper and
    hazard across files, so a per-file scan of any single file would
    see nothing — the finding only exists through the summaries."""
    bad = lint_paths([str(FIXTURES / f"{stem}_bad")])
    assert any(c.startswith(family) for c in codes_of(bad)), (
        f"{stem}_bad must raise a {family}xx finding, got {bad}"
    )

    good = lint_paths([str(FIXTURES / f"{stem}_good")])
    assert not any(c.startswith(family) for c in codes_of(good)), (
        f"{stem}_good must be clean of {family}xx, got {good}"
    )

    sup = lint_paths([str(FIXTURES / f"{stem}_suppressed")])
    assert not any(c.startswith(family) for c in codes_of(sup)), (
        f"suppression comment must silence {family}xx, got {sup}"
    )
    assert "TPM900" not in codes_of(sup), sup


def test_collective_divergence_seeded_mutant(tmp_path):
    """Mutation gate (acceptance criterion): a seeded rank-divergent
    collective — rank test in one function, collective through a helper
    in ANOTHER FILE — is flagged; hoisting the collective out of the
    branch clears it."""
    pkg = tmp_path / "spmd"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "comms.py").write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def global_sum(x, mesh):\n"
        "    return allreduce_sum(x, mesh)\n"
    )
    step = pkg / "step.py"
    step.write_text(
        "from spmd.comms import global_sum\n"
        "def run(x, mesh, rank):\n"
        "    if rank == 0:\n"
        "        x = global_sum(x, mesh)\n"
        "    return x\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert "TPM1101" in codes_of(findings), findings
    f = next(f for f in findings if f.code == "TPM1101")
    assert f.line == 3 and "allreduce_sum" in f.message, f
    # the fix: every rank enters the collective
    step.write_text(
        "from spmd.comms import global_sum\n"
        "def run(x, mesh, rank):\n"
        "    x = global_sum(x, mesh)\n"
        "    if rank == 0:\n"
        "        print('done')\n"
        "    return x\n"
    )
    assert "TPM1101" not in codes_of(lint_paths([str(tmp_path)]))


def test_collective_divergence_both_branches_equal_is_clean(tmp_path):
    """A rank branch whose BOTH paths dispatch the same collective
    sequence does not diverge (e.g. selecting an operand, then the same
    reduce on each side)."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def run(x, y, mesh, rank):\n"
        "    if rank == 0:\n"
        "        out = allreduce_sum(x, mesh)\n"
        "    else:\n"
        "        out = allreduce_sum(y, mesh)\n"
        "    return out\n"
    )
    assert "TPM1101" not in codes_of(lint_paths([str(p)]))


def test_donation_safety_seeded_mutant_through_helper(tmp_path):
    """Mutation gate (acceptance criterion): a use-after-donate where
    the donation happens ONE HELPER LEVEL down (the helper forwards its
    param into allreduce_sum's donated position 0) is flagged; the
    rebind idiom clears it."""
    pkg = tmp_path / "dnt"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "helper.py").write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def reduce_into(buf, mesh):\n"
        "    return allreduce_sum(buf, mesh)\n"
    )
    drv = pkg / "driver.py"
    drv.write_text(
        "from dnt.helper import reduce_into\n"
        "def step(x, mesh):\n"
        "    total = reduce_into(x, mesh)\n"
        "    return x + total\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert "TPM1201" in codes_of(findings), findings
    f = next(f for f in findings if f.code == "TPM1201")
    assert f.line == 4  # anchored at the read of the deleted buffer
    assert "reduce_into" in f.message
    drv.write_text(
        "from dnt.helper import reduce_into\n"
        "def step(x, mesh):\n"
        "    x = reduce_into(x, mesh)\n"
        "    return x * 2.0\n"
    )
    assert "TPM1201" not in codes_of(lint_paths([str(tmp_path)]))


def test_donation_safety_loop_and_return_shapes(tmp_path):
    """TPM1201 beyond the goldens: donating inside a loop that never
    rebinds feeds a deleted buffer to iteration 2 (flagged at the
    call); a donation under `return` exits the statement list, so the
    mutually-exclusive-branch dispatch fork is clean; and same-named
    locals in SIBLING functions are unrelated (no cross-scope leak)."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def looped(x, mesh, n):\n"
        "    for _ in range(n):\n"
        "        allreduce_sum(x, mesh)\n"
        "    return x\n"
    )
    findings = lint_paths([str(p)])
    assert "TPM1201" in codes_of(findings), findings
    assert "inside a loop" in findings[0].message
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def fork(x, mesh, host):\n"
        "    if host:\n"
        "        return allreduce_sum(x, mesh)\n"
        "    return x.sum()\n"
    )
    assert "TPM1201" not in codes_of(lint_paths([str(p)]))
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def donates(x, mesh):\n"
        "    x = allreduce_sum(x, mesh)\n"
        "    return x\n"
        "def unrelated(x):\n"
        "    return x + 1\n"  # different scope's x, not a stale read
    )
    assert "TPM1201" not in codes_of(lint_paths([str(p)]))


def test_axis_program_consistency_seeded_mutant(tmp_path):
    """Mutation gate (acceptance criterion): a cross-file unbound axis
    — psum over an axis no file in the program binds — is flagged
    (TPM502), in a file TPM501 used to SKIP for having no local mesh
    context; binding the axis in the OTHER file clears it (the
    same-file skip is lifted, not just re-scoped)."""
    (tmp_path / "kernel.py").write_text(
        "from jax import lax\n"
        "def local_sum(v):\n"
        "    return lax.psum(v, 'ghost')\n"
    )
    mesh = tmp_path / "meshes.py"
    mesh.write_text(
        "from jax.sharding import Mesh\n"
        "def make(devs):\n"
        "    return Mesh(devs, ('x',))\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert "TPM502" in codes_of(findings), findings
    f = next(f for f in findings if f.code == "TPM502")
    assert f.line == 3 and "'ghost'" in f.message, f
    # alone, the kernel file still skips per-file (no local context) —
    # the program rule is what closed that hole
    alone = lint_paths([str(tmp_path / "kernel.py")])
    assert "TPM501" not in codes_of(alone)
    assert "TPM502" in codes_of(alone)
    # bind the axis ANYWHERE in the program: clean
    mesh.write_text(
        "from jax.sharding import Mesh\n"
        "def make(devs):\n"
        "    return Mesh(devs, ('x', 'ghost'))\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert "TPM502" not in codes_of(findings), findings


def test_escaped_async_handle_seeded_mutant(tmp_path):
    """Mutation gate (acceptance criterion): an async_span handle
    returned by a helper and assigned to a name the caller never reads
    is flagged (TPM802) — nobody will done() it; consuming the handle
    clears it."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from tpu_mpi_tests.instrument.telemetry import async_span\n"
        "def start(op):\n"
        "    h = async_span(op)\n"
        "    return h\n"
        "def run(fn, z):\n"
        "    hh = start('exchange')\n"
        "    return fn(z)\n"
    )
    findings = lint_paths([str(p)])
    assert "TPM802" in codes_of(findings), findings
    f = next(f for f in findings if f.code == "TPM802")
    assert f.line == 6 and "'hh'" in f.message, f
    p.write_text(
        "from tpu_mpi_tests.instrument.telemetry import async_span\n"
        "def start(op):\n"
        "    h = async_span(op)\n"
        "    return h\n"
        "def run(fn, z):\n"
        "    hh = start('exchange')\n"
        "    out = fn(z)\n"
        "    hh.done(out)\n"
        "    return out\n"
    )
    assert "TPM802" not in codes_of(lint_paths([str(p)]))


def test_sync_honesty_interprocedural(tmp_path):
    """TPM102: a timed region that dispatches jax work only THROUGH a
    helper is dishonest timing one frame deeper — flagged via the
    summaries; a helper that syncs internally is honest and clean."""
    p = tmp_path / "mod.py"
    p.write_text(
        "import time\n"
        "import jax.numpy as jnp\n"
        "def helper(x):\n"
        "    return jnp.sin(x)\n"
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = helper(x)\n"
        "    return y, time.perf_counter() - t0\n"
    )
    findings = lint_paths([str(p)])
    assert "TPM102" in codes_of(findings), findings
    # TPM101 stays silent — there is no DIRECT dispatch in the region
    assert "TPM101" not in codes_of(findings)
    f = next(f for f in findings if f.code == "TPM102")
    assert f.line == 7 and "helper" in f.message, f
    p.write_text(
        "import time\n"
        "import jax.numpy as jnp\n"
        "from tpu_mpi_tests.instrument.timers import block\n"
        "def helper(x):\n"
        "    return block(jnp.sin(x))\n"
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = helper(x)\n"
        "    return y, time.perf_counter() - t0\n"
    )
    assert "TPM102" not in codes_of(lint_paths([str(p)]))


@pytest.mark.parametrize("variant,expect", [
    ("tpm4_bad", True),
    ("tpm4_good", False),
    ("tpm4_suppressed", False),
])
def test_import_hygiene_mini_trees(variant, expect):
    findings = lint_paths(
        [str(FIXTURES / variant)],
        entry_modules={"app.cli": "app.cli"},
    )
    has = any(c == "TPM401" for c in codes_of(findings))
    assert has == expect, findings
    if variant == "tpm4_suppressed":
        assert "TPM900" not in codes_of(findings), findings


def test_import_hygiene_duplicate_module_names_all_scanned():
    """Linting the bad and good mini-trees TOGETHER must still report
    the bad tree's TPM401: both define module 'app.cli', and collapsing
    duplicates would silently drop one tree from the reachability scan
    (the gate must widen, never under-report)."""
    findings = lint_paths(
        [str(FIXTURES / "tpm4_bad"), str(FIXTURES / "tpm4_good")],
        entry_modules={"app.cli": "app.cli"},
    )
    assert "TPM401" in codes_of(findings), findings
    assert all("tpm4_bad" in f.path for f in findings
               if f.code == "TPM401"), findings


def test_import_hygiene_exempts_importerror_guarded_try(tmp_path):
    """`try: import jax / except ImportError:` imports fine where jax
    is absent — the canonical safe optional import must not be flagged.
    An import in the HANDLER still is: it runs exactly when the body
    already failed."""
    pkg = tmp_path / "app"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "cli.py").write_text(
        "try:\n"
        "    import jax\n"
        "except ImportError:\n"
        "    jax = None\n"
    )
    findings = lint_paths([str(tmp_path)],
                          entry_modules={"app.cli": "app.cli"})
    assert "TPM401" not in codes_of(findings), findings

    (pkg / "cli.py").write_text(
        "try:\n"
        "    import jax\n"
        "except ImportError:\n"
        "    from jax.experimental import compat as jax\n"
    )
    findings = lint_paths([str(tmp_path)],
                          entry_modules={"app.cli": "app.cli"})
    assert codes_of(findings).count("TPM401") == 1, findings


def test_missing_py_file_reports_one_finding(tmp_path):
    """A nonexistent explicit .py path must yield exactly ONE TPM902
    (the existence check), not a second contradictory parse error."""
    findings = lint_paths([str(tmp_path / "ghost.py")])
    assert codes_of(findings) == ["TPM902"], findings
    assert "does not exist" in findings[0].message


def test_bad_fixture_findings_carry_lines_and_messages():
    findings = lint_paths([str(FIXTURES / "tpm1_bad.py")])
    f = next(f for f in findings if f.code == "TPM101")
    assert f.line == 10  # the dispatch line, where the fix goes
    assert "block" in f.message
    assert str(FIXTURES / "tpm1_bad.py") == f.path


def test_unused_suppression_is_a_finding():
    findings = lint_paths([str(FIXTURES / "tpm9_unused.py")])
    assert codes_of(findings) == ["TPM900"]
    assert "TPM101" in findings[0].message


def test_malformed_suppression_is_a_finding(tmp_path):
    p = tmp_path / "mal.py"
    p.write_text("x = 1  # tpumt: ignore TPM101 (missing brackets)\n")
    findings = lint_paths([str(p)])
    assert codes_of(findings) == ["TPM901"]


def test_suppression_marker_inside_string_is_not_parsed():
    # tokenize-based collection: the marker in a string literal is data
    src = 's = "# tpumt: ignore[TPM101]"\n'
    supps, malformed = collect_suppressions(src)
    assert supps == [] and malformed == []


def test_suppression_on_closing_paren_of_multiline_call(tmp_path):
    """Findings anchor to a multi-line call's FIRST line; a trailing
    suppression on the closing paren must still silence it (matched via
    the logical statement's start line) and count as used."""
    p = tmp_path / "multi.py"
    p.write_text(
        "import time\n"
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = jnp.sin(\n"
        "        x\n"
        "    )  # tpumt: ignore[TPM101]\n"
        "    return y, time.perf_counter() - t0\n"
    )
    assert lint_paths([str(p)]) == []


def test_missing_path_is_a_finding_not_a_clean_pass(tmp_path):
    """A lint gate pointed at a renamed/missing directory must fail
    loudly, never lint nothing and exit 0."""
    findings = lint_paths([str(tmp_path / "no_such_dir")])
    assert codes_of(findings) == ["TPM902"]
    assert "vacuously" in findings[0].message
    notes = tmp_path / "notes.txt"
    notes.write_text("not python\n")
    findings = lint_paths([str(notes)])
    assert codes_of(findings) == ["TPM902"]


def test_select_and_ignore_filter_families():
    bad = str(FIXTURES / "tpm2_bad.py")
    assert lint_paths([bad], select=["TPM1xx"]) == []
    assert lint_paths([bad], ignore=["TPM2"]) == []
    kept = lint_paths([bad], select=["TPM2"])
    assert kept and all(c == "TPM201" for c in codes_of(kept))


def test_ignored_family_does_not_warn_unused_suppression():
    sup = str(FIXTURES / "tpm1_suppressed.py")
    assert lint_paths([sup], ignore=["TPM1"]) == []


def test_syntax_error_reports_tpm902(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = lint_paths([str(p)])
    assert codes_of(findings) == ["TPM902"]


def test_recursive_walk_skips_fixtures_dir(tmp_path):
    sub = tmp_path / "pkg" / "fixtures"
    sub.mkdir(parents=True)
    (sub / "bad.py").write_text(
        (FIXTURES / "tpm1_bad.py").read_text()
    )
    assert lint_paths([str(tmp_path)]) == []


def test_schedule_constants_tune_modules_exempt():
    """The priors tables live in tpu_mpi_tests/tune/ by design — the
    sanctioned home lints clean while the same text elsewhere fires
    (tpm7_bad mirrors the pre-autotuner comm/ring.py tables)."""
    findings = lint_paths([str(REPO / "tpu_mpi_tests" / "tune")])
    assert not any(c == "TPM701" for c in codes_of(findings)), findings


def test_schedule_constants_mutation_outside_tune(tmp_path):
    """Mutation check: re-pinning a MEASURED_BEST-style table in a
    non-tune module is caught; registering the SAME numbers through
    declare_space is not (routing through the registry IS the fix),
    and non-schedule caps constants stay out of scope."""
    p = tmp_path / "mod.py"
    p.write_text('MEASURED_BEST_K_TILE = {"contig": 2048}\n')
    assert "TPM701" in codes_of(lint_paths([str(p)]))
    p.write_text(
        "from tpu_mpi_tests.tune.registry import declare_space\n"
        'SPACE_K_TILE = declare_space("demo/k", (2048, 512))\n'
    )
    assert "TPM701" not in codes_of(lint_paths([str(p)]))
    p.write_text("FLIGHT_CAPACITY = 64\n")  # no schedule keyword
    assert "TPM701" not in codes_of(lint_paths([str(p)]))
    # the ISSUE-7 pipeline knobs are schedule words too: a re-pinned
    # depth constant outside tune/ fires, the declared space does not
    p.write_text("RING_PIPELINE_DEPTH = 2\n")
    assert "TPM701" in codes_of(lint_paths([str(p)]))


def test_schedule_constants_workloads_extended_keywords(tmp_path):
    """ISSUE-8 extension: inside tpu_mpi_tests.workloads the keyword
    set grows the serving-era knob vocabulary (CAPACITY/LOOKUP/COMBINE/
    ROUTE/EXPERT/FANOUT) — a spec's pinned capacity constant fires and
    is exempt ONLY via declare_space; the same name outside workloads/
    stays out of scope (FLIGHT_CAPACITY is a ring-buffer bound there)."""
    pkg = tmp_path / "tpu_mpi_tests" / "workloads"
    pkg.mkdir(parents=True)
    (tmp_path / "tpu_mpi_tests" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    spec = pkg / "myspec.py"
    spec.write_text("MOE_CAPACITY_FACTOR = 1.25\n")
    assert "TPM701" in codes_of(lint_paths([str(spec)]))
    spec.write_text("EMBED_LOOKUP_WIDTH = 128\n")
    assert "TPM701" in codes_of(lint_paths([str(spec)]))
    # declare_space is the sanctioned route, inside workloads/ too
    spec.write_text(
        "from tpu_mpi_tests.tune.registry import declare_space\n"
        'CAPACITY_SPACE = declare_space("moe/cap", (1.25, 2.0))\n'
    )
    assert "TPM701" not in codes_of(lint_paths([str(spec)]))
    # outside workloads/, the extended words stay out of scope
    other = tmp_path / "other.py"
    other.write_text("MOE_CAPACITY_FACTOR = 1.25\n")
    assert "TPM701" not in codes_of(lint_paths([str(other)]))


def test_overlap_region_scoping(tmp_path):
    """TPM801 behavior beyond the goldens: the region closes at the
    handle's consume point (a sync after `.done()` is clean), an
    UNCONSUMED handle keeps the region open to the end of the function,
    and a nested function's syncs do not leak into the outer region."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from tpu_mpi_tests.instrument.telemetry import async_span\n"
        "from tpu_mpi_tests.instrument.timers import block\n"
        "def good(fn, z):\n"
        "    h = async_span('op')\n"
        "    ex = fn(z)\n"
        "    h.done(ex)\n"
        "    return block(ex)\n"
    )
    assert "TPM801" not in codes_of(lint_paths([str(p)]))
    p.write_text(
        "from tpu_mpi_tests.instrument.telemetry import async_span\n"
        "from tpu_mpi_tests.instrument.timers import block\n"
        "def dangling(fn, z):\n"
        "    h = async_span('op')\n"
        "    ex = fn(z)\n"
        "    return block(ex)\n"  # handle never consumed: still a region
    )
    assert "TPM801" in codes_of(lint_paths([str(p)]))
    p.write_text(
        "from tpu_mpi_tests.instrument.telemetry import async_span\n"
        "from tpu_mpi_tests.instrument.timers import block\n"
        "def outer(fn, z):\n"
        "    h = async_span('op')\n"
        "    ex = fn(z)\n"
        "    h.done(ex)\n"
        "def unrelated(y):\n"
        "    return block(y)\n"  # no region in unrelated's scope
    )
    assert "TPM801" not in codes_of(lint_paths([str(p)]))


def test_chaos_containment_scoping(tmp_path):
    """TPM1001 beyond the goldens: a driver-shaped module touching the
    chaos layer is a finding, while test modules are exempt (tests
    exist to exercise the faults). The sanctioned arm-point and the
    chaos package itself are proven exempt by the self-clean gate —
    drivers/_common and tpu_mpi_tests/chaos both lint in-tree."""
    src = (
        "from tpu_mpi_tests.chaos import arm_from_spec\n"
        "def run(args):\n"
        "    arm_from_spec('kill:rank=1:op=x', rank=0)\n"
    )
    prod = tmp_path / "hotpath.py"
    prod.write_text(src)
    codes = codes_of(lint_paths([str(prod)]))
    assert codes.count("TPM1001") == 2  # the import AND the call
    for exempt_name in ("test_hotpath.py", "conftest.py"):
        p = tmp_path / exempt_name
        p.write_text(src)
        assert "TPM1001" not in codes_of(lint_paths([str(p)]))


def test_cli_human_output_and_exit_codes(capsys):
    rc = cli.main([str(FIXTURES / "tpm1_bad.py")])
    out = capsys.readouterr()
    assert rc == 1
    assert "TPM101" in out.out
    assert "finding" in out.err

    rc = cli.main([str(FIXTURES / "tpm1_good.py")])
    out = capsys.readouterr()
    assert rc == 0
    assert out.out == ""


def test_cli_json_output(capsys):
    rc = cli.main(["--format", "json", str(FIXTURES / "tpm3_bad.py")])
    out = capsys.readouterr()
    assert rc == 1
    doc = json.loads(out.out)
    assert doc["version"] == 1
    assert doc["count"] == len(doc["findings"]) > 0
    f = doc["findings"][0]
    assert set(f) == {"path", "line", "col", "code", "message"}
    assert {x["code"] for x in doc["findings"]} == {"TPM301", "TPM302"}


def test_cli_list_rules_covers_every_family(capsys):
    rc = cli.main(["--list-rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for code in ("TPM101", "TPM102", "TPM201", "TPM301", "TPM302",
                 "TPM401", "TPM501", "TPM502", "TPM601", "TPM701",
                 "TPM801", "TPM802", "TPM900", "TPM1001", "TPM1101",
                 "TPM1201"):
        assert code in out
    # table rows match the registry (README is hand-synced to this)
    assert len(rule_table()) >= 16


def test_cli_sarif_golden(capsys):
    """Pin the SARIF 2.1.0 subset we emit — the fields CI hosts need to
    render findings inline: schema/version, driver name + full rule
    table, and per-result ruleId/level/message/physical location with
    1-based columns."""
    rc = cli.main(["--format", "sarif", str(FIXTURES / "tpm1_bad.py")])
    out = capsys.readouterr()
    assert rc == 1
    doc = json.loads(out.out)
    assert doc["$schema"].endswith("sarif-2.1.0.json")
    assert doc["version"] == "2.1.0"
    assert len(doc["runs"]) == 1
    driver = doc["runs"][0]["tool"]["driver"]
    assert driver["name"] == "tpumt-lint"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert rule_ids == [code for code, _ in rule_table()]
    assert all(r["shortDescription"]["text"] for r in driver["rules"])
    results = doc["runs"][0]["results"]
    assert len(results) == 1
    res = results[0]
    assert res["ruleId"] == "TPM101"
    assert res["level"] == "error"
    assert "block" in res["message"]["text"]
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("tpm1_bad.py")
    # SARIF columns are 1-based; the engine's are 0-based
    assert loc["region"] == {"startLine": 10, "startColumn": 11}


def test_cli_sarif_clean_run_is_valid_empty(capsys):
    rc = cli.main(["--format", "sarif", str(FIXTURES / "tpm1_good.py")])
    out = capsys.readouterr()
    assert rc == 0
    doc = json.loads(out.out)
    assert doc["runs"][0]["results"] == []


def test_cache_cold_warm_touch_cycle(tmp_path):
    """The incrementality contract (acceptance criterion): a cold run
    analyzes every file; a warm run over the unchanged tree re-parses
    ZERO files and reproduces the identical findings — file-scope ones
    replayed, project-scope ones recomputed from cached facts (the
    cross-file TPM502 here proves the project pass sees deserialized
    summaries); touching one file re-analyzes exactly that file."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "kernel.py").write_text(
        "from jax import lax\n"
        "def local_sum(v):\n"
        "    return lax.psum(v, 'ghost')\n"
    )
    clean = proj / "meshes.py"
    clean.write_text(
        "from jax.sharding import Mesh\n"
        "def make(devs):\n"
        "    return Mesh(devs, ('x',))\n"
    )
    cache = tmp_path / "cache.json"

    s1: dict = {}
    f1 = lint_paths([str(proj)], cache_path=str(cache), stats=s1)
    assert s1 == {"files": 2, "analyzed": 2, "cache_hits": 0}
    assert "TPM502" in codes_of(f1), f1
    assert cache.exists() and json.loads(cache.read_text())["entries"]

    s2: dict = {}
    f2 = lint_paths([str(proj)], cache_path=str(cache), stats=s2)
    assert s2 == {"files": 2, "analyzed": 0, "cache_hits": 2}
    assert f2 == f1  # byte-identical findings, zero re-parsing

    clean.write_text(clean.read_text() + "\n# touched\n")
    s3: dict = {}
    f3 = lint_paths([str(proj)], cache_path=str(cache), stats=s3)
    assert s3 == {"files": 2, "analyzed": 1, "cache_hits": 1}
    assert f3 == f1


def test_cache_replays_suppressions_and_file_findings(tmp_path):
    """Warm runs must replay suppression state too: a used suppression
    stays silent (no finding, no TPM900) and an unused one keeps
    warning, identically to the cold run."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "sup.py").write_text(
        (FIXTURES / "tpm1_suppressed.py").read_text()
    )
    (proj / "unused.py").write_text(
        (FIXTURES / "tpm9_unused.py").read_text()
    )
    cache = tmp_path / "cache.json"
    f1 = lint_paths([str(proj)], cache_path=str(cache))
    s2: dict = {}
    f2 = lint_paths([str(proj)], cache_path=str(cache), stats=s2)
    assert s2["analyzed"] == 0 and s2["cache_hits"] == 2
    assert f2 == f1
    assert codes_of(f2) == ["TPM900"]


def test_cache_misses_when_package_anchoring_changes(tmp_path):
    """Content hashes alone can't see an added/removed ``__init__.py``:
    it re-anchors every module name in the tree without touching the
    files' bytes, and replaying facts under stale names would make warm
    project findings diverge from a cold run. The replay validates the
    module name and degrades to re-analysis instead."""
    pkg = tmp_path / "dnt"
    pkg.mkdir()
    init = pkg / "__init__.py"
    init.write_text("")
    (pkg / "helper.py").write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def reduce_into(buf, mesh):\n"
        "    return allreduce_sum(buf, mesh)\n"
    )
    (pkg / "driver.py").write_text(
        "from dnt.helper import reduce_into\n"
        "def step(x, mesh):\n"
        "    total = reduce_into(x, mesh)\n"
        "    return x + total\n"
    )
    cache = tmp_path / "cache.json"
    f1 = lint_paths([str(tmp_path)], cache_path=str(cache))
    assert "TPM1201" in codes_of(f1), f1

    init.unlink()  # helper.py / driver.py bytes are unchanged
    cold = lint_paths([str(tmp_path)])
    s: dict = {}
    warm = lint_paths([str(tmp_path)], cache_path=str(cache), stats=s)
    assert warm == cold, (warm, cold)
    assert s["analyzed"] == 2 and s["cache_hits"] == 0, s


def test_cache_type_corrupted_entry_degrades_to_miss(tmp_path):
    """An entry with the right hash but a wrong-typed field (a
    hand-edit, a partial write) must re-analyze that file — never crash
    the run or replay partial facts."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "bad.py").write_text((FIXTURES / "tpm1_bad.py").read_text())
    cache = tmp_path / "cache.json"
    f1 = lint_paths([str(proj)], cache_path=str(cache))
    doc = json.loads(cache.read_text())
    (entry,) = doc["entries"].values()
    entry["findings"] = 0  # right hash, wrong shape
    cache.write_text(json.dumps(doc))
    s: dict = {}
    f2 = lint_paths([str(proj)], cache_path=str(cache), stats=s)
    assert f2 == f1
    assert s["analyzed"] == 1 and s["cache_hits"] == 0, s


def test_cache_corruption_degrades_to_cold_run(tmp_path):
    """A truncated/garbage cache file must never fail the lint or
    change its verdict — it reads as empty and the run goes cold."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "bad.py").write_text((FIXTURES / "tpm1_bad.py").read_text())
    cache = tmp_path / "cache.json"
    f1 = lint_paths([str(proj)], cache_path=str(cache))
    cache.write_text('{"version": 1, "salt": "stale", "entr')
    s: dict = {}
    f2 = lint_paths([str(proj)], cache_path=str(cache), stats=s)
    assert s["analyzed"] == 1 and s["cache_hits"] == 0
    assert f2 == f1


def test_cli_stats_and_no_cache(tmp_path, capsys):
    """--stats reports the cache-hit counters on stderr; --no-cache
    forces analyzed == files on every run and writes nothing."""
    cache = tmp_path / "cli_cache.json"
    target = str(FIXTURES / "tpm1_good.py")
    cli.main(["--cache", str(cache), "--stats", target])
    err = capsys.readouterr().err
    assert "files=1 analyzed=1 cache_hits=0" in err
    cli.main(["--cache", str(cache), "--stats", target])
    err = capsys.readouterr().err
    assert "files=1 analyzed=0 cache_hits=1" in err
    cli.main(["--no-cache", "--stats", target])
    err = capsys.readouterr().err
    assert "files=1 analyzed=1 cache_hits=0" in err
    assert "cache=off" in err


def test_self_clean_gate():
    """The acceptance gate: the repo's own code lints clean — the same
    invocation ``make lint`` runs. A finding here means either new code
    regressed a gated hazard class or a rule grew a false positive;
    both block CI by design."""
    findings = lint_paths([
        str(REPO / "tpu_mpi_tests"),
        str(REPO / "tpu"),
        str(REPO / "tests"),
        str(REPO / "__graft_entry__.py"),
        str(REPO / "bench.py"),
    ])
    assert findings == [], "\n".join(f.format() for f in findings)
