"""tpumt-lint golden tests: every rule family fails on its bad fixture,
passes on its good fixture, and respects ``# tpumt: ignore[...]``;
engine behaviors (suppressions, select/ignore, output formats, exit
codes, self-clean gate) on top.

The fixtures live in ``tpu_mpi_tests/analysis/fixtures/`` — excluded
from recursive walks (deliberately-bad code must not fail the
self-clean gate) but always linted when passed explicitly, which is
what these tests do.
"""

import json
from pathlib import Path

import pytest

from tpu_mpi_tests.analysis import cli
from tpu_mpi_tests.analysis.core import (
    collect_suppressions,
    lint_paths,
    rule_table,
)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tpu_mpi_tests" / "analysis" / "fixtures"


@pytest.fixture(autouse=True)
def _hermetic_cache(tmp_path, monkeypatch):
    """CLI invocations default the analysis cache ON — point it at a
    per-test temp file so tests never touch (or depend on) the user's
    ~/.cache/tpumt/lint.json."""
    monkeypatch.setenv(
        "TPU_MPI_LINT_CACHE", str(tmp_path / "_lintcache.json")
    )


#: (family prefix, fixture stem) for the single-file families
FILE_FAMILIES = [
    ("TPM1", "tpm1"),
    ("TPM2", "tpm2"),
    ("TPM3", "tpm3"),
    ("TPM5", "tpm5"),
    ("TPM7", "tpm7"),
    ("TPM8", "tpm8"),
    ("TPM10", "tpm10"),
    # ISSUE-12 flow-sensitive families (single-file goldens; the
    # interprocedural shapes are pinned by the seeded mutants below)
    ("TPM1102", "tpm1102"),
    ("TPM1301", "tpm1301"),
    ("TPM140", "tpm14"),
    # ISSUE 13 demoted TPM601: the tpm6 single-file fixtures are now
    # TPM1601 goldens (their Timer target resolves, so the lockset
    # engine owns them); the TPM601 fallback keeps its own test below
    ("TPM16", "tpm6"),
]

#: (family prefix, fixture stem) for the whole-program families — mini
#: package trees, because the findings are interprocedural by
#: construction (helper in one file, hazard in another)
TREE_FAMILIES = [
    ("TPM11", "tpm11"),
    ("TPM12", "tpm12"),
    ("TPM16", "tpm16"),
    # ISSUE 18: the protocol verifier's composed-schedule shapes —
    # TPM1701 (rank-guarded broadcast handshake), TPM1702
    # (rank-dependent trip count), TPM1703 (swallowing handler), one
    # file each, all through the cross-file wrappers in proto/comms.py
    ("TPM17", "tpm17"),
]


def codes_of(findings):
    return [f.code for f in findings]


def counts_of(stats):
    """The cache-relevant stats triple (``seconds``/``jobs`` vary)."""
    return {k: stats[k] for k in ("files", "analyzed", "cache_hits")}


@pytest.mark.parametrize("family,stem", FILE_FAMILIES)
def test_family_bad_good_suppressed(family, stem):
    bad = lint_paths([str(FIXTURES / f"{stem}_bad.py")])
    assert any(c.startswith(family) for c in codes_of(bad)), (
        f"{stem}_bad.py must raise a {family}xx finding, got {bad}"
    )

    good = lint_paths([str(FIXTURES / f"{stem}_good.py")])
    assert not any(c.startswith(family) for c in codes_of(good)), (
        f"{stem}_good.py must be clean of {family}xx, got {good}"
    )

    sup = lint_paths([str(FIXTURES / f"{stem}_suppressed.py")])
    assert not any(c.startswith(family) for c in codes_of(sup)), (
        f"suppression comment must silence {family}xx, got {sup}"
    )
    # a suppression that fired is used: no TPM900 on the same file
    assert "TPM900" not in codes_of(sup), sup


@pytest.mark.parametrize("family,stem", TREE_FAMILIES)
def test_project_family_bad_good_suppressed_trees(family, stem):
    """The whole-program families' goldens: each tree splits helper and
    hazard across files, so a per-file scan of any single file would
    see nothing — the finding only exists through the summaries."""
    bad = lint_paths([str(FIXTURES / f"{stem}_bad")])
    assert any(c.startswith(family) for c in codes_of(bad)), (
        f"{stem}_bad must raise a {family}xx finding, got {bad}"
    )

    good = lint_paths([str(FIXTURES / f"{stem}_good")])
    assert not any(c.startswith(family) for c in codes_of(good)), (
        f"{stem}_good must be clean of {family}xx, got {good}"
    )

    sup = lint_paths([str(FIXTURES / f"{stem}_suppressed")])
    assert not any(c.startswith(family) for c in codes_of(sup)), (
        f"suppression comment must silence {family}xx, got {sup}"
    )
    assert "TPM900" not in codes_of(sup), sup


def test_collective_divergence_seeded_mutant(tmp_path):
    """Mutation gate (acceptance criterion): a seeded rank-divergent
    collective — rank test in one function, collective through a helper
    in ANOTHER FILE — is flagged; hoisting the collective out of the
    branch clears it."""
    pkg = tmp_path / "spmd"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "comms.py").write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def global_sum(x, mesh):\n"
        "    return allreduce_sum(x, mesh)\n"
    )
    step = pkg / "step.py"
    step.write_text(
        "from spmd.comms import global_sum\n"
        "def run(x, mesh, rank):\n"
        "    if rank == 0:\n"
        "        x = global_sum(x, mesh)\n"
        "    return x\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert "TPM1101" in codes_of(findings), findings
    f = next(f for f in findings if f.code == "TPM1101")
    assert f.line == 3 and "allreduce_sum" in f.message, f
    # the fix: every rank enters the collective
    step.write_text(
        "from spmd.comms import global_sum\n"
        "def run(x, mesh, rank):\n"
        "    x = global_sum(x, mesh)\n"
        "    if rank == 0:\n"
        "        print('done')\n"
        "    return x\n"
    )
    assert "TPM1101" not in codes_of(lint_paths([str(tmp_path)]))


def test_collective_divergence_both_branches_equal_is_clean(tmp_path):
    """A rank branch whose BOTH paths dispatch the same collective
    sequence does not diverge (e.g. selecting an operand, then the same
    reduce on each side)."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def run(x, y, mesh, rank):\n"
        "    if rank == 0:\n"
        "        out = allreduce_sum(x, mesh)\n"
        "    else:\n"
        "        out = allreduce_sum(y, mesh)\n"
        "    return out\n"
    )
    assert "TPM1101" not in codes_of(lint_paths([str(p)]))


def test_tpm1101_false_negative_regressions():
    """The ROADMAP carry-over goldens: under the PR-10 LEXICAL engine
    both shapes in tpm11_truthy_bad.py linted CLEAN — `_rank_dependent`
    only matched Compare nodes against rank-NAMED variables, so the
    truthiness test (`if not rank:`, no Compare at all) and the
    process_index() local alias (`r = process_index(); if r == 0:`)
    were invisible, and branch event sequences did not model control
    flow. The CFG engine must convict both."""
    findings = lint_paths([str(FIXTURES / "tpm11_truthy_bad.py")])
    assert codes_of(findings) == ["TPM1101", "TPM1101"], findings
    lines = sorted(f.line for f in findings)
    assert lines == [24, 31], findings  # the two `if` guards


def test_early_return_guard_convicts_tpm1102():
    """The second carry-over shape: `if rank != 0: return` BEFORE a
    collective. The lexical engine compared the two branch bodies —
    both collective-free — and missed it (documented false negative);
    the CFG engine models the return as an exit edge and convicts it
    as TPM1102, the early-exit half of the divergence family. TPM1101
    must stay silent on the same `if` (exactly one code per divergent
    branch)."""
    findings = lint_paths([str(FIXTURES / "tpm1102_bad.py")])
    assert codes_of(findings) == ["TPM1102"], findings
    f = findings[0]
    assert f.line == 13 and "allreduce_sum" in f.message, f


def test_early_exit_divergence_seeded_mutant(tmp_path):
    """Mutation gate (acceptance criterion): an early-return rank guard
    before an allreduce THROUGH A HELPER IN ANOTHER FILE is the
    mutant's SOLE finding; hoisting the collective above the guarded
    exit clears it."""
    pkg = tmp_path / "spmd"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "comms.py").write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def global_sum(x, mesh):\n"
        "    return allreduce_sum(x, mesh)\n"
    )
    step = pkg / "step.py"
    step.write_text(
        "from spmd.comms import global_sum\n"
        "def run(x, mesh, rank):\n"
        "    if rank != 0:\n"
        "        return x\n"
        "    x = global_sum(x, mesh)\n"
        "    return x\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert codes_of(findings) == ["TPM1102"], findings
    f = findings[0]
    assert f.line == 3 and "allreduce_sum" in f.message, f
    # the fix: every rank enters the collective before the exit
    step.write_text(
        "from spmd.comms import global_sum\n"
        "def run(x, mesh, rank):\n"
        "    x = global_sum(x, mesh)\n"
        "    if rank != 0:\n"
        "        return x\n"
        "    return x\n"
    )
    assert lint_paths([str(tmp_path)]) == []


def test_early_exit_continue_in_loop_diverges(tmp_path):
    """A rank-guarded `continue` before a per-iteration collective is
    the same deadlock one loop level down: rank 0 runs N allreduces,
    everyone else runs zero. The CFG cuts the back edge, so the
    continue path visibly skips the collective."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def pump(xs, mesh, rank):\n"
        "    out = []\n"
        "    for x in xs:\n"
        "        if rank != 0:\n"
        "            continue\n"
        "        out.append(allreduce_sum(x, mesh))\n"
        "    return out\n"
    )
    findings = lint_paths([str(p)])
    assert "TPM1102" in codes_of(findings), findings


def test_early_exit_inside_loop_sees_post_loop_collective(tmp_path):
    """Loop-exit reachability regression (code-review finding): the
    loop's fall-through must have a forward path to post-loop code, or
    (a) a rank-guarded return INSIDE a loop before a post-loop
    collective — the PR's headline deadlock class one level down — is
    silently missed, and (b) a rank-guarded `break` before a post-loop
    collective EVERY rank reaches is falsely convicted."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def ret_in_loop(xs, x, mesh, rank):\n"
        "    for _ in xs:\n"
        "        if rank != 0:\n"
        "            return x\n"
        "    return allreduce_sum(x, mesh)\n"
    )
    assert "TPM1102" in codes_of(lint_paths([str(p)]))
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def break_then_all_reduce(xs, x, mesh, rank):\n"
        "    for _ in xs:\n"
        "        if rank != 0:\n"
        "            break\n"
        "    return allreduce_sum(x, mesh)\n"  # ALL ranks reach this
    )
    findings = lint_paths([str(p)])
    assert not any(c.startswith("TPM11") for c in codes_of(findings)), \
        findings


def test_ambiguous_proc_truthiness_is_not_a_rank_test(tmp_path):
    """Code-review regression: `proc` is usually a subprocess handle —
    `if not self.proc: return` before a collective is a liveness check,
    not a rank guard, and must not convict; a COMPARISON against proc
    (`proc == 0`) keeps its lexical-era rank meaning."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def step(self, x, mesh):\n"
        "    if not self.proc:\n"
        "        return x\n"
        "    return allreduce_sum(x, mesh)\n"
    )
    assert not any(c.startswith("TPM11")
                   for c in codes_of(lint_paths([str(p)])))
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def step(x, mesh, proc):\n"
        "    if proc != 0:\n"
        "        return x\n"
        "    return allreduce_sum(x, mesh)\n"
    )
    assert "TPM1102" in codes_of(lint_paths([str(p)]))


def test_broadcast_consistency_params_and_imports_are_bound(tmp_path):
    """Code-review regression: kwonly/vararg/kwarg parameters and
    imported names are bound on EVERY rank — refreshing one under a
    rank guard is not a one-sided binding and must not convict."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from jax import process_index\n"
        "import mylib\n"
        "def f(x, *rest, cfg=None, **kw):\n"
        "    if process_index() == 0:\n"
        "        cfg = refine(cfg)\n"
        "        rest = tuple(kw)\n"
        "        kw = dict(cfg=cfg)\n"
        "        mylib = patch()\n"
        "    return use(x, cfg, rest, kw, mylib)\n"
    )
    assert "TPM1301" not in codes_of(lint_paths([str(p)]))


def test_broadcast_consistency_none_then_rebind_is_clean(tmp_path):
    """_real_bound regression (code-review finding): the placeholder
    filter is per store SITE, not per name — an else arm that
    None-initializes and then really binds (`winner = None;
    winner = local_fallback()`) holds a value on every rank and must
    not convict. The annotated placeholder (`winner: object = None`)
    is the same absence-of-a-value and must still convict."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from jax import process_index\n"
        "def pick(sweep, fallback, apply_fn, space, x):\n"
        "    if process_index() == 0:\n"
        "        winner = sweep(space)\n"
        "    else:\n"
        "        winner = None\n"
        "        winner = fallback(space)\n"
        "    return apply_fn(x, winner)\n"
    )
    assert "TPM1301" not in codes_of(lint_paths([str(p)]))
    p.write_text(
        "from jax import process_index\n"
        "def pick(sweep, apply_fn, space, x):\n"
        "    if process_index() == 0:\n"
        "        winner = sweep(space)\n"
        "    else:\n"
        "        winner: object = None\n"
        "    return apply_fn(x, winner)\n"
    )
    assert "TPM1301" in codes_of(lint_paths([str(p)]))


def test_broadcast_consistency_prebranch_none_placeholder(tmp_path):
    """Code-review regression: the hazard's most common spelling —
    `winner = None` BEFORE the rank guard — is the same
    absence-of-a-value as the else-arm placeholder and must convict;
    an AugAssign on the unguarded path is a READ of the one-sided
    value (not a kill) and convicts at its own line."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from jax import process_index\n"
        "def pick(sweep, apply_fn, space, x):\n"
        "    winner = None\n"
        "    if process_index() == 0:\n"
        "        winner = sweep(space)\n"
        "    return apply_fn(x, winner)\n"
    )
    assert "TPM1301" in codes_of(lint_paths([str(p)]))
    p.write_text(
        "from jax import process_index\n"
        "def pick(sweep, apply_fn, space, x):\n"
        "    if process_index() == 0:\n"
        "        w = sweep(space)\n"
        "    else:\n"
        "        w = None\n"
        "    w += 1\n"
        "    return apply_fn(x, w)\n"
    )
    findings = lint_paths([str(p)])
    assert "TPM1301" in codes_of(findings), findings
    f = next(x for x in findings if x.code == "TPM1301")
    assert f.line == 7, f  # the `w += 1` read of the divergent value


def test_broadcast_consistency_postjoin_rebind_kills_value(tmp_path):
    """Code-review regression: an unconditional rebind on the shared
    path (`plan = load_cached(...)` on every rank) replaces the
    one-sided value — a read AFTER the rebind is safe and must not
    convict; a read BEFORE it still does."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from jax import process_index\n"
        "def pick(sweep, load_cached, apply_fn, space, x):\n"
        "    if process_index() == 0:\n"
        "        plan = sweep(space)\n"
        "    plan = load_cached(space)\n"
        "    return apply_fn(x, plan)\n"
    )
    assert "TPM1301" not in codes_of(lint_paths([str(p)]))
    p.write_text(
        "from jax import process_index\n"
        "def pick(sweep, load_cached, apply_fn, persist, space, x):\n"
        "    if process_index() == 0:\n"
        "        plan = sweep(space)\n"
        "    persist(plan)\n"
        "    plan = load_cached(space)\n"
        "    return apply_fn(x, plan)\n"
    )
    findings = lint_paths([str(p)])
    assert "TPM1301" in codes_of(findings), findings
    f = next(x for x in findings if x.code == "TPM1301")
    assert f.line == 5, f  # the pre-rebind read, not the safe one


def test_broadcast_consistency_seeded_mutant(tmp_path):
    """Mutation gate (acceptance criterion): an unbroadcast rank-0
    tune-winner — bound under the rank guard, None on the other arm,
    then dispatched into per-rank work — is the mutant's SOLE finding;
    routing it through broadcast_one_to_all clears it."""
    pkg = tmp_path / "fleet"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "sweep.py").write_text(
        "def sweep_halo(space):\n"
        "    return min(space)\n"
    )
    main = pkg / "main.py"
    main.write_text(
        "from jax import process_index\n"
        "from fleet.sweep import sweep_halo\n"
        "def tune_and_apply(space, apply_fn, x):\n"
        "    if process_index() == 0:\n"
        "        winner = sweep_halo(space)\n"
        "    else:\n"
        "        winner = None\n"
        "    return apply_fn(x, winner)\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert codes_of(findings) == ["TPM1301"], findings
    f = findings[0]
    assert f.line == 8 and "'winner'" in f.message, f
    # the fix: replicate before any rank acts on the value
    main.write_text(
        "from jax import process_index\n"
        "from jax.experimental.multihost_utils import "
        "broadcast_one_to_all\n"
        "from fleet.sweep import sweep_halo\n"
        "def tune_and_apply(space, apply_fn, x):\n"
        "    if process_index() == 0:\n"
        "        winner = sweep_halo(space)\n"
        "    else:\n"
        "        winner = None\n"
        "    winner = broadcast_one_to_all(winner)\n"
        "    return apply_fn(x, winner)\n"
    )
    assert lint_paths([str(tmp_path)]) == []


def test_symmetric_loop_collective_in_rank_branch_is_clean(tmp_path):
    """Block-ordering regression (code-review finding): a rank branch
    whose guarded arm runs the collective IN A LOOP and whose other arm
    runs the same collective straight-line must compare equal — the
    loop's after-block must number after its body, or the post-loop
    barrier would sort before the in-loop allreduce and fabricate a
    divergence."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum, "
        "barrier\n"
        "def step(x, mesh, rank, k):\n"
        "    if rank == 0:\n"
        "        for _ in range(k):\n"
        "            x = allreduce_sum(x, mesh)\n"
        "        x = barrier(x, mesh)\n"
        "    else:\n"
        "        x = allreduce_sum(x, mesh)\n"
        "        x = barrier(x, mesh)\n"
        "    return x\n"
    )
    findings = lint_paths([str(p)])
    assert not any(c.startswith("TPM11") for c in codes_of(findings)), \
        findings


def test_broadcast_consistency_rank_gated_read_is_clean(tmp_path):
    """Code-review regression: a value bound under a rank guard and
    read ONLY under another rank guard (the rank-0-only logger shape)
    never crosses to the unguarded ranks — TPM1301 must not convict
    it. An unguarded read of the same name elsewhere still does."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from jax import process_index\n"
        "def report_loop(make_log, recs):\n"
        "    if process_index() == 0:\n"
        "        log = make_log()\n"
        "    for rec in recs:\n"
        "        if process_index() == 0:\n"
        "            log.write(rec)\n"
        "    return len(recs)\n"
    )
    assert "TPM1301" not in codes_of(lint_paths([str(p)]))
    p.write_text(
        "from jax import process_index\n"
        "def report_loop(make_log, recs, flush):\n"
        "    if process_index() == 0:\n"
        "        log = make_log()\n"
        "    flush(log)\n"
        "    return len(recs)\n"
    )
    assert "TPM1301" in codes_of(lint_paths([str(p)]))


def test_record_producer_scopes_do_not_bleed(tmp_path):
    """Code-review regression: two functions both naming their local
    record dict `rec` must keep separate schemas — a build-up store in
    one function must not credit the OTHER function's kind with the
    field (which would mask a real TPM1401)."""
    (tmp_path / "w.py").write_text(
        "def a(sink):\n"
        '    rec = {"kind": "alpha", "x": 1}\n'
        "    sink(rec)\n"
        "def b(sink):\n"
        '    rec = {"kind": "beta", "y": 2}\n'
        '    rec["z"] = 3\n'
        "    sink(rec)\n"
    )
    (tmp_path / "r.py").write_text(
        "def read(records):\n"
        "    out = []\n"
        "    for rec in records:\n"
        '        if rec.get("kind") == "alpha":\n'
        '            out.append(rec.get("z"))\n'
        "    return out\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert codes_of(findings) == ["TPM1401"], findings
    assert "'z'" in findings[0].message


def test_broadcast_consistency_prebound_name_is_clean(tmp_path):
    """A name bound BEFORE the rank branch and merely refreshed under
    the guard is out of TPM1301's scope (every rank holds a value), and
    a value consumed only inside its own guarded branch never crosses
    paths — both stay clean."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from jax import process_index\n"
        "def report(stats, render):\n"
        "    lines = []\n"
        "    if process_index() == 0:\n"
        "        lines = render(stats)\n"
        "    return lines\n"
        "def local_only(stats, render, emit):\n"
        "    if process_index() == 0:\n"
        "        text = render(stats)\n"
        "        emit(text)\n"
        "    return stats\n"
    )
    assert "TPM1301" not in codes_of(lint_paths([str(p)]))


def test_record_contract_seeded_mutant(tmp_path):
    """Mutation gate (acceptance criterion): a consumer reading a field
    no producer emits — the producer lives in ANOTHER file — is the
    mutant's SOLE finding; reading the produced field clears it."""
    (tmp_path / "writer.py").write_text(
        "def write(sink, us):\n"
        '    sink({"kind": "lat", "event": "window",\n'
        '          "p50_us": us, "n": 1})\n'
    )
    reader = tmp_path / "reader.py"
    reader.write_text(
        "def latencies(records):\n"
        "    vals = []\n"
        "    for rec in records:\n"
        '        if rec.get("kind") == "lat":\n'
        '            vals.append(rec.get("p99_us"))\n'
        "    return vals\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert codes_of(findings) == ["TPM1401"], findings
    f = findings[0]
    assert f.line == 5 and "'p99_us'" in f.message, f
    reader.write_text(
        "def latencies(records):\n"
        "    vals = []\n"
        "    for rec in records:\n"
        '        if rec.get("kind") == "lat":\n'
        '            vals.append(rec.get("p50_us"))\n'
        "    return vals\n"
    )
    assert lint_paths([str(tmp_path)]) == []


def test_record_contract_unknown_kind_tpm1402(tmp_path):
    """A consumer filtering on a kind nothing produces is TPM1402,
    anchored at the kind test — and the field check stands down for
    that variable (the unknown schema would make every read a false
    TPM1401)."""
    (tmp_path / "writer.py").write_text(
        "def write(sink):\n"
        '    sink({"kind": "lat", "p50_us": 1})\n'
    )
    (tmp_path / "reader.py").write_text(
        "def count(records):\n"
        "    n = 0\n"
        "    for rec in records:\n"
        '        if rec.get("kind") == "latency":\n'
        '            n += rec.get("whatever", 0)\n'
        "    return n\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert codes_of(findings) == ["TPM1402"], findings
    assert "'latency'" in findings[0].message


def test_record_contract_flow_sensitive_attribution(tmp_path):
    """The flow-sensitivity contract: (a) each arm of a kind-dispatch
    chain is judged against ITS kind's schema only — a field valid for
    'a' read under the 'b' arm convicts; (b) reads exclusively on the
    complement side of a positive kind test (`else:` of == 'a') are
    unjudgeable and never flagged; (c) an open producer (**spread)
    silences the field check for its kind."""
    (tmp_path / "writer.py").write_text(
        "def write(sink, extra):\n"
        '    sink({"kind": "a", "x": 1})\n'
        '    sink({"kind": "b", "y": 2})\n'
        '    sink({"kind": "c", **extra})\n'
    )
    reader = tmp_path / "reader.py"
    reader.write_text(
        "def split(records):\n"
        "    xs, ys = [], []\n"
        "    for rec in records:\n"
        '        kind = rec.get("kind")\n'
        '        if kind == "a":\n'
        '            xs.append(rec.get("x"))\n'
        '        elif kind == "b":\n'
        '            ys.append(rec.get("x"))\n'
        "    return xs, ys\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert codes_of(findings) == ["TPM1401"], findings
    f = findings[0]
    assert f.line == 8 and "kind b" in f.message, f
    reader.write_text(
        "def split(records):\n"
        "    out = []\n"
        "    for rec in records:\n"
        '        if rec.get("kind") == "a":\n'
        '            out.append(rec.get("x"))\n'
        "        else:\n"
        '            out.append(rec.get("anything"))\n'
        '            if rec.get("kind") == "c":\n'
        '                out.append(rec.get("dynamic_field"))\n'
        "    return out\n"
    )
    assert lint_paths([str(tmp_path)]) == []


def test_donation_safety_seeded_mutant_through_helper(tmp_path):
    """Mutation gate (acceptance criterion): a use-after-donate where
    the donation happens ONE HELPER LEVEL down (the helper forwards its
    param into allreduce_sum's donated position 0) is flagged; the
    rebind idiom clears it."""
    pkg = tmp_path / "dnt"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "helper.py").write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def reduce_into(buf, mesh):\n"
        "    return allreduce_sum(buf, mesh)\n"
    )
    drv = pkg / "driver.py"
    drv.write_text(
        "from dnt.helper import reduce_into\n"
        "def step(x, mesh):\n"
        "    total = reduce_into(x, mesh)\n"
        "    return x + total\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert "TPM1201" in codes_of(findings), findings
    f = next(f for f in findings if f.code == "TPM1201")
    assert f.line == 4  # anchored at the read of the deleted buffer
    assert "reduce_into" in f.message
    drv.write_text(
        "from dnt.helper import reduce_into\n"
        "def step(x, mesh):\n"
        "    x = reduce_into(x, mesh)\n"
        "    return x * 2.0\n"
    )
    assert "TPM1201" not in codes_of(lint_paths([str(tmp_path)]))


def test_donation_safety_loop_and_return_shapes(tmp_path):
    """TPM1201 beyond the goldens: donating inside a loop that never
    rebinds feeds a deleted buffer to iteration 2 (flagged at the
    call); a donation under `return` exits the statement list, so the
    mutually-exclusive-branch dispatch fork is clean; and same-named
    locals in SIBLING functions are unrelated (no cross-scope leak)."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def looped(x, mesh, n):\n"
        "    for _ in range(n):\n"
        "        allreduce_sum(x, mesh)\n"
        "    return x\n"
    )
    findings = lint_paths([str(p)])
    assert "TPM1201" in codes_of(findings), findings
    assert "inside a loop" in findings[0].message
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def fork(x, mesh, host):\n"
        "    if host:\n"
        "        return allreduce_sum(x, mesh)\n"
        "    return x.sum()\n"
    )
    assert "TPM1201" not in codes_of(lint_paths([str(p)]))
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def donates(x, mesh):\n"
        "    x = allreduce_sum(x, mesh)\n"
        "    return x\n"
        "def unrelated(x):\n"
        "    return x + 1\n"  # different scope's x, not a stale read
    )
    assert "TPM1201" not in codes_of(lint_paths([str(p)]))


def test_axis_program_consistency_seeded_mutant(tmp_path):
    """Mutation gate (acceptance criterion): a cross-file unbound axis
    — psum over an axis no file in the program binds — is flagged
    (TPM502), in a file TPM501 used to SKIP for having no local mesh
    context; binding the axis in the OTHER file clears it (the
    same-file skip is lifted, not just re-scoped)."""
    (tmp_path / "kernel.py").write_text(
        "from jax import lax\n"
        "def local_sum(v):\n"
        "    return lax.psum(v, 'ghost')\n"
    )
    mesh = tmp_path / "meshes.py"
    mesh.write_text(
        "from jax.sharding import Mesh\n"
        "def make(devs):\n"
        "    return Mesh(devs, ('x',))\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert "TPM502" in codes_of(findings), findings
    f = next(f for f in findings if f.code == "TPM502")
    assert f.line == 3 and "'ghost'" in f.message, f
    # alone, the kernel file still skips per-file (no local context) —
    # the program rule is what closed that hole
    alone = lint_paths([str(tmp_path / "kernel.py")])
    assert "TPM501" not in codes_of(alone)
    assert "TPM502" in codes_of(alone)
    # bind the axis ANYWHERE in the program: clean
    mesh.write_text(
        "from jax.sharding import Mesh\n"
        "def make(devs):\n"
        "    return Mesh(devs, ('x', 'ghost'))\n"
    )
    findings = lint_paths([str(tmp_path)])
    assert "TPM502" not in codes_of(findings), findings


def test_escaped_async_handle_seeded_mutant(tmp_path):
    """Mutation gate (acceptance criterion): an async_span handle
    returned by a helper and assigned to a name the caller never reads
    is flagged (TPM802) — nobody will done() it; consuming the handle
    clears it."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from tpu_mpi_tests.instrument.telemetry import async_span\n"
        "def start(op):\n"
        "    h = async_span(op)\n"
        "    return h\n"
        "def run(fn, z):\n"
        "    hh = start('exchange')\n"
        "    return fn(z)\n"
    )
    findings = lint_paths([str(p)])
    assert "TPM802" in codes_of(findings), findings
    f = next(f for f in findings if f.code == "TPM802")
    assert f.line == 6 and "'hh'" in f.message, f
    p.write_text(
        "from tpu_mpi_tests.instrument.telemetry import async_span\n"
        "def start(op):\n"
        "    h = async_span(op)\n"
        "    return h\n"
        "def run(fn, z):\n"
        "    hh = start('exchange')\n"
        "    out = fn(z)\n"
        "    hh.done(out)\n"
        "    return out\n"
    )
    assert "TPM802" not in codes_of(lint_paths([str(p)]))


def test_sync_honesty_interprocedural(tmp_path):
    """TPM102: a timed region that dispatches jax work only THROUGH a
    helper is dishonest timing one frame deeper — flagged via the
    summaries; a helper that syncs internally is honest and clean."""
    p = tmp_path / "mod.py"
    p.write_text(
        "import time\n"
        "import jax.numpy as jnp\n"
        "def helper(x):\n"
        "    return jnp.sin(x)\n"
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = helper(x)\n"
        "    return y, time.perf_counter() - t0\n"
    )
    findings = lint_paths([str(p)])
    assert "TPM102" in codes_of(findings), findings
    # TPM101 stays silent — there is no DIRECT dispatch in the region
    assert "TPM101" not in codes_of(findings)
    f = next(f for f in findings if f.code == "TPM102")
    assert f.line == 7 and "helper" in f.message, f
    p.write_text(
        "import time\n"
        "import jax.numpy as jnp\n"
        "from tpu_mpi_tests.instrument.timers import block\n"
        "def helper(x):\n"
        "    return block(jnp.sin(x))\n"
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = helper(x)\n"
        "    return y, time.perf_counter() - t0\n"
    )
    assert "TPM102" not in codes_of(lint_paths([str(p)]))


@pytest.mark.parametrize("variant,expect", [
    ("tpm4_bad", True),
    ("tpm4_good", False),
    ("tpm4_suppressed", False),
])
def test_import_hygiene_mini_trees(variant, expect):
    findings = lint_paths(
        [str(FIXTURES / variant)],
        entry_modules={"app.cli": "app.cli"},
    )
    has = any(c == "TPM401" for c in codes_of(findings))
    assert has == expect, findings
    if variant == "tpm4_suppressed":
        assert "TPM900" not in codes_of(findings), findings


def test_import_hygiene_duplicate_module_names_all_scanned():
    """Linting the bad and good mini-trees TOGETHER must still report
    the bad tree's TPM401: both define module 'app.cli', and collapsing
    duplicates would silently drop one tree from the reachability scan
    (the gate must widen, never under-report)."""
    findings = lint_paths(
        [str(FIXTURES / "tpm4_bad"), str(FIXTURES / "tpm4_good")],
        entry_modules={"app.cli": "app.cli"},
    )
    assert "TPM401" in codes_of(findings), findings
    assert all("tpm4_bad" in f.path for f in findings
               if f.code == "TPM401"), findings


def test_import_hygiene_exempts_importerror_guarded_try(tmp_path):
    """`try: import jax / except ImportError:` imports fine where jax
    is absent — the canonical safe optional import must not be flagged.
    An import in the HANDLER still is: it runs exactly when the body
    already failed."""
    pkg = tmp_path / "app"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "cli.py").write_text(
        "try:\n"
        "    import jax\n"
        "except ImportError:\n"
        "    jax = None\n"
    )
    findings = lint_paths([str(tmp_path)],
                          entry_modules={"app.cli": "app.cli"})
    assert "TPM401" not in codes_of(findings), findings

    (pkg / "cli.py").write_text(
        "try:\n"
        "    import jax\n"
        "except ImportError:\n"
        "    from jax.experimental import compat as jax\n"
    )
    findings = lint_paths([str(tmp_path)],
                          entry_modules={"app.cli": "app.cli"})
    assert codes_of(findings).count("TPM401") == 1, findings


def test_missing_py_file_reports_one_finding(tmp_path):
    """A nonexistent explicit .py path must yield exactly ONE TPM902
    (the existence check), not a second contradictory parse error."""
    findings = lint_paths([str(tmp_path / "ghost.py")])
    assert codes_of(findings) == ["TPM902"], findings
    assert "does not exist" in findings[0].message


def test_bad_fixture_findings_carry_lines_and_messages():
    findings = lint_paths([str(FIXTURES / "tpm1_bad.py")])
    f = next(f for f in findings if f.code == "TPM101")
    assert f.line == 10  # the dispatch line, where the fix goes
    assert "block" in f.message
    assert str(FIXTURES / "tpm1_bad.py") == f.path


def test_unused_suppression_is_a_finding():
    findings = lint_paths([str(FIXTURES / "tpm9_unused.py")])
    assert codes_of(findings) == ["TPM900"]
    assert "TPM101" in findings[0].message


def test_fused_runner_factory_convicts_without_origin_resolution():
    """ISSUE 15 satellite: the fused-tier runner factory is on the
    compiled-fn-factory NAME list (analysis/core.FACTORY_NAMES,
    alongside ``pick_kernel_tier``), so a perf_counter pair timing its
    result convicts TPM101 even when the import graph cannot resolve
    the call's origin (the fixture binds the module dynamically)."""
    from tpu_mpi_tests.analysis.core import FACTORY_NAMES

    assert {"pick_kernel_tier", "iterate_fused_rdma_fn"} <= FACTORY_NAMES
    findings = lint_paths([str(FIXTURES / "tpm1_factory_bad.py")])
    assert codes_of(findings) == ["TPM101"], findings
    assert "run" in findings[0].message


def test_malformed_suppression_is_a_finding(tmp_path):
    p = tmp_path / "mal.py"
    p.write_text("x = 1  # tpumt: ignore TPM101 (missing brackets)\n")
    findings = lint_paths([str(p)])
    assert codes_of(findings) == ["TPM901"]


def test_suppression_marker_inside_string_is_not_parsed():
    # tokenize-based collection: the marker in a string literal is data
    src = 's = "# tpumt: ignore[TPM101]"\n'
    supps, malformed = collect_suppressions(src)
    assert supps == [] and malformed == []


def test_suppression_on_closing_paren_of_multiline_call(tmp_path):
    """Findings anchor to a multi-line call's FIRST line; a trailing
    suppression on the closing paren must still silence it (matched via
    the logical statement's start line) and count as used."""
    p = tmp_path / "multi.py"
    p.write_text(
        "import time\n"
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = jnp.sin(\n"
        "        x\n"
        "    )  # tpumt: ignore[TPM101]\n"
        "    return y, time.perf_counter() - t0\n"
    )
    assert lint_paths([str(p)]) == []


def test_missing_path_is_a_finding_not_a_clean_pass(tmp_path):
    """A lint gate pointed at a renamed/missing directory must fail
    loudly, never lint nothing and exit 0."""
    findings = lint_paths([str(tmp_path / "no_such_dir")])
    assert codes_of(findings) == ["TPM902"]
    assert "vacuously" in findings[0].message
    notes = tmp_path / "notes.txt"
    notes.write_text("not python\n")
    findings = lint_paths([str(notes)])
    assert codes_of(findings) == ["TPM902"]


def test_select_and_ignore_filter_families():
    bad = str(FIXTURES / "tpm2_bad.py")
    assert lint_paths([bad], select=["TPM1xx"]) == []
    assert lint_paths([bad], ignore=["TPM2"]) == []
    kept = lint_paths([bad], select=["TPM2"])
    assert kept and all(c == "TPM201" for c in codes_of(kept))


def test_ignored_family_does_not_warn_unused_suppression():
    sup = str(FIXTURES / "tpm1_suppressed.py")
    assert lint_paths([sup], ignore=["TPM1"]) == []


def test_syntax_error_reports_tpm902(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = lint_paths([str(p)])
    assert codes_of(findings) == ["TPM902"]


def test_recursive_walk_skips_fixtures_dir(tmp_path):
    sub = tmp_path / "pkg" / "fixtures"
    sub.mkdir(parents=True)
    (sub / "bad.py").write_text(
        (FIXTURES / "tpm1_bad.py").read_text()
    )
    assert lint_paths([str(tmp_path)]) == []


def test_schedule_constants_tune_modules_exempt():
    """The priors tables live in tpu_mpi_tests/tune/ by design — the
    sanctioned home lints clean while the same text elsewhere fires
    (tpm7_bad mirrors the pre-autotuner comm/ring.py tables)."""
    findings = lint_paths([str(REPO / "tpu_mpi_tests" / "tune")])
    assert not any(c == "TPM701" for c in codes_of(findings)), findings


def test_schedule_constants_mutation_outside_tune(tmp_path):
    """Mutation check: re-pinning a MEASURED_BEST-style table in a
    non-tune module is caught; registering the SAME numbers through
    declare_space is not (routing through the registry IS the fix),
    and non-schedule caps constants stay out of scope."""
    p = tmp_path / "mod.py"
    p.write_text('MEASURED_BEST_K_TILE = {"contig": 2048}\n')
    assert "TPM701" in codes_of(lint_paths([str(p)]))
    p.write_text(
        "from tpu_mpi_tests.tune.registry import declare_space\n"
        'SPACE_K_TILE = declare_space("demo/k", (2048, 512))\n'
    )
    assert "TPM701" not in codes_of(lint_paths([str(p)]))
    p.write_text("FLIGHT_CAPACITY = 64\n")  # no schedule keyword
    assert "TPM701" not in codes_of(lint_paths([str(p)]))
    # the ISSUE-7 pipeline knobs are schedule words too: a re-pinned
    # depth constant outside tune/ fires, the declared space does not
    p.write_text("RING_PIPELINE_DEPTH = 2\n")
    assert "TPM701" in codes_of(lint_paths([str(p)]))


def test_schedule_constants_workloads_extended_keywords(tmp_path):
    """ISSUE-8 extension: inside tpu_mpi_tests.workloads the keyword
    set grows the serving-era knob vocabulary (CAPACITY/LOOKUP/COMBINE/
    ROUTE/EXPERT/FANOUT) — a spec's pinned capacity constant fires and
    is exempt ONLY via declare_space; the same name outside workloads/
    stays out of scope (FLIGHT_CAPACITY is a ring-buffer bound there)."""
    pkg = tmp_path / "tpu_mpi_tests" / "workloads"
    pkg.mkdir(parents=True)
    (tmp_path / "tpu_mpi_tests" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    spec = pkg / "myspec.py"
    spec.write_text("MOE_CAPACITY_FACTOR = 1.25\n")
    assert "TPM701" in codes_of(lint_paths([str(spec)]))
    spec.write_text("EMBED_LOOKUP_WIDTH = 128\n")
    assert "TPM701" in codes_of(lint_paths([str(spec)]))
    # declare_space is the sanctioned route, inside workloads/ too
    spec.write_text(
        "from tpu_mpi_tests.tune.registry import declare_space\n"
        'CAPACITY_SPACE = declare_space("moe/cap", (1.25, 2.0))\n'
    )
    assert "TPM701" not in codes_of(lint_paths([str(spec)]))
    # outside workloads/, the extended words stay out of scope
    other = tmp_path / "other.py"
    other.write_text("MOE_CAPACITY_FACTOR = 1.25\n")
    assert "TPM701" not in codes_of(lint_paths([str(other)]))


def test_overlap_region_scoping(tmp_path):
    """TPM801 behavior beyond the goldens: the region closes at the
    handle's consume point (a sync after `.done()` is clean), an
    UNCONSUMED handle keeps the region open to the end of the function,
    and a nested function's syncs do not leak into the outer region."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from tpu_mpi_tests.instrument.telemetry import async_span\n"
        "from tpu_mpi_tests.instrument.timers import block\n"
        "def good(fn, z):\n"
        "    h = async_span('op')\n"
        "    ex = fn(z)\n"
        "    h.done(ex)\n"
        "    return block(ex)\n"
    )
    assert "TPM801" not in codes_of(lint_paths([str(p)]))
    p.write_text(
        "from tpu_mpi_tests.instrument.telemetry import async_span\n"
        "from tpu_mpi_tests.instrument.timers import block\n"
        "def dangling(fn, z):\n"
        "    h = async_span('op')\n"
        "    ex = fn(z)\n"
        "    return block(ex)\n"  # handle never consumed: still a region
    )
    assert "TPM801" in codes_of(lint_paths([str(p)]))
    p.write_text(
        "from tpu_mpi_tests.instrument.telemetry import async_span\n"
        "from tpu_mpi_tests.instrument.timers import block\n"
        "def outer(fn, z):\n"
        "    h = async_span('op')\n"
        "    ex = fn(z)\n"
        "    h.done(ex)\n"
        "def unrelated(y):\n"
        "    return block(y)\n"  # no region in unrelated's scope
    )
    assert "TPM801" not in codes_of(lint_paths([str(p)]))


def test_chaos_containment_scoping(tmp_path):
    """TPM1001 beyond the goldens: a driver-shaped module touching the
    chaos layer is a finding, while test modules are exempt (tests
    exist to exercise the faults). The sanctioned arm-point and the
    chaos package itself are proven exempt by the self-clean gate —
    drivers/_common and tpu_mpi_tests/chaos both lint in-tree."""
    src = (
        "from tpu_mpi_tests.chaos import arm_from_spec\n"
        "def run(args):\n"
        "    arm_from_spec('kill:rank=1:op=x', rank=0)\n"
    )
    prod = tmp_path / "hotpath.py"
    prod.write_text(src)
    codes = codes_of(lint_paths([str(prod)]))
    assert codes.count("TPM1001") == 2  # the import AND the call
    for exempt_name in ("test_hotpath.py", "conftest.py"):
        p = tmp_path / exempt_name
        p.write_text(src)
        assert "TPM1001" not in codes_of(lint_paths([str(p)]))


def test_cli_human_output_and_exit_codes(capsys):
    rc = cli.main([str(FIXTURES / "tpm1_bad.py")])
    out = capsys.readouterr()
    assert rc == 1
    assert "TPM101" in out.out
    assert "finding" in out.err

    rc = cli.main([str(FIXTURES / "tpm1_good.py")])
    out = capsys.readouterr()
    assert rc == 0
    assert out.out == ""


def test_cli_json_output(capsys):
    rc = cli.main(["--format", "json", str(FIXTURES / "tpm3_bad.py")])
    out = capsys.readouterr()
    assert rc == 1
    doc = json.loads(out.out)
    assert doc["version"] == 1
    assert doc["count"] == len(doc["findings"]) > 0
    f = doc["findings"][0]
    assert set(f) == {"path", "line", "col", "code", "message"}
    assert {x["code"] for x in doc["findings"]} == {"TPM301", "TPM302"}


def test_cli_list_rules_covers_every_family(capsys):
    rc = cli.main(["--list-rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for code in ("TPM101", "TPM102", "TPM201", "TPM301", "TPM302",
                 "TPM401", "TPM501", "TPM502", "TPM601", "TPM701",
                 "TPM801", "TPM802", "TPM900", "TPM1001", "TPM1101",
                 "TPM1102", "TPM1201", "TPM1301", "TPM1401",
                 "TPM1402", "TPM1601", "TPM1602", "TPM1603"):
        assert code in out
    # table rows match the registry (README is hand-synced to this)
    assert len(rule_table()) >= 20


def test_cli_sarif_golden(capsys):
    """Pin the SARIF 2.1.0 subset we emit — the fields CI hosts need to
    render findings inline: schema/version, driver name + full rule
    table, and per-result ruleId/level/message/physical location with
    1-based columns."""
    rc = cli.main(["--format", "sarif", str(FIXTURES / "tpm1_bad.py")])
    out = capsys.readouterr()
    assert rc == 1
    doc = json.loads(out.out)
    assert doc["$schema"].endswith("sarif-2.1.0.json")
    assert doc["version"] == "2.1.0"
    assert len(doc["runs"]) == 1
    driver = doc["runs"][0]["tool"]["driver"]
    assert driver["name"] == "tpumt-lint"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert rule_ids == [code for code, _ in rule_table()]
    assert all(r["shortDescription"]["text"] for r in driver["rules"])
    results = doc["runs"][0]["results"]
    assert len(results) == 1
    res = results[0]
    assert res["ruleId"] == "TPM101"
    assert res["level"] == "error"
    assert "block" in res["message"]["text"]
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("tpm1_bad.py")
    # SARIF columns are 1-based; the engine's are 0-based
    assert loc["region"] == {"startLine": 10, "startColumn": 11}


def test_cli_sarif_clean_run_is_valid_empty(capsys):
    rc = cli.main(["--format", "sarif", str(FIXTURES / "tpm1_good.py")])
    out = capsys.readouterr()
    assert rc == 0
    doc = json.loads(out.out)
    assert doc["runs"][0]["results"] == []


def test_cache_cold_warm_touch_cycle(tmp_path):
    """The incrementality contract (acceptance criterion): a cold run
    analyzes every file; a warm run over the unchanged tree re-parses
    ZERO files and reproduces the identical findings — file-scope ones
    replayed, project-scope ones recomputed from cached facts (the
    cross-file TPM502 here proves the project pass sees deserialized
    summaries); touching one file re-analyzes exactly that file."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "kernel.py").write_text(
        "from jax import lax\n"
        "def local_sum(v):\n"
        "    return lax.psum(v, 'ghost')\n"
    )
    clean = proj / "meshes.py"
    clean.write_text(
        "from jax.sharding import Mesh\n"
        "def make(devs):\n"
        "    return Mesh(devs, ('x',))\n"
    )
    cache = tmp_path / "cache.json"

    s1: dict = {}
    f1 = lint_paths([str(proj)], cache_path=str(cache), stats=s1)
    assert counts_of(s1) == {"files": 2, "analyzed": 2, "cache_hits": 0}
    assert "TPM502" in codes_of(f1), f1
    assert cache.exists() and json.loads(cache.read_text())["entries"]

    s2: dict = {}
    f2 = lint_paths([str(proj)], cache_path=str(cache), stats=s2)
    assert counts_of(s2) == {"files": 2, "analyzed": 0, "cache_hits": 2}
    assert f2 == f1  # byte-identical findings, zero re-parsing

    clean.write_text(clean.read_text() + "\n# touched\n")
    s3: dict = {}
    f3 = lint_paths([str(proj)], cache_path=str(cache), stats=s3)
    assert counts_of(s3) == {"files": 2, "analyzed": 1, "cache_hits": 1}
    assert f3 == f1


def test_cache_replays_suppressions_and_file_findings(tmp_path):
    """Warm runs must replay suppression state too: a used suppression
    stays silent (no finding, no TPM900) and an unused one keeps
    warning, identically to the cold run."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "sup.py").write_text(
        (FIXTURES / "tpm1_suppressed.py").read_text()
    )
    (proj / "unused.py").write_text(
        (FIXTURES / "tpm9_unused.py").read_text()
    )
    cache = tmp_path / "cache.json"
    f1 = lint_paths([str(proj)], cache_path=str(cache))
    s2: dict = {}
    f2 = lint_paths([str(proj)], cache_path=str(cache), stats=s2)
    assert s2["analyzed"] == 0 and s2["cache_hits"] == 2
    assert f2 == f1
    assert codes_of(f2) == ["TPM900"]


def test_cache_misses_when_package_anchoring_changes(tmp_path):
    """Content hashes alone can't see an added/removed ``__init__.py``:
    it re-anchors every module name in the tree without touching the
    files' bytes, and replaying facts under stale names would make warm
    project findings diverge from a cold run. The replay validates the
    module name and degrades to re-analysis instead."""
    pkg = tmp_path / "dnt"
    pkg.mkdir()
    init = pkg / "__init__.py"
    init.write_text("")
    (pkg / "helper.py").write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def reduce_into(buf, mesh):\n"
        "    return allreduce_sum(buf, mesh)\n"
    )
    (pkg / "driver.py").write_text(
        "from dnt.helper import reduce_into\n"
        "def step(x, mesh):\n"
        "    total = reduce_into(x, mesh)\n"
        "    return x + total\n"
    )
    cache = tmp_path / "cache.json"
    f1 = lint_paths([str(tmp_path)], cache_path=str(cache))
    assert "TPM1201" in codes_of(f1), f1

    init.unlink()  # helper.py / driver.py bytes are unchanged
    cold = lint_paths([str(tmp_path)])
    s: dict = {}
    warm = lint_paths([str(tmp_path)], cache_path=str(cache), stats=s)
    assert warm == cold, (warm, cold)
    assert s["analyzed"] == 2 and s["cache_hits"] == 0, s


def test_cache_type_corrupted_entry_degrades_to_miss(tmp_path):
    """An entry with the right hash but a wrong-typed field (a
    hand-edit, a partial write) must re-analyze that file — never crash
    the run or replay partial facts."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "bad.py").write_text((FIXTURES / "tpm1_bad.py").read_text())
    cache = tmp_path / "cache.json"
    f1 = lint_paths([str(proj)], cache_path=str(cache))
    doc = json.loads(cache.read_text())
    (entry,) = doc["entries"].values()
    entry["findings"] = 0  # right hash, wrong shape
    cache.write_text(json.dumps(doc))
    s: dict = {}
    f2 = lint_paths([str(proj)], cache_path=str(cache), stats=s)
    assert f2 == f1
    assert s["analyzed"] == 1 and s["cache_hits"] == 0, s


def test_cache_evicts_deleted_paths_on_save(tmp_path):
    """The ISSUE-12 carry-over nit: entries for deleted/renamed files
    must leave the cache at save() instead of accumulating until an
    engine-salt reset — lint two files, delete one, lint again, and the
    stale entry is gone (even though the second run had nothing new to
    write)."""
    proj = tmp_path / "proj"
    proj.mkdir()
    keep = proj / "keep.py"
    keep.write_text("KEEP = 1\n")
    gone = proj / "gone.py"
    gone.write_text("GONE = 1\n")
    cache = tmp_path / "cache.json"
    lint_paths([str(proj)], cache_path=str(cache))
    entries = json.loads(cache.read_text())["entries"]
    assert set(entries) == {str(keep), str(gone)}

    gone.unlink()
    s: dict = {}
    lint_paths([str(proj)], cache_path=str(cache), stats=s)
    assert counts_of(s) == {"files": 1, "analyzed": 0, "cache_hits": 1}
    entries = json.loads(cache.read_text())["entries"]
    assert set(entries) == {str(keep)}, entries


def test_cache_engine_salt_mismatch_invalidates_once(tmp_path):
    """The engine-salt contract this PR's `lint-smoke` pins in CI: a
    cache written by a DIFFERENT engine (stale salt — e.g. the one-time
    bump this PR's rule changes cause) reads as empty, the next run
    re-analyzes everything exactly once, and the run after that is all
    cache hits again."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "mod.py").write_text("X = 1\n")
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps({
        "version": 1, "salt": "pre-bump-engine",
        "entries": {str(proj / "mod.py"): {"hash": "stale"}},
    }))
    s1: dict = {}
    lint_paths([str(proj)], cache_path=str(cache), stats=s1)
    assert counts_of(s1) == {"files": 1, "analyzed": 1, "cache_hits": 0}
    s2: dict = {}
    lint_paths([str(proj)], cache_path=str(cache), stats=s2)
    assert counts_of(s2) == {"files": 1, "analyzed": 0, "cache_hits": 1}


def test_records_generator_and_check_mode(tmp_path, capsys):
    """RECORDS.md generation (acceptance criterion): the table is
    non-empty for every record kind the four stdlib consumers parse,
    --check passes on a fresh file and fails (exit 1) once the file
    drifts — the `make records` / CI staleness gate."""
    from tpu_mpi_tests.analysis import records as records_mod

    out = tmp_path / "RECORDS.md"
    rc = records_mod.main(["-o", str(out)])
    capsys.readouterr()
    assert rc == 0
    text = out.read_text()
    # every kind the shipped consumers filter on has a non-empty row
    kinds, _stamps = records_mod.collect(
        [str(REPO / "tpu_mpi_tests"), str(REPO / "tpu")], REPO
    )
    consumed = {k for k, e in kinds.items() if e["consumers"]}
    assert consumed >= {"span", "time", "serve", "mem", "manifest",
                        "health", "overlap", "chaos", "vmem"}
    for kind in consumed:
        assert f"| `{kind}` |" in text, kind
        row = next(ln for ln in text.splitlines()
                   if ln.startswith(f"| `{kind}` |"))
        cells = [c.strip() for c in row.split("|")]
        assert cells[3] and cells[3] != "—", (kind, row)  # fields
        assert cells[5] and cells[5] != "—", (kind, row)  # consumers
    # the envelope stamp (rank via {**rec, ...} sink wrappers) is doc'd
    assert "Envelope fields" in text and "`rank`" in text

    rc = records_mod.main(["-o", str(out), "--check"])
    capsys.readouterr()
    assert rc == 0
    out.write_text(text + "drift\n")
    rc = records_mod.main(["-o", str(out), "--check"])
    err = capsys.readouterr().err
    assert rc == 1 and "stale" in err


def test_records_in_repo_is_fresh(capsys):
    """The committed RECORDS.md matches the code — the same gate
    `make ci` runs (generate → diff)."""
    from tpu_mpi_tests.analysis import records as records_mod

    rc = records_mod.main(["--check"])
    capsys.readouterr()
    assert rc == 0


def test_cache_corruption_degrades_to_cold_run(tmp_path):
    """A truncated/garbage cache file must never fail the lint or
    change its verdict — it reads as empty and the run goes cold."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "bad.py").write_text((FIXTURES / "tpm1_bad.py").read_text())
    cache = tmp_path / "cache.json"
    f1 = lint_paths([str(proj)], cache_path=str(cache))
    cache.write_text('{"version": 1, "salt": "stale", "entr')
    s: dict = {}
    f2 = lint_paths([str(proj)], cache_path=str(cache), stats=s)
    assert s["analyzed"] == 1 and s["cache_hits"] == 0
    assert f2 == f1


def test_cli_stats_and_no_cache(tmp_path, capsys):
    """--stats reports the cache-hit counters on stderr; --no-cache
    forces analyzed == files on every run and writes nothing."""
    cache = tmp_path / "cli_cache.json"
    target = str(FIXTURES / "tpm1_good.py")
    cli.main(["--cache", str(cache), "--stats", target])
    err = capsys.readouterr().err
    assert "files=1 analyzed=1 cache_hits=0" in err
    cli.main(["--cache", str(cache), "--stats", target])
    err = capsys.readouterr().err
    assert "files=1 analyzed=0 cache_hits=1" in err
    cli.main(["--no-cache", "--stats", target])
    err = capsys.readouterr().err
    assert "files=1 analyzed=1 cache_hits=0" in err
    assert "cache=off" in err


def test_tpm601_fallback_covers_unresolvable_bound_method(tmp_path):
    """Code-review regression (ISSUE 13): a spawn target that CAPTURES
    a ref but resolves to nothing at project scope (`obj.run` — untyped
    receiver, blocklisted common method name) leaves the lockset engine
    with no root, so the TPM601 fallback must still fire — resolution
    is judged where the project can actually see, not at capture time."""
    p = tmp_path / "mod.py"
    p.write_text(
        "import threading\n"
        "class R:\n"
        "    def __init__(self, path, obj):\n"
        "        self._f = open(path, 'a')\n"
        "        self._obj = obj\n"
        "    def arm(self, obj):\n"
        "        threading.Timer(1.0, obj.run).start()\n"
        "    def record(self, line):\n"
        "        self._f.write(line)\n"
    )
    assert codes_of(lint_paths([str(p)])) == ["TPM601"]


def test_duplicate_qualname_defs_keep_their_own_lock_facts(tmp_path):
    """Code-review regression (ISSUE 13): two same-qualname defs (the
    try/except-ImportError and platform-variant idioms) must each keep
    their OWN lock summary — an unlocked write in the first variant
    races even when the second variant is locked."""
    p = tmp_path / "mod.py"
    p.write_text(
        "import threading\n"
        "class R:\n"
        "    def __init__(self, path):\n"
        "        self._f = open(path, 'a')\n"
        "        self._lock = threading.Lock()\n"
        "    if True:\n"
        "        def emit(self, line):\n"
        "            self._f.write(line)\n"
        "    else:\n"
        "        def emit(self, line):\n"
        "            with self._lock:\n"
        "                self._f.write(line)\n"
        "    def arm(self):\n"
        "        threading.Timer(1.0, self._dump).start()\n"
        "    def _dump(self):\n"
        "        with self._lock:\n"
        "            self._f.write('fired')\n"
    )
    findings = lint_paths([str(p)])
    assert "TPM1601" in codes_of(findings), findings
    f = next(x for x in findings if x.code == "TPM1601")
    assert f.line == 8, f  # the unlocked variant's write


def test_module_level_lock_self_deadlock_convicts(tmp_path):
    """Code-review regression (ISSUE 13): module-scope ``_LOCK =
    threading.Lock()`` kinds must reach TPM1602 like class locks do —
    a lock-held call into a helper re-acquiring the same module lock
    is the same guaranteed self-deadlock; an RLock stays clean."""
    p = tmp_path / "mod.py"
    p.write_text(
        "import threading\n"
        "_LOCK = threading.Lock()\n"
        "def outer(vals):\n"
        "    with _LOCK:\n"
        "        helper(vals)\n"
        "def helper(vals):\n"
        "    with _LOCK:\n"
        "        vals.clear()\n"
    )
    assert "TPM1602" in codes_of(lint_paths([str(p)]))
    p.write_text(
        "import threading\n"
        "_LOCK = threading.RLock()\n"
        "def outer(vals):\n"
        "    with _LOCK:\n"
        "        helper(vals)\n"
        "def helper(vals):\n"
        "    with _LOCK:\n"
        "        vals.clear()\n"
    )
    assert "TPM1602" not in codes_of(lint_paths([str(p)]))


def test_deadlock_in_call_cycle_is_order_independent(tmp_path):
    """Code-review regression (ISSUE 13): the transitive-acquire memo
    must not cache a cycle-truncated result — a re-acquire deadlock
    inside an a→b→a cycle convicts even when an unrelated lock-held
    call forces the cycle to be explored from another entry first."""
    p = tmp_path / "mod.py"
    p.write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._lock2 = threading.Lock()\n"
        "    def early(self):\n"
        "        with self._lock2:\n"
        "            self.a()\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            self.b()\n"
        "    def b(self):\n"
        "        self.a()\n"
    )
    findings = lint_paths([str(p)])
    assert "TPM1602" in codes_of(findings), findings


def test_with_wrapped_early_exit_still_convicts(tmp_path):
    """Code-review regression (ISSUE 13): the new with-region CFG
    blocks must not resurrect terminated flow — a rank-guarded early
    return WRAPPED IN A `with` is still an exit edge, so TPM1102 keeps
    convicting the deadlock shape PR 12 closed."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "def run(x, mesh, rank, span):\n"
        "    if rank != 0:\n"
        "        with span('skip'):\n"
        "            return x\n"
        "    return allreduce_sum(x, mesh)\n"
    )
    assert "TPM1102" in codes_of(lint_paths([str(p)]))


def test_module_level_slot_install_is_not_a_rebind(tmp_path):
    """Code-review regression (ISSUE 13): an import-time cross-module
    slot assignment is a declaration-shaped initializer, not the
    arm-time rebind TPM1603 judges — only the function-scope install
    without a disarm convicts."""
    pkg = tmp_path / "plane"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "slots.py").write_text(
        "_SPAN_HOOK = None\n"
        "def fire(op):\n"
        "    h = _SPAN_HOOK\n"
        "    return h and h(op)\n"
    )
    boot = pkg / "boot.py"
    boot.write_text(
        "from plane import slots\n"
        "def default_hook(op):\n"
        "    return op\n"
        "slots._SPAN_HOOK = _install()\n"
        "def _install():\n"
        "    return default_hook\n"
    )
    assert "TPM1603" not in codes_of(lint_paths([str(tmp_path)]))
    boot.write_text(
        "from plane import slots\n"
        "def arm():\n"
        "    slots._SPAN_HOOK = _install()\n"
        "def _install():\n"
        "    def hook(op):\n"
        "        return op\n"
        "    return hook\n"
    )
    assert "TPM1603" in codes_of(lint_paths([str(tmp_path)]))


def _copy_lint_tree(tmp_path):
    """A tmp copy of the self-clean lint root set (tests/ excluded —
    test modules are exempt from the contract families anyway), for
    the seeded-mutant runs that must convict against the REAL tree."""
    import shutil

    roots = []
    for name in ("tpu_mpi_tests", "tpu"):
        shutil.copytree(REPO / name, tmp_path / name,
                        ignore=shutil.ignore_patterns("__pycache__"))
        roots.append(str(tmp_path / name))
    for name in ("bench.py", "__graft_entry__.py"):
        shutil.copyfile(REPO / name, tmp_path / name)
        roots.append(str(tmp_path / name))
    return roots


def test_seeded_fleet_mutant_winner_broadcast_dropped(tmp_path):
    """Mutation gate (ISSUE 14 acceptance): stripping the winner
    broadcast from the fleet sweep — rank 0 keeps its locally-built
    result record, every other rank's ``result`` stays the None
    placeholder — is exactly the rank-divergence TPM1301 was built for,
    convicted as the run's SOLE finding, anchored at the unbroadcast
    read in sweep.py. The SHIPPED code routes the value through
    ``fleet.bcast`` (a curated broadcast-class call) and lints clean —
    the dogfood half of the contract is ``make lint`` / the self-clean
    gate."""
    roots = _copy_lint_tree(tmp_path)
    sp = tmp_path / "tpu_mpi_tests" / "tune" / "sweep.py"
    src = sp.read_text()
    old = '    result = fleet.bcast(result, f"{knob}:result")\n'
    assert old in src, "fleet sweep broadcast shape changed — update me"
    sp.write_text(src.replace(old, ""))
    findings = lint_paths(roots)
    assert codes_of(findings) == ["TPM1301"], findings
    f = findings[0]
    assert f.path.endswith("sweep.py"), f
    assert "result" in f.message, f


def test_seeded_race_mutant_jsonl_lock_stripped(tmp_path):
    """Mutation gate (acceptance criterion): stripping ``with
    self._jsonl_lock:`` from Reporter.jsonl makes the handle write a
    disjoint-lockset race between the main thread and the live-plane
    threads that reach jsonl through the sink escapes — convicted as
    the run's SOLE finding, anchored in report.py."""
    roots = _copy_lint_tree(tmp_path)
    rp = tmp_path / "tpu_mpi_tests" / "instrument" / "report.py"
    src = rp.read_text()
    old = (
        "        with self._jsonl_lock:\n"
        "            if self._jsonl_file is None:\n"
        '                self._jsonl_file = open(self.jsonl_path, "a")\n'
        "            self._jsonl_file.write(line)\n"
        "            self._jsonl_file.flush()\n"
    )
    new = (
        "        if self._jsonl_file is None:\n"
        '            self._jsonl_file = open(self.jsonl_path, "a")\n'
        "        self._jsonl_file.write(line)\n"
        "        self._jsonl_file.flush()\n"
    )
    assert old in src, "report.py jsonl lock shape changed — update me"
    rp.write_text(src.replace(old, new))
    findings = lint_paths(roots)
    assert codes_of(findings) == ["TPM1601"], findings
    f = findings[0]
    assert f.path.endswith("report.py"), f
    assert "_jsonl_file" in f.message, f


def test_seeded_deadlock_mutant_lock_held_call(tmp_path):
    """Mutation gate (acceptance criterion): inlining a call to the
    lock-taking ``value`` helper INSIDE set_gauge's ``with self._lock:``
    region re-acquires the non-reentrant registry lock — convicted as
    the run's SOLE finding (TPM1602), anchored at the call. Run with
    --jobs 2 so the parallel extraction path feeds the project pass
    in-suite."""
    roots = _copy_lint_tree(tmp_path)
    mp = tmp_path / "tpu_mpi_tests" / "instrument" / "metrics.py"
    src = mp.read_text()
    old = (
        "        with self._lock:\n"
        '            s = self._get(name, "gauge", labels)\n'
        "            if s is not None:\n"
        "                s.value = v\n"
    )
    new = old + "            self.value(name, labels)\n"
    assert old in src, "metrics.py set_gauge shape changed — update me"
    mp.write_text(src.replace(old, new))
    findings = lint_paths(roots, jobs=2)
    assert codes_of(findings) == ["TPM1602"], findings
    f = findings[0]
    assert f.path.endswith("metrics.py"), f
    assert "value" in f.message and "_lock" in f.message, f


def test_tpm601_fallback_fires_only_without_resolved_roots(tmp_path):
    """The demotion contract: the lexical TPM601 heuristic fires ONLY
    where thread-entry discovery resolved nothing (a dynamic spawn
    target) — a resolvable target hands the file to the TPM16xx engine
    and TPM601 stands down."""
    p = tmp_path / "dyn.py"
    p.write_text(
        "import threading\n"
        "class R:\n"
        "    def __init__(self, path, hooks):\n"
        "        self._f = open(path, 'a')\n"
        "        self._hooks = hooks\n"
        "    def arm(self):\n"
        "        threading.Timer(1.0, self._hooks[0]).start()\n"
        "    def record(self, line):\n"
        "        self._f.write(line)\n"
    )
    findings = lint_paths([str(p)])
    assert codes_of(findings) == ["TPM601"], findings
    # same file, but the Timer target now resolves: the lockset engine
    # owns the file — TPM601 silent, the race convicted as TPM1601
    p.write_text(
        "import threading\n"
        "class R:\n"
        "    def __init__(self, path):\n"
        "        self._f = open(path, 'a')\n"
        "    def arm(self):\n"
        "        threading.Timer(1.0, self._dump).start()\n"
        "    def _dump(self):\n"
        "        self._f.write('fired')\n"
        "    def record(self, line):\n"
        "        self._f.write(line)\n"
    )
    findings = lint_paths([str(p)])
    assert "TPM601" not in codes_of(findings), findings
    assert "TPM1601" in codes_of(findings), findings


def test_race_inheritance_merges_locations(tmp_path):
    """A subclass's ``self.phase`` store and the base's timer-thread
    read are ONE abstract location (base-climbed) — the IdleAwareWatchdog
    shape; unrelated same-named attrs on unrelated classes are not."""
    p = tmp_path / "wd.py"
    p.write_text(
        "import threading\n"
        "class Base:\n"
        "    def __init__(self):\n"
        "        self.phase = 'idle'\n"
        "    def start(self):\n"
        "        threading.Timer(1.0, self._fire).start()\n"
        "    def _fire(self):\n"
        "        print(self.phase)\n"
        "class Sub(Base):\n"
        "    def arm(self, phase):\n"
        "        self.phase = phase\n"
    )
    findings = lint_paths([str(p)])
    assert "TPM1601" in codes_of(findings), findings
    f = next(x for x in findings if x.code == "TPM1601")
    assert f.line == 11, f  # the subclass store (the write anchors)


def test_hook_roots_are_not_mhp_with_main(tmp_path):
    """Phase hooks fire ON the thread running the phase: a hook-only
    root must not fabricate a race against main-thread code (the
    PhaseProgress shape is single-threaded in reality)."""
    p = tmp_path / "hooks.py"
    p.write_text(
        "from tpu_mpi_tests.instrument.timers import add_phase_hook\n"
        "class Progress:\n"
        "    def __init__(self):\n"
        "        self._tot = {}\n"
        "    def __call__(self, name, event):\n"
        "        self._tot[name] = self._tot.get(name, 0) + 1\n"
        "    def start(self):\n"
        "        add_phase_hook(self)\n"
        "    def stop(self):\n"
        "        self._tot.clear()\n"
    )
    findings = lint_paths([str(p)])
    assert not any(c.startswith("TPM16") for c in codes_of(findings)), \
        findings


def test_cache_replays_concurrency_facts(tmp_path):
    """Acceptance criterion: warm-cache lint re-parses ZERO files with
    the new facts schema, and the TPM16xx project findings recompute
    identically from the REPLAYED threading-plane facts (spawns,
    escapes, locksets all cross the JSON boundary)."""
    import shutil

    proj = tmp_path / "tree"
    shutil.copytree(FIXTURES / "tpm16_bad", proj)
    cache = tmp_path / "cache.json"
    s1: dict = {}
    f1 = lint_paths([str(proj)], cache_path=str(cache), stats=s1)
    assert counts_of(s1)["analyzed"] == counts_of(s1)["files"] > 0
    assert {"TPM1601", "TPM1602", "TPM1603"} <= set(codes_of(f1)), f1
    s2: dict = {}
    f2 = lint_paths([str(proj)], cache_path=str(cache), stats=s2)
    assert s2["analyzed"] == 0 and s2["cache_hits"] == s2["files"]
    assert f2 == f1


def test_jobs_parallel_extraction_matches_sequential(tmp_path):
    """--jobs N farms per-file analysis to worker processes; findings
    are identical to the sequential run, and a warm-cache run stays
    zero-reparse regardless of N."""
    import shutil

    proj = tmp_path / "tree"
    shutil.copytree(FIXTURES / "tpm16_bad", proj)
    seq = lint_paths([str(proj)], jobs=1)
    par = lint_paths([str(proj)], jobs=2)
    assert par == seq and par, par
    cache = tmp_path / "cache.json"
    lint_paths([str(proj)], cache_path=str(cache), jobs=2)
    s: dict = {}
    warm = lint_paths([str(proj)], cache_path=str(cache), jobs=3,
                      stats=s)
    assert s["analyzed"] == 0 and s["jobs"] == 3
    assert warm == seq


def test_cli_json_and_sarif_carry_tpm16(capsys):
    """The output-format goldens extended with a TPM16xx finding
    (satellite): --format json carries the race finding with its
    anchor, and the SARIF rule table + results include the family."""
    bad = str(FIXTURES / "tpm16_bad")
    rc = cli.main(["--no-cache", "--format", "json", bad])
    out = capsys.readouterr()
    assert rc == 1
    doc = json.loads(out.out)
    by_code = {f["code"]: f for f in doc["findings"]}
    assert {"TPM1601", "TPM1602", "TPM1603"} <= set(by_code)
    race = by_code["TPM1601"]
    assert race["path"].endswith("recorder.py") and race["line"] == 20

    rc = cli.main(["--no-cache", "--format", "sarif", bad])
    out = capsys.readouterr()
    assert rc == 1
    doc = json.loads(out.out)
    driver = doc["runs"][0]["tool"]["driver"]
    rule_ids = [r["id"] for r in driver["rules"]]
    assert {"TPM1601", "TPM1602", "TPM1603", "TPM601"} <= set(rule_ids)
    result_codes = {r["ruleId"] for r in doc["runs"][0]["results"]}
    assert {"TPM1601", "TPM1602", "TPM1603"} <= result_codes


# --------------------------------------------------------------------------
# ISSUE 18: the collective-protocol verifier (TPM17xx + --conform)


def test_seeded_protocol_mutant_rank_guarded_handshake(tmp_path):
    """Mutation gate (ISSUE 18 acceptance): rank-guarding the fleet
    sweep's opening broadcast handshake is invisible to every prior
    family — the handshake binds nothing (TPM1301 silent), ``bcast`` is
    outside TPM1101's core alphabet, and no branch exits (TPM1102
    silent) — yet rank 0's composed schedule gains a replication point
    the other ranks never enter. Convicted as the run's SOLE finding,
    TPM1701, anchored at the guard in sweep.py."""
    roots = _copy_lint_tree(tmp_path)
    sp = tmp_path / "tpu_mpi_tests" / "tune" / "sweep.py"
    src = sp.read_text()
    old = ('        fleet.bcast({"knob": knob, "n": len(candidates)}, '
           'f"{knob}:open")\n')
    assert old in src, "fleet sweep handshake shape changed — update me"
    new = ('        if fleet.process_index() == 0:\n'
           '            fleet.bcast({"knob": knob, "n": '
           'len(candidates)}, f"{knob}:open")\n')
    sp.write_text(src.replace(old, new))
    findings = lint_paths(roots)
    assert codes_of(findings) == ["TPM1701"], findings
    f = findings[0]
    assert f.path.endswith("sweep.py"), f
    assert "broadcast" in f.message, f


def test_seeded_protocol_mutant_rank_dependent_trip_count(tmp_path):
    """Mutation gate (ISSUE 18 acceptance): making the serve step's
    halo-batch trip count a function of the rank leaves every op and
    every branch identical across ranks — only the iteration COUNT
    diverges, the deadlock TPM1702 exists for. Sole finding, anchored
    at the loop in stencil1d.py. Run with --jobs 2 so the parallel
    extraction path feeds the protocol pass in-suite."""
    roots = _copy_lint_tree(tmp_path)
    st = tmp_path / "tpu_mpi_tests" / "workloads" / "stencil1d.py"
    src = st.read_text()
    old = "                    for _ in range(k):\n"
    assert old in src, "serve step batch-loop shape changed — update me"
    new = "                    for _ in range(k - jax.process_index()):\n"
    st.write_text(src.replace(old, new))
    findings = lint_paths(roots, jobs=2)
    assert codes_of(findings) == ["TPM1702"], findings
    f = findings[0]
    assert f.path.endswith("stencil1d.py"), f
    assert "trip count" in f.message, f


def _write_pair_tree(tmp_path):
    """A loop-free two-collective schedule: ``allreduce`` then
    ``reduce_scatter``, exactly once each — the tightest automaton a
    fabricated stream can violate (the real tree's dynamically-named
    overlap spans deliberately widen its language)."""
    pkg = tmp_path / "duo"
    pkg.mkdir()
    (pkg / "__init__.py").write_text('"""pair-schedule tree."""\n')
    (pkg / "pair.py").write_text(
        "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
        "from tpu_mpi_tests.comm.collectives import reduce_scatter\n"
        "from tpu_mpi_tests.instrument.telemetry import comm_span\n"
        "\n"
        "\n"
        "def pair(x, mesh):\n"
        '    with comm_span("allreduce", axis_name="ring"):\n'
        "        x = allreduce_sum(x, mesh)\n"
        '    with comm_span("reduce_scatter", axis_name="ring"):\n'
        "        x = reduce_scatter(x, mesh)\n"
        "    return x\n"
    )
    return str(tmp_path)


def _write_stream(path, rank, events, seq=True):
    """One rank's telemetry JSONL: a manifest then span records, with
    (``seq=False``) or without the PR-17 per-(op, axis) stamps."""
    recs = [{"kind": "manifest", "process_index": rank,
             "process_count": 2}]
    counters: dict = {}
    for op, ax in events:
        rec = {"kind": "span", "op": op, "axis": ax, "seconds": 0.001}
        if seq:
            key = (op, ax)
            rec["seq"] = counters.get(key, 0)
            counters[key] = rec["seq"] + 1
        recs.append(rec)
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(path)


PAIR = [("allreduce", "ring"), ("reduce_scatter", "ring")]


def test_conform_matching_streams_clean(tmp_path):
    """Both ranks emit exactly the static schedule → no findings, no
    notes."""
    from tpu_mpi_tests.analysis.core import collect_project
    from tpu_mpi_tests.analysis.protocol import conform_paths

    tree = _write_pair_tree(tmp_path)
    s0 = _write_stream(tmp_path / "s.p0.jsonl", 0, PAIR)
    s1 = _write_stream(tmp_path / "s.p1.jsonl", 1, PAIR)
    findings, notes = conform_paths([s0, s1], collect_project([tree]))
    assert findings == [] and notes == [], (findings, notes)


def test_conform_rank_set_expansion(tmp_path):
    """Passing the base path finds the ``.p<i>`` siblings — the same
    expansion the launcher/aggregator contract uses."""
    from tpu_mpi_tests.analysis.core import collect_project
    from tpu_mpi_tests.analysis.protocol import conform_paths

    tree = _write_pair_tree(tmp_path)
    _write_stream(tmp_path / "s.p0.jsonl", 0, PAIR)
    _write_stream(tmp_path / "s.p1.jsonl", 1, PAIR + PAIR[:1])
    findings, _notes = conform_paths([str(tmp_path / "s.jsonl")],
                                     collect_project([tree]))
    # rank 1's third event leaves the pair schedule → the expansion
    # must have loaded BOTH rank files for the finding to exist
    assert codes_of(findings) == ["TPM1704"], findings


def test_conform_divergent_stream_tpm1704(tmp_path):
    """A runtime sequence no static path generates: the second
    ``allreduce`` has nowhere to go after the pair schedule's first —
    cited with the longest matched prefix and the diverging event."""
    from tpu_mpi_tests.analysis.core import collect_project
    from tpu_mpi_tests.analysis.protocol import conform_paths

    tree = _write_pair_tree(tmp_path)
    s0 = _write_stream(tmp_path / "s.p0.jsonl", 0,
                       [("allreduce", "ring"), ("allreduce", "ring")])
    findings, _notes = conform_paths([s0], collect_project([tree]))
    assert codes_of(findings) == ["TPM1704"], findings
    f = findings[0]
    assert f.path.endswith("s.p0.jsonl"), f
    assert "after 1 matched event" in f.message, f
    assert "op='allreduce'" in f.message, f
    assert "reduce_scatter" in f.message, f  # the expected next op


def test_conform_truncated_stream_tpm1705(tmp_path):
    """Rank 1 stops one event short while its sibling emitted the
    statically-mandatory next collective — the static twin of the
    doctor's missing_rank, citing the automaton state and expected
    op."""
    from tpu_mpi_tests.analysis.core import collect_project
    from tpu_mpi_tests.analysis.protocol import conform_paths

    tree = _write_pair_tree(tmp_path)
    s0 = _write_stream(tmp_path / "s.p0.jsonl", 0, PAIR)
    s1 = _write_stream(tmp_path / "s.p1.jsonl", 1, PAIR[:1])
    findings, _notes = conform_paths([s0, s1], collect_project([tree]))
    assert codes_of(findings) == ["TPM1705"], findings
    f = findings[0]
    assert f.path.endswith("s.p1.jsonl"), f
    assert "rank 1" in f.message and "sibling rank 0" in f.message, f
    assert "op='reduce_scatter'" in f.message, f
    assert "state" in f.message, f


def test_conform_preseq_stream_notes_never_convicts(tmp_path):
    """Acceptance criterion: pre-seq telemetry (no span carries the
    PR-17 stamp) degrades to a visible NOTE — even when the sequence
    would otherwise convict."""
    from tpu_mpi_tests.analysis.core import collect_project
    from tpu_mpi_tests.analysis.protocol import conform_paths

    tree = _write_pair_tree(tmp_path)
    s0 = _write_stream(tmp_path / "s.p0.jsonl", 0,
                       [("allreduce", "ring"), ("allreduce", "ring")],
                       seq=False)
    findings, notes = conform_paths([s0], collect_project([tree]))
    assert findings == [], findings
    assert any("insufficient stamps" in n for n in notes), notes


def test_conform_warm_cache_replays_automata(tmp_path):
    """Satellite (ISSUE 18): conformance replays its automata from the
    lint cache — the warm ``collect_project`` re-parses ZERO files
    (asserted via stats) and convicts identically; ``--jobs 2`` parity
    for the same pass."""
    from tpu_mpi_tests.analysis.core import collect_project
    from tpu_mpi_tests.analysis.protocol import conform_paths

    tree = _write_pair_tree(tmp_path)
    s0 = _write_stream(tmp_path / "s.p0.jsonl", 0, PAIR)
    s1 = _write_stream(tmp_path / "s.p1.jsonl", 1, PAIR[:1])
    cache = str(tmp_path / "cache.json")

    cold: dict = {}
    proj = collect_project([tree], cache_path=cache, stats=cold,
                           jobs=2)
    first, _ = conform_paths([s0, s1], proj)
    assert cold["analyzed"] == cold["files"] > 0, cold

    warm: dict = {}
    proj2 = collect_project([tree], cache_path=cache, stats=warm)
    again, _ = conform_paths([s0, s1], proj2)
    assert warm["analyzed"] == 0, warm
    assert warm["cache_hits"] == cold["files"], warm
    assert codes_of(first) == codes_of(again) == ["TPM1705"]


def test_protocol_pass_jobs_parity():
    """The protocol project pass yields identical findings whether the
    facts came from the sequential or the pooled extraction path."""
    bad = str(FIXTURES / "tpm17_bad")
    seq = lint_paths([bad])
    par = lint_paths([bad], jobs=2)
    assert par == seq and par, par


def test_cli_conform_exit_codes(tmp_path, capsys):
    """CLI contract: --conform exits 0 on a conformant stream set and 1
    on the truncated copy, naming TPM1705; NOTEs go to stderr."""
    tree = _write_pair_tree(tmp_path)
    s0 = _write_stream(tmp_path / "s.p0.jsonl", 0, PAIR)
    s1 = _write_stream(tmp_path / "s.p1.jsonl", 1, PAIR)
    rc = cli.main(["--conform", s0, s1, "--conform-tree", tree,
                   "--no-cache"])
    assert rc == 0, capsys.readouterr()
    capsys.readouterr()

    _write_stream(tmp_path / "s.p1.jsonl", 1, PAIR[:1])
    rc = cli.main(["--conform", s0, s1, "--conform-tree", tree,
                   "--no-cache"])
    out = capsys.readouterr()
    assert rc == 1
    assert "TPM1705" in out.out

    _write_stream(tmp_path / "s.p1.jsonl", 1, PAIR, seq=False)
    rc = cli.main(["--conform", s1, "--conform-tree", tree,
                   "--no-cache"])
    out = capsys.readouterr()
    assert rc == 0
    assert "insufficient stamps" in out.err


def test_cli_json_and_sarif_carry_tpm17(capsys):
    """The output-format goldens extended with the TPM17xx family:
    --format json carries all three static findings with their anchors,
    and the SARIF rule table lists the full family — the --conform-only
    codes (TPM1704/1705) included, so CI hosts can render them."""
    bad = str(FIXTURES / "tpm17_bad")
    rc = cli.main(["--no-cache", "--format", "json", bad])
    out = capsys.readouterr()
    assert rc == 1
    doc = json.loads(out.out)
    by_code = {f["code"]: f for f in doc["findings"]}
    assert {"TPM1701", "TPM1702", "TPM1703"} <= set(by_code)
    assert by_code["TPM1701"]["path"].endswith("driver.py")
    assert by_code["TPM1702"]["path"].endswith("halo_loop.py")
    assert by_code["TPM1703"]["path"].endswith("guard.py")

    rc = cli.main(["--no-cache", "--format", "sarif", bad])
    out = capsys.readouterr()
    assert rc == 1
    doc = json.loads(out.out)
    driver = doc["runs"][0]["tool"]["driver"]
    rule_ids = [r["id"] for r in driver["rules"]]
    assert {"TPM1701", "TPM1702", "TPM1703",
            "TPM1704", "TPM1705"} <= set(rule_ids)
    result_codes = {r["ruleId"] for r in doc["runs"][0]["results"]}
    assert {"TPM1701", "TPM1702", "TPM1703"} <= result_codes


def test_self_clean_gate():
    """The acceptance gate: the repo's own code lints clean — the same
    invocation ``make lint`` runs. A finding here means either new code
    regressed a gated hazard class or a rule grew a false positive;
    both block CI by design."""
    findings = lint_paths([
        str(REPO / "tpu_mpi_tests"),
        str(REPO / "tpu"),
        str(REPO / "tests"),
        str(REPO / "__graft_entry__.py"),
        str(REPO / "bench.py"),
    ])
    assert findings == [], "\n".join(f.format() for f in findings)
