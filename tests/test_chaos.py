"""Chaos layer (tpu_mpi_tests/chaos/): fault-spec grammar, arm/disarm
zero-state contract, hook behavior, the disarmed-identity acceptance
gate, end-to-end fault legs (wedge / oom in subprocesses; kill /
straggler across real processes under the native launcher), and
flight-recorder fidelity under a dying rank.

The multi-process legs use a LOCAL-compute workload (daxpy --iters):
this image's CPU backend has no cross-process collectives (the whole
test_multiproc family documents that), so the collective-triggered
variants (op= span faults) are exercised single-process where real
halo-exchange spans exist, and the rank-identity story is exercised
across real processes via phase triggers."""

import json
import os
import re
import shutil
import subprocess
import sys
import time
from pathlib import Path

import pytest

from tpu_mpi_tests.chaos import inject
from tpu_mpi_tests.chaos.spec import (
    FAULT_CLASSES,
    FINDING_FOR,
    parse_chaos_spec,
)
from tpu_mpi_tests.instrument import diagnose

REPO = Path(__file__).resolve().parent.parent
LAUNCHER = REPO / "native" / "tpumt_run"

#: fast-exit shim for the kill leg: the survivor must not sit in jax's
#: distributed-shutdown barrier (~100 s heartbeat timeout) waiting for
#: the rank chaos just killed
FAST_EXIT_DAXPY = (
    "import sys, os\n"
    "from tpu_mpi_tests.workloads.daxpy import main\n"
    "rc = main(sys.argv[1:])\n"
    "sys.stdout.flush(); sys.stderr.flush()\n"
    "os._exit(rc)\n"
)

#: wedge-leg shim: rank 0 (the jax.distributed coordinator) must stay
#: alive until rank 1's watchdog fires — the --deadline watchdog bounds
#: the WHOLE run, so rank 0 cannot simply be given more work; instead
#: it sleeps AFTER its run completes (watchdog already disarmed),
#: keeping the coordination service up past the peer's fire
KEEPALIVE_DAXPY = (
    "import sys, os, time\n"
    "from tpu_mpi_tests.workloads.daxpy import main\n"
    "rc = main(sys.argv[1:])\n"
    "sys.stdout.flush(); sys.stderr.flush()\n"
    "if os.environ.get('JAX_PROCESS_ID') == '0':\n"
    "    time.sleep(8)\n"
    "os._exit(rc)\n"
)


@pytest.fixture(autouse=True)
def _disarmed():
    yield
    inject.disarm()


# ---------------------------------------------------------------------------
# spec grammar
# ---------------------------------------------------------------------------


class TestSpec:
    def test_every_fault_class_has_a_finding_class(self):
        assert set(FINDING_FOR) == set(FAULT_CLASSES)
        assert set(FINDING_FOR.values()) <= set(diagnose.FINDING_CLASSES)

    def test_full_grammar_round_trip(self):
        (s,) = parse_chaos_spec("kill:rank=1:op=halo_exchange:after=3")
        assert (s.fault, s.rank, s.op, s.after) == (
            "kill", 1, "halo_exchange", 3)
        two = parse_chaos_spec(
            "straggler:rank=1:delay_ms=40, oom:step_mb=8:frac=0.5")
        assert [s.fault for s in two] == ["straggler", "oom"]
        assert two[0].delay_ms == 40.0 and two[1].frac == 0.5

    @pytest.mark.parametrize("bad", [
        "boom", "kill", "wedge", "kill:rank=x", "oom:frac=2",
        "oom:frac=0", "wedge:op=a:phase=b", "kill:op=a:after=0",
        "straggler:delay_ms=0", "flood:burst=0", "kill:op=a:nope=1",
        # keys the class ignores are rejected, not silently dropped —
        # accepting straggler:phase= would arm a uniform straggler
        # while the spec claims a phase-scoped one
        "straggler:phase=copyIn:delay_ms=40", "oom:op=daxpy",
        "flood:phase=kernel:burst=10", "kill:op=a:delay_ms=5",
        # duplicate keys are rejected, not silently last-wins
        "kill:rank=1:op=x:rank=0",
        # a zero stall cap hard-exits before the watchdog can fire
        "wedge:op=a:stall_s=0",
        "",
    ])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises(ValueError):
            parse_chaos_spec(bad)


# ---------------------------------------------------------------------------
# arm / disarm: the zero-state contract
# ---------------------------------------------------------------------------


class TestArm:
    def test_non_matching_rank_installs_nothing(self):
        from tpu_mpi_tests.instrument import telemetry, timers
        from tpu_mpi_tests.serve import loop as serve_loop

        orig_block = timers.block
        specs = parse_chaos_spec(
            "kill:rank=1:op=x,straggler:rank=1,flood:rank=1,"
            "oom:rank=1")
        assert inject.arm(specs, rank=0) == []
        assert telemetry._CHAOS_SPAN_HOOK is None
        assert serve_loop._CHAOS_FLOOD is None
        assert timers.block is orig_block
        assert inject.armed() == []

    def test_arm_installs_and_disarm_restores(self):
        from tpu_mpi_tests.instrument import telemetry, timers
        from tpu_mpi_tests.serve import loop as serve_loop

        orig_block = timers.block
        specs = parse_chaos_spec(
            "straggler:rank=0:op=halo,flood:rank=0,straggler:rank=0,"
            "oom:rank=0")
        mine = inject.arm(specs, rank=0)
        assert len(mine) == 4
        assert telemetry._CHAOS_SPAN_HOOK is not None
        assert serve_loop._CHAOS_FLOOD is not None
        assert timers.block is not orig_block  # uniform straggler wrap
        assert timers._PHASE_HOOKS  # oom ballast hook
        inject.disarm()
        assert telemetry._CHAOS_SPAN_HOOK is None
        assert serve_loop._CHAOS_FLOOD is None
        assert timers.block is orig_block
        assert inject._PHASE_HOOK is None
        assert inject._BALLAST == []

    def test_rearm_is_idempotent(self):
        from tpu_mpi_tests.instrument import timers

        orig_block = timers.block
        specs = parse_chaos_spec("straggler:rank=0")
        inject.arm(specs, rank=0)
        inject.arm(specs, rank=0)  # re-arm: must not double-wrap
        inject.disarm()
        assert timers.block is orig_block


class TestHooks:
    def test_op_straggler_sleeps_outside_measured_window(self):
        """The op-scoped straggler's delay lands AFTER the span's
        clock stops: the culprit's own spans stay honest while its
        late arrival inflates the siblings' next collective."""
        from tpu_mpi_tests.instrument import telemetry

        recs = []
        telemetry.enable(sink=recs.append)
        try:
            inject.arm(parse_chaos_spec(
                "straggler:rank=0:op=halo:delay_ms=60:after=2"),
                rank=0)
            t0 = time.perf_counter()
            with telemetry.comm_span("halo_exchange"):
                pass
            first = time.perf_counter() - t0  # event 1: no delay yet
            t0 = time.perf_counter()
            with telemetry.comm_span("halo_exchange"):
                pass
            second = time.perf_counter() - t0  # event 2: 60 ms outside
        finally:
            telemetry.disable()
            inject.disarm()
        spans = [r for r in recs if r.get("kind") == "span"]
        assert len(spans) == 2
        assert first < 0.05
        assert second >= 0.055
        # the measured span itself must NOT include the delay
        assert all(r["seconds"] < 0.05 for r in spans)
        # the injection audited itself exactly once
        fires = [r for r in recs if r.get("kind") == "chaos"
                 and r.get("event") == "fire"]
        assert len(fires) == 1

    def test_op_prefix_filter(self):
        from tpu_mpi_tests.instrument import telemetry

        telemetry.enable(sink=None)
        try:
            inject.arm(parse_chaos_spec(
                "straggler:rank=0:op=halo:delay_ms=80"), rank=0)
            t0 = time.perf_counter()
            with telemetry.comm_span("allreduce"):
                pass
            assert time.perf_counter() - t0 < 0.05  # no match, no delay
        finally:
            telemetry.disable()
            inject.disarm()

    def test_uniform_straggler_wraps_block(self):
        from tpu_mpi_tests.instrument import timers

        timers.block([0])  # warm-up: the first block pays jax init
        inject.arm(parse_chaos_spec(
            "straggler:rank=0:delay_ms=50:after=2"), rank=0)
        try:
            t0 = time.perf_counter()
            timers.block([1, 2])
            first = time.perf_counter() - t0
            t0 = time.perf_counter()
            timers.block([1, 2])
            second = time.perf_counter() - t0
        finally:
            inject.disarm()
        assert first < 0.04 and second >= 0.045

    def test_flood_hook_fires_once_at_its_window(self):
        inject.arm(parse_chaos_spec("flood:burst=37:after=2"), rank=0)
        try:
            from tpu_mpi_tests.serve import loop as serve_loop

            hook = serve_loop._CHAOS_FLOOD
            assert hook(1) == 0
            assert hook(2) == 37
            assert hook(2) == 0  # one-shot
            assert hook(3) == 0
        finally:
            inject.disarm()

    def test_flood_sheds_through_the_serve_loop(self):
        from tpu_mpi_tests.serve.arrival import OpenLoopPoisson
        from tpu_mpi_tests.serve.loop import ServeLoop
        from tpu_mpi_tests.serve.workloads import parse_workload_table

        class FakeClock:
            t = 0.0

            def clock(self):
                return self.t

            def sleep(self, dt):
                self.t += dt

        clk = FakeClock()
        classes = parse_workload_table("daxpy:128:float32")
        recs = []
        inject.arm(parse_chaos_spec("flood:burst=100:after=1"), rank=0)
        try:
            loop = ServeLoop(
                classes, {classes[0].key: lambda n: None},
                OpenLoopPoisson(5.0, seed=0), duration_s=6.0,
                window_s=2.0, max_queue=16, sink=recs.append,
                clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
            )
            (summary,) = loop.run()
        finally:
            inject.disarm()
        assert summary["shed"] >= 80  # burst 100 into a 16-deep queue
        windows = [r for r in recs if r.get("event") == "window"]
        assert any(w["shed"] > 0 for w in windows)

    def test_flood_never_inflates_closed_population(self):
        """Synthetic flood completions must NOT feed the arrival
        process: a closed loop's fixed client population has to return
        to exactly --concurrency once the burst drains, or every
        post-flood window measures a permanently different
        experiment."""
        from tpu_mpi_tests.serve.arrival import ClosedLoop
        from tpu_mpi_tests.serve.loop import ServeLoop
        from tpu_mpi_tests.serve.workloads import parse_workload_table

        class FakeClock:
            t = 0.0

            def clock(self):
                return self.t

            def sleep(self, dt):
                self.t += dt

        clk = FakeClock()
        classes = parse_workload_table("daxpy:128:float32")
        arrival = ClosedLoop(2)
        fed = []
        orig = arrival.on_complete
        arrival.on_complete = lambda n, now: (fed.append(n),
                                              orig(n, now))

        def handler(n):
            clk.t += 0.005 * n

        inject.arm(parse_chaos_spec("flood:burst=10:after=1"), rank=0)
        try:
            loop = ServeLoop(
                classes, {classes[0].key: handler}, arrival,
                duration_s=6.0, window_s=2.0, max_queue=32,
                clock=clk.clock, wall=clk.clock, sleep=clk.sleep,
            )
            (summary,) = loop.run()
        finally:
            inject.disarm()
        assert summary["requests"] > 10  # the burst was genuinely served
        # every completion fed back is an organic client; the 10
        # synthetic served requests re-armed nothing
        assert sum(fed) == summary["requests"] - 10

    def test_oom_explicit_limit_wins_over_device_limit(
        self, monkeypatch
    ):
        """An explicit limit_mb is a promise about how far the ramp
        goes: it must NOT be silently replaced by the device-reported
        HBM limit (which would ramp toward tens of GB on a real chip).
        Only the default defers to the hardware."""
        from tpu_mpi_tests.instrument import memwatch

        monkeypatch.setattr(
            memwatch, "device_memory_stats",
            lambda: {"d0": {"bytes_limit": 32 << 30}})
        monkeypatch.setattr(
            memwatch, "_live_totals", lambda: (1, 10 << 20))
        died = []
        monkeypatch.setattr(
            inject, "_die",
            lambda spec, code, why: died.append((code, why)))
        # explicit 8 MB limit: 10 MB live crosses 0.8*8MB -> dies
        (s,) = parse_chaos_spec("oom:step_mb=1:limit_mb=8:frac=0.8")
        inject._grow_ballast(s, "kernel")
        inject._BALLAST.clear()
        assert died and died[0][0] == inject.OOM_EXIT
        # default limit: defers to the 32 GB device limit -> no death
        died.clear()
        (s,) = parse_chaos_spec("oom:step_mb=1:frac=0.8")
        inject._grow_ballast(s, "kernel")
        inject._BALLAST.clear()
        assert not died


# ---------------------------------------------------------------------------
# flight-recorder fidelity under a dying rank (single-process half)
# ---------------------------------------------------------------------------


class TestFlightRecorderFidelity:
    def test_watchdog_dump_is_exactly_the_jsonl_tail(self):
        """The last 16 events in the fire dump must be exactly the
        tail of the JSONL record stream — same events, same order,
        ages non-increasing (oldest first)."""
        from tpu_mpi_tests.instrument import telemetry
        from tpu_mpi_tests.instrument.watchdog import DUMP_EVENTS, Watchdog

        telemetry.registry().reset()
        recs = []
        telemetry.enable(sink=recs.append)
        try:
            for i in range(20):
                with telemetry.comm_span(f"op{i:02d}"):
                    pass
            telemetry.note_dispatch("wedged-dma", op="rdma_ring")
            captured = []
            Watchdog(1.0, "t", _on_timeout=captured.append)._fire()
        finally:
            telemetry.disable()
        (msg,) = captured
        m = re.search(
            r"comm ops \(newest last\):\n((?:\s+.*\n)+?)\s+memory at fire:"
            r"|comm ops \(newest last\):\n((?:\s+.*\n)+?)\s+aborting",
            msg,
        )
        assert m, msg
        lines = [ln.strip() for ln in (m.group(1) or m.group(2))
                 .strip().splitlines()]
        assert len(lines) == DUMP_EVENTS
        dumped = [ln.split()[0] for ln in lines]
        # the JSONL stream saw the same events in the same order
        stream = [r.get("op") if r["kind"] == "span" else r.get("note")
                  for r in recs if r.get("kind") in ("span", "dispatch")]
        assert dumped == [
            s if s == "wedged-dma" else s for s in stream[-DUMP_EVENTS:]
        ]
        ages = [float(re.search(r"([\d.]+)s ago$", ln).group(1))
                for ln in lines]
        assert ages == sorted(ages, reverse=True)  # oldest first


# ---------------------------------------------------------------------------
# subprocess end-to-end legs
# ---------------------------------------------------------------------------


def _run(code_or_module, args, chaos=None, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("TPU_MPI_CHAOS", None)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    if chaos is not None:
        env["TPU_MPI_CHAOS"] = chaos
    if code_or_module.endswith(".py") or "\n" in code_or_module:
        cmd = [sys.executable, "-c", code_or_module, *args]
    else:
        cmd = [sys.executable, "-m", code_or_module, *args]
    return subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                          text=True, timeout=timeout)


class TestEndToEnd:
    def test_disarmed_run_identical_to_build_without_chaos(
        self, tmp_path
    ):
        """THE acceptance identity: a disarmed run's stdout (numbers
        masked) and JSONL record-kind sequence are byte-identical to a
        run where the chaos package cannot even be imported."""
        blocked = (
            "import sys\n"
            "class Block:\n"
            "    def find_spec(self, name, path=None, target=None):\n"
            "        if name.startswith('tpu_mpi_tests.chaos'):\n"
            "            raise ImportError('chaos layer removed')\n"
            "sys.meta_path.insert(0, Block())\n"
            "from tpu_mpi_tests.workloads.daxpy import main\n"
            "sys.exit(main(sys.argv[1:]))\n"
        )
        plain = (
            "import sys\n"
            "from tpu_mpi_tests.workloads.daxpy import main\n"
            "sys.exit(main(sys.argv[1:]))\n"
        )
        outs = []
        for code, jsonl in ((blocked, tmp_path / "a.jsonl"),
                            (plain, tmp_path / "b.jsonl")):
            r = _run(code, ["--fake-devices", "2", "--n", "4096",
                            "--telemetry", "--jsonl", str(jsonl)])
            assert r.returncode == 0, r.stderr[-2000:]
            outs.append(r.stdout)
        mask = re.compile(r"[0-9][0-9.e+-]*")

        def masked(s):
            return [mask.sub("#", ln) for ln in s.splitlines()
                    if not ln.startswith("MANIFEST")]  # git sha varies

        assert masked(outs[0]) == masked(outs[1])
        kinds = [
            [json.loads(ln).get("kind") for ln in open(p)]
            for p in (tmp_path / "a.jsonl", tmp_path / "b.jsonl")
        ]
        assert kinds[0] == kinds[1]
        assert "chaos" not in kinds[1]

    def test_wedge_leg_watchdog_convicts_and_dump_matches_jsonl(
        self, tmp_path
    ):
        """Single-process wedge: the injected stall fires the hang
        watchdog; the doctor convicts wedge on rank 0; the fire dump's
        event tail matches the JSONL stream (the driver-level half of
        the fidelity satellite)."""
        jsonl = tmp_path / "wedge.jsonl"
        r = _run(
            "tpu_mpi_tests.drivers.stencil1d",
            ["--fake-devices", "2", "--n-global", "65536",
             "--overlap", "1", "--overlap-iters", "12", "--telemetry",
             "--deadline", "5", "--jsonl", str(jsonl)],
            chaos="wedge:op=halo_exchange:after=3:stall_s=60",
        )
        assert r.returncode == 9, (r.stdout, r.stderr[-2000:])
        assert "WATCHDOG" in r.stderr
        (f,) = diagnose.diagnose_files([str(jsonl)])
        assert f["class"] == "wedge" and f["rank"] == 0
        # dump tail vs JSONL tail: same events, same order
        m = re.search(r"comm ops \(newest last\):\n((?:\s+.*\n)+?)"
                      r"\s+(?:memory at fire:|aborting)", r.stderr)
        assert m, r.stderr
        dumped = [ln.strip().split()[0]
                  for ln in m.group(1).strip().splitlines()]
        recs = [json.loads(ln) for ln in open(jsonl)]
        stream = [x.get("op") if x["kind"] == "span" else x.get("note")
                  for x in recs if x.get("kind") in ("span", "dispatch")]
        assert dumped == [s.split()[0] for s in stream[-len(dumped):]]

    def test_oom_leg_ramp_convicts(self, tmp_path):
        jsonl = tmp_path / "oom.jsonl"
        r = _run(
            "tpu_mpi_tests.drivers.daxpy",
            ["--fake-devices", "2", "--n", "1048576", "--iters", "20",
             "--telemetry", "--memwatch", "--mem-interval", "0.05",
             "--jsonl", str(jsonl)],
            chaos="oom:step_mb=8:limit_mb=48:frac=0.8",
        )
        assert r.returncode == inject.OOM_EXIT, r.stderr[-2000:]
        (f,) = diagnose.diagnose_files([str(jsonl)])
        assert f["class"] == "oom" and f["rank"] == 0

    def test_bad_spec_fails_fast(self, tmp_path):
        r = _run(
            "tpu_mpi_tests.drivers.daxpy",
            ["--fake-devices", "2", "--n", "4096", "--jsonl",
             str(tmp_path / "x.jsonl")],
            chaos="explode:rank=1",
        )
        assert r.returncode == 2
        assert "bad --chaos spec" in r.stdout + r.stderr


# ---------------------------------------------------------------------------
# multi-process legs (real separate processes, native launcher)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(shutil.which("g++") is None,
                    reason="no C++ toolchain for tpumt_run")
class TestMultiProcess:
    @pytest.fixture(scope="class")
    def tpumt_run(self):
        subprocess.run(
            ["make", "-C", str(REPO / "native"), "tpumt_run"],
            capture_output=True, check=True, timeout=120,
        )
        return str(LAUNCHER)

    def _launch(self, tpumt_run, nprocs, *cmd, chaos, out_prefix,
                timeout=240):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["TPU_MPI_CHAOS"] = chaos
        env["PYTHONPATH"] = str(REPO) + (
            ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        proc = subprocess.Popen(
            [tpumt_run, "-n", str(nprocs), "-o", str(out_prefix),
             "--", *cmd],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=REPO, env=env, start_new_session=True,
        )
        try:
            stdout, stderr = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, 9)
            stdout, stderr = proc.communicate()
            pytest.fail(f"launcher timed out; partial:\n{stdout}\n"
                        f"{stderr}")
        return proc.returncode

    def test_kill_leg_convicts_missing_rank(self, tpumt_run, tmp_path):
        """A rank killed mid-run across REAL processes: its stream
        truncates without close markers while the survivor records on
        — the doctor names the dead rank."""
        jsonl = tmp_path / "kill.jsonl"
        rc = self._launch(
            tpumt_run, 2, sys.executable, "-c", FAST_EXIT_DAXPY,
            "--fake-devices", "1", "--n", "8388608", "--iters", "120",
            "--telemetry", "--memwatch", "--mem-interval", "0.05",
            "--jsonl", str(jsonl),
            chaos="kill:rank=1:phase=kernel:after=10",
            out_prefix=tmp_path / "kill-out-",
        )
        assert rc == inject.KILL_EXIT
        (f,) = diagnose.diagnose_files([str(jsonl)])
        assert f["class"] == "missing_rank" and f["rank"] == 1
        assert f["phase"] == "kernel"

    def test_straggler_leg_convicts_slow_rank(self, tpumt_run,
                                              tmp_path):
        jsonl = tmp_path / "strag.jsonl"
        rc = self._launch(
            tpumt_run, 2, sys.executable, "-m",
            "tpu_mpi_tests.drivers.daxpy",
            "--fake-devices", "1", "--n", "1048576", "--iters", "40",
            "--telemetry", "--memwatch", "--mem-interval", "0.05",
            "--jsonl", str(jsonl),
            chaos="straggler:rank=1:delay_ms=25",
            out_prefix=tmp_path / "strag-out-",
        )
        assert rc == 0
        (f,) = diagnose.diagnose_files([str(jsonl)])
        assert f["class"] == "straggler" and f["rank"] == 1

    def test_wedge_dump_fidelity_on_dying_rank(self, tpumt_run,
                                               tmp_path):
        """Multi-process half of the fidelity satellite: rank 1 wedges
        (dispatch note, no completion), its own deadline watchdog
        dumps, and the dump tail matches rank 1's JSONL stream while
        rank 0 finishes untouched. (A true killed-peer dump on the
        SURVIVOR needs cross-process collectives, which this image's
        CPU backend lacks — on real pods the kill path produces it.)"""
        jsonl = tmp_path / "wedge.jsonl"
        rc = self._launch(
            tpumt_run, 2, sys.executable, "-c", KEEPALIVE_DAXPY,
            "--fake-devices", "1", "--n", "1048576", "--iters", "40",
            "--telemetry", "--deadline", "4", "--jsonl", str(jsonl),
            chaos="wedge:rank=1:phase=kernel:after=3:stall_s=60",
            out_prefix=tmp_path / "wedge-out-",
        )
        assert rc == 9  # rank 1's watchdog hard-exit
        out0 = (tmp_path / "wedge-out-0.txt").read_text()
        out1 = (tmp_path / "wedge-out-1.txt").read_text()
        assert "SUM = " in out0  # rank 0 unaffected
        assert "WATCHDOG" in out1 and "chaos:wedge" in out1
        m = re.search(r"comm ops \(newest last\):\n((?:\s+.*\n)+?)"
                      r"\s+(?:memory at fire:|aborting)", out1)
        assert m, out1
        dumped = [ln.strip().split()[0]
                  for ln in m.group(1).strip().splitlines()]
        recs = [json.loads(ln)
                for ln in open(tmp_path / "wedge.p1.jsonl")]
        stream = [x.get("note") or x.get("op") for x in recs
                  if x.get("kind") in ("span", "dispatch")]
        assert dumped == [s.split()[0] for s in stream[-len(dumped):]]
        (f,) = diagnose.diagnose_files([str(jsonl)])
        assert f["class"] == "wedge" and f["rank"] == 1
