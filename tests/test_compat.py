"""Direct unit coverage for the ``compat.py`` jax-version shims.

Every internal module imports ``shard_map``/``axis_size``/
``pcast_varying``/``tpu_compiler_params`` from ``tpu_mpi_tests.compat``;
when the installed jax drifts past what the shims paper over, the
failure mode used to be mass import/trace errors across the whole suite.
These tests pin each shim's contract on the installed jax so drift
fails HERE, loudly and attributably, first.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_mpi_tests import compat


def test_shard_map_check_vma_spelling(mesh8):
    """The shim accepts the current ``check_vma`` kwarg name on every
    jax version (older jax spells it ``check_rep``)."""
    x = jnp.arange(8.0)

    def body(v):
        return v * 2

    out = compat.shard_map(
        body, mesh=mesh8, in_specs=P("shard"), out_specs=P("shard"),
        check_vma=False,
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 2)


def test_shard_map_default_checking(mesh8):
    """Default (check_vma=True) path traces and runs too — the flag
    rename is the compat risk, not the value."""
    x = jnp.arange(8.0)
    out = compat.shard_map(
        lambda v: v + 1, mesh=mesh8, in_specs=P("shard"),
        out_specs=P("shard"),
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0) + 1)


def test_axis_size_inside_shard_map(mesh8):
    """``axis_size`` resolves the bound mesh axis size inside a
    shard_map body (lax.axis_size on current jax, axis_frame on 0.4.x)."""
    x = jnp.zeros(8)

    def body(v):
        n = compat.axis_size("shard")
        return v + n

    out = compat.shard_map(
        body, mesh=mesh8, in_specs=P("shard"), out_specs=P("shard"),
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 8.0))


def test_pcast_varying_value_preserving(mesh8):
    """``pcast_varying`` must be a value-level identity on every
    version (on new jax it only changes the varying-axes tracking; on
    old jax it IS the identity) — and its output must be consumable by
    a collective over the same axis."""
    from jax import lax

    x = jnp.arange(8.0)

    def body(v):
        cast = compat.pcast_varying(jnp.sum(v), "shard")
        return v + 0 * lax.psum(cast, "shard")

    out = compat.shard_map(
        body, mesh=mesh8, in_specs=P("shard"), out_specs=P("shard"),
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0))


def test_tpu_compiler_params_known_and_unknown_fields():
    """The shim constructs the installed jax's params class; fields it
    knows must round-trip, fields it lacks (older jax) must be dropped,
    not raised — with the repo's real call shape
    (``has_side_effects=True, collective_id=...``, pallas_kernels.py)."""
    pltpu = pytest.importorskip("jax.experimental.pallas.tpu")
    params = compat.tpu_compiler_params(
        has_side_effects=True, collective_id=0
    )
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    assert isinstance(params, cls)
    for field in ("has_side_effects", "collective_id"):
        if hasattr(params, field):
            assert getattr(params, field) in (True, 0)


def test_tpu_compiler_params_rejects_nothing_silently_on_current_api():
    """On a jax new enough to have ``CompilerParams``, unknown-field
    dropping must NOT be active: a typo'd field should raise there (the
    drop path exists only for the legacy class)."""
    pltpu = pytest.importorskip("jax.experimental.pallas.tpu")
    if getattr(pltpu, "CompilerParams", None) is None:
        pytest.skip("legacy TPUCompilerParams: drop path is by design")
    with pytest.raises(TypeError):
        compat.tpu_compiler_params(definitely_not_a_field=1)


def test_exports_match_internal_consumers():
    """The four shim names every internal module imports must exist —
    a rename here is the mass-import-failure mode this file guards."""
    for name in ("shard_map", "axis_size", "pcast_varying",
                 "tpu_compiler_params"):
        assert callable(getattr(compat, name)), name


def test_installed_jax_has_exactly_one_shard_map_home():
    """Sanity on the shim's version probe: whichever branch was taken,
    the wrapped callable is the installed jax's shard_map."""
    if hasattr(jax, "shard_map"):
        assert compat._shard_map is jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as legacy

        assert compat._shard_map is legacy
        assert compat._VMA_FLAG == "check_rep"
