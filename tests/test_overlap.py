"""Overlap-engine gates (ISSUE 7): the interior/boundary seam is
bit-identical across pipeline depths (the same compiled programs run in
both schedules, so equality is structural), the ring prefetch pipeline
is result-invariant at every depth, the collective dispatch window
bounds in-flight chains without changing results, the depth knobs sweep
and persist under the full fingerprint, and the measured
``overlap_frac`` discriminates a pipelined run (> 0) from a serialized
one (exactly 0) all the way through the JSONL → tpumt-report OVERLAP
table → ``--diff`` gate pipeline."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_mpi_tests.arrays.domain import Domain1D
from tpu_mpi_tests.comm import collectives as C
from tpu_mpi_tests.comm import halo as H
from tpu_mpi_tests.instrument import telemetry as T
from tpu_mpi_tests.instrument.aggregate import summarize, _jsonl_metrics
from tpu_mpi_tests.instrument.timers import PhaseTimer, block
from tpu_mpi_tests.kernels.stencil import N_BND, analytic_pairs
from tpu_mpi_tests.tune import registry as tr


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    """Unconfigured tune registry + disabled telemetry around each test
    (the engine records spans; a leaked sink corrupts other tests)."""
    monkeypatch.delenv("TPU_MPI_TUNE_CACHE", raising=False)
    tr.deconfigure()
    T.disable()
    T.registry().reset()
    yield
    tr.deconfigure()
    T.disable()
    T.registry().reset()


EPS = 1e-6


def _jacobi_setup(mesh8, dtype=jnp.float32, n=4096):
    d = Domain1D(n_global=n, n_shards=8, n_bnd=2)
    f, _ = analytic_pairs()["1d"]
    z0 = jnp.asarray(d.init_global(f), dtype)
    fns = H.overlap_jacobi_fns(
        mesh8, "shard", 0, 1, 2, float(d.scale), EPS
    )
    return d, z0, fns


def _run_pipeline(mesh8, z0, fns, depth, n_steps, timer=None):
    ex_fn, core_fn, seam_fn = fns
    runner = H.OverlapRunner(
        "halo_exchange", depth=depth, timer=timer,
        phase="overlap_interior",
    )
    z = C.shard_1d(z0, mesh8)
    for _ in range(n_steps):
        ex, zc = runner.step(ex_fn, core_fn, z)
        z = block(seam_fn(ex, zc))
    return np.asarray(z), runner


# ------------------------------------------------------- seam identity


class TestJacobiSeam:
    def test_depth1_equals_depth2_bitwise(self, mesh8):
        """The acceptance gate: the pipelined schedule is byte-identical
        to the serialized one (same programs, reordered)."""
        _, z0, fns = _jacobi_setup(mesh8)
        d1, _ = _run_pipeline(mesh8, z0, fns, 1, 6)
        d2, _ = _run_pipeline(mesh8, z0, fns, 2, 6)
        np.testing.assert_array_equal(d1, d2)

    def test_depth1_matches_iterate_fused(self, mesh8):
        """The split formulation computes the fused device-chained
        loop's recurrence (exact to roundoff — XLA fuses the
        one-program formulation with different FMA boundaries, so
        bitwise equality is only guaranteed WITHIN the engine)."""
        d, z0, fns = _jacobi_setup(mesh8)
        run = H.iterate_fused_fn(
            mesh8, "shard", 0, 1, 2, float(d.scale), EPS
        )
        ref = np.asarray(block(run(C.shard_1d(z0, mesh8), 6)))
        d1, _ = _run_pipeline(mesh8, z0, fns, 1, 6)
        np.testing.assert_allclose(d1, ref, rtol=1e-6, atol=1e-12)

    def test_overlap_frac_discriminates(self, mesh8):
        """Serialized run: exactly 0 (the exchange drains before the
        phase opens). Pipelined run: > 0 measured wall overlap."""
        _, z0, fns = _jacobi_setup(mesh8)
        _, r1 = _run_pipeline(mesh8, z0, fns, 1, 4)
        _, r2 = _run_pipeline(mesh8, z0, fns, 2, 4)
        assert r1.overlap_frac == 0.0
        assert r1.comm_s == 0.0
        assert r2.overlap_frac > 0.0
        assert r2.comm_s > 0.0

    def test_annotate_attaches_to_phase_record(self, mesh8):
        _, z0, fns = _jacobi_setup(mesh8)
        timer = PhaseTimer()
        _, runner = _run_pipeline(mesh8, z0, fns, 2, 3, timer=timer)
        runner.annotate(timer)
        extras = timer.extras["overlap_interior"]
        assert extras["overlap_frac"] == runner.overlap_frac
        assert extras["overlap_depth"] == 2


class TestHeatSeam:
    @staticmethod
    def _setup(mesh2d):
        import math

        px, py, nxl, nyl = 4, 2, 12, 12
        nx, ny = px * nxl, py * nyl
        dx, dy = 2 * math.pi / nx, 2 * math.pi / ny
        nu = 0.1
        dt = 0.4 / (nu * (1 / dx**2 + 1 / dy**2))
        cx, cy = nu * dt / dx**2, nu * dt / dy**2
        gxs, gys = nxl + 2, nyl + 2
        zg = np.zeros((px * gxs, py * gys), np.float32)
        xs = np.arange(nx) * dx
        ys = np.arange(ny) * dy
        z0 = np.sin(xs)[:, None] * np.sin(ys)[None, :]
        for rx in range(px):
            for ry in range(py):
                zg[rx * gxs + 1:rx * gxs + 1 + nxl,
                   ry * gys + 1:ry * gys + 1 + nyl] = z0[
                    rx * nxl:(rx + 1) * nxl, ry * nyl:(ry + 1) * nyl]
        from jax.sharding import NamedSharding, PartitionSpec as P

        place = NamedSharding(mesh2d, P("x", "y"))
        return zg, place, float(cx), float(cy)

    def test_depths_bitwise_and_fused_close(self, mesh2d):
        zg, place, cx, cy = self._setup(mesh2d)
        ex_fn, core_fn, seam_fn = H.heat_overlap_fns(
            mesh2d, "x", "y", cx, cy
        )

        def run(depth, n):
            runner = H.OverlapRunner("halo_exchange2d", depth=depth)
            z = jax.device_put(zg, place)
            for _ in range(n):
                ex, zc = runner.step(ex_fn, core_fn, z)
                z = block(seam_fn(ex, zc))
            return np.asarray(z)

        d1, d2 = run(1, 5), run(2, 5)
        np.testing.assert_array_equal(d1, d2)
        fused = H.heat_step2d_fn(mesh2d, "x", "y", 1, cx, cy)
        ref = np.asarray(block(fused(jax.device_put(zg, place), 5)))
        np.testing.assert_allclose(d1, ref, rtol=1e-6, atol=1e-7)


class TestGridSeam:
    def test_depths_bitwise_and_step2d_close(self, mesh2d):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpu_mpi_tests.drivers.stencil2d_grid import _init_block

        dx = Domain1D(n_global=4 * 12, n_shards=4)
        dy = Domain1D(n_global=2 * 12, n_shards=2)
        zf, _ = analytic_pairs()["2d_dim0"]
        zg = np.zeros((4 * dx.n_ghosted, 2 * dy.n_ghosted), np.float32)
        for rx in range(4):
            for ry in range(2):
                zg[rx * dx.n_ghosted:(rx + 1) * dx.n_ghosted,
                   ry * dy.n_ghosted:(ry + 1) * dy.n_ghosted] = \
                    _init_block(dx, dy, rx, ry, 4, 2, zf, np.float32)
        zs = jax.device_put(zg, NamedSharding(mesh2d, P("x", "y")))
        ex_fn, core_fn, seam_fn = H.grid_overlap_fns(
            mesh2d, "x", "y", N_BND, float(dx.scale), float(dy.scale)
        )

        def run(depth):
            runner = H.OverlapRunner("halo_exchange2d", depth=depth)
            ex, cores = runner.step(ex_fn, core_fn, zs)
            return block(seam_fn(ex, *cores))

        ax, ay, ares = run(1)
        bx, by, bres = run(2)
        np.testing.assert_array_equal(np.asarray(ax), np.asarray(bx))
        np.testing.assert_array_equal(np.asarray(ay), np.asarray(by))
        assert float(ares) == float(bres)
        step = H.step2d_fn(
            mesh2d, "x", "y", N_BND, float(dx.scale), float(dy.scale)
        )
        rx_, ry_, res_ = block(step(zs))
        # exact-to-roundoff vs the fused program: the frame strips'
        # cancellation amplifies reformulation roundoff by ~scale
        np.testing.assert_allclose(
            np.asarray(ax), np.asarray(rx_), rtol=1e-4, atol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(ay), np.asarray(ry_), rtol=1e-4, atol=1e-3
        )
        np.testing.assert_allclose(float(ares), float(res_), rtol=1e-5)


# ------------------------------------------------------ ring pipelining


class TestRingPipeline:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("depth", [2, 4])
    def test_depth_invariant_bitwise(self, mesh8, causal, depth):
        """The prefetched ring consumes the same block values at every
        step — results must be bit-identical to the depth-1 ring."""
        from tpu_mpi_tests.comm.ring import ring_attention_fn

        key = jax.random.PRNGKey(3)
        q, k, v = (
            jax.random.normal(kk, (64, 16), jnp.float32)
            for kk in jax.random.split(key, 3)
        )
        qs, ks, vs = (C.shard_1d(t, mesh8) for t in (q, k, v))
        base = ring_attention_fn(mesh8, "shard", causal=causal, depth=1)
        piped = ring_attention_fn(
            mesh8, "shard", causal=causal, depth=depth
        )
        np.testing.assert_array_equal(
            np.asarray(base(qs, ks, vs)), np.asarray(piped(qs, ks, vs))
        )

    def test_depth_clamps_to_ring_size(self, mesh8):
        from tpu_mpi_tests.comm.ring import ring_attention_fn

        key = jax.random.PRNGKey(4)
        q, k, v = (
            jax.random.normal(kk, (32, 8), jnp.float32)
            for kk in jax.random.split(key, 3)
        )
        qs, ks, vs = (C.shard_1d(t, mesh8) for t in (q, k, v))
        base = ring_attention_fn(mesh8, "shard", depth=1)
        deep = ring_attention_fn(mesh8, "shard", depth=64)
        np.testing.assert_array_equal(
            np.asarray(base(qs, ks, vs)), np.asarray(deep(qs, ks, vs))
        )


# --------------------------------------------------- dispatch window


class TestDispatchWindow:
    def test_depth1_is_plain_span_call(self, mesh8):
        """Depth 1 must take the per-call sync-honest path: sync spans,
        never async ones."""
        records = []
        T.enable(sink=records.append)
        x = C.shard_1d(jnp.ones((64,), jnp.float32), mesh8)
        win = C.DispatchWindow(1)
        y = win.call("allreduce", lambda a: a, x, nbytes=64, world=8)
        win.drain()
        T.disable()
        spans = [r for r in records if r.get("kind") == "span"]
        assert len(spans) == 1
        assert "async" not in spans[0]
        assert y is x

    def test_bounded_inflight_and_async_spans(self, mesh8):
        records = []
        T.enable(sink=records.append)
        fn = C._allreduce_fn(mesh8, "shard", 1)
        x = C.shard_1d(jnp.ones((8,), jnp.float32), mesh8)
        win = C.DispatchWindow(3)
        for _ in range(7):
            x = win.call("allreduce", fn, x, nbytes=64, world=8)
            # the window may hold at most depth−1 after serving a call
            assert len(win._inflight) <= 2
        win.drain()
        assert not win._inflight
        T.disable()
        spans = [r for r in records if r.get("kind") == "span"]
        assert len(spans) == 7
        assert all(s.get("async") is True for s in spans)
        assert all(s.get("dispatch_depth") == 3 for s in spans)
        # results flowed through the real collective chain
        assert float(np.asarray(x)[0]) == 8.0**7

    def test_window_results_match_direct_chain(self, mesh8):
        fn = C._allreduce_fn(mesh8, "shard", 1)
        x0 = jnp.arange(8, dtype=jnp.float32)
        direct = C.shard_1d(x0, mesh8)
        for _ in range(4):
            direct = fn(direct)
        windowed = C.shard_1d(x0, mesh8)
        with C.DispatchWindow(4) as win:
            for _ in range(4):
                windowed = win.call("allreduce", fn, windowed)
        np.testing.assert_array_equal(
            np.asarray(direct), np.asarray(windowed)
        )

    def test_halo_exchange_window_routing(self, mesh8):
        """halo_exchange(window=...) rides the window (async span);
        window=None stays the per-call sync span — byte-identical
        results either way."""
        d = Domain1D(n_global=256, n_shards=8, n_bnd=2)
        f, _ = analytic_pairs()["1d"]
        z0 = jnp.asarray(d.init_global(f))
        records = []
        T.enable(sink=records.append)
        plain = H.halo_exchange(C.shard_1d(z0, mesh8), mesh8)
        with C.DispatchWindow(2) as win:
            wind = H.halo_exchange(
                C.shard_1d(z0, mesh8), mesh8, window=win
            )
        T.disable()
        np.testing.assert_array_equal(np.asarray(plain), np.asarray(wind))
        spans = [r for r in records if r.get("kind") == "span"
                 and r.get("op") == "halo_exchange"]
        assert len(spans) == 2
        assert "async" not in spans[0]
        assert spans[1].get("async") is True


# ------------------------------------------------- async span telemetry


def test_async_span_record_shape(mesh8):
    records = []
    T.enable(sink=records.append)
    h = T.async_span("demo_op", nbytes=1000, axis_name="shard", world=8,
                     overlap_depth=2)
    x = C.shard_1d(jnp.ones((8,), jnp.float32), mesh8)
    h.done(x)
    h.done(x)  # idempotent: one record
    T.disable()
    spans = [r for r in records if r.get("kind") == "span"]
    assert len(spans) == 1
    s = spans[0]
    assert s["op"] == "demo_op"
    assert s["async"] is True
    assert s["overlap_depth"] == 2
    assert s["t_end"] >= s["t_start"]
    assert s["mono_end"] >= s["mono_start"]
    # counters accumulate like any other span
    assert T.counters()["demo_op"]["ops"] == 1


def test_async_span_inert_when_disabled():
    h = T.async_span("demo_op")
    h.done(None)
    assert h.mono_end >= h.mono_start
    assert T.counters().get("demo_op") is None


# -------------------------------------------------- depth knob tuning


class TestDepthTuning:
    def test_sweep_records_winner_under_full_fingerprint(self, tmp_path):
        from tpu_mpi_tests.tune.fingerprint import fingerprint
        from tpu_mpi_tests.tune.sweep import sweep

        tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)
        records = []
        secs = {1: 0.5, 2: 0.2}

        def measure(cand):
            return secs[int(cand)]

        win = sweep(
            "halo/overlap", measure, emit=records.append,
            dtype="float32", n=65536, world=8,
        )
        assert int(win) == 2
        fp = fingerprint(dtype="float32", n=65536, world=8)
        cache = tr.configured_cache()
        assert cache.lookup("halo/overlap", fp) == 2
        kinds = [r["kind"] for r in records]
        assert kinds.count("tune") == 2 and "tune_result" in kinds
        assert all(r["fingerprint"] == fp for r in records
                   if r["kind"] == "tune")
        # resolution now serves the tuned depth
        assert H.resolve_overlap_depth(
            None, dtype="float32", n=65536, world=8
        ) == 2

    def test_resolution_precedence_and_prior(self, tmp_path):
        # unconfigured: prior (1) — byte-identical to the pre-overlap era
        assert H.resolve_overlap_depth(None, dtype="x", n=1, world=8) == 1
        assert C.resolve_dispatch_depth(None, dtype="x", n=1) == 1
        from tpu_mpi_tests.comm.ring import _resolve_pipeline_depth

        assert _resolve_pipeline_depth(None, dtype="x", lq=8) == 1
        # explicit always wins
        assert H.resolve_overlap_depth(2) == 2
        assert C.resolve_dispatch_depth(4) == 4
        assert _resolve_pipeline_depth(4) == 4

    def test_malformed_cache_degrades_to_prior(self, tmp_path):
        from tpu_mpi_tests.tune.fingerprint import fingerprint

        tr.configure(cache_path=str(tmp_path / "t.json"))
        cache = tr.configured_cache()
        fp = fingerprint(dtype="float32", n=64, world=8)
        cache.store("halo/overlap", fp, "garbage")
        cache.store("coll/dispatch_depth", fp, {"not": "an int"})
        assert H.resolve_overlap_depth(
            None, dtype="float32", n=64, world=8
        ) == 1
        assert C.resolve_dispatch_depth(
            None, dtype="float32", n=64, world=8
        ) == 1

    def test_spaces_declared_with_unoverlapped_priors(self):
        spaces = tr.spaces()
        for knob in ("halo/overlap", "ring/pipeline_depth",
                     "coll/dispatch_depth"):
            assert knob in spaces, knob
            assert spaces[knob].prior == 1, knob


def test_serve_halo_handler_uses_tuned_window(tmp_path, mesh8):
    """Satellite 2: the serve-mode halo factory resolves the tuned
    dispatch depth like any other knob — a warmed cache entry makes
    steady-state traffic dispatch through the window (async spans),
    while the unconfigured prior keeps today's per-call sync path."""
    from tpu_mpi_tests.drivers import _common
    from tpu_mpi_tests.tune.fingerprint import device_fingerprint

    tr.configure(cache_path=str(tmp_path / "t.json"))
    tr.configured_cache().store(
        "coll/dispatch_depth", device_fingerprint(), 3
    )
    records = []
    T.enable(sink=records.append)
    step = _common.workload_factory("halo")(mesh8, (256,), "float32")
    records.clear()  # drop the warmup batch's spans
    step(4)
    T.disable()
    spans = [r for r in records if r.get("kind") == "span"
             and r.get("op") == "halo_exchange"]
    assert len(spans) == 4
    assert all(s.get("async") is True for s in spans)
    assert all(s.get("dispatch_depth") == 3 for s in spans)


# ------------------------------------- report / diff / trace pipeline


class TestOverlapReporting:
    @staticmethod
    def _run_driver(tmp_path, name, depth):
        from tpu_mpi_tests.drivers import stencil1d

        out = tmp_path / f"{name}.jsonl"
        rc = stencil1d.main([
            "--n-global", "4096", "--dtype", "float64",
            "--overlap", str(depth), "--overlap-iters", "4",
            "--telemetry", "--jsonl", str(out),
        ])
        assert rc == 0
        return out

    def test_driver_records_and_report_table(self, tmp_path, capsys):
        """The acceptance pipeline: a depth-2 fake-device run produces
        a merged timeline whose overlap_frac > 0 while the depth-1 run
        reports exactly 0 — and the OVERLAP table renders both."""
        d1 = self._run_driver(tmp_path, "d1", 1)
        d2 = self._run_driver(tmp_path, "d2", 2)
        capsys.readouterr()

        s1 = summarize([str(d1)])
        s2 = summarize([str(d2)])
        assert s1["overlap"]["halo"]["overlap_frac"] == 0.0
        assert s1["overlap"]["halo"]["depth"] == 1
        assert s2["overlap"]["halo"]["overlap_frac"] > 0.0
        assert s2["overlap"]["halo"]["depth"] == 2
        # the annotated phase record carries the frac too
        assert s2["phases"]["overlap_interior"]["overlap_frac"] > 0.0
        assert s1["phases"]["overlap_interior"]["overlap_frac"] == 0.0

        from tpu_mpi_tests.instrument import aggregate

        for f in (d1, d2):
            assert aggregate.main([str(f)]) == 0
        out = capsys.readouterr().out
        assert "OVERLAP halo: depth=1 frac=0.000" in out
        assert "OVERLAP halo: depth=2 frac=" in out

    def test_diff_gates_reserialization(self, tmp_path, capsys):
        """A pipeline that silently re-serializes (frac → 0) must fail
        the --diff noise-band gate."""
        d1 = self._run_driver(tmp_path, "d1", 1)
        d2 = self._run_driver(tmp_path, "d2", 2)
        capsys.readouterr()
        from tpu_mpi_tests.instrument.aggregate import diff_main

        rc = diff_main(str(d2), str(d1))
        out = capsys.readouterr().out
        assert rc == 1
        assert "overlap:halo:frac" in out
        assert "REGRESSION" in out

    def test_trace_carries_async_span(self, tmp_path):
        d2 = self._run_driver(tmp_path, "d2", 2)
        from tpu_mpi_tests.instrument.timeline import chrome_trace

        doc = chrome_trace([str(d2)])
        spans = [e for e in doc["traceEvents"]
                 if e["ph"] == "X" and e["name"] == "halo_exchange"
                 and e["args"].get("overlap_depth") == 2]
        assert spans, "pipelined exchange spans must reach the timeline"
        assert all(e["args"].get("async") is True for e in spans)

    def test_bench_rows_become_gated_series(self):
        recs = [
            {"kind": "attn", "tier": "ring", "stripe": False,
             "tflops": 1.5},
            {"kind": "attn", "tier": "ring", "stripe": False,
             "tflops": 1.7},
            {"kind": "heat", "steps_per_s": 650.0},
            {"kind": "overlap", "op": "heat2d", "depth": 2,
             "overlap_frac": 0.8, "comm_s": 0.1, "compute_s": 0.2,
             "steps": 10, "steps_per_s": 650.0},
        ]
        import os
        import tempfile

        with tempfile.NamedTemporaryFile(
            "w", suffix=".jsonl", delete=False
        ) as fh:
            for r in recs:
                fh.write(json.dumps(r) + "\n")
            path = fh.name
        try:
            s = summarize([path])
            assert s["bench"]["attn:ring:tflops"]["value"] == \
                pytest.approx(1.6)
            assert s["bench"]["heat:steps_per_s"]["value"] == 650.0
            assert s["overlap"]["heat2d"]["rate"] == 650.0
            m = _jsonl_metrics([path])
            assert m["bench:attn:ring:tflops"]["higher_better"] is True
            assert m["overlap:heat2d:frac"]["value"] == \
                pytest.approx(0.8)
            assert m["overlap:heat2d:rate"]["value"] == 650.0
        finally:
            os.unlink(path)


# -------------------------------------------------- driver overlap modes


class TestDriverOverlapModes:
    def test_heat2d_overlap_eigen_gate(self, capsys):
        from tpu_mpi_tests.drivers import heat2d

        rc = heat2d.main([
            "--nx-local", "12", "--ny-local", "12", "--n-steps", "30",
            "--overlap", "2",
        ])
        out = capsys.readouterr().out
        assert rc == 0
        assert "OVERLAP heat2d depth=2" in out
        assert "overlap_frac=" in out

    def test_heat2d_overlap_requires_xla_per_step(self, capsys):
        from tpu_mpi_tests.drivers import heat2d

        with pytest.raises(SystemExit):
            heat2d.main([
                "--overlap", "2", "--kernel", "pallas",
            ])

    def test_grid_overlap_err_gate(self, capsys):
        from tpu_mpi_tests.drivers import stencil2d_grid

        rc = stencil2d_grid.main([
            "--nx-local", "12", "--ny-local", "12", "--n-iter", "4",
            "--n-warmup", "1", "--overlap", "2",
        ])
        out = capsys.readouterr().out
        assert rc == 0
        assert "OVERLAP stencil2d_grid depth=2" in out

    def test_stencil1d_overlap_seam_gate(self, capsys):
        from tpu_mpi_tests.drivers import stencil1d

        rc = stencil1d.main([
            "--n-global", "4096", "--dtype", "float64",
            "--overlap", "2", "--overlap-iters", "4",
        ])
        out = capsys.readouterr().out
        assert rc == 0
        assert "OVERLAP halo depth=2" in out
        assert "OVERLAP FAIL" not in out

    def test_stencil1d_overlap_auto_tune_sweeps(self, tmp_path, capsys):
        """--overlap auto --tune: a cache miss sweeps the depth
        candidates, persists the winner, and a rerun is a pure hit."""
        from tpu_mpi_tests.drivers import stencil1d

        cache = tmp_path / "cache.json"
        argv = [
            "--n-global", "4096", "--dtype", "float64",
            "--overlap", "auto", "--overlap-iters", "4",
            "--tune", "--tune-cache", str(cache),
            "--jsonl", str(tmp_path / "r1.jsonl"),
        ]
        assert stencil1d.main(argv) == 0
        doc = json.loads(cache.read_text())
        assert any(k.startswith("halo/overlap|") for k in doc["entries"])
        argv2 = argv[:-1] + [str(tmp_path / "r2.jsonl")]
        assert stencil1d.main(argv2) == 0
        recs = [json.loads(line) for line in
                (tmp_path / "r2.jsonl").read_text().splitlines()]
        kinds = [r.get("kind") for r in recs]
        assert "tune_hit" in kinds
        hit_knobs = {r["knob"] for r in recs
                     if r.get("kind") == "tune_hit"}
        assert "halo/overlap" in hit_knobs
