"""Pure-numpy semantics tests for tpu/microbench.py's shared helpers.

The measurement groups themselves are hardware-only (chained device
loops), but the grid-validity logic both stripe groups share is pure
numpy and its contract is load-bearing: a suspect grid must invalidate
derived rows (BASELINE's OUTLIER-SUSPECT / NaN-cell discipline), and
the stripeskip best-arm pick must never report an unmeasured grid as
the winner.
"""

import importlib.util
import os

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_microbench():
    spec = importlib.util.spec_from_file_location(
        "tpumt_microbench", os.path.join(_REPO, "tpu", "microbench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


MB = _load_microbench()


def test_paced_with_suspect_clean_grid():
    t = np.full((4, 4), 1e-3)
    paced, note, suspect = MB._paced_with_suspect(t)
    assert not suspect
    assert note == ""
    assert abs(paced - 4e-3) < 1e-12  # sum over steps of max over ranks


def test_paced_with_suspect_nan_cell():
    """A double-failed cell (NaN after the retry) must both poison the
    paced sum AND flag the grid — silently dropping it from the stats
    (NaN > 0 is False) was the reviewed-out failure mode."""
    t = np.full((4, 4), 1e-3)
    t[1, 2] = np.nan
    paced, note, suspect = MB._paced_with_suspect(t)
    assert suspect
    assert "NaN" in note
    assert np.isnan(paced)


def test_paced_with_suspect_outlier_cell():
    """A lone live cell >5x the grid median marks the grid
    OUTLIER-SUSPECT (the contention-spike self-identification that
    invalidated a round-4 stripebalance replicate grid)."""
    t = np.full((4, 4), 1e-3)
    t[2, 3] = 10e-3
    paced, note, suspect = MB._paced_with_suspect(t)
    assert suspect
    assert "OUTLIER-SUSPECT" in note
    # the paced proxy itself is still finite — only derived
    # cross-grid rows are invalidated by the flag
    assert np.isfinite(paced)


def test_paced_with_suspect_zero_cells_ignored():
    """Geometrically-dead cells are stored as exact 0 and excluded from
    the outlier statistics (the contig grid's dead-future cells)."""
    t = np.full((4, 4), 1e-3)
    t[0, 1:] = 0.0  # dead cells
    paced, note, suspect = MB._paced_with_suspect(t)
    assert not suspect
    assert np.isfinite(paced)


def test_best_finite_arm_skips_nan():
    """The stripeskip best-arm pick must never report a NaN
    (unmeasured) arm as the winner — plain min() over a dict with a NaN
    value can, because NaN comparisons are always False."""
    assert MB._best_finite_arm({128: np.nan, 256: 2e-3, 512: 3e-3}) == 256
    # NaN first in iteration order is the case plain min() gets wrong
    assert MB._best_finite_arm({128: np.nan, 256: np.nan, 512: 1.0}) == 512
    assert MB._best_finite_arm({128: np.nan}) is None
    assert MB._best_finite_arm({}) is None
    assert MB._best_finite_arm({128: 3e-3, 256: 1e-3}) == 256
