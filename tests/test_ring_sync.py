"""Executed coverage of the RDMA ring synchronization logic (VERDICT r3
next #1).

The hand ring collectives carry an entry neighborhood barrier and (for the
reduce-scatter) a 1-credit receiver-backpressure handshake protecting the
single-slot ``comm_ref``. Under the plain bool interpreter those lines are
compiled out (devices serialize; remote signals are unimplemented), so
until round 4 the one correctness-critical synchronization path in the
repo had zero executed coverage — the reference, by contrast, runs its
multi-rank exchanges under MPI's real runtime with per-request error
reporting (``mpi_stencil2d_gt.cc:230-247``) on routine 12-rank allocations
(``summit/job.lsf:9-16``).

These tests run the REAL synchronization under JAX's simulated
multi-device TPU interpreter (``pltpu.InterpretParams``): one thread per
simulated device, shared-memory semaphores, simulated remote DMA, and
vector-clock race detection. Because the detector is happens-before based,
a missing synchronization edge is flagged on EVERY run — independent of
how the threads actually interleave — which is strictly stronger than
timing-based skew stress. Coverage:

- reduce-scatter / allgather / allreduce / halo at non-loopback
  w ∈ {4, 8} with ``use_barrier=True`` / ``use_handshake=True`` actually
  executing: results exact, no race reported;
- the negative control: with the handshake force-disabled
  (``unsafe_no_handshake=True``) the detector DOES report the comm-slot
  hazard the handshake exists to close — proof the detector sees this
  hazard class, so the green runs above are evidence, not vacuity.

MAINTENANCE CONTRACT (VERDICT r4 weak #6): ``_races`` below imports a
PRIVATE JAX surface (``jax._src.pallas.mosaic.interpret``) — a JAX bump
that renames the module trips its assert loudly, but a bump that changes
the FLAG SEMANTICS (e.g. ``detect_races`` silently becoming a no-op)
would not. The negative control
(``test_reduce_scatter_without_handshake_races``) is the CANARY for
exactly that failure: a silently-dead detector fails it, because it
asserts a race IS reported. Therefore these tests must stay
UNSKIPPABLE — never add ``importorskip``/``skipif`` around the private
import; if the surface moves, fix ``_races``, don't skip the suite.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from tpu_mpi_tests.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.pallas import tpu as pltpu

from tpu_mpi_tests.comm import collectives as C
from tpu_mpi_tests.kernels import collectives_pallas as CP
from tpu_mpi_tests.kernels import pallas_kernels as PK

# happens-before analysis is interleaving-independent, so one schedule
# seed suffices; on_wait matches hardware DMA-completion semantics
SIM = pltpu.InterpretParams(detect_races=True, dma_execution_mode="on_wait")


def _races():
    """The interpreter's race-detection state for the LAST simulated run.

    Private JAX surface (no public getter exists); the import is kept in
    one place so a future rename breaks exactly one helper.
    """
    from jax._src.pallas.mosaic.interpret import interpret_pallas_call as ipc

    assert ipc.races is not None, (
        "no simulated-interpret run recorded race state — did the kernel "
        "actually run under InterpretParams?"
    )
    return ipc.races


def _reset_sim():
    pltpu.reset_tpu_interpret_mode_state()


def _mesh(w: int) -> Mesh:
    devs = jax.devices()
    assert len(devs) >= w, f"suite needs {w} fake devices"
    return Mesh(np.array(devs[:w]), ("shard",))


@pytest.mark.parametrize("credits", [1, 2])
@pytest.mark.parametrize("w", [4, 8])
def test_reduce_scatter_handshake_executes_race_free(w, credits):
    """Barrier + receiver-backpressure handshake RUN at non-loopback w;
    exact + clean. credits=2 is the double-buffered pod-latency variant
    (two comm slots, per-parity recv semaphores): its wall-clock benefit
    needs real multi-chip skew, but its CORRECTNESS executes here —
    ready for pod validation, closing the round-3 analysis item."""
    _reset_sim()
    mesh = _mesh(w)
    rows = w * 8  # per-shard rows: w chunks × sublane(8)
    per_rank = (
        np.arange(w * rows * 8, dtype=np.float32).reshape(w, rows, 8) % 53
    )

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("shard"), out_specs=P("shard"),
        check_vma=False,
    )
    def rs(x):
        return PK.ring_reduce_scatter_pallas(
            x[0], axis_name="shard", interpret=SIM, credits=credits
        )[None]

    got = np.asarray(rs(C.shard_1d(jnp.asarray(per_rank), mesh)))
    want = per_rank.sum(axis=0).reshape(w, rows // w, 8)
    assert np.array_equal(got, want)
    assert not _races().races_found


@pytest.mark.parametrize("credits", [1, 2])
def test_reduce_scatter_without_handshake_races(credits):
    """Negative control: the comm-slot hazard IS detected when the
    handshake is disabled — the detector sees the hazard class the green
    runs rely on. credits=2 without credits races too (writes s and s+2
    share a slot with run-ahead unbounded — the round-3 analysis of why
    a naive double-buffer is not a fix, now executed)."""
    _reset_sim()
    w = 8
    mesh = _mesh(w)
    rows = w * 8
    x = np.arange(w * rows * 8, dtype=np.float32).reshape(w, rows, 8)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("shard"), out_specs=P("shard"),
        check_vma=False,
    )
    def rs(x):
        return PK.ring_reduce_scatter_pallas(
            x[0], axis_name="shard", interpret=SIM,
            unsafe_no_handshake=True, credits=credits,
        )[None]

    out = np.asarray(rs(C.shard_1d(jnp.asarray(x), mesh)))
    assert out.shape == (w, rows // w, 8)  # value undefined under a race
    assert _races().races_found, (
        "handshake-off run reported no race: either the simulator stopped "
        "modeling cross-device DMA ordering or the kernel no longer has "
        "the single-slot hazard the handshake was built for"
    )
    _reset_sim()  # don't leak the intentional race into later asserts


@pytest.mark.parametrize("w", [4, 8])
def test_allgather_barrier_executes_race_free(w):
    _reset_sim()
    mesh = _mesh(w)
    rows = 8 * w
    full = np.arange(rows * 8, dtype=np.float32).reshape(rows, 8)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("shard"), out_specs=P("shard"),
        check_vma=False,
    )
    def ag(x):
        out = PK.ring_allgather_pallas(x, axis_name="shard", interpret=SIM)
        # hand back a RECEIVED region — rank r's own block (region r) is
        # seeded locally and never touched by any incoming DMA, so
        # returning it would verify zero communicated bytes; region
        # (r+1) mod w arrives on the ring's LAST hop (w−1 forwards), the
        # longest communicated path
        r = jax.lax.axis_index("shard")
        n = out.shape[0] // w
        nxt = jax.lax.rem(r + 1, jnp.int32(w))
        return jax.lax.dynamic_slice_in_dim(out, nxt * n, n, axis=0)

    got = np.asarray(ag(jnp.asarray(full)))
    # rank r returned block r+1 (mod w): the blocks of `full` rolled up one
    want = np.roll(full.reshape(w, rows // w, 8), -1, axis=0)
    assert np.array_equal(got.reshape(w, rows // w, 8), want)
    assert not _races().races_found


def test_allreduce_chain_race_free(mesh8):
    """reduce-scatter → allgather chained (the full hand allreduce) with
    both kernels' sync enabled. The interpreter re-creates its race state
    per interpreted pallas_call, so the stages run as separate calls with
    the race assert after EACH — a single end-of-chain assert would only
    cover the allgather. The comm-layer wrapper is exercised too (its
    race assert covers the final kernel only)."""
    _reset_sim()
    w = 8
    rows = w * 8
    per_rank = (
        np.arange(w * rows * 8, dtype=np.float32).reshape(w, rows, 8) % 31
    ) - 15.0

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh8, in_specs=P("shard"), out_specs=P("shard"),
        check_vma=False,
    )
    def rs(x):
        return PK.ring_reduce_scatter_pallas(
            x[0], axis_name="shard", interpret=SIM
        )[None]

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh8, in_specs=P("shard"), out_specs=P("shard"),
        check_vma=False,
    )
    def ag(x):
        out = PK.ring_allgather_pallas(
            x[0], axis_name="shard", interpret=SIM, collective_id=11
        )
        r = jax.lax.axis_index("shard")
        n = out.shape[0] // w
        nxt = jax.lax.rem(r + 1, jnp.int32(w))
        return jax.lax.dynamic_slice_in_dim(out, nxt * n, n, axis=0)[None]

    scattered = rs(C.shard_1d(jnp.asarray(per_rank), mesh8))
    want_rs = per_rank.sum(axis=0).reshape(w, rows // w, 8)
    assert np.array_equal(np.asarray(scattered), want_rs)
    assert not _races().races_found  # reduce-scatter stage

    _reset_sim()
    gathered = np.asarray(ag(scattered))
    # rank r returned reduced chunk r+1 (mod w), received on the last hop
    assert np.array_equal(gathered, np.roll(want_rs, -1, axis=0))
    assert not _races().races_found  # allgather stage

    # wrapper threading smoke: full allreduce through the comm layer
    _reset_sim()
    L = w * 1024  # the w·128·sublane f32 1-D ring unit
    flat = (np.arange(w * L, dtype=np.float32).reshape(w, L) % 13) - 6.0
    got = np.asarray(
        C.allreduce_rdma(
            C.shard_1d(jnp.asarray(flat), mesh8), mesh8, interpret=SIM
        )
    )
    assert np.array_equal(got, np.broadcast_to(flat.sum(0), got.shape))
    assert not _races().races_found  # final (allgather) kernel of the chain


@pytest.mark.parametrize("periodic", [False, True])
def test_halo_hardware_path_race_free(mesh8, periodic):
    """ring_halo_pallas under the simulator runs the HARDWARE path —
    conditional sends + entry barrier (symmetric fallback off) — and
    matches the ppermute exchange."""
    from tpu_mpi_tests.comm.halo import Staging, halo_exchange

    _reset_sim()
    n_bnd = 2
    gx = 8 * (8 + 2 * n_bnd)
    z = np.arange(gx * 8, dtype=np.float32).reshape(gx, 8) / (gx * 8)
    # the exchanges donate their input — give each its own placement
    want = np.asarray(
        halo_exchange(
            C.shard_1d(jnp.asarray(z), mesh8), mesh8, axis=0, n_bnd=n_bnd,
            periodic=periodic, staging=Staging.DIRECT,
        )
    )
    got = np.asarray(
        halo_exchange(
            C.shard_1d(jnp.asarray(z), mesh8), mesh8, axis=0, n_bnd=n_bnd,
            periodic=periodic, staging=Staging.PALLAS_RDMA, interpret=SIM,
        )
    )
    assert np.array_equal(got, want)
    assert not _races().races_found


@pytest.mark.parametrize("w", [2, 4])
def test_fused_rdma_executes_race_free(w):
    """ISSUE 15: the one-launch fused halo+stencil kernel under the
    threaded simulator — interior blocks stream while the remote DMAs
    are genuinely in flight, the seam blocks read the arrivals, and the
    vector-clock detector must find NO seam-read/ghost-arrival race:
    the recv-semaphore waits are the happens-before edge. w=2 is the
    ISSUE's named configuration; w=4 adds a ring where neither neighbor
    is also the other neighbor. Result checked exact against the
    chained two-launch tier (the bitwise contract, executed under the
    simulator)."""
    from tpu_mpi_tests.comm.halo import (
        iterate_fused_rdma_fn,
        iterate_pallas_fn,
    )

    _reset_sim()
    mesh = _mesh(w)
    steps, K, nloc = 2, 4, 16
    zg = (
        np.arange(w * (nloc + 2 * K) * 16, dtype=np.float32)
        .reshape(w * (nloc + 2 * K), 16) % 37
    ) / 37.0
    ref = iterate_pallas_fn(
        mesh, "shard", K, 1e-2, axis=0, interpret=True, steps=steps,
        rdma=True,
    )
    want = np.asarray(ref(C.shard_1d(jnp.asarray(zg), mesh, axis=0), 2))
    _reset_sim()
    fused = iterate_fused_rdma_fn(
        mesh, "shard", K, 1e-2, interpret=SIM, steps=steps, tile_rows=8,
    )
    got = np.asarray(fused(C.shard_1d(jnp.asarray(zg), mesh, axis=0), 2))
    assert np.array_equal(got, want)
    assert not _races().races_found


@pytest.mark.parametrize("op", ["gather", "sum"])
@pytest.mark.parametrize("w", [4, 8])
def test_oneshot_collective_executes_race_free(w, op):
    """ISSUE 19: the one-shot in-kernel burst (every rank fires w−1
    remote copies into per-source comm slots in one launch) under the
    threaded simulator — the entry barrier plus the counting recv-sem
    wait are the happens-before edges between each arrival and the
    combine read. Exact against the fixed ascending-src fold / the
    sharded input, and the vector-clock detector must stay clean."""
    _reset_sim()
    mesh = _mesh(w)
    rows = 8  # one f32 sublane tile per shard
    per_rank = (
        np.arange(w * rows * 8, dtype=np.float32).reshape(w, rows, 8)
        % 41
    ) - 20.0

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("shard"), out_specs=P("shard"),
        check_vma=False,
    )
    def run(x):
        if op == "gather":
            return CP.oneshot_allgather_pallas(
                x[0], axis_name="shard", interpret=SIM
            ).reshape(x.shape[1:])[None]
        return CP.oneshot_allreduce_pallas(
            x[0].reshape(-1), axis_name="shard", interpret=SIM
        ).reshape(x.shape[1:])[None]

    got = np.asarray(run(C.shard_1d(jnp.asarray(per_rank), mesh)))
    if op == "gather":
        # every rank holds the full concatenation; shard r of the
        # (w, rows, 8) output is the gathered array's slice r
        want = per_rank.reshape(w * rows, 8).reshape(w, rows, 8)
    else:
        acc = per_rank[0].reshape(-1)
        for r in range(1, w):  # the pinned ascending-src fold order
            acc = acc + per_rank[r].reshape(-1)
        want = np.broadcast_to(acc.reshape(rows, 8), (w, rows, 8))
    assert np.array_equal(got, want)
    assert not _races().races_found


def test_oneshot_without_recv_wait_races():
    """Negative control: with the recv-semaphore waits removed
    (``unsafe_no_recv_wait=True``) the combine reads the comm slots
    with no happens-before edge to the peers' remote writes — the
    detector MUST report it (the gather's comm→out copy exists
    precisely so the skipped wait is an in-kernel RAW hazard, not an
    invisible one)."""
    _reset_sim()
    w = 8
    mesh = _mesh(w)
    x = np.ones((w, 8, 8), np.float32)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("shard"), out_specs=P("shard"),
        check_vma=False,
    )
    def ag(x):
        return CP.oneshot_allgather_pallas(
            x[0], axis_name="shard", interpret=SIM,
            unsafe_no_recv_wait=True,
        ).reshape(x.shape[1:])[None]

    out = np.asarray(ag(C.shard_1d(jnp.asarray(x), mesh)))
    assert out.shape == x.shape  # value undefined under a race
    assert _races().races_found, (
        "recv-wait-off run reported no race: either the simulator "
        "stopped modeling remote-DMA ordering or the one-shot combine "
        "no longer reads the peer landing slots"
    )
    _reset_sim()  # don't leak the intentional race into later asserts


@pytest.mark.parametrize("w", [4, 8])
def test_fused_ring_attention_executes_race_free(w):
    """ISSUE 19 tentpole b: the one-launch fused-RDMA ring attention
    under the threaded simulator — each step's K/V RDMA is genuinely in
    flight under the previous block's matmul, the per-parity recv waits
    and the credit handshake are the happens-before edges, and the
    detector must stay clean. Exact against the serial-interpret run of
    the SAME kernel (identical fold order → bitwise)."""
    _reset_sim()
    mesh = _mesh(w)
    lq, d = 16, 16
    rng = np.random.default_rng(19)
    q, k, v = (
        rng.normal(size=(w * lq, d)).astype(np.float32)
        for _ in range(3)
    )

    def fn(interp):
        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("shard", None),
            out_specs=P("shard", None), check_vma=False,
        )
        def attn(q, k, v):
            return CP.fused_ring_attention_pallas(
                q, k, v, axis_name="shard", interpret=interp
            )

        return attn

    args = tuple(
        C.shard_1d(jnp.asarray(t), mesh) for t in (q, k, v)
    )
    want = np.asarray(fn(True)(*args))  # serial interpret: no threads
    _reset_sim()
    got = np.asarray(fn(SIM)(*args))
    assert np.array_equal(got, want)
    assert not _races().races_found


def test_fused_ring_attention_without_credits_races():
    """Negative control: with the credit handshake disabled
    (``unsafe_no_credits=True``) a fast sender's step-s RDMA can land
    in the parity slot the receiver is still staging from (run-ahead
    ≥ 2 on one of two slots) with no happens-before edge — the
    detector MUST report it. w=8 gives the ring enough run-ahead for
    the two-slot reuse to occur at every interleaving."""
    _reset_sim()
    w = 8
    mesh = _mesh(w)
    lq, d = 16, 16
    z = np.ones((w * lq, d), np.float32)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("shard", None),
        out_specs=P("shard", None), check_vma=False,
    )
    def attn(q, k, v):
        return CP.fused_ring_attention_pallas(
            q, k, v, axis_name="shard", interpret=SIM,
            unsafe_no_credits=True,
        )

    zs = C.shard_1d(jnp.asarray(z), mesh)
    out = np.asarray(attn(zs, zs, zs))
    assert out.shape == z.shape  # value undefined under a race
    assert _races().races_found, (
        "credits-off run reported no race: either the simulator "
        "stopped modeling remote-DMA ordering or the fused kernel no "
        "longer reuses its two comm parity slots"
    )
    _reset_sim()  # don't leak the intentional race into later asserts


def test_fused_rdma_without_seam_wait_races():
    """Negative control: with the recv waits removed
    (``unsafe_no_seam_wait=True``) the seam blocks read the ghost
    landing zone with no happens-before edge to the neighbor's remote
    write — the detector MUST report it, proving the green run above
    covers this hazard class (the ring-suite canary pattern)."""
    from tpu_mpi_tests.comm.halo import iterate_fused_rdma_fn

    _reset_sim()
    w, steps, K, nloc = 2, 2, 4, 16
    mesh = _mesh(w)
    zg = np.ones((w * (nloc + 2 * K), 16), np.float32)
    run = iterate_fused_rdma_fn(
        mesh, "shard", K, 1e-2, interpret=SIM, steps=steps, tile_rows=8,
        unsafe_no_seam_wait=True,
    )
    out = np.asarray(run(C.shard_1d(jnp.asarray(zg), mesh, axis=0), 1))
    assert out.shape == zg.shape  # value undefined under a race
    assert _races().races_found, (
        "seam-wait-off run reported no race: either the simulator "
        "stopped modeling remote-DMA ordering or the fused kernel no "
        "longer reads the ghost landing zone at the seam"
    )
    _reset_sim()  # don't leak the intentional race into later asserts
