"""Driver-level integration tests: run the real main()s on the 8-fake-device
mesh (≅ launching the reference binaries under mpirun -np 8)."""

import re

from tpu_mpi_tests.drivers import envprobe, gather_inplace, mpi_daxpy, mpi_daxpy_nvtx


def test_mpi_daxpy(capsys):
    rc = mpi_daxpy.main(["--n-total", "8192", "--dtype", "float64"])
    out = capsys.readouterr().out
    assert rc == 0
    # 8 per-rank SUM lines, each n(n+1)/2 for n=1024
    sums = re.findall(r"(\d)/8 SUM = ([\d.]+)", out)
    assert len(sums) == 8
    assert all(float(v) == 1024 * 1025 / 2 for _, v in sums)


def test_mpi_daxpy_oversubscription(capsys):
    """32 logical ranks over 8 devices (≅ ranks_per_device > 1,
    mpi_daxpy.cc:49-51)."""
    rc = mpi_daxpy.main(
        ["--n-total", "131072", "--ranks", "32", "--dtype", "float64"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "32 logical ranks over 8 devices (4 ranks/device)" in out
    sums = re.findall(r"(\d+)/32 SUM = ([\d.]+)", out)
    assert len(sums) == 32
    n = 131072 // 32
    assert all(float(v) == n * (n + 1) / 2 for _, v in sums)


def test_mpi_daxpy_nvtx_full_phase_structure(capsys):
    rc = mpi_daxpy_nvtx.main(
        ["--n-per-node", "65536", "--dtype", "float64", "--barrier"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    n = 65536 // 8
    assert out.count("SUM = ") == 9  # 8 local + 1 ALLSUM
    assert f"0/8 ALLSUM = {8 * (n + 1) / 2:f}" in out
    for phase in ("total", "kernel", "barrier", "gather"):
        assert f"TIME {phase} : " in out
    assert "1 nodes, 8 ranks" in out


def test_mpi_daxpy_nvtx_managed_space(capsys):
    rc = mpi_daxpy_nvtx.main(
        ["--n-per-node", "8192", "--dtype", "float64", "--space", "managed"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "ALLSUM" in out


def test_mpi_daxpy_nvtx_device_init_f64(capsys):
    # --init device + --dtype float64 accumulates checksums in f64 on chip
    # (regression: f32 accumulation spuriously failed the tol gate)
    rc = mpi_daxpy_nvtx.main(
        ["--n-per-node", "65536", "--dtype", "float64", "--init", "device"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    n = 65536 // 8
    assert f"ALLSUM = {8 * (n + 1) / 2:f}" in out
    assert "FAIL" not in out


def test_mpi_daxpy_nvtx_f32_tolerance(capsys):
    # float32 path: checksum gate uses tolerance, must still pass
    rc = mpi_daxpy_nvtx.main(["--n-per-node", "65536", "--dtype", "float32"])
    assert rc == 0


def test_gather_inplace_parity(capsys):
    rc = gather_inplace.main(["--n-per-rank", "2048", "--dtype", "float64"])
    out = capsys.readouterr().out
    assert rc == 0
    # rank r local sum (r+1)*n; global sum n*36
    assert "0/8 lsum=2048.0 asum=73728.0" in out
    assert "7/8 lsum=16384.0 asum=73728.0" in out


def test_gather_inplace_rdma_tier(capsys):
    """The hand-written RDMA ring gather passes the same exact parity gate
    as the lax tier (≅ validating a hand MPI_Allgather end to end)."""
    rc = gather_inplace.main(
        ["--n-per-rank", "1024", "--dtype", "float32", "--rdma"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "PARITY FAIL" not in out
    assert "asum=36864.0" in out  # 1024 * 8*9/2


def test_envprobe(capsys, monkeypatch):
    monkeypatch.setenv("MEMORY_PER_CORE", "1024")
    rc = envprobe.main([])
    assert rc == 0
    assert "MEMORY_PER_CORE=1024" in capsys.readouterr().out

    monkeypatch.delenv("MEMORY_PER_CORE")
    rc = envprobe.main([])
    assert rc == 0
    assert "MEMORY_PER_CORE=<not set>" in capsys.readouterr().out
