"""2-D process-grid driver tests (8 fake devices → 2×4 / 4×2 / 8×1 grids)."""

import re

import pytest

from tpu_mpi_tests.drivers import stencil2d_grid

SMALL = ["--nx-local", "16", "--ny-local", "24", "--n-iter", "4",
         "--n-warmup", "2"]


def run_ok(capsys, extra):
    rc = stencil2d_grid.main(SMALL + extra)
    out = capsys.readouterr().out
    assert rc == 0, out
    m = re.search(
        r"GRID TEST px:(\d) py:(\d); ([\d.]+), err_dx=([\d.e+-]+), "
        r"err_dy=([\d.e+-]+)",
        out,
    )
    assert m, out
    return m


def test_auto_mesh_f64(capsys):
    m = run_ok(capsys, ["--dtype", "float64"])
    assert (m.group(1), m.group(2)) == ("2", "4")
    assert float(m.group(4)) < 1e-8 and float(m.group(5)) < 1e-8


@pytest.mark.parametrize("mesh", ["4,2", "8,1", "1,8"])
def test_explicit_meshes(capsys, mesh):
    px, py = mesh.split(",")
    m = run_ok(capsys, ["--dtype", "float64", "--mesh", mesh])
    assert (m.group(1), m.group(2)) == (px, py)
    assert float(m.group(4)) < 1e-8


def test_f32_with_extent_tol(capsys):
    m = run_ok(capsys, ["--dtype", "float32"])
    assert float(m.group(4)) >= 0


def test_tight_tol_fails(capsys):
    rc = stencil2d_grid.main(SMALL + ["--dtype", "float32", "--tol", "1e-20"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "ERR_NORM FAIL grid" in out


def test_bad_mesh_shape(capsys):
    rc = stencil2d_grid.main(SMALL + ["--mesh", "3,2"])
    assert rc != 0


def test_iter_line_emitted(capsys):
    rc = stencil2d_grid.main(SMALL + ["--dtype", "float64"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "step mean=" in out


def test_pallas_kernel_tier(capsys):
    """The streamed dual-derivative Pallas tier must pass the same
    analytic error gates as the XLA tier on the 2x4 grid."""
    m = run_ok(capsys, ["--dtype", "float64", "--kernel", "pallas"])
    assert float(m.group(4)) < 1e-8 and float(m.group(5)) < 1e-8


def test_pallas_width_limit_falls_back_to_xla(capsys):
    """Above the pallas tier's VMEM width limit the driver must fall back
    to XLA with a visible NOTE and still pass the analytic gates."""
    # f64 width past the round-3 calibrated live model at the minimum
    # 8-row block (temps are itemsize-scaled above f32): (4·8·8 +
    # 44·12)·W > the 15 MiB budget
    rc = stencil2d_grid.main([
        "--fake-devices", "8", "--mesh", "2,4", "--nx-local", "16",
        "--ny-local", "23040", "--n-iter", "1", "--n-warmup", "0",
        "--dtype", "float64", "--kernel", "pallas",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "NOTE pallas kernel unavailable, using xla" in out
