import jax.numpy as jnp
import numpy as np
import pytest

from tpu_mpi_tests.arrays.domain import Domain1D, Domain2D
import tpu_mpi_tests.kernels.daxpy as K
from tpu_mpi_tests.kernels.pack import interior, pack_edges, unpack_ghosts
from tpu_mpi_tests.kernels.reductions import err_norm, sum_axis, sum_squares
from tpu_mpi_tests.kernels.stencil import (
    analytic_pairs,
    stencil1d_5,
    stencil2d_1d_5,
)


class TestDaxpy:
    def test_reference_semantics_f64(self):
        # daxpy.cu:56-59,82-87: x=i+1, y=-(i+1), a=2 → y=i+1, SUM=n(n+1)/2
        n = 1024
        x, y = K.init_xy(n, jnp.float64)
        out = K.daxpy(2.0, x, y)
        np.testing.assert_allclose(
            np.asarray(out), np.arange(1, n + 1, dtype=np.float64)
        )
        assert float(out.sum()) == K.expected_checksum(n)

    def test_f32(self):
        x, y = K.init_xy(256, jnp.float32)
        out = K.daxpy(2.0, x, y)
        assert out.dtype == jnp.float32
        assert float(out.sum()) == K.expected_checksum(256)

    def test_bytes(self):
        assert K.daxpy_bytes(1024, jnp.float32) == 3 * 1024 * 4
        assert K.daxpy_bytes(1024, jnp.float64) == 3 * 1024 * 8


class TestStencil1D:
    def test_exact_for_cubic_f64(self):
        # 4th-order stencil is exact for x³ — err is rounding only
        # (the reference's err_norm ≈ 0 gate, mpi_stencil_gt.cc:222)
        d = Domain1D(n_global=256, n_shards=1, n_bnd=2)
        f, df = analytic_pairs()["1d"]
        yg = jnp.asarray(d.init_shard(f, 0))
        dydx = stencil1d_5(yg, scale=d.scale)
        expected = df(np.asarray(d.interior_coords(0)))
        assert float(err_norm(dydx, jnp.asarray(expected))) < 1e-9

    def test_convergence_for_nonpolynomial(self):
        # sin(x): error should drop ~16x per grid doubling (4th order)
        errs = []
        for n in (64, 128):
            d = Domain1D(n_global=n, n_shards=1, n_bnd=2, length=2 * np.pi)
            yg = jnp.asarray(d.init_shard(np.sin, 0))
            dydx = stencil1d_5(yg, scale=d.scale)
            e = np.abs(
                np.asarray(dydx) - np.cos(d.interior_coords(0))
            ).max()
            errs.append(e)
        assert errs[1] < errs[0] / 12  # ~16x for 4th order, slack for const

    def test_too_small_axis_raises(self):
        with pytest.raises(ValueError):
            stencil1d_5(jnp.zeros(4))


class TestStencil2D:
    @pytest.mark.parametrize("dim", [0, 1])
    def test_exact_both_dims(self, dim):
        d = Domain2D(
            n_local_deriv=64, n_global_other=16, n_shards=1, dim=dim, n_bnd=2
        )
        pairs = analytic_pairs()
        f, df = pairs[f"2d_dim{dim}"]
        zg = jnp.asarray(d.init_shard(f, 0))
        dz = stencil2d_1d_5(zg, scale=d.scale, dim=dim)
        expected = jnp.asarray(d.interior_global(df))
        assert dz.shape == expected.shape
        assert float(err_norm(dz, expected)) < 1e-9


class TestPack:
    @pytest.mark.parametrize("axis", [0, 1])
    def test_pack_unpack_roundtrip(self, axis):
        rng = np.random.default_rng(0)
        z = jnp.asarray(rng.standard_normal((12, 10)))
        lo, hi = pack_edges(z, axis=axis, n_bnd=2)
        assert lo.shape[axis] == 2 and hi.shape[axis] == 2
        # neighbor's perspective: my lo edge becomes right neighbor's hi ghost
        z2 = unpack_ghosts(z, hi, lo, axis=axis, n_bnd=2)
        # ghost regions now hold what was packed
        n = z.shape[axis]
        from jax import lax

        np.testing.assert_array_equal(
            np.asarray(lax.slice_in_dim(z2, 0, 2, axis=axis)), np.asarray(hi)
        )
        np.testing.assert_array_equal(
            np.asarray(lax.slice_in_dim(z2, n - 2, n, axis=axis)),
            np.asarray(lo),
        )
        # interior untouched
        np.testing.assert_array_equal(
            np.asarray(interior(z2, axis=axis)),
            np.asarray(interior(z, axis=axis)),
        )

    def test_pack_is_the_manual_test_buf_view(self):
        # ≅ test_buf_view (mpi_stencil2d_sycl.cc:118-159), as a real assert:
        # pack of a known ramp extracts exactly the expected rows
        z = jnp.arange(48.0).reshape(8, 6)
        lo, hi = pack_edges(z, axis=0, n_bnd=2)
        np.testing.assert_array_equal(np.asarray(lo), np.asarray(z[2:4]))
        np.testing.assert_array_equal(np.asarray(hi), np.asarray(z[4:6]))


class TestReductions:
    def test_sum_squares(self):
        x = jnp.asarray([3.0, 4.0])
        assert float(sum_squares(x)) == 25.0

    def test_err_norm_zero_for_equal(self):
        x = jnp.arange(10.0)
        assert float(err_norm(x, x)) == 0.0

    def test_sum_axis(self):
        z = jnp.ones((4, 6))
        np.testing.assert_array_equal(np.asarray(sum_axis(z, 0)), 4 * np.ones(6))
        np.testing.assert_array_equal(np.asarray(sum_axis(z, 1)), 6 * np.ones(4))
