"""Memory observability (instrument/memwatch.py): live-array census,
``kind: "mem"`` record shapes, the no-``memory_stats`` degrade path
(CPU/fake devices), MemWatch sampler + phase hooks, and the end-to-end
driver → JSONL → counter-track pipeline."""

import json
import threading
import time

import pytest

from tpu_mpi_tests.instrument import memwatch
from tpu_mpi_tests.instrument import timeline


def test_census_buckets_by_shape_dtype():
    import jax.numpy as jnp

    a = jnp.ones((128, 8), jnp.float32)
    b = jnp.ones((128, 8), jnp.float32)
    c = jnp.ones((64,), jnp.bfloat16)
    census = memwatch.live_array_census(top_k=8)
    assert census is not None
    by_key = {e["key"]: e for e in census["top"]}
    assert by_key["128x8·float32"]["count"] >= 2
    assert by_key["128x8·float32"]["bytes"] >= 2 * 128 * 8 * 4
    assert by_key["64·bfloat16"]["bytes"] >= 64 * 2
    assert census["count"] >= 3
    assert census["bytes"] >= sum(e["bytes"] for e in census["top"][:2])
    # top is sorted by bytes, descending
    tops = [e["bytes"] for e in census["top"]]
    assert tops == sorted(tops, reverse=True)
    del a, b, c


def test_census_top_k_truncates():
    import jax.numpy as jnp

    keep = [jnp.ones((n + 1,), jnp.float32) for n in range(6)]
    census = memwatch.live_array_census(top_k=2)
    assert len(census["top"]) == 2
    assert census["count"] >= 6  # totals still cover everything
    del keep


def test_mem_record_degrades_to_census_only_on_cpu():
    """CPU/fake devices return None/{} from memory_stats(): the record
    must carry the census and OMIT the watermark fields — absent, not
    zero (the acceptance contract for the no-memory_stats path)."""
    import jax.numpy as jnp

    keep = jnp.ones((256,), jnp.float32)
    assert memwatch.device_memory_stats() == {}
    rec = memwatch.mem_record(event="sample", top_k=4)
    assert rec["kind"] == "mem" and rec["event"] == "sample"
    assert "devices" not in rec
    assert "bytes_in_use" not in rec and "peak_bytes_in_use" not in rec
    assert rec["live_bytes"] >= 256 * 4
    assert rec["t"] == pytest.approx(time.time(), abs=60)
    assert rec["census"]["top"]
    del keep


def test_mem_record_with_fake_device_stats(monkeypatch):
    """Where the backend DOES report stats, the record carries per-device
    watermarks + the cross-device aggregates."""
    monkeypatch.setattr(
        memwatch, "device_memory_stats",
        lambda: {"0": {"bytes_in_use": 100, "peak_bytes_in_use": 150,
                       "bytes_limit": 1000},
                 "1": {"bytes_in_use": 40, "peak_bytes_in_use": 60,
                       "bytes_limit": 1000}},
    )
    rec = memwatch.mem_record(event="phase", phase="kernel")
    assert rec["devices"]["1"]["peak_bytes_in_use"] == 60
    assert rec["bytes_in_use"] == 140
    assert rec["peak_bytes_in_use"] == 150
    assert rec["phase"] == "kernel"


def test_watermark_lines_census_only():
    import jax.numpy as jnp

    keep = jnp.ones((512,), jnp.float32)
    lines = memwatch.watermark_lines(top_k=8)
    text = "\n".join(lines)
    assert "LIVE census:" in text
    assert "512·float32" in text
    del keep


class TestMemWatch:
    def test_sampler_and_lifecycle_records(self):
        records = []
        mw = memwatch.MemWatch(sink=records.append, interval_s=0.03)
        mw.start()
        time.sleep(0.15)
        mw.stop()
        mw.stop()  # idempotent
        events = [r["event"] for r in records]
        assert events[0] == "start" and events[-1] == "final"
        assert events.count("sample") >= 1
        assert all(r["kind"] == "mem" and "t" in r for r in records)
        # census on the start/final records, not on samples
        assert "census" in records[0] and "census" in records[-1]
        assert all("census" not in r for r in records
                   if r["event"] == "sample")

    def test_phase_hooks_emit_first_exit_only(self):
        """A hot-loop phase re-enters thousands of times; the phase
        record is emitted at the FIRST exit (with census) and not again
        unless the peak watermark grows — bounded JSONL by design."""
        from tpu_mpi_tests.instrument.timers import PhaseTimer

        records = []
        mw = memwatch.MemWatch(sink=records.append, interval_s=60.0)
        mw.start()
        try:
            timer = PhaseTimer()
            for _ in range(5):
                with timer.phase("hot"):
                    pass
        finally:
            mw.stop()
        phase_recs = [r for r in records if r.get("event") == "phase"]
        assert len(phase_recs) == 1
        (rec,) = phase_recs
        assert rec["phase"] == "hot"
        assert rec["t_start"] <= rec["t_end"]
        assert "census" in rec

    def test_phase_hooks_detached_after_stop(self):
        from tpu_mpi_tests.instrument import timers
        from tpu_mpi_tests.instrument.timers import PhaseTimer

        records = []
        mw = memwatch.MemWatch(sink=records.append, interval_s=60.0)
        mw.start()
        mw.stop()
        n = len(records)
        timer = PhaseTimer()
        with timer.phase("after"):
            pass
        assert len(records) == n
        assert mw._on_phase not in timers._PHASE_HOOKS

    def test_sink_errors_never_propagate(self):
        def bad_sink(rec):
            raise OSError("disk full")

        mw = memwatch.MemWatch(sink=bad_sink, interval_s=0.02)
        mw.start()
        time.sleep(0.06)
        mw.stop()  # no raise anywhere


def test_phase_hook_error_does_not_break_timer():
    from tpu_mpi_tests.instrument import timers
    from tpu_mpi_tests.instrument.timers import PhaseTimer

    def bad_hook(name, event):
        raise RuntimeError("observer bug")

    timers.add_phase_hook(bad_hook)
    try:
        timer = PhaseTimer()
        with timer.phase("p"):
            pass
        assert timer.counts["p"] == 1
    finally:
        timers.remove_phase_hook(bad_hook)


def test_driver_memwatch_end_to_end(tmp_path, capsys):
    """daxpy --memwatch --telemetry: mem + compile records land in the
    JSONL, merge into a VALID trace with a counter track, and the report
    renders MEMORY + COMPILE tables — the mem-smoke contract, in-suite."""
    from tpu_mpi_tests.drivers import daxpy
    from tpu_mpi_tests.instrument import aggregate

    jl = tmp_path / "run.jsonl"
    tr = tmp_path / "trace.json"
    rc = daxpy.main(
        ["--n", "512", "--dtype", "float32", "--telemetry", "--memwatch",
         "--mem-interval", "0.05", "--jsonl", str(jl),
         "--trace-out", str(tr)]
    )
    assert rc == 0
    recs = [json.loads(ln) for ln in jl.read_text().splitlines()]
    mems = [r for r in recs if r.get("kind") == "mem"]
    assert mems and all("t" in r for r in mems)
    # CPU degrade path: census-only, no fabricated watermarks
    assert all("devices" not in r for r in mems)
    assert any(r.get("event") == "phase" for r in mems)
    assert any(r.get("kind") == "compile" for r in recs)
    # manifest says memory_stats was unavailable (self-describing runs)
    (manifest,) = [r for r in recs if r.get("kind") == "manifest"]
    assert manifest["memory_stats_available"] is False

    doc = json.load(open(tr))
    counters = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert counters
    assert all("ts" in e and "pid" in e for e in counters)
    assert {e["name"] for e in counters} == {"live bytes"}
    compile_spans = [e for e in doc["traceEvents"]
                    if e["ph"] == "X" and e.get("cat") == "compile"]
    assert compile_spans and compile_spans[0]["tid"] == timeline.TID_COMPILE

    capsys.readouterr()
    assert aggregate.main([str(jl)]) == 0
    out = capsys.readouterr().out
    assert "MEM phase=kernel:" in out
    assert "MEMTOP" in out
    assert "COMPILE daxpy:" in out


def test_memwatch_without_jsonl_notes_and_runs(capsys):
    from tpu_mpi_tests.drivers import daxpy

    rc = daxpy.main(["--n", "64", "--dtype", "float32", "--memwatch"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "--memwatch needs --jsonl" in out


def test_concurrent_sink_writes_stay_line_atomic(tmp_path):
    """The sampler thread and main-thread phase hooks write through the
    Reporter's locked jsonl sink concurrently; every line must stay
    valid JSON (the TPM601 hazard class, exercised live)."""
    import io

    from tpu_mpi_tests.instrument.report import Reporter
    from tpu_mpi_tests.instrument.timers import PhaseTimer

    jl = tmp_path / "c.jsonl"
    with Reporter(stream=io.StringIO(), jsonl_path=str(jl)) as rep:
        mw = memwatch.MemWatch(
            sink=lambda rec: rep.jsonl({**rec, "rank": 0}),
            interval_s=0.005,
        )
        mw.start()
        timer = PhaseTimer()
        stop = threading.Event()

        def churn():
            while not stop.is_set():
                rep.jsonl({"kind": "span", "op": "x", "seconds": 1e-6})

        t = threading.Thread(target=churn, daemon=True)
        t.start()
        for i in range(20):
            with timer.phase(f"p{i}"):
                time.sleep(0.002)
        stop.set()
        t.join(timeout=2)
        mw.stop()
    for line in jl.read_text().splitlines():
        json.loads(line)  # raises on any interleaved write


def test_phase_record_device_stats_deltas(monkeypatch):
    """Where the backend reports allocator stats, the phase record
    carries per-device watermarks + in-use delta + peak raise across
    the phase body (begin snapshot vs end)."""
    from tpu_mpi_tests.instrument.timers import PhaseTimer

    base = {"0": {"bytes_in_use": 100, "peak_bytes_in_use": 150}}
    stats = [
        base,  # start(): has-stats probe
        base,  # start(): the "start" mem_record
        base,  # phase begin snapshot
        {"0": {"bytes_in_use": 160, "peak_bytes_in_use": 400}},  # end
        {},  # stop(): the "final" mem_record
    ]
    seq = iter(stats)
    monkeypatch.setattr(
        memwatch, "device_memory_stats",
        lambda: next(seq, {}),
    )
    records = []
    mw = memwatch.MemWatch(sink=records.append, interval_s=60.0)
    mw.start()
    try:
        timer = PhaseTimer()
        with timer.phase("alloc"):
            pass
    finally:
        mw.stop()
    (rec,) = [r for r in records if r.get("event") == "phase"]
    assert rec["devices"]["0"]["peak_bytes_in_use"] == 400
    assert rec["delta_bytes"] == 60
    assert rec["peak_delta"] == 250
    assert rec["peak_bytes_in_use"] == 400


def test_census_only_runs_report_degrade_note(tmp_path, capsys):
    """End-to-end on CPU: the report explains the missing watermarks."""
    from tpu_mpi_tests.drivers import daxpy
    from tpu_mpi_tests.instrument import aggregate

    jl = tmp_path / "r.jsonl"
    assert daxpy.main(["--n", "64", "--memwatch",
                       "--jsonl", str(jl)]) == 0
    capsys.readouterr()
    assert aggregate.main([str(jl)]) == 0
    out = capsys.readouterr().out
    assert "MEM census-only:" in out
    assert "no device memory_stats" in out
