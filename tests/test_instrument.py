import io
import json

import jax.numpy as jnp
import pytest

from tpu_mpi_tests.instrument import PhaseTimer, Reporter
from tpu_mpi_tests.instrument.timers import block
from tpu_mpi_tests.instrument.trace import ProfilerGate, trace_range


class TestPhaseTimer:
    def test_accumulates(self):
        t = PhaseTimer()
        for _ in range(3):
            with t.phase("a"):
                pass
        assert t.counts["a"] == 3
        assert t.seconds["a"] >= 0

    def test_warmup_skipped(self):
        t = PhaseTimer(skip_first=2)
        for _ in range(5):
            with t.phase("x"):
                pass
        assert t.counts["x"] == 3

    def test_lines_format(self):
        t = PhaseTimer()
        with t.phase("gather"):
            pass
        (line,) = t.lines()
        assert line.startswith("TIME gather : 0.")

    def test_timed_blocks_result(self):
        t = PhaseTimer()
        out = t.timed("k", lambda: jnp.ones(8) * 2)
        assert float(out.sum()) == 16.0
        assert t.counts["k"] == 1

    def test_block_passthrough(self):
        x = jnp.ones(4)
        assert block(x) is x
        a, b = block(x, x + 1)
        assert float(b.sum()) == 8.0


class TestReporter:
    def test_line_shapes(self):
        buf = io.StringIO()
        r = Reporter(rank=2, size=8, stream=buf)
        r.sum_line(12.5)
        r.time_line("kernel", 0.25)
        r.test_line(0, "device", True, 1.5, 1e-7)
        r.test_line(1, "managed", False, 0.5, 0.0, extra_label="allreduce")
        r.exchange_line(0.125)
        out = buf.getvalue().splitlines()
        assert out[0] == "2/8 SUM = 12.500000"
        assert out[1] == "TIME kernel : 0.250000"
        assert out[2].startswith("TEST dim:0, device , buf:1; 1.5")
        assert "err=1" in out[2]
        assert out[3].startswith("TEST dim:1, managed, buf:0; allreduce=0.5")
        assert out[4] == "2/8 exchange time 0.12500000 ms"

    def test_banner_rank0_only(self):
        buf = io.StringIO()
        Reporter(rank=1, size=2, stream=buf).banner("config")
        assert buf.getvalue() == ""
        Reporter(rank=0, size=2, stream=buf).banner("config")
        assert buf.getvalue() == "config\n"

    def test_jsonl_sink(self, tmp_path):
        p = tmp_path / "out.jsonl"
        buf = io.StringIO()
        r = Reporter(stream=buf, jsonl_path=str(p))
        r.sum_line(1.0)
        r.time_line("kernel", 2.0)
        r.close()
        recs = [json.loads(ln) for ln in p.read_text().splitlines()]
        assert recs[0]["kind"] == "sum" and recs[0]["value"] == 1.0
        assert recs[1]["kind"] == "time" and recs[1]["phase"] == "kernel"

    def test_context_manager_closes_jsonl(self, tmp_path):
        p = tmp_path / "out.jsonl"
        with Reporter(stream=io.StringIO(), jsonl_path=str(p)) as r:
            r.sum_line(1.0)
            assert r._jsonl_file is not None
        assert r._jsonl_file is None
        assert json.loads(p.read_text())["kind"] == "sum"

    def test_multiprocess_jsonl_path_suffixed_per_rank(self, tmp_path):
        """Two processes appending to one path corrupt it; proc_count > 1
        auto-suffixes (out.jsonl -> out.p<i>.jsonl) so each rank owns its
        file and tpumt-report merges the set."""
        base = tmp_path / "out.jsonl"
        buf = io.StringIO()
        for i in range(2):
            with Reporter(rank=i, size=2, stream=buf, jsonl_path=str(base),
                          proc_index=i, proc_count=2) as r:
                r.sum_line(float(i))
        assert not base.exists()
        for i in range(2):
            rec = json.loads((tmp_path / f"out.p{i}.jsonl").read_text())
            assert rec["value"] == float(i)
        # single process keeps the exact path
        with Reporter(stream=buf, jsonl_path=str(base)) as r:
            r.sum_line(5.0)
        assert base.exists()

    def test_time_lines_stats(self, tmp_path):
        p = tmp_path / "out.jsonl"
        buf = io.StringIO()
        t = PhaseTimer()
        for _ in range(3):
            with t.phase("k"):
                pass
        with Reporter(stream=buf, jsonl_path=str(p)) as r:
            r.time_lines(t, stats=True)
        (line,) = buf.getvalue().splitlines()
        assert line.startswith("TIME k : ")
        assert "count=3" in line and "mean=" in line
        assert "min=" in line and "max=" in line
        (rec,) = [json.loads(ln) for ln in p.read_text().splitlines()]
        assert rec["count"] == 3
        assert rec["min_s"] <= rec["mean_s"] <= rec["max_s"]
        assert rec["seconds"] == pytest.approx(3 * rec["mean_s"])


def test_trace_range_and_gate_smoke(tmp_path):
    with trace_range("phase"):
        x = jnp.arange(4.0) * 2
    assert float(x.sum()) == 12.0
    # gate without logdir is a no-op; with logdir it must start/stop cleanly
    with ProfilerGate(None):
        pass
    with ProfilerGate(str(tmp_path / "trace")):
        jnp.ones(4).block_until_ready()


def test_daxpy_driver_end_to_end(capsys):
    from tpu_mpi_tests.drivers import daxpy as drv

    rc = drv.main(["--n", "512", "--dtype", "float64"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0/1 SUM = 131328.000000" in out  # 512*513/2
    assert "TIME kernel :" in out


def test_daxpy_driver_checksum_gate(capsys):
    # sanity: a wrong `a` must trip the gate
    from tpu_mpi_tests.drivers import daxpy as drv

    rc = drv.main(["--n", "64", "--a", "3.0", "--dtype", "float64"])
    assert rc == 1
    assert "CHECKSUM FAIL" in capsys.readouterr().out


def test_daxpy_driver_catches_compensating_error(capsys, monkeypatch):
    """A compensating per-element corruption (+1/−1) leaves the checksum
    exact; the per-element verification must still fail it (≅ the
    reference's element loop, daxpy.cu:82-87; VERDICT r1 missing #3)."""
    import jax.numpy as jnp

    import tpu_mpi_tests.kernels.daxpy as kd
    from tpu_mpi_tests.drivers import daxpy as drv

    real = kd.daxpy

    def corrupted(a, x, y):
        out = real(a, x, y)
        return out.at[0].add(1.0).at[1].add(-1.0)

    monkeypatch.setattr(kd, "daxpy", corrupted)
    rc = drv.main(["--n", "64", "--dtype", "float64"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "ELEMENT FAIL" in out
    assert "CHECKSUM FAIL" not in out


def test_chain_rate_repeats_survives_invalid_first_reading(monkeypatch):
    """Round-5 ``repeats``: the finite-MIN over repeated short/long pairs —
    a contention-spiked (non-positive-delta → invalid) first repeat must
    not poison a clean second one, and the min must be symmetric in the
    repeat order. Clock scripted via perf_counter so the semantics are
    deterministic (no sleep flakiness)."""
    from tpu_mpi_tests.instrument import timers as T

    # perf_counter readings consumed in order: each repeat takes 4
    # (t0/short, t0/long). Repeat 1: short=5s, long=1s -> delta<0 ->
    # invalid. Repeat 2: short=1s, long=3s -> delta=2s over (200-100)
    # iters = 0.02 s/iter.
    ticks = iter([
        0.0, 5.0,      # repeat 1 short
        5.0, 6.0,      # repeat 1 long (delta = 1 - 5 < 0 -> invalid)
        6.0, 7.0,      # repeat 2 short
        7.0, 10.0,     # repeat 2 long (delta = 3 - 1 = 2)
    ])
    monkeypatch.setattr(T.time, "perf_counter", lambda: next(ticks))
    monkeypatch.setattr(T, "block", lambda x: x)

    per, state = T.chain_rate(
        lambda st, n: st, "state", n_short=100, n_long=200, repeats=2
    )
    assert per == 2.0 / 100
    assert state == "state"

    # all repeats invalid -> NaN (the invalid-looks-invalid convention)
    ticks = iter([0.0, 5.0, 5.0, 6.0, 6.0, 11.0, 11.0, 12.0])
    monkeypatch.setattr(T.time, "perf_counter", lambda: next(ticks))
    per, _ = T.chain_rate(
        lambda st, n: st, "state", n_short=100, n_long=200, repeats=2
    )
    assert per != per
