"""Comm-layer telemetry tests: span accounting, flight recorder, manifest,
driver wiring (--telemetry), and the tpumt-report cross-rank aggregator."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_mpi_tests.instrument import telemetry as T


@pytest.fixture()
def fresh(monkeypatch):
    """A fresh registry so cross-test state cannot satisfy assertions;
    the module-level functions read ``_TELEMETRY`` at call time."""
    reg = T.Telemetry()
    monkeypatch.setattr(T, "_TELEMETRY", reg)
    return reg


class TestCommSpan:
    def test_counters_and_sink_records(self, fresh):
        records = []
        fresh.enable(sink=records.append)
        with T.comm_span("all_gather", nbytes=1024, axis_name="shard",
                         world=8) as span:
            span.result = jnp.ones(4)
        with T.comm_span("all_gather", nbytes=1024, axis_name="shard",
                         world=8):
            pass
        c = T.counters()
        assert c["all_gather"]["ops"] == 2
        assert c["all_gather"]["bytes"] == 2048
        assert c["all_gather"]["seconds"] > 0
        (r1, r2) = records
        assert r1["kind"] == "span" and r1["op"] == "all_gather"
        assert r1["nbytes"] == 1024 and r1["axis"] == "shard"
        assert r1["world"] == 8 and r1["seconds"] > 0
        assert r1["gbps"] == pytest.approx(
            1024 / r1["seconds"] / 1e9
        )
        # timeline placement: wall + monotonic bounds; the wall pair is
        # start + dt by construction, exact only to double resolution at
        # epoch magnitude (~1 us), the monotonic pair exactly spans dt
        assert r1["t_end"] - r1["t_start"] == pytest.approx(
            r1["seconds"], abs=2e-6
        )
        assert r1["mono_end"] - r1["mono_start"] == pytest.approx(
            r1["seconds"]
        )
        assert r2["t_start"] >= r1["t_end"]

    def test_nesting_records_each_level(self, fresh):
        fresh.enable()
        with T.comm_span("outer", nbytes=100):
            with T.comm_span("inner", nbytes=10):
                pass
        c = T.counters()
        assert c["outer"]["ops"] == 1 and c["inner"]["ops"] == 1
        assert c["outer"]["bytes"] == 100 and c["inner"]["bytes"] == 10
        # the outer span's wall time includes the inner's
        assert c["outer"]["seconds"] >= c["inner"]["seconds"]

    def test_span_call_disabled_is_passthrough(self, fresh):
        assert not fresh.enabled
        out = T.span_call("op", lambda a, b: a + b, 1, 2, nbytes=5)
        assert out == 3
        assert T.counters() == {}
        assert T.flight_events() == []

    def test_span_under_jit_trace_is_not_recorded(self, fresh):
        """A wrapper invoked inside a jitted loop body executes ONCE at
        trace time; recording there would fabricate telemetry (ops=1,
        trace-duration seconds, garbage GB/s) for an n-iteration loop —
        spans must pass through unrecorded under a trace."""
        from jax import lax

        fresh.enable()

        @jax.jit
        def loop(x):
            def body(_, xx):
                # suppressed: deliberately calling the telemetry layer
                # under a trace is this test's point — it asserts the
                # passthrough no-op the lint rule enforces elsewhere
                return T.span_call(  # tpumt: ignore[TPM201]
                    "traced_op", lambda a: a + 1, xx, nbytes=1024
                )
            return lax.fori_loop(0, 1000, body, x)

        out = loop(jnp.zeros(4))
        assert float(out[0]) == 1000.0
        assert "traced_op" not in T.counters()

    def test_span_call_enabled_blocks_and_records(self, fresh):
        fresh.enable()
        out = T.span_call(
            "k", lambda: jnp.arange(8.0) * 2, nbytes=64, axis_name="x",
            world=4,
        )
        assert float(out.sum()) == 56.0
        assert T.counters()["k"] == {
            "ops": 1,
            "bytes": 64,
            "seconds": pytest.approx(T.counters()["k"]["seconds"]),
        }


class TestFlightRecorder:
    def test_dispatch_notes_recorded_even_when_disabled(self, fresh):
        assert not fresh.enabled
        T.note_dispatch("ring_halo_pallas(world=8)")
        (e,) = T.flight_events()
        assert e.note == "ring_halo_pallas(world=8)"
        assert "dispatched" in e.describe()

    def test_capacity_bounds_buffer(self, monkeypatch):
        reg = T.Telemetry(flight_capacity=4)
        monkeypatch.setattr(T, "_TELEMETRY", reg)
        for i in range(10):
            T.note_dispatch(f"op{i}")
        notes = [e.note for e in T.flight_events()]
        assert notes == ["op6", "op7", "op8", "op9"]

    def test_flight_lines_order_and_ages(self, fresh):
        for i in range(5):
            T.note_dispatch(f"op{i}")
        lines = T.flight_lines(3)
        assert len(lines) == 3
        assert lines[0].startswith("op2") and lines[2].startswith("op4")
        assert all("s ago" in line for line in lines)


class TestWrapperSpans:
    """Every public collective/halo wrapper records a span when enabled."""

    def test_collectives_and_halo_record(self, fresh, mesh8):
        from tpu_mpi_tests.comm import collectives as C
        from tpu_mpi_tests.comm.halo import Staging, halo_exchange

        fresh.enable()
        x = C.shard_1d(jnp.arange(64, dtype=jnp.float32), mesh8)
        C.all_gather(x, mesh8)
        pr = C.shard_1d(jnp.ones((8, 16), jnp.float32), mesh8)
        C.allreduce_sum(pr, mesh8)
        pr2 = C.shard_1d(jnp.ones((8, 16), jnp.float32), mesh8)
        C.reduce_scatter_sum(pr2, mesh8)
        C.barrier(mesh8)
        z = np.arange(8 * 12 * 8, dtype=np.float32).reshape(96, 8)
        zs = jax.device_put(z, NamedSharding(mesh8, P("shard", None)))
        halo_exchange(zs, mesh8, axis=0, staging=Staging.DIRECT)

        c = T.counters()
        for op in ("all_gather", "allreduce", "reduce_scatter", "barrier",
                   "halo_exchange"):
            assert c[op]["ops"] >= 1, f"missing span for {op}"
        # payload conventions: gather moves (w-1)*global bytes
        assert c["all_gather"]["bytes"] == 7 * 64 * 4
        # halo: 2 directions x (w-1) pairs x n_bnd*W*itemsize bands
        assert c["halo_exchange"]["bytes"] == 2 * 7 * 2 * 8 * 4
        # bandwidth derivable for every byte-carrying op
        assert all(
            v["seconds"] > 0 for v in c.values()
        )

    def test_ring_attention_records(self, fresh, mesh8):
        from tpu_mpi_tests.comm.ring import ring_attention_fn

        fresh.enable()
        attn = ring_attention_fn(mesh8, "shard")
        q = jax.device_put(
            jnp.ones((16, 4), jnp.float32),
            NamedSharding(mesh8, P("shard", None)),
        )
        attn(q, q, q)
        c = T.counters()
        assert c["ring_attention"]["ops"] == 1
        assert c["ring_attention"]["bytes"] == 7 * 2 * 16 * 4 * 4

    def test_ulysses_attention_records(self, fresh, mesh8):
        from tpu_mpi_tests.comm.alltoall import ulysses_attention_fn

        fresh.enable()
        attn = ulysses_attention_fn(mesh8, "shard")
        q = jax.device_put(
            jnp.ones((16, 8, 4), jnp.float32),
            NamedSharding(mesh8, P("shard", None, None)),
        )
        attn(q, q, q)
        assert T.counters()["ulysses_attention"]["ops"] == 1


def test_watchdog_flight_dump_meets_floor(fresh):
    """Acceptance: a watchdog fire includes the last >= 8 comm ops."""
    from tpu_mpi_tests.instrument.watchdog import DUMP_EVENTS, Watchdog

    assert DUMP_EVENTS >= 8
    assert T.FLIGHT_CAPACITY >= DUMP_EVENTS
    for i in range(DUMP_EVENTS + 4):
        T.note_dispatch(f"collective_{i}")
    msgs = []
    wd = Watchdog(0.01, "p", _on_timeout=msgs.append)
    wd._fire()
    for i in range(4, DUMP_EVENTS + 4):
        assert f"collective_{i}" in msgs[0]


class TestManifest:
    def test_schema_and_serializable(self):
        from tpu_mpi_tests.instrument.manifest import (
            manifest_banner,
            run_manifest,
        )

        m = run_manifest(argv=["prog", "--flag"], extra_key=7)
        for key in ("kind", "time_unix", "time_iso", "argv", "hostname",
                    "python", "jax", "process_index", "process_count",
                    "local_device_count", "global_device_count", "platform",
                    "device_kinds", "env", "git_sha"):
            assert key in m, key
        assert m["kind"] == "manifest"
        assert m["argv"] == ["prog", "--flag"]
        assert m["extra_key"] == 7
        assert m["platform"] == "cpu" and m["global_device_count"] == 8
        # env capture includes the framework/JAX knobs the conftest sets
        assert "XLA_FLAGS" in m["env"]
        json.dumps(m)  # JSONL-safe
        banner = manifest_banner(m)
        assert banner.startswith("MANIFEST cpu")
        assert "jax=" in banner and "git=" in banner


def test_clock_sync_single_process_is_zero_offset():
    """One process = one clock: the alignment record is offset 0 with no
    collective round (fake-device meshes share the host clock)."""
    from tpu_mpi_tests.instrument.manifest import clock_sync_record

    rec = clock_sync_record()
    assert rec["kind"] == "clock_sync"
    assert rec["offset_s"] == 0.0 and rec["spread_s"] == 0.0
    assert rec["method"] == "single_process"
    json.dumps(rec)  # JSONL-safe


def test_dispatch_note_reaches_sink_when_enabled(fresh):
    """Enabled telemetry mirrors flight-recorder dispatch notes into the
    JSONL sink (kind "dispatch") so the timeline can show a wedged op's
    last dispatch; disabled telemetry keeps them flight-only."""
    records = []
    T.note_dispatch("pre_enable_dma")  # disabled: flight only
    fresh.enable(sink=records.append)
    T.note_dispatch("ring_halo_pallas(world=8)", op="rdma")
    assert [e.note for e in T.flight_events()] == [
        "pre_enable_dma", "ring_halo_pallas(world=8)"
    ]
    (rec,) = records
    assert rec["kind"] == "dispatch" and rec["op"] == "rdma"
    assert rec["note"] == "ring_halo_pallas(world=8)"
    assert rec["t"] > 0


def test_watchdog_fire_emits_timeline_record(fresh):
    """A watchdog fire lands a kind="watchdog" record in the sink — the
    flow-terminating marker the trace renders — before the hang dump."""
    from tpu_mpi_tests.instrument.watchdog import Watchdog

    records = []
    fresh.enable(sink=records.append)
    Watchdog(30.0, "allgather", _on_timeout=lambda m: None)._fire()
    wd = [r for r in records if r["kind"] == "watchdog"]
    assert len(wd) == 1
    assert wd[0]["phase"] == "allgather" and wd[0]["deadline_s"] == 30.0
    assert wd[0]["t"] > 0


def test_driver_telemetry_end_to_end(tmp_path, capsys, fresh):
    """--telemetry --jsonl: manifest first, span records per comm op,
    TELEMETRY counter lines + summary records on close (acceptance)."""
    from tpu_mpi_tests.drivers import stencil2d

    jl = tmp_path / "run.jsonl"
    rc = stencil2d.main(
        ["--n-local", "32", "--n-other", "64", "--n-iter", "2",
         "--n-warmup", "1", "--dtype", "float32", "--only", "1:0",
         "--telemetry", "--jsonl", str(jl)]
    )
    out = capsys.readouterr().out
    assert rc == 0
    recs = [json.loads(line) for line in jl.read_text().splitlines()]
    assert recs[0]["kind"] == "manifest"
    spans = [r for r in recs if r.get("kind") == "span"]
    assert spans, "no span records emitted"
    halo = [r for r in spans if r["op"] == "halo_exchange"]
    assert halo and all(r["nbytes"] > 0 and r["seconds"] > 0 for r in halo)
    assert all("rank" in r for r in spans)
    # acceptance: every span record is timeline-placeable
    assert all(
        r["t_start"] is not None and r["t_end"] >= r["t_start"]
        for r in spans
    )
    assert any(r.get("kind") == "clock_sync" for r in recs)
    summaries = [r for r in recs if r.get("kind") == "telemetry_summary"]
    assert any(s["op"] == "halo_exchange" for s in summaries)
    assert "MANIFEST cpu" in out
    assert "TELEMETRY halo_exchange :" in out
    # the registry was disabled when the reporter closed
    assert not T.registry().enabled


def test_driver_without_telemetry_emits_no_spans(tmp_path, capsys, fresh):
    from tpu_mpi_tests.drivers import gather_inplace

    jl = tmp_path / "run.jsonl"
    rc = gather_inplace.main(
        ["--n-per-rank", "64", "--jsonl", str(jl)]
    )
    capsys.readouterr()
    assert rc == 0
    recs = [json.loads(line) for line in jl.read_text().splitlines()]
    # manifest still present (self-describing results), but no spans
    assert recs[0]["kind"] == "manifest"
    assert not [r for r in recs if r.get("kind") == "span"]
