"""Compile-cost capture (instrument/costs.py): the AOT probe's record
shape, telemetry gating + dedupe, the span cost provider (roofline
fields on matching spans), and graceful failure on un-AOT-able fns."""

import pytest

from tpu_mpi_tests.instrument import costs
from tpu_mpi_tests.instrument import telemetry as T


@pytest.fixture(autouse=True)
def fresh_state(monkeypatch):
    monkeypatch.setattr(T, "_TELEMETRY", T.Telemetry())
    monkeypatch.setattr(T, "_COST_PROVIDER", None)
    costs.reset()
    yield
    costs.reset()
    T.set_cost_provider(None)


def _enable(records):
    T._TELEMETRY.enable(sink=records.append)


def test_probe_noop_when_telemetry_disabled():
    import jax
    import jax.numpy as jnp

    records = []
    info = costs.compile_probe(
        jax.jit(lambda x: x * 2), (jnp.ones((8,)),), label="f",
        emit=records.append,
    )
    assert info is None and records == []
    assert costs.cost_info("f") is None


def test_probe_records_compile_span_and_cost_model():
    import jax
    import jax.numpy as jnp

    records = []
    _enable(records)
    x = jnp.ones((1024,), jnp.float32)
    info = costs.compile_probe(
        jax.jit(lambda a, b: a * 2.0 + b), (x, x), label="axpb",
        phase="kernel", n=1024, dtype="float32",
    )
    assert info is not None
    (rec,) = [r for r in records if r.get("kind") == "compile"]
    assert rec["label"] == "axpb" and rec["phase"] == "kernel"
    assert rec["seconds"] > 0
    # PR-2 clock: placeable on the merged timeline
    assert rec["t_end"] == pytest.approx(
        rec["t_start"] + rec["seconds"], abs=1e-6
    )
    assert rec["mono_end"] > rec["mono_start"]
    # the compiler's cost model: flops + bytes for a 1024-elt a*2+b
    assert rec["flops"] and rec["flops"] >= 1024
    assert rec["bytes_accessed"] and rec["bytes_accessed"] >= 3 * 4096
    assert rec["output_bytes"] == 4096
    # tune-layer fingerprint carries the caller's context
    assert "dtype=float32" in rec["fingerprint"]
    assert "platform=cpu" in rec["fingerprint"]
    # CPU: no peak table entry -> no fabricated roofline denominator
    assert "peak_gbps" not in rec


def test_probe_dedupes_per_label_and_shapes():
    import jax
    import jax.numpy as jnp

    records = []
    _enable(records)
    f = jax.jit(lambda x: x + 1)
    costs.compile_probe(f, (jnp.ones((8,)),), label="g")
    costs.compile_probe(f, (jnp.ones((8,)),), label="g")  # dup: skipped
    costs.compile_probe(f, (jnp.ones((16,)),), label="g")  # new shape
    assert len([r for r in records if r.get("kind") == "compile"]) == 2


def test_probe_wraps_unjitted_and_survives_failure():
    import jax.numpy as jnp

    records = []
    _enable(records)
    # plain python fn: wrapped in jax.jit internally
    assert costs.compile_probe(
        lambda x: x * 3, (jnp.ones((4,)),), label="plain"
    ) is not None
    # un-AOT-able garbage: swallowed, nothing emitted under that label
    assert costs.compile_probe(
        lambda: (_ for _ in ()).throw(RuntimeError("no")), (), label="bad"
    ) is None
    labels = [r.get("label") for r in records
              if r.get("kind") == "compile"]
    assert labels == ["plain"]


def test_cost_fields_and_span_attachment():
    """After a probe, spans whose op matches the label carry the cost
    model + model-implied rates; unknown ops stay untouched."""
    import jax
    import jax.numpy as jnp

    records = []
    _enable(records)
    x = jnp.ones((4096,), jnp.float32)
    f = jax.jit(lambda a: a * 2.0)
    costs.compile_probe(f, (x,), label="scale")

    fields = costs.cost_fields("scale", 1e-3)
    assert fields["cost_bytes"] >= 2 * 16384
    assert fields["model_gbps"] == pytest.approx(
        fields["cost_bytes"] / 1e-3 / 1e9
    )
    assert "roofline_frac" not in fields  # no CPU peak
    assert costs.cost_fields("scale", 0) == {}
    assert costs.cost_fields("unknown", 1e-3) == {}

    out = T.span_call("scale", f, x)
    jax.block_until_ready(out)
    span = [r for r in records if r.get("kind") == "span"
            and r.get("op") == "scale"][-1]
    assert span["cost_bytes"] == fields["cost_bytes"]
    assert span["model_gbps"] > 0

    # a non-jitted fn is not auto-probed, so its op has no cost model
    out2 = T.span_call("other_op", lambda a: a, x)
    jax.block_until_ready(out2)
    span2 = [r for r in records if r.get("kind") == "span"
             and r.get("op") == "other_op"][-1]
    assert "cost_bytes" not in span2


def test_span_call_auto_probes_jitted_fns():
    """The comm wrappers all route through span_call: a jitted fn
    flowing through it gets its compile record without per-wrapper
    wiring — one probe per (op, shapes)."""
    import jax
    import jax.numpy as jnp

    records = []
    _enable(records)
    f = jax.jit(lambda x: x - 1)
    x = jnp.ones((32,))
    for _ in range(3):
        jax.block_until_ready(T.span_call("auto_op", f, x))
    compiles = [r for r in records if r.get("kind") == "compile"]
    assert len(compiles) == 1 and compiles[0]["label"] == "auto_op"
    assert len([r for r in records if r.get("kind") == "span"]) == 3


def test_roofline_frac_with_known_peak(monkeypatch):
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("TPU_MPI_PEAK_GBPS", "100")
    records = []
    _enable(records)
    x = jnp.ones((4096,), jnp.float32)
    costs.compile_probe(jax.jit(lambda a: a + 1), (x,), label="peaked")
    info = costs.cost_info("peaked")
    assert info["peak_gbps"] == 100.0
    fields = costs.cost_fields("peaked", 1e-3)
    assert fields["roofline_frac"] == pytest.approx(
        fields["model_gbps"] / 100.0, rel=1e-6
    )


def test_provider_error_never_breaks_span(monkeypatch):
    records = []
    _enable(records)
    T.set_cost_provider(lambda op, s: (_ for _ in ()).throw(ValueError()))
    with T.comm_span("op", nbytes=8) as span:
        span.result = None
    assert [r["kind"] for r in records] == ["span"]


def test_peak_gbps_env_override(monkeypatch):
    monkeypatch.setenv("TPU_MPI_PEAK_GBPS", "123.5")
    assert costs.peak_gbps() == 123.5
    monkeypatch.setenv("TPU_MPI_PEAK_GBPS", "not-a-number")
    assert costs.peak_gbps() is None  # CPU device kind not in the table


def test_multi_shape_label_is_ambiguous_no_span_attachment():
    """A label probed at several shapes (collbench sweeping payload
    sizes) has no single cost model: spans must get NOTHING attached
    rather than the last shape's numbers (review fix)."""
    import jax
    import jax.numpy as jnp

    records = []
    _enable(records)
    f = jax.jit(lambda x: x + 1)
    costs.compile_probe(f, (jnp.ones((8,)),), label="swept")
    assert costs.cost_fields("swept", 1e-3)  # single shape: attaches
    costs.compile_probe(f, (jnp.ones((1024,)),), label="swept")
    assert costs.cost_info("swept")["ambiguous"] is True
    assert costs.cost_fields("swept", 1e-3) == {}
    out = T.span_call("swept", f, jnp.ones((8,)))
    jax.block_until_ready(out)
    span = [r for r in records if r.get("kind") == "span"][-1]
    assert "cost_bytes" not in span and "model_gbps" not in span
    # both per-shape compile records were still emitted (each is
    # correct for its own shape)
    assert len([r for r in records if r.get("kind") == "compile"]) == 2
