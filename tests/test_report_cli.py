"""tpumt-report (instrument/aggregate.py): cross-rank JSONL merging,
straggler detection, and the rank-file suffix conventions."""

import json

import pytest

from tpu_mpi_tests.instrument import aggregate


def _write_jsonl(path, records):
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


@pytest.fixture()
def two_rank_run(tmp_path):
    """A deterministic two-rank run: rank 1 is a 2x straggler on the
    exchange phase; spans carry bandwidth."""
    manifest = {
        "kind": "manifest", "platform": "cpu", "global_device_count": 8,
        "device_kinds": ["cpu"], "process_count": 2, "jax": "0.0-test",
        "git_sha": "abc123", "argv": ["stencil2d", "--telemetry"],
    }
    _write_jsonl(tmp_path / "run.p0.jsonl", [
        dict(manifest, process_index=0),
        {"kind": "time", "phase": "exchange", "seconds": 1.0, "rank": 0},
        {"kind": "time", "phase": "kernel", "seconds": 0.5, "rank": 0},
        {"kind": "span", "op": "all_gather", "nbytes": 1 << 30,
         "seconds": 1.0, "gbps": 1.0, "world": 8, "rank": 0},
        {"kind": "span", "op": "all_gather", "nbytes": 1 << 30,
         "seconds": 0.5, "gbps": 2.0, "world": 8, "rank": 0},
    ])
    _write_jsonl(tmp_path / "run.p1.jsonl", [
        dict(manifest, process_index=1),
        {"kind": "time", "phase": "exchange", "seconds": 2.0, "rank": 1},
        {"kind": "time", "phase": "kernel", "seconds": 0.5, "rank": 1},
        {"kind": "span", "op": "all_gather", "nbytes": 1 << 30,
         "seconds": 0.25, "gbps": 4.0, "world": 8, "rank": 1},
    ])
    return tmp_path


def test_expand_rank_files_finds_suffixed_set(two_rank_run):
    base = str(two_rank_run / "run.jsonl")
    files = aggregate.expand_rank_files([base])
    assert [f.rsplit("/", 1)[-1] for f in files] == [
        "run.p0.jsonl", "run.p1.jsonl"
    ]


def test_summary_merges_ranks_and_finds_straggler(two_rank_run):
    files = aggregate.expand_rank_files([str(two_rank_run / "run.jsonl")])
    s = aggregate.summarize(files)
    assert s["manifest"]["process_index"] == 0
    assert s["manifest_count"] == 2

    ph = s["phases"]["exchange"]
    assert ph["ranks"] == 2 and ph["count"] == 2
    assert ph["mean_s"] == 1.5 and ph["min_s"] == 1.0 and ph["max_s"] == 2.0
    assert ph["skew"] == 2.0 and ph["straggler_rank"] == 1
    assert s["phases"]["kernel"]["skew"] == 1.0

    op = s["ops"]["all_gather"]
    assert op["ops"] == 3 and op["bytes"] == 3 * (1 << 30)
    assert op["ranks"] == 2
    # per-rank totals: rank0 = 1.5s, rank1 = 0.25s -> rank0 straggles
    assert op["skew"] == 6.0 and op["straggler_rank"] == 0
    assert op["gbps_p50"] == 2.0
    assert op["gbps_p10"] == 1.0 and op["gbps_p90"] == 4.0


def test_cli_text_output_golden(two_rank_run, capsys):
    """Golden-file shape of the text report on the two-rank fixture."""
    rc = aggregate.main([str(two_rank_run / "run.jsonl")])
    out = capsys.readouterr().out.splitlines()
    assert rc == 0
    assert out[0] == (
        "RUN cpux8 (cpu) procs=2 jax=0.0-test git=abc123"
    )
    assert out[1] == "ARGV stencil2d --telemetry"
    assert out[2].startswith("FILES 2: ")
    assert (
        "PHASE exchange: ranks=2 n=2 mean=1.5 min=1 max=2 skew=2" in out
    )
    assert (
        "PHASE kernel: ranks=2 n=2 mean=0.5 min=0.5 max=0.5 skew=1" in out
    )
    assert any(
        line.startswith("OP all_gather: ranks=2 ops=3 bytes=3221225472")
        and "gbps p10/p50/p90=1/2/4" in line
        for line in out
    )
    assert "STRAGGLER PHASE exchange: rank 1 is 2x the fastest rank" in "\n".join(out)
    assert "STRAGGLER OP all_gather: rank 0 is 6x the fastest rank" in "\n".join(out)


def test_cli_json_output(two_rank_run, capsys):
    rc = aggregate.main(["--json", str(two_rank_run / "run.jsonl")])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["phases"]["exchange"]["skew"] == 2.0


def test_cli_skew_threshold_silences_stragglers(two_rank_run, capsys):
    rc = aggregate.main(
        ["--skew-threshold", "10", str(two_rank_run / "run.jsonl")]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "STRAGGLER" not in out
    assert "OK no stragglers above 10x" in out


def test_cli_missing_files(tmp_path, capsys):
    rc = aggregate.main([str(tmp_path / "nope.jsonl")])
    assert rc == 1


def test_corrupt_lines_skipped(tmp_path):
    p = tmp_path / "r.jsonl"
    p.write_text('{"kind": "time", "phase": "a", "seconds": 1.0}\n'
                 "not json at all\n"
                 '{"kind": "time", "phase": "a", "seconds": 3.0}\n')
    s = aggregate.summarize([str(p)])
    # both valid records land on the same (file-index) rank
    assert s["phases"]["a"]["per_rank_s"] == {"0": 4.0}


def test_avg_py_expands_rank_suffixed_jsonl(two_rank_run, capsys):
    """tpu/avg.py --key globs the per-rank set from the base path."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "tpu_avg", Path(__file__).resolve().parent.parent / "tpu" / "avg.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(
        ["--no-native", "--pattern", "time", "--key", "seconds",
         str(two_rank_run / "run.jsonl")]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "run.p0.jsonl" in out and "run.p1.jsonl" in out


# ---------------------------------------------------------------------------
# MEMORY / COMPILE / VMEM tables (PR 5) — canned JSONL, stdlib-only path
# ---------------------------------------------------------------------------


@pytest.fixture()
def mem_cost_run(tmp_path):
    """Canned two-rank run with mem/compile/vmem records: rank 1 holds
    the HBM peak; the daxpy compile record joins the kernel phase."""
    _write_jsonl(tmp_path / "m.p0.jsonl", [
        {"kind": "manifest", "process_index": 0, "process_count": 2},
        {"kind": "time", "phase": "kernel", "seconds": 0.2, "count": 100,
         "rank": 0},
        {"kind": "mem", "event": "phase", "phase": "kernel", "t": 10.0,
         "t_start": 9.0, "t_end": 10.0, "rank": 0,
         "devices": {"0": {"bytes_in_use": 100, "peak_bytes_in_use": 150,
                           "bytes_limit": 1000}},
         "bytes_in_use": 100, "peak_bytes_in_use": 150,
         "delta_bytes": 50, "peak_delta": 25,
         "census": {"count": 2, "bytes": 90, "top": [
             {"key": "8x8·float32", "count": 1, "bytes": 64},
             {"key": "scalar·float32", "count": 1, "bytes": 4}]}},
        {"kind": "compile", "label": "daxpy", "phase": "kernel",
         "seconds": 0.5, "flops": 2048.0, "bytes_accessed": 1.0e6,
         "temp_bytes": 0, "output_bytes": 4096, "peak_gbps": 100.0,
         "t_start": 8.0, "t_end": 8.5, "rank": 0},
        {"kind": "vmem", "config": "heat_k4", "model_bytes": 100,
         "actual_bytes": 96, "ratio": 1.042},
        {"kind": "vmem", "config": "stream_d0", "model_bytes": 90,
         "actual_bytes": 100, "ratio": 0.9},
    ])
    _write_jsonl(tmp_path / "m.p1.jsonl", [
        {"kind": "manifest", "process_index": 1, "process_count": 2},
        {"kind": "time", "phase": "kernel", "seconds": 0.2, "count": 100,
         "rank": 1},
        {"kind": "mem", "event": "sample", "t": 9.5, "rank": 1,
         "devices": {"0": {"bytes_in_use": 300,
                           "peak_bytes_in_use": 400}},
         "bytes_in_use": 300, "peak_bytes_in_use": 400},
    ])
    return tmp_path


def test_memory_table_summary_and_text(mem_cost_run, capsys):
    files = aggregate.expand_rank_files([str(mem_cost_run / "m.jsonl")])
    s = aggregate.summarize(files)
    mem = s["memory"]
    assert mem["records"] == 2
    ph = mem["phases"]["kernel"]
    assert ph["peak_bytes"] == 150 and ph["delta_bytes"] == 50
    assert ph["peak_delta"] == 25 and ph["ranks"] == 1
    # run-wide peak held by rank 1's sample
    assert mem["peak"]["peak_bytes_in_use"] == {"bytes": 400, "rank": 1}
    assert mem["top"]["8x8·float32"]["bytes"] == 64

    rc = aggregate.main([str(mem_cost_run / "m.jsonl")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "MEM phase=kernel: peak=150 delta=50 peak_delta=25" in out
    assert "peak_bytes_in_use=400 (r1)" in out
    assert "MEMTOP 8x8·float32: bytes=64 count=1 (r0)" in out


def test_compile_table_roofline_join(mem_cost_run, capsys):
    files = aggregate.expand_rank_files([str(mem_cost_run / "m.jsonl")])
    s = aggregate.summarize(files)
    c = s["compile"]["daxpy"]
    assert c["compiles"] == 1 and c["seconds"] == 0.5
    # phase join: 0.4 s over 200 calls -> 2 ms/call
    assert c["mean_call_s"] == pytest.approx(0.002)
    assert c["model_gbps"] == pytest.approx(1.0e6 / 0.002 / 1e9)
    assert c["roofline_frac"] == pytest.approx(0.005)

    aggregate.main([str(mem_cost_run / "m.jsonl")])
    out = capsys.readouterr().out
    assert "COMPILE daxpy: n=1 compile=0.5s" in out
    assert "roofline=0.5%" in out


def test_compile_table_joins_span_op_over_phase(tmp_path, capsys):
    """When the probed label matches a span op, the per-call seconds
    come from the span table (the op IS the fn), not the phase."""
    _write_jsonl(tmp_path / "c.jsonl", [
        {"kind": "span", "op": "halo_exchange", "nbytes": 1000,
         "seconds": 0.01, "rank": 0},
        {"kind": "span", "op": "halo_exchange", "nbytes": 1000,
         "seconds": 0.03, "rank": 0},
        {"kind": "compile", "label": "halo_exchange", "seconds": 0.2,
         "bytes_accessed": 2.0e6, "rank": 0},
    ])
    s = aggregate.summarize([str(tmp_path / "c.jsonl")])
    c = s["compile"]["halo_exchange"]
    assert c["mean_call_s"] == pytest.approx(0.02)
    assert c["model_gbps"] == pytest.approx(2.0e6 / 0.02 / 1e9)
    assert "roofline_frac" not in c  # no peak recorded


def test_vmem_table_flags_unsafe(mem_cost_run, capsys):
    rc = aggregate.main([str(mem_cost_run / "m.jsonl")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "VMEM heat_k4: model=100 actual=96 model/actual=1.04" in out
    (unsafe,) = [l for l in out.splitlines()
                 if l.startswith("VMEM stream_d0")]
    assert unsafe.endswith("UNSAFE")


def test_old_files_report_shape_unchanged(two_rank_run, capsys):
    """Runs without mem/compile/vmem records must not grow MEMORY /
    COMPILE / VMEM lines (pre-PR report shape preserved)."""
    rc = aggregate.main([str(two_rank_run / "run.jsonl")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "MEM" not in out and "COMPILE" not in out
    assert "VMEM" not in out


def test_tables_render_without_jax(mem_cost_run, tmp_path):
    """The MEMORY/COMPILE/VMEM golden under a blocked jax import: the
    aggregate path must stay stdlib-only (TPM401-clean) so login nodes
    render the new tables too."""
    import subprocess
    import sys
    from pathlib import Path

    base = str(mem_cost_run / "m.jsonl")
    code = (
        "import sys\n"
        "class Block:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('jax blocked: login-node sim')\n"
        "sys.meta_path.insert(0, Block())\n"
        "from tpu_mpi_tests.instrument import aggregate\n"
        f"assert aggregate.main([{base!r}]) == 0\n"
        "print('NOJAX TABLES OK')\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "NOJAX TABLES OK" in r.stdout
    assert "MEM phase=kernel:" in r.stdout
    assert "COMPILE daxpy:" in r.stdout
    assert "VMEM heat_k4:" in r.stdout


# ---------------------------------------------------------------------------
# --diff: bench JSON + JSONL comparison with noise bands
# ---------------------------------------------------------------------------


def _bench_doc(value, samples, hbm=None, bf16=None):
    doc = {"metric": "stencil2d_fullstep_8192_iters_per_s",
           "value": value, "unit": "iter/s", "samples": samples}
    if hbm is not None:
        doc["hbm_peak_bytes"] = hbm
    if bf16 is not None:
        doc["bfloat16"] = bf16
    return doc


def test_diff_bench_regression_beyond_noise(tmp_path, capsys):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_bench_doc(
        2500.0, [2480.0, 2500.0, 2520.0], hbm=1000,
        bf16={"value": 3000.0, "unit": "iter/s",
              "samples": [2990.0, 3000.0, 3010.0]},
    )))
    b.write_text(json.dumps(_bench_doc(
        2000.0, [1980.0, 2000.0, 2020.0], hbm=1000,
        bf16={"value": 2990.0, "unit": "iter/s",
              "samples": [2980.0, 2990.0, 3000.0]},
    )))
    rc = aggregate.main(["--diff", str(a), str(b)])
    out = capsys.readouterr().out
    assert rc == 1  # the -20% primary drop is a regression
    assert "DIFF iter/s: A=2500 B=2000 change=-20.00%" in out
    (line,) = [l for l in out.splitlines()
               if l.startswith("DIFF iter/s:")]
    assert line.endswith("REGRESSION")
    # the bf16 -0.3% drift sits inside the 5% floor: not flagged
    (bf,) = [l for l in out.splitlines()
             if l.startswith("DIFF bfloat16.iter/s:")]
    assert "REGRESSION" not in bf
    # equal memory: no flag
    (hbm,) = [l for l in out.splitlines()
              if l.startswith("DIFF hbm_peak_bytes:")]
    assert "REGRESSION" not in hbm
    assert "DIFF REGRESSIONS 1" in out


def test_diff_bench_within_noise_ok(tmp_path, capsys):
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    # ±10% sample spread: the 8% drop is inside the run's own noise
    a.write_text(json.dumps(_bench_doc(
        2500.0, [2250.0, 2500.0, 2750.0])))
    b.write_text(json.dumps(_bench_doc(
        2300.0, [2070.0, 2300.0, 2530.0])))
    rc = aggregate.main(["--diff", str(a), str(b)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "DIFF OK within noise" in out


def test_diff_reads_bench_r_wrapper(tmp_path, capsys):
    """BENCH_r*.json wraps the result line in a driver capture object:
    --diff must parse the last JSON line out of its tail."""
    inner = json.dumps(_bench_doc(100.0, [99.0, 100.0, 101.0]))
    a = tmp_path / "BENCH_rA.json"
    b = tmp_path / "BENCH_rB.json"
    a.write_text(json.dumps(
        {"n": 5, "cmd": "python bench.py", "rc": 0,
         "tail": "WARNING: noise line\n" + inner}))
    b.write_text(json.dumps(_bench_doc(120.0, [119.0, 120.0, 121.0])))
    rc = aggregate.main(["--diff", str(a), str(b)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "DIFF iter/s: A=100 B=120 change=+20.00%" in out
    assert "improved" in out


def test_diff_jsonl_runs(two_rank_run, tmp_path, capsys):
    """JSONL-vs-JSONL diff: per-phase means compared, a 2x slower phase
    beyond the cross-rank band flagged, rc 1."""
    slow = tmp_path / "slow"
    slow.mkdir()
    _write_jsonl(slow / "run.p0.jsonl", [
        {"kind": "manifest", "process_index": 0},
        {"kind": "time", "phase": "exchange", "seconds": 3.1, "rank": 0},
        {"kind": "time", "phase": "kernel", "seconds": 0.5, "rank": 0},
    ])
    _write_jsonl(slow / "run.p1.jsonl", [
        {"kind": "manifest", "process_index": 1},
        {"kind": "time", "phase": "exchange", "seconds": 3.2, "rank": 1},
        {"kind": "time", "phase": "kernel", "seconds": 0.5, "rank": 1},
    ])
    rc = aggregate.main([
        "--diff", str(two_rank_run / "run.jsonl"), str(slow / "run.jsonl")
    ])
    out = capsys.readouterr().out
    assert rc == 1
    (ex,) = [l for l in out.splitlines()
             if l.startswith("DIFF phase:exchange:")]
    assert "REGRESSION" in ex
    (kn,) = [l for l in out.splitlines()
             if l.startswith("DIFF phase:kernel:")]
    assert "REGRESSION" not in kn


def test_diff_needs_two_paths(tmp_path, capsys):
    assert aggregate.main(["--diff", str(tmp_path / "only.json")]) == 1


def test_vmemprobe_emits_reporter_compatible_jsonl(tmp_path, monkeypatch,
                                                   capsys):
    """tpu/vmemprobe.py --jsonl: kind:"vmem" records (manifest first)
    land next to the unchanged stdout lines, and tpumt-report renders
    the model-vs-actual table from them. Measurement is stubbed — the
    real probe needs Mosaic on a TPU; the record contract does not."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "tpu_vmemprobe",
        Path(__file__).resolve().parent.parent / "tpu" / "vmemprobe.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    monkeypatch.setattr(mod, "configs", lambda: [
        ("cfg_ok", lambda: None, 100),
        ("cfg_rejected", None, "width exceeds budget"),
    ])
    monkeypatch.setattr(mod, "measure_scoped_bytes", lambda fn: 96)

    jl = tmp_path / "vmem.jsonl"
    rc = mod.main(["--jsonl", str(jl)])
    out = capsys.readouterr().out
    assert rc == 1  # the rejected config still counts unsafe
    assert '"model_over_actual": 1.042' in out  # stdout contract intact

    recs = [json.loads(ln) for ln in jl.read_text().splitlines()]
    assert recs[0]["kind"] == "manifest"
    vmems = [r for r in recs if r["kind"] == "vmem"]
    assert {r["config"] for r in vmems} == {"cfg_ok", "cfg_rejected"}
    (ok,) = [r for r in vmems if r["config"] == "cfg_ok"]
    assert ok["model_bytes"] == 100 and ok["actual_bytes"] == 96
    assert ok["ratio"] == 1.042

    capsys.readouterr()
    assert aggregate.main([str(jl)]) == 0
    out = capsys.readouterr().out
    assert "VMEM cfg_ok: model=100 actual=96 model/actual=1.04" in out
    assert "VMEM cfg_rejected: ERROR width exceeds budget" in out


def test_compile_table_skips_model_join_for_multi_shape_labels(
    tmp_path, capsys
):
    """Two compile records under one label with different cost models
    (a payload-size sweep): the table must NOT divide one shape's bytes
    by every shape's mean seconds (review fix) — mean_call still shown,
    model_gbps/roofline withheld, cost_models surfaced."""
    _write_jsonl(tmp_path / "s.jsonl", [
        {"kind": "span", "op": "coll_allgather", "seconds": 0.01,
         "rank": 0},
        {"kind": "span", "op": "coll_allgather", "seconds": 0.02,
         "rank": 0},
        {"kind": "compile", "label": "coll_allgather", "seconds": 0.1,
         "bytes_accessed": 4096.0, "peak_gbps": 100.0, "rank": 0},
        {"kind": "compile", "label": "coll_allgather", "seconds": 0.1,
         "bytes_accessed": 1.0e6, "peak_gbps": 100.0, "rank": 0},
    ])
    s = aggregate.summarize([str(tmp_path / "s.jsonl")])
    c = s["compile"]["coll_allgather"]
    assert c["cost_models"] == 2 and c["compiles"] == 2
    assert c["mean_call_s"] == pytest.approx(0.015)
    assert "model_gbps" not in c and "roofline_frac" not in c
    aggregate.main([str(tmp_path / "s.jsonl")])
    out = capsys.readouterr().out
    (line,) = [l for l in out.splitlines() if l.startswith("COMPILE")]
    assert "cost_models=2" in line and "model_gbps" not in line


# ---------------------------------------------------------------------------
# SLO table + serve percentile diff (kind:"serve" records, serving mode)
# ---------------------------------------------------------------------------


def _serve_records(p50, p95, p99, achieved=20.0, requests=100,
                   errors=0, shed=0, qmax=3, rank=0, windows=3,
                   jitter=0.02):
    """One rank's serve stream for one class: `windows` window records
    with a small percentile spread (the cross-window noise band) plus
    the run summary."""
    cls = "daxpy:4096:float32"
    recs = []
    for i in range(windows):
        f = 1.0 + jitter * (i - windows // 2)
        recs.append({
            "kind": "serve", "event": "window", "class": cls,
            "workload": "daxpy", "shape": [4096], "dtype": "float32",
            "t_start": 10.0 + i, "t_end": 11.0 + i, "duration_s": 1.0,
            "arrivals": requests // windows,
            "requests": requests // windows, "errors": 0, "shed": 0,
            "batches": requests // windows,
            "offered_hz": achieved, "achieved_hz": achieved * f,
            "p50_ms": p50 * f, "p95_ms": p95 * f, "p99_ms": p99 * f,
            "queue_max": qmax - 1, "rank": rank,
        })
    recs.append({
        "kind": "serve", "event": "summary", "class": cls,
        "workload": "daxpy", "shape": [4096], "dtype": "float32",
        "t_start": 10.0, "t_end": 10.0 + windows,
        "duration_s": float(windows),
        "arrivals": requests + errors + shed, "requests": requests,
        "errors": errors, "shed": shed, "batches": requests,
        "offered_hz": (requests + errors + shed) / windows,
        "achieved_hz": achieved, "p50_ms": p50, "p95_ms": p95,
        "p99_ms": p99, "mean_ms": p50, "queue_max": qmax, "rank": rank,
    })
    return recs


def test_slo_table_summary_and_text(tmp_path, capsys):
    """Golden SLO row from canned two-rank serve records: counts/rates
    sum across ranks, percentiles take the worst rank's tail."""
    _write_jsonl(tmp_path / "s.p0.jsonl", [
        {"kind": "manifest", "process_index": 0, "process_count": 2},
        *_serve_records(2.0, 4.0, 8.0, achieved=20.0, requests=100,
                        errors=1, shed=2, qmax=3, rank=0),
    ])
    _write_jsonl(tmp_path / "s.p1.jsonl", [
        {"kind": "manifest", "process_index": 1, "process_count": 2},
        *_serve_records(2.5, 5.0, 10.0, achieved=18.0, requests=90,
                        qmax=5, rank=1),
    ])
    files = aggregate.expand_rank_files([str(tmp_path / "s.jsonl")])
    s = aggregate.summarize(files)
    sv = s["serve"]["daxpy:4096:float32"]
    assert sv["ranks"] == 2 and sv["windows"] == 6
    assert sv["requests"] == 190 and sv["errors"] == 1
    assert sv["shed"] == 2
    assert sv["achieved_hz"] == pytest.approx(38.0)
    # SLO = worst-rank tail, not the mean
    assert sv["p50_ms"] == 2.5 and sv["p99_ms"] == 10.0
    assert sv["queue_max"] == 5
    # the band spans window AND rank spread — rank 1's slower tail
    # widens it well past the per-rank ±2% jitter
    assert sv["bands"]["p99_ms"] > 0.1

    rc = aggregate.main([str(tmp_path / "s.jsonl")])
    out = capsys.readouterr().out
    assert rc == 0
    (line,) = [ln for ln in out.splitlines() if ln.startswith("SLO ")]
    # a pre-decomposition stream (no qd_/svc_ fields) renders dashes in
    # the qd99/svc99 columns — absent data, never fake zeros
    assert line == (
        "SLO daxpy:4096:float32: ranks=2 offered=64.33/s "
        "achieved=38/s n=190 err=1 shed=2 p50=2.5ms p95=5ms "
        "p99=10ms qd99=-ms svc99=-ms qmax=5 windows=6"
    )


def test_slo_table_surfaces_quarantine(tmp_path, capsys):
    """A summary carrying quarantine accounting (serve
    --quarantine-after graceful degradation) lands in the SLO row as
    quarantines=/quar_s=; rows without it keep their exact shape."""
    recs = _serve_records(2.0, 4.0, 8.0, requests=50, errors=9,
                          shed=40)
    recs[-1]["quarantines"] = 1
    recs[-1]["quarantine_s"] = 12.5
    n_windows = sum(1 for r in recs if r.get("event") == "window")
    # lifecycle markers ride the same kind:"serve" stream but must not
    # count as traffic windows in the row
    lifecycle = [
        {"kind": "serve", "event": "quarantine", "rank": 0,
         "class": "daxpy:4096:float32", "t": 100.0},
        {"kind": "serve", "event": "recover", "rank": 0,
         "class": "daxpy:4096:float32", "t": 112.5,
         "quarantine_s": 12.5},
    ]
    _write_jsonl(tmp_path / "s.p0.jsonl", [
        {"kind": "manifest", "process_index": 0, "process_count": 1},
        *recs[:-1], *lifecycle, recs[-1],
    ])
    s = aggregate.summarize([str(tmp_path / "s.p0.jsonl")])
    sv = s["serve"]["daxpy:4096:float32"]
    assert sv["quarantines"] == 1
    assert sv["quarantine_s"] == pytest.approx(12.5)
    assert sv["windows"] == n_windows
    aggregate.main([str(tmp_path / "s.p0.jsonl")])
    out = capsys.readouterr().out
    (line,) = [ln for ln in out.splitlines() if ln.startswith("SLO ")]
    assert "quarantines=1 quar_s=12.5" in line


def test_slo_table_synthesized_from_windows(tmp_path):
    """A run that died before its summary still gets an SLO row from
    the window records alone."""
    recs = _serve_records(1.0, 2.0, 3.0, requests=90)[:-1]  # no summary
    _write_jsonl(tmp_path / "w.jsonl", recs)
    s = aggregate.summarize([str(tmp_path / "w.jsonl")])
    sv = s["serve"]["daxpy:4096:float32"]
    assert sv["requests"] == 90 and sv["ranks"] == 1
    assert sv["achieved_hz"] == pytest.approx(30.0)
    assert sv["p99_ms"] == pytest.approx(3.0 * 1.02)  # worst window
    # single rank: the band is the pure cross-window jitter (±2%)
    assert sv["bands"]["p99_ms"] == pytest.approx(0.02, rel=0.1)


def test_slo_table_mixed_summary_and_crashed_rank(tmp_path):
    """Per-rank synthesis: rank 0 finished (summary), rank 1 crashed
    after windows only — rank 1's tail must still be in the row, not
    silently dropped because a sibling finished cleanly."""
    _write_jsonl(tmp_path / "m.p0.jsonl",
                 _serve_records(2.0, 4.0, 8.0, requests=90, rank=0))
    crashed = _serve_records(4.0, 8.0, 16.0, requests=90, rank=1)[:-1]
    _write_jsonl(tmp_path / "m.p1.jsonl", crashed)
    files = aggregate.expand_rank_files([str(tmp_path / "m.jsonl")])
    sv = aggregate.summarize(files)["serve"]["daxpy:4096:float32"]
    assert sv["ranks"] == 2
    assert sv["requests"] == 180
    # the crashed rank's worst window is the row's tail
    assert sv["p99_ms"] == pytest.approx(16.0 * 1.02)


def test_old_files_grow_no_slo_table(two_rank_run, capsys):
    aggregate.main([str(two_rank_run / "run.jsonl")])
    assert "SLO" not in capsys.readouterr().out


def test_diff_serve_percentile_regression(tmp_path, capsys):
    """A p99 regression beyond the cross-window band exits 1; the same
    tail inside the band exits 0 — the serve SLO joins the bench diff's
    exit contract."""
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    c = tmp_path / "c.jsonl"
    _write_jsonl(a, _serve_records(2.0, 4.0, 8.0))
    _write_jsonl(b, _serve_records(2.0, 4.0, 16.0))  # p99 2x: regression
    _write_jsonl(c, _serve_records(2.0, 4.0, 8.2))  # inside 5% floor
    rc = aggregate.main(["--diff", str(a), str(b)])
    out = capsys.readouterr().out
    assert rc == 1
    (line,) = [ln for ln in out.splitlines()
               if ln.startswith("DIFF serve:daxpy:4096:float32:p99_ms:")]
    assert line.endswith("REGRESSION")
    # achieved throughput compared too (higher-better, unchanged here)
    assert any(
        ln.startswith("DIFF serve:daxpy:4096:float32:achieved_hz:")
        for ln in out.splitlines()
    )
    rc = aggregate.main(["--diff", str(a), str(c)])
    out = capsys.readouterr().out
    assert rc == 0 and "DIFF OK within noise" in out


def _traffic_record(fp, event="replay", count=100):
    return {"kind": "traffic", "event": event, "fingerprint": fp,
            "count": count, "duration_s": 3.0, "rank": 0,
            "path": "t.json"}


def test_diff_refuses_differing_traffic_fingerprints(tmp_path, capsys):
    """Two serve runs that saw DIFFERENT recorded traffic are not a
    comparison: --diff refuses with exit 2 and a DIFF ERROR before any
    metric is judged; --allow-traffic-mismatch downgrades the refusal
    to a NOTE and the metric gate proceeds."""
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    _write_jsonl(a, [_traffic_record("aaaa111122223333"),
                     *_serve_records(2.0, 4.0, 8.0)])
    _write_jsonl(b, [_traffic_record("bbbb444455556666"),
                     *_serve_records(2.0, 4.0, 8.0)])
    rc = aggregate.main(["--diff", str(a), str(b)])
    cap = capsys.readouterr()
    assert rc == 2
    assert "DIFF ERROR traffic fingerprints differ" in cap.err
    assert "aaaa111122223333" in cap.err and "bbbb444455556666" in cap.err
    # identical metrics, so once allowed the diff itself is clean
    rc = aggregate.main(["--diff", "--allow-traffic-mismatch",
                         str(a), str(b)])
    cap = capsys.readouterr()
    assert rc == 0
    assert "DIFF NOTE traffic fingerprints differ" in cap.out
    # ... but --allow does not mask a real regression
    _write_jsonl(b, [_traffic_record("bbbb444455556666"),
                     *_serve_records(2.0, 4.0, 40.0)])
    rc = aggregate.main(["--diff", "--allow-traffic-mismatch",
                         str(a), str(b)])
    capsys.readouterr()
    assert rc == 1


def test_diff_matching_traffic_fingerprints_announced(tmp_path, capsys):
    """Matching fingerprints print the match line — the visible signal
    that this diff compared the SAME traffic, not two draws."""
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    _write_jsonl(a, [_traffic_record("cafe000011112222", event="record"),
                     *_serve_records(2.0, 4.0, 8.0)])
    _write_jsonl(b, [_traffic_record("cafe000011112222"),
                     *_serve_records(2.0, 4.0, 8.0)])
    rc = aggregate.main(["--diff", str(a), str(b)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "DIFF traffic fingerprints match (cafe000011112222)" in out


def test_diff_one_sided_fingerprint_notes_not_refuses(tmp_path, capsys):
    """A pre-PR-16 baseline carries no fingerprint: the diff proceeds
    (refusing would orphan every old baseline) but says out loud that
    identical load cannot be verified."""
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    _write_jsonl(a, [_traffic_record("cafe000011112222"),
                     *_serve_records(2.0, 4.0, 8.0)])
    _write_jsonl(b, _serve_records(2.0, 4.0, 8.0))
    rc = aggregate.main(["--diff", str(a), str(b)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "DIFF NOTE only" in out and "traffic fingerprint" in out


def test_report_renders_traffic_line(tmp_path, capsys):
    """The text report surfaces the run's traffic identity next to the
    SLO table it qualifies."""
    p = tmp_path / "s.jsonl"
    _write_jsonl(p, [_traffic_record("cafe000011112222"),
                     *_serve_records(2.0, 4.0, 8.0)])
    rc = aggregate.main([str(p)])
    out = capsys.readouterr().out
    assert rc == 0
    (line,) = [ln for ln in out.splitlines()
               if ln.startswith("TRAFFIC ")]
    assert line.startswith(
        "TRAFFIC replay: fingerprint=cafe000011112222 count=100 "
        "duration=3s")


def test_diff_serve_total_stall_flags(tmp_path, capsys):
    """achieved_hz=0 (every batch errored) must still emit the metric:
    a -100% throughput collapse is the regression the gate exists for,
    not a present-on-one-side NOTE."""
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    _write_jsonl(a, _serve_records(2.0, 4.0, 8.0, achieved=20.0))
    dead = _serve_records(2.0, 4.0, 8.0, achieved=0.0, requests=0,
                          errors=100, jitter=0.0)
    for r in dead:  # a stalled run records no latencies
        for k in ("p50_ms", "p95_ms", "p99_ms", "mean_ms"):
            r.pop(k, None)
    _write_jsonl(b, dead)
    rc = aggregate.main(["--diff", str(a), str(b)])
    out = capsys.readouterr().out
    assert rc == 1
    (line,) = [
        ln for ln in out.splitlines()
        if ln.startswith("DIFF serve:daxpy:4096:float32:achieved_hz:")
    ]
    assert "-100.00%" in line and line.endswith("REGRESSION")


def test_diff_serve_throughput_drop_flags(tmp_path, capsys):
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    _write_jsonl(a, _serve_records(2.0, 4.0, 8.0, achieved=20.0))
    _write_jsonl(b, _serve_records(2.0, 4.0, 8.0, achieved=10.0))
    rc = aggregate.main(["--diff", str(a), str(b)])
    out = capsys.readouterr().out
    assert rc == 1
    (line,) = [
        ln for ln in out.splitlines()
        if ln.startswith("DIFF serve:daxpy:4096:float32:achieved_hz:")
    ]
    assert line.endswith("REGRESSION")


def test_slo_table_renders_without_jax(tmp_path):
    """The SLO path must stay stdlib-only like every other table."""
    import subprocess
    import sys
    from pathlib import Path

    _write_jsonl(tmp_path / "s.jsonl", _serve_records(2.0, 4.0, 8.0))
    code = (
        "import sys\n"
        "class Block:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('jax blocked: login-node sim')\n"
        "sys.meta_path.insert(0, Block())\n"
        "from tpu_mpi_tests.instrument import aggregate\n"
        f"assert aggregate.main([{str(tmp_path / 's.jsonl')!r}]) == 0\n"
        "print('NOJAX SLO OK')\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "NOJAX SLO OK" in r.stdout
    assert "SLO daxpy:4096:float32:" in r.stdout


def test_memory_census_only_note(tmp_path, capsys):
    """Census-only runs (CPU/fake devices) must say WHY there are no
    watermark numbers — live totals alone must not read as real HBM
    (review fix: the note used to be unreachable)."""
    _write_jsonl(tmp_path / "c.jsonl", [
        {"kind": "mem", "event": "sample", "t": 1.0, "live_bytes": 4096,
         "live_count": 2, "rank": 0},
    ])
    aggregate.main([str(tmp_path / "c.jsonl")])
    out = capsys.readouterr().out
    assert "MEM census-only: 1 records, no device memory_stats" in out
    assert "live_bytes=4096" in out


# --------------------------------------------------------------------------
# ISSUE 8: ROUTE / DECODE / WORKLOAD tables + their --diff gating
# --------------------------------------------------------------------------


def _route_rec(overflow=5.0, imbalance=1.3, **over):
    rec = {
        "kind": "route", "op": "moe", "world": 4, "capacity": 8,
        "tokens": 128, "routed": 120, "dropped": 8,
        "overflow_pct": overflow, "occupancy_pct": 93.75,
        "imbalance": imbalance, "combine": "alltoall",
    }
    rec.update(over)
    return rec


def test_route_table_summary_and_text(tmp_path, capsys):
    _write_jsonl(tmp_path / "r.jsonl", [
        _route_rec(overflow=4.0),
        _route_rec(overflow=6.0),
    ])
    files = [str(tmp_path / "r.jsonl")]
    s = aggregate.summarize(files)
    rt = s["route"]["moe"]
    assert rt["calls"] == 2
    assert rt["tokens"] == 256 and rt["dropped"] == 16
    assert rt["overflow_pct"] == pytest.approx(5.0)
    assert rt["overflow_band"] > 0  # cross-call spread is the band
    aggregate.main(files)
    out = capsys.readouterr().out
    assert (
        "ROUTE moe: calls=2 world=4 capacity=8 tokens=256 routed=240 "
        "dropped=16 overflow=5.00% occupancy=93.8% imbalance=1.300 "
        "combine=alltoall"
    ) in out


def test_decode_and_workload_rows_render(tmp_path, capsys):
    _write_jsonl(tmp_path / "d.jsonl", [
        {"kind": "decode", "collective": "allreduce", "batch": 1,
         "heads": 16, "shard_bytes": 64, "us_per_op": 50.0, "world": 4,
         "n_iter": 100},
        {"kind": "workload", "workload": "moe", "metric": "us_per_step",
         "value": 900.0, "unit": "us", "higher_better": False},
    ])
    aggregate.main([str(tmp_path / "d.jsonl")])
    out = capsys.readouterr().out
    assert "DECODE allreduce:1x16: us_per_op=50 bytes=64 n=1" in out
    assert "WORKLOAD moe:us_per_step: value=900 us n=1" in out


def test_diff_route_overflow_regression(tmp_path, capsys):
    """The moe-smoke contract: overflow % beyond the noise band exits 1
    as a lower-is-better regression; an equal run passes clean."""
    _write_jsonl(tmp_path / "a.jsonl", [_route_rec(overflow=5.0)])
    _write_jsonl(tmp_path / "b.jsonl", [_route_rec(overflow=20.0)])
    rc = aggregate.main(
        ["--diff", str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")]
    )
    out = capsys.readouterr().out
    assert rc == 1
    assert "route:moe:overflow_pct" in out and "REGRESSION" in out
    rc = aggregate.main(
        ["--diff", str(tmp_path / "a.jsonl"), str(tmp_path / "a.jsonl")]
    )
    assert rc == 0


def test_diff_decode_latency_lower_better(tmp_path, capsys):
    base = {"kind": "decode", "collective": "allreduce", "batch": 8,
            "heads": 16, "shard_bytes": 512, "world": 4, "n_iter": 100}
    _write_jsonl(tmp_path / "a.jsonl", [dict(base, us_per_op=50.0)])
    _write_jsonl(tmp_path / "b.jsonl", [dict(base, us_per_op=500.0)])
    rc = aggregate.main(
        ["--diff", str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")]
    )
    out = capsys.readouterr().out
    assert rc == 1
    assert "decode:allreduce:8x16:us_per_op" in out
    # the reverse direction is an improvement, not a regression
    rc = aggregate.main(
        ["--diff", str(tmp_path / "b.jsonl"), str(tmp_path / "a.jsonl")]
    )
    assert rc == 0
    assert "improved" in capsys.readouterr().out


def test_diff_workload_row_direction_from_record(tmp_path, capsys):
    """kind:"workload" rows carry their own regression direction: a
    lower-better metric growing flags; a higher-better one growing is
    an improvement."""
    def row(metric, value, higher):
        return {"kind": "workload", "workload": "w", "metric": metric,
                "value": value, "unit": "u", "higher_better": higher}

    _write_jsonl(tmp_path / "a.jsonl",
                 [row("lat", 10.0, False), row("rate", 10.0, True)])
    _write_jsonl(tmp_path / "b.jsonl",
                 [row("lat", 100.0, False), row("rate", 100.0, True)])
    rc = aggregate.main(
        ["--diff", str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")]
    )
    out = capsys.readouterr().out
    assert rc == 1
    assert "workload:w:lat" in out
    lat = [l for l in out.splitlines() if "workload:w:lat" in l][0]
    rate = [l for l in out.splitlines() if "workload:w:rate" in l][0]
    assert "REGRESSION" in lat
    assert "improved" in rate


def test_old_files_grow_no_route_tables(two_rank_run, capsys):
    """Pre-ISSUE-8 record streams keep their exact report shape: no
    ROUTE/DECODE/WORKLOAD lines appear for runs that recorded none."""
    files = aggregate.expand_rank_files([str(two_rank_run / "run.jsonl")])
    aggregate.main(files)
    out = capsys.readouterr().out
    assert "ROUTE" not in out
    assert "DECODE" not in out
    assert "WORKLOAD" not in out
