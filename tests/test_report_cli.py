"""tpumt-report (instrument/aggregate.py): cross-rank JSONL merging,
straggler detection, and the rank-file suffix conventions."""

import json

import pytest

from tpu_mpi_tests.instrument import aggregate


def _write_jsonl(path, records):
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


@pytest.fixture()
def two_rank_run(tmp_path):
    """A deterministic two-rank run: rank 1 is a 2x straggler on the
    exchange phase; spans carry bandwidth."""
    manifest = {
        "kind": "manifest", "platform": "cpu", "global_device_count": 8,
        "device_kinds": ["cpu"], "process_count": 2, "jax": "0.0-test",
        "git_sha": "abc123", "argv": ["stencil2d", "--telemetry"],
    }
    _write_jsonl(tmp_path / "run.p0.jsonl", [
        dict(manifest, process_index=0),
        {"kind": "time", "phase": "exchange", "seconds": 1.0, "rank": 0},
        {"kind": "time", "phase": "kernel", "seconds": 0.5, "rank": 0},
        {"kind": "span", "op": "all_gather", "nbytes": 1 << 30,
         "seconds": 1.0, "gbps": 1.0, "world": 8, "rank": 0},
        {"kind": "span", "op": "all_gather", "nbytes": 1 << 30,
         "seconds": 0.5, "gbps": 2.0, "world": 8, "rank": 0},
    ])
    _write_jsonl(tmp_path / "run.p1.jsonl", [
        dict(manifest, process_index=1),
        {"kind": "time", "phase": "exchange", "seconds": 2.0, "rank": 1},
        {"kind": "time", "phase": "kernel", "seconds": 0.5, "rank": 1},
        {"kind": "span", "op": "all_gather", "nbytes": 1 << 30,
         "seconds": 0.25, "gbps": 4.0, "world": 8, "rank": 1},
    ])
    return tmp_path


def test_expand_rank_files_finds_suffixed_set(two_rank_run):
    base = str(two_rank_run / "run.jsonl")
    files = aggregate.expand_rank_files([base])
    assert [f.rsplit("/", 1)[-1] for f in files] == [
        "run.p0.jsonl", "run.p1.jsonl"
    ]


def test_summary_merges_ranks_and_finds_straggler(two_rank_run):
    files = aggregate.expand_rank_files([str(two_rank_run / "run.jsonl")])
    s = aggregate.summarize(files)
    assert s["manifest"]["process_index"] == 0
    assert s["manifest_count"] == 2

    ph = s["phases"]["exchange"]
    assert ph["ranks"] == 2 and ph["count"] == 2
    assert ph["mean_s"] == 1.5 and ph["min_s"] == 1.0 and ph["max_s"] == 2.0
    assert ph["skew"] == 2.0 and ph["straggler_rank"] == 1
    assert s["phases"]["kernel"]["skew"] == 1.0

    op = s["ops"]["all_gather"]
    assert op["ops"] == 3 and op["bytes"] == 3 * (1 << 30)
    assert op["ranks"] == 2
    # per-rank totals: rank0 = 1.5s, rank1 = 0.25s -> rank0 straggles
    assert op["skew"] == 6.0 and op["straggler_rank"] == 0
    assert op["gbps_p50"] == 2.0
    assert op["gbps_p10"] == 1.0 and op["gbps_p90"] == 4.0


def test_cli_text_output_golden(two_rank_run, capsys):
    """Golden-file shape of the text report on the two-rank fixture."""
    rc = aggregate.main([str(two_rank_run / "run.jsonl")])
    out = capsys.readouterr().out.splitlines()
    assert rc == 0
    assert out[0] == (
        "RUN cpux8 (cpu) procs=2 jax=0.0-test git=abc123"
    )
    assert out[1] == "ARGV stencil2d --telemetry"
    assert out[2].startswith("FILES 2: ")
    assert (
        "PHASE exchange: ranks=2 n=2 mean=1.5 min=1 max=2 skew=2" in out
    )
    assert (
        "PHASE kernel: ranks=2 n=2 mean=0.5 min=0.5 max=0.5 skew=1" in out
    )
    assert any(
        line.startswith("OP all_gather: ranks=2 ops=3 bytes=3221225472")
        and "gbps p10/p50/p90=1/2/4" in line
        for line in out
    )
    assert "STRAGGLER PHASE exchange: rank 1 is 2x the fastest rank" in "\n".join(out)
    assert "STRAGGLER OP all_gather: rank 0 is 6x the fastest rank" in "\n".join(out)


def test_cli_json_output(two_rank_run, capsys):
    rc = aggregate.main(["--json", str(two_rank_run / "run.jsonl")])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["phases"]["exchange"]["skew"] == 2.0


def test_cli_skew_threshold_silences_stragglers(two_rank_run, capsys):
    rc = aggregate.main(
        ["--skew-threshold", "10", str(two_rank_run / "run.jsonl")]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "STRAGGLER" not in out
    assert "OK no stragglers above 10x" in out


def test_cli_missing_files(tmp_path, capsys):
    rc = aggregate.main([str(tmp_path / "nope.jsonl")])
    assert rc == 1


def test_corrupt_lines_skipped(tmp_path):
    p = tmp_path / "r.jsonl"
    p.write_text('{"kind": "time", "phase": "a", "seconds": 1.0}\n'
                 "not json at all\n"
                 '{"kind": "time", "phase": "a", "seconds": 3.0}\n')
    s = aggregate.summarize([str(p)])
    # both valid records land on the same (file-index) rank
    assert s["phases"]["a"]["per_rank_s"] == {"0": 4.0}


def test_avg_py_expands_rank_suffixed_jsonl(two_rank_run, capsys):
    """tpu/avg.py --key globs the per-rank set from the base path."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "tpu_avg", Path(__file__).resolve().parent.parent / "tpu" / "avg.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main(
        ["--no-native", "--pattern", "time", "--key", "seconds",
         str(two_rank_run / "run.jsonl")]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "run.p0.jsonl" in out and "run.p1.jsonl" in out
