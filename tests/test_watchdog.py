"""Watchdog (hang failure-detection) tests."""

import threading
import time

from tpu_mpi_tests.instrument.watchdog import Watchdog, deadline


def test_deadline_noop_when_disabled():
    with deadline(None):
        pass
    with deadline(0):
        pass


def test_deadline_completes_in_time():
    with deadline(30, "fast-phase"):
        time.sleep(0.01)
    # completing cancels the timer; nothing fires afterwards
    time.sleep(0.05)


def test_watchdog_fires_on_timeout():
    fired = threading.Event()
    msgs = []

    def on_timeout(msg):
        msgs.append(msg)
        fired.set()

    wd = Watchdog(0.05, "hung-allgather", _on_timeout=on_timeout).start()
    assert fired.wait(timeout=5.0)
    wd.cancel()
    assert "hung-allgather" in msgs[0]
    assert "hung collective" in msgs[0]


def test_watchdog_attributes_last_comm_op(monkeypatch):
    """A wedged RDMA semaphore hangs silently; the watchdog names the last
    dispatched comm op so the hang is attributable (VERDICT r1 missing #4)."""
    from tpu_mpi_tests.instrument import telemetry as T
    from tpu_mpi_tests.instrument import watchdog as W

    # fresh registry so state from other tests cannot satisfy the asserts
    monkeypatch.setattr(T, "_TELEMETRY", T.Telemetry())
    W.note_comm_op("ring_halo_pallas(axis=0, world=8)")
    fired = threading.Event()
    msgs = []

    def on_timeout(msg):
        msgs.append(msg)
        fired.set()

    wd = Watchdog(0.05, "rdma-exchange", _on_timeout=on_timeout).start()
    assert fired.wait(timeout=5.0)
    wd.cancel()
    assert "ring_halo_pallas(axis=0, world=8)" in msgs[0]
    assert "dispatched" in msgs[0]


def test_watchdog_dumps_flight_recorder_history(monkeypatch):
    """A watchdog fire dumps the recent comm-op HISTORY (≥8 events with
    ages), not just the single most recent op — 'wedged on the first
    collective' and 'ran 10k exchanges then stalled' must look different."""
    from tpu_mpi_tests.instrument import telemetry as T
    from tpu_mpi_tests.instrument import watchdog as W

    monkeypatch.setattr(T, "_TELEMETRY", T.Telemetry())
    for i in range(12):
        W.note_comm_op(f"op_number_{i}(world=8)")

    fired = threading.Event()
    msgs = []

    def on_timeout(msg):
        msgs.append(msg)
        fired.set()

    wd = Watchdog(0.05, "hung-ring", _on_timeout=on_timeout).start()
    assert fired.wait(timeout=5.0)
    wd.cancel()
    # the last >= 8 recorded ops appear, newest last, each with an age
    for i in range(4, 12):
        assert f"op_number_{i}(world=8)" in msgs[0]
    assert msgs[0].index("op_number_4") < msgs[0].index("op_number_11")
    assert "s ago" in msgs[0]


def test_rdma_exchange_records_comm_op(mesh8, monkeypatch):
    """The PALLAS_RDMA halo path registers itself with the watchdog."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_mpi_tests.comm.halo import Staging, halo_exchange
    from tpu_mpi_tests.instrument import telemetry as T
    from tpu_mpi_tests.instrument import watchdog as W

    # fresh registry so the assertions below can only be satisfied by the
    # halo_exchange call itself
    monkeypatch.setattr(T, "_TELEMETRY", T.Telemetry())
    assert W.last_comm_op() is None
    z = np.arange(8 * 12 * 8, dtype=np.float32).reshape(8 * 12, 8)
    zs = jax.device_put(z, NamedSharding(mesh8, P("shard", None)))
    try:  # tpumt: ignore[TPM1703] — the swallow IS the contract under test
        halo_exchange(zs, mesh8, axis=0, staging=Staging.PALLAS_RDMA)
    except Exception:
        # the dispatch note must precede kernel build/launch — that is the
        # attribution contract (a wedged kernel can never report itself),
        # so it must be recorded even where this jax cannot run the kernel
        pass
    op = W.last_comm_op()
    assert op is not None and "ring_halo_pallas(axis=0" in op
    assert "world=8" in op


def test_watchdog_cancel_prevents_firing():
    fired = threading.Event()
    wd = Watchdog(
        0.05, "p", _on_timeout=lambda m: fired.set()
    ).start()
    wd.cancel()
    time.sleep(0.15)
    assert not fired.is_set()


def test_idle_watchdog_idle_gap_does_not_fire():
    """Serve-mode contract: an idle gap LONGER than the deadline must
    not dump — the deadline clock only runs while armed (open-loop
    Poisson gaps between arrivals are legitimate idleness)."""
    from tpu_mpi_tests.instrument.watchdog import IdleAwareWatchdog

    fired = threading.Event()
    wd = IdleAwareWatchdog(
        0.05, "serve", _on_timeout=lambda m: fired.set()
    )
    # armed + disarmed around a fast batch, then idle 3x the deadline
    wd.arm("serve:daxpy")
    wd.disarm()
    time.sleep(0.15)
    assert not fired.is_set()
    # re-arm/disarm cycles across idle gaps stay quiet too
    for _ in range(3):
        wd.arm()
        wd.disarm()
        time.sleep(0.06)
    assert not fired.is_set()


def test_idle_watchdog_wedged_batch_still_fires():
    """Armed and never disarmed (a genuinely hung batch) fires with the
    armed phase in the diagnosis."""
    from tpu_mpi_tests.instrument.watchdog import IdleAwareWatchdog

    fired = threading.Event()
    msgs = []

    def on_timeout(msg):
        msgs.append(msg)
        fired.set()

    wd = IdleAwareWatchdog(0.05, "serve", _on_timeout=on_timeout)
    wd.arm("serve:allreduce:512:float32")
    assert fired.wait(timeout=5.0)
    wd.disarm()
    assert "serve:allreduce:512:float32" in msgs[0]


def test_idle_watchdog_arm_resets_deadline():
    """Each arm() restarts the clock: N short batches each under the
    deadline never fire even though they sum past it."""
    from tpu_mpi_tests.instrument.watchdog import IdleAwareWatchdog

    fired = threading.Event()
    wd = IdleAwareWatchdog(
        0.08, "serve", _on_timeout=lambda m: fired.set()
    )
    for _ in range(4):
        with wd.active("serve:daxpy"):
            time.sleep(0.04)  # half the deadline, 2x total
    assert not fired.is_set()


def test_watchdog_dumps_memory_state(monkeypatch):
    """The fire dump carries the memory axis: live-array census buckets
    (census-only on CPU — memory_stats is absent there) alongside the
    comm-op history, and mirrors a kind:"mem" record to the sink."""
    import jax.numpy as jnp

    from tpu_mpi_tests.instrument import telemetry as T

    monkeypatch.setattr(T, "_TELEMETRY", T.Telemetry())
    records = []
    T._TELEMETRY.enable(sink=records.append)
    keep = jnp.ones((333,), jnp.float32)
    fired = threading.Event()
    msgs = []

    def on_timeout(msg):
        msgs.append(msg)
        fired.set()

    wd = Watchdog(0.05, "hung-oom", _on_timeout=on_timeout).start()
    assert fired.wait(timeout=5.0)
    wd.cancel()
    assert "memory at fire:" in msgs[0]
    assert "LIVE census:" in msgs[0]
    assert "333·float32" in msgs[0]
    mems = [r for r in records if r.get("kind") == "mem"]
    assert mems and mems[0]["event"] == "watchdog"
    assert mems[0]["census"]["top"]
    del keep


def test_watchdog_memory_dump_includes_device_stats(monkeypatch):
    """Where the backend reports memory_stats, per-device watermark
    lines appear (top-8 census entries stay alongside)."""
    from tpu_mpi_tests.instrument import memwatch
    from tpu_mpi_tests.instrument import watchdog as W

    monkeypatch.setattr(
        memwatch, "device_memory_stats",
        lambda: {"0": {"bytes_in_use": 123, "peak_bytes_in_use": 456}},
    )
    lines = W.memory_state_lines(top_k=8)
    text = "\n".join(lines)
    assert "HBM dev0: bytes_in_use=123 peak_bytes_in_use=456" in text
