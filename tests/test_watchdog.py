"""Watchdog (hang failure-detection) tests."""

import threading
import time

from tpu_mpi_tests.instrument.watchdog import Watchdog, deadline


def test_deadline_noop_when_disabled():
    with deadline(None):
        pass
    with deadline(0):
        pass


def test_deadline_completes_in_time():
    with deadline(30, "fast-phase"):
        time.sleep(0.01)
    # completing cancels the timer; nothing fires afterwards
    time.sleep(0.05)


def test_watchdog_fires_on_timeout():
    fired = threading.Event()
    msgs = []

    def on_timeout(msg):
        msgs.append(msg)
        fired.set()

    wd = Watchdog(0.05, "hung-allgather", _on_timeout=on_timeout).start()
    assert fired.wait(timeout=5.0)
    wd.cancel()
    assert "hung-allgather" in msgs[0]
    assert "hung collective" in msgs[0]


def test_watchdog_cancel_prevents_firing():
    fired = threading.Event()
    wd = Watchdog(
        0.05, "p", _on_timeout=lambda m: fired.set()
    ).start()
    wd.cancel()
    time.sleep(0.15)
    assert not fired.is_set()
