"""Communication anatomy (instrument/anatomy.py): wait/wire
decomposition over seq-matched collective spans, the clock-uncertainty
honesty floor, the rank-pair traffic matrix, and each consumer surface
(ANATOMY/COMMGRAPH report tables, --diff series, trace sub-spans and
traffic counters, doctor evidence upgrade, tpumt-top WAIT column) —
plus the pre-seq degrade every surface keys its legacy shape on.

Fixtures are synthesized with KNOWN clock offsets so decompositions
check as exact arithmetic, not tolerances: rank 1's raw clock runs
0.5 s ahead and it enters every collective 0.2 s late on the corrected
axis, so each matched call splits into wait=0.2 wire=0.1 per the early
rank exactly.
"""

import json

import pytest

from tpu_mpi_tests.instrument import aggregate, anatomy, diagnose, timeline
from tpu_mpi_tests.instrument.live import Dashboard, render


def _write_jsonl(path, records):
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def _manifest(rank, n=2):
    return {"kind": "manifest", "process_index": rank,
            "process_count": n, "platform": "cpu",
            "global_device_count": n, "device_kinds": ["cpu"],
            "jax": "0.0-test", "argv": ["anatomy-test"]}


def _sync(rank, offset, spread=0.0005):
    return {"kind": "clock_sync", "rank": rank, "offset_s": offset,
            "spread_s": spread, "method": "barrier_echo",
            "run_sync_us": 1}


def _span(op, seq, t0, t1, *, axis="x", world=2, nbytes=1 << 20,
          **extra):
    return {"kind": "span", "op": op, "axis": axis, "seq": seq,
            "world": world, "nbytes": nbytes, "seconds": t1 - t0,
            "t_start": t0, "t_end": t1, **extra}


def _skewed_run(tmp_path, calls=4, spread=0.0005, drop_last_on_r1=False):
    """Two ranks, rank 1 offset +0.5 raw, entering 0.2 s late every
    call; each call: r0 [100+k, 100.3+k], r1 [100.2+k, 100.3+k] on the
    corrected axis. unc = 2*spread."""
    r0 = [_manifest(0), _sync(0, 0.0, spread)]
    r1 = [_manifest(1), _sync(1, 0.5, spread)]
    for k in range(calls):
        r0.append(_span("allreduce", k, 100.0 + k, 100.3 + k))
        if not (drop_last_on_r1 and k == calls - 1):
            r1.append(_span("allreduce", k, 100.7 + k, 100.8 + k))
    _write_jsonl(tmp_path / "run.p0.jsonl", r0)
    _write_jsonl(tmp_path / "run.p1.jsonl", r1)
    return [str(tmp_path / "run.p0.jsonl"),
            str(tmp_path / "run.p1.jsonl")]


class TestDecomposition:
    def test_known_offsets_exact_split(self, tmp_path):
        files = _skewed_run(tmp_path)
        anat = anatomy.anatomize(timeline.rank_streams(files))
        row = anat["ops"]["allreduce"]
        # per call: r0 wait 0.2 wire 0.1, r1 wait 0 wire 0.1
        assert row["calls"] == 4 and row["unmatched"] == 0
        assert row["wait_s"] == pytest.approx(0.8)
        assert row["wire_s"] == pytest.approx(0.8)
        assert row["span_s"] == pytest.approx(1.6)
        assert row["wait_frac"] == pytest.approx(0.5)
        assert row["unresolved"] == 0
        # the latest entrant holds ALL the wait: rank 1
        assert row["wait_share"] == [(1, pytest.approx(1.0))]
        # bytes priced per matched call across both ranks
        assert row["bytes"] == 4 * 2 * (1 << 20)
        assert row["pure_gbps"] == pytest.approx(
            row["bytes"] / 0.8 / 1e9)
        assert row["eff_gbps"] == pytest.approx(
            row["bytes"] / 1.6 / 1e9)
        assert anat["clock_unc_s"] == pytest.approx(0.001)

    def test_unresolved_floor_never_fabricates(self, tmp_path):
        # spread 0.15 each -> unc 0.3 > the true 0.2 skew: every
        # per-rank wait reads unresolved, the split is refused
        files = _skewed_run(tmp_path, spread=0.15)
        row = anatomy.anatomize(
            timeline.rank_streams(files))["ops"]["allreduce"]
        assert row["unresolved"] == 4
        assert row["wait_s"] == 0.0
        assert row["wait_frac"] == 0.0
        assert row["wait_share"] == []
        # all span time reads as wire; the wire total clears the floor
        # so pure GB/s still reports (now equal to effective)
        assert row["wire_s"] == pytest.approx(row["span_s"])
        assert row["pure_gbps"] == pytest.approx(row["eff_gbps"])

    def test_missing_rank_call_counts_unmatched(self, tmp_path):
        files = _skewed_run(tmp_path, drop_last_on_r1=True)
        row = anatomy.anatomize(
            timeline.rank_streams(files))["ops"]["allreduce"]
        assert row["calls"] == 3
        assert row["unmatched"] == 1  # r0's orphan seq 3
        assert row["wait_s"] == pytest.approx(0.6)

    def test_pre_seq_streams_anatomize_none(self, tmp_path):
        recs0 = [_manifest(0), _sync(0, 0.0)]
        recs1 = [_manifest(1), _sync(1, 0.5)]
        for k in range(4):
            for recs, t0 in ((recs0, 100.0 + k), (recs1, 100.7 + k)):
                s = _span("allreduce", 0, t0, t0 + 0.1)
                del s["seq"]
                recs.append(s)
        _write_jsonl(tmp_path / "run.p0.jsonl", recs0)
        _write_jsonl(tmp_path / "run.p1.jsonl", recs1)
        files = [str(tmp_path / "run.p0.jsonl"),
                 str(tmp_path / "run.p1.jsonl")]
        assert anatomy.anatomize(timeline.rank_streams(files)) is None

    def test_single_rank_spans_do_not_match(self, tmp_path):
        _write_jsonl(tmp_path / "run.p0.jsonl", [
            _manifest(0, n=1), _sync(0, 0.0),
            _span("allreduce", 0, 100.0, 100.1),
        ])
        streams = timeline.rank_streams([str(tmp_path / "run.p0.jsonl")])
        anat = anatomy.anatomize(streams)
        assert anat is None or anat["ops"] == {}

    def test_wait_wire_subspans_split_points(self, tmp_path):
        files = _skewed_run(tmp_path, calls=2)
        splits = anatomy.wait_wire_subspans(timeline.rank_streams(files))
        assert splits == {
            ("allreduce", "x", 0): pytest.approx(100.2),
            ("allreduce", "x", 1): pytest.approx(101.2),
        }

    def test_critical_path_walks_backward_across_ranks(self, tmp_path):
        files = _skewed_run(tmp_path, calls=2)
        path = anatomy.critical_path(timeline.rank_streams(files))
        assert path, "skewed run must yield a chain"
        # oldest first; the chain ends at the globally last segment
        assert path[-1]["t_start"] == max(s["t_start"] for s in path)
        assert all(s["seconds"] > 0 for s in path)


class TestTrafficMatrix:
    def test_halo_partner_edges_symmetric_non_periodic(self, tmp_path):
        per_edge = 4096
        for rank in (0, 1):
            _write_jsonl(tmp_path / f"run.p{rank}.jsonl", [
                _manifest(rank), _sync(rank, 0.0),
                _span("halo_exchange", 0, 100.0, 100.1,
                      partners=[-1, 1], periodic=False,
                      partner_nbytes=per_edge),
            ])
        files = [str(tmp_path / f"run.p{r}.jsonl") for r in (0, 1)]
        m = anatomy.traffic_matrix(timeline.rank_streams(files))
        # out-of-range neighbors dropped at the edges; the kept pair
        # of directed edges is symmetric
        assert m == {(0, 1): {"halo_exchange": per_edge},
                     (1, 0): {"halo_exchange": per_edge}}

    def test_periodic_ring_wraps_modulo_world(self):
        rec = _span("ring_attention", 0, 0.0, 1.0, world=4,
                    partners=[1], periodic=True, partner_nbytes=300)
        assert anatomy.partner_edges(rec, 3) == [(0, 300)]

    def test_spans_without_partners_contribute_nothing(self):
        assert anatomy.partner_edges(
            _span("allreduce", 0, 0.0, 1.0), 0) == []


class TestReportSurface:
    def test_text_tables_and_json_key(self, tmp_path, capsys):
        files = _skewed_run(tmp_path)
        assert aggregate.main(files) == 0
        out = capsys.readouterr().out
        line = next(ln for ln in out.splitlines()
                    if ln.startswith("ANATOMY allreduce:"))
        assert "wait_frac=0.500" in line
        assert "wait_share r1=100%" in line
        assert "unresolved=0" in line
        assert "ANATOMY critpath:" in out
        assert aggregate.main(files + ["--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["anatomy"]["ops"]["allreduce"]["calls"] == 4

    def test_commgraph_rows(self, tmp_path, capsys):
        for rank in (0, 1):
            _write_jsonl(tmp_path / f"run.p{rank}.jsonl", [
                _manifest(rank), _sync(rank, 0.0),
                _span("halo_exchange", 0, 100.0, 100.1,
                      partners=[-1, 1], periodic=False,
                      partner_nbytes=512),
            ])
        assert aggregate.main(
            [str(tmp_path / f"run.p{r}.jsonl") for r in (0, 1)]) == 0
        out = capsys.readouterr().out
        assert "COMMGRAPH 0->1: bytes=512 halo_exchange=512" in out
        assert "COMMGRAPH 1->0: bytes=512 halo_exchange=512" in out

    def test_pre_seq_report_has_no_anatomy_surface(self, tmp_path,
                                                   capsys):
        """The legacy-shape gate: pre-seq files must produce a summary
        WITHOUT the anatomy key and a report without the new tables."""
        for rank in (0, 1):
            recs = [_manifest(rank), _sync(rank, 0.0)]
            for k in range(4):
                s = _span("allreduce", 0, 100.0 + k, 100.1 + k)
                del s["seq"]
                recs.append(s)
            _write_jsonl(tmp_path / f"run.p{rank}.jsonl", recs)
        files = [str(tmp_path / f"run.p{r}.jsonl") for r in (0, 1)]
        assert aggregate.main(files + ["--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert "anatomy" not in s
        assert aggregate.main(files) == 0
        out = capsys.readouterr().out
        assert "ANATOMY" not in out and "COMMGRAPH" not in out

    def test_diff_series_flags_wait_regression(self, tmp_path, capsys):
        files = _skewed_run(tmp_path)
        s = aggregate.summarize(files)
        m = aggregate._metrics_from_summary(s)
        assert m["anatomy:allreduce:wait_frac"]["value"] == \
            pytest.approx(0.5)
        assert m["anatomy:allreduce:wait_frac"]["higher_better"] is False
        assert m["anatomy:allreduce:pure_gbps"]["higher_better"] is True
        # self-diff is clean (exit 0)...
        base = str(tmp_path / "run.jsonl")
        assert aggregate.main(["--diff", base, base]) == 0
        capsys.readouterr()
        # ...and a degraded copy (every call 3x more skewed) exits 1
        # with the anatomy series named
        worse = tmp_path / "worse"
        worse.mkdir()
        r0 = [_manifest(0), _sync(0, 0.0)]
        r1 = [_manifest(1), _sync(1, 0.5)]
        for k in range(4):
            r0.append(_span("allreduce", k, 100.0 + k, 100.9 + k))
            r1.append(_span("allreduce", k, 101.3 + k, 101.4 + k))
        _write_jsonl(worse / "run.p0.jsonl", r0)
        _write_jsonl(worse / "run.p1.jsonl", r1)
        assert aggregate.main(
            ["--diff", base, str(worse / "run.jsonl")]) == 1
        out = capsys.readouterr().out
        assert "anatomy:allreduce:wait_frac" in out


class TestTraceSurface:
    def test_wait_wire_subspans_rendered(self, tmp_path):
        files = _skewed_run(tmp_path)
        doc = timeline.chrome_trace(files)
        waits = [e for e in doc["traceEvents"]
                 if e.get("ph") == "X" and e["name"] == "wait allreduce"]
        wires = [e for e in doc["traceEvents"]
                 if e.get("ph") == "X" and e["name"] == "wire allreduce"]
        # the early rank's 4 calls split; the late rank (last arriver)
        # renders all-wire
        assert len(waits) == 4
        assert len(wires) == 8
        assert all(e["cat"] == "comm_wait" for e in waits)
        # each wait sub-span covers the known 0.2 s skew
        for e in waits:
            assert e["dur"] == pytest.approx(0.2e6, rel=1e-3)

    def test_traffic_counter_track(self, tmp_path):
        for rank in (0, 1):
            _write_jsonl(tmp_path / f"run.p{rank}.jsonl", [
                _manifest(rank), _sync(rank, 0.0),
                _span("halo_exchange", 0, 100.0, 100.1,
                      partners=[-1, 1], periodic=False,
                      partner_nbytes=256),
                _span("halo_exchange", 1, 101.0, 101.1,
                      partners=[-1, 1], periodic=False,
                      partner_nbytes=256),
            ])
        doc = timeline.chrome_trace(
            [str(tmp_path / f"run.p{r}.jsonl") for r in (0, 1)])
        cnt = [e for e in doc["traceEvents"]
               if e.get("ph") == "C" and e["name"] == "comm bytes sent"]
        assert cnt and all(e["cat"] == "traffic" for e in cnt)
        # cumulative: the second call doubles the edge byte count
        last = max((e for e in cnt if e["pid"] == cnt[0]["pid"]),
                   key=lambda e: e["ts"])
        assert 512 in last["args"].values()

    def test_pre_seq_trace_has_no_new_tracks(self, tmp_path):
        recs = [_manifest(0), _sync(0, 0.0)]
        s = _span("allreduce", 0, 100.0, 100.1)
        del s["seq"]
        recs.append(s)
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)
        doc = timeline.chrome_trace([str(tmp_path / "run.p0.jsonl")])
        names = {e.get("name") for e in doc["traceEvents"]}
        assert "wait allreduce" not in names
        assert "comm bytes sent" not in names


class TestDoctorSurface:
    def _streams(self, tmp_path, with_seq=True):
        """Rank 1 enters every halo_exchange 0.49 s late: sync-honest
        spans make rank 0 (the waiter) slow and rank 1 fast."""
        r0 = [_manifest(0), _sync(0, 0.0, 0.001)]
        r1 = [_manifest(1), _sync(1, 0.0, 0.001)]
        for k in range(6):
            a = _span("halo_exchange", k, 100.0 + k, 100.5 + k)
            b = _span("halo_exchange", k, 100.49 + k, 100.5 + k)
            if not with_seq:
                del a["seq"], b["seq"]
            r0.append(a)
            r1.append(b)
        for recs, rank in ((r0, 0), (r1, 1)):
            recs += [{"kind": "mem", "event": "final", "t": 120.0,
                      "live_bytes": 100},
                     {"kind": "telemetry_summary", "op": "x",
                      "rank": rank, "ops": 1, "bytes": 1,
                      "seconds": 0.0}]
        _write_jsonl(tmp_path / "run.p0.jsonl", r0)
        _write_jsonl(tmp_path / "run.p1.jsonl", r1)
        return [str(tmp_path / "run.p0.jsonl"),
                str(tmp_path / "run.p1.jsonl")]

    def test_seq_streams_upgrade_to_anatomy_evidence(self, tmp_path):
        (f,) = diagnose.diagnose_files(self._streams(tmp_path))
        assert f["class"] == "straggler" and f["rank"] == 1
        assert f["confidence"] >= 0.75
        assert "anatomy: rank 1 held 100% of the wait" in f["detail"]
        assert any(ev.startswith("anatomy: 6 matched halo_exchange")
                   for ev in f["evidence"])
        # call-level ref: file:line of the culprit's worst entry
        assert any("seq=" in ev and ".jsonl:" in ev
                   for ev in f["evidence"])

    def test_pre_seq_streams_keep_inversion_verdict(self, tmp_path):
        files = self._streams(tmp_path, with_seq=False)
        (f,) = diagnose.diagnose_files(files)
        assert f["class"] == "straggler" and f["rank"] == 1
        assert "invert" in f["detail"]
        assert "anatomy" not in f["detail"]
        assert f["evidence"] == []


class TestLiveSurface:
    def test_dashboard_wait_column(self, tmp_path):
        files = _skewed_run(tmp_path)
        dash = Dashboard()
        for path in files:
            for ln in open(path):
                dash.feed(json.loads(ln), path)
        frame = render(dash, files)
        ops_hdr = next(ln for ln in frame.splitlines()
                       if ln.startswith("OPS"))
        assert "wait%" in ops_hdr
        row = next(ln for ln in frame.splitlines()
                   if "allreduce" in ln)
        # cumulative wait_frac of the 4 matched calls: exactly 50%
        assert "50.0" in row

    def test_pre_seq_feed_renders_dash(self, tmp_path):
        dash = Dashboard()
        s = _span("allreduce", 0, 100.0, 100.1)
        del s["seq"]
        for rec in [_manifest(0), _sync(0, 0.0), s]:
            dash.feed(rec, "p0")
        row = next(ln for ln in render(dash, ["p0"]).splitlines()
                   if "allreduce" in ln)
        assert row.rstrip().endswith("-")
