"""Driver-level tests for the stencil pillar."""

import re

from tpu_mpi_tests.drivers import stencil1d


def test_stencil1d_exact_f64(capsys):
    rc = stencil1d.main(["--n-global", "4096", "--dtype", "float64"])
    out = capsys.readouterr().out
    assert rc == 0
    errs = re.findall(r"\d/8 \[cpu\] err_norm = ([\d.]+)", out)
    assert len(errs) == 8
    assert all(float(e) < 1e-6 for e in errs)
    assert out.count("exchange time") == 8


def test_stencil1d_all_stagings(capsys):
    for staging in ("direct", "device", "host", "pallas"):
        rc = stencil1d.main(
            ["--n-global", "4096", "--dtype", "float64", "--staging", staging]
        )
        assert rc == 0, staging


def test_stencil1d_f32_gate_scales(capsys):
    rc = stencil1d.main(["--n-global", "65536", "--dtype", "float32"])
    assert rc == 0


def test_stencil1d_tight_tol_fails(capsys):
    rc = stencil1d.main(
        ["--n-global", "65536", "--dtype", "float32", "--tol", "1e-12"]
    )
    out = capsys.readouterr().out
    assert rc == 1
    assert "ERR_NORM FAIL" in out


def test_stencil1d_mi_units(capsys):
    rc = stencil1d.main(["--n-global-mi", "1", "--dtype", "float64"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "n_global=1048576" in out
