"""Test harness: distributed-on-CPU via fake devices (SURVEY.md §4).

The reference could only verify its distributed paths on real allocations
(summit/, jlse/). Here every distributed test runs on CPU with 8 fake
devices — real XLA collectives through the same shard_map code that runs on
TPU slices. Env must be set before jax is imported anywhere.
"""

import os

# The image pins JAX_PLATFORMS to the TPU tunnel; tests always run on the
# fake-device CPU mesh unless explicitly opted onto hardware.
if not os.environ.get("TPU_MPI_TESTS_ON_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
# The suite hard-requires 8 fake devices; strip any pre-existing count flag
# rather than producing confusing MeshErrors under a different value.
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)

import jax  # noqa: E402

if not os.environ.get("TPU_MPI_TESTS_ON_TPU"):
    # The image's sitecustomize registers the TPU plugin and sets
    # jax_platforms programmatically, overriding the env var — force it back.
    jax.config.update("jax_platforms", "cpu")

# The reference is float64 throughout (MPI_DOUBLE); enable x64 so parity
# tests can use the reference's dtype. Kernels take explicit dtypes, so
# float32 paths are still exercised (SURVEY §7 hard part 1).
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from tpu_mpi_tests.comm.mesh import make_mesh

    return make_mesh({"shard": 8})


@pytest.fixture(scope="session")
def mesh2d():
    from tpu_mpi_tests.comm.mesh import make_mesh

    return make_mesh({"x": 4, "y": 2})
