"""The online re-tune controller (ISSUE 14 tentpole c): tune_stale →
bounded between-windows re-sweep → hot swap through registry.resolve →
kind:"control" tune_swap records — plus the CONTROL table, the trace
marker, and the doctor's stale_schedule verdict over the same records."""

import json

import pytest

from tpu_mpi_tests.instrument.metrics import (
    STALE_SAMPLES,
    MetricsRegistry,
)
from tpu_mpi_tests.tune import registry as tr
from tpu_mpi_tests.tune.controller import TuneController


@pytest.fixture(autouse=True)
def _isolated_registry(monkeypatch):
    monkeypatch.delenv("TPU_MPI_TUNE_CACHE", raising=False)
    tr.deconfigure()
    yield
    tr.deconfigure()


def _span(op, gbps):
    return {"kind": "span", "op": op, "nbytes": 1 << 20,
            "seconds": 0.01, "gbps": gbps}


def _latch_stale(reg, op, base=10.0, sagged=1.0):
    """Drive the registry's tune_stale watch to a latch: a tuned knob
    goes live, the op baselines at ``base`` GB/s, then a full rolling
    window sags to ``sagged``."""
    reg.observe({"kind": "tune_hit", "knob": "demo/knob", "value": 1})
    for _ in range(STALE_SAMPLES):
        reg.observe(_span(op, base))
    for _ in range(STALE_SAMPLES):
        reg.observe(_span(op, sagged))


def _teed_registry(records):
    """A registry whose health sink mirrors the Reporter wiring: the
    fired record lands in the JSONL (``records``) AND tees back through
    observe — which is what delivers it to health listeners (the
    controller's latch)."""
    reg = MetricsRegistry()
    reg.set_health_sink(
        lambda rec: (records.append(rec), reg.observe(rec)))
    return reg


class _FakeHandlers:
    """A rebuildable serve handler whose speed is keyed on the resolved
    candidate — the degraded-winner shape the controller exists for."""

    def __init__(self, knob, timing, default):
        self.knob = knob
        self.timing = dict(timing)
        self.default = default
        self.built = []

    def build(self, value=None):
        eff = value if value is not None else tr.resolve(
            self.knob, prior=self.default)
        self.built.append(eff)
        cost = self.timing[eff]

        def step(k: int):
            import time

            time.sleep(cost * k)

        step.tune_info = {
            "knob": self.knob,
            "ctx": {},
            "candidates": tuple(self.timing),
            "rebuild": self.build,
        }
        return step


def test_controller_closes_the_loop(tmp_path):
    """stale latch → re-sweep (real sweep engine, winner persisted) →
    hot swap via registry.resolve → control record → latch reset."""
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)
    records = []
    reg = _teed_registry(records)
    fake = _FakeHandlers("demo/knob", {"slow": 0.005, "fast": 0.0},
                         default="slow")
    handlers = {"daxpy:64:float32": fake.build()}
    ctl = TuneController(reg, handlers, sink=records.append,
                         line=lambda s: None, budget_s=30.0)

    op = "serve:daxpy:64:float32"
    _latch_stale(reg, op)
    stale = [r for r in records if r.get("kind") == "health"
             and r.get("event") == "tune_stale"]
    assert len(stale) == 1 and stale[0]["op"] == op

    old_step = handlers["daxpy:64:float32"]
    assert ctl.window_boundary(1000.0) == 1
    # the re-sweep ran through the REAL sweep engine: candidate records
    # plus a tune_result, winner measured not guessed
    kinds = [r["kind"] for r in records]
    assert kinds.count("tune") == 2 and "tune_result" in kinds
    swap = [r for r in records if r.get("kind") == "control"][0]
    assert swap["event"] == "tune_swap"
    assert swap["class"] == "daxpy:64:float32"
    assert swap["knob"] == "demo/knob"
    assert swap["old"] == "slow" and swap["new"] == "fast"
    assert swap["op"] == op and swap["t"] == 1000.0
    assert swap["resweep_s"] > 0
    assert isinstance(swap["sag_pct"], (int, float))
    # hot-swapped THROUGH registry.resolve: the new handler re-resolved
    # and picked up the persisted winner
    assert handlers["daxpy:64:float32"] is not old_step
    assert fake.built[-1] == "fast"
    assert tr.resolve("demo/knob", prior="slow") == "fast"
    # the stale latch was reset: the op re-baselines on the new
    # schedule and can fire again after another full sag cycle
    _latch_stale(reg, op, base=8.0, sagged=1.0)
    assert [r for r in records if r.get("event") == "tune_stale"][1:]


def test_controller_ignores_classes_without_tune_info(tmp_path):
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)
    records = []
    reg = _teed_registry(records)

    def bare_step(k):
        return None

    handlers = {"daxpy:64:float32": bare_step}
    ctl = TuneController(reg, handlers, sink=records.append,
                         line=lambda s: None)
    _latch_stale(reg, "serve:daxpy:64:float32")
    assert ctl.window_boundary(1.0) == 0
    assert [r for r in records if r.get("kind") == "control"] == []
    assert handlers["daxpy:64:float32"] is bare_step


def test_controller_ignores_non_serve_ops(tmp_path):
    """A stale op inside a handler (halo_exchange) has no handler to
    rebuild: the controller degrades to a no-op, never an error."""
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)
    reg = _teed_registry([])
    fake = _FakeHandlers("demo/knob", {"a": 0.0}, default="a")
    handlers = {"daxpy:64:float32": fake.build()}
    ctl = TuneController(reg, handlers, sink=lambda r: None,
                         line=lambda s: None)
    _latch_stale(reg, "halo_exchange")
    assert ctl.window_boundary(1.0) == 0


def test_controller_survives_failing_rebuild(tmp_path):
    """A re-tune that blows up mid-sweep must not kill serving: the old
    handler stays installed and the error surfaces as a line."""
    tr.configure(cache_path=str(tmp_path / "t.json"), enabled=True)
    lines = []
    reg = _teed_registry([])

    def exploding_rebuild(value=None):
        raise RuntimeError("compile blew up")

    def step(k):
        return None

    step.tune_info = {"knob": "demo/knob", "ctx": {},
                      "candidates": ("a", "b"),
                      "rebuild": exploding_rebuild}
    handlers = {"daxpy:64:float32": step}
    ctl = TuneController(reg, handlers, sink=lambda r: None,
                         line=lines.append)
    op = "serve:daxpy:64:float32"
    _latch_stale(reg, op)
    assert ctl.window_boundary(1.0) == 0
    assert handlers["daxpy:64:float32"] is step
    errors = [ln for ln in lines if "RETUNE ERROR" in ln]
    assert len(errors) == 1
    # the one-shot stale latch must not be abandoned on a transient
    # failure: later boundaries RETRY (bounded), then the watch is
    # re-baselined so a sustained sag can latch again
    assert ctl.window_boundary(2.0) == 0
    assert ctl.window_boundary(3.0) == 0
    errors = [ln for ln in lines if "RETUNE ERROR" in ln]
    assert len(errors) == 3  # initial + RETUNE_RETRIES
    assert ctl.window_boundary(4.0) == 0
    assert len([ln for ln in lines if "RETUNE ERROR" in ln]) == 3
    # retries spent → counter cleared AND the op's watch reset: a
    # fresh sag re-latches and gets the FULL retry budget again
    _latch_stale(reg, op, base=5.0, sagged=0.5)
    for t in (5.0, 6.0, 7.0):
        assert ctl.window_boundary(t) == 0
    assert len([ln for ln in lines if "RETUNE ERROR" in ln]) == 6
    assert ctl.window_boundary(8.0) == 0
    assert len([ln for ln in lines if "RETUNE ERROR" in ln]) == 6


def test_serve_loop_calls_controller_between_windows():
    """The loop consults the controller at window boundaries only —
    the quarantine-probe point, never mid-batch."""
    from tpu_mpi_tests.serve.arrival import OpenLoopPoisson
    from tpu_mpi_tests.serve.loop import ServeLoop
    from tpu_mpi_tests.serve.workloads import parse_workload_table

    calls = []

    class StubController:
        def window_boundary(self, t_wall):
            calls.append(t_wall)
            return 0

    classes = parse_workload_table("daxpy:64:float32")
    t = {"now": 0.0}

    def clock():
        return t["now"]

    def sleep(dt):
        t["now"] += max(dt, 1e-3)

    loop = ServeLoop(
        classes, {"daxpy:64:float32": lambda n: None},
        OpenLoopPoisson(5.0, seed=1),
        duration_s=10.0, window_s=2.0, seed=1,
        controller=StubController(),
        clock=clock, wall=clock, sleep=sleep,
    )
    loop.run()
    assert len(calls) >= 3  # one per elapsed window boundary


def test_metrics_reset_stale_rebaselines(tmp_path):
    """reset_stale forgets baseline AND latch: after a swap the op can
    latch again from fresh post-swap readings."""
    fired = []
    reg = MetricsRegistry(health_sink=fired.append)
    op = "serve:x"
    _latch_stale(reg, op)
    assert len(fired) == 1
    # latched: more sag does not re-fire
    for _ in range(STALE_SAMPLES):
        reg.observe(_span(op, 0.5))
    assert len(fired) == 1
    reg.reset_stale(op)
    _latch_stale(reg, op, base=5.0, sagged=0.5)
    assert len(fired) == 2


# ------------------------------------------------------------- surfacing


def test_report_control_table(tmp_path, capsys):
    from tpu_mpi_tests.instrument.aggregate import main as report_main

    f = tmp_path / "run.jsonl"
    recs = [
        {"kind": "control", "event": "tune_swap",
         "class": "daxpy:64:float32", "knob": "daxpy/chunk",
         "op": "serve:daxpy:64:float32", "signal": "gbps",
         "sag_pct": 41.5, "old": 1, "new": 32, "resweep_s": 0.25,
         "t": 100.0, "rank": 0},
        {"kind": "control", "event": "tune_swap",
         "class": "daxpy:64:float32", "knob": "daxpy/chunk",
         "op": "serve:daxpy:64:float32", "signal": "gbps",
         "sag_pct": 20.5, "old": 32, "new": 8, "resweep_s": 0.75,
         "t": 200.0, "rank": 0},
    ]
    f.write_text("".join(json.dumps(r) + "\n" for r in recs))
    assert report_main([str(f)]) == 0
    out = capsys.readouterr().out
    line = [ln for ln in out.splitlines()
            if ln.startswith("CONTROL")][0]
    assert "tune_swap daxpy:64:float32" in line
    assert "knob=daxpy/chunk" in line and "n=2" in line
    assert "old=1" in line and "new=8" in line
    assert "sag=31.0%" in line  # mean of the two swaps
    assert "resweep=1s" in line

    from tpu_mpi_tests.instrument.aggregate import summarize

    s = summarize([str(f)])
    json.dumps(s)  # --json path stays serializable
    row = s["control"]["daxpy:64:float32|daxpy/chunk"]
    assert row["swaps"] == 2 and row["old"] == 1 and row["new"] == 8


def test_trace_places_control_marker(tmp_path):
    from tpu_mpi_tests.instrument.timeline import chrome_trace

    f = tmp_path / "run.jsonl"
    recs = [
        {"kind": "manifest", "process_index": 0, "process_count": 1},
        {"kind": "span", "op": "serve:daxpy:64:float32",
         "seconds": 0.01, "t_start": 100.0, "t_end": 100.01},
        {"kind": "control", "event": "tune_swap",
         "class": "daxpy:64:float32", "knob": "daxpy/chunk",
         "op": "serve:daxpy:64:float32", "signal": "gbps",
         "sag_pct": 40.0, "old": 1, "new": 32, "resweep_s": 0.5,
         "t": 101.0, "rank": 0},
    ]
    f.write_text("".join(json.dumps(r) + "\n" for r in recs))
    doc = chrome_trace([str(f)])
    marks = [e for e in doc["traceEvents"]
             if e.get("cat") == "control"]
    assert len(marks) == 1, doc["traceEvents"]
    assert "tune_swap" in marks[0]["name"]
    assert marks[0]["args"]["old"] == 1 and marks[0]["args"]["new"] == 32


# ------------------------------------------------------ doctor verdicts


def _doctor_stream(tmp_path, recs, name="run.jsonl"):
    f = tmp_path / name
    f.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(f)


def _stale_rec(t=100.0, op="serve:daxpy:64:float32"):
    return {"kind": "health", "event": "tune_stale", "op": op,
            "signal": "gbps", "baseline": 10.0, "rolling": 1.0,
            "sag_pct": 90.0, "threshold_pct": 15.0, "n": 8,
            "knobs": ["daxpy/chunk"], "t": t, "rank": 0}


def _closing(t):
    return [{"kind": "span", "op": "x", "seconds": 0.01, "world": 1,
             "t_start": t, "t_end": t + 0.01},
            {"kind": "telemetry_summary", "op": "x", "rank": 0,
             "t": t + 1.0}]


def test_doctor_convicts_unanswered_stale_schedule(tmp_path):
    from tpu_mpi_tests.instrument.diagnose import diagnose_files

    f = _doctor_stream(
        tmp_path,
        [{"kind": "manifest", "process_index": 0, "process_count": 1}]
        + [_stale_rec(t=100.0)] + _closing(200.0),
    )
    findings = diagnose_files([f])
    assert [x["class"] for x in findings] == ["stale_schedule"]
    x = findings[0]
    assert x["rank"] == 0 and x["last_op"] == "serve:daxpy:64:float32"
    assert "no tune_swap followed" in x["detail"]
    assert x["t"] == 100.0


def test_doctor_exonerates_answered_stale(tmp_path):
    """A tune_swap after the latch is the loop CLOSING — the doctor
    must not convict exactly the runs the controller saves."""
    from tpu_mpi_tests.instrument.diagnose import diagnose_files

    swap = {"kind": "control", "event": "tune_swap",
            "class": "daxpy:64:float32", "knob": "daxpy/chunk",
            "op": "serve:daxpy:64:float32", "signal": "gbps",
            "sag_pct": 90.0, "old": 1, "new": 32, "resweep_s": 0.5,
            "t": 105.0, "rank": 0}
    f = _doctor_stream(
        tmp_path,
        [{"kind": "manifest", "process_index": 0, "process_count": 1},
         _stale_rec(t=100.0), swap] + _closing(200.0),
    )
    assert diagnose_files([f]) == []


def test_doctor_relatch_after_swap_still_convicts(tmp_path):
    """Latest latch wins in the digest: the --retune controller re-arms
    the watch after a swap, so an op can latch AGAIN — the old swap
    must not exonerate the new, unanswered latch."""
    from tpu_mpi_tests.instrument.diagnose import diagnose_files

    swap = {"kind": "control", "event": "tune_swap",
            "class": "daxpy:64:float32", "knob": "daxpy/chunk",
            "op": "serve:daxpy:64:float32", "signal": "gbps",
            "sag_pct": 90.0, "old": 1, "new": 32, "resweep_s": 0.5,
            "t": 15.0, "rank": 0}
    f = _doctor_stream(
        tmp_path,
        [{"kind": "manifest", "process_index": 0, "process_count": 1},
         _stale_rec(t=10.0), swap, _stale_rec(t=50.0)]
        + _closing(100.0),
    )
    findings = diagnose_files([f])
    assert [x["class"] for x in findings] == ["stale_schedule"]
    assert findings[0]["t"] == 50.0  # anchored at the NEW latch


def test_doctor_stale_grace_on_live_stream(tmp_path):
    """Mid-follow (followed=True), a latch fresher than the grace
    window stays unconvicted — the controller only acts at the next
    window boundary; the post-mortem pass convicts every unanswered
    latch regardless of freshness (the run ended, no swap can come)."""
    from tpu_mpi_tests.instrument.diagnose import diagnose_files

    # a mid-run stream: the stale latch landed 1 s before the last
    # record — inside the grace while followed, convicted post-mortem
    f = _doctor_stream(
        tmp_path,
        [{"kind": "manifest", "process_index": 0, "process_count": 1},
         {"kind": "span", "op": "x", "seconds": 0.01, "world": 1,
          "t_start": 99.0, "t_end": 99.01},
         _stale_rec(t=100.0),
         {"kind": "span", "op": "x", "seconds": 0.01, "world": 1,
          "t_start": 101.0, "t_end": 101.01}],
    )
    assert diagnose_files([f], followed=True) == []
    assert [x["class"] for x in diagnose_files([f])] \
        == ["stale_schedule"]
    # a latch older than the grace convicts even mid-follow
    f2 = _doctor_stream(
        tmp_path,
        [{"kind": "manifest", "process_index": 0, "process_count": 1},
         _stale_rec(t=100.0),
         {"kind": "span", "op": "x", "seconds": 0.01, "world": 1,
          "t_start": 120.0, "t_end": 120.01}],
        name="run2.jsonl",
    )
    assert [x["class"] for x in diagnose_files([f2], followed=True)] \
        == ["stale_schedule"]
