"""Native-layer tests: C++ aggregator (≅ avg.sh) and phase-timer library.

Native artifacts build on demand via make; tests skip if no toolchain."""

import os
import re
import shutil
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
AVG = REPO / "tpu" / "avg.py"


@pytest.fixture()
def outfiles(tmp_path):
    (tmp_path / "out-a.txt").write_text(
        "TIME gather : 1.5\nTIME gather : 2.5\nTIME kernel : 9.0\n"
    )
    (tmp_path / "out-b.txt").write_text("TIME gather : 4.0\n")
    (tmp_path / "out-c.txt").write_text(
        '{"kind": "time", "phase": "gather", "seconds": 0.25}\n'
        '{"kind": "time", "phase": "gather", "seconds": 0.75}\n'
    )
    return tmp_path


def run_avg(args, cwd):
    return subprocess.run(
        [sys.executable, str(AVG), *args],
        cwd=cwd,
        capture_output=True,
        text=True,
    )


def test_avg_python_fallback_matches_reference_semantics(outfiles):
    r = run_avg(["--no-native", "out-a.txt", "out-b.txt"], outfiles)
    assert r.returncode == 0
    assert "PATTERN=gather" in r.stdout  # avg.sh:9 prints the pattern
    assert "out-a.txt 2" in r.stdout  # mean of 1.5, 2.5
    assert "out-b.txt 4" in r.stdout


def test_avg_jsonl_key(outfiles):
    r = run_avg(["--no-native", "-k", "seconds", "out-c.txt"], outfiles)
    assert r.returncode == 0
    assert "out-c.txt 0.5" in r.stdout


def test_avg_default_glob_and_pattern(outfiles):
    r = run_avg(["--no-native", "--pattern", "kernel"], outfiles)
    assert "out-a.txt 9" in r.stdout


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_native_aggregator_matches_python(outfiles):
    r_native = run_avg(["-s", "out-a.txt"], outfiles)
    assert r_native.returncode == 0
    assert "out-a.txt 2 min=1.5 max=2.5 n=2" in r_native.stdout


@pytest.fixture(scope="module")
def launcher_bin():
    """Build tpumt_run from the current sources so no test runs a stale
    binary that predates the flag it exercises."""
    subprocess.run(
        ["make", "-C", str(REPO / "native"), "tpumt_run"],
        capture_output=True,
        check=True,
        timeout=120,
    )
    return str(REPO / "native" / "tpumt_run")


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_native_launcher_wires_rank_env(launcher_bin):
    r = subprocess.run(
        [
            launcher_bin,
            "-n", "3", "--",
            "sh", "-c",
            'echo "rank=$JAX_PROCESS_ID of $JAX_NUM_PROCESSES '
            'coord=$JAX_COORDINATOR_ADDRESS"',
        ],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert r.returncode == 0, r.stderr
    for rank in range(3):
        assert f"rank={rank} of 3" in r.stdout
    assert "coord=localhost:" in r.stdout


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_native_launcher_deadline_kills_hung_ranks(launcher_bin):
    """-t arms the batch-walltime backstop: hung ranks are killed and the
    launcher exits 124 instead of wedging forever (§5.3 failure detection
    at the launcher layer, ≅ job.lsf/job.pbs walltime). The hung rank is a
    shell with a background grandchild — the whole process group must die,
    not just the direct child."""
    sentinel = "31256.5"  # unique duration so the ps grep can't match
    t0 = time.time()
    r = subprocess.run(
        [launcher_bin, "-n", "2", "-t", "1", "--",
         "sh", "-c", f"sleep {sentinel} & wait"],
        capture_output=True,
        text=True,
        timeout=30,
    )
    assert r.returncode == 124
    assert "deadline of 1 s exceeded" in r.stderr
    # generous bound: the semantic claim is "did not wait out the sleep";
    # tight wall-clock bounds flake on loaded CI hosts
    assert time.time() - t0 < 25
    # no orphaned grandchild survives the group kill
    ps = subprocess.run(
        ["ps", "-eo", "args"], capture_output=True, text=True
    ).stdout
    assert f"sleep {sentinel}" not in ps


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_native_launcher_rejects_bad_timeout(launcher_bin):
    r = subprocess.run(
        [launcher_bin, "-n", "1", "-t", "bogus", "--", "true"],
        capture_output=True,
        text=True,
        timeout=30,
    )
    assert r.returncode == 2
    assert "-t wants seconds" in r.stderr


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_native_launcher_propagates_failure(launcher_bin):
    r = subprocess.run(
        [launcher_bin, "-n", "2", "--",
         "sh", "-c", 'exit "$JAX_PROCESS_ID"'],
        capture_output=True,
        timeout=60,
    )
    assert r.returncode == 1  # rank 1's nonzero exit surfaces


def test_native_time_monotonic_and_slots():
    from tpu_mpi_tests.instrument import native_time as NT

    t0 = NT.monotonic_ns()
    time.sleep(0.01)
    assert NT.monotonic_ns() - t0 >= 9_000_000  # >= 9 ms elapsed

    s = NT.NativePhaseSlots()
    s.reset(0)
    for _ in range(2):
        s.start(0)
        time.sleep(0.005)
        s.stop(0)
    assert s.count(0) == 2
    assert 0.008 <= s.seconds(0) <= 1.0


@pytest.mark.skipif(shutil.which("bash") is None, reason="no bash")
def test_job_matrix_sweep(tmp_path):
    """tpu/job.sh drives a 2×2 {world × space} matrix through run.sh and
    ends with the avg.py summary (≅ one summit/job.lsf submission,
    /root/reference/summit/job.lsf:9-16): every cell writes its
    out-<tag>.txt, multi-process cells get per-world-and-rank tags (the
    %q{PMIX_RANK} analog — VERDICT r2 missing #1/#2), and the final
    table aggregates a REAL numeric field (the reference's default
    'gather' pattern over 'TIME gather : <s>' lines)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [
            "bash", str(REPO / "tpu" / "job.sh"),
            "-w", "1 2", "-d", "mpi_daxpy_nvtx",
            "-s", "device managed",
            "--", "--fake-devices", "1", "--n-per-node", "65536",
        ],
        capture_output=True,
        text=True,
        cwd=tmp_path,
        env=env,
        timeout=420,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    host = subprocess.run(
        ["hostname", "-s"], capture_output=True, text=True
    ).stdout.strip()
    names = {p.name for p in tmp_path.glob("out-*.txt")}
    want = set()
    for space in ("device", "managed"):
        want.add(f"out-{space}_none_mpi_daxpy_nvtx_{host}.txt")
        for rank in (0, 1):
            want.add(
                f"out-{space}_none_mpi_daxpy_nvtx_{host}_w2_r{rank}.txt"
            )
    assert want <= names, (want, names)
    # the summary table must list every file WITH a parsed numeric mean
    # of the gather phase (not the no-matches branch)
    tail = (r.stdout + r.stderr).split("matrix complete", 1)
    assert len(tail) == 2, r.stdout + r.stderr
    for name in want:
        m = re.search(
            rf"{re.escape(name)}\s+([\d.eE+-]+)", tail[1]
        )
        assert m, (name, tail[1])
        assert float(m.group(1)) >= 0.0


def test_vmemprobe_configs_build():
    """tpu/vmemprobe.py's config table must stay buildable as the fit
    models evolve (each entry computes a model through the real fit
    functions; fn=None rows carry the fit's own rejection). The Mosaic
    bisection itself needs a TPU — this gates the host-side half."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "vmemprobe", REPO / "tpu" / "vmemprobe.py"
    )
    vp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(vp)
    cfgs = vp.configs()
    assert len(cfgs) >= 10
    names = [name for name, _, _ in cfgs]
    assert len(set(names)) == len(names)
    for name, fn, model in cfgs:
        if fn is None:
            continue  # a fit legitimately rejected this shape
        assert isinstance(model, int) and 0 < model <= 16 * 2**20, (
            name, model,
        )


def test_spawn_world_returns_first_nonzero_rc(tmp_path):
    """spawn_world's documented contract (round-3 advisor finding): the
    FIRST nonzero child exit code wins, later failures don't overwrite
    it, and the errexit-safe guard doesn't abort a `set -e` caller."""
    script = tmp_path / "t.sh"
    script.write_text(
        "set -eu\n"
        f". {REPO / 'tpu' / 'worldlib.sh'}\n"
        # rank 0 fails fast with 7; rank 1 fails later with 3 — pid-order
        # wait must return 7 (and keep waiting for rank 1)
        "fake() {\n"
        "  if [ \"$JAX_PROCESS_ID\" -eq 0 ]; then exit 7; fi\n"
        "  sleep 0.3; exit 3\n"
        "}\n"
        "rc=0\n"
        "spawn_world 2 fake || rc=$?\n"
        "echo \"rc=$rc\"\n"
    )
    r = subprocess.run(
        ["bash", str(script)], capture_output=True, text=True, timeout=60
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "rc=7" in r.stdout, r.stdout
