"""Pallas kernel tests (interpreter mode on CPU; the same code compiles on
TPU — validated there manually, see BASELINE.md A/B numbers).

≅ the role of ``test_buf_view`` for the SYCL pack/unpack kernels
(``mpi_stencil2d_sycl.cc:118-159``), promoted from a commented-out visual
check to real assertions (SURVEY.md §4.3)."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_mpi_tests.kernels import pallas_kernels as PK
from tpu_mpi_tests.kernels.daxpy import init_xy
from tpu_mpi_tests.kernels.pack import pack_edges, unpack_ghosts
from tpu_mpi_tests.kernels.stencil import stencil1d_5


def rng(seed, shape, dtype=np.float32):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape).astype(dtype)
    )


def test_daxpy_matches_reference_init():
    x, y = init_xy(1024, jnp.float32)
    out = PK.daxpy_pallas(2.0, x, y)
    assert jnp.allclose(out, x)  # y = 2x + (-x) = x (daxpy.cu:56-59,72-73)


def test_daxpy_multi_block():
    x, y = init_xy(128 * 1024, jnp.float32)
    out = PK.daxpy_pallas(2.0, x, y, block_rows=64)
    assert jnp.allclose(out, x)


def test_daxpy_rejects_unaligned():
    x = jnp.ones(100)
    with pytest.raises(ValueError, match="128"):
        PK.daxpy_pallas(2.0, x, x)


def test_stream_scale_matches_xla():
    x, _ = init_xy(64 * 1024, jnp.float32)
    out = PK.stream_scale_pallas(1.5, x)
    assert jnp.allclose(out, 1.5 * x)
    out = PK.stream_scale_pallas(0.5, x, block_rows=64)
    assert jnp.allclose(out, 0.5 * x)
    out = PK.stream_scale_pallas(2.0, x, inplace=True)
    assert jnp.allclose(out, 2.0 * x)


def test_stream_sum3_matches_xla():
    """The 4-stream ceiling probe (round-3 stream-count family) computes
    w + x + y, aliased and not."""
    w, x = init_xy(64 * 1024, jnp.float32)
    y = 2.0 * x
    for inplace in (False, True):
        out = PK.stream_sum3_pallas(w, x, y, inplace=inplace)
        assert jnp.allclose(out, w + x + y)
    out = PK.stream_sum3_pallas(w, x, y, block_rows=64)
    assert jnp.allclose(out, w + x + y)


def test_stream_block_rows_fits_vmem():
    # 3-buffer f32 → 4096 rows (12 MB double-buffered); 2-buffer f32 → 4096
    # (power-of-two floor); f64 halves, bf16 doubles — always ≤ 12 MB
    for itemsize, n_bufs in ((4, 3), (4, 2), (8, 3), (2, 3)):
        rows = PK._stream_block_rows(itemsize, n_bufs)
        assert rows & (rows - 1) == 0
        assert n_bufs * 2 * rows * 128 * itemsize <= 12 * 2**20


@pytest.mark.parametrize("dim", [0, 1])
def test_stencil_matches_xla(dim):
    shape = (260, 256) if dim == 0 else (256, 260)
    z = rng(dim, shape)
    got = PK.stencil2d_pallas(z, 3.0, dim=dim, tile=128)
    ref = stencil1d_5(z, 3.0, axis=dim)
    assert got.shape == ref.shape
    assert jnp.allclose(got, ref, atol=1e-5)


@pytest.mark.parametrize("dim", [0, 1])
def test_stencil_ragged_strips(dim):
    # extents that no power-of-two strip divides (257 prime factors)
    shape = (1028, 384) if dim == 0 else (384, 1028)
    z = rng(10 + dim, shape)
    got = PK.stencil2d_pallas(z, 2.0, dim=dim, tile=256)
    assert jnp.allclose(got, stencil1d_5(z, 2.0, axis=dim), atol=1e-5)


@pytest.mark.parametrize("dim", [0, 1])
def test_iterate_inplace_step(dim):
    shape = (68, 64) if dim == 0 else (64, 68)
    z0 = np.random.default_rng(0).normal(size=shape).astype(np.float32)
    got = PK.stencil2d_iterate_pallas(jnp.asarray(z0), 0.5, dim=dim)
    ref = np.array(z0)
    sl = (slice(2, -2), slice(None)) if dim == 0 else (slice(None),
                                                      slice(2, -2))
    ref[sl] += 0.5 * np.asarray(stencil1d_5(jnp.asarray(z0), 1.0, axis=dim))
    assert np.allclose(np.asarray(got), ref, atol=1e-5)


def _check_multistep_vs_repeated(dim, steps, m, other, dtype, flags,
                                 seed=0, stream=False):
    """Shared gate: a deep-halo ``steps``-step call must reproduce ``steps``
    single-step calls on the interior (both-sides-physical Dirichlet band)
    and leave the physical band untouched. One copy of the layout algebra
    serves the parametrized cases and the fuzz sweep."""
    K = steps * 2
    shape = (m + 2 * K, other) if dim == 0 else (other, m + 2 * K)
    z0 = np.random.default_rng(seed).normal(size=shape).astype(dtype)
    # the narrow (ghost-width-2) layout is the inner slice of the deep one
    sl = [slice(None), slice(None)]
    sl[dim] = slice(K - 2, K - 2 + m + 4)
    phys_kw = (
        {"phys_static": (1, 1)}
        if flags == "static"
        else {"phys": jnp.asarray([1, 1])}
    )
    extra = (
        {"stream": True, "stream_tile_rows": 16}
        if stream and dim == 0
        else {}
    )
    got = PK.stencil2d_iterate_pallas(
        jnp.asarray(z0), 0.25, dim=dim, steps=steps, **extra, **phys_kw
    )
    ref = jnp.asarray(z0[tuple(sl)])
    for _ in range(steps):
        ref = PK.stencil2d_iterate_pallas(ref, 0.25, dim=dim)

    interior = [slice(None), slice(None)]
    interior[dim] = slice(K, K + m)
    ref_interior = [slice(None), slice(None)]
    ref_interior[dim] = slice(2, 2 + m)
    tol = 1e-6 if dtype == np.float32 else 1e-12
    np.testing.assert_allclose(
        np.asarray(got[tuple(interior)]),
        np.asarray(ref[tuple(ref_interior)]),
        atol=tol,
        err_msg=f"dim={dim} steps={steps} m={m} other={other} "
        f"{np.dtype(dtype).name} {flags}",
    )
    # the deep call must also leave its own physical band untouched
    lo = [slice(None), slice(None)]
    lo[dim] = slice(0, K)
    np.testing.assert_array_equal(np.asarray(got[tuple(lo)]), z0[tuple(lo)])


@pytest.mark.parametrize("dim", [0, 1])
@pytest.mark.parametrize("steps", [2, 3])
@pytest.mark.parametrize("flags", ["static", "dynamic"])
def test_iterate_multistep_matches_repeated_single(dim, steps, flags):
    """Temporal blocking (k steps per HBM pass over a deep ghost band) must
    reproduce k single-step calls exactly. Single shard, both sides
    physical (fixed band, ≅ the per-step scheme's Dirichlet ghosts)."""
    _check_multistep_vs_repeated(dim, steps, 40, 24, np.float32, flags,
                                 seed=steps)


@pytest.mark.parametrize("axis", [0, 1])
@pytest.mark.parametrize("periodic", [False, True])
def test_iterate_multistep_distributed(mesh8, axis, periodic):
    """Deep-halo k-step iterate over 8 shards == per-step-exchange XLA
    iterate, on the true interior (the layouts differ only in ghost width).
    Covers exchange-fed sides (span shrink per step) and, non-periodic,
    physical edge shards (fixed band)."""
    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.halo import iterate_fused_fn, iterate_pallas_fn

    steps, outer = 2, 3
    K, nloc, other = 2 * steps, 16, 32
    rng_ = np.random.default_rng(7 + axis)
    deep_blocks = [
        rng_.normal(size=(nloc + 2 * K, other)).astype(np.float32)
        for _ in range(8)
    ]
    narrow_blocks = [b[K - 2 : K - 2 + nloc + 4] for b in deep_blocks]
    if axis == 1:
        deep_blocks = [b.T for b in deep_blocks]
        narrow_blocks = [b.T for b in narrow_blocks]
    z_deep = shard_1d(
        jnp.asarray(np.concatenate(deep_blocks, axis=axis)), mesh8, axis=axis
    )
    z_narrow = shard_1d(
        jnp.asarray(np.concatenate(narrow_blocks, axis=axis)),
        mesh8,
        axis=axis,
    )

    fused = iterate_fused_fn(
        mesh8, "shard", axis, 2, 2, 10.0, 1e-3, periodic=periodic
    )
    deep = iterate_pallas_fn(
        mesh8, "shard", K, 1e-2, axis=axis, interpret=True, steps=steps,
        periodic=periodic,
    )
    ra = np.split(np.asarray(fused(z_narrow, steps * outer)), 8, axis=axis)
    rb = np.split(np.asarray(deep(z_deep, outer)), 8, axis=axis)
    sl_n = [slice(None), slice(None)]
    sl_n[axis] = slice(2, 2 + nloc)
    sl_d = [slice(None), slice(None)]
    sl_d[axis] = slice(K, K + nloc)
    for a, b in zip(ra, rb):
        np.testing.assert_allclose(
            a[tuple(sl_n)], b[tuple(sl_d)], atol=1e-5
        )


@pytest.mark.parametrize("steps", [1, 2, 4])
@pytest.mark.parametrize("flags", ["static11", "static00", "dynamic"])
def test_iterate_stream0_matches_fullheight(steps, flags):
    """The row-streaming dim-0 kernel must reproduce the full-height strip
    kernel exactly — same spans, same ghost-band behavior — across physical
    and exchange-fed flags, masked edge blocks and unmasked interior
    blocks, and a ragged last row block (stream_tile_rows=16 forces many
    blocks at test size; in production streaming engages only above the
    VMEM height limit)."""
    K = 2 * steps
    nx = 70 + 2 * K  # 70 % 16 != 0 → ragged last block
    z0 = np.random.default_rng(steps).normal(size=(nx, 24)).astype(
        np.float32
    )
    phys_kw = {
        "static11": {"phys_static": (1, 1)},
        "static00": {"phys_static": (0, 0)},
        "dynamic": {"phys": jnp.asarray([1, 0])},
    }[flags]
    full = PK.stencil2d_iterate_pallas(
        jnp.asarray(z0), 0.25, dim=0, steps=steps, stream=False, **phys_kw
    )
    streamed = PK.stencil2d_iterate_pallas(
        jnp.asarray(z0), 0.25, dim=0, steps=steps, stream=True,
        stream_tile_rows=16, **phys_kw
    )
    np.testing.assert_allclose(
        np.asarray(streamed), np.asarray(full), atol=1e-6,
        err_msg=f"steps={steps} flags={flags}"
    )


def test_iterate_stream0_distributed(mesh8):
    """Streaming dim-0 k-step over 8 shards (non-periodic: real dynamic
    phys flags on edge shards) == per-step XLA iterate on the interior."""
    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.halo import iterate_fused_fn, iterate_pallas_fn

    steps, outer = 2, 2
    K, nloc, other = 2 * steps, 24, 16
    rng_ = np.random.default_rng(3)
    deep_blocks = [
        rng_.normal(size=(nloc + 2 * K, other)).astype(np.float32)
        for _ in range(8)
    ]
    narrow_blocks = [b[K - 2: K - 2 + nloc + 4] for b in deep_blocks]
    z_deep = shard_1d(
        jnp.asarray(np.concatenate(deep_blocks, axis=0)), mesh8, axis=0
    )
    z_narrow = shard_1d(
        jnp.asarray(np.concatenate(narrow_blocks, axis=0)), mesh8, axis=0
    )
    fused = iterate_fused_fn(mesh8, "shard", 0, 2, 2, 10.0, 1e-3)
    deep = iterate_pallas_fn(
        mesh8, "shard", K, 1e-2, axis=0, interpret=True, steps=steps,
        stream=True,
    )
    ra = np.split(np.asarray(fused(z_narrow, steps * outer)), 8, axis=0)
    rb = np.split(np.asarray(deep(z_deep, outer)), 8, axis=0)
    for a, b in zip(ra, rb):
        np.testing.assert_allclose(
            a[2: 2 + nloc], b[K: K + nloc], atol=1e-5
        )


def test_stencil2d_pallas_stream0_matches_strip():
    """The streaming dim-0 derivative path (forced via _stencil_stream0)
    must equal the full-height strip kernel and the XLA stencil."""
    # 1000 out rows = 3 full 256-row blocks + a ragged 232-row last block
    z0 = np.random.default_rng(5).normal(size=(1004, 24)).astype(np.float32)
    scale = 0.75
    full = PK.stencil2d_pallas(jnp.asarray(z0), scale, dim=0)
    streamed = PK._stencil_stream0(
        jnp.asarray(z0), jnp.asarray([scale], jnp.float32), interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(streamed), np.asarray(full), atol=1e-6
    )
    ref = np.asarray(
        stencil1d_5(jnp.asarray(z0), scale, axis=0)
    )
    np.testing.assert_allclose(np.asarray(streamed), ref, atol=1e-5)


def test_iterate_stream0_edge_wider_than_block():
    """G = steps·N_BND wider than the row block (K=10 > B=8) must still be
    exact — the edge builder chunks wide edges over ⌈G/B⌉ strided passes."""
    steps = 5
    K = 2 * steps
    z0 = np.random.default_rng(42).normal(
        size=(40 + 2 * K, 16)
    ).astype(np.float32)
    full = PK.stencil2d_iterate_pallas(
        jnp.asarray(z0), 0.25, dim=0, steps=steps, stream=False,
        phys_static=(0, 0),
    )
    streamed = PK.stencil2d_iterate_pallas(
        jnp.asarray(z0), 0.25, dim=0, steps=steps, stream=True,
        stream_tile_rows=8, phys_static=(0, 0),
    )
    np.testing.assert_allclose(
        np.asarray(streamed), np.asarray(full), atol=1e-6
    )


def test_iterate_stream_rejects_dim1():
    with pytest.raises(ValueError, match="dim=0 only"):
        PK.stencil2d_iterate_pallas(
            jnp.ones((32, 32), jnp.float32), 0.1, dim=1, stream=True
        )


def test_iterate_pallas_fn_rejects_mismatched_ghost_width(mesh8):
    from tpu_mpi_tests.comm.halo import iterate_pallas_fn
    from tpu_mpi_tests.utils import TpuMtError

    with pytest.raises(TpuMtError, match="deep halos"):
        iterate_pallas_fn(mesh8, "shard", 2, 1e-2, steps=2)


@pytest.mark.parametrize("axis", [0, 1])
def test_iterate_pallas_matches_fused_distributed(mesh8, axis):
    """The bench fast path (pallas in-place step + halo exchange, chained in
    a device-side loop) must match the XLA iterate over 8 shards — on both
    decomposition axes (dim 1 = lane shifts, dim 0 = sublane shifts)."""
    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.halo import iterate_fused_fn, iterate_pallas_fn

    rng_ = np.random.default_rng(1)
    shape = (8 * 20, 32) if axis == 0 else (32, 8 * 20)
    zg = rng_.normal(size=shape).astype(np.float32)
    za = shard_1d(jnp.asarray(zg), mesh8, axis=axis)
    zb = shard_1d(jnp.asarray(zg), mesh8, axis=axis)
    fused = iterate_fused_fn(mesh8, "shard", axis, 2, 2, 10.0, 1e-3)
    pallas = iterate_pallas_fn(mesh8, "shard", 2, 1e-2, axis=axis,
                               interpret=True)
    ra = np.asarray(fused(za, 5))
    rb = np.asarray(pallas(zb, 5))
    assert np.allclose(ra, rb, atol=1e-5)


@pytest.mark.parametrize("axis", [0, 1])
@pytest.mark.parametrize("periodic", [False, True])
def test_ring_rdma_halo_matches_ppermute(mesh8, axis, periodic):
    """The hand-written inter-chip RDMA ring (make_async_remote_copy) must
    produce the same ghost fills as the ppermute exchange, in all ring
    configurations (≅ validating the manual MPI staging path against the
    direct path, mpi_stencil2d_gt.cc's buf:0/1 twins)."""
    from tpu_mpi_tests.comm import halo as H
    from tpu_mpi_tests.comm.collectives import shard_1d

    shape = (8 * 12, 16) if axis == 0 else (16, 8 * 12)
    zg = np.random.default_rng(axis).normal(size=shape).astype(np.float32)
    ref = np.asarray(
        H.halo_exchange(
            shard_1d(jnp.asarray(zg), mesh8, axis=axis),
            mesh8,
            axis=axis,
            periodic=periodic,
            staging="direct",
        )
    )
    got = np.asarray(
        H._exchange_pallas_fn(
            mesh8, "shard", axis, 2, 2, periodic, interpret=True
        )(shard_1d(jnp.asarray(zg), mesh8, axis=axis))
    )
    assert np.allclose(ref, got)


def test_stencil2d_driver_rdma_mode(capsys):
    from tpu_mpi_tests.drivers import stencil2d

    rc = stencil2d.main(
        ["--n-local", "32", "--n-other", "64", "--n-iter", "2",
         "--n-warmup", "1", "--dtype", "float64", "--rdma"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("TEST dim:") == 4 + 2


@pytest.mark.parametrize("axis", [0, 1])
def test_pack_unpack_roundtrip(axis):
    z = rng(20 + axis, (64, 48))
    lo, hi = PK.pack_edges_pallas(z, axis=axis)
    rlo, rhi = pack_edges(z, axis=axis)
    assert jnp.allclose(lo, rlo) and jnp.allclose(hi, rhi)
    got = PK.unpack_ghosts_pallas(z, lo, hi, axis=axis)
    ref = unpack_ghosts(z, lo, hi, axis=axis)
    assert jnp.allclose(got, ref)


def test_ring_allgather_rdma_matches_lax(mesh8):
    """The hand-written RDMA ring all-gather must equal lax.all_gather
    (≅ validating a hand MPI_Allgather against the library one)."""
    from tpu_mpi_tests.comm import collectives as C

    rng_ = np.random.default_rng(5)
    full = rng_.normal(size=(8 * 16, 24)).astype(np.float32)
    xs = C.shard_1d(jnp.asarray(full), mesh8)
    got = np.asarray(C.all_gather_rdma(xs, mesh8, interpret=True))
    want = np.asarray(C.all_gather(C.shard_1d(jnp.asarray(full), mesh8),
                                   mesh8))
    assert got.shape == full.shape
    assert np.array_equal(got, want)
    assert np.array_equal(got, full)


def test_ring_allgather_rdma_1d(mesh8):
    from tpu_mpi_tests.comm import collectives as C

    # 1024 elements/shard: the minimum 1-D unit (128 lanes × 8 sublanes
    # f32) — the lane-aligned fold that real-TPU Mosaic DMA requires (a
    # (n, 1) view compiled nowhere but interpret mode; round-2 bug)
    full = np.arange(8 * 1024, dtype=np.float32)
    xs = C.shard_1d(jnp.asarray(full), mesh8)
    got = np.asarray(C.all_gather_rdma(xs, mesh8, interpret=True))
    assert np.array_equal(got, full)


def test_ring_allgather_rdma_1d_rejects_subtile():
    from tpu_mpi_tests.kernels import pallas_kernels as PK

    with pytest.raises(ValueError, match="n % 1024 == 0"):
        PK.ring_allgather_pallas(
            jnp.ones((96,)), axis_name="shard", interpret=True
        )


def test_ring_allgather_rejects_unaligned_rows():
    from tpu_mpi_tests.kernels import pallas_kernels as PK

    with pytest.raises(ValueError, match="rows % 8"):
        # outside shard_map axis context this fails earlier on alignment
        PK.ring_allgather_pallas(
            jnp.ones((12, 4)), axis_name="shard", interpret=True
        )


def test_ring_allreduce_rdma_matches_psum(mesh8):
    """The hand ring allreduce (reduce-scatter + all-gather RDMA) must
    equal lax.psum — integer-valued f32 so ring vs library summation order
    cannot differ (≅ validating a hand MPI_Allreduce)."""
    from tpu_mpi_tests.comm import collectives as C

    rng_ = np.random.default_rng(11)
    L = 8 * 1024  # minimum 1-D ring unit on 8 devices (w·128·8 f32)
    per_rank = rng_.integers(-50, 50, size=(8, L)).astype(np.float32)
    xs = C.shard_1d(jnp.asarray(per_rank), mesh8)
    got = np.asarray(C.allreduce_rdma(xs, mesh8, interpret=True))
    want = np.asarray(
        C.allreduce_sum(C.shard_1d(jnp.asarray(per_rank), mesh8), mesh8)
    )
    assert got.shape == per_rank.shape
    assert np.array_equal(got, want)
    assert np.array_equal(got[0], per_rank.sum(axis=0))


def test_ring_reduce_scatter_2d(mesh8):
    """2-D path: rank r must own chunk r of the sum (psum_scatter order),
    exercising the multi-tile VMEM accumulate loop."""
    import functools

    import jax
    from tpu_mpi_tests.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from tpu_mpi_tests.comm import collectives as C

    mesh = mesh8
    rows = 8 * 8 * 8  # per-shard rows: w(8) × sublane(8) × 8 tiles
    per_rank = np.arange(8 * rows * 16, dtype=np.float32).reshape(
        8, rows, 16
    ) % 97

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("shard"), out_specs=P("shard"),
        check_vma=False,
    )
    def rs(x):
        # tile_rows=16 forces the multi-tile VMEM accumulate loop (4 tiles
        # per 64-row chunk) that auto-fit would only hit at multi-GB shards
        return PK.ring_reduce_scatter_pallas(
            x[0], axis_name="shard", interpret=True, tile_rows=16
        )[None]

    xs = C.shard_1d(jnp.asarray(per_rank), mesh)
    got = np.asarray(rs(xs))  # (8, rows/8, 16): rank r's chunk r
    want = per_rank.sum(axis=0).reshape(8, rows // 8, 16)
    assert np.array_equal(got, want)


def test_ring_allreduce_single_device():
    """w=1 ring degenerates to a copy (loops empty, copy path)."""
    import functools

    import jax
    from tpu_mpi_tests.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:1]), ("shard",))
    x = np.arange(1024, dtype=np.float32)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(), out_specs=P(),
        check_vma=False,
    )
    def ar(x):
        return PK.ring_allreduce_pallas(
            x, axis_name="shard", interpret=True
        )

    assert np.array_equal(np.asarray(ar(jnp.asarray(x))), x)


@pytest.mark.parametrize("credits", [1, 2])
def test_ring_reduce_scatter_self_ring(credits):
    """self_ring=k on one device must return the sum of the shard's own k
    chunks — the schedule's result when every virtual rank holds the same
    data (this is the mode that lets ONE real chip execute the full loop
    body: sliced DMA, self-RDMA, VMEM accumulate, handshake). Both
    credit levels: the loopback+credits interplay (self-targeted parity
    recv sems, self-signaled credit schedule) is the path the on-chip
    BASELINE claim rests on, so CI pins it."""
    import functools

    import jax
    from tpu_mpi_tests.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:1]), ("shard",))
    x = (np.arange(4 * 16 * 8, dtype=np.float32).reshape(4 * 16, 8) % 23)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False,
    )
    def rs(x):
        return PK.ring_reduce_scatter_pallas(
            x, axis_name="shard", interpret=True, self_ring=4,
            credits=credits,
        )

    got = np.asarray(rs(jnp.asarray(x)))
    want = x.reshape(4, 16, 8).sum(axis=0)
    assert np.array_equal(got, want)


def test_vpu_probe_mixes():
    """The VPU roofline probe's mixes compute what they claim (interpret):
    fma applies a·z+b reps times; step5 applies the kernel's exact
    update — on a unit ramp the 5-point first derivative is exactly 1, so
    each rep adds se to the interior span."""
    reps = 3
    # fma on ones: closed form via the recurrence (a = 1 − 2⁻⁷ exact in
    # both probe dtypes, so the reference needs no rounding model)
    a, b = 0.9921875, 1e-10
    for dt in (jnp.float32, jnp.bfloat16):
        z = jnp.ones((16, 128), dt)
        out = PK.vpu_probe_pallas(z, reps, "fma", interpret=True)
        want = 1.0
        for _ in range(reps):
            want = a * want + b
        np.testing.assert_allclose(
            np.asarray(out, np.float32), want,
            rtol=1e-6 if dt == jnp.float32 else 1e-2,
        )

    # step5: se visible (0.01 — the 1e-9 timing default underflows f32
    # against the ramp and would make this check vacuous), expected via
    # an exact numpy recurrence of the same update (edge rows' stencils
    # see the span boundary from rep 2 on, so a closed form won't do)
    from tpu_mpi_tests.kernels.stencil import STENCIL5

    se = 0.01
    c1, c2 = float(STENCIL5[3]), float(STENCIL5[4])
    for mix, axis in (("step5_d0", 0), ("step5_d1", 1)):
        shape = [8, 128]
        ramp = np.broadcast_to(
            np.arange(shape[axis], dtype=np.float32).reshape(
                [-1, 1] if axis == 0 else [1, -1]
            ),
            shape,
        ).copy()
        got = np.asarray(PK.vpu_probe_pallas(
            jnp.asarray(ramp), reps, mix, se=se, interpret=True
        ))
        N = shape[axis]
        z = np.moveaxis(ramp.astype(np.float64), axis, 0)
        for _ in range(reps):
            d = c1 * (z[3:N - 1] - z[1:N - 3]) + c2 * (z[4:N] - z[:N - 4])
            z[2:N - 2] = z[2:N - 2] + se * d
        want2 = np.moveaxis(z, 0, axis)
        np.testing.assert_allclose(got, want2, rtol=0, atol=1e-4)
        # sanity: the update must actually be visible, or this assertion
        # proves nothing
        assert np.abs(want2 - ramp).max() > 1e-3

    # step5fma (the raw 4-tap se-folded form, probed to test whether
    # the dual-dim FMA lesson transfers to the headline body — it does
    # NOT, BASELINE round-5 VPU note): same update up to FP
    # association, tap constants folded with se at trace time
    for mix, axis in (("step5fma_d0", 0), ("step5fma_d1", 1)):
        shape = [8, 128]
        ramp = np.broadcast_to(
            np.arange(shape[axis], dtype=np.float32).reshape(
                [-1, 1] if axis == 0 else [1, -1]
            ),
            shape,
        ).copy()
        got = np.asarray(PK.vpu_probe_pallas(
            jnp.asarray(ramp), reps, mix, se=se, interpret=True
        ))
        N = shape[axis]
        t1, t2 = np.float32(se * c1), np.float32(se * c2)
        z = np.moveaxis(ramp.astype(np.float64), axis, 0)
        for _ in range(reps):
            z[2:N - 2] = (z[2:N - 2] + t1 * z[3:N - 1]
                          + np.float32(-se * c1) * z[1:N - 3]
                          + t2 * z[4:N]
                          + np.float32(-se * c2) * z[:N - 4])
        want3 = np.moveaxis(z, 0, axis)
        np.testing.assert_allclose(got, want3, rtol=0, atol=1e-4)
        assert np.abs(want3 - ramp).max() > 1e-3


def test_vpu_probe_heat5_mix():
    """Round-5 probe mix (VERDICT r4 #6): heat5 applies the heat
    streamer's exact per-step body — replicate it in numpy (clamped-edge
    shifts, two-axis Euler, interior-only mask) and compare."""
    reps = 3
    cx = cy = 0.0078125
    rng = np.random.default_rng(9)
    z0 = rng.normal(size=(16, 128)).astype(np.float32)
    got = np.asarray(PK.vpu_probe_pallas(
        jnp.asarray(z0), reps, "heat5", interpret=True
    ))
    w = z0.astype(np.float64)
    for _ in range(reps):
        up = np.concatenate([w[1:], w[-1:]], axis=0)
        down = np.concatenate([w[:1], w[:-1]], axis=0)
        right = np.concatenate([w[:, 1:], w[:, -1:]], axis=1)
        left = np.concatenate([w[:, :1], w[:, :-1]], axis=1)
        new = (w + cx * (up + down - 2.0 * w)
               + cy * (left + right - 2.0 * w))
        keep = np.zeros_like(w, bool)
        keep[1:-1, 1:-1] = True
        w = np.where(keep, new, w)
    np.testing.assert_allclose(got, w, rtol=0, atol=1e-5)
    assert np.abs(w - z0).max() > 1e-3  # the update is visible


def test_vpu_probe_dualdim_mix():
    """Round-5 probe mix: dualdim applies 4-tap derivatives on both axes,
    folds them into the interior at ``se`` scale, and adds the f32
    squared-residual scalar — the exact recurrence replicated in numpy."""
    from tpu_mpi_tests.kernels.stencil import N_BND, STENCIL5

    reps = 2
    se = 0.05  # visible against the 2⁻⁷ derivative scale
    sx = sy = 0.0078125
    rng = np.random.default_rng(10)
    z0 = rng.normal(size=(16, 128)).astype(np.float32)
    got = np.asarray(PK.vpu_probe_pallas(
        jnp.asarray(z0), reps, "dualdim", se=se, interpret=True
    ))
    taps = [(k, float(c)) for k, c in enumerate(STENCIL5) if c != 0.0]
    z = z0.astype(np.float64)
    H, W = z.shape
    for _ in range(reps):
        dx = sum(c * z[k:k + H - 2 * N_BND, :] for k, c in taps) * sx
        dy = sum(c * z[:, k:k + W - 2 * N_BND] for k, c in taps) * sy
        # the probe mirrors the kernel's two row-masked reductions
        # (each excludes its last row — mixed-mask, fold-proof)
        sqx = dx.astype(np.float32) ** 2
        sqx[H - 2 * N_BND - 1:, :] = 0.0
        sqy = dy.astype(np.float32) ** 2
        sqy[H - 1:, :] = 0.0
        r = (sqx.sum(dtype=np.float64)
             + sqy.sum(dtype=np.float64)) / 1024.0
        zx = z.copy()
        zx[N_BND:H - N_BND, :] += se * dx
        zy = zx.copy()
        zy[:, N_BND:W - N_BND] += se * dy
        z = zy + se * r
    np.testing.assert_allclose(got, z, rtol=0, atol=1e-3)
    assert np.abs(z - z0).max() > 1e-3


def test_vpu_probe_dualdim_lean_mix():
    """Round-5 op-diet probe mix: difference-form folded-coefficient
    taps on the both-dims interior + ONE masked fused residual
    reduction (mask excludes the last derivative row — mixed
    true/false so nothing constant-folds) — the exact recurrence
    replicated in numpy."""
    from tpu_mpi_tests.kernels.stencil import N_BND, STENCIL5

    reps = 2
    se = 0.05
    s = 0.0078125
    c1, c2 = float(STENCIL5[3]), float(STENCIL5[4])
    fc1 = np.float32(np.float32(s) * c1)
    fc2 = np.float32(np.float32(s) * c2)
    rng_ = np.random.default_rng(11)
    z0 = rng_.normal(size=(16, 128)).astype(np.float32)
    got = np.asarray(PK.vpu_probe_pallas(
        jnp.asarray(z0), reps, "dualdim_lean", se=se, interpret=True
    ))
    z = z0.astype(np.float64)
    H, W = z.shape
    G = N_BND
    for _ in range(reps):
        core = z[:, G:W - G]
        mid = z[G:H - G, :]
        dx = (fc1 * (core[G + 1:H - G + 1] - core[G - 1:H - G - 1])
              + fc2 * (core[G + 2:H - G + 2] - core[G - 2:H - G - 2]))
        dy = (fc1 * (mid[:, G + 1:W - G + 1] - mid[:, G - 1:W - G - 1])
              + fc2 * (mid[:, G + 2:W - G + 2] - mid[:, G - 2:W - G - 2]))
        sq = (dx.astype(np.float32) ** 2
              + dy.astype(np.float32) ** 2).astype(np.float64)
        sq[H - 2 * G - 1:, :] = 0.0  # last derivative row masked out
        r = sq.sum() / 1024.0
        zn = z.copy()
        zn[G:H - G, G:W - G] += se * dx + se * dy
        z = zn + se * r
    np.testing.assert_allclose(got, z, rtol=0, atol=1e-3)
    assert np.abs(z - z0).max() > 1e-3


def test_dual_dim_lean_default_pinned():
    """The lean-body default records the on-chip interleaved A/B verdict
    (BASELINE round-5 dual-dim op-diet note: raw/lean marginal 0.75x
    f32 / 0.915x bf16 — the raw 4-tap body is measured-best at BOTH
    dtypes because its const-mul+add pairs execute as FMAs). A change
    here must come with a new measurement."""
    assert PK._DUAL_DIM_LEAN_DEFAULT == {
        "float32": False, "bfloat16": False,
    }


def test_vpu_probe_rejects_vmem_blowout():
    with pytest.raises(ValueError, match="VMEM"):
        PK.vpu_probe_pallas(
            jnp.ones((2048, 1024), jnp.float32), 2, "fma", interpret=True
        )


def test_ring_allgather_self_ring():
    """self_ring=k on one device: every region pre-seeded then forwarded
    through the full k-step schedule → tile(x, k). A Mosaic
    compile/execute smoke for the per-step send/recv semaphore pairs
    (round-4 race fix) and sliced self-DMAs — the loopback value result
    is identity by construction (each DMA is region → same region), so
    data-path coverage at w>1 is test_ring_sync.py's job."""
    import functools

    import jax
    from tpu_mpi_tests.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:1]), ("shard",))
    x = (np.arange(16 * 8, dtype=np.float32).reshape(16, 8) % 19)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False,
    )
    def ag(x):
        return PK.ring_allgather_pallas(
            x, axis_name="shard", interpret=True, self_ring=4
        )

    got = np.asarray(ag(jnp.asarray(x)))
    assert np.array_equal(got, np.tile(x, (4, 1)))


def test_ring_allgather_self_ring_rejects_multi_device(mesh8):
    import functools

    import jax
    from tpu_mpi_tests.compat import shard_map
    from jax.sharding import PartitionSpec as P

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh8, in_specs=P("shard"), out_specs=P("shard"),
        check_vma=False,
    )
    def ag(x):
        return PK.ring_allgather_pallas(
            x, axis_name="shard", interpret=True, self_ring=4
        )

    with pytest.raises(ValueError, match="single-device validation"):
        ag(jnp.ones((64, 8), jnp.float32))


def test_ring_reduce_scatter_rejects_bad_credits(mesh8):
    import functools

    import jax
    from tpu_mpi_tests.compat import shard_map
    from jax.sharding import PartitionSpec as P

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh8, in_specs=P("shard"), out_specs=P("shard"),
        check_vma=False,
    )
    def rs(x):
        return PK.ring_reduce_scatter_pallas(
            x, axis_name="shard", interpret=True, credits=3
        )

    with pytest.raises(ValueError, match="credits=3"):
        rs(jnp.ones((8 * 64, 8), jnp.float32))


def test_allreduce_rdma_credits_2_matches_psum(mesh8):
    """The comm-layer credits passthrough: the 2-credit hand allreduce
    equals lax.psum (integer-valued so summation order cannot differ)."""
    from tpu_mpi_tests.comm import collectives as C

    L = 8 * 1024
    per_rank = (np.arange(8 * L, dtype=np.float32).reshape(8, L) % 17) - 8.0
    got = np.asarray(C.allreduce_rdma(
        C.shard_1d(jnp.asarray(per_rank), mesh8), mesh8, interpret=True,
        credits=2,
    ))
    assert np.array_equal(got, np.broadcast_to(per_rank.sum(0), got.shape))


def test_ring_reduce_scatter_self_ring_rejects_multi_device(mesh8):
    import functools

    import jax
    from tpu_mpi_tests.compat import shard_map
    from jax.sharding import PartitionSpec as P

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh8, in_specs=P("shard"), out_specs=P("shard"),
        check_vma=False,
    )
    def rs(x):
        return PK.ring_reduce_scatter_pallas(
            x, axis_name="shard", interpret=True, self_ring=2
        )

    with pytest.raises(Exception, match="single-device validation"):
        rs(jnp.ones((8 * 16, 8), jnp.float32))


def test_ring_allreduce_rejects_unaligned(mesh8):
    from tpu_mpi_tests.comm import collectives as C

    with pytest.raises(Exception, match="n % 8192"):
        # 8-ring f32: L must be a multiple of 8·128·8 = 8192
        C.allreduce_rdma(
            C.shard_1d(jnp.ones((8, 1024), jnp.float32), mesh8),
            mesh8, interpret=True,
        )


def test_ring_reduce_scatter_rejects_vmem_blowout(mesh8):
    """A minor dim so wide that one sublane-tile row per accumulate buffer
    exceeds VMEM must fail with the explicit budget error (flash-kernel
    convention), not an opaque Mosaic allocation failure."""
    import functools

    import jax
    from tpu_mpi_tests.compat import shard_map
    from jax.sharding import PartitionSpec as P

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh8, in_specs=P("shard"), out_specs=P("shard"),
        check_vma=False,
    )
    def rs(x):
        return PK.ring_reduce_scatter_pallas(
            x[0], axis_name="shard", interpret=True
        )[None]

    wide = jax.ShapeDtypeStruct((8, 8 * 8, 2**20), jnp.float32)
    with pytest.raises(Exception, match="VMEM budget"):
        jax.eval_shape(rs, wide)


def test_allreduce_rdma_rejects_bad_shape(mesh8):
    from tpu_mpi_tests.comm import collectives as C

    with pytest.raises(ValueError, match="n_ranks=8"):
        C.allreduce_rdma(jnp.ones((4, 8192), jnp.float32), mesh8)


@pytest.mark.parametrize("axis", [0, 1])
@pytest.mark.parametrize("periodic", [False, True])
def test_iterate_overlap_matches_sequential(mesh8, axis, periodic):
    """The comm/compute-overlap schedule (core kernel runs while edge
    ppermutes fly, strips patched after — ≅ the reference's
    Irecv/compute/Waitall pattern) must produce the same field as the
    sequential exchange+kernel iterate."""
    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.halo import iterate_overlap_fn, iterate_pallas_fn

    rng_ = np.random.default_rng(11 + axis)
    shape = (8 * 24, 16) if axis == 0 else (16, 8 * 24)
    zg = rng_.normal(size=shape).astype(np.float32)
    za = shard_1d(jnp.asarray(zg), mesh8, axis=axis)
    zb = shard_1d(jnp.asarray(zg), mesh8, axis=axis)
    seq = iterate_pallas_fn(mesh8, "shard", 2, 1e-2, axis=axis,
                            interpret=True, periodic=periodic)
    ovl = iterate_overlap_fn(mesh8, "shard", 2, 1e-2, axis=axis,
                             interpret=True, periodic=periodic)
    ra = np.asarray(seq(za, 5))
    rb = np.asarray(ovl(zb, 5))
    np.testing.assert_allclose(ra, rb, atol=1e-5)


@pytest.mark.parametrize("axis", [0, 1])
@pytest.mark.parametrize("periodic", [False, True])
def test_iterate_rdma_matches_ppermute_tier(mesh8, axis, periodic):
    """The 100%-hand-tier hot loop (RDMA ring exchange + in-place kernel,
    chained) must match the ppermute-exchange tier over 8 shards —
    including the periodic self-ring configuration BASELINE.md times."""
    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.halo import iterate_pallas_fn

    rng_ = np.random.default_rng(21 + axis)
    shape = (8 * 16, 16) if axis == 0 else (16, 8 * 16)
    zg = rng_.normal(size=shape).astype(np.float32)
    za = shard_1d(jnp.asarray(zg), mesh8, axis=axis)
    zb = shard_1d(jnp.asarray(zg), mesh8, axis=axis)
    pp = iterate_pallas_fn(mesh8, "shard", 2, 1e-2, axis=axis,
                           interpret=True, periodic=periodic)
    hand = iterate_pallas_fn(mesh8, "shard", 2, 1e-2, axis=axis,
                             interpret=True, periodic=periodic, rdma=True)
    np.testing.assert_allclose(
        np.asarray(pp(za, 4)), np.asarray(hand(zb, 4)), atol=1e-6
    )


def test_iterate_multistep_fuzz_shapes():
    """Property sweep: random shapes (down to 1-wide), dtypes, dims, step
    counts, AND flag modes (static spans vs the dynamic SMEM iota-mask
    path) — the k-step kernel must always match k single steps on the
    interior. (A 60-trial offline sweep passed; 10 pinned-seed trials in
    CI, via the same shared gate as the parametrized cases.)"""
    rng_ = np.random.default_rng(0)
    for trial in range(10):
        _check_multistep_vs_repeated(
            dim=int(rng_.integers(0, 2)),
            steps=int(rng_.integers(1, 5)),
            m=int(rng_.integers(1, 90)),
            other=int(rng_.integers(1, 70)),
            dtype=rng_.choice([np.float32, np.float64]),
            flags=rng_.choice(["static", "dynamic"]),
            seed=100 + trial,
        )


def test_iterate_stream0_fuzz_shapes():
    """Property sweep for the row-streaming dim-0 path: random shapes
    (down to 1-wide interiors), dtypes, step counts, and flag modes, with
    16-row blocks forcing multi-block streaming + ragged last blocks —
    must match k single steps on the interior like the full-height path."""
    rng_ = np.random.default_rng(1)
    for trial in range(10):
        _check_multistep_vs_repeated(
            dim=0,
            steps=int(rng_.integers(1, 5)),
            m=int(rng_.integers(1, 90)),
            other=int(rng_.integers(1, 70)),
            dtype=rng_.choice([np.float32, np.float64]),
            flags=rng_.choice(["static", "dynamic"]),
            seed=200 + trial,
            stream=True,
        )


def test_daxpy_inplace_alias_matches():
    """inplace=True (output aliased onto y — cuBLAS's real semantics, and
    required for chained loops per the BASELINE A/B) computes the same
    values as the out-of-place form."""
    x, y = init_xy(64 * 1024, jnp.float32)
    want = np.asarray(PK.daxpy_pallas(2.0, x, y))
    got = np.asarray(PK.daxpy_pallas(2.0, x, y, inplace=True))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("lean", [False, True])
@pytest.mark.parametrize("tile_rows", [None, 16])
def test_dual_dim_step_pallas_matches_xla(tile_rows, lean):
    """The streamed dual-derivative kernel must match dual_dim_step on
    both derivatives and (to summation rounding) the residual; tile_rows
    forces multi-block streaming with a ragged last block. Both kernel
    bodies (raw 4-tap accumulation and the round-5 lean difference form,
    which differs only by FP association) meet the same gates."""
    from tpu_mpi_tests.kernels.stencil import N_BND, dual_dim_step

    z = rng(31, (4 + 2 * N_BND + 66, 52 + 2 * N_BND))
    ax, ay, ar = dual_dim_step(z, N_BND, 1.5, 0.75)
    bx, by, br = PK.dual_dim_step_pallas(
        z, N_BND, 1.5, 0.75, interpret=True, tile_rows=tile_rows,
        lean=lean
    )
    np.testing.assert_allclose(np.asarray(bx), np.asarray(ax), atol=1e-5)
    np.testing.assert_allclose(np.asarray(by), np.asarray(ay), atol=1e-5)
    assert abs(float(br) - float(ar)) <= 1e-3 * max(1.0, abs(float(ar)))


@pytest.mark.parametrize("lean", [False, True])
def test_dual_dim_step_pallas_bfloat16(lean):
    """bf16 dualdim: round-4 vmemprobe coverage found the kernel had
    never compiled at bf16 (Mosaic cannot legalize bf16 cross-lane
    reductions or scalar divides); the residual now accumulates in f32.
    Value parity vs the f32 XLA tier at 16-bit tolerances. The lean
    body's coefficient fold runs on the f32 scalar unit (converts
    legalize; bf16 scalar arith does not) and is covered here at bf16."""
    from tpu_mpi_tests.kernels.stencil import N_BND, dual_dim_step

    z32 = rng(33, (48 + 2 * N_BND, 40 + 2 * N_BND))
    z16 = z32.astype(jnp.bfloat16)
    ax, ay, ar = dual_dim_step(z32, N_BND, 1.5, 0.75)
    bx, by, br = PK.dual_dim_step_pallas(
        z16, N_BND, 1.5, 0.75, interpret=True, lean=lean
    )
    assert bx.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(bx, np.float32), np.asarray(ax), atol=0.05
    )
    np.testing.assert_allclose(
        np.asarray(by, np.float32), np.asarray(ay), atol=0.05
    )
    assert abs(float(br) - float(ar)) <= 0.02 * max(1.0, abs(float(ar)))


def test_kstep_d1_strip_fit():
    """The direct dim-1 strip fit: budget-max 8-multiples, tile as an
    8-multiple cap, f32 64 at the headline width, bf16 96 budget-max
    under the calibrated coefficient (production caps at 64 — measured
    flat — but the fit must expose the honest max for opt-in tiles)."""
    ny = 8192 + 16
    f32, bf16, f16 = jnp.float32, jnp.bfloat16, jnp.float16
    assert PK._kstep_d1_strip(8192, ny, f32, 512) == 64   # f32 budget-max
    assert PK._kstep_d1_strip(8192, ny, bf16, 512) == 96  # bf16 budget-max
    assert PK._kstep_d1_strip(8192, ny, bf16, 64) == 64   # production cap
    assert PK._kstep_d1_strip(8192, ny, bf16, 90) == 88   # 8-multiple cap
    assert PK._kstep_d1_strip(16, ny, bf16, 512) == 16    # extent-bounded
    # float16 keeps the CONSERVATIVE shared model: the narrowed
    # coefficients were bisected on bfloat16 kernels only
    assert PK._d1_strip_rows_bytes(ny, f16) ==         PK._strip_rows_bytes(ny, 2)
    assert PK._d1_strip_rows_bytes(ny, f16) >         PK._d1_strip_rows_bytes(ny, bf16)
    with pytest.raises(ValueError, match="VMEM"):
        PK._kstep_d1_strip(8192, 3 * 10**6, f32, 512)


def test_stream_live_bytes_calibration():
    """Calibrated bf16 temps stay at/above their measured floors and the
    default stays conservative for uncalibrated kernels."""
    assert PK._BF16_TEMPS_ITER_STREAM >= 17.51
    assert PK._BF16_TEMPS_HEAT >= 14.57
    assert PK._BF16_TEMPS_DEFAULT >= PK._BF16_TEMPS_ITER_STREAM
    # f32 path unchanged by the bf16 parameter
    assert PK._stream_live_bytes(128, 4, 2056, 4) == \
        PK._stream_live_bytes(128, 4, 2056, 4, bf16_temps=15.3)
    # calibrated bf16 model is smaller than the default, never tiny
    lo = PK._stream_live_bytes(128, 4, 2056, 2,
                               bf16_temps=PK._BF16_TEMPS_HEAT)
    hi = PK._stream_live_bytes(128, 4, 2056, 2)
    io = 4 * 2 * 128 * 2056
    assert io < lo < hi


def test_dual_dim_step_pallas_reference_shard_geometry():
    """1028-row shard (the reference's n_local+ghosts geometry): the fast
    edge path must source the last block's bottom edge from the real
    trailing ghost rows even though the output blocking covers fewer rows
    than z (regression: a negative pad crashed here, and a wrapped roll
    would silently corrupt the last block's taps)."""
    from tpu_mpi_tests.kernels.stencil import N_BND, dual_dim_step

    z = rng(77, (1028, 512))
    ax, ay, ar = dual_dim_step(z, N_BND, 2.0, 0.5)
    bx, by, br = PK.dual_dim_step_pallas(z, N_BND, 2.0, 0.5, interpret=True)
    np.testing.assert_allclose(np.asarray(bx), np.asarray(ax), atol=1e-5)
    np.testing.assert_allclose(np.asarray(by), np.asarray(ay), atol=1e-5)
    assert abs(float(br) - float(ar)) <= 1e-3 * max(1.0, abs(float(ar)))


def test_stencil_stream0_blocking_shorter_than_input():
    """_stencil_stream0 blocks over the ghost-stripped output; heights
    where nb·B < nx (e.g. 1028 rows at B=256) must still be exact."""
    z = rng(78, (1028, 40))
    got = PK._stencil_stream0(
        z, jnp.asarray([1.25], jnp.float32), interpret=True
    )
    ref = stencil1d_5(z, 1.25, axis=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("shape", [(24, 1028), (40, 2052), (8, 260)])
def test_stencil_stream1_matches_xla(shape):
    """The column-streaming dim-1 derivative (round 3: removes the last
    fall-back-to-XLA width limit) must match the XLA stencil, including
    ragged last column blocks and widths where nb·B < ny."""
    z = rng(79, shape)
    got = PK._stencil_stream1(
        z, jnp.asarray([0.75], jnp.float32), interpret=True
    )
    ref = stencil1d_5(z, 0.75, axis=1)
    assert got.shape == (shape[0], shape[1] - 4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_stencil2d_pallas_dim1_wide_takes_stream(monkeypatch):
    """A dim-1 extent too wide for even a minimum strip must route to the
    streaming kernel (not raise — the VERDICT r2 weak #5 ValueError is
    unreachable for dim=1 now)."""
    monkeypatch.setattr(PK, "_VMEM_BUDGET_BYTES", 40_000)
    z = rng(80, (16, 516))
    got = PK.stencil2d_pallas(z, 1.5, dim=1, interpret=True)
    ref = stencil1d_5(z, 1.5, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_dual_dim_step_pallas_rejects_bad_nbnd():
    with pytest.raises(ValueError, match="n_bnd"):
        PK.dual_dim_step_pallas(jnp.ones((32, 32)), 3, 1.0, 1.0,
                                interpret=True)


def test_dual_dim_step_pallas_rejects_too_small():
    with pytest.raises(ValueError, match=">= 5 points"):
        PK.dual_dim_step_pallas(jnp.ones((4, 60)), 2, 1.0, 1.0,
                                interpret=True)


@pytest.mark.parametrize("n_blocks", [2, 3])
def test_iterate_blocks_matches_fused(n_blocks):
    """The resident-block single-chip schedule (split → k-step with
    per-k-group inter-block ghost refresh → merge) must reproduce the
    per-step-exchange XLA iterate on the interior, including the physical
    top/bottom bands — the bench.py fast-path gate."""
    import jax
    from jax.sharding import Mesh

    from tpu_mpi_tests.comm.halo import (
        iterate_fused_fn,
        iterate_pallas_blocks_fn,
        merge_blocks,
        split_blocks,
    )

    steps, outer = 2, 3
    K = 2 * steps
    H, W = n_blocks * 12, 24
    z0 = np.random.default_rng(41).normal(
        size=(H + 2 * K, W)
    ).astype(np.float32)
    # deep-ghost layout: physical bands at both ends (world=1 semantics)
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("shard",))
    fused = iterate_fused_fn(mesh1, "shard", 0, 2, 2, 10.0, 1e-3)
    narrow = jnp.asarray(z0[K - 2: K - 2 + H + 4])
    want = np.asarray(fused(narrow, steps * outer))

    run = iterate_pallas_blocks_fn(
        n_blocks, K, 1e-2, steps=steps, interpret=True  # = scale·eps
    )
    state = split_blocks(jnp.asarray(z0), n_blocks, K)
    state = run(state, outer)
    got = np.asarray(merge_blocks(state, K))
    np.testing.assert_allclose(
        got[K:K + H], want[2:2 + H], atol=1e-5
    )


def test_split_merge_blocks_roundtrip():
    from tpu_mpi_tests.comm.halo import merge_blocks, split_blocks

    z = rng(9, (4 * 10 + 8, 16))
    st = split_blocks(z, 4, 4)
    assert all(b.shape == (18, 16) for b in st)
    np.testing.assert_array_equal(np.asarray(merge_blocks(st, 4)),
                                  np.asarray(z))


@pytest.mark.parametrize("n_blocks", [2, 3])
@pytest.mark.parametrize("periodic", [False, True])
def test_iterate_blocks_sharded_matches_fused(mesh8, n_blocks, periodic):
    """The SHARDED resident-block schedule (S resident blocks per shard on
    an 8-device mesh, outermost ghosts over ppermute) must reproduce the
    per-step-exchange XLA iterate on the true interior — the bench.py
    multi-device fast-path gate (VERDICT r2 next #1)."""
    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.halo import (
        iterate_fused_fn,
        iterate_pallas_blocks_fn,
        merge_blocks,
        split_blocks,
    )

    steps, outer = 2, 3
    K = 2 * steps
    nloc = n_blocks * 6  # interior rows per shard, divisible by S
    other = 24
    rng_ = np.random.default_rng(17 + n_blocks)
    deep_blocks = [
        rng_.normal(size=(nloc + 2 * K, other)).astype(np.float32)
        for _ in range(8)
    ]
    narrow_blocks = [b[K - 2: K - 2 + nloc + 4] for b in deep_blocks]
    z_deep = shard_1d(
        jnp.asarray(np.concatenate(deep_blocks, axis=0)), mesh8, axis=0
    )
    z_narrow = shard_1d(
        jnp.asarray(np.concatenate(narrow_blocks, axis=0)), mesh8, axis=0
    )

    fused = iterate_fused_fn(
        mesh8, "shard", 0, 2, 2, 10.0, 1e-3, periodic=periodic
    )
    want = np.split(np.asarray(fused(z_narrow, steps * outer)), 8, axis=0)

    run = iterate_pallas_blocks_fn(
        n_blocks, K, 1e-2, steps=steps, interpret=True,
        mesh=mesh8, axis_name="shard", periodic=periodic,
    )
    state = split_blocks(z_deep, n_blocks, K, mesh=mesh8)
    state = run(state, outer)
    got = np.split(
        np.asarray(merge_blocks(state, K, mesh=mesh8)), 8, axis=0
    )
    for a, b in zip(want, got):
        np.testing.assert_allclose(
            a[2:2 + nloc], b[K:K + nloc], atol=1e-5
        )


# --------------------------------------------------------------------------
# ISSUE 15: the one-launch fused halo+stencil tier
# --------------------------------------------------------------------------


@pytest.mark.parametrize("steps", [1, 4])
@pytest.mark.parametrize("periodic", [False, True])
def test_fused_rdma_matches_chained_bitwise(mesh8, steps, periodic):
    """The ONE-launch fused tier (in-kernel RDMA overlapped with the
    interior stream) must reproduce the chained two-launch tier
    (``ring_halo_pallas`` → ``stencil2d_iterate_pallas``) BITWISE — the
    two paths share the update functions (``_step5``/``_masked_step``)
    and the ghost bytes, so equality is engineered, not hoped for
    (the ISSUE-15 honesty gate). steps ∈ {1, 4} covers shallow and
    deep-ghost temporal blocking; both ring topologies covered."""
    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.halo import (
        iterate_fused_rdma_fn,
        iterate_pallas_fn,
    )

    K = 2 * steps
    nloc, other = 16, 32
    rng_ = np.random.default_rng(5 + steps)
    zg = rng_.normal(size=(8 * (nloc + 2 * K), other)).astype(np.float32)
    za = shard_1d(jnp.asarray(zg), mesh8, axis=0)
    zb = shard_1d(jnp.asarray(zg), mesh8, axis=0)
    chained = iterate_pallas_fn(
        mesh8, "shard", K, 1e-2, axis=0, interpret=True, steps=steps,
        periodic=periodic, rdma=True,
    )
    fused = iterate_fused_rdma_fn(
        mesh8, "shard", K, 1e-2, interpret=True, steps=steps,
        periodic=periodic, tile_rows=16,
    )
    ra = np.asarray(chained(za, 3))
    rb = np.asarray(fused(zb, 3))
    # full-array equality: interiors AND ghost bands (arrived values on
    # exchange-fed sides, physical ghosts kept on non-periodic edges)
    assert np.array_equal(ra, rb)


def test_fused_rdma_multiblock_stream(mesh8):
    """nb > 2 row blocks per shard: interior blocks stream before the
    seam point, edge blocks after — same bitwise contract."""
    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.halo import (
        iterate_fused_rdma_fn,
        iterate_pallas_fn,
    )

    K = 2
    nloc, other = 36, 16  # R = 40 -> five 8-row blocks per shard
    zg = np.random.default_rng(9).normal(
        size=(8 * (nloc + 2 * K), other)
    ).astype(np.float32)
    za = shard_1d(jnp.asarray(zg), mesh8, axis=0)
    zb = shard_1d(jnp.asarray(zg), mesh8, axis=0)
    chained = iterate_pallas_fn(
        mesh8, "shard", K, 1e-2, axis=0, interpret=True, rdma=True,
    )
    fused = iterate_fused_rdma_fn(
        mesh8, "shard", K, 1e-2, interpret=True, tile_rows=8,
    )
    assert np.array_equal(np.asarray(chained(za, 4)),
                          np.asarray(fused(zb, 4)))


def test_fused_rdma_bfloat16_bitwise(mesh8):
    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.halo import (
        iterate_fused_rdma_fn,
        iterate_pallas_fn,
    )

    zg = np.random.default_rng(2).normal(size=(8 * 24, 32))
    za = shard_1d(jnp.asarray(zg, jnp.bfloat16), mesh8, axis=0)
    zb = shard_1d(jnp.asarray(zg, jnp.bfloat16), mesh8, axis=0)
    ch = iterate_pallas_fn(mesh8, "shard", 2, 1e-2, axis=0,
                           interpret=True, rdma=True)
    fu = iterate_fused_rdma_fn(mesh8, "shard", 2, 1e-2, interpret=True)
    assert np.array_equal(np.asarray(ch(za, 3)), np.asarray(fu(zb, 3)))


def test_fused_rdma_world1_pure_compute():
    """world=1 non-periodic degenerates to a pure compute pass (no
    barrier, no sends — ``local_only``): bitwise-identical to the plain
    in-place kernel with both sides physical."""
    import jax

    from jax.sharding import Mesh

    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.halo import iterate_fused_rdma_fn

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("shard",))
    zg = np.random.default_rng(1).normal(size=(40, 32)).astype(np.float32)
    run = iterate_fused_rdma_fn(mesh1, "shard", 4, 1e-2, interpret=True,
                                steps=2)
    got = np.asarray(run(shard_1d(jnp.asarray(zg), mesh1, axis=0), 2))
    want = jnp.asarray(zg)
    for _ in range(2):
        want = PK.stencil2d_iterate_pallas(
            want, 1e-2, dim=0, interpret=True, steps=2,
            phys_static=(1, 1),
        )
    assert np.array_equal(got, np.asarray(want))


def test_fused_rdma_world1_periodic_self_ring():
    """world=1 periodic keeps the self-ring RDMA (loopback sends) and
    matches the chained self-ring tier bitwise."""
    import jax

    from jax.sharding import Mesh

    from tpu_mpi_tests.comm.collectives import shard_1d
    from tpu_mpi_tests.comm.halo import (
        iterate_fused_rdma_fn,
        iterate_pallas_fn,
    )

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("shard",))
    zg = np.random.default_rng(3).normal(size=(40, 32)).astype(np.float32)
    fu = iterate_fused_rdma_fn(mesh1, "shard", 4, 1e-2, interpret=True,
                               steps=2, periodic=True)
    ch = iterate_pallas_fn(mesh1, "shard", 4, 1e-2, axis=0,
                           interpret=True, steps=2, periodic=True,
                           rdma=True)
    ra = np.asarray(fu(shard_1d(jnp.asarray(zg), mesh1, axis=0), 2))
    rb = np.asarray(ch(shard_1d(jnp.asarray(zg), mesh1, axis=0), 2))
    assert np.array_equal(ra, rb)


def test_fused_rdma_rejects_bad_geometry(mesh8):
    from tpu_mpi_tests.comm.halo import iterate_fused_rdma_fn
    from tpu_mpi_tests.utils import TpuMtError

    with pytest.raises(TpuMtError, match="dim-0"):
        iterate_fused_rdma_fn(mesh8, "shard", 2, 1e-2, axis=1)
    with pytest.raises(TpuMtError, match="deep halos"):
        iterate_fused_rdma_fn(mesh8, "shard", 2, 1e-2, steps=2)


def test_fused_rdma_kernel_rejects_unblockable_height():
    """A ghosted height with no row blocking that holds the seam must
    raise (visible decline — the sweep records it, never mislabels)."""
    # height 34, K=8: every divisor under the clamped 8-row block is
    # smaller than the 16-row seam
    z = jnp.asarray(np.zeros((34, 16), np.float32))
    with pytest.raises(ValueError, match="seam"):
        PK.stencil2d_fused_rdma_pallas(
            z, 1e-2, axis_name="shard", steps=4, local_only=True,
            interpret=True, tile_rows=8,
        )
