"""Driver-level tests for the flagship 2-D stencil matrix (≅ the in-situ
integration-test role of ``mpi_stencil2d_gt.cc``'s main, SURVEY.md §4.4)."""

import re

from tpu_mpi_tests.drivers import stencil2d

SMALL = ["--n-local", "32", "--n-other", "64", "--n-iter", "3",
         "--n-warmup", "2"]


def test_full_matrix_f64(capsys):
    rc = stencil2d.main(SMALL + ["--dtype", "float64", "--managed"])
    out = capsys.readouterr().out
    assert rc == 0
    deriv = re.findall(
        r"TEST dim:(\d), (device|managed)\s*, buf:(\d); ([\d.]+), "
        r"err=([\d.e+-]+)",
        out,
    )
    assert len(deriv) == 8  # 2 dims x 2 buf x 2 spaces
    assert {(d, s, b) for d, s, b, _, _ in deriv} == {
        (d, s, b)
        for d in "01"
        for s in ("device", "managed")
        for b in "01"
    }
    assert all(float(e) < 1e-8 for *_, e in deriv)
    allred = re.findall(r"allreduce=([\d.]+)", out)
    assert len(allred) == 4  # 2 dims x 2 spaces


def test_matrix_f32_device_only(capsys):
    rc = stencil2d.main(SMALL + ["--dtype", "float32"])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("TEST dim:") == 4 + 2


def test_allreduce_raw_components_in_jsonl(tmp_path, capsys):
    """The allreduce benchmark reports raw t_with/t_without in JSONL so a
    clamped-to-zero difference is diagnosable (VERDICT r1 weak #7)."""
    import json

    jl = tmp_path / "out.jsonl"
    rc = stencil2d.main(
        SMALL + ["--dtype", "float32", "--only", "1:0", "--jsonl", str(jl)]
    )
    capsys.readouterr()
    assert rc == 0
    raws = [
        json.loads(line)
        for line in jl.read_text().splitlines()
        if json.loads(line).get("kind") == "allreduce_raw"
    ]
    assert len(raws) == 1
    assert raws[0]["t_with_s"] > 0 and raws[0]["t_without_s"] > 0


def test_iter_lines_report_periter_stats(capsys):
    """Per-iteration accumulation past warmup (≅ mpi_stencil2d_gt.cc:512-526):
    every TEST line gets an ITER twin with mean/min/max, and min <= mean <=
    max with mean*n_iter ~ the rank-summed total / world."""
    rc = stencil2d.main(SMALL + ["--dtype", "float32"])
    out = capsys.readouterr().out
    assert rc == 0
    iters = re.findall(
        r"ITER dim:(\d), (device|managed)\s*, buf:(\d); exchange "
        r"mean=([\d.e+-]+), min=([\d.e+-]+), max=([\d.e+-]+)",
        out,
    )
    assert len(iters) == 4
    for *_, mean, mn, mx in iters:
        assert float(mn) <= float(mean) <= float(mx)
        assert float(mn) > 0


def test_fused_mode(capsys):
    """--fused times exchange+stencil as one program (split-vs-fused A/B);
    err gates must still pass from the fused derivative."""
    rc = stencil2d.main(SMALL + ["--dtype", "float64", "--fused"])
    out = capsys.readouterr().out
    assert rc == 0
    deriv = re.findall(r"fused=([\d.]+), err=([\d.e+-]+)", out)
    assert deriv and all(float(e) < 1e-8 for _, e in deriv)
    assert "ITER dim:0" in out and "fused mean=" in out
    # the HOST_STAGED config can't fuse and is skipped, not silently dropped
    assert "SKIP dim:0, device, buf:1" in out

    import pytest

    with pytest.raises(SystemExit):
        stencil2d.main(SMALL + ["--fused", "--kernel", "pallas"])


def test_tight_tol_fails(capsys):
    rc = stencil2d.main(SMALL + ["--dtype", "float32", "--tol", "1e-14"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "ERR_NORM FAIL" in out


def test_pallas_kernel_mode(capsys):
    rc = stencil2d.main(
        SMALL + ["--dtype", "float64", "--kernel", "pallas"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    deriv = re.findall(r"err=([\d.e+-]+)", out)
    assert deriv and all(float(e) < 1e-8 for e in deriv)


def test_debug_dump(capsys):
    rc = stencil2d.main(SMALL + ["--dtype", "float64", "--debug-dump"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "DEBUG rank 0 lo ghost+edge:" in out
    assert "DEBUG rank 7 hi ghost+edge:" in out


def test_determinism_across_runs(capsys):
    """Cross-run determinism assert — the framework's race-detector analog
    (SURVEY §5.2): two identical distributed runs must emit identical
    err/time-independent results."""
    import re as _re

    def errs():
        rc = stencil2d.main(SMALL + ["--dtype", "float32"])
        assert rc == 0
        return _re.findall(r"err=([\d.e+-]+)", capsys.readouterr().out)

    assert errs() == errs()


def test_rejects_bad_sizes(capsys):
    import pytest

    with pytest.raises(SystemExit):
        stencil2d.main(["--n-local", "3"])
    with pytest.raises(SystemExit):
        stencil2d.main(["--n-iter", "0"])


def test_iterate_tier_leg_fused(capsys):
    """ISSUE 15: the kernel-tier iterate leg under the fused tier — the
    ITER line, the fused-vs-chained bitwise gate, the analytic eigen
    err-norm gate, and the seam-wait OVERLAP record all fire."""
    rc = stencil2d.main(
        ["--n-local", "16", "--n-other", "32", "--dtype", "float32",
         "--iterate-tier", "rdma-fused", "--iterate-only",
         "--iterate-iters", "3"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "ITER tier=rdma-fused" in out
    assert "ITER BITWISE fused==chained" in out and "OK" in out
    assert "ITER ERR rel=" in out
    assert "OVERLAP stencil2d_fused_rdma overlap_frac=" in out
    assert "TEST dim:" not in out  # --iterate-only skips the matrix


def test_iterate_tier_leg_steps4_records_overlap(tmp_path, capsys):
    """steps=4 deep-ghost leg; the overlap record lands in JSONL with
    the fused tier named (the OVERLAP-table/provenance contract)."""
    import json

    jl = tmp_path / "iter.jsonl"
    rc = stencil2d.main(
        ["--n-local", "24", "--n-other", "32", "--dtype", "float32",
         "--iterate-tier", "rdma-fused", "--iterate-steps", "4",
         "--iterate-only", "--iterate-iters", "2", "--jsonl", str(jl)]
    )
    capsys.readouterr()
    assert rc == 0
    ovs = [
        json.loads(line)
        for line in jl.read_text().splitlines()
        if json.loads(line).get("kind") == "overlap"
    ]
    assert len(ovs) == 1
    ov = ovs[0]
    assert ov["op"] == "stencil2d_fused_rdma"
    assert ov["tier"] == "rdma-fused"
    assert 0.0 <= ov["overlap_frac"] <= 1.0
    assert ov["comm_s"] > 0 and ov["compute_s"] > 0
    assert ov["drain_s"] >= 0


def test_iterate_tier_leg_tune_sweep(tmp_path, capsys):
    """--iterate-tier auto --tune sweeps stencil/tier through the PR-4
    engine: every tier candidate measured (or visibly declined), the
    winner persisted and applied."""
    import json

    from tpu_mpi_tests.tune import registry as tr

    jl = tmp_path / "tune.jsonl"
    try:
        rc = stencil2d.main(
            ["--n-local", "24", "--n-other", "32", "--dtype", "float32",
             "--iterate-tier", "auto", "--iterate-only",
             "--iterate-iters", "2", "--tune",
             "--tune-cache", str(tmp_path / "cache.json"),
             "--tune-budget", "600", "--jsonl", str(jl)]
        )
    finally:
        tr.deconfigure()
    out = capsys.readouterr().out
    assert rc == 0
    recs = [json.loads(line) for line in jl.read_text().splitlines()]
    tune = [r for r in recs if r.get("kind") == "tune"
            and r.get("knob") == "stencil/tier"]
    # prior first, every candidate measured or visibly declined
    assert tune and tune[0]["candidate"] == "blocks"
    assert {t["candidate"] for t in tune} == {
        "blocks", "rdma-chained", "rdma-fused", "xla"}
    fused = [t for t in tune if t["candidate"] == "rdma-fused"][0]
    assert fused.get("seconds") or fused.get("error")
    results = [r for r in recs if r.get("kind") == "tune_result"
               and r.get("knob") == "stencil/tier"]
    assert len(results) == 1
    assert f"ITER tier={results[0]['value']}" in out


def test_iterate_only_requires_tier():
    import pytest

    with pytest.raises(SystemExit):
        stencil2d.main(["--iterate-only"])
