"""Collective-sweep driver tests (8 fake devices, real collectives)."""

import json
import re

from tpu_mpi_tests.drivers import collbench


def test_sweep_all_collectives(capsys, tmp_path):
    jl = tmp_path / "coll.jsonl"
    rc = collbench.main(
        ["--sizes-kib", "4,64", "--n-iter", "20", "--jsonl", str(jl)]
    )
    out = capsys.readouterr().out
    assert rc == 0
    rows = [m[:4] for m in re.findall(collbench.COLL_LINE_RE, out)]
    assert len(rows) == len(collbench.COLLECTIVES) * 2  # x 2 sizes
    assert {r[0] for r in rows} == set(collbench.COLLECTIVES)
    import math

    for name, nbytes, us, busbw in rows:
        # timing positivity is not assertable in CI (a loaded host can make
        # the short/long differencing go non-positive, which chain_rate
        # surfaces as NaN) — assert structure: values are NaN or >= 0,
        # never negative/inf; hardware meaning comes from real-chip runs
        for v in (float(us), float(busbw)):
            assert math.isnan(v) or (math.isfinite(v) and v >= 0)
    recs = [json.loads(line) for line in jl.read_text().splitlines()]
    coll = [r for r in recs if r.get("kind") == "coll"]
    assert len(coll) == len(collbench.COLLECTIVES) * 2
    assert all(r["world"] == 8 for r in coll)


def test_rdma_credits_2_sweep(capsys):
    """--rdma-credits 2 runs the double-buffered reduce-scatter variant
    through the driver (the one-command pod experiment) and reports a
    structurally valid row."""
    rc = collbench.main([
        "--collectives", "allreduce_rdma", "--sizes-kib", "64",
        "--n-iter", "20", "--rdma-credits", "2",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "rdma_credits=2" in out
    rows = re.findall(collbench.COLL_LINE_RE, out)
    assert rows and rows[0][0] == "allreduce_rdma"
    # the SHARED parse pattern recovers the credit depth (last group) —
    # format and regex live next to each other in collbench by contract
    assert rows[0][5] == "2"


def test_busbw_accounting():
    # nccl-tests conventions at w=8, 1 MiB shards
    b = 1 << 20
    assert collbench._busbw_bytes("allgather", b, 8) == 7 * b
    assert collbench._busbw_bytes("allreduce", b, 8) == 2 * 7 / 8 * b
    assert collbench._busbw_bytes("reducescatter", b, 8) == 7 / 8 * b
    assert collbench._busbw_bytes("ppermute", b, 8) == b
    assert collbench._busbw_bytes("alltoall", b, 8) == 7 / 8 * b
    assert collbench._busbw_bytes("allreduce", b, 1) == 0.0
    # hand ring twins move the same bytes as their XLA counterparts
    assert collbench._busbw_bytes("allgather_rdma", b, 8) == 7 * b
    assert collbench._busbw_bytes("allreduce_rdma", b, 8) == 2 * 7 / 8 * b


def test_rdma_tier_sweep_reports_rows_and_alignment_skip(capsys):
    rc = collbench.main([
        "--collectives", "allgather_rdma,allreduce_rdma",
        "--sizes-kib", "4,64", "--n-iter", "20",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    names = [m[0] for m in re.findall(collbench.COLL_LINE_RE, out)]
    assert "allgather_rdma" in names and "allreduce_rdma" in names
    # 4 KiB f32 shards (1024 elts) sit below the 8-ring allreduce floor of
    # w x 128 x 8 = 8192 elements: skipped visibly, not silently
    assert "COLL-SKIP allreduce_rdma bytes=4096" in out


def test_rejects_unknown_collective(capsys):
    rc = collbench.main(["--collectives", "allgather,bogus", "--n-iter", "20"])
    out = capsys.readouterr().out
    assert rc == 2
    assert "unknown collective" in out


class TestAttnbench:
    """Attention benchmark driver (same chained-measurement pattern as
    collbench; correctness of the tiers is gated in test_ring.py)."""

    def test_tiers_run_and_report(self, capsys):
        from tpu_mpi_tests.drivers import attnbench

        rc = attnbench.main([
            "--fake-devices", "8", "--seq-len", "128", "--head-dim", "16",
            "--tiers", "xla,flash,ring,ulysses", "--n-iter", "20",
        ])
        out = capsys.readouterr().out
        assert rc == 0, out
        for tier in ("xla", "flash", "ring", "ulysses"):
            assert f"ATTN {tier} L=128 d=16 float32 " in out
        assert "FAIL" not in out

    def test_ring_stripe_runs_and_requires_causal(self, capsys):
        from tpu_mpi_tests.drivers import attnbench

        rc = attnbench.main([
            "--fake-devices", "8", "--seq-len", "128", "--head-dim", "16",
            "--tiers", "ring", "--n-iter", "20", "--causal", "--stripe",
        ])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "ATTN ring[striped] L=128 d=16 float32 " in out
        assert "FAIL" not in out

        import pytest as _pytest

        with _pytest.raises(SystemExit):
            attnbench.main([
                "--fake-devices", "8", "--seq-len", "128", "--head-dim",
                "16", "--tiers", "ring", "--n-iter", "20", "--stripe",
            ])

    def test_unknown_tier_rejected(self, capsys):
        from tpu_mpi_tests.drivers import attnbench

        rc = attnbench.main([
            "--fake-devices", "8", "--seq-len", "64", "--head-dim", "8",
            "--tiers", "bogus", "--n-iter", "20",
        ])
        assert rc == 2
        assert "unknown tier" in capsys.readouterr().out

    def test_indivisible_sequence_fails_fast(self):
        import pytest as _pytest

        from tpu_mpi_tests.drivers import attnbench
        from tpu_mpi_tests.utils import TpuMtError

        # 100 % 8 != 0 → the fail-fast divisibility exception propagates
        # (the framework's CHECK-abort analog, PARITY §2.2 #13)
        with _pytest.raises(TpuMtError, match="not evenly divisible"):
            attnbench.main([
                "--fake-devices", "8", "--seq-len", "100", "--head-dim",
                "8", "--tiers", "ring", "--n-iter", "20",
            ])
