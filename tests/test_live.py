"""tpumt-top (instrument/live.py) and the ONLINE doctor
(tpumt-doctor --follow): the incremental JSONL tailer, the shared
ghost-sibling run filter, dashboard rendering, and the
online-equals-offline byte-identity acceptance (shared rule kernels)."""

import json
import os
import threading
import time

import pytest

from tpu_mpi_tests.instrument import diagnose, live


def _write_jsonl(path, records):
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def _manifest(rank, n=2):
    return {"kind": "manifest", "process_index": rank,
            "process_count": n, "platform": "cpu",
            "global_device_count": n}


def _clock_sync(run_id):
    return {"kind": "clock_sync", "run_sync_us": run_id, "offset_s": 0.0}


def _progress(phase, seconds, count, t):
    return {"kind": "time", "event": "progress", "phase": phase,
            "seconds": seconds, "count": count, "t": t}


def _final_time(phase, seconds, count, t):
    return {"kind": "time", "phase": phase, "seconds": seconds,
            "count": count, "t": t}


def _close_markers(t):
    return [{"kind": "telemetry_summary", "op": "x"},
            {"kind": "mem", "event": "final", "t": t}]


def _straggler_run(run_id=777, n=30, slow_factor=4.0, t0=100.0):
    """Two ranks' record streams: rank 1's kernel phase runs
    ``slow_factor`` slower — progress snapshots during the run, final
    records + close markers at the end."""
    streams = {0: [_manifest(0), _clock_sync(run_id)],
               1: [_manifest(1), _clock_sync(run_id)]}
    for i in range(1, n + 1):
        t = t0 + i
        streams[0].append(_progress("kernel", 0.1 * i, 5 * i, t))
        streams[1].append(_progress("kernel", 0.1 * slow_factor * i,
                                    5 * i, t))
        for rank in (0, 1):
            # local (world=1) telemetry spans: mid-run the stream has
            # recorded spans but no summary marker yet, which is what
            # makes offline semantics read it as not-yet-judgeable
            streams[rank].append(
                {"kind": "span", "op": "local_step", "nbytes": 0,
                 "world": 1, "seconds": 0.01, "t_start": t,
                 "t_end": t + 0.01})
    t_end = t0 + n + 1
    streams[0].append(_final_time("kernel", 0.1 * n, 5 * n, t_end))
    streams[1].append(_final_time("kernel", 0.1 * slow_factor * n,
                                  5 * n, t_end))
    for rank in (0, 1):
        streams[rank].extend(_close_markers(t_end))
    return streams


class TestFileTail:
    def test_incremental_with_partial_lines(self, tmp_path):
        p = tmp_path / "t.jsonl"
        p.write_text('{"kind": "a"}\n{"kind": ')
        tail = live.FileTail(str(p))
        recs = tail.poll()
        assert [(ln, r["kind"]) for ln, r in recs] == [(1, "a")]
        # the partial line is NOT consumed until its newline arrives
        with open(p, "a") as f:
            f.write('"b"}\n')
        recs = tail.poll()
        assert [(ln, r["kind"]) for ln, r in recs] == [(2, "b")]
        assert tail.poll() == []

    def test_line_numbers_skip_garbage(self, tmp_path):
        p = tmp_path / "t.jsonl"
        p.write_text('{"kind": "a"}\nnot json\n{"kind": "b"}\n')
        tail = live.FileTail(str(p))
        assert [(ln, r["kind"]) for ln, r in tail.poll()] \
            == [(1, "a"), (3, "b")]

    def test_truncation_restarts(self, tmp_path):
        p = tmp_path / "t.jsonl"
        p.write_text('{"kind": "a"}\n{"kind": "b"}\n')
        tail = live.FileTail(str(p))
        tail.poll()
        p.write_text('{"kind": "c"}\n')
        assert [(ln, r["kind"]) for ln, r in tail.poll()] == [(1, "c")]

    def test_missing_file_is_quietly_empty(self, tmp_path):
        tail = live.FileTail(str(tmp_path / "nope.jsonl"))
        assert tail.poll() == []


class TestRunTail:
    def test_stale_sibling_of_an_earlier_run_is_ignored(self, tmp_path):
        """The ghost-track hazard (PR-2's offline fix, shared helper):
        a leftover .p1 file stamped by an EARLIER run at the same base
        path must not be tailed as a live rank."""
        _write_jsonl(tmp_path / "out.p0.jsonl",
                     [_manifest(0), _clock_sync(111)])
        _write_jsonl(tmp_path / "out.p1.jsonl",
                     [_manifest(1), _clock_sync(42)])  # stale run
        old = time.time() - 3600
        os.utime(tmp_path / "out.p1.jsonl", (old, old))
        tail = live.RunTail([str(tmp_path / "out.jsonl")])
        recs = tail.poll()
        assert tail.files() == [str(tmp_path / "out.p0.jsonl")]
        assert all(p.endswith(".p0.jsonl") for p, _ln, _r in recs)

    def test_same_run_sibling_is_admitted(self, tmp_path):
        _write_jsonl(tmp_path / "out.p0.jsonl",
                     [_manifest(0), _clock_sync(111)])
        _write_jsonl(tmp_path / "out.p1.jsonl",
                     [_manifest(1), _clock_sync(111)])
        tail = live.RunTail([str(tmp_path / "out.jsonl")])
        tail.poll()
        assert len(tail.files()) == 2

    def test_rank_file_appearing_mid_follow_is_picked_up(self, tmp_path):
        _write_jsonl(tmp_path / "out.p0.jsonl",
                     [_manifest(0), _clock_sync(111)])
        tail = live.RunTail([str(tmp_path / "out.jsonl")])
        tail.poll()
        assert len(tail.files()) == 1
        _write_jsonl(tmp_path / "out.p1.jsonl",
                     [_manifest(1), _clock_sync(111)])
        recs = tail.poll()
        assert len(tail.files()) == 2
        assert any(p.endswith(".p1.jsonl") for p, _ln, _r in recs)


class TestRunIdScan:
    def test_fast_scan_matches_full_parse(self, tmp_path):
        """The admission fast path must agree with the canonical
        timeline parser on every file shape: multiple appended runs,
        stampless segments, garbage lines, and decoys."""
        from tpu_mpi_tests.instrument import timeline

        p = tmp_path / "runs.jsonl"
        recs = (
            [_manifest(0), _clock_sync(11),
             {"kind": "span", "op": "clock_sync_decoy",
              "note": '"clock_sync"'}]
            + [_manifest(0)]  # stampless middle segment
            + [_manifest(0), _clock_sync(33)]
        )
        body = "".join(json.dumps(r) + "\n" for r in recs)
        p.write_text(body + "not json but \"clock_sync\" anyway\n")
        ids, newest = live._scan_run_ids(str(p))
        assert ids == timeline.run_sync_ids(str(p)) == {11, 33}
        # newest = the newest segment's stamp, per the canonical
        # segmenter the offline consumers use
        segs = timeline._run_segments(
            [r for r in recs] + [])
        ref = None
        for seg in segs:
            rid = timeline._segment_run_id(seg)
            if rid is not None:
                ref = rid
        assert newest == ref == 33

    def test_missing_file_scans_empty(self, tmp_path):
        assert live._scan_run_ids(str(tmp_path / "no.jsonl")) \
            == (set(), None)


class TestDashboard:
    def _fed(self):
        dash = live.Dashboard()
        for rec in [
            _manifest(0),
            {"kind": "serve", "event": "window",
             "class": "daxpy:4096:float32", "arrivals": 10,
             "requests": 9, "errors": 0, "shed": 1, "queue_depth": 2,
             "p50_ms": 1.2, "p95_ms": 2.5, "p99_ms": 4.0,
             "qd_p99_ms": 3.1, "svc_p99_ms": 0.9,
             "offered_hz": 10.0, "achieved_hz": 9.0, "t_end": 105.0},
            {"kind": "span", "op": "halo_exchange", "nbytes": 1 << 20,
             "world": 2, "seconds": 0.01, "gbps": 0.105, "t_end": 105.5},
            {"kind": "mem", "rank": 0, "bytes_in_use": 3 << 20,
             "peak_bytes_in_use": 4 << 20, "t": 106.0},
            {"kind": "overlap", "op": "halo", "depth": 2,
             "overlap_frac": 0.91, "drain_s": 0.002},
            {"kind": "health", "event": "heartbeat", "rank": 0,
             "seq": 3, "t": 106.5},
            {"kind": "health", "event": "tune_stale", "op": "halo",
             "signal": "gbps", "sag_pct": 31.0, "t": 107.0},
        ]:
            dash.feed(rec)
        return dash

    def test_render_sections(self):
        dash = self._fed()
        frame = live.render(dash, ["out.p0.jsonl"])
        assert "SLO" in frame and "daxpy:4096:float32" in frame
        # the latency-anatomy columns render live (dashes pre-PR-16)
        assert "qd99" in frame and "svc99" in frame
        assert "3.1" in frame and "0.9" in frame
        assert "OPS" in frame and "halo_exchange" in frame
        assert "MEM" in frame and "3.0MiB" in frame
        assert "OVLP" in frame and "frac=0.910" in frame
        assert "HEALTH" in frame and "tune_stale" in frame
        assert "sag=31.0%" in frame
        assert "BEAT" in frame

    def test_rerun_appended_to_same_file_resets_the_model(self):
        """Append-mode JSONL holds several runs back to back; like
        every other consumer, the dashboard must show only the newest
        segment — a second manifest on a followed path starts the
        model over (and sibling ranks' manifests of the SAME new run
        do not re-reset it)."""
        dash = live.Dashboard()
        span = {"kind": "span", "op": "allreduce", "nbytes": 4096,
                "world": 2, "seconds": 0.01, "t_end": 100.0}
        dash.feed(_manifest(0), "p0")
        dash.feed(_manifest(1), "p1")
        for _ in range(5):
            dash.feed(span, "p0")
        assert dash.registry.value("tpumt_spans",
                                   (("op", "allreduce"),)) == 5
        # the rerun: new manifests on both paths, then fresh traffic
        dash.feed(_manifest(0), "p0")
        dash.feed(span, "p0")
        dash.feed(_manifest(1), "p1")  # sibling manifest: NO re-reset
        dash.feed(span, "p0")
        assert dash.registry.value("tpumt_spans",
                                   (("op", "allreduce"),)) == 2

    def test_render_empty_model_is_just_the_header(self):
        frame = live.render(live.Dashboard(), [])
        assert frame.splitlines()[0].startswith("tpumt-top")
        assert "SLO" not in frame

    def test_main_single_frame(self, tmp_path, capsys):
        _write_jsonl(tmp_path / "out.jsonl", [
            _manifest(0, n=1),
            {"kind": "span", "op": "allreduce", "nbytes": 4096,
             "world": 2, "seconds": 0.001, "gbps": 4.1, "t_end": 100.0},
        ])
        assert live.main([str(tmp_path / "out.jsonl")]) == 0
        outp = capsys.readouterr().out
        assert "tpumt-top" in outp and "allreduce" in outp

    def test_main_missing_path_exits_two(self, tmp_path, capsys):
        """One-shot mode shares the sibling CLIs' no-input guard: a
        typo'd path must not read as a clean empty frame."""
        assert live.main([str(tmp_path / "typo.jsonl")]) == 2
        assert "no input files found" in capsys.readouterr().err

    def test_main_frames_flag_bounds_follow(self, tmp_path, capsys):
        _write_jsonl(tmp_path / "out.jsonl", [_manifest(0, n=1)])
        t0 = time.monotonic()
        assert live.main([str(tmp_path / "out.jsonl"), "--frames", "2",
                          "--interval", "0.05"]) == 0
        assert time.monotonic() - t0 < 10.0
        assert capsys.readouterr().out.count("tpumt-top") == 2


class TestOnlineOfflineAgreement:
    def test_incremental_equals_batch_byte_identical(self, tmp_path):
        """THE shared-kernel acceptance: feeding a completed organic
        stream record-by-record through the incremental digests yields
        byte-identical findings to the offline batch load."""
        streams = _straggler_run()
        files = {}
        for rank, recs in streams.items():
            p = tmp_path / f"run.p{rank}.jsonl"
            _write_jsonl(p, recs)
            files[rank] = str(p)
        batch = diagnose.diagnose_files(sorted(files.values()))
        assert [f["class"] for f in batch] == ["straggler"]

        inc_streams = []
        for rank, recs in streams.items():
            s = diagnose._Stream(rank, files[rank])
            for ln, rec in enumerate(recs, start=1):
                s.add(ln, rec)
            inc_streams.append(s)
        inc = diagnose.diagnose_streams(
            inc_streams, {"manifest": streams[0][0], "expected": 2})
        assert json.dumps(inc, sort_keys=True) \
            == json.dumps(batch, sort_keys=True)

    def test_followed_mode_convicts_midrun_from_progress_only(self):
        """Mid-run there are no close markers and no final records —
        followed=True must still convict the slow rank from the
        cumulative progress snapshots alone."""
        streams = _straggler_run()
        inc = []
        for rank in (0, 1):
            s = diagnose._Stream(rank, f"run.p{rank}.jsonl")
            # feed only a prefix: manifests + progress + spans, no
            # finals and no close markers — the mid-run state
            for ln, rec in enumerate(streams[rank][:40], start=1):
                s.add(ln, rec)
            inc.append(s)
        assert not any(s.closed for s in inc)
        offline = diagnose.diagnose_streams(inc, {})
        assert offline == []  # mid-run streams judge as nothing offline
        online = diagnose.diagnose_streams(inc, {}, followed=True)
        assert [(f["class"], f["rank"]) for f in online] \
            == [("straggler", 1)]

    def test_final_time_records_override_progress(self):
        """A completed stream must diagnose identically with and
        without the live progress trail — finals win."""
        base = _straggler_run()
        stripped = {
            rank: [r for r in recs
                   if not (r.get("kind") == "time"
                           and r.get("event") == "progress")]
            for rank, recs in base.items()
        }

        def load(streams):
            out = []
            for rank in (0, 1):
                s = diagnose._Stream(rank, f"p{rank}")
                for ln, rec in enumerate(streams[rank], start=1):
                    s.add(ln, rec)
                out.append(s)
            return diagnose.diagnose_streams(out, {})

        with_trail = load(base)
        without_trail = load(stripped)
        assert json.dumps(with_trail, sort_keys=True) \
            == json.dumps(without_trail, sort_keys=True)

    def test_follow_cli_convicts_while_writer_is_alive(self, tmp_path):
        """The live-conviction acceptance, in-process: a writer thread
        streams the straggler run; tpumt-doctor --follow --expect must
        exit 0 BEFORE the writer finishes."""
        streams = _straggler_run(n=40)
        base = tmp_path / "run.jsonl"
        paths = {r: tmp_path / f"run.p{r}.jsonl" for r in (0, 1)}
        writer_done = threading.Event()

        def writer():
            handles = {r: open(paths[r], "a") for r in (0, 1)}
            idx = {r: 0 for r in (0, 1)}
            # header first, then interleave the bodies slowly
            for r in (0, 1):
                for rec in streams[r][:2]:
                    handles[r].write(json.dumps(rec) + "\n")
                handles[r].flush()
                idx[r] = 2
            n = max(len(streams[r]) for r in (0, 1))
            for i in range(2, n):
                for r in (0, 1):
                    if i < len(streams[r]):
                        handles[r].write(
                            json.dumps(streams[r][i]) + "\n")
                        handles[r].flush()
                time.sleep(0.05)
            for h in handles.values():
                h.close()
            writer_done.set()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        rc = diagnose.main([str(base), "--follow", "--expect",
                            "straggler:1", "--interval", "0.05",
                            "--timeout", "30"])
        convicted_live = not writer_done.is_set()
        t.join(timeout=30)
        assert rc == 0
        assert convicted_live, "conviction must land mid-run"
        # and the SAME organic stream post-mortem agrees
        assert diagnose.main([str(base), "--expect",
                              "straggler:1"]) == 0

    def test_follow_final_output_matches_offline(self, tmp_path,
                                                 capsys):
        """--follow on a COMPLETED stream finalizes immediately (all
        ranks closed) and its verdict lines are byte-identical to the
        offline doctor's."""
        streams = _straggler_run()
        for rank, recs in streams.items():
            _write_jsonl(tmp_path / f"run.p{rank}.jsonl", recs)
        base = str(tmp_path / "run.jsonl")
        rc_follow = diagnose.main([base, "--follow", "--interval",
                                   "0.05", "--timeout", "10"])
        out_follow = capsys.readouterr().out
        rc_offline = diagnose.main([base])
        out_offline = capsys.readouterr().out
        assert rc_follow == rc_offline == 1
        follow_findings = [ln for ln in out_follow.splitlines()
                           if ln.startswith("FINDING")]
        offline_findings = [ln for ln in out_offline.splitlines()
                            if ln.startswith("FINDING")]
        # the final (offline-semantics) pass prints the identical
        # verdict the post-mortem doctor prints; the live pass printed
        # it once already as it landed
        assert follow_findings[-len(offline_findings):] \
            == offline_findings

    def test_follow_json_expect_early_exit_emits_document(
        self, tmp_path, capsys
    ):
        """--json keeps stdout a parseable JSON document on EVERY exit
        path — including the live --expect early exit (the EXPECT OK
        status goes to stderr there, like offline)."""
        streams = _straggler_run()
        for rank, recs in streams.items():
            # mid-run prefix only: conviction comes from followed mode
            _write_jsonl(tmp_path / f"run.p{rank}.jsonl",
                         recs[:40])
        rc = diagnose.main([str(tmp_path / "run.jsonl"), "--follow",
                            "--json", "--expect", "straggler:1",
                            "--interval", "0.05", "--timeout", "10"])
        cap = capsys.readouterr()
        assert rc == 0
        doc = json.loads(cap.out)
        assert [(f["class"], f["rank"]) for f in doc["findings"]] \
            == [("straggler", 1)]
        assert "DOCTOR EXPECT OK" in cap.err

    def test_follow_never_appearing_file_finalizes(self, tmp_path,
                                                   monkeypatch):
        """A typo'd path / crashed-before-open run must not hang the
        follower forever even without --timeout: the no-files wait is
        floored, then finalizes."""
        monkeypatch.setattr(diagnose, "NO_FILE_GRACE_S", 0.2)
        t0 = time.monotonic()
        rc = diagnose.main([str(tmp_path / "never.jsonl"), "--follow",
                            "--interval", "0.05", "--idle", "0.1"])
        assert time.monotonic() - t0 < 10.0
        # same contract as offline on a missing path: exit 2, never a
        # clean "DOCTOR OK" for a file that was never followed
        assert rc == 2

    def test_follow_header_only_gap_holds_past_idle(self, tmp_path,
                                                    monkeypatch):
        """A stream that has only its manifest/clock_sync header (the
        driver is still importing jax / compiling) must not finalize
        at --idle — the startup floor holds until the first workload
        record."""
        monkeypatch.setattr(diagnose, "NO_FILE_GRACE_S", 1.0)
        _write_jsonl(tmp_path / "run.p0.jsonl",
                     [_manifest(0, n=1), _clock_sync(1)])
        t0 = time.monotonic()
        rc = diagnose.main([str(tmp_path / "run.jsonl"), "--follow",
                            "--interval", "0.05", "--idle", "0.1"])
        elapsed = time.monotonic() - t0
        assert elapsed >= 0.9, "finalized during the startup gap"
        assert rc == 0  # header-only run: empty diagnosis

    def test_followed_mode_gives_grace_to_unopened_rank_files(self):
        """Mid-run, a manifest-declared sibling whose file has not
        appeared yet (still importing jax) must NOT convict as
        missing_rank — the absent-file rule is post-mortem-only; the
        follower's FINAL pass still applies it."""
        streams = _straggler_run()
        s = diagnose._Stream(0, "run.p0.jsonl")
        for ln, rec in enumerate(streams[0][:20], start=1):
            s.add(ln, rec)
        ctx = {"manifest": streams[0][0], "expected": 2}
        online = diagnose.diagnose_streams([s], ctx, followed=True)
        assert online == []
        offline = diagnose.diagnose_streams([s], ctx, followed=False)
        assert [(f["class"], f["rank"]) for f in offline] \
            == [("missing_rank", 1)]

    def test_followed_mode_oom_exonerated_by_live_sibling(self):
        """Mid-follow every mem-recording stream is still missing its
        final marker — a sibling ACTIVELY recording at the same
        watermark must still exonerate a census-only growth ramp, or
        two healthy growing ranks convict each other of oom live."""
        def grower(rank):
            s = diagnose._Stream(rank, f"run.p{rank}.jsonl")
            s.add(1, _manifest(rank))
            for i in range(1, 9):
                # both ranks grow 8x with the tail still climbing —
                # the same (legitimate) working-set ramp on each
                s.add(1 + i, {"kind": "mem", "event": "sample",
                              "t": 100.0 + i,
                              "live_bytes": 1000 * i})
            return s

        inc = [grower(0), grower(1)]
        online = diagnose.diagnose_streams(inc, {}, followed=True)
        assert [f for f in online if f["class"] == "oom"] == []

    def test_follow_rerun_resets_expected_rank_count(self, tmp_path,
                                                     capsys):
        """A 2-process rerun appended after a 4-process run must not
        inherit expected=4: the follower's final pass would otherwise
        convict phantom missing ranks the offline (newest-segment)
        doctor never sees."""
        streams = _straggler_run()
        four = [{**_manifest(r, n=4), "process_index": r}
                for r in (0, 1)]
        for rank, recs in streams.items():
            _write_jsonl(tmp_path / f"run.p{rank}.jsonl",
                         [four[rank]] + recs)  # old 4-proc segment,
            # then the full 2-proc run appended (manifest n=2 inside)
        base = str(tmp_path / "run.jsonl")
        rc = diagnose.main([base, "--follow", "--json", "--interval",
                            "0.05", "--timeout", "10"])
        follow_doc = json.loads(capsys.readouterr().out)
        rc_off = diagnose.main([base, "--json"])
        offline_doc = json.loads(capsys.readouterr().out)
        # the straggler verdict, NOT missing_rank:2/3 phantoms —
        # byte-identical to the offline newest-segment doctor
        assert rc == rc_off == 1
        assert [(f["class"], f["rank"])
                for f in follow_doc["findings"]] \
            == [("straggler", 1)]
        assert json.dumps(follow_doc["findings"], sort_keys=True) \
            == json.dumps(offline_doc["findings"], sort_keys=True)

    def test_shed_storm_older_than_retention_still_convicts(
        self, monkeypatch
    ):
        """Windows evicted from the bounded digest fold into a settled
        aggregate: a storm in the first windows of a long run must
        still convict post-mortem with its ORIGINAL evidence refs,
        exactly like the pre-digest unbounded scan."""
        monkeypatch.setattr(diagnose, "SHED_WINDOWS_KEPT", 8)

        def win(i, shed):
            return {"kind": "serve", "event": "window", "class": "c",
                    "arrivals": 20, "shed": shed, "queue_max": 30,
                    "t_end": 100.0 + i}

        s = diagnose._Stream(0, "run.p0.jsonl")
        s.add(1, _manifest(0, n=1))
        ln = 2
        for i in range(5):          # the early storm
            s.add(ln, win(i, 15))
            ln += 1
        for i in range(5, 60):      # long clean tail evicts the storm
            s.add(ln, win(i, 0))
            ln += 1
        assert len(s.serve_windows["c"]) == 8  # digest stayed bounded
        (f,) = diagnose.diagnose_streams([s], {})
        assert f["class"] == "shed_storm"
        assert "75 shed" in f["detail"] or "75 requests shed" \
            in f["detail"]
        # evidence refs point at the ORIGINAL first shed windows
        assert f["evidence"] and ":2:" in f["evidence"][0]

    def test_quarantined_storm_stays_exempt_across_eviction(
        self, monkeypatch
    ):
        """The summary-only total-retro-exemption (-inf boundary,
        arriving at stream END) must still exempt windows that were
        already folded into the settled aggregate."""
        monkeypatch.setattr(diagnose, "SHED_WINDOWS_KEPT", 8)
        s = diagnose._Stream(0, "run.p0.jsonl")
        s.add(1, _manifest(0, n=1))
        ln = 2
        for i in range(40):
            s.add(ln, {"kind": "serve", "event": "window", "class": "c",
                       "arrivals": 20, "shed": 15, "queue_max": 30,
                       "t_end": 100.0 + i})
            ln += 1
        s.add(ln, {"kind": "serve", "event": "summary", "class": "c",
                   "quarantines": 2, "t_end": 200.0})
        assert diagnose.diagnose_streams([s], {}) == []

    def test_follow_ctrl_c_finalizes_instead_of_traceback(
        self, tmp_path, monkeypatch
    ):
        """Ctrl-C on a live watch must end with the final
        offline-semantics verdict, not a KeyboardInterrupt traceback."""
        streams = _straggler_run()
        for rank, recs in streams.items():
            _write_jsonl(tmp_path / f"run.p{rank}.jsonl", recs)

        real_sleep = time.sleep
        calls = {"n": 0}

        def interrupting_sleep(s):
            calls["n"] += 1
            if calls["n"] >= 1:
                raise KeyboardInterrupt
            real_sleep(s)

        monkeypatch.setattr(diagnose.time, "sleep", interrupting_sleep)
        # closed streams normally finalize before any sleep; follow an
        # INCOMPLETE copy so the loop reaches its sleep
        _write_jsonl(tmp_path / "run.p1.jsonl", streams[1][:8])
        rc = diagnose.main([str(tmp_path / "run.jsonl"), "--follow",
                            "--interval", "0.01", "--idle", "1e9",
                            "--timeout", "1e9"])
        assert rc == 1  # the finalize verdict, not an uncaught crash

    def test_follow_idle_finalizes_truncated_stream(self, tmp_path):
        """A run that died (files stop growing, no close markers) must
        not hang the follower: --idle finalizes with the offline
        verdict."""
        streams = _straggler_run()
        # rank 1 dies early: no finals, no close markers
        _write_jsonl(tmp_path / "run.p0.jsonl", streams[0])
        _write_jsonl(tmp_path / "run.p1.jsonl", streams[1][:8])
        rc = diagnose.main([str(tmp_path / "run.jsonl"), "--follow",
                            "--interval", "0.05", "--idle", "0.3",
                            "--timeout", "10"])
        assert rc == 1
        # and the final verdict is the offline one: the truncated rank
        # convicts as missing while its healthy sibling closed cleanly
        offline = diagnose.diagnose_files(
            [str(tmp_path / "run.p0.jsonl"),
             str(tmp_path / "run.p1.jsonl")])
        assert [(f["class"], f["rank"]) for f in offline] \
            == [("missing_rank", 1)]


class TestNoJaxContract:
    def test_live_module_imports_without_jax(self):
        """live.py, metrics.py, and export.py must already be imported
        by this test run; the real no-jax subprocess contract is pinned
        in test_entry_points.py — here we pin the cheap invariant that
        none of them imported jax at module scope."""
        import tpu_mpi_tests.instrument.export  # noqa: F401
        import tpu_mpi_tests.instrument.metrics  # noqa: F401

        src = ""
        for mod in ("live", "metrics", "export"):
            p = os.path.join(os.path.dirname(live.__file__),
                             f"{mod}.py")
            src += open(p).read()
        import re

        assert not re.search(r"^import jax|^from jax", src,
                             re.MULTILINE)
