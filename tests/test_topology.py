"""Topology observability (ISSUE 20): host/link-class discovery from
fabricated device lists, wrapper-build-time mesh stamps, the per-link
anatomy split and its report/trace/live/doctor surfaces, topology-keyed
fingerprints, and the pack import shape gate — plus the flat-topology
degrade every surface keys its legacy shape on (fields absent, never
guessed; single-host/CPU reports grow no lines).

Fixtures follow tests/test_anatomy.py: fabricated per-rank JSONL with
KNOWN clock offsets so the per-link decompositions check as exact
arithmetic (rank 1 runs +0.5 s raw and enters 0.2 s late — each call
splits wait=0.2 wire=0.1 exactly).
"""

import json

import numpy as np
import pytest

from tpu_mpi_tests.comm import topology
from tpu_mpi_tests.instrument import aggregate, anatomy, diagnose, timeline
from tpu_mpi_tests.instrument.live import Dashboard, render


class _Dev:
    """A fabricated device: just the identity attributes discovery
    reads (absent slice_index == backend does not report one)."""

    def __init__(self, process_index=None, slice_index=None):
        if process_index is not None:
            self.process_index = process_index
        if slice_index is not None:
            self.slice_index = slice_index


class _Mesh:
    """Mesh stand-in for the stamp helpers: ``devices`` ndarray +
    ``axis_names``, hashable by identity like the real Mesh."""

    def __init__(self, shape, axis_names, devs):
        self.axis_names = axis_names
        self.devices = np.empty(shape, dtype=object)
        self.devices.ravel()[:] = devs


def _hosts(*pids, slices=None):
    if slices is None:
        return [_Dev(p) for p in pids]
    return [_Dev(p, s) for p, s in zip(pids, slices)]


def _write_jsonl(path, records):
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def _manifest(rank, n=2, **extra):
    return {"kind": "manifest", "process_index": rank,
            "process_count": n, "platform": "cpu",
            "global_device_count": n, "device_kinds": ["cpu"],
            "jax": "0.0-test", "argv": ["topo-test"], **extra}


def _topo(world=2, hosts=2, rph=1):
    return {"kind": "topo", "world": world, "topology": f"h{hosts}x{rph}",
            "declared": "discovered", "hosts": hosts,
            "ranks_per_host": rph,
            "host_by_rank": [r // rph for r in range(world)],
            "link_classes": (["intra_host", "inter_host"] if rph > 1
                             else ["inter_host"])}


def _sync(rank, offset, spread=0.0005):
    return {"kind": "clock_sync", "rank": rank, "offset_s": offset,
            "spread_s": spread, "method": "barrier_echo",
            "run_sync_us": 1}


def _span(op, seq, t0, t1, *, axis="x", world=2, nbytes=1 << 20,
          **extra):
    return {"kind": "span", "op": op, "axis": axis, "seq": seq,
            "world": world, "nbytes": nbytes, "seconds": t1 - t0,
            "t_start": t0, "t_end": t1, **extra}


def _stamped_run(tmp_path, calls=4, link="inter_host", with_topo=True):
    """The test_anatomy skew fixture on a fabricated 2-host shape:
    every span link-stamped; per call r0 waits 0.2 wire 0.1."""
    shape = {"hosts": 2, "ranks_per_host": 1} if with_topo else {}
    r0 = [_manifest(0, **shape), _sync(0, 0.0)]
    r1 = [_manifest(1, **shape), _sync(1, 0.5)]
    if with_topo:
        r0.insert(1, _topo())
        r1.insert(1, _topo())
    extra = {"link": link} if link else {}
    for k in range(calls):
        r0.append(_span("allreduce", k, 100.0 + k, 100.3 + k, **extra))
        r1.append(_span("allreduce", k, 100.7 + k, 100.8 + k, **extra))
    _write_jsonl(tmp_path / "run.p0.jsonl", r0)
    _write_jsonl(tmp_path / "run.p1.jsonl", r1)
    return [str(tmp_path / "run.p0.jsonl"),
            str(tmp_path / "run.p1.jsonl")]


# -------------------------------------------------------------- discovery


class TestDiscovery:
    def test_two_host_shape(self):
        t = topology.discover(_hosts(0, 0, 1, 1))
        assert t.declared == "discovered" and not t.is_flat
        assert (t.world, t.num_hosts, t.ranks_per_host) == (4, 2, 2)
        assert t.label() == "h2x2"
        assert t.classes() == ("intra_host", "inter_host")
        assert t.link_class(0, 0) == "self"
        assert t.link_class(0, 1) == "intra_host"
        assert t.link_class(0, 2) == "inter_host"

    def test_slice_axis_classifies_strongest(self):
        t = topology.discover(_hosts(0, 1, 2, 3, slices=[0, 0, 1, 1]))
        assert t.label() == "s2h4x1"
        assert t.link_class(0, 1) == "inter_host"
        assert t.link_class(0, 2) == "inter_slice"
        assert t.classes() == ("inter_host", "inter_slice")

    def test_missing_process_index_declares_flat(self):
        t = topology.discover([_Dev(0), _Dev()])
        assert t.declared == "flat" and t.is_flat
        assert t.hosts is None and t.slices is None
        assert t.label() == "flat"

    def test_bool_process_index_is_not_an_index(self):
        # a truthy-but-wrong attribute must degrade, not classify
        assert topology.discover([_Dev(True), _Dev(True)]).declared \
            == "flat"

    def test_partial_slice_index_contributes_nothing(self):
        t = topology.discover([_Dev(0, 0), _Dev(1)])
        assert t.declared == "discovered"
        assert t.slices is None and t.hosts == (0, 1)

    def test_ragged_hosts_have_no_rph(self):
        t = topology.discover(_hosts(0, 0, 1))
        assert t.ranks_per_host is None
        assert t.label() == "h2"

    def test_single_host_is_flat(self):
        t = topology.discover(_hosts(0, 0))
        assert t.is_flat and t.label() == "flat"

    def test_strength_order_and_anatomy_lockstep(self):
        assert topology.stronger("intra_host", "inter_host") \
            == "inter_host"
        assert topology.stronger("inter_slice", "self") == "inter_slice"
        # anatomy is stdlib-only and duplicates the vocabulary — the
        # two tuples must never drift
        assert anatomy.LINK_ORDER == topology.LINK_CLASSES

    def test_topo_record_fields_absent_when_undiscovered(self):
        rec = topology.topo_record(topology.discover(_hosts(0, 0, 1, 1)))
        assert rec["kind"] == "topo" and rec["topology"] == "h2x2"
        assert rec["hosts"] == 2 and rec["ranks_per_host"] == 2
        assert rec["host_by_rank"] == [0, 0, 1, 1]
        assert rec["link_classes"] == ["intra_host", "inter_host"]
        flat = topology.topo_record(topology.discover([_Dev(), _Dev()]))
        assert flat["declared"] == "flat"
        for k in ("hosts", "ranks_per_host", "host_by_rank", "slices",
                  "link_classes"):
            assert k not in flat


# ------------------------------------------------------------ mesh stamps


class TestMeshStamps:
    def test_two_level_mesh_axes_classify(self):
        # 2 hosts x 2 local devices: the dcn axis crosses hosts, the
        # ici axis stays inside one — the observability win
        devs = [_Dev(h) for h in (0, 0, 1, 1)]
        mesh = _Mesh((2, 2), ("dcn", "ici"),
                     [devs[0], devs[1], devs[2], devs[3]])
        assert topology.mesh_link_meta(mesh, "ici") \
            == {"link": "intra_host"}
        assert topology.mesh_link_meta(mesh, "dcn") \
            == {"link": "inter_host"}

    def test_flat_mesh_stamps_nothing(self):
        mesh = _Mesh((4,), ("x",), _hosts(0, 0, 0, 0))
        assert topology.mesh_link_meta(mesh, "x") == {}
        assert topology.mesh_partner_links(mesh, "x", (-1, 1), False) \
            == {}

    def test_partner_links_strongest_per_offset(self):
        mesh = _Mesh((4,), ("x",), _hosts(0, 0, 1, 1))
        got = topology.mesh_partner_links(mesh, "x", (-1, 1), False)
        # offset ±1 each cross the host seam somewhere on the ring —
        # the honest scalar for an aggregated-edges span is strongest
        assert got == {"partner_link": ["inter_host", "inter_host"],
                       "link": "inter_host"}


# --------------------------------------------------------- anatomy split


class TestAnatomyByLink:
    def test_by_link_split_exact(self, tmp_path):
        files = _stamped_run(tmp_path)
        row = anatomy.anatomize(
            timeline.rank_streams(files))["ops"]["allreduce"]
        sub = row["by_link"]["inter_host"]
        # every call stamped inter_host: the split IS the op row
        assert sub["calls"] == 4
        assert sub["wait_s"] == pytest.approx(row["wait_s"])
        assert sub["wire_s"] == pytest.approx(row["wire_s"])
        assert sub["bytes"] == row["bytes"]
        assert sub["wait_frac"] == pytest.approx(0.5)
        assert sub["pure_gbps"] == pytest.approx(row["pure_gbps"])
        assert sub["eff_gbps"] == pytest.approx(row["eff_gbps"])

    def test_mixed_classes_split_per_seq(self, tmp_path):
        r0 = [_manifest(0), _sync(0, 0.0)]
        r1 = [_manifest(1), _sync(1, 0.5)]
        for k in range(4):
            cls = "intra_host" if k < 2 else "inter_host"
            r0.append(_span("allreduce", k, 100.0 + k, 100.3 + k,
                            link=cls))
            r1.append(_span("allreduce", k, 100.7 + k, 100.8 + k,
                            link=cls))
        _write_jsonl(tmp_path / "run.p0.jsonl", r0)
        _write_jsonl(tmp_path / "run.p1.jsonl", r1)
        anat = anatomy.anatomize(timeline.rank_streams(
            [str(tmp_path / "run.p0.jsonl"),
             str(tmp_path / "run.p1.jsonl")]))
        by_link = anat["ops"]["allreduce"]["by_link"]
        assert by_link["intra_host"]["calls"] == 2
        assert by_link["inter_host"]["calls"] == 2
        assert by_link["intra_host"]["wait_s"] == pytest.approx(0.4)
        # top-level per-class aggregate feeds the TOPOLOGY table
        assert anat["by_link"]["inter_host"]["calls"] == 2
        assert anat["by_link"]["inter_host"]["wait_frac"] \
            == pytest.approx(0.5)

    def test_unstamped_spans_keep_legacy_row_shape(self, tmp_path):
        files = _stamped_run(tmp_path, link=None, with_topo=False)
        # link=None serializes as null → treated as unstamped
        anat = anatomy.anatomize(timeline.rank_streams(files))
        assert "by_link" not in anat["ops"]["allreduce"]
        assert "by_link" not in anat

    def test_edge_link_classes_mirror_partner_drop_rule(self, tmp_path):
        for rank in (0, 1):
            _write_jsonl(tmp_path / f"run.p{rank}.jsonl", [
                _manifest(rank), _sync(rank, 0.0),
                _span("halo_exchange", 0, 100.0, 100.1,
                      partners=[-1, 1], periodic=False,
                      partner_nbytes=256,
                      partner_link=["intra_host", "inter_host"],
                      link="inter_host"),
            ])
        streams = timeline.rank_streams(
            [str(tmp_path / f"run.p{r}.jsonl") for r in (0, 1)])
        # rank 0 keeps only +1 (its class), rank 1 only -1 — the
        # out-of-range offsets drop exactly as partner_edges drops them
        assert anatomy.edge_link_classes(streams) \
            == {(0, 1): "inter_host", (1, 0): "intra_host"}
        m = anatomy.anatomize(streams)["matrix"]
        assert m["0->1"]["link"] == "inter_host"
        assert m["1->0"]["link"] == "intra_host"


# --------------------------------------------------------- report surface


class TestReportSurface:
    def test_topology_tables_and_header(self, tmp_path, capsys):
        files = _stamped_run(tmp_path)
        assert aggregate.main(files) == 0
        out = capsys.readouterr().out
        run_line = next(ln for ln in out.splitlines()
                        if ln.startswith("RUN "))
        assert "hosts=2x1" in run_line
        assert "TOPOLOGY h2x1: world=2 hosts=2x1 links=inter_host" in out
        link_row = next(ln for ln in out.splitlines()
                        if ln.startswith("TOPOLOGY inter_host:"))
        assert "calls=4" in link_row and "wait_frac=0.500" in link_row
        split = next(ln for ln in out.splitlines()
                     if ln.startswith("ANATOMY allreduce[inter_host]:"))
        assert "calls=4" in split and "wait_frac=0.500" in split

    def test_json_summary_carries_topo(self, tmp_path, capsys):
        files = _stamped_run(tmp_path)
        assert aggregate.main(files + ["--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["topo"]["topology"] == "h2x1"
        assert s["anatomy"]["by_link"]["inter_host"]["calls"] == 4

    def test_flat_run_report_grows_no_lines(self, tmp_path, capsys):
        """The acceptance byte-shape gate: unstamped files produce a
        report with no TOPOLOGY lines, no [link] rows, no header
        suffix, no summary key."""
        files = _stamped_run(tmp_path, link=None, with_topo=False)
        assert aggregate.main(files) == 0
        out = capsys.readouterr().out
        assert "TOPOLOGY" not in out
        assert "allreduce[" not in out
        assert "hosts=" not in next(ln for ln in out.splitlines()
                                    if ln.startswith("RUN "))
        assert aggregate.main(files + ["--json"]) == 0
        assert "topo" not in json.loads(capsys.readouterr().out)

    def test_diff_series_per_link_class(self, tmp_path):
        files = _stamped_run(tmp_path)
        m = aggregate._metrics_from_summary(aggregate.summarize(files))
        key = "anatomy:allreduce:inter_host:pure_gbps"
        assert m[key]["higher_better"] is True
        assert m[key]["value"] == pytest.approx(
            4 * 2 * (1 << 20) / 0.8 / 1e9)


# ---------------------------------------------------------- trace surface


class TestTraceSurface:
    def _halo_files(self, tmp_path, stamped=True):
        extra = ({"partner_link": ["inter_host", "inter_host"],
                  "link": "inter_host"} if stamped else {})
        for rank in (0, 1):
            _write_jsonl(tmp_path / f"run.p{rank}.jsonl", [
                _manifest(rank), _sync(rank, 0.0),
                _span("halo_exchange", 0, 100.0, 100.1,
                      partners=[-1, 1], periodic=False,
                      partner_nbytes=256, **extra),
                _span("halo_exchange", 1, 101.0, 101.1,
                      partners=[-1, 1], periodic=False,
                      partner_nbytes=256, **extra),
            ])
        return [str(tmp_path / f"run.p{r}.jsonl") for r in (0, 1)]

    def test_link_counter_track_cumulative(self, tmp_path):
        doc = timeline.chrome_trace(self._halo_files(tmp_path))
        cnt = [e for e in doc["traceEvents"]
               if e.get("ph") == "C"
               and e["name"] == "comm bytes by link"]
        assert cnt and all(e["cat"] == "traffic" for e in cnt)
        last = max((e for e in cnt if e["pid"] == cnt[0]["pid"]),
                   key=lambda e: e["ts"])
        # each rank keeps ONE in-range edge per call (non-periodic
        # pair): 2 calls x 256 B, all inter_host
        assert last["args"] == {"inter_host": 512}
        # span args carry the link class for hover inspection
        spans = [e for e in doc["traceEvents"]
                 if e.get("ph") == "X" and e["name"] == "halo_exchange"]
        assert spans and all(
            e["args"].get("link") == "inter_host" for e in spans)

    def test_unstamped_trace_has_no_link_track(self, tmp_path):
        doc = timeline.chrome_trace(
            self._halo_files(tmp_path, stamped=False))
        names = {e.get("name") for e in doc["traceEvents"]}
        assert "comm bytes sent" in names
        assert "comm bytes by link" not in names


# ----------------------------------------------------- live/top surface


class TestLiveSurface:
    def test_link_table_renders(self, tmp_path):
        files = _stamped_run(tmp_path)
        dash = Dashboard()
        for path in files:
            for ln in open(path):
                dash.feed(json.loads(ln), path)
        frame = render(dash, files)
        hdr = next(ln for ln in frame.splitlines()
                   if ln.startswith("LINK"))
        assert "class" in hdr and "GB/s" in hdr
        assert any("inter_host" in ln for ln in frame.splitlines()
                   if not ln.startswith("TOPO"))

    def test_flat_feed_has_no_link_table(self, tmp_path):
        files = _stamped_run(tmp_path, link=None, with_topo=False)
        dash = Dashboard()
        for path in files:
            for ln in open(path):
                dash.feed(json.loads(ln), path)
        assert not any(ln.startswith("LINK")
                       for ln in render(dash, files).splitlines())


# ---------------------------------------------------------- doctor link


class TestDoctorLinkEvidence:
    def _streams(self, tmp_path, link="inter_host", mixed=False):
        r0 = [_manifest(0), _sync(0, 0.0, 0.001)]
        r1 = [_manifest(1), _sync(1, 0.0, 0.001)]
        for k in range(6):
            cls = ("intra_host" if mixed and k % 2 else link)
            extra = {"link": cls} if cls else {}
            r0.append(_span("halo_exchange", k, 100.0 + k, 100.5 + k,
                            **extra))
            r1.append(_span("halo_exchange", k, 100.49 + k, 100.5 + k,
                            **extra))
        for recs, rank in ((r0, 0), (r1, 1)):
            recs += [{"kind": "mem", "event": "final", "t": 120.0,
                      "live_bytes": 100},
                     {"kind": "telemetry_summary", "op": "x",
                      "rank": rank, "ops": 1, "bytes": 1,
                      "seconds": 0.0}]
        _write_jsonl(tmp_path / "run.p0.jsonl", r0)
        _write_jsonl(tmp_path / "run.p1.jsonl", r1)
        return [str(tmp_path / "run.p0.jsonl"),
                str(tmp_path / "run.p1.jsonl")]

    def test_all_inter_host_ops_note_link(self, tmp_path):
        (f,) = diagnose.diagnose_files(self._streams(tmp_path))
        assert f["class"] == "straggler" and f["link"] == "inter_host"
        assert "link=inter_host" in diagnose.format_finding(f)

    def test_mixed_classes_claim_nothing(self, tmp_path):
        (f,) = diagnose.diagnose_files(
            self._streams(tmp_path, mixed=True))
        assert f["link"] is None

    def test_unstamped_streams_claim_nothing(self, tmp_path):
        (f,) = diagnose.diagnose_files(self._streams(tmp_path, link=None))
        assert f["link"] is None
        assert "link=" not in diagnose.format_finding(f)


# ------------------------------------------------- fingerprint and packs


class TestFingerprintTopology:
    @pytest.fixture(autouse=True)
    def _fresh_fields(self):
        from tpu_mpi_tests.tune import fingerprint as fp

        fp.device_fields.cache_clear()
        yield
        fp.device_fields.cache_clear()

    def test_non_flat_fields_and_flat_unchanged(self, monkeypatch):
        from tpu_mpi_tests.tune import fingerprint as fp

        monkeypatch.setattr(
            topology, "current",
            lambda: topology.discover(_hosts(0, 0, 1, 1)))
        fields = dict(fp.device_fields())
        assert fields["hosts"] == "2" and fields["rph"] == "2"
        fp.device_fields.cache_clear()
        monkeypatch.setattr(
            topology, "current",
            lambda: topology.discover(_hosts(0, 0, 0, 0)))
        flat = dict(fp.device_fields())
        # PR-4 precedence contract: flat fingerprints are unchanged
        assert "hosts" not in flat and "rph" not in flat
        assert set(flat) == {"platform", "device", "ndev", "procs"}


class TestPackTopologyGate:
    def _pack(self, tmp_path, name, fp_extra=""):
        from tpu_mpi_tests.tune import pack as tp

        fp = "device=v5e;platform=tpu" + fp_extra
        doc = tp.make_pack({f"demo/k|{fp}": {
            "value": 7, "seconds": 0.1, "knob": "demo/k",
            "fingerprint": fp, "t": 100.0}})
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p), doc

    def test_fp_topology_labels(self):
        from tpu_mpi_tests.tune import pack as tp

        assert tp._fp_topology({"hosts": "2", "rph": "4"}) == "h2x4"
        assert tp._fp_topology({"hosts": "2"}) == "h2"
        assert tp._fp_topology({}) == "flat"

    def test_provenance_records_topologies(self, tmp_path):
        _, doc = self._pack(tmp_path, "p.json", ";hosts=2;rph=4")
        assert doc["provenance"]["topologies"] == ["h2x4"]

    def test_import_refuses_disjoint_shapes(self, tmp_path, capsys):
        from tpu_mpi_tests.tune import pack as tp
        from tpu_mpi_tests.tune.cache import ScheduleCache

        packed, _ = self._pack(tmp_path, "p.json", ";hosts=2;rph=4")
        dest = tmp_path / "cache.json"
        c = ScheduleCache.load(str(dest))
        c.store("demo/k", "device=v5e;platform=tpu", 1, seconds=0.1)
        c.save()
        assert tp.main(["import", packed, "--cache", str(dest)]) == 3
        out = capsys.readouterr().out
        assert "NOTE topology mismatch" in out
        assert "h2x4" in out and "flat" in out
        # override flag and same-shape/fresh-cache imports go through
        assert tp.main(["import", packed, "--cache", str(dest),
                        "--allow-topology-mismatch"]) == 0
        fresh = tmp_path / "fresh.json"
        assert tp.main(["import", packed, "--cache", str(fresh)]) == 0

    def test_pack_line_names_topology(self, tmp_path, capsys):
        from tpu_mpi_tests.tune import pack as tp
        from tpu_mpi_tests.tune.cache import ScheduleCache

        c = ScheduleCache.load(str(tmp_path / "w.json"))
        c.store("demo/k", "device=v5e;hosts=2;platform=tpu;rph=4", 1,
                seconds=0.1)
        c.save()
        assert tp.main(["pack", "--cache", str(tmp_path / "w.json"),
                        "-o", str(tmp_path / "o.json")]) == 0
        assert "topo=h2x4" in capsys.readouterr().out

    def test_driver_pack_note_on_mismatch(self, tmp_path, capsys,
                                          monkeypatch):
        import argparse

        from tpu_mpi_tests.drivers import _common

        packed, _ = self._pack(tmp_path, "p.json", ";hosts=2;rph=2")
        monkeypatch.setattr(
            topology, "current",
            lambda: topology.discover(_hosts(0, 0)))
        _common._check_pack_topology(
            argparse.Namespace(tune_pack=packed))
        assert "will not resolve here" in capsys.readouterr().err
        # same-shape pack (live h2x2) says nothing
        monkeypatch.setattr(
            topology, "current",
            lambda: topology.discover(_hosts(0, 0, 1, 1)))
        _common._check_pack_topology(
            argparse.Namespace(tune_pack=packed))
        assert "will not resolve" not in capsys.readouterr().err
