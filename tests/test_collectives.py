import jax.numpy as jnp
import numpy as np
import pytest

from tpu_mpi_tests.comm import collectives as C


class TestAllGather:
    def test_gather_replicates_global(self, mesh8):
        x = jnp.arange(64.0)
        xs = C.shard_1d(x, mesh8)
        g = C.all_gather(xs, mesh8)
        assert g.shape == (64,)
        np.testing.assert_array_equal(np.asarray(g), np.arange(64.0))
        # replicated: every device holds the full array
        assert all(
            s.data.shape == (64,) for s in g.addressable_shards
        )

    def test_gather_2d_axis(self, mesh8):
        z = jnp.arange(64.0).reshape(8, 8)
        zs = C.shard_1d(z, mesh8, axis=1)
        g = C.all_gather(zs, mesh8, axis=1)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(z))

    def test_inplace_parity_checksums(self, mesh8):
        # ≅ mpigatherinplace.f90:31-48: fill own slice, in-place allgather,
        # global sum must equal the sum of per-rank local sums exactly.
        n_per_rank = 1024
        world = 8
        rng = np.random.default_rng(42)
        # integers → exact float sums
        allx = rng.integers(0, 100, world * n_per_rank).astype(np.float64)
        local_sums = [
            allx[r * n_per_rank : (r + 1) * n_per_rank].sum()
            for r in range(world)
        ]
        xs = C.shard_1d(jnp.asarray(allx), mesh8)
        g = C.all_gather_inplace(xs, mesh8)
        asum = float(np.asarray(g).sum())
        assert asum == sum(local_sums)
        np.testing.assert_array_equal(np.asarray(g), allx)


class TestAllreduce:
    def test_every_rank_gets_elementwise_sum(self, mesh8):
        per_rank = jnp.asarray(
            np.arange(8 * 16, dtype=np.float64).reshape(8, 16)
        )
        ps = C.shard_1d(per_rank, mesh8)
        out = C.allreduce_sum(ps, mesh8)
        expected = np.asarray(per_rank).sum(axis=0)
        for row in np.asarray(out):
            np.testing.assert_array_equal(row, expected)

    def test_wrong_leading_axis_raises(self, mesh8):
        bad = C.shard_1d(jnp.zeros((16, 4)), mesh8)
        with pytest.raises(ValueError, match="must equal"):
            C.allreduce_sum(bad, mesh8)

    def test_matches_global_axis_sum(self, mesh8):
        # the idiomatic path: jnp.sum over a sharded axis == allreduce of
        # per-shard partials (XLA inserts the psum) — both must agree
        z = jnp.asarray(np.random.default_rng(0).standard_normal((8, 32)))
        zs = C.shard_1d(z, mesh8)
        np.testing.assert_allclose(
            np.asarray(jnp.sum(zs, axis=0)),
            np.asarray(z).sum(axis=0),
            rtol=1e-12,
        )


def test_reduce_sum_scalars():
    vals = [0.5 * r for r in range(8)]
    assert C.reduce_sum(vals) == sum(vals)


def test_per_rank_sums(mesh8):
    per_rank = np.arange(8 * 4, dtype=np.float64).reshape(8, 4)
    xs = C.shard_1d(jnp.asarray(per_rank), mesh8)
    sums = C.per_rank_sums(xs, mesh8).reshape(-1)
    np.testing.assert_array_equal(sums, per_rank.sum(axis=1))


def test_host_value_replicated_and_sharded(mesh8):
    x = jnp.arange(16.0)
    np.testing.assert_array_equal(C.host_value(C.replicate(x, mesh8)), x)
    np.testing.assert_array_equal(C.host_value(C.shard_1d(x, mesh8)), x)
    np.testing.assert_array_equal(C.host_value(np.arange(3)), np.arange(3))


def test_barrier_completes(mesh8):
    C.barrier(mesh8)  # must simply not hang or raise


def test_replicate(mesh8):
    x = C.replicate(jnp.arange(10.0), mesh8)
    assert all(s.data.shape == (10,) for s in x.addressable_shards)


class TestOneshotTier:
    """ISSUE 19 fixed-cost tier: ONE in-kernel all-to-all DMA burst per
    collective (``kernels/collectives_pallas.py``). Honesty gates are
    BITWISE: the gather must replicate the sharded input exactly, and
    the reduce's fold order is fixed (ascending source rank), so every
    rank must equal ``reduce(np.add, rows)`` bit for bit."""

    def test_gather_bitwise_and_replicated(self, mesh8):
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal(4096).astype(np.float32))
        g = C.all_gather_oneshot(C.shard_1d(x, mesh8), mesh8)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(x))
        # replicated: every device holds the full array
        assert all(
            s.data.shape == (4096,) for s in g.addressable_shards
        )

    def test_gather_decode_payload_below_ring_floor(self, mesh8):
        # 8 f32 per shard (32 B): far below the ring tier's lane floor —
        # the pad-to-tile wrapper is what admits the decode regime
        x = jnp.arange(64.0, dtype=jnp.float32)
        g = C.all_gather_oneshot(C.shard_1d(x, mesh8), mesh8)
        np.testing.assert_array_equal(
            np.asarray(g), np.arange(64.0, dtype=np.float32)
        )

    def test_gather_2d_rows(self, mesh8):
        z = jnp.asarray(
            np.random.default_rng(5)
            .standard_normal((64, 3))
            .astype(np.float32)
        )
        g = C.all_gather_oneshot(C.shard_1d(z, mesh8), mesh8)
        np.testing.assert_array_equal(np.asarray(g), np.asarray(z))

    def test_reduce_bitwise_matches_fixed_fold(self, mesh8):
        import functools as ft

        rng = np.random.default_rng(7)
        per_rank = rng.standard_normal((8, 1024)).astype(np.float32)
        out = C.allreduce_oneshot(
            C.shard_1d(jnp.asarray(per_rank), mesh8), mesh8
        )
        # the pinned sum order: ascending source rank, rank-independent
        want = ft.reduce(np.add, [per_rank[r] for r in range(8)])
        got = np.asarray(out)
        assert got.shape == (8, 1024)
        for row in got:
            np.testing.assert_array_equal(row, want)

    def test_reduce_decode_payload(self, mesh8):
        # the tier's target regime: a (8, 4) f32 payload — 16 B rows
        import functools as ft

        per_rank = (np.arange(32, dtype=np.float32).reshape(8, 4)
                    % 13) - 5
        out = C.allreduce_oneshot(
            C.shard_1d(jnp.asarray(per_rank), mesh8), mesh8
        )
        want = ft.reduce(np.add, [per_rank[r] for r in range(8)])
        for row in np.asarray(out):
            np.testing.assert_array_equal(row, want)

    def test_reduce_wrong_shape_raises(self, mesh8):
        with pytest.raises(ValueError, match="n_ranks=8"):
            C.allreduce_oneshot(jnp.ones((4, 64), jnp.float32), mesh8)


class TestReduceScatter:
    def test_rank_r_gets_chunk_r_of_sum(self, mesh8):
        per_rank = (np.arange(8 * 64, dtype=np.float32).reshape(8, 64)
                    % 19) - 9
        xs = C.shard_1d(jnp.asarray(per_rank), mesh8)
        got = np.asarray(C.reduce_scatter_sum(xs, mesh8))
        assert got.shape == (8, 8)
        want = per_rank.sum(axis=0).reshape(8, 8)
        np.testing.assert_array_equal(got, want)

    def test_matches_hand_ring_tier(self, mesh8):
        """lax.psum_scatter and the RDMA ring reduce-scatter must agree on
        chunk ownership (rank r owns chunk r) and values."""
        import functools

        import jax
        from tpu_mpi_tests.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from tpu_mpi_tests.kernels import pallas_kernels as PK

        L = 8 * 1024  # ring 1-D floor on 8 devices f32
        per_rank = (np.arange(8 * L, dtype=np.float32).reshape(8, L)
                    % 23) - 11
        xs = C.shard_1d(jnp.asarray(per_rank), mesh8)
        want = np.asarray(C.reduce_scatter_sum(xs, mesh8))

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh8, in_specs=P("shard"),
            out_specs=P("shard"), check_vma=False,
        )
        def ring(x):
            return PK.ring_reduce_scatter_pallas(
                x[0], axis_name="shard", interpret=True
            )[None]

        got = np.asarray(ring(C.shard_1d(jnp.asarray(per_rank), mesh8)))
        np.testing.assert_array_equal(got, want)

    def test_indivisible_raises(self, mesh8):
        with pytest.raises(Exception, match="reduce_scatter_sum chunking"):
            C.reduce_scatter_sum(
                C.shard_1d(jnp.ones((8, 12), jnp.float32), mesh8), mesh8
            )

    def test_wrong_leading_axis_raises(self, mesh8):
        with pytest.raises(ValueError, match="n_ranks=8"):
            C.reduce_scatter_sum(jnp.ones((4, 64), jnp.float32), mesh8)
