"""tpumt-doctor (instrument/diagnose.py): cross-rank root-cause rules
over synthesized per-rank streams, the --expect CI contract, and the
DIAGNOSIS/NOTE/marker surfacing in tpumt-report / tpumt-trace."""

import json

import pytest

from tpu_mpi_tests.instrument import aggregate, diagnose, timeline


def _write_jsonl(path, records):
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def _manifest(rank, n=2, **extra):
    return {"kind": "manifest", "process_index": rank,
            "process_count": n, "platform": "cpu",
            "global_device_count": n, "device_kinds": ["cpu"],
            "jax": "0.0-test", "argv": ["chaos-test"], **extra}


def _span(rank, op, t, seconds=0.01, world=2):
    return {"kind": "span", "op": op, "nbytes": 1 << 20, "world": world,
            "seconds": seconds, "t_start": t, "t_end": t + seconds,
            "rank": rank}


def _mem(t, live, event="sample", **extra):
    return {"kind": "mem", "event": event, "t": t, "live_bytes": live,
            **extra}


def _summary_marker(rank):
    return {"kind": "telemetry_summary", "op": "x", "rank": rank,
            "ops": 1, "bytes": 1, "seconds": 0.0}


def _healthy_stream(rank, t0=100.0, n_spans=8):
    recs = [_manifest(rank)]
    recs += [_span(rank, "allreduce", t0 + i) for i in range(n_spans)]
    recs += [_mem(t0 + n_spans, 1000, event="final"),
             _summary_marker(rank)]
    return recs


@pytest.fixture()
def clean_run(tmp_path):
    _write_jsonl(tmp_path / "run.p0.jsonl", _healthy_stream(0))
    _write_jsonl(tmp_path / "run.p1.jsonl", _healthy_stream(1))
    return tmp_path


def _files(tmp_path):
    return sorted(str(p) for p in tmp_path.glob("run.p*.jsonl"))


class TestRules:
    def test_clean_run_zero_findings(self, clean_run):
        assert diagnose.diagnose_files(_files(clean_run)) == []

    def test_missing_rank_convicted_when_siblings_progress(
        self, tmp_path
    ):
        # rank 1 stops at t=103 (no close markers); rank 0 records on
        # to t=110 and closes cleanly
        _write_jsonl(tmp_path / "run.p0.jsonl",
                     _healthy_stream(0, n_spans=10))
        recs = [_manifest(1)] + [
            _span(1, "allreduce", 100.0 + i) for i in range(3)
        ]
        _write_jsonl(tmp_path / "run.p1.jsonl", recs)
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "missing_rank" and f["rank"] == 1
        assert f["last_op"] == "allreduce"
        assert f["kind"] == "finding"

    def test_missing_rank_sibling_watchdog_raises_confidence(
        self, tmp_path
    ):
        surv = _healthy_stream(0, n_spans=10)
        surv.insert(-2, {"kind": "watchdog", "phase": "driver",
                         "deadline_s": 8.0, "t": 109.5, "rank": 0})
        _write_jsonl(tmp_path / "run.p0.jsonl", surv)
        _write_jsonl(tmp_path / "run.p1.jsonl", [_manifest(1)] + [
            _span(1, "allreduce", 100.0 + i) for i in range(3)
        ])
        (f,) = [x for x in diagnose.diagnose_files(_files(tmp_path))
                if x["class"] == "missing_rank"]
        assert f["confidence"] >= 0.95

    def test_missing_rank_file_absent_entirely(self, tmp_path):
        # the manifest claims 3 processes; only ranks 0 and 1 merged
        _write_jsonl(tmp_path / "run.p0.jsonl",
                     [_manifest(0, n=3)] + _healthy_stream(0)[1:])
        _write_jsonl(tmp_path / "run.p1.jsonl",
                     [_manifest(1, n=3)] + _healthy_stream(1)[1:])
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "missing_rank" and f["rank"] == 2
        assert "no rank file" in f["detail"]

    def test_lone_truncated_stream_not_convicted(self, tmp_path):
        """Without siblings (or wedge/oom evidence) a truncated stream
        is indistinguishable from a user interrupt — no verdict."""
        _write_jsonl(tmp_path / "run.p0.jsonl", [_manifest(0, n=1)] + [
            _span(0, "allreduce", 100.0 + i) for i in range(5)
        ])
        assert diagnose.diagnose_files(_files(tmp_path)) == []

    def test_wedge_convicted_from_dispatch_plus_watchdog(self, tmp_path):
        recs = [_manifest(0, n=1)]
        recs += [_span(0, "halo_exchange", 100.0 + i) for i in range(3)]
        recs += [
            {"kind": "dispatch", "note": "chaos:wedge halo_exchange",
             "op": "halo_exchange", "t": 103.5, "rank": 0},
            {"kind": "watchdog", "phase": "driver", "deadline_s": 6.0,
             "t": 109.5, "rank": 0},
        ]
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "wedge" and f["rank"] == 0
        assert f["last_op"] == "halo_exchange"
        assert len(f["evidence"]) == 2

    def test_wedge_not_convicted_when_spans_close_after_dispatch(
        self, tmp_path
    ):
        """A dispatch note followed by later span closes is a healthy
        RDMA path, not a wedge — even with a watchdog somewhere."""
        recs = [_manifest(0, n=1)]
        recs += [{"kind": "dispatch", "note": "rdma ring", "t": 100.0,
                  "rank": 0}]
        recs += [_span(0, "halo_exchange", 100.5 + i) for i in range(5)]
        recs += [{"kind": "watchdog", "phase": "driver",
                  "deadline_s": 6.0, "t": 110.0, "rank": 0},
                 _summary_marker(0)]
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)
        assert not [f for f in diagnose.diagnose_files(_files(tmp_path))
                    if f["class"] == "wedge"]

    def test_oom_census_ramp_convicted(self, tmp_path):
        recs = [_manifest(0, n=1)]
        for i in range(6):
            recs.append(_mem(100.0 + i, (1 + i) * 16 << 20))
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)  # no final marker
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "oom" and f["rank"] == 0
        assert f["confidence"] == pytest.approx(0.7)
        assert "census-only" in f["detail"]

    def test_oom_limit_crossing_raises_confidence(self, tmp_path):
        limit = 256 << 20
        recs = [_manifest(0, n=1, hbm_bytes_limit=limit)]
        for i in range(6):
            recs.append(_mem(100.0 + i, (1 + i) * 32 << 20,
                             bytes_in_use=(1 + i) * 32 << 20))
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "oom"
        assert f["confidence"] == pytest.approx(0.9)
        assert "hbm_bytes_limit" in f["detail"]

    def test_flat_memory_death_is_not_oom(self, tmp_path):
        """A killed rank with flat memory must convict as missing_rank,
        never oom — the ramp is the signature, not the mem records."""
        _write_jsonl(tmp_path / "run.p0.jsonl",
                     _healthy_stream(0, n_spans=10))
        recs = [_manifest(1)]
        for i in range(4):
            recs.append(_mem(100.0 + i, 16 << 20))
            recs.append(_span(1, "allreduce", 100.2 + i))
        _write_jsonl(tmp_path / "run.p1.jsonl", recs)
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "missing_rank" and f["rank"] == 1

    def test_straggler_phase_skew_convicts_slow_rank(self, tmp_path):
        def stream(rank, kernel_s):
            recs = [_manifest(rank)]
            recs.append({"kind": "time", "phase": "kernel",
                         "seconds": kernel_s, "count": 20,
                         "t_start": 100.0, "t_end": 100.0 + kernel_s,
                         "rank": rank})
            recs += [_mem(101.0, 100, event="final"),
                     _summary_marker(rank)]
            return recs

        _write_jsonl(tmp_path / "run.p0.jsonl", stream(0, 0.5))
        _write_jsonl(tmp_path / "run.p1.jsonl", stream(1, 2.0))
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "straggler" and f["rank"] == 1
        assert "phase kernel" in f["detail"]
        # anchored at the culprit's last convicting record so
        # tpumt-trace can place the FINDING marker on its track
        assert f["t"] == pytest.approx(102.0)

    def test_straggler_collective_inversion_convicts_fast_rank(
        self, tmp_path
    ):
        """Sync-honest collective spans charge the wait to the EARLY
        rank: the culprit is the one that never waits (min seconds)."""
        def stream(rank, span_s):
            recs = [_manifest(rank)]
            recs += [_span(rank, "halo_exchange", 100.0 + i,
                           seconds=span_s) for i in range(8)]
            recs += [_mem(120.0, 100, event="final"),
                     _summary_marker(rank)]
            return recs

        _write_jsonl(tmp_path / "run.p0.jsonl", stream(0, 0.2))
        _write_jsonl(tmp_path / "run.p1.jsonl", stream(1, 0.005))
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "straggler" and f["rank"] == 1
        assert "invert" in f["detail"]

    def test_straggler_below_threshold_or_count_not_convicted(
        self, tmp_path
    ):
        def stream(rank, kernel_s, count):
            return [_manifest(rank),
                    {"kind": "time", "phase": "kernel",
                     "seconds": kernel_s, "count": count,
                     "t_start": 100.0, "t_end": 101.0, "rank": rank},
                    _mem(101.0, 100, event="final"),
                    _summary_marker(rank)]

        # 1.8x skew: below the 2x conviction threshold
        _write_jsonl(tmp_path / "run.p0.jsonl", stream(0, 1.0, 20))
        _write_jsonl(tmp_path / "run.p1.jsonl", stream(1, 1.8, 20))
        assert diagnose.diagnose_files(_files(tmp_path)) == []
        # huge skew but only 2 calls each: below min_calls
        _write_jsonl(tmp_path / "run.p0.jsonl", stream(0, 0.1, 2))
        _write_jsonl(tmp_path / "run.p1.jsonl", stream(1, 3.0, 2))
        assert diagnose.diagnose_files(_files(tmp_path)) == []

    def test_shed_storm_convicted_from_serve_windows(self, tmp_path):
        recs = [_manifest(0, n=1)]
        for i in range(4):
            recs.append({
                "kind": "serve", "event": "window",
                "class": "daxpy:4096:float32", "t_start": 100.0 + i,
                "t_end": 101.0 + i, "arrivals": 100, "requests": 30,
                "shed": 60 + i * 10, "errors": 0,
                "queue_max": 32, "rank": 0,
            })
        recs += [_summary_marker(0), _mem(110.0, 1, event="final")]
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "shed_storm" and f["rank"] == 0
        assert f["last_op"] == "daxpy:4096:float32"

    def test_quarantined_class_sheds_are_not_a_storm(self, tmp_path):
        """Graceful degradation (serve --quarantine-after) sheds the
        quarantined class's load BY DESIGN — the doctor must not
        convict the exact runs the driver deliberately exits 0 for.
        An un-quarantined class shedding at the queue bound in the
        same stream still convicts."""
        recs = [_manifest(0, n=1), {
            "kind": "serve", "event": "quarantine", "class": "dead:c",
            "t": 100.5, "rank": 0,
        }]
        for i in range(4):
            recs.append({
                "kind": "serve", "event": "window", "class": "dead:c",
                "t_start": 100.0 + i, "t_end": 101.0 + i,
                "arrivals": 100, "requests": 0, "shed": 100,
                "errors": 0, "queue_max": 2, "rank": 0,
            })
        recs += [_summary_marker(0), _mem(110.0, 1, event="final")]
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)
        assert diagnose.diagnose_files(_files(tmp_path)) == []
        # the same windows WITHOUT the quarantine record are a storm
        _write_jsonl(tmp_path / "run.p0.jsonl",
                     [recs[0]] + recs[2:])
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "shed_storm"
        # the exemption is scoped, not retroactive: a quarantine that
        # lands AFTER the storm windows does not absolve them
        late = dict(recs[1], t=200.0)
        _write_jsonl(tmp_path / "run.p0.jsonl",
                     [recs[0]] + recs[2:-2] + [late] + recs[-2:])
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "shed_storm"

    def test_queue_ramp_convicted_before_any_shed(self, tmp_path):
        """The PR-16 early warning: a rising queue-delay share of the
        e2e p99 with a standing backlog convicts queue_ramp from the
        window decomposition alone — zero sheds anywhere."""
        recs = [_manifest(0, n=1)]
        for i, (qd, p99, depth) in enumerate(
                [(2.0, 10.0, 3), (6.0, 10.0, 12),
                 (8.0, 10.0, 30), (9.5, 10.0, 60)]):
            recs.append({
                "kind": "serve", "event": "window", "class": "c:1:f32",
                "t_start": 100.0 + i, "t_end": 101.0 + i,
                "arrivals": 100, "requests": 100, "shed": 0,
                "errors": 0, "queue_max": depth + 5,
                "queue_depth": depth, "p99_ms": p99, "qd_p99_ms": qd,
                "rank": 0,
            })
        recs += [_summary_marker(0), _mem(110.0, 1, event="final")]
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "queue_ramp" and f["rank"] == 0
        assert f["last_op"] == "c:1:f32"
        assert "backlog" in f["detail"]

    def test_queue_ramp_requires_share_depth_and_sustain(self, tmp_path):
        """No conviction when any leg of the rule is missing: a
        service-dominated tail (low qd share), a draining queue (depth
        under the floor), or a falling share (not sustained)."""
        def windows(rows):
            recs = [_manifest(0, n=1)]
            for i, (qd, p99, depth) in enumerate(rows):
                recs.append({
                    "kind": "serve", "event": "window",
                    "class": "c:1:f32", "t_start": 100.0 + i,
                    "t_end": 101.0 + i, "arrivals": 100,
                    "requests": 100, "shed": 0, "errors": 0,
                    "queue_max": 99, "queue_depth": depth,
                    "p99_ms": p99, "qd_p99_ms": qd, "rank": 0,
                })
            recs += [_summary_marker(0), _mem(110.0, 1, event="final")]
            _write_jsonl(tmp_path / "run.p0.jsonl", recs)
            return diagnose.diagnose_files(_files(tmp_path))

        # service-dominated: share never reaches the floor
        assert windows([(2.0, 10.0, 50)] * 4) == []
        # queue drains: final depth under the floor in every 3-run
        assert windows([(9.0, 10.0, 4)] * 4) == []
        # share collapsing, not sustained
        assert windows([(9.0, 10.0, 50), (5.0, 10.0, 50),
                        (2.0, 10.0, 50), (1.0, 10.0, 50)]) == []
        # fewer windows than the rule needs
        assert windows([(9.0, 10.0, 50)] * 2) == []

    def test_queue_ramp_convicts_a_drained_storm_post_mortem(
            self, tmp_path):
        """The scan is over EVERY consecutive window run, not just the
        stream tail: a flood that fully drained by run end (the serve
        loop always drains before summarizing) still convicts over the
        windows where it was ramping — so --follow's mid-run conviction
        and the post-mortem doctor agree on the same file."""
        ramp = [(5.0, 10.0, 40), (8.0, 10.0, 30), (9.5, 10.0, 20)]
        drained = [(1.0, 10.0, 0), (0.5, 10.0, 0)]
        recs = [_manifest(0, n=1)]
        for i, (qd, p99, depth) in enumerate(ramp + drained):
            recs.append({
                "kind": "serve", "event": "window", "class": "c:1:f32",
                "t_start": 100.0 + i, "t_end": 101.0 + i,
                "arrivals": 100, "requests": 100, "shed": 0,
                "errors": 0, "queue_max": 99, "queue_depth": depth,
                "p99_ms": p99, "qd_p99_ms": qd, "rank": 0,
            })
        recs += [_summary_marker(0), _mem(110.0, 1, event="final")]
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "queue_ramp"

    def test_queue_ramp_suppressed_by_shed_storm(self, tmp_path):
        """Once the queue bound is actually dropping load the storm is
        the verdict; the ramp (its own prelude) must not double-convict
        the rank."""
        recs = [_manifest(0, n=1)]
        for i in range(4):
            recs.append({
                "kind": "serve", "event": "window", "class": "c:1:f32",
                "t_start": 100.0 + i, "t_end": 101.0 + i,
                "arrivals": 100, "requests": 30, "shed": 60 + i * 10,
                "errors": 0, "queue_max": 32, "queue_depth": 32,
                "p99_ms": 10.0, "qd_p99_ms": 9.5, "rank": 0,
            })
        recs += [_summary_marker(0), _mem(110.0, 1, event="final")]
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)
        (f,) = diagnose.diagnose_files(_files(tmp_path))
        assert f["class"] == "shed_storm"

    def test_queue_ramp_ignores_pre_decomposition_streams(
            self, tmp_path):
        """Window records from builds before the qd/svc decomposition
        carry no qd_p99_ms: the rule must stay silent, never guess a
        share from partial fields."""
        recs = [_manifest(0, n=1)]
        for i in range(4):
            recs.append({
                "kind": "serve", "event": "window", "class": "c:1:f32",
                "t_start": 100.0 + i, "t_end": 101.0 + i,
                "arrivals": 100, "requests": 100, "shed": 0,
                "errors": 0, "queue_max": 99, "queue_depth": 50,
                "p99_ms": 10.0, "rank": 0,
            })
        recs += [_summary_marker(0), _mem(110.0, 1, event="final")]
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)
        assert diagnose.diagnose_files(_files(tmp_path)) == []

    def test_small_shed_not_a_storm(self, tmp_path):
        recs = [_manifest(0, n=1), {
            "kind": "serve", "event": "window", "class": "c",
            "t_start": 100.0, "t_end": 101.0, "arrivals": 1000,
            "requests": 995, "shed": 5, "errors": 0, "queue_max": 4,
            "rank": 0,
        }, _summary_marker(0)]
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)
        assert diagnose.diagnose_files(_files(tmp_path)) == []

    def test_chaos_audit_records_are_ignored(self, tmp_path):
        """The injection audit trail must not be usable as evidence:
        a stream whose ONLY anomaly is a chaos record diagnoses clean."""
        recs = _healthy_stream(0)
        recs.insert(3, {"kind": "chaos", "event": "fire",
                        "fault": "kill", "chaos_rank": 0,
                        "spec": "kill:op=x", "t": 100.5, "rank": 0})
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)
        _write_jsonl(tmp_path / "run.p1.jsonl", _healthy_stream(1))
        assert diagnose.diagnose_files(_files(tmp_path)) == []

    def test_pre_timeline_records_diagnose_as_nothing(self, tmp_path):
        """Old JSONL without timestamps must not fabricate deaths."""
        recs = [_manifest(0),
                {"kind": "span", "op": "all_gather", "seconds": 0.5,
                 "rank": 0}]
        _write_jsonl(tmp_path / "run.p0.jsonl", recs)
        _write_jsonl(tmp_path / "run.p1.jsonl", _healthy_stream(1))
        assert diagnose.diagnose_files(_files(tmp_path)) == []


class TestCli:
    def test_expect_contract(self, tmp_path, capsys):
        _write_jsonl(tmp_path / "run.p0.jsonl",
                     _healthy_stream(0, n_spans=10))
        _write_jsonl(tmp_path / "run.p1.jsonl", [_manifest(1)] + [
            _span(1, "allreduce", 100.0 + i) for i in range(3)
        ])
        base = str(tmp_path / "run.jsonl")
        assert diagnose.main([base, "--expect", "missing_rank:1"]) == 0
        assert "DOCTOR EXPECT OK" in capsys.readouterr().out
        assert diagnose.main([base, "--expect", "missing_rank:0"]) == 2
        assert diagnose.main([base, "--expect", "oom:1"]) == 2
        assert diagnose.main([base, "--expect", "nonsense:1"]) == 2
        capsys.readouterr()
        # --json keeps stdout a parseable document: the expect status
        # line moves to stderr
        assert diagnose.main(
            [base, "--json", "--expect", "missing_rank:1"]) == 0
        cap = capsys.readouterr()
        assert "DOCTOR EXPECT OK" in cap.err
        doc = json.loads(cap.out)
        assert doc["findings"][0]["class"] == "missing_rank"

    def test_clean_exit_zero_findings_exit_one(self, clean_run, capsys):
        base = str(clean_run / "run.jsonl")
        assert diagnose.main([base]) == 0
        assert "DOCTOR OK" in capsys.readouterr().out
        # now break rank 1
        _write_jsonl(clean_run / "run.p1.jsonl", [_manifest(1)] + [
            _span(1, "allreduce", 100.0 + i) for i in range(3)
        ])
        assert diagnose.main([base]) == 1
        out = capsys.readouterr().out
        assert out.startswith("FINDING missing_rank: rank=1")
        assert "evidence:" in out

    def test_json_output(self, tmp_path, capsys):
        _write_jsonl(tmp_path / "run.p0.jsonl",
                     _healthy_stream(0, n_spans=10))
        _write_jsonl(tmp_path / "run.p1.jsonl", [_manifest(1)] + [
            _span(1, "allreduce", 100.0 + i) for i in range(3)
        ])
        assert diagnose.main(
            [str(tmp_path / "run.jsonl"), "--json"]
        ) == 1
        doc = json.loads(capsys.readouterr().out)
        (f,) = doc["findings"]
        assert f["kind"] == "finding"
        assert f["class"] == "missing_rank" and f["rank"] == 1

    def test_missing_input_exits_two(self, tmp_path, capsys):
        assert diagnose.main([str(tmp_path / "nope.jsonl")]) == 2


class TestProtocolModel:
    """``--protocol-model``: the schedule automaton replayed from
    tpumt-lint's analysis cache upgrades missing_rank evidence with the
    statically-expected next collective — and is byte-for-byte inert
    without the flag or without a warm cache."""

    def _warm_cache(self, tmp_path):
        from tpu_mpi_tests.analysis.core import lint_paths

        pkg = tmp_path / "duo"
        pkg.mkdir()
        (pkg / "__init__.py").write_text('"""pair-schedule tree."""\n')
        (pkg / "pair.py").write_text(
            "from tpu_mpi_tests.comm.collectives import allreduce_sum\n"
            "from tpu_mpi_tests.comm.collectives import reduce_scatter\n"
            "from tpu_mpi_tests.instrument.telemetry import comm_span\n"
            "\n"
            "\n"
            "def pair(x, mesh):\n"
            '    with comm_span("allreduce", axis_name="ring"):\n'
            "        x = allreduce_sum(x, mesh)\n"
            '    with comm_span("reduce_scatter", axis_name="ring"):\n'
            "        x = reduce_scatter(x, mesh)\n"
            "    return x\n"
        )
        cache = str(tmp_path / "lintcache.json")
        lint_paths([str(pkg)], cache_path=cache)
        return cache

    def _dead_after_allreduce(self, tmp_path):
        # rank 0 completes the pair schedule and closes cleanly; rank 1
        # emits only the allreduce span (seq-stamped) and goes silent.
        surv = [_manifest(0),
                dict(_span(0, "allreduce", 100.0), axis="ring", seq=0),
                dict(_span(0, "reduce_scatter", 105.0), axis="ring",
                     seq=0),
                _mem(108.0, 900),
                _mem(110.0, 1000, event="final"),
                _summary_marker(0)]
        _write_jsonl(tmp_path / "run.p0.jsonl", surv)
        _write_jsonl(tmp_path / "run.p1.jsonl", [
            _manifest(1),
            dict(_span(1, "allreduce", 100.0), axis="ring", seq=0),
        ])
        return str(tmp_path / "run.jsonl")

    def test_protocol_model_cites_expected_next_collective(
            self, tmp_path, capsys):
        cache = self._warm_cache(tmp_path)
        base = self._dead_after_allreduce(tmp_path)
        assert diagnose.main([base]) == 1
        plain = capsys.readouterr().out
        assert "FINDING missing_rank: rank=1" in plain
        assert "protocol-model" not in plain

        assert diagnose.main([base, "--protocol-model", cache]) == 1
        out = capsys.readouterr().out
        assert "protocol-model: after 1 matched span(s)" in out
        assert "reduce_scatter" in out
        assert "tpumt-lint analysis cache" in out
        # strictly additive: dropping the one protocol-model line
        # restores the flagless output exactly
        kept = [ln for ln in out.splitlines()
                if "protocol-model" not in ln]
        assert kept == [ln for ln in plain.splitlines()
                        if "protocol-model" not in ln]

    def test_protocol_model_inert_on_cold_cache_or_preseq(
            self, tmp_path, capsys):
        cache = self._warm_cache(tmp_path)
        base = self._dead_after_allreduce(tmp_path)
        assert diagnose.main([base]) == 1
        plain = capsys.readouterr().out

        # absent cache file: flag present, nothing replayable
        assert diagnose.main(
            [base, "--protocol-model", str(tmp_path / "absent.json")]
        ) == 1
        assert capsys.readouterr().out == plain

        # pre-seq stream (no PR-17 stamps): model declines, never
        # convicts on guesswork
        _write_jsonl(tmp_path / "run.p1.jsonl", [
            _manifest(1),
            dict(_span(1, "allreduce", 100.0), axis="ring"),
        ])
        assert diagnose.main([base]) == 1
        plain2 = capsys.readouterr().out
        assert diagnose.main([base, "--protocol-model", cache]) == 1
        assert capsys.readouterr().out == plain2
        assert "protocol-model" not in plain2

    def test_protocol_model_json_evidence(self, tmp_path, capsys):
        cache = self._warm_cache(tmp_path)
        base = self._dead_after_allreduce(tmp_path)
        assert diagnose.main(
            [base, "--json", "--protocol-model", cache]) == 1
        doc = json.loads(capsys.readouterr().out)
        (f,) = doc["findings"]
        assert f["class"] == "missing_rank" and f["rank"] == 1
        assert any(e.startswith("protocol-model:")
                   for e in f["evidence"])


class TestReportSurfacing:
    def test_diagnosis_line_in_report(self, tmp_path, capsys):
        _write_jsonl(tmp_path / "run.p0.jsonl",
                     _healthy_stream(0, n_spans=10))
        _write_jsonl(tmp_path / "run.p1.jsonl", [_manifest(1)] + [
            _span(1, "allreduce", 100.0 + i) for i in range(3)
        ])
        rc = aggregate.main([str(tmp_path / "run.jsonl")])
        out = capsys.readouterr().out
        assert rc == 0
        assert any(line.startswith("DIAGNOSIS missing_rank: rank=1")
                   for line in out.splitlines())

    def test_clean_report_has_no_diagnosis_lines(self, clean_run,
                                                 capsys):
        aggregate.main([str(clean_run / "run.jsonl")])
        assert "DIAGNOSIS" not in capsys.readouterr().out

    def test_incomplete_rank_set_note(self, tmp_path, capsys):
        _write_jsonl(tmp_path / "run.p0.jsonl",
                     [_manifest(0, n=4)] + _healthy_stream(0)[1:])
        _write_jsonl(tmp_path / "run.p1.jsonl",
                     [_manifest(1, n=4)] + _healthy_stream(1)[1:])
        aggregate.main([str(tmp_path / "run.jsonl")])
        out = capsys.readouterr().out
        assert "NOTE incomplete rank set (2 of 4 from manifest)" in out
        assert "missing rank(s) 2,3" in out

    def test_complete_rank_set_no_note(self, clean_run, capsys):
        aggregate.main([str(clean_run / "run.jsonl")])
        assert "incomplete rank set" not in capsys.readouterr().out

    def test_diff_refuses_partial_baseline(self, tmp_path, capsys):
        partial = tmp_path / "a"
        partial.mkdir()
        _write_jsonl(partial / "run.p0.jsonl",
                     [_manifest(0, n=2)] + _healthy_stream(0)[1:])
        full = tmp_path / "b"
        full.mkdir()
        _write_jsonl(full / "run.p0.jsonl", _healthy_stream(0))
        _write_jsonl(full / "run.p1.jsonl", _healthy_stream(1))
        rc = aggregate.main([
            "--diff", str(partial / "run.jsonl"),
            str(full / "run.jsonl"),
        ])
        captured = capsys.readouterr()
        assert rc == 2
        assert "partial-rank run" in captured.err
        # the complete run IS a valid baseline; a partial B side is
        # compared (what regressed before the crash?) — but never
        # silently: the survivors-only coverage is a visible NOTE
        rc = aggregate.main([
            "--diff", str(full / "run.jsonl"),
            str(partial / "run.jsonl"),
        ])
        captured = capsys.readouterr()
        assert rc in (0, 1)
        assert "DIFF NOTE candidate" in captured.out
        assert "surviving ranks only" in captured.out

    def test_trace_renders_finding_marker_on_culprit_rank(
        self, tmp_path
    ):
        _write_jsonl(tmp_path / "run.p0.jsonl",
                     _healthy_stream(0, n_spans=10))
        _write_jsonl(tmp_path / "run.p1.jsonl", [_manifest(1)] + [
            _span(1, "allreduce", 100.0 + i) for i in range(3)
        ])
        doc = timeline.chrome_trace(_files(tmp_path))
        marks = [e for e in doc["traceEvents"]
                 if e.get("cat") == "finding"]
        assert len(marks) == 1
        assert marks[0]["ph"] == "i" and marks[0]["s"] == "p"
        assert marks[0]["pid"] == 1
        assert marks[0]["name"] == "FINDING missing_rank"
        assert marks[0]["args"]["confidence"] >= 0.85

    def test_clean_trace_has_no_finding_markers(self, clean_run):
        doc = timeline.chrome_trace(_files(clean_run))
        assert not [e for e in doc["traceEvents"]
                    if e.get("cat") == "finding"]
