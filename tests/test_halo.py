import jax.numpy as jnp
import numpy as np
import pytest

from tpu_mpi_tests.arrays.domain import Domain1D, Domain2D
from tpu_mpi_tests.comm import collectives as C
from tpu_mpi_tests.comm import halo as H
from tpu_mpi_tests.kernels.stencil import analytic_pairs
from tpu_mpi_tests.utils import TpuMtError

STAGINGS = [H.Staging.DIRECT, H.Staging.DEVICE_STAGED, H.Staging.HOST_STAGED]


def x_cubed(x):
    return x**3


def expected_ghosted_global(d: Domain1D, fn):
    """What the ghosted-global array must hold after a correct exchange:
    every ghost (interior and physical) continues the analytic grid."""
    return np.concatenate(
        [fn(d.ghosted_coords(r)) for r in range(d.n_shards)]
    )


class TestExchange1D:
    @pytest.mark.parametrize("staging", STAGINGS)
    def test_ghosts_filled_from_neighbors(self, mesh8, staging):
        d = Domain1D(n_global=64, n_shards=8, n_bnd=2)
        zg = C.shard_1d(jnp.asarray(d.init_global(x_cubed)), mesh8)
        out = H.halo_exchange(zg, mesh8, staging=staging)
        np.testing.assert_allclose(
            np.asarray(out), expected_ghosted_global(d, x_cubed), rtol=1e-12
        )

    def test_all_stagings_bitwise_identical(self, mesh8):
        d = Domain1D(n_global=64, n_shards=8, n_bnd=2)
        z0 = d.init_global(x_cubed)
        outs = [
            np.asarray(
                H.halo_exchange(
                    C.shard_1d(jnp.asarray(z0), mesh8), mesh8, staging=s
                )
            )
            for s in STAGINGS
        ]
        np.testing.assert_array_equal(outs[0], outs[1])
        np.testing.assert_array_equal(outs[0], outs[2])

    def test_periodic_wraparound(self, mesh8):
        n_bnd, nloc = 2, 8
        vals = np.arange(8 * (nloc + 2 * n_bnd), dtype=np.float64)
        zg = C.shard_1d(jnp.asarray(vals), mesh8)
        out = np.asarray(H.halo_exchange(zg, mesh8, periodic=True))
        blocks = out.reshape(8, nloc + 2 * n_bnd)
        orig = vals.reshape(8, nloc + 2 * n_bnd)
        # shard 0's lo ghost == shard 7's hi edge
        np.testing.assert_array_equal(blocks[0][:2], orig[7][-4:-2])
        # shard 7's hi ghost == shard 0's lo edge
        np.testing.assert_array_equal(blocks[7][-2:], orig[0][2:4])

    def test_nonperiodic_edges_keep_physical_ghosts(self, mesh8):
        d = Domain1D(n_global=64, n_shards=8, n_bnd=2)
        z0 = d.init_global(x_cubed)
        out = np.asarray(
            H.halo_exchange(C.shard_1d(jnp.asarray(z0), mesh8), mesh8)
        )
        # physical ghosts of shard 0 (left) and shard 7 (right) unchanged
        np.testing.assert_array_equal(out[:2], z0[:2])
        np.testing.assert_array_equal(out[-2:], z0[-2:])

    def test_bad_staging_name(self):
        with pytest.raises(TpuMtError, match="unknown staging"):
            H.Staging.parse("gpu")


class TestExchange2D:
    @pytest.mark.parametrize("dim", [0, 1])
    @pytest.mark.parametrize(
        "staging", [H.Staging.DIRECT, H.Staging.DEVICE_STAGED]
    )
    def test_2d_exchange_both_dims(self, mesh8, dim, staging):
        d = Domain2D(
            n_local_deriv=8, n_global_other=6, n_shards=8, dim=dim, n_bnd=2
        )
        f, _ = analytic_pairs()[f"2d_dim{dim}"]
        zg = C.shard_1d(jnp.asarray(d.init_global(f)), mesh8, axis=dim)
        out = np.asarray(
            H.halo_exchange(zg, mesh8, axis=dim, staging=staging)
        )
        # every shard's ghosts now continue the analytic function
        expected_blocks = []
        for r in range(8):
            x, y = d._coords(r, ghosted=True, dtype=np.float64)
            expected_blocks.append(f(x[:, None], y[None, :]))
        expected = np.concatenate(expected_blocks, axis=dim)
        np.testing.assert_allclose(out, expected, rtol=1e-12)

    def test_2d_host_staged_matches_direct(self, mesh8):
        d = Domain2D(
            n_local_deriv=8, n_global_other=6, n_shards=8, dim=0, n_bnd=2
        )
        f, _ = analytic_pairs()["2d_dim0"]
        z0 = d.init_global(f)
        direct = np.asarray(
            H.halo_exchange(C.shard_1d(jnp.asarray(z0), mesh8), mesh8)
        )
        host = np.asarray(
            H.halo_exchange(
                C.shard_1d(jnp.asarray(z0), mesh8),
                mesh8,
                staging=H.Staging.HOST_STAGED,
            )
        )
        np.testing.assert_array_equal(direct, host)


class TestExchangePlusStencil:
    def test_distributed_derivative_exact_for_cubic(self, mesh8):
        # the full reference pipeline (mpi_stencil_gt.cc): init, exchange,
        # stencil, err_norm ≈ 0 — distributed over 8 shards
        d = Domain1D(n_global=512, n_shards=8, n_bnd=2)
        f, df = analytic_pairs()["1d"]
        zg = C.shard_1d(jnp.asarray(d.init_global(f)), mesh8)
        zg = H.halo_exchange(zg, mesh8)
        deriv = H.stencil_fn(mesh8, "shard", 0, 1, d.scale)(zg)
        expected = d.interior_global(df)
        err = np.sqrt(((np.asarray(deriv) - expected) ** 2).sum())
        assert err < 1e-8

    def test_fused_matches_split(self, mesh8):
        d = Domain1D(n_global=512, n_shards=8, n_bnd=2)
        f, _ = analytic_pairs()["1d"]
        z0 = jnp.asarray(d.init_global(f))
        split = H.stencil_fn(mesh8, "shard", 0, 1, d.scale)(
            H.halo_exchange(C.shard_1d(z0, mesh8), mesh8)
        )
        fused = H.exchange_stencil_fused_fn(
            mesh8, "shard", 0, 1, 2, d.scale
        )(C.shard_1d(z0, mesh8))
        np.testing.assert_array_equal(np.asarray(split), np.asarray(fused))

    def test_broken_exchange_detected(self, mesh8):
        # without the exchange, interior-ghost zeros poison shard seams —
        # the err_norm gate must catch it (what the reference's norm tests)
        d = Domain1D(n_global=512, n_shards=8, n_bnd=2)
        f, df = analytic_pairs()["1d"]
        zg = C.shard_1d(jnp.asarray(d.init_global(f)), mesh8)
        deriv = H.stencil_fn(mesh8, "shard", 0, 1, d.scale)(zg)
        err = np.sqrt(
            ((np.asarray(deriv) - d.interior_global(df)) ** 2).sum()
        )
        assert err > 1.0
