"""CI gate for the pod-day protocol (tpu/pod.sh, round 5 — VERDICT r4
#7). Lives OUTSIDE test_multiproc.py on purpose: that module skips
wholesale without a C++ toolchain (tpumt_run), but pod.sh needs only
bash + python — the gate must not rot on toolchain-less machines."""

import json
import os
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

def test_pod_protocol_dryrun(tmp_path):
    """The pod-day protocol (tpu/pod.sh, round 5 — VERDICT r4 #7) must
    stay runnable: a 2-process localhost CPU world at CI shapes executes
    every cell (dual-dtype bench, XLA + RDMA collective sweeps at both
    credit depths, contiguous + striped causal ring attention, the
    stencil2d halo driver, the in-place RDMA gather) and writes a
    MULTICHIP-shaped PODRUN.json with all cells rc=0 — so real pod
    access converts to BASELINE rows with zero new engineering on the
    day. The attention pairs run at BOTH dtypes (round-5 dtype note:
    the striped layout's verdict inverts between f32 and bf16)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + (
        ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    proc = subprocess.Popen(
        ["bash", str(REPO / "tpu" / "pod.sh"), "-w", "2", "-c",
         "-o", str(tmp_path)],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=REPO,
        env=env,
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=840)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, 9)
        stdout, stderr = proc.communicate()
        pytest.fail(f"pod.sh dry-run timed out; partial:\n{stdout}\n{stderr}")
    assert proc.returncode == 0, stdout + stderr

    rec = json.loads((tmp_path / "PODRUN.json").read_text())
    assert rec["ok"] is True
    assert rec["world"] == 2
    expected = {"bench", "coll-xla", "coll-rdma-c1", "coll-rdma-c2",
                "attn-contig-f32", "attn-striped-f32",
                "attn-contig-bf16", "attn-striped-bf16",
                "stencil2d", "gather-rdma"}
    assert set(rec["cells"]) == expected, rec
    assert all(rc == 0 for rc in rec["cells"].values()), rec
    # the bench cell's rank-0 output must carry the dual-dtype JSON line
    bench_out = (tmp_path / "out-pod-bench-r0.txt").read_text()
    line = [l for l in bench_out.splitlines() if l.startswith("{")][-1]
    brec = json.loads(line)
    assert brec["dtype"] == "float32" and "bfloat16" in brec
