import jax
import pytest

from tpu_mpi_tests.comm import mesh as M


def test_eight_fake_devices():
    assert jax.device_count() == 8


def test_topology():
    t = M.topology()
    assert t.global_device_count == 8
    assert t.process_count == 1
    assert t.process_index == 0
    assert not t.is_multi_host
    assert t.platform == "cpu"


def test_make_mesh_default():
    m = M.make_mesh()
    assert m.axis_names == ("shard",)
    assert m.devices.shape == (8,)


def test_make_mesh_2d_and_wildcard():
    m = M.make_mesh({"x": 2, "y": -1})
    assert m.shape == {"x": 2, "y": 4}
    m2 = M.make_mesh([("dp", 4), ("sp", 2)])
    assert m2.shape == {"dp": 4, "sp": 2}


def test_make_mesh_bad_shapes():
    with pytest.raises(M.MeshError):
        M.make_mesh({"x": 3})
    with pytest.raises(M.MeshError):
        M.make_mesh({"x": -1, "y": -1})
    with pytest.raises(M.MeshError):
        M.make_mesh({"x": 3, "x2": -1})  # 8 % 3 != 0
    with pytest.raises(M.MeshError, match="duplicate"):
        M.make_mesh([("x", 2), ("x", 4)])


def test_check_divisible():
    from tpu_mpi_tests.utils import TpuMtError

    assert M.check_divisible(8, 2) == 4
    with pytest.raises(TpuMtError):
        M.check_divisible(7, 2)
    with pytest.raises(TpuMtError):
        M.check_divisible(8, 0)


def test_ranks_per_device():
    assert M.ranks_per_device(None) == 1
    assert M.ranks_per_device(8) == 1
    assert M.ranks_per_device(16) == 2
    with pytest.raises(M.MeshError):
        M.ranks_per_device(12)


def test_device_report_smoke():
    s = M.device_report(verbose=True)
    assert "0/1 processes" in s
    assert "8 global" in s


def test_make_mesh_2level():
    from tpu_mpi_tests.comm.mesh import make_mesh_2level

    m = make_mesh_2level()
    assert m.axis_names == ("dcn", "ici")
    # single-process test env: dcn=1, ici=all fake devices
    assert dict(m.shape) == {"dcn": 1, "ici": 8}
