"""Heat-equation mini-app driver tests (2-D process grid, periodic).

The verification gate is eigenstructure-exact (driver docstring): after T
explicit-Euler steps the field must equal g^T·z0 to roundoff, so a broken
exchange on EITHER mesh axis or a wrong Laplacian coefficient fails
immediately — no discretization-tolerance slack to hide behind."""

import re

import pytest

from tpu_mpi_tests.drivers import heat2d


def run_driver(capsys, *argv):
    rc = heat2d.main(["--fake-devices", "8", *argv])
    return rc, capsys.readouterr().out


def test_eigen_gate_f64_2x4(capsys):
    rc, out = run_driver(
        capsys, "--mesh", "2,4", "--nx-local", "16", "--ny-local", "8",
        "--n-steps", "50", "--dtype", "float64",
    )
    assert rc == 0, out
    rel = float(re.search(r"HEAT ERR rel=([\d.e+-]+)", out).group(1))
    assert rel < 1e-13  # roundoff-exact across both mesh axes


def test_eigen_gate_f32_higher_mode(capsys):
    rc, out = run_driver(
        capsys, "--mesh", "4,2", "--nx-local", "8", "--ny-local", "16",
        "--n-steps", "30", "--kx", "3", "--ky", "2",
    )
    assert rc == 0, out
    assert "HEAT FAIL" not in out


def test_decay_factor_applied(capsys):
    """One step must decay the field by exactly g (printed in JSONL via
    the gate); a no-op loop would pass a lazy norm check but not this."""
    rc, out = run_driver(
        capsys, "--mesh", "2,4", "--nx-local", "16", "--ny-local", "8",
        "--n-steps", "1", "--dtype", "float64",
    )
    assert rc == 0, out
    # with defaults cx+cy=0.4, k=1 modes: g < 1 strictly
    rel = float(re.search(r"HEAT ERR rel=([\d.e+-]+)", out).group(1))
    assert rel < 1e-14


def test_bad_mesh_rejected(capsys):
    rc, out = run_driver(capsys, "--mesh", "3,5")
    assert rc == 2
    assert "ERROR" in out


def test_unstable_dt_fails_gate(capsys):
    """dt past the explicit stability limit must blow up and be caught by
    the gate (the driver reports, not hides, an unstable configuration)."""
    rc, out = run_driver(
        capsys, "--mesh", "2,4", "--nx-local", "16", "--ny-local", "8",
        "--n-steps", "200", "--dt", "1.0", "--dtype", "float64",
    )
    assert rc == 1
    assert "HEAT FAIL" in out


@pytest.mark.parametrize("halo_steps", [2, 4])
def test_temporal_blocking_keeps_eigen_gate(capsys, halo_steps):
    """k Euler steps fused per dual-axis exchange over k-deep ghosts must
    stay eigenstructure-exact — stale values creep only within the ghost
    band the next deep exchange overwrites (2-D validity argument)."""
    rc, out = run_driver(
        capsys, "--mesh", "2,4", "--nx-local", "16", "--ny-local", "12",
        "--n-steps", "48", "--halo-steps", str(halo_steps),
        "--dtype", "float64",
    )
    assert rc == 0, out
    rel = float(re.search(r"HEAT ERR rel=([\d.e+-]+)", out).group(1))
    assert rel < 1e-13


def test_halo_steps_must_divide(capsys):
    with pytest.raises(SystemExit) as exc:
        heat2d.main([
            "--fake-devices", "8", "--n-steps", "50", "--halo-steps", "4",
        ])
    assert exc.value.code == 2
    assert "must be a multiple" in capsys.readouterr().err


@pytest.mark.parametrize("halo_steps", [1, 3])
def test_pallas_kernel_tier_keeps_eigen_gate(capsys, halo_steps):
    """The Pallas update body must preserve the eigenstructure exactly,
    through the same driver gate as the XLA tier (f64, 2x4 grid)."""
    rc, out = run_driver(
        capsys, "--mesh", "2,4", "--nx-local", "16", "--ny-local", "12",
        "--n-steps", "48", "--halo-steps", str(halo_steps),
        "--dtype", "float64", "--kernel", "pallas",
    )
    assert rc == 0, out
    rel = float(re.search(r"HEAT ERR rel=([\d.e+-]+)", out).group(1))
    assert rel < 1e-13


def test_pallas_tier_matches_xla_tier_bitwise():
    """Both tiers run the same recurrence update-for-update: identical
    results on the same shard (single device, k > 1). Direct kernel call
    with tile_rows=16 additionally forces multiple row blocks (masked
    edge blocks + unmasked interior blocks + ragged last block)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from jax.sharding import Mesh

    from tpu_mpi_tests.comm.halo import heat_step2d_fn
    from tpu_mpi_tests.kernels.pallas_kernels import heat2d_pallas

    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("x", "y"))
    nb = 2
    z0 = np.random.default_rng(9).normal(
        size=(64 + 2 * nb, 48 + 2 * nb)
    ).astype(np.float32)
    xla = heat_step2d_fn(mesh, "x", "y", nb, 0.1, 0.2, steps=2)
    pal = heat_step2d_fn(
        mesh, "x", "y", nb, 0.1, 0.2, steps=2, kernel="pallas",
        interpret=True,
    )
    a = np.asarray(xla(jnp.asarray(z0), 3))
    b = np.asarray(pal(jnp.asarray(z0), 3))
    np.testing.assert_array_equal(a, b)

    # multi-block streaming (68 rows / 16-row blocks = 5 incl. ragged)
    single = np.asarray(heat2d_pallas(
        jnp.asarray(z0), 0.1, 0.2, steps=2, n_bnd=nb, interpret=True
    ))
    multi = np.asarray(heat2d_pallas(
        jnp.asarray(z0), 0.1, 0.2, steps=2, n_bnd=nb, interpret=True,
        tile_rows=16,
    ))
    np.testing.assert_array_equal(multi, single)

    # round-5 border-coefficient variant (zeroed coefficient arrays
    # replace the per-step select): w + 0·δ²x + 0·δ²y == w exactly for
    # finite fields, so the variant must be BIT-identical to the
    # where-masked path, ragged multi-block included
    for tr in (None, 16):
        coeff = np.asarray(heat2d_pallas(
            jnp.asarray(z0), 0.1, 0.2, steps=2, n_bnd=nb, interpret=True,
            tile_rows=tr, border_coeff=True,
        ))
        np.testing.assert_array_equal(coeff, single)

    # f64: the coefficient select must run NATIVELY in the array dtype
    # (a review-caught first cut routed every dtype through an f32
    # select, silently rounding f64 coefficients)
    z64 = z0.astype(np.float64)
    a64 = np.asarray(heat2d_pallas(
        jnp.asarray(z64), 0.1, 0.2, steps=2, n_bnd=nb, interpret=True,
    ))
    c64 = np.asarray(heat2d_pallas(
        jnp.asarray(z64), 0.1, 0.2, steps=2, n_bnd=nb, interpret=True,
        border_coeff=True,
    ))
    np.testing.assert_array_equal(c64, a64)


def test_heat_step2d_rejects_unknown_kernel():
    import jax
    import numpy as np

    from jax.sharding import Mesh

    from tpu_mpi_tests.comm.halo import heat_step2d_fn

    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("x", "y"))
    with pytest.raises(ValueError, match="unknown kernel"):
        heat_step2d_fn(mesh, "x", "y", 1, 0.1, 0.1, kernel="bogus")


def test_pallas_width_limit_falls_back_to_xla(capsys):
    """Above the pallas body's VMEM width limit the driver must fall back
    to the XLA tier with a visible NOTE (and still pass the eigen gate),
    never crash or silently switch."""
    # f64 width past the round-3 calibrated live model at the minimum
    # 8-row block (temps are itemsize-scaled above f32): (4·8·8 +
    # 44·16)·W > the 15 MiB budget
    rc, out = run_driver(
        capsys, "--mesh", "2,4", "--nx-local", "16", "--ny-local", "23040",
        "--n-steps", "2", "--kernel", "pallas", "--dtype", "float64",
    )
    assert rc == 0, out
    assert "NOTE pallas kernel unavailable, using xla" in out
    assert "HEAT FAIL" not in out
