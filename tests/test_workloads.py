"""Workload-spec subsystem tests (ISSUE 8): the registry + generic
runner, the byte-identical daxpy/stencil1d ports, the three serving-era
pillars as one-shot drivers and serve handlers, and the embedding
primitives' exact parity with their dense references."""

import json
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_mpi_tests import workloads
from tpu_mpi_tests.drivers import _common
from tpu_mpi_tests.workloads import runner as wrunner
from tpu_mpi_tests.workloads.decode import DECODE_LINE_RE


# ------------------------------------------------------ registry / CLI


class TestRegistry:
    def test_all_specs_registered(self):
        names = workloads.spec_names()
        for name in ("daxpy", "decode", "embedding", "moe", "stencil1d"):
            assert name in names, names

    def test_specs_register_serve_handlers(self):
        """Registering a spec wires its serve workload class — the
        three new pillars serve without any serve-layer edits."""
        names = _common.workload_names()
        for name in ("daxpy", "halo", "moe", "decode", "embedding"):
            assert name in names, names

    def test_get_spec_unknown_name(self):
        with pytest.raises(KeyError):
            workloads.get_spec("nope")

    def test_umbrella_cli_lists_specs(self, capsys):
        assert wrunner.main(["--list"]) == 0
        out = capsys.readouterr().out.split()
        assert "moe" in out and "stencil1d" in out

    def test_umbrella_cli_unknown_spec(self, capsys):
        assert wrunner.main(["nope"]) == 2


# ------------------------------------------------- byte-identical ports


class TestPortedDrivers:
    """The daxpy/stencil1d driver bodies live on specs now; their
    stdout must stay byte-identical to the pre-port drivers — every
    line accounted for, static text exact, numeric fields in the
    historical formats."""

    def test_daxpy_output_shape_is_exact(self, capsys):
        from tpu_mpi_tests.drivers import daxpy

        rc = daxpy.main(["--n", "512", "--dtype", "float64"])
        assert rc == 0
        lines = capsys.readouterr().out.splitlines()
        assert lines[0] == "0/1 SUM = 131328.000000"  # 512*513/2, %f
        for i, phase in enumerate(
            ("copyInput", "kernel", "copyOutput"), start=1
        ):
            assert re.fullmatch(
                rf"TIME {phase} : \d+\.\d{{6}}", lines[i]
            ), lines[i]
        assert len(lines) == 4  # nothing extra crept in

    def test_daxpy_print_elements_precede_sum(self, capsys):
        from tpu_mpi_tests.drivers import daxpy

        rc = daxpy.main(
            ["--n", "4", "--dtype", "float64", "--print-elements"]
        )
        assert rc == 0
        lines = capsys.readouterr().out.splitlines()
        assert lines[:4] == [f"{v:f}" for v in (1.0, 2.0, 3.0, 4.0)]
        assert lines[4] == "0/1 SUM = 10.000000"

    def test_stencil1d_output_shape_is_exact(self, capsys):
        from tpu_mpi_tests.drivers import stencil1d

        rc = stencil1d.main(["--n-global", "4096", "--dtype", "float64"])
        assert rc == 0
        lines = capsys.readouterr().out.splitlines()
        assert lines[0] == (
            "stencil1d: n_global=4096 world=8 n_local=512 "
            "dtype=float64 staging=direct"
        )
        for r in range(8):
            assert re.fullmatch(
                rf"{r}/8 exchange time \d+\.\d{{8}}", lines[1 + r]
            ), lines[1 + r]
            assert re.fullmatch(
                rf"{r}/8 \[cpu\] err_norm = \d+\.\d{{8}}", lines[9 + r]
            ), lines[9 + r]
        assert len(lines) == 17

    def test_ported_drivers_keep_module_api(self):
        """The compat surface embedders/tests rely on survives the
        port: run/main/_serve_step_factory on both driver modules."""
        from tpu_mpi_tests.drivers import daxpy, stencil1d

        for mod in (daxpy, stencil1d):
            assert callable(mod.run)
            assert callable(mod.main)
            assert callable(mod._serve_step_factory)

    def test_daxpy_run_via_spec_runner(self):
        """daxpy.run(args) — the embedder entry — still works."""
        from tpu_mpi_tests.drivers import daxpy

        p = _common.base_parser("t")
        daxpy.SPEC.add_args(p)
        args = p.parse_args(["--n", "64", "--dtype", "float64"])
        assert daxpy.run(args) == 0


# ---------------------------------------------------- the new pillars


class TestMoESpec:
    def test_one_shot_driver_end_to_end(self, capsys, tmp_path):
        from tpu_mpi_tests.workloads import moe

        out = tmp_path / "moe.jsonl"
        rc = moe.main([
            "--tokens", "256", "--d-model", "16", "--iters", "2",
            "--capacity-factor", "1.0", "--jsonl", str(out),
        ])
        text = capsys.readouterr().out
        assert rc == 0
        m = re.search(
            r"ROUTE moe: world=8 capacity=(\d+) tokens=256 "
            r"routed=(\d+) dropped=(\d+) overflow=([\d.]+)% "
            r"occupancy=([\d.]+)% imbalance=([\d.]+)",
            text,
        )
        assert m, text
        assert int(m.group(2)) + int(m.group(3)) == 256
        assert "WORKLOAD moe: us_per_step=" in text
        recs = [json.loads(line) for line in out.read_text().splitlines()]
        rows = [r for r in recs if r.get("kind") == "workload"]
        assert rows and rows[0]["workload"] == "moe"
        assert rows[0]["higher_better"] is False
        assert any(r.get("kind") == "route" for r in recs)

    def test_bad_args_exit_2(self):
        from tpu_mpi_tests.workloads import moe

        with pytest.raises(SystemExit) as e:
            moe.main(["--tokens", "0"])
        assert e.value.code == 2

    def test_serve_handler_runs_batches(self, mesh8):
        step = _common.workload_factory("moe")(mesh8, (256, 16),
                                               "float32")
        step(3)  # chained routed steps; raises on any defect

    def test_serve_handler_rejects_bad_shape(self, mesh8):
        with pytest.raises(ValueError):
            _common.workload_factory("moe")(mesh8, (256,), "float32")


class TestDecodeSpec:
    def test_one_shot_driver_rows_parse(self, capsys, tmp_path):
        from tpu_mpi_tests.workloads import decode

        out = tmp_path / "dec.jsonl"
        rc = decode.main([
            "--batches", "1,4", "--heads", "8", "--n-iter", "20",
            "--jsonl", str(out),
        ])
        text = capsys.readouterr().out
        assert rc == 0
        rows = re.findall(DECODE_LINE_RE, text)
        assert len(rows) == 4  # 2 colls x 2 batches
        # µs/op latency rows, not GB/s: no bandwidth field on the line
        assert "GB/s" not in text
        recs = [json.loads(line) for line in out.read_text().splitlines()]
        dec = [r for r in recs if r.get("kind") == "decode"]
        assert len(dec) == 4
        assert all(r["us_per_op"] > 0 for r in dec)

    def test_unknown_collective_exits_2(self, capsys):
        from tpu_mpi_tests.workloads import decode

        assert decode.main(["--colls", "nope", "--n-iter", "20"]) == 2
        assert "ERROR unknown decode collective" in (
            capsys.readouterr().out
        )

    def test_serve_handler_runs_batches(self, mesh8):
        step = _common.workload_factory("decode")(mesh8, (4, 8),
                                                  "float32")
        step(2)

    def test_rows_resolve_cached_variant_per_payload(self, capsys,
                                                     tmp_path):
        """ISSUE-14 satellite: the µs/op pillar consumes the SAME
        ``coll_variant/*`` schedules collbench sweeps — per payload
        size, cached > prior — and a malformed cache value degrades to
        the XLA prior instead of crashing the row."""
        from tpu_mpi_tests.tune import registry as tr
        from tpu_mpi_tests.tune.fingerprint import fingerprint
        from tpu_mpi_tests.workloads import decode

        out = tmp_path / "dec.jsonl"
        try:
            tr.configure(cache_path=str(tmp_path / "t.json"))
            # batch=1 x heads=8 x f32 = 32 B per shard on world=8: a
            # cached rdma winner is below the ring kernel's lane floor
            # at this payload — the consult must be VISIBLE (the NOTE
            # proves the lookup engaged) and degrade to the XLA tier
            tr.configured_cache().store(
                "coll_variant/allreduce",
                fingerprint(dtype="float32", bytes=32, world=8),
                "rdma",
            )
            tr.configured_cache().save()
            rc = decode.main([
                "--batches", "1", "--heads", "8", "--n-iter", "20",
                "--colls", "allreduce",
                "--tune-cache", str(tmp_path / "t.json"),
                "--jsonl", str(out),
            ])
        finally:
            tr.deconfigure()
        text = capsys.readouterr().out
        assert rc == 0
        assert "cached rdma variant infeasible" in text
        recs = [json.loads(line) for line in
                out.read_text().splitlines()]
        dec = [r for r in recs if r.get("kind") == "decode"]
        assert len(dec) == 1
        assert dec[0]["variant"] == "xla"

    def test_malformed_cached_variant_degrades_to_prior(self, capsys,
                                                        tmp_path):
        from tpu_mpi_tests.tune import registry as tr
        from tpu_mpi_tests.tune.fingerprint import fingerprint
        from tpu_mpi_tests.workloads import decode

        out = tmp_path / "dec.jsonl"
        try:
            tr.configure(cache_path=str(tmp_path / "t.json"))
            tr.configured_cache().store(
                "coll_variant/allreduce",
                fingerprint(dtype="float32", bytes=32, world=8),
                "garbage",
            )
            tr.configured_cache().save()
            rc = decode.main([
                "--batches", "1", "--heads", "8", "--n-iter", "20",
                "--colls", "allreduce",
                "--tune-cache", str(tmp_path / "t.json"),
                "--jsonl", str(out),
            ])
        finally:
            tr.deconfigure()
        capsys.readouterr()
        assert rc == 0
        recs = [json.loads(line) for line in
                out.read_text().splitlines()]
        dec = [r for r in recs if r.get("kind") == "decode"]
        assert len(dec) == 1 and dec[0]["variant"] == "xla"

    def test_serve_handler_carries_tune_info(self, mesh8):
        """The --retune contract: the decode handler exposes its knob,
        context, candidates, and a rebuild that honors an explicit
        variant (the controller's re-sweep measure path)."""
        step = _common.workload_factory("decode")(mesh8, (4, 8),
                                                  "float32")
        info = step.tune_info
        assert info["knob"] == "coll_variant/allreduce"
        assert info["candidates"] == ("xla", "rdma", "oneshot")
        assert info["ctx"]["world"] == 8
        rebuilt = info["rebuild"]("xla")
        rebuilt(2)  # a working, warmed handler
        assert rebuilt.tune_info["knob"] == "coll_variant/allreduce"


class TestDaxpyChunkSchedule:
    """The ``daxpy/chunk`` knob (ISSUE 14): chunking is a dispatch-count
    schedule, never a numerics change — and the default resolution is
    the prior (1), byte-identical to the pre-knob loop."""

    def test_chunked_result_is_bitwise_identical(self, capsys,
                                                 tmp_path):
        from tpu_mpi_tests.tune import registry as tr
        from tpu_mpi_tests.tune.fingerprint import fingerprint
        from tpu_mpi_tests.workloads import daxpy

        rc = daxpy.main(["--n", "512", "--dtype", "float64",
                         "--iters", "5"])
        base = capsys.readouterr().out
        assert rc == 0
        try:
            cache = tr.configure(cache_path=str(tmp_path / "t.json"))
            cache.store("daxpy/chunk",
                        fingerprint(n=512, dtype="float64"), 4)
            cache.save()
            rc = daxpy.main(["--n", "512", "--dtype", "float64",
                             "--iters", "5",
                             "--tune-cache", str(tmp_path / "t.json")])
        finally:
            tr.deconfigure()
        chunked = capsys.readouterr().out
        assert rc == 0  # the per-element + checksum gates passed
        # same SUM, same line shapes (TIME values differ — timing)
        sum_of = lambda t: [ln for ln in t.splitlines()  # noqa: E731
                            if "SUM =" in ln]
        assert sum_of(chunked) == sum_of(base)

    def test_malformed_chunk_degrades_to_prior(self, capsys, tmp_path):
        from tpu_mpi_tests.tune import registry as tr
        from tpu_mpi_tests.tune.fingerprint import fingerprint
        from tpu_mpi_tests.workloads import daxpy

        try:
            cache = tr.configure(cache_path=str(tmp_path / "t.json"))
            cache.store("daxpy/chunk",
                        fingerprint(n=512, dtype="float64"), "bogus")
            cache.save()
            rc = daxpy.main(["--n", "512", "--dtype", "float64",
                             "--iters", "3",
                             "--tune-cache", str(tmp_path / "t.json")])
        finally:
            tr.deconfigure()
        capsys.readouterr()
        assert rc == 0

    def test_space_declared_with_prior_one(self):
        from tpu_mpi_tests.tune import registry as tr

        sp = tr.space("daxpy/chunk")
        assert sp.prior == 1
        assert sp.candidates[0] == 1


class TestEmbeddingSpec:
    def test_one_shot_driver_end_to_end(self, capsys, tmp_path):
        from tpu_mpi_tests.workloads import embedding

        out = tmp_path / "emb.jsonl"
        rc = embedding.main([
            "--vocab", "1024", "--d-model", "16", "--batch", "64",
            "--iters", "2", "--jsonl", str(out),
        ])
        text = capsys.readouterr().out
        assert rc == 0
        assert re.search(
            r"EMBED lookup: variant=take us_per_op=[\d.]+", text
        )
        assert re.search(r"EMBED scatter: us_per_op=[\d.]+", text)
        assert "WORKLOAD embedding: lookup_us_per_op=" in text

    def test_onehot_variant_verifies_too(self, capsys):
        from tpu_mpi_tests.workloads import embedding

        rc = embedding.main([
            "--vocab", "256", "--d-model", "8", "--batch", "32",
            "--iters", "1", "--lookup", "onehot",
        ])
        assert rc == 0
        assert "variant=onehot" in capsys.readouterr().out

    def test_serve_handler_runs_batches(self, mesh8):
        step = _common.workload_factory("embedding")(
            mesh8, (1024, 32, 16), "float32"
        )
        step(2)


# ------------------------------------------- embedding comm primitives


class TestEmbeddingComm:
    @pytest.mark.parametrize("variant", ["take", "onehot"])
    def test_lookup_matches_dense(self, mesh8, variant):
        from tpu_mpi_tests.comm import embedding as E

        rng = np.random.default_rng(0)
        tab = rng.integers(-4, 5, size=(64, 8)).astype(np.float32)
        ids = rng.integers(0, 64, size=(24,)).astype(np.int32)
        tabs = jax.device_put(
            jnp.asarray(tab), NamedSharding(mesh8, P("shard", None))
        )
        idr = jax.device_put(jnp.asarray(ids), NamedSharding(mesh8, P()))
        out = E.embedding_lookup(tabs, idr, mesh8, variant=variant)
        np.testing.assert_array_equal(np.asarray(out), tab[ids])

    def test_scatter_add_accumulates_duplicates(self, mesh8):
        from tpu_mpi_tests.comm import embedding as E

        tab = np.zeros((64, 4), np.float32)
        # every rank's ids hit row 5 → 8 independent adds must all land
        ids = np.full((8,), 5, np.int32)
        upd = np.ones((8, 4), np.float32)
        tabs = jax.device_put(
            jnp.asarray(tab), NamedSharding(mesh8, P("shard", None))
        )
        ids_s = jax.device_put(
            jnp.asarray(ids), NamedSharding(mesh8, P("shard"))
        )
        upd_s = jax.device_put(
            jnp.asarray(upd), NamedSharding(mesh8, P("shard", None))
        )
        new = E.embedding_scatter_add(tabs, ids_s, upd_s, mesh8)
        ref = tab.copy()
        np.add.at(ref, ids, upd)
        np.testing.assert_array_equal(np.asarray(new), ref)
        assert ref[5, 0] == 8.0  # the duplicates genuinely accumulated

    def test_lookup_variant_precedence_cached_over_prior(self, mesh8,
                                                         tmp_path):
        from tpu_mpi_tests.comm.embedding import resolve_lookup
        from tpu_mpi_tests.tune import registry as tr
        from tpu_mpi_tests.tune.fingerprint import fingerprint

        ctx = dict(dtype="float32", n=64, bytes=24, world=8)
        assert resolve_lookup(None, **ctx) == "take"  # prior
        cache = tr.configure(cache_path=str(tmp_path / "c.json"))
        try:
            cache.store("embedding/lookup", fingerprint(**ctx), "onehot")
            assert resolve_lookup(None, **ctx) == "onehot"  # cached
            assert resolve_lookup("take", **ctx) == "take"  # explicit
            cache.store("embedding/lookup", fingerprint(**ctx), "bogus")
            assert resolve_lookup(None, **ctx) == "take"  # degrade
        finally:
            tr.deconfigure()


# --------------------------------------------------- runner behaviors


class TestRunner:
    def test_workload_row_record_shape(self, capsys, tmp_path):
        """The runner's stable bench row: WORKLOAD line + kind:"workload"
        record carrying the regression direction."""
        from tpu_mpi_tests.workloads import decode

        out = tmp_path / "d.jsonl"
        rc = decode.main([
            "--batches", "1", "--heads", "8", "--n-iter", "20",
            "--colls", "allreduce", "--jsonl", str(out),
        ])
        assert rc == 0
        text = capsys.readouterr().out
        assert re.search(
            r"WORKLOAD decode: allreduce_us_per_op=[\d.]+ us", text
        )
        recs = [json.loads(line) for line in out.read_text().splitlines()]
        (row,) = [r for r in recs if r.get("kind") == "workload"]
        assert row["metric"] == "allreduce_us_per_op"
        assert row["higher_better"] is False
        assert row["unit"] == "us"
        assert row["world"] == 8

    def test_spec_spaces_resolve_through_registry(self):
        """The new pillars' knobs are declared spaces — visible to the
        registry (and so to serve-mode preload) like every PR-4 knob."""
        from tpu_mpi_tests.tune import registry as tr

        spaces = tr.spaces()
        assert spaces["moe/combine"].prior == "alltoall"
        assert spaces["embedding/lookup"].prior == "take"
