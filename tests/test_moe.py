"""MoE token-routing tests: exact parity with the dense reference and
the ragged/non-divisible occupancy cases the capacity buckets exist for
(ISSUE 8): overflowing occupancy tables, an expert receiving zero
tokens, and deterministic drop accounting under a fixed seed."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_mpi_tests.comm import moe as M
from tpu_mpi_tests.utils import TpuMtError

W = 8  # the suite's fake-device world


def _place(mesh, x, dest):
    xs = jax.device_put(
        jnp.asarray(x, jnp.float32), NamedSharding(mesh, P("shard", None))
    )
    ds = jax.device_put(
        jnp.asarray(dest), NamedSharding(mesh, P("shard"))
    )
    return xs, ds


def _tokens(seed, t, d=4):
    rng = np.random.default_rng(seed)
    x = rng.integers(1, 8, size=(t, d)).astype(np.float32)
    dest = rng.integers(0, W, size=(t,)).astype(np.int32)
    return x, dest


class TestRouting:
    @pytest.mark.parametrize("combine", ["alltoall", "allgather"])
    @pytest.mark.parametrize("capacity", [1, 3, 64])
    def test_matches_dense_reference_exactly(self, mesh8, capacity,
                                             combine):
        x, dest = _tokens(0, 64)
        xs, ds = _place(mesh8, x, dest)
        y, stats = M.route_tokens(xs, ds, mesh8, capacity,
                                  combine=combine)
        ref = M.route_reference(x, dest, W, capacity)
        np.testing.assert_array_equal(np.asarray(y), ref)
        assert stats.tokens == 64
        assert stats.routed + stats.dropped == 64

    def test_overflowing_occupancy_table(self, mesh8):
        """Every token on every rank names expert 0: each (source, 0)
        pair offers T_local tokens against `capacity` slots — the
        accounting must show exactly the overflow the table implies."""
        t = 64
        t_local = t // W
        x = np.arange(t * 4, dtype=np.float32).reshape(t, 4) + 1
        dest = np.zeros(t, np.int32)
        xs, ds = _place(mesh8, x, dest)
        cap = 2
        y, stats = M.route_tokens(xs, ds, mesh8, cap)
        assert stats.dropped == (t_local - cap) * W
        assert stats.overflow_pct == pytest.approx(
            100.0 * (t_local - cap) / t_local
        )
        # expert 0 holds every routed token; the load vector says so
        assert stats.expert_load[0] == cap * W
        assert all(v == 0 for v in stats.expert_load[1:])
        np.testing.assert_array_equal(
            np.asarray(y), M.route_reference(x, dest, W, cap)
        )

    def test_expert_receiving_zero_tokens(self, mesh8):
        """A rank nobody routes to must read load 0 (its capacity slots
        fly empty) while the rest of the routing stays exact."""
        x, dest = _tokens(1, 64)
        dest = np.where(dest == 3, 4, dest).astype(np.int32)  # starve 3
        xs, ds = _place(mesh8, x, dest)
        y, stats = M.route_tokens(xs, ds, mesh8, 8)
        assert stats.expert_load[3] == 0
        assert stats.counts[:, 3].sum() == 0
        np.testing.assert_array_equal(
            np.asarray(y), M.route_reference(x, dest, W, 8)
        )

    def test_imbalance_of_uniform_load_is_one(self, mesh8):
        """A perfectly balanced table (each shard's tokens round-robin
        the experts) reads imbalance exactly 1.0."""
        t = 64
        x = np.ones((t, 4), np.float32)
        dest = (np.arange(t) % W).astype(np.int32)
        xs, ds = _place(mesh8, x, dest)
        _, stats = M.route_tokens(xs, ds, mesh8, 4)
        assert stats.imbalance == 1.0
        assert stats.dropped == 0

    def test_drop_accounting_deterministic_under_fixed_seed(self, mesh8):
        """Same seed → byte-identical route records across runs (the
        serve-mode class identity depends on it): counts matrix, drop
        totals, overflow %, imbalance, and the record dict itself."""
        recs = []
        for _ in range(2):
            x, dest = _tokens(7, 64)
            xs, ds = _place(mesh8, x, dest)
            _, stats = M.route_tokens(xs, ds, mesh8, 2)
            recs.append(stats.record(op="moe"))
        assert recs[0] == recs[1]
        a, b = (M.route_tokens(*_place(mesh8, *_tokens(7, 64)),
                               mesh8, 2)[1] for _ in range(2))
        np.testing.assert_array_equal(a.counts, b.counts)
        assert a.dropped == b.dropped

    def test_non_divisible_tokens_fail_fast(self, mesh8):
        x, dest = _tokens(2, 60)  # 60 % 8 != 0
        xs = jnp.asarray(x)
        ds = jnp.asarray(dest)
        with pytest.raises(TpuMtError):
            M.route_tokens(xs, ds, mesh8, 4)

    def test_bad_capacity_rejected(self, mesh8):
        x, dest = _tokens(3, 64)
        xs, ds = _place(mesh8, x, dest)
        with pytest.raises(ValueError):
            M.route_tokens(xs, ds, mesh8, 0)

    def test_route_record_reaches_telemetry_sink(self, mesh8):
        """With telemetry on, every routed call mirrors its accounting
        as a kind:"route" record — the ROUTE table's input."""
        from tpu_mpi_tests.instrument import telemetry as T

        x, dest = _tokens(4, 64)
        xs, ds = _place(mesh8, x, dest)
        records = []
        T.enable(sink=records.append)
        try:
            M.route_tokens(xs, ds, mesh8, 3)
        finally:
            T.disable()
            T.registry().reset()
        routes = [r for r in records if r.get("kind") == "route"]
        assert len(routes) == 1
        assert routes[0]["tokens"] == 64
        assert routes[0]["capacity"] == 3
        spans = [r for r in records if r.get("kind") == "span"
                 and r.get("op") == "moe"]
        assert len(spans) == 1
        assert spans[0]["nbytes"] == M.route_payload_bytes(
            xs, W, 3, "alltoall"
        )

    def test_combine_variants_agree(self, mesh8):
        """Both combine schedules are the same function: byte-identical
        outputs and accounting."""
        x, dest = _tokens(5, 64)
        xs, ds = _place(mesh8, x, dest)
        y_a, st_a = M.route_tokens(xs, ds, mesh8, 3, combine="alltoall")
        y_g, st_g = M.route_tokens(xs, ds, mesh8, 3, combine="allgather")
        np.testing.assert_array_equal(np.asarray(y_a), np.asarray(y_g))
        np.testing.assert_array_equal(st_a.counts, st_g.counts)

    def test_malformed_cached_combine_degrades_to_prior(self, mesh8,
                                                        tmp_path):
        """A corrupted cache value for moe/combine must resolve to the
        shipped prior, not crash or run an unknown schedule."""
        from tpu_mpi_tests.tune import registry as tr
        from tpu_mpi_tests.tune.fingerprint import fingerprint

        cache = tr.configure(cache_path=str(tmp_path / "c.json"))
        try:
            cache.store(
                "moe/combine",
                fingerprint(dtype="float32", n=64, world=W),
                "bogus",
            )
            assert M.resolve_combine(
                None, dtype="float32", n=64, world=W
            ) == "alltoall"
        finally:
            tr.deconfigure()


class TestRouteStats:
    def test_stats_properties_from_counts(self):
        counts = np.zeros((2, 2), np.int64)
        counts[0, 0] = 5  # over a capacity of 3
        counts[1, 1] = 1
        st = M.RouteStats(world=2, capacity=3, counts=counts)
        assert st.tokens == 6
        assert st.routed == 4  # min(5,3) + 1
        assert st.dropped == 2
        assert st.overflow_pct == pytest.approx(100 * 2 / 6)
        assert list(st.expert_load) == [3, 1]
        assert st.imbalance == pytest.approx(3 / 2)
        rec = st.record(op="x")
        assert rec["kind"] == "route" and rec["dropped"] == 2

    def test_empty_table_degenerates_cleanly(self):
        st = M.RouteStats(
            world=2, capacity=3, counts=np.zeros((2, 2), np.int64)
        )
        assert st.overflow_pct == 0.0
        assert st.imbalance == 1.0
        assert st.occupancy_pct == 0.0
