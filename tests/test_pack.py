"""Portable schedule packs (ISSUE 14 tentpole b): pack → import →
pure-hit resolution, merge conflict/provenance semantics, corrupted-pack
degradation, the ``--tune-pack`` driver preload, and the ``tpumt-tune``
no-jax login-node golden."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from tpu_mpi_tests.tune import pack as tp
from tpu_mpi_tests.tune import registry as tr
from tpu_mpi_tests.tune.cache import ScheduleCache
from tpu_mpi_tests.tune.fingerprint import device_fingerprint, fingerprint
from tpu_mpi_tests.tune.sweep import ensure_tuned

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _isolated_registry(monkeypatch):
    monkeypatch.delenv("TPU_MPI_TUNE_CACHE", raising=False)
    tr.deconfigure()
    yield
    tr.deconfigure()


def _warm_cache(tmp_path, name="warm.json"):
    """A cache with two swept-looking entries (full + device slots)."""
    c = ScheduleCache.load(str(tmp_path / name))
    c.store("demo/packed", fingerprint(dtype="float32", n=4096), 32,
            seconds=0.5)
    c.store("demo/packed", device_fingerprint(), 32, seconds=0.5)
    c.save()
    return str(tmp_path / name)


# ------------------------------------------------------------- round trip


def test_pack_import_fresh_cache_is_pure_hits(tmp_path):
    """The fleet contract end to end in-process: pack a warmed cache,
    import into a FRESH cache, and every resolution is a pure tune_hit
    — zero sweeps, zero measurements."""
    warm = _warm_cache(tmp_path)
    pack_file = tmp_path / "sched.pack.json"
    assert tp.main(["pack", "--cache", warm,
                    "-o", str(pack_file)]) == 0
    fresh = tmp_path / "fresh.json"
    assert tp.main(["import", str(pack_file),
                    "--cache", str(fresh)]) == 0

    tr.configure(cache_path=str(fresh), enabled=True)
    records = []
    out = ensure_tuned(
        "demo/packed", lambda c: pytest.fail("pure hit: no sweep"),
        candidates=(1, 32), emit=records.append,
        dtype="float32", n=4096,
    )
    assert out == 32
    assert [r["kind"] for r in records] == ["tune_hit"]


def test_pack_carries_provenance(tmp_path):
    warm = _warm_cache(tmp_path)
    pack_file = tmp_path / "p.json"
    tp.main(["pack", "--cache", warm, "-o", str(pack_file)])
    doc = json.loads(pack_file.read_text())
    assert doc["kind"] == "tpumt-tune-pack" and doc["version"] == 1
    prov = doc["provenance"]
    assert prov["entries"] == 2
    assert prov["knobs"] == ["demo/packed"]
    # device identity read back out of the fingerprints the sweeps
    # stored under — platform/device/world/procs all present
    assert prov["devices"] and prov["platforms"]
    assert prov["worlds"] and prov["procs"]
    assert "engine" in doc


def test_pack_missing_cache_is_an_error(tmp_path, capsys):
    assert tp.main(["pack", "--cache", str(tmp_path / "nope.json"),
                    "-o", str(tmp_path / "o.json")]) == 2


# ------------------------------------------------------------------ merge


def _mini_pack(path, key, value, t):
    doc = tp.make_pack({
        key: {"value": value, "seconds": 0.1,
              "knob": key.split("|")[0],
              "fingerprint": key.split("|")[1], "t": t},
    })
    Path(path).write_text(json.dumps(doc))
    return str(path)


def test_merge_newer_measurement_wins_and_reports(tmp_path, capsys):
    a = _mini_pack(tmp_path / "a.json", "k|fp", "old-winner", 100.0)
    b = _mini_pack(tmp_path / "b.json", "k|fp", "new-winner", 200.0)
    out = tmp_path / "m.json"
    assert tp.main(["merge", a, b, "-o", str(out)]) == 0
    printed = capsys.readouterr().out
    assert "CONFLICT k|fp" in printed
    assert "newer measurement wins" in printed
    doc = json.loads(out.read_text())
    assert doc["entries"]["k|fp"]["value"] == "new-winner"
    # order-independent: the newer stamp wins from either side
    out2 = tmp_path / "m2.json"
    assert tp.main(["merge", b, a, "-o", str(out2)]) == 0
    assert json.loads(out2.read_text())["entries"]["k|fp"]["value"] \
        == "new-winner"


def test_merge_disjoint_and_identical_keys_are_not_conflicts(
        tmp_path, capsys):
    a = _mini_pack(tmp_path / "a.json", "k1|fp", 1, 100.0)
    b = _mini_pack(tmp_path / "b.json", "k2|fp", 2, 50.0)
    out = tmp_path / "m.json"
    assert tp.main(["merge", a, b, "-o", str(out)]) == 0
    assert "CONFLICT" not in capsys.readouterr().out
    assert len(json.loads(out.read_text())["entries"]) == 2


def test_import_dry_run_writes_nothing(tmp_path, capsys):
    warm = _warm_cache(tmp_path)
    pack_file = tmp_path / "p.json"
    tp.main(["pack", "--cache", warm, "-o", str(pack_file)])
    fresh = tmp_path / "fresh.json"
    assert tp.main(["import", str(pack_file), "--cache", str(fresh),
                    "--dry-run"]) == 0
    printed = capsys.readouterr().out
    assert "would write" in printed and "ADD" in printed
    assert not fresh.exists()


# ------------------------------------------------------------ degradation


@pytest.mark.parametrize("content", [
    "not json{{{",
    '{"version": 99, "kind": "tpumt-tune-pack", "entries": {}}',
    '{"version": 1, "kind": "something-else", "entries": {}}',
    '{"version": 1, "kind": "tpumt-tune-pack", "entries": "nope"}',
])
def test_corrupted_pack_degrades_to_empty(tmp_path, content):
    p = tmp_path / "bad.json"
    p.write_text(content)
    assert tp.load_pack(str(p))["entries"] == {}


def test_tune_pack_flag_preloads_and_degrades(tmp_path, capsys):
    """The --tune-pack driver path: setup_tuning absorbs a pack into
    the in-memory cache (resolutions then hit), and a corrupted pack
    degrades to the local cache/priors with a NOTE, never a crash."""
    import argparse

    from tpu_mpi_tests.drivers._common import setup_tuning

    warm = _warm_cache(tmp_path)
    pack_file = tmp_path / "p.json"
    tp.main(["pack", "--cache", warm, "-o", str(pack_file)])
    capsys.readouterr()

    args = argparse.Namespace(
        tune=False, tune_cache=str(tmp_path / "local.json"),
        tune_pack=str(pack_file), tune_budget=None,
    )
    setup_tuning(args)
    assert "preloaded" in capsys.readouterr().out
    assert tr.lookup("demo/packed", dtype="float32", n=4096) == 32
    # in-memory only: the local cache file was not created by preload
    assert not (tmp_path / "local.json").exists()

    tr.deconfigure()
    bad = tmp_path / "bad.json"
    bad.write_text("corrupt{{{")
    args.tune_pack = str(bad)
    setup_tuning(args)
    assert "empty or unreadable" in capsys.readouterr().out
    assert tr.lookup("demo/packed", dtype="float32", n=4096) is None


def test_absorb_newer_wins_against_local_entries(tmp_path):
    cache = ScheduleCache.load(str(tmp_path / "c.json"))
    cache.entries["k|fp"] = {"value": "local", "t": 200.0,
                             "knob": "k", "fingerprint": "fp"}
    doc = tp.make_pack({
        "k|fp": {"value": "packed", "t": 100.0, "knob": "k",
                 "fingerprint": "fp"},
        "k2|fp": {"value": "new", "t": 100.0, "knob": "k2",
                  "fingerprint": "fp"},
    })
    adopted = tp.absorb(cache, doc)
    assert cache.entries["k|fp"]["value"] == "local"  # newer local kept
    assert cache.entries["k2|fp"]["value"] == "new"
    assert adopted == 1


# ------------------------------------------------------------ entry point


def test_tpumt_tune_runs_without_jax(tmp_path):
    """The tpumt-tune console script must pack/merge/import in a
    process where ``import jax`` raises — the login-node contract of
    the sibling CLIs (packs are built and shipped from build hosts)."""
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps({
        "version": 1,
        "entries": {"demo/k|device=v5e;platform=tpu": {
            "value": 7, "seconds": 0.1, "knob": "demo/k",
            "fingerprint": "device=v5e;platform=tpu", "t": 100.0}},
    }))
    code = (
        "import sys\n"
        "class Block:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('jax blocked: login-node sim')\n"
        "sys.meta_path.insert(0, Block())\n"
        "from tpu_mpi_tests.tune import pack\n"
        "try:\n"
        "    pack.main(['--help'])\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        f"cache = {str(cache)!r}\n"
        f"out = {str(tmp_path / 'p.json')!r}\n"
        f"fresh = {str(tmp_path / 'fresh.json')!r}\n"
        "assert pack.main(['pack', '--cache', cache, '-o', out]) == 0\n"
        "assert pack.main(['merge', out, out, '-o', out + '.m']) == 0\n"
        "assert pack.main(['import', out, '--cache', fresh]) == 0\n"
        "import json\n"
        "doc = json.load(open(fresh))\n"
        "assert doc['entries'], doc\n"
        "print('TUNE PACK NOJAX OK')\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO,
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "TUNE PACK NOJAX OK" in r.stdout
    pyproject = (REPO / "pyproject.toml").read_text()
    assert 'tpumt-tune = "tpu_mpi_tests.tune.pack:main"' in pyproject
