"""Ring primitives + ring attention tests (8-shard CPU mesh).

The halo layer is the 1-step special case of this machinery (SURVEY §5.7);
these tests prove the generic ring carries full sequence parallelism."""

import numpy as np

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_mpi_tests.comm import ring as R
from tpu_mpi_tests.comm.collectives import shard_1d


def reference_attention(q, k, v, causal=False):
    s = (q @ k.T) / np.sqrt(q.shape[-1])
    if causal:
        L = s.shape[0]
        s = np.where(np.tril(np.ones((L, L), bool)), s, -np.inf)
    p = np.exp(s - s.max(axis=-1, keepdims=True))
    p /= p.sum(axis=-1, keepdims=True)
    return p @ v


def blockwise_causal_reference(q, k, v, block=512):
    """reference_attention(causal=True) computed in q-row blocks — O(B·L)
    temporaries instead of a dense L×L f64 score matrix (~300 MB at
    L=6144), for the large-geometry tests."""
    L, d = q.shape
    out = np.empty((L, d), np.float64)
    k64, v64 = k.astype(np.float64), v.astype(np.float64)
    for i0 in range(0, L, block):
        sb = (q[i0:i0 + block].astype(np.float64) @ k64.T) / np.sqrt(d)
        rows = np.arange(i0, i0 + sb.shape[0])[:, None]
        sb = np.where(np.arange(L)[None, :] <= rows, sb, -np.inf)
        pb = np.exp(sb - sb.max(-1, keepdims=True))
        out[i0:i0 + block] = (pb / pb.sum(-1, keepdims=True)) @ v64
    return out


def test_ring_pass_rotates(mesh8):
    import functools

    import jax
    from tpu_mpi_tests.compat import shard_map

    x = shard_1d(jnp.arange(8, dtype=jnp.float32).reshape(8, 1), mesh8)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh8, in_specs=P("shard", None),
        out_specs=P("shard", None),
    )
    def rot(x):
        return R.ring_pass(x, "shard")

    out = np.asarray(rot(x)).reshape(-1)
    assert out.tolist() == [7, 0, 1, 2, 3, 4, 5, 6]


def test_ring_scan_sums_all_blocks(mesh8):
    import functools

    import jax
    from tpu_mpi_tests.compat import shard_map

    x = shard_1d(
        jnp.arange(16, dtype=jnp.float32).reshape(16, 1), mesh8
    )  # blocks of 2 rows

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh8, in_specs=P("shard", None),
        out_specs=P("shard", None),
    )
    def total(x):
        return R.ring_scan(
            lambda c, blk, src: c + blk.sum(), jnp.float32(0), x, "shard"
        ).reshape(1, 1)

    out = np.asarray(total(x)).reshape(-1)
    assert np.allclose(out, 120.0)  # every rank saw every block


import pytest


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(mesh8, causal):
    """The all-to-all (Ulysses) flavor must agree with full multi-head
    attention — and with the ring flavor, per head."""
    from tpu_mpi_tests.comm.alltoall import ulysses_attention_fn

    rng = np.random.default_rng(3)
    L, H, Dh = 8 * 8, 16, 8
    q, k, v = (
        rng.normal(size=(L, H, Dh)).astype(np.float32) for _ in range(3)
    )
    attn = ulysses_attention_fn(mesh8, "shard", causal=causal)
    got = np.asarray(
        attn(
            shard_1d(jnp.asarray(q), mesh8),
            shard_1d(jnp.asarray(k), mesh8),
            shard_1d(jnp.asarray(v), mesh8),
        )
    )
    assert got.shape == (L, H, Dh)
    for h in range(H):
        ref = reference_attention(
            q[:, h].astype(np.float64),
            k[:, h].astype(np.float64),
            v[:, h].astype(np.float64),
            causal=causal,
        )
        assert np.allclose(got[:, h], ref, atol=2e-5), h


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_blockwise_matches_full(mesh8, causal):
    """The flash-style blockwise local attention (key tiles + online
    softmax) must agree exactly with the full-matrix path, including a
    ragged final tile (VERDICT r1 weak #8: round 1 materialized L² scores,
    capping sequence length)."""
    from tpu_mpi_tests.comm.alltoall import ulysses_attention_fn

    rng = np.random.default_rng(7)
    # L_global=8·88=704 per-head after all-to-all; block_keys=96 → 8 tiles
    # with a ragged 32-key tail
    L, H, Dh = 8 * 88, 8, 8
    q, k, v = (
        rng.normal(size=(L, H, Dh)).astype(np.float32) for _ in range(3)
    )
    blocked = ulysses_attention_fn(mesh8, "shard", causal=causal,
                                   block_keys=96)
    full = ulysses_attention_fn(mesh8, "shard", causal=causal,
                                block_keys=L)
    args = tuple(
        shard_1d(jnp.asarray(t), mesh8) for t in (q, k, v)
    )
    got = np.asarray(blocked(*args))
    want = np.asarray(full(*args))
    assert np.allclose(got, want, atol=2e-5)
    ref = reference_attention(
        q[:, 0].astype(np.float64), k[:, 0].astype(np.float64),
        v[:, 0].astype(np.float64), causal=causal,
    )
    assert np.allclose(got[:, 0], ref, atol=2e-5)


def test_ulysses_long_sequence_blockwise(mesh8):
    """Long-context smoke: L where the full (H_local, L, L) score tensor
    (8·4096² f32 = 537 MB per device) would be the dominant allocation;
    blockwise peak is O(L·block_keys·H_local) ≈ 8 MB. Two different tile
    widths must agree — a scale-level check on the online-softmax
    accumulation and tail masking."""
    from tpu_mpi_tests.comm.alltoall import ulysses_attention_fn

    rng = np.random.default_rng(11)
    L, H, Dh = 4096, 8, 8
    q, k, v = (
        rng.normal(size=(L, H, Dh)).astype(np.float32) for _ in range(3)
    )
    args = tuple(shard_1d(jnp.asarray(t), mesh8) for t in (q, k, v))
    a = np.asarray(
        ulysses_attention_fn(mesh8, "shard", block_keys=512)(*args)
    )
    b = np.asarray(
        ulysses_attention_fn(mesh8, "shard", block_keys=768)(*args)
    )
    assert a.shape == (L, H, Dh)
    assert np.allclose(a, b, atol=2e-5)


def _ulysses_span_nbytes(mesh8, block_keys, records):
    """Run the ulysses fn (a fresh lru-cache key via ``block_keys``)
    under a capturing telemetry sink; return (span_nbytes, args)."""
    from tpu_mpi_tests.comm.alltoall import ulysses_attention_fn
    from tpu_mpi_tests.instrument import telemetry as T

    L, H, Dh = 8 * 4, 8, 8
    args = tuple(
        shard_1d(jnp.ones((L, H, Dh), jnp.float32), mesh8)
        for _ in range(3)
    )
    T.enable(sink=records.append)
    try:
        ulysses_attention_fn(mesh8, "shard", block_keys=block_keys)(*args)
    finally:
        T.disable()
        T.registry().reset()
    spans = [r for r in records
             if r.get("kind") == "span" and r.get("op") == (
                 "ulysses_attention")]
    assert len(spans) == 1, records
    return spans[0]["nbytes"], args


def test_ulysses_telemetry_bytes_default_path(mesh8):
    """Regression (ISSUE 8 satellite): the recorded ulysses payload
    used ``2*q.nbytes`` for the output all-to-all. On the default path
    the output IS q-shaped, so the fix must record exactly the same
    number — (w−1)/w of q+k+v plus the output operand."""
    records = []
    nbytes, (q, k, v) = _ulysses_span_nbytes(mesh8, 4093, records)
    moved = q.nbytes + k.nbytes + v.nbytes + q.nbytes  # out == q shape
    assert nbytes == 7 * moved // 8


def test_ulysses_telemetry_bytes_track_padded_output(mesh8,
                                                     monkeypatch):
    """When the local attention returns a PADDED output (the
    flash/blockwise-padding case the old q-shaped accounting silently
    mis-counted), the recorded bytes must follow the actual output
    operand of the head→seq all-to-all."""
    from tpu_mpi_tests.comm import alltoall as A

    real = A._local_attention

    def padded(q, k, v, causal, precision, block_keys=512):
        out = real(q, k, v, causal, precision, block_keys=block_keys)
        return jnp.concatenate([out, jnp.zeros_like(out)], axis=0)

    monkeypatch.setattr(A, "_local_attention", padded)
    records = []
    nbytes, (q, k, v) = _ulysses_span_nbytes(mesh8, 4091, records)
    # the out operand is 2x q-sized now; q-shaped accounting would
    # still claim 4*q.nbytes worth of operands
    moved = q.nbytes + k.nbytes + v.nbytes + 2 * q.nbytes
    assert nbytes == 7 * moved // 8
    assert nbytes != 7 * (4 * q.nbytes) // 8


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(mesh8, causal):
    rng = np.random.default_rng(0)
    L, d = 8 * 16, 32
    q = rng.normal(size=(L, d)).astype(np.float32)
    k = rng.normal(size=(L, d)).astype(np.float32)
    v = rng.normal(size=(L, d)).astype(np.float32)

    attn = R.ring_attention_fn(mesh8, "shard", causal=causal)
    got = np.asarray(
        attn(
            shard_1d(jnp.asarray(q), mesh8),
            shard_1d(jnp.asarray(k), mesh8),
            shard_1d(jnp.asarray(v), mesh8),
        )
    )
    ref = reference_attention(
        q.astype(np.float64),
        k.astype(np.float64),
        v.astype(np.float64),
        causal=causal,
    )
    assert np.isfinite(got).all()
    assert np.allclose(got, ref, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    """Single-block Pallas flash attention (interpret) vs exact softmax."""
    from tpu_mpi_tests.kernels.pallas_kernels import flash_attention_pallas

    rng = np.random.default_rng(3)
    L, d = 128, 32
    q, k, v = (rng.normal(size=(L, d)).astype(np.float32) for _ in range(3))
    got = np.asarray(
        flash_attention_pallas(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal,
            q_tile=32, k_tile=64, interpret=True,
        )
    )
    ref = reference_attention(
        q.astype(np.float64), k.astype(np.float64), v.astype(np.float64),
        causal=causal,
    )
    assert np.isfinite(got).all()
    assert np.allclose(got, ref, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_attention_matches_full(mesh8, causal):
    """Ring attention with the Pallas flash local kernel == exact reference
    over 8 shards — the two tiers are interchangeable (≅ the reference's
    gtensor-vs-SYCL dual implementation pattern, applied to attention)."""
    rng = np.random.default_rng(4)
    L, d = 8 * 16, 32
    q, k, v = (rng.normal(size=(L, d)).astype(np.float32) for _ in range(3))

    attn = R.ring_attention_fn(
        mesh8, "shard", causal=causal, flash=True, interpret=True
    )
    got = np.asarray(
        attn(
            shard_1d(jnp.asarray(q), mesh8),
            shard_1d(jnp.asarray(k), mesh8),
            shard_1d(jnp.asarray(v), mesh8),
        )
    )
    ref = reference_attention(
        q.astype(np.float64), k.astype(np.float64), v.astype(np.float64),
        causal=causal,
    )
    assert np.isfinite(got).all()
    assert np.allclose(got, ref, atol=2e-5)


@pytest.mark.parametrize(
    "causal,stripe", [(False, False), (True, False), (True, True)]
)
def test_ring_fused_tier_matches_reference(mesh8, causal, stripe):
    """ISSUE 19 tentpole b: the one-launch fused-RDMA rotation tier
    (``tier="fused"`` — in-kernel K/V rotation overlapped with the
    block matmul) matches the exact reference AND the pipelined tier at
    every layout; swapping the rotation schedule never moves the
    numerics beyond kernel-order rounding."""
    rng = np.random.default_rng(11)
    L, d = 8 * 16, 32
    q, k, v = (rng.normal(size=(L, d)).astype(np.float32)
               for _ in range(3))
    ref = reference_attention(
        q.astype(np.float64), k.astype(np.float64),
        v.astype(np.float64), causal=causal,
    )
    if stripe:  # inputs AND outputs live in the striped layout
        q, k, v = (R.to_striped(t, 8) for t in (q, k, v))

    def run(tier):
        attn = R.ring_attention_fn(
            mesh8, "shard", causal=causal, stripe=stripe, tier=tier
        )
        out = np.asarray(
            attn(
                shard_1d(jnp.asarray(q), mesh8),
                shard_1d(jnp.asarray(k), mesh8),
                shard_1d(jnp.asarray(v), mesh8),
            )
        )
        return np.asarray(R.from_striped(jnp.asarray(out), 8)) \
            if stripe else out

    fused = run("fused")
    assert np.isfinite(fused).all()
    assert np.allclose(fused, ref, atol=2e-5)
    # tier-swap gate: fused vs pipelined agree to kernel-order rounding
    # (bitwise on this interpret-mode CPU config)
    np.testing.assert_allclose(fused, run("pipelined"), atol=1e-5)


def test_ring_fused_tier_infeasible_raises(mesh8):
    """An EXPLICIT fused request at a geometry whose live block set
    exceeds VMEM is a loud error naming the pipelined escape hatch —
    only a cached winner degrades silently (``ring_attention``)."""
    from tpu_mpi_tests.kernels.collectives_pallas import (
        fused_ring_feasible,
    )

    assert not fused_ring_feasible(2048, 2048, 256, np.float32)
    big = jnp.zeros((8 * 2048, 256), jnp.float32)
    attn = R.ring_attention_fn(mesh8, "shard", tier="fused")
    with pytest.raises(ValueError, match="pipelined"):
        attn(
            shard_1d(big, mesh8), shard_1d(big, mesh8),
            shard_1d(big, mesh8),
        )


def test_ring_fused_tier_cached_winner_degrades_at_infeasible(
    mesh8, tmp_path
):
    """A cached fused winner traveling to an infeasible geometry
    degrades to the pipelined schedule instead of crashing: the result
    must be byte-identical to an explicit pipelined run."""
    from tpu_mpi_tests.tune import registry as tr
    from tpu_mpi_tests.tune.fingerprint import fingerprint

    lq, d = 2048, 256  # feasibility: lq*lk score block alone > 14 MiB
    from tpu_mpi_tests.kernels.collectives_pallas import (
        fused_ring_feasible,
    )

    assert not fused_ring_feasible(lq, lq, d, np.float32)
    tr.configure(cache_path=str(tmp_path / "t.json"))
    try:
        tr.configured_cache().store(
            "ring/tier", fingerprint(dtype="float32", lq=lq), "fused"
        )
        rng = np.random.default_rng(13)
        q, k, v = (
            jnp.asarray(
                rng.normal(size=(8 * lq, d)).astype(np.float32)
            )
            for _ in range(3)
        )
        got = np.asarray(
            R.ring_attention_fn(mesh8, "shard")(
                shard_1d(q, mesh8), shard_1d(k, mesh8),
                shard_1d(v, mesh8),
            )
        )
        want = np.asarray(
            R.ring_attention_fn(mesh8, "shard", tier="pipelined")(
                shard_1d(q, mesh8), shard_1d(k, mesh8),
                shard_1d(v, mesh8),
            )
        )
        np.testing.assert_array_equal(got, want)
    finally:
        tr.deconfigure()


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_attention_matches_full(mesh8, causal):
    """Ulysses with the per-head Pallas flash local kernel == exact
    reference (vmapped kernel over the head axis after the all-to-all)."""
    from tpu_mpi_tests.comm.alltoall import ulysses_attention_fn

    rng = np.random.default_rng(5)
    L, H, d = 8 * 16, 8, 16
    q, k, v = (
        rng.normal(size=(L, H, d)).astype(np.float32) for _ in range(3)
    )
    attn = ulysses_attention_fn(
        mesh8, "shard", causal=causal, flash=True, interpret=True
    )
    got = np.asarray(
        attn(
            shard_1d(jnp.asarray(q), mesh8),
            shard_1d(jnp.asarray(k), mesh8),
            shard_1d(jnp.asarray(v), mesh8),
        )
    )
    ref = np.stack(
        [
            reference_attention(
                q[:, h].astype(np.float64),
                k[:, h].astype(np.float64),
                v[:, h].astype(np.float64),
                causal=causal,
            )
            for h in range(H)
        ],
        axis=1,
    )
    assert np.isfinite(got).all()
    assert np.allclose(got, ref, atol=2e-5)


def test_flash_attention_fuzz_shapes():
    """Property sweep: random L/d/tiles (tiles auto-shrink to divisors of
    arbitrary lengths), causal and full — flash must match the exact
    reference. (A 40-trial offline sweep passed; 8 pinned-seed trials in
    CI.)"""
    from tpu_mpi_tests.kernels.pallas_kernels import flash_attention_pallas

    rng = np.random.default_rng(1)
    for _ in range(8):
        L = int(rng.integers(8, 260))
        d = int(rng.integers(4, 80))
        causal = bool(rng.integers(0, 2))
        qt = int(rng.integers(8, 300))
        kt = int(rng.integers(8, 300))
        skt = int(rng.integers(0, 80))  # 0 = legacy coupled path
        q, k, v = (
            rng.normal(size=(L, d)).astype(np.float32) for _ in range(3)
        )
        got = np.asarray(flash_attention_pallas(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal,
            q_tile=qt, k_tile=kt, skip_tile=skt, interpret=True,
        ))
        ref = reference_attention(
            q.astype(np.float64), k.astype(np.float64),
            v.astype(np.float64), causal=causal,
        )
        assert np.isfinite(got).all()
        np.testing.assert_allclose(
            got, ref, atol=5e-5,
            err_msg=f"L={L} d={d} causal={causal} qt={qt} kt={kt} "
                    f"skt={skt}",
        )


@pytest.mark.parametrize("skip_tile", [0, 16, 32, 128])
def test_flash_skip_rescale_decoupling(skip_tile):
    """Round 5 (VERDICT r4 #1): the causal skip granularity (``skip_tile``
    sub-spans) is decoupled from the rescale granularity (``k_tile``).
    Geometry chosen so every regime executes: L=256, q_tile=32,
    k_tile=128 → 8 q tiles × 2 k tiles, with n_full/boundary splits at
    every diagonal crossing; skip_tile sweeps sub-spans-per-tile from 8
    (16-wide) down to 1 (128 = k_tile) plus the legacy coupled path (0).
    All must equal the exact reference AND each other's math up to
    reassociation."""
    from tpu_mpi_tests.kernels.pallas_kernels import flash_attention_pallas

    rng = np.random.default_rng(17)
    L, d = 256, 32
    q, k, v = (rng.normal(size=(L, d)).astype(np.float32) for _ in range(3))
    got = np.asarray(flash_attention_pallas(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True,
        q_tile=32, k_tile=128, skip_tile=skip_tile, interpret=True,
    ))
    ref = reference_attention(
        q.astype(np.float64), k.astype(np.float64), v.astype(np.float64),
        causal=True,
    )
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, ref, atol=5e-5)


def test_flash_bf16_highest_precision_upcast():
    """bf16 inputs at precision=HIGHEST (the documented default) must
    work AND deliver better-than-bf16 arithmetic: Mosaic rejects bf16
    operands with fp32 contract precision ("Bad lhs type",
    hardware-discovered round 5), so the kernels upcast sub-f32 matmul
    operands to f32 in VMEM (`_qk_operands`/`_pv_operands`). Gate: the
    HIGHEST result from bf16 inputs tracks the f64 reference of the
    bf16-ROUNDED inputs distinctly tighter than storage rounding alone
    would require — proof the dots really ran wider than bf16."""
    from tpu_mpi_tests.kernels.pallas_kernels import flash_attention_pallas

    rng = np.random.default_rng(21)
    L, d = 256, 64
    qb, kb, vb = (
        jnp.asarray(rng.normal(size=(L, d)), jnp.bfloat16) for _ in range(3)
    )
    got_hi = np.asarray(flash_attention_pallas(
        qb, kb, vb, causal=True, q_tile=64, k_tile=128, interpret=True,
    ).astype(jnp.float32))
    from jax import lax

    got_lo = np.asarray(flash_attention_pallas(
        qb, kb, vb, causal=True, q_tile=64, k_tile=128, interpret=True,
        precision=lax.Precision.DEFAULT,
    ).astype(jnp.float32))
    ref = reference_attention(
        np.asarray(qb, np.float64), np.asarray(kb, np.float64),
        np.asarray(vb, np.float64), causal=True,
    )
    assert np.isfinite(got_hi).all()
    err_hi = np.abs(got_hi - ref).max()
    err_lo = np.abs(got_lo - ref).max()
    # the bf16 OUTPUT cast floors both at ~4e-3; HIGHEST's advantage is
    # keeping the probabilities f32 into the PV matmul (DEFAULT downcasts
    # p to bf16), so it must track the reference at least as tightly
    assert err_hi <= 8e-3, err_hi
    assert err_hi <= err_lo + 1e-6, (err_hi, err_lo)


def test_flash_skip_tile_striped_stride(mesh8):
    """The sub-span skip path under the STRIPED layout's stride=world
    positions (the configuration the decoupling was built for): striped
    causal ring attention with skip_tile well below k_tile must match the
    exact reference after the layout round-trip."""
    rng = np.random.default_rng(18)
    L, d = 8 * 64, 32
    q, k, v = (rng.normal(size=(L, d)).astype(np.float32) for _ in range(3))
    ref = reference_attention(
        q.astype(np.float64), k.astype(np.float64), v.astype(np.float64),
        causal=True,
    )
    qs, ks, vs = (
        R.to_striped(jnp.asarray(t), 8) for t in (q, k, v)
    )
    attn = R.ring_attention_fn(
        mesh8, "shard", causal=True, flash=True, interpret=True,
        stripe=True, q_tile=16, k_tile=32, skip_tile=8,
    )
    got = np.asarray(R.from_striped(
        attn(shard_1d(qs, mesh8), shard_1d(ks, mesh8), shard_1d(vs, mesh8)),
        8,
    ))
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, ref, atol=5e-5)


def test_measured_best_tiles_pinned():
    """The default flash tile configuration is the MEASURED-best one
    (VERDICT r4 #2: 'a measurement that doesn't change a default is a
    report, not an optimization'). Pinned to the BASELINE.md round-5
    stripebalance section (three grids interleaved same-window): wide
    k tiles win for BOTH ring layouts, and the causal-skip granularity
    is LAYOUT-dependent — striped wants 256-wide sub-span skipping
    (1.645 vs 1.859 ms paced, 18% less total work than coupled,
    same-window), while the contiguous/self-causal narrow band trades
    within window noise with a slight coupled edge, keeping the simpler
    homogeneous full-width masked loop (skip 0)."""
    assert R.MEASURED_BEST_K_TILE == {"contig": 2048, "striped": 2048}
    assert R.MEASURED_BEST_SKIP_TILE == {"contig": 0, "striped": 256}
    assert R._resolve_k_tile(None, False) == 2048
    assert R._resolve_k_tile(None, True) == 2048
    assert R._resolve_k_tile(512, True) == 512  # explicit overrides win
    assert R._resolve_skip_tile(None, False) == 0
    assert R._resolve_skip_tile(None, True) == 256
    assert R._resolve_skip_tile(64, False) == 64


def test_flash_tile_skip_at_default_geometry(monkeypatch):
    """Causal tile-skip at NON-degenerate geometry (VERDICT r3 next #7):
    L = 3·k_tile at the flash DEFAULTS (q_tile=256, k_tile=2048), so the
    resident kernel's ``n_live`` bound walks through every regime — q
    tiles with 1 live + 2 skipped, 2 live + 1 skipped, and all-live —
    including the exact tile-boundary rows where an off-by-one in
    ``lim // k_tile + 1`` would mis-skip. The dryrun's checks 2/2b use
    L = 4·n, d = 8, where tiles auto-shrink to trivial sizes and never
    hit these boundaries. A second pass shrinks the budget to 3 MiB —
    below the ~3.3 MB full-K/V residency floor, asserted via
    ``_fit_flash_tiles`` returning None — so the STREAMING kernel runs
    the same geometry with a k_tile well above the 256 floor the
    existing streaming test sits at, covering its dead-cell K/V index
    remap at scale."""
    from tpu_mpi_tests.kernels import pallas_kernels as PK

    rng = np.random.default_rng(11)
    L, d = 3 * 2048, 64
    q, k, v = (rng.normal(size=(L, d)).astype(np.float32) for _ in range(3))
    ref = blockwise_causal_reference(q, k, v)

    # resident path at untouched defaults: K/V (3.1 MB) + scores tile
    # (4.2 MB) fit the real budget, so q_tile/k_tile stay 256/2048
    got = np.asarray(PK.flash_attention_pallas(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True,
        interpret=True,
    ))
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, ref, atol=5e-5)

    # streaming path at the same geometry (budget-forced); assert the
    # budget actually forces it — at 4 MiB the resident kernel still fits
    # with shrunken tiles and the streaming claim would be vacuous
    PK.flash_attention_pallas.clear_cache()
    PK._flash_attention_block_jit.clear_cache()
    monkeypatch.setattr(PK, "_VMEM_BUDGET_BYTES", 3 * 1024 * 1024)
    assert PK._fit_flash_tiles(L, L, d, 4, 256, 2048) is None, (
        "budget no longer forces the streaming path; shrink it"
    )
    qt_s, kt_s = PK._fit_stream_tiles(L, L, d, 4, 256, 2048)
    assert kt_s > 256, f"streaming k_tile collapsed to the floor ({kt_s})"
    try:
        got_s = np.asarray(PK.flash_attention_pallas(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True,
            interpret=True,
        ))
    finally:
        PK.flash_attention_pallas.clear_cache()
        PK._flash_attention_block_jit.clear_cache()
    assert np.isfinite(got_s).all()
    np.testing.assert_allclose(got_s, ref, atol=5e-5)


@pytest.mark.parametrize("causal,skip_tile", [
    (False, None), (True, None), (True, 64), (True, 16),
])
def test_flash_streaming_kv_path(causal, skip_tile, monkeypatch):
    """When full K/V residency exceeds the VMEM budget the kernel falls
    back to streaming K/V tiles over a 2-D grid (accumulators resident
    across the inner dimension) — unbounded sequence length on one chip
    (verified at L=32768 d=128 on real hardware, BASELINE.md). Forced
    here by shrinking the budget so small shapes take the streaming path;
    L=1024 with the 256-key tile floor gives 4 inner grid steps, so the
    j>0 carry fold (the kernel's novel logic) actually executes.
    skip_tile ∈ {64, 16} (round 5) exercises the streaming three-regime
    split: mask-free fully-live cells + the boundary cell's masked
    sub-span loop (4 and 16 sub-spans per 256-wide tile)."""
    from tpu_mpi_tests.kernels import pallas_kernels as PK

    # the budget is read at TRACE time: clear the jit caches so earlier
    # resident-path traces of the same signature can't mask the patch
    # (and streaming-path traces can't leak to later tests)
    PK.flash_attention_pallas.clear_cache()
    PK._flash_attention_block_jit.clear_cache()
    monkeypatch.setattr(PK, "_VMEM_BUDGET_BYTES", 450_000)
    rng = np.random.default_rng(5)
    L, d = 1024, 64
    q, k, v = (rng.normal(size=(L, d)).astype(np.float32) for _ in range(3))
    try:
        got = np.asarray(PK.flash_attention_pallas(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal,
            skip_tile=skip_tile, interpret=True,
        ))
    finally:
        PK.flash_attention_pallas.clear_cache()
        PK._flash_attention_block_jit.clear_cache()
    ref = reference_attention(
        q.astype(np.float64), k.astype(np.float64), v.astype(np.float64),
        causal=causal,
    )
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, ref, atol=5e-5)


def test_striped_layout_roundtrip():
    """to_striped puts global token i·n + r at striped row r·L_loc + i;
    from_striped inverts it."""
    n, lloc = 8, 6
    x = np.arange(n * lloc * 3, dtype=np.float32).reshape(n * lloc, 3)
    s = np.asarray(R.to_striped(jnp.asarray(x), n))
    for r in range(n):
        for i in range(lloc):
            np.testing.assert_array_equal(s[r * lloc + i], x[i * n + r])
    np.testing.assert_array_equal(
        np.asarray(R.from_striped(jnp.asarray(s), n)), x
    )


@pytest.mark.parametrize("flash", [False, True])
def test_ring_attention_striped_matches_full(mesh8, flash):
    """Causal ring attention on the STRIPED (load-balanced) layout ==
    exact reference after the layout round-trip, both tiers (VERDICT r2
    weak #1: every rank now does ~half a block pair of useful work per
    ring step instead of rank n−1 pacing the ring)."""
    rng = np.random.default_rng(6)
    L, d = 8 * 16, 32
    q, k, v = (rng.normal(size=(L, d)).astype(np.float32) for _ in range(3))
    ref = reference_attention(
        q.astype(np.float64), k.astype(np.float64), v.astype(np.float64),
        causal=True,
    )

    attn = R.ring_attention_fn(
        mesh8, "shard", causal=True, flash=flash, stripe=True,
        interpret=True,
    )
    got_striped = np.asarray(
        attn(
            shard_1d(R.to_striped(jnp.asarray(q), 8), mesh8),
            shard_1d(R.to_striped(jnp.asarray(k), 8), mesh8),
            shard_1d(R.to_striped(jnp.asarray(v), 8), mesh8),
        )
    )
    got = np.asarray(R.from_striped(jnp.asarray(got_striped), 8))
    assert np.isfinite(got).all()
    assert np.allclose(got, ref, atol=2e-5)


def test_ring_attention_stripe_requires_causal(mesh8):
    with pytest.raises(ValueError, match="stripe"):
        R.ring_attention_fn(
            mesh8, "shard", causal=False, stripe=True, interpret=True
        )(
            shard_1d(jnp.zeros((8 * 4, 8), jnp.float32), mesh8),
            shard_1d(jnp.zeros((8 * 4, 8), jnp.float32), mesh8),
            shard_1d(jnp.zeros((8 * 4, 8), jnp.float32), mesh8),
        )
