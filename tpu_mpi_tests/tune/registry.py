"""Tunable-space declarations + the process-wide resolution state.

Spaces are declared WHERE THE KNOB LIVES (``comm/ring.py`` declares the
flash tile spaces, ``comm/halo.py`` the halo staging / resident-block /
k-group spaces, ``drivers/collbench.py`` the collective variants) by
calling :func:`declare_space` at import time; the numeric candidate
values come from :mod:`~tpu_mpi_tests.tune.priors` (rule TPM701 keeps
pinned schedule constants out of everywhere else). The first candidate
is the PRIOR: what a sweep tries first, and what resolution returns
when tuning is off and the cache has no entry — so a run with no cache
resolves byte-identically to the hand-pinned era.

Resolution precedence at EVERY site (gated by ``tests/test_tune.py``):

    explicit argument  >  cached winner  >  shipped prior

The cache is consulted only after :func:`configure` loaded one (drivers
do this from ``--tune-cache``/``TPU_MPI_TUNE_CACHE``; ``bench.py`` from
the env/default path) — bare library use never reads a cache file, so
tests and embedders see pure prior behavior unless they opt in.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Iterable

from tpu_mpi_tests.tune.cache import ScheduleCache, default_cache_path


@dataclass(frozen=True)
class TunableSpace:
    """One declared knob: its candidate schedules (prior FIRST) and a
    one-line rationale. Candidates are JSON-serializable by contract
    (ints, strings, or flat dicts of those) — they round-trip through
    the cache file."""

    knob: str
    candidates: tuple
    describe: str = ""

    @property
    def prior(self):
        """Cold-start winner: the shipped measured-best."""
        return self.candidates[0]


_SPACES: dict[str, TunableSpace] = {}
_SPACES_LOCK = threading.Lock()


def declare_space(
    knob: str, candidates: Iterable, describe: str = ""
) -> TunableSpace:
    """Register a tunable space (idempotent: redeclaring the same knob
    returns the existing space — modules declaring at import time may be
    re-imported under test runners)."""
    with _SPACES_LOCK:
        existing = _SPACES.get(knob)
        if existing is not None:
            return existing
        sp = TunableSpace(knob, tuple(candidates), describe)
        if not sp.candidates:
            raise ValueError(f"tunable space {knob!r} has no candidates")
        _SPACES[knob] = sp
        return sp


def space(knob: str) -> TunableSpace:
    # import for side effect: the knob owners declare their spaces at
    # import time, and asking for a space must not depend on whether the
    # caller happened to import the owning module first
    _import_knob_owners()
    return _SPACES[knob]


def spaces() -> dict[str, TunableSpace]:
    _import_knob_owners()
    return dict(_SPACES)


def _import_knob_owners() -> None:
    """Import every module that declares spaces. Lazy (not at this
    module's import) so the registry itself stays importable without
    jax; the owners all import jax."""
    import tpu_mpi_tests.comm.collectives  # noqa: F401
    import tpu_mpi_tests.comm.embedding  # noqa: F401
    import tpu_mpi_tests.comm.halo  # noqa: F401
    import tpu_mpi_tests.comm.moe  # noqa: F401
    import tpu_mpi_tests.comm.ring  # noqa: F401
    import tpu_mpi_tests.drivers.collbench  # noqa: F401
    import tpu_mpi_tests.workloads.daxpy  # noqa: F401


class _State:
    def __init__(self):
        self.cache: ScheduleCache | None = None
        self.enabled = False
        self.budget_s: float | None = None
        self.emit: Callable[[dict], None] | None = None


_STATE = _State()
_STATE_LOCK = threading.Lock()


def configure(
    cache_path: str | None = None,
    enabled: bool = False,
    budget_s: float | None = None,
    emit: Callable[[dict], None] | None = None,
) -> ScheduleCache:
    """Load the schedule cache and set the process-wide tuning switches.

    ``cache_path=None`` resolves ``TPU_MPI_TUNE_CACHE`` then the default
    ``~/.cache/tpumt/tune.json``; a missing/corrupted file loads as
    empty (priors apply). ``enabled`` arms on-miss sweeps
    (:func:`~tpu_mpi_tests.tune.sweep.ensure_tuned`); lookups of an
    existing cache work regardless, which is how ``bench.py`` consults a
    warmed cache without any flag. ``emit`` is the default JSONL sink
    for sweep records (a driver passes its Reporter's).

    Multi-process runs get ONE cache writer: non-zero ranks load and
    resolve like any other, but their cache is marked read-only so no
    code path (a fleet sweep, bench's on-miss sweep, the serve-loop
    re-tune controller) can ever interleave a merge-on-write save with
    rank 0's on a shared homedir — the winner every rank applies
    arrives by broadcast, not through the file."""
    with _STATE_LOCK:
        _STATE.cache = ScheduleCache.load(cache_path or default_cache_path())
        _STATE.cache.read_only = _nonzero_rank()
        _STATE.enabled = bool(enabled)
        _STATE.budget_s = budget_s
        _STATE.emit = emit
        return _STATE.cache


def _nonzero_rank() -> bool:
    """True on the non-writer ranks of a multi-process run. Reads the
    jax.distributed process-global state only (set by
    ``jax.distributed.initialize`` / ``comm.mesh.bootstrap``) — never
    initializes a backend, and answers False wherever jax itself is
    absent, so stdlib/login-node callers are untouched."""
    try:
        from jax._src import distributed

        st = distributed.global_state
        return bool(st.num_processes and st.num_processes > 1
                    and st.process_id)
    except Exception:
        return False


def mark_fleet_rank() -> None:
    """Re-evaluate the single-writer marking. Drivers call
    :func:`configure` from ``setup_platform`` BEFORE
    ``comm.mesh.bootstrap`` initializes jax.distributed, so the
    configure-time check sees an uninitialized state and every rank
    looks like a writer; ``drivers/_common.make_reporter`` (which runs
    after bootstrap on every driver path) calls this to apply the
    marking once the process-global rank is actually known."""
    with _STATE_LOCK:
        if _STATE.cache is not None and _nonzero_rank():
            _STATE.cache.read_only = True


def deconfigure() -> None:
    """Back to the unconfigured state (tests)."""
    with _STATE_LOCK:
        _STATE.cache = None
        _STATE.enabled = False
        _STATE.budget_s = None
        _STATE.emit = None


def configured_cache() -> ScheduleCache | None:
    return _STATE.cache


def tuning_enabled() -> bool:
    return _STATE.enabled


def tune_budget_s() -> float | None:
    return _STATE.budget_s


def default_emit() -> Callable[[dict], None] | None:
    return _STATE.emit


def set_emit(emit: Callable[[dict], None] | None) -> None:
    """Install the default sweep-record sink after configuration (the
    driver's Reporter exists only later than ``setup_platform``)."""
    with _STATE_LOCK:
        _STATE.emit = emit


def lookup(knob: str, device_fallback: bool = True, **ctx) -> Any | None:
    """The cached winner for ``knob`` under the caller's context, or
    None. Tries the full fingerprint first, then (``device_fallback``,
    the default) the device-only fingerprint — sweeps store both, so
    context-free sites like the flash kernel can still hit a winner
    tuned with full context. Sites whose optimum is context-SENSITIVE
    (a dtype-keyed block count: the f32 winner is measured-wrong at
    bf16) pass ``device_fallback=False`` so a sibling context's winner
    can never leak in. Touches the jax backend only when a non-empty
    cache is actually loaded."""
    cache = _STATE.cache
    if cache is None or not len(cache):
        return None
    from tpu_mpi_tests.tune.fingerprint import device_fingerprint, fingerprint

    val = cache.lookup(knob, fingerprint(**ctx))
    if val is None and ctx and device_fallback:
        val = cache.lookup(knob, device_fingerprint())
    return val


def resolve(knob: str, explicit=None, prior=None,
            device_fallback: bool = True, **ctx):
    """The value a knob site should use: ``explicit`` when the caller
    was given one (CLI flag / env var / function argument), else the
    cached winner, else ``prior`` (defaulting to the declared space's
    first candidate). This is THE precedence order — explicit > cached
    > prior — at every site."""
    if explicit is not None:
        return explicit
    cached = lookup(knob, device_fallback=device_fallback, **ctx)
    if cached is not None:
        return cached
    if prior is not None:
        return prior
    return space(knob).prior


def preload() -> dict[str, Any]:
    """Warm the resolution path before steady-state traffic opens.

    Serve mode (``drivers/serve.py``) calls this once at startup: it
    imports every knob-owning module (their ``declare_space`` calls run
    now, not on the first request), touches the loaded cache so the
    device-fingerprint computation happens up front, and returns the
    device-level resolution of every declared space — cached winner
    where the warmed cache has one, shipped prior otherwise. Context-
    sensitive sites still re-resolve with their full context at use
    time (precedence unchanged); this pass exists so no first request
    pays a cold import, cache read, or fingerprint build inside its
    measured latency."""
    _import_knob_owners()
    return {knob: resolve(knob) for knob in sorted(_SPACES)}
