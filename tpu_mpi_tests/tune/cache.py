"""Persistent schedule cache: one JSON file mapping (knob, fingerprint)
to a measured winner.

Default location ``~/.cache/tpumt/tune.json`` (override:
``--tune-cache PATH`` / ``TPU_MPI_TUNE_CACHE``). The file is versioned;
a corrupted, unreadable, or version-mismatched file degrades to an
empty cache — resolvers then fall back to the shipped priors, never
crash a run over a stale artifact (gated by ``tests/test_tune.py``).
Writes are atomic (tmp + rename) so a killed sweep cannot leave a
half-written file for the next run to choke on.

Entry shape (JSON-serializable by contract — candidates are ints,
strings, or flat dicts of those)::

    {"version": 1,
     "entries": {"<knob>|<fingerprint>": {
         "value": <winner>, "seconds": <measured best>,
         "knob": ..., "fingerprint": ...}}}
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from pathlib import Path
from typing import Any

CACHE_VERSION = 1

#: env override for every consumer (drivers expose ``--tune-cache`` on
#: top; ``bench.py`` has no argparse and reads only this)
CACHE_ENV = "TPU_MPI_TUNE_CACHE"


def default_cache_path() -> str:
    """``$TPU_MPI_TUNE_CACHE``, else ``~/.cache/tpumt/tune.json``
    (honoring ``XDG_CACHE_HOME``)."""
    env = os.environ.get(CACHE_ENV)
    if env:
        return env
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return os.path.join(base, "tpumt", "tune.json")


def _key(knob: str, fingerprint: str) -> str:
    return f"{knob}|{fingerprint}"


class ScheduleCache:
    """In-memory view of one cache file. ``load`` never raises on bad
    content; ``save`` is atomic and merge-on-write (a concurrent sweep
    of a DIFFERENT knob on the same file loses nothing).

    ``read_only`` makes :meth:`save` a no-op: the multi-process
    single-writer contract (ISSUE 14). Every rank of a fleet run loads
    and resolves from the shared file, but only rank 0 may write it —
    N ranks' merge-on-write saves interleaving on one shared homedir is
    exactly the race the atomic rename cannot fix (each rename is
    atomic; the read-merge-write sequences still clobber each other).
    ``tune.registry.configure`` marks non-zero ranks read-only."""

    def __init__(self, path: str):
        self.path = str(path)
        self.entries: dict[str, dict[str, Any]] = {}
        self.read_only = False
        self._lock = threading.Lock()

    @classmethod
    def load(cls, path: str) -> "ScheduleCache":
        cache = cls(path)
        cache.entries = cls._read_entries(path)
        return cache

    @staticmethod
    def _read_entries(path: str) -> dict[str, dict[str, Any]]:
        try:
            doc = json.loads(Path(path).read_text())
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            return {}
        if (
            not isinstance(doc, dict)
            or doc.get("version") != CACHE_VERSION
            or not isinstance(doc.get("entries"), dict)
        ):
            return {}  # stale/foreign format: priors, not a crash
        return {
            k: v for k, v in doc["entries"].items() if isinstance(v, dict)
        }

    def lookup(self, knob: str, fingerprint: str):
        """The cached winner value, or None. (None is never a valid
        winner — candidates are concrete schedules.)"""
        entry = self.entries.get(_key(knob, fingerprint))
        return None if entry is None else entry.get("value")

    def store(
        self,
        knob: str,
        fingerprint: str,
        value,
        seconds: float | None = None,
        **extra,
    ) -> None:
        entry = {
            "value": value,
            "seconds": seconds,
            "knob": knob,
            "fingerprint": fingerprint,
            # measurement time: what `tpumt-tune merge`'s
            # newer-measurement-wins rule arbitrates conflicts with
            # (pre-timestamp entries read as oldest)
            "t": time.time(),
            **extra,
        }
        with self._lock:
            self.entries[_key(knob, fingerprint)] = entry

    def save(self) -> None:
        """Atomic write, merged over the file's current content so
        concurrent writers of disjoint keys compose. A ``read_only``
        cache (non-zero ranks of a fleet run) never writes."""
        if self.read_only:
            return
        with self._lock:
            merged = self._read_entries(self.path)
            merged.update(self.entries)
            doc = {"version": CACHE_VERSION, "entries": merged}
            directory = os.path.dirname(self.path) or "."
            os.makedirs(directory, exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=directory, prefix=".tune.", suffix=".tmp"
            )
            try:
                with os.fdopen(fd, "w") as fh:
                    json.dump(doc, fh, indent=1, sort_keys=True)
                    fh.write("\n")
                os.replace(tmp, self.path)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            self.entries = merged

    def __len__(self) -> int:
        return len(self.entries)
