"""Online re-tune controller: close observability → schedule → SLO
(ISSUE 14 tentpole c).

The pieces this joins were built waiting for it: the metrics tee (PR 11)
latches ``kind:"health" event:"tune_stale"`` when an op's rolling
achieved GB/s (or ``roofline_frac``) sags below the tuned winner's own
fresh baseline, and the serve loop (PR 6) has a natural between-windows
point where nothing is mid-batch. The controller subscribes to the
stale latch, and at the next window boundary runs a BOUNDED re-sweep of
the sagging class's knob — quarantine-style degraded service: arrivals
keep queueing while it runs, the watchdog stays armed, and the budget
is the batch deadline — then hot-swaps the handler through
``registry.resolve`` (the re-sweep persisted a new winner, so a rebuild
with no explicit value picks it up) and emits a ``kind:"control"
event:"tune_swap"`` record. ``tpumt-report`` renders those as the
CONTROL table, ``tpumt-trace`` places them as instant markers, and
``tpumt-doctor`` convicts ``stale_schedule`` exactly where a stale
latch was left UNanswered.

Handler contract (``drivers/_common.py`` workload registry): a serve
factory that wants closed-loop re-tuning attaches ``step.tune_info``::

    step.tune_info = {
        "knob": "coll_variant/allreduce",   # the declared space
        "ctx": {...},                       # its fingerprint context
        "candidates": (...),                # or None = the space's
        "rebuild": callable(value) -> step  # compile a new handler;
    }                                       # value None = re-resolve

``rebuild`` must return a warmed handler (the factory contract already
requires it) and re-attach ``tune_info`` so a swapped class can be
re-tuned again later. Classes without ``tune_info`` are simply never
re-tuned — the controller degrades to a no-op, never an error.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from tpu_mpi_tests.tune import registry
from tpu_mpi_tests.tune.sweep import sweep

#: how many coalesced requests the re-sweep's probe batch executes per
#: candidate measurement — long enough to clear dispatch noise, short
#: enough that candidates × probe stays inside a batch deadline
PROBE_REQUESTS = 4

#: failed re-tunes retried at later window boundaries before giving up
#: — the stale latch is one-shot per op, so abandoning on the first
#: transient rebuild error would leave the loop silently open for good
RETUNE_RETRIES = 2


class TuneController:
    """Latches ``tune_stale`` health events and answers each with a
    between-windows re-sweep + hot swap. Single-threaded apply: the
    latch callback only records (any thread); all re-tuning happens in
    :meth:`window_boundary` on the serve loop's thread."""

    def __init__(
        self,
        metrics,
        handlers: dict[str, Callable],
        *,
        sink: Callable[[dict], None] | None = None,
        line: Callable[[str], None] = print,
        budget_s: float | None = None,
        watchdog=None,
        probe_requests: int = PROBE_REQUESTS,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
    ):
        self._metrics = metrics
        self._handlers = handlers  # the LIVE dict the loop dispatches from
        self._sink = sink
        self._line = line
        self._budget = budget_s
        self._watchdog = watchdog
        self._probe_n = max(1, int(probe_requests))
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        self._pending: list[dict] = []
        self._retries: dict[str, int] = {}  # op -> failed attempts
        self.swaps = 0
        if metrics is not None:
            metrics.add_health_listener(self._on_health)

    # -- latch (any thread) ------------------------------------------------

    def _on_health(self, rec: dict) -> None:
        if rec.get("event") != "tune_stale":
            return
        with self._lock:
            self._pending.append(dict(rec))

    # -- apply (the serve loop's thread, between windows) ------------------

    def _class_key(self, op) -> str | None:
        """A stale span op → the serve class it belongs to. Request
        spans are ``serve:<class>`` (serve/loop.py); anything else
        (an op inside a handler) has no handler to rebuild."""
        if isinstance(op, str) and op.startswith("serve:"):
            key = op[len("serve:"):]
            if key in self._handlers:
                return key
        return None

    def window_boundary(self, t_wall: float) -> int:
        """Drain the latched stale events; re-sweep + hot-swap each
        class that exposes a ``tune_info`` recipe. Returns how many
        swaps happened (0 on the overwhelmingly common quiet path)."""
        with self._lock:
            pending, self._pending = self._pending, []
        swapped = 0
        for stale in pending:
            key = self._class_key(stale.get("op"))
            if key is None:
                continue
            info = getattr(self._handlers[key], "tune_info", None)
            if not info:
                continue
            swapped += self._retune(key, info, stale, t_wall)
        return swapped

    def _retune(self, key: str, info: dict, stale: dict,
                t_wall: float) -> int:
        knob = info["knob"]
        ctx = dict(info.get("ctx") or {})
        candidates = info.get("candidates")
        rebuild = info["rebuild"]

        def _guarded(fn, *args):
            # the watchdog is re-armed PER candidate (and per rebuild):
            # the whole re-sweep legitimately runs up to budget + one
            # candidate, and the budget often IS the batch deadline —
            # arming once across the sweep would hard-exit a healthy
            # budget-saturating re-sweep, while per-dispatch arming
            # still catches a genuinely wedged rebuild/probe
            if self._watchdog is not None:
                self._watchdog.arm(f"serve:retune:{key}")
            try:
                return fn(*args)
            finally:
                if self._watchdog is not None:
                    self._watchdog.disarm()

        t0 = self._clock()
        try:
            old = registry.resolve(
                knob, prior=(candidates[0] if candidates else None),
                **ctx)
            # the real sweep engine: sync-honest candidate windows,
            # budget-capped with reported skips, winner persisted (rank
            # 0 is the only writer; serve mode is single-process) — each
            # candidate is a freshly compiled handler timed over a probe
            # batch, exactly what the class's latency is made of
            def measure(cand):
                step = _guarded(rebuild, cand)
                t = time.perf_counter()
                _guarded(step, self._probe_n)  # blocks by contract
                return time.perf_counter() - t

            winner = sweep(
                knob, measure,
                candidates=candidates,
                budget_s=self._budget,
                emit=self._sink,
                **ctx,
            )
            # hot swap THROUGH registry.resolve: rebuild(None)
            # re-resolves the knob, which now hits the re-swept winner
            new_step = _guarded(rebuild, None)
        except Exception as e:  # a failed re-tune must not kill serving
            self._line(f"RETUNE ERROR {key}: {type(e).__name__}: {e}")
            # the tune_stale latch is ONE-SHOT per op: dropping this
            # event would disable re-tuning for the op forever. Retry
            # at later window boundaries; once the retries are spent,
            # re-baseline the watch so a sustained sag can latch again
            # instead of the loop staying silently open.
            op = str(stale.get("op"))
            tries = self._retries.get(op, 0) + 1
            self._retries[op] = tries
            if tries <= RETUNE_RETRIES:
                with self._lock:
                    self._pending.append(stale)
            else:
                # retries spent: clear the counter so a FUTURE episode
                # gets the full retry budget again, and re-baseline the
                # watch so a sustained sag can re-latch
                self._retries.pop(op, None)
                if self._metrics is not None:
                    self._metrics.reset_stale(op)
            return 0
        resweep_s = self._clock() - t0
        self._handlers[key] = new_step
        self.swaps += 1
        self._retries.pop(str(stale.get("op")), None)
        if self._metrics is not None:
            # re-baseline the op on the new schedule so recovery is
            # measurable and a future sag can latch again
            self._metrics.reset_stale(str(stale.get("op")))
        rec = {
            "kind": "control",
            "event": "tune_swap",
            "class": key,
            "knob": knob,
            "op": stale.get("op"),
            "signal": stale.get("signal"),
            "sag_pct": stale.get("sag_pct"),
            "old": old,
            "new": winner,
            "resweep_s": resweep_s,
            "t": t_wall,
        }
        if self._sink is not None:
            self._sink(rec)
        self._line(
            f"RETUNE {key}: {knob} {old!r} -> {winner!r} "
            f"(sag={stale.get('sag_pct')}% signal={stale.get('signal')} "
            f"resweep={resweep_s:.2f}s)"
        )
        return 1
