"""Measured autotuner + persistent schedule cache for every hot-path knob.

The paper's core observation is that the fastest comm/compute schedule is
machine- and layout-dependent (staging strategy, tile widths, exchange
flavor — SURVEY §2), yet the repo's fastest numbers historically rode
hand-pinned constants from one-off sweeps (``MEASURED_BEST_*`` tables,
``TPU_MPI_BENCH_BLOCKS``, halo ``Staging``). This package is the
XLA/Triton-style answer: an on-device sweep engine whose results persist,
so every topology re-derives its own optimum once and reuses it forever.

Pieces (each importable without jax at module scope — jax loads lazily
only when a fingerprint actually needs the live backend):

* :mod:`~tpu_mpi_tests.tune.priors` — the shipped measured-best tables,
  demoted to cold-start priors: the first candidates a sweep tries, and
  the fallback when tuning is disabled or the cache is absent, so
  behavior without ``--tune`` and without a cache is byte-identical to
  the hand-pinned era. The ONLY sanctioned home for numeric schedule
  constants (enforced by lint rule TPM701).
* :mod:`~tpu_mpi_tests.tune.fingerprint` — the cache key: device kind,
  platform, mesh/topology shape, dtype, shape-bucket.
* :mod:`~tpu_mpi_tests.tune.cache` — JSON persistence
  (``~/.cache/tpumt/tune.json`` or ``--tune-cache PATH``); corrupted or
  version-mismatched files fall back to priors, never crash.
* :mod:`~tpu_mpi_tests.tune.registry` — tunable-space declarations
  (spaces are declared WHERE THE KNOB LIVES — comm/ring.py declares the
  flash tile spaces, comm/halo.py the staging/blocks/steps spaces,
  drivers/collbench.py the collective variants) plus the process-wide
  resolution state. Precedence at every site: explicit > cached > prior.
* :mod:`~tpu_mpi_tests.tune.sweep` — the measured sweep: sync-honest
  candidate timing windows (``instrument.timers.block`` discipline,
  ``comm_span`` wrapping so ``tpumt-trace`` shows sweep windows), a
  ``--tune-budget`` wall-clock cap with reported (never silent) skips,
  JSONL ``tune``/``tune_result``/``tune_hit`` records for
  ``tpumt-report``'s tuning table, and winner persistence.
"""

from tpu_mpi_tests.tune.registry import (  # noqa: F401
    configure,
    configured_cache,
    declare_space,
    lookup,
    resolve,
    space,
    spaces,
    tuning_enabled,
)
from tpu_mpi_tests.tune.sweep import ensure_tuned, sweep  # noqa: F401
