"""Fleet broadcast transport: replicate rank-0 decisions to every process.

The rank-0-swept, broadcast-applied sweep protocol (ISSUE 14 tentpole a)
needs exactly one primitive: a small JSON-serializable value computed on
process 0 delivered verbatim to every process, at a point every process
reaches together. :func:`bcast` is that primitive, and the ONLY sanctioned
shape for consuming it is the TPM1301 shape the broadcast-consistency
rule was built to police::

    if jax.process_index() == 0:
        decision = ...          # only rank 0 computed the real value
    else:
        decision = None         # placeholder, not a value
    decision = bcast(decision, tag)   # now identical on every rank

(The helper is deliberately named ``bcast`` — one of the curated
``BROADCAST_CALLS`` the analyzer recognizes as a replication point — so
the shipped protocol lints clean while a mutant that drops the broadcast
is convicted; ``tests/test_lint.py`` seeds exactly that mutant.)

Two transports, probed once per process:

* **device** — ``multihost_utils.broadcast_one_to_all`` over a
  fixed-size length-prefixed ``uint8`` buffer: the documented jax
  multihost path, used on real TPU pods.
* **kv** — the ``jax.distributed`` coordination-service key-value store
  (the same service ``jax.distributed.initialize`` stands up for every
  multi-process run): rank 0 ``key_value_set``s the payload under a
  sequence-numbered key, every other rank blocks on
  ``blocking_key_value_get``. This is the fallback where the backend has
  no cross-process device collectives (this repo's CI image: the CPU
  backend raises ``Multiprocess computations aren't implemented``), and
  it is what ``make fleet-smoke`` exercises.

Key sequencing relies on the SPMD contract the sweep protocol already
guarantees: every process calls :func:`bcast` the same number of times
in the same order, so the per-process counters agree and keys collide
never. A process where neither transport exists raises
:class:`FleetUnavailable` — callers degrade to the PR-4 skip contract
(record the skip, resolve cached > prior) instead of diverging.
"""

from __future__ import annotations

import itertools
import json
import os
import struct

#: fixed device-broadcast buffer: 4-byte little-endian length prefix +
#: payload. Schedule values are ints/strings/flat dicts by the cache
#: contract — a decision that does not fit here is a bug, not a payload.
MAX_PAYLOAD = 4096

#: how long a non-zero rank waits on a rank-0 KV decision before giving
#: up (seconds; ``TPU_MPI_FLEET_TIMEOUT_S`` overrides). Generous by
#: design: the ranks measure the same candidates at the same time, so
#: the wait is bounded by cross-rank measurement skew, not sweep length.
KV_TIMEOUT_S = 600.0

_SEQ = itertools.count()
_TRANSPORT: str | None = None  # "device" | "kv", decided at first use


class FleetUnavailable(RuntimeError):
    """No broadcast transport exists in this process: device collectives
    unavailable AND no coordination-service client. Callers fall back to
    the single-process-era skip contract."""


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def _encode(obj) -> str:
    return json.dumps(obj)


def _device_bcast(payload: str) -> str:
    """``broadcast_one_to_all`` of a length-prefixed uint8 buffer. Every
    process passes the same-shape buffer (receivers' contents are
    ignored), so the call is SPMD-symmetric by construction."""
    import numpy as np
    from jax.experimental import multihost_utils

    data = payload.encode("utf-8")
    if len(data) > MAX_PAYLOAD - 4:
        raise ValueError(
            f"fleet broadcast payload of {len(data)} bytes exceeds "
            f"{MAX_PAYLOAD - 4} (schedule decisions are tiny by the "
            f"cache contract)"
        )
    buf = np.zeros(MAX_PAYLOAD, np.uint8)
    buf[:4] = np.frombuffer(struct.pack("<I", len(data)), np.uint8)
    buf[4:4 + len(data)] = np.frombuffer(data, np.uint8)
    out = np.asarray(multihost_utils.broadcast_one_to_all(buf), np.uint8)
    n = struct.unpack("<I", out[:4].tobytes())[0]
    return out[4:4 + n].tobytes().decode("utf-8")


def _kv_client():
    """The jax.distributed coordination-service client, or None. Reads
    process-global distributed state only — never initializes a
    backend."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:
        return None


def _kv_bcast(payload: str, key: str) -> str:
    client = _kv_client()
    if client is None:
        raise FleetUnavailable(
            "no fleet broadcast transport: device collectives are "
            "unavailable on this backend and the jax.distributed "
            "coordination client is not initialized"
        )
    timeout_ms = int(
        float(os.environ.get("TPU_MPI_FLEET_TIMEOUT_S", KV_TIMEOUT_S))
        * 1000
    )
    if process_index() == 0:
        client.key_value_set(key, payload)
        return payload
    return client.blocking_key_value_get(key, timeout_ms)


def bcast(obj, tag: str = ""):
    """Replicate rank 0's JSON-serializable ``obj`` to every process.

    Single-process: identity (after a JSON round-trip on neither path —
    the value is returned as-is). Multi-process: the device transport is
    tried once; a backend without cross-process collectives permanently
    falls back to the coordination-service KV store. A transport that
    worked once is never silently switched mid-run — a failure after
    that propagates, because half a fleet changing transports is a
    divergence, not a degradation.

    Every process MUST call this the same number of times in the same
    order (the sweep protocol guarantees it); the shared sequence
    counter is what keys the KV path."""
    global _TRANSPORT
    if process_count() <= 1:
        return obj
    seq = next(_SEQ)
    payload = _encode(obj)
    if _TRANSPORT in (None, "device"):
        try:
            out = _device_bcast(payload)
            _TRANSPORT = "device"
            return json.loads(out)
        except ValueError:
            raise  # oversized payload: a bug on every transport
        except Exception:
            if _TRANSPORT == "device":
                raise  # worked before: do not silently switch mid-run
    out = _kv_bcast(payload, f"tpumt/tune/{tag}/{seq}")
    _TRANSPORT = "kv"
    return json.loads(out)


def _reset_transport_for_tests() -> None:
    global _TRANSPORT
    _TRANSPORT = None
