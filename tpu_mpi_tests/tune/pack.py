"""``tpumt-tune``: portable schedule packs — tune once, ship the
schedule with the deployment (ISSUE 14 tentpole b).

A schedule *pack* is a fingerprint-keyed artifact exported from a tune
cache: the measured winners plus provenance (device kinds, world sizes,
process counts, engine version) so a pack file says what hardware its
schedules are valid on months later. A fleet of identical topologies
tunes on ONE machine, packs the cache, and every deployment preloads
the artifact (``--tune-pack`` on any driver / ``tpumt-serve``) — the
fingerprint layer then guarantees a schedule only ever applies where it
was measured, exactly as if the cache file had been warmed locally.

Subcommands (stdlib-only — the login-node contract of the sibling
CLIs; also runnable uninstalled as ``python -m tpu_mpi_tests.tune.pack``):

* ``pack [--cache PATH] -o PACK`` — export a cache as a pack;
* ``merge A B -o OUT`` — union two packs; the same (knob, fingerprint)
  key measured in both resolves newer-measurement-wins (the per-entry
  ``t`` stamp the cache writes), and every such conflict is reported;
* ``import PACK [--cache PATH] [--dry-run]`` — merge a pack into a
  cache file with the same conflict rule; ``--dry-run`` prints the
  add/update/keep diff without writing.

A corrupted, unreadable, or foreign-format pack degrades to an empty
one (reported, never a crash) — the same contract as the cache file.

Artifact shape::

    {"version": 1, "kind": "tpumt-tune-pack",
     "engine": "<tpu-mpi-tests version>",
     "provenance": {"devices": [...], "platforms": [...],
                    "worlds": [...], "procs": [...],
                    "knobs": [...], "topologies": [...], "entries": N},
     "entries": {"<knob>|<fingerprint>": {value, seconds, knob,
                                          fingerprint, t}}}
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tpu_mpi_tests.tune.cache import ScheduleCache, default_cache_path

PACK_VERSION = 1
PACK_KIND = "tpumt-tune-pack"


def _engine_version() -> str:
    try:
        from importlib.metadata import version

        return version("tpu-mpi-tests")
    except Exception:
        return "uninstalled"


def _fp_fields(fp: str) -> dict[str, str]:
    """``k=v;k=v`` fingerprint → dict (malformed parts skipped)."""
    out: dict[str, str] = {}
    for part in (fp or "").split(";"):
        k, sep, v = part.partition("=")
        if sep:
            out[k] = v
    return out


def _fp_topology(fields: dict[str, str]) -> str:
    """Topology shape label of one fingerprint: ``h{hosts}x{rph}``
    from the topology key fields (tune/fingerprint stamps them only on
    non-flat machines), ``flat`` when absent — absent fields mean a
    single-host measurement, by the discovery degrade contract."""
    hosts = fields.get("hosts")
    if not hosts:
        return "flat"
    rph = fields.get("rph")
    return f"h{hosts}" + (f"x{rph}" if rph else "")


def entry_topologies(entries: dict) -> set[str]:
    """The set of topology shape labels a pack/cache's entries were
    measured on (see :func:`_fp_topology`)."""
    topos: set[str] = set()
    for key, e in entries.items():
        if not isinstance(e, dict):
            continue
        topos.add(_fp_topology(_fp_fields(
            e.get("fingerprint") or key.split("|", 1)[-1]
        )))
    return topos


def provenance(entries: dict) -> dict:
    """What hardware/topology these winners were measured on, read back
    out of the fingerprints the sweeps stored them under."""
    devices: set[str] = set()
    platforms: set[str] = set()
    worlds: set[str] = set()
    procs: set[str] = set()
    knobs: set[str] = set()
    for key, e in entries.items():
        if not isinstance(e, dict):
            continue
        knobs.add(e.get("knob") or key.split("|", 1)[0])
        f = _fp_fields(e.get("fingerprint")
                       or key.split("|", 1)[-1])
        for field, dst in (("device", devices), ("platform", platforms),
                           ("ndev", worlds), ("procs", procs)):
            if field in f:
                dst.add(f[field])
    return {
        "devices": sorted(devices),
        "platforms": sorted(platforms),
        "worlds": sorted(worlds),
        "procs": sorted(procs),
        "knobs": sorted(knobs),
        "topologies": sorted(entry_topologies(entries)),
        "entries": len(entries),
    }


def make_pack(entries: dict) -> dict:
    return {
        "version": PACK_VERSION,
        "kind": PACK_KIND,
        "engine": _engine_version(),
        "provenance": provenance(entries),
        "entries": dict(entries),
    }


def load_pack(path: str) -> dict:
    """A pack document from ``path``; corrupted/foreign content degrades
    to an empty pack (``entries == {}``) so a stale artifact can never
    crash a deployment that ships it."""
    try:
        doc = json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return make_pack({})
    if (
        not isinstance(doc, dict)
        or doc.get("version") != PACK_VERSION
        or doc.get("kind") != PACK_KIND
        or not isinstance(doc.get("entries"), dict)
    ):
        return make_pack({})
    doc["entries"] = {
        k: v for k, v in doc["entries"].items() if isinstance(v, dict)
    }
    return doc


def _stamp(entry: dict) -> float:
    t = entry.get("t")
    return float(t) if isinstance(t, (int, float)) else 0.0


def merge_entries(
    a: dict, b: dict
) -> tuple[dict, list[tuple[str, dict, dict]]]:
    """Union of two entry maps. A key present in both with a different
    value is a CONFLICT: the newer measurement (the ``t`` stamp; a
    pre-timestamp entry reads as oldest) wins, and the conflict is
    returned as ``(key, kept, dropped)`` so callers report it — two
    fleets that measured different winners for one fingerprint is a
    fact worth surfacing, not silently averaging away."""
    merged = dict(a)
    conflicts: list[tuple[str, dict, dict]] = []
    for key, eb in b.items():
        ea = merged.get(key)
        if ea is None:
            merged[key] = eb
            continue
        if ea.get("value") == eb.get("value"):
            # same winner: keep the newer measurement metadata
            if _stamp(eb) > _stamp(ea):
                merged[key] = eb
            continue
        kept, dropped = (ea, eb) if _stamp(ea) >= _stamp(eb) else (eb, ea)
        merged[key] = kept
        conflicts.append((key, kept, dropped))
    return merged, conflicts


def absorb(cache: ScheduleCache, pack_doc: dict) -> int:
    """Preload a pack into a live in-memory cache (the ``--tune-pack``
    driver path): pack entries fill the gaps, conflicts resolve
    newer-measurement-wins. Returns how many entries were adopted. No
    disk write happens here — non-zero fleet ranks hold read-only
    caches, and rank 0 persists only when a sweep actually runs."""
    merged, _ = merge_entries(cache.entries, pack_doc.get("entries", {}))
    adopted = sum(
        1 for k, v in merged.items() if cache.entries.get(k) != v
    )
    cache.entries = merged
    return adopted


def _print_conflicts(conflicts) -> None:
    for key, kept, dropped in conflicts:
        print(
            f"CONFLICT {key}: kept={json.dumps(kept.get('value'))} "
            f"(t={_stamp(kept):.0f}) "
            f"dropped={json.dumps(dropped.get('value'))} "
            f"(t={_stamp(dropped):.0f}) — newer measurement wins"
        )


def _cmd_pack(args) -> int:
    cache_path = args.cache or default_cache_path()
    if not Path(cache_path).exists():
        print(f"tpumt-tune: no cache at {cache_path}", file=sys.stderr)
        return 2
    entries = ScheduleCache.load(cache_path).entries
    doc = make_pack(entries)
    Path(args.output).write_text(json.dumps(doc, indent=1,
                                            sort_keys=True) + "\n")
    p = doc["provenance"]
    print(f"PACK {args.output}: {p['entries']} entries, "
          f"{len(p['knobs'])} knobs, devices={','.join(p['devices']) or '-'} "
          f"worlds={','.join(p['worlds']) or '-'} "
          f"procs={','.join(p['procs']) or '-'} "
          f"topo={','.join(p.get('topologies') or []) or '-'} "
          f"engine={doc['engine']}")
    return 0


def _cmd_merge(args) -> int:
    packs = []
    for path in (args.a, args.b):
        if not Path(path).exists():
            print(f"tpumt-tune: no pack at {path}", file=sys.stderr)
            return 2
        doc = load_pack(path)
        if not doc["entries"]:
            print(f"NOTE {path}: empty or unreadable pack "
                  f"(corrupted packs degrade to empty)")
        packs.append(doc)
    merged, conflicts = merge_entries(packs[0]["entries"],
                                      packs[1]["entries"])
    _print_conflicts(conflicts)
    doc = make_pack(merged)
    Path(args.output).write_text(json.dumps(doc, indent=1,
                                            sort_keys=True) + "\n")
    print(f"MERGE {args.output}: {len(merged)} entries "
          f"({len(conflicts)} conflict(s) resolved newer-wins)")
    return 0


def _cmd_import(args) -> int:
    if not Path(args.pack).exists():
        print(f"tpumt-tune: no pack at {args.pack}", file=sys.stderr)
        return 2
    doc = load_pack(args.pack)
    if not doc["entries"]:
        print(f"NOTE {args.pack}: empty or unreadable pack "
              f"(corrupted packs degrade to empty)")
    cache_path = args.cache or default_cache_path()
    cache = ScheduleCache.load(cache_path)
    # topology gate (ISSUE 20): a pack measured on one slice shape
    # contributes nothing on a different shape (the fingerprints can
    # never match) — importing it anyway would only bloat the cache and
    # LOOK like a successful deployment. Disjoint non-empty shape sets
    # refuse with a NOTE; an empty destination cache has no shape
    # evidence and accepts (first import on a fresh machine).
    pack_topos = entry_topologies(doc["entries"])
    cache_topos = entry_topologies(cache.entries)
    if (pack_topos and cache_topos and not (pack_topos & cache_topos)
            and not args.allow_topology_mismatch):
        print(f"NOTE topology mismatch: pack measured on "
              f"{','.join(sorted(pack_topos))}, cache holds "
              f"{','.join(sorted(cache_topos))} entries — no schedule "
              f"could ever resolve; refusing import "
              f"(--allow-topology-mismatch to override)")
        return 3
    merged, conflicts = merge_entries(cache.entries, doc["entries"])
    added = [k for k in merged if k not in cache.entries]
    updated = [k for k in merged
               if k in cache.entries and merged[k] != cache.entries[k]]
    _print_conflicts(conflicts)
    for k in sorted(added):
        print(f"ADD  {k} = {json.dumps(merged[k].get('value'))}")
    for k in sorted(updated):
        print(f"UPD  {k} = {json.dumps(merged[k].get('value'))}")
    verb = "would write" if args.dry_run else "wrote"
    print(f"IMPORT {cache_path}: {len(added)} added, "
          f"{len(updated)} updated, "
          f"{len(merged) - len(added) - len(updated)} kept ({verb})")
    if not args.dry_run:
        cache.entries = merged
        cache.save()
    return 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tpumt-tune",
        description="portable schedule packs: export (pack), union "
        "(merge), and preload (import) fingerprint-keyed tuned-schedule "
        "artifacts so a fleet of identical topologies tunes once "
        "(README 'Fleet tuning')",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("pack", help="export a tune cache as a pack")
    sp.add_argument("--cache", default=None, metavar="PATH",
                    help="cache file to export (default: "
                    "$TPU_MPI_TUNE_CACHE, else ~/.cache/tpumt/tune.json)")
    sp.add_argument("-o", "--output", required=True, metavar="PACK",
                    help="pack file to write")
    sp.set_defaults(fn=_cmd_pack)

    sm = sub.add_parser("merge", help="union two packs (newer "
                        "measurement wins; conflicts reported)")
    sm.add_argument("a", help="first pack")
    sm.add_argument("b", help="second pack")
    sm.add_argument("-o", "--output", required=True, metavar="PACK",
                    help="merged pack to write")
    sm.set_defaults(fn=_cmd_merge)

    si = sub.add_parser("import", help="merge a pack into a cache file")
    si.add_argument("pack", help="pack file to import")
    si.add_argument("--cache", default=None, metavar="PATH",
                    help="cache file to import into (default: "
                    "$TPU_MPI_TUNE_CACHE, else ~/.cache/tpumt/tune.json)")
    si.add_argument("--dry-run", action="store_true",
                    help="print the add/update/keep diff without writing")
    si.add_argument("--allow-topology-mismatch", action="store_true",
                    help="import even when the pack's topology shape "
                    "labels share nothing with the destination cache's "
                    "(the entries still only resolve where their "
                    "fingerprints match)")
    si.set_defaults(fn=_cmd_import)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
