"""Schedule-cache keys: what has to match for a tuned winner to transfer.

A tuned schedule is only valid on the configuration it was measured on:
the paper's whole point is that the optimum moves with the hardware and
the layout. The fingerprint pins the axes that move it — device kind,
platform, mesh/topology shape (device + process counts), dtype, and a
power-of-two shape bucket (a 8192-row sweep should serve 8192 exactly,
not 8193; bucketing keeps near-identical shapes from fragmenting the
cache) — into one canonical ``k=v;k=v`` string.

Two layers:

* :func:`device_fields` — the live backend's identity (lazy ``import
  jax``; lru-cached per process). Callers that never consult a cache
  never touch it, so library-level resolution stays backend-free on the
  prior fast path.
* :func:`compose` — pure string composition from explicit fields, used
  directly by tests (fingerprint stability across process restarts is a
  gate: same inputs MUST give the same string, no id()/hash()/time
  leakage).

Call sites differ in how much context they have (the flash kernel knows
neither layout nor mesh; a driver knows everything), so lookups fall
back from the full fingerprint to the device-only fingerprint — sweeps
store their winner under both (:mod:`~tpu_mpi_tests.tune.sweep`).
"""

from __future__ import annotations

import functools


def shape_bucket(n: int) -> int:
    """Round ``n`` up to the next power of two (1 stays 1): the shape
    axis of the fingerprint. Exact shapes would fragment the cache over
    trivially-different lengths; pow2 buckets match how the schedules
    themselves scale (tile divisors, VMEM fits)."""
    n = int(n)
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


@functools.lru_cache(maxsize=None)
def device_fields() -> tuple[tuple[str, str], ...]:
    """The live backend's identity fields, probed once per process:
    platform, device kind, global device count, process count — plus
    the topology shape (hosts, ranks-per-host) when the machine is
    NOT flat. Flat/CPU fingerprints are unchanged (the PR-4 precedence
    contract and every existing cache entry stay intact); a winner
    measured on a 4-host slice resolves on any same-shape slice and
    never on a different one. Requires an initialized jax backend —
    only reached when a cache lookup or a sweep actually needs a key."""
    import jax

    from tpu_mpi_tests.comm.topology import current

    devs = jax.devices()
    fields = (
        ("platform", devs[0].platform),
        ("device", devs[0].device_kind.replace(";", ",")),
        # named ndev, not world: knob contexts pass their mesh-axis ring
        # size as `world` and must not silently overwrite the device count
        ("ndev", str(len(devs))),
        ("procs", str(jax.process_count())),
    )
    topo = current()
    if not topo.is_flat:
        fields += (("hosts", str(topo.num_hosts)),)
        if topo.ranks_per_host:
            fields += (("rph", str(topo.ranks_per_host)),)
    return fields


def compose(base: dict[str, str] | None = None, **ctx) -> str:
    """Canonical fingerprint string from explicit fields: sorted
    ``k=v`` pairs joined with ``;``. ``shape``-named integer fields are
    bucketed (:func:`shape_bucket`); everything else is stringified.
    Pure — the process-restart stability gate tests exactly this."""
    fields = dict(base or ())
    for k, v in ctx.items():
        if v is None:
            continue
        if k in ("shape", "lq", "n", "extent", "bytes") and not isinstance(
            v, str
        ):
            v = shape_bucket(v)
        fields[k] = str(v)
    return ";".join(f"{k}={fields[k]}" for k in sorted(fields))


def fingerprint(**ctx) -> str:
    """Full cache key: live device fields + the caller's context
    (dtype, shape bucket, layout, …)."""
    return compose(dict(device_fields()), **ctx)


def device_fingerprint() -> str:
    """Device-only key — the fallback slot context-free resolution
    sites (e.g. inside the flash kernel, which knows neither layout nor
    shape at its resolve point) can still hit."""
    return compose(dict(device_fields()))
