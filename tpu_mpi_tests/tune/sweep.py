"""The measured sweep: price candidate schedules on-device, persist the
winner, report every measurement.

Measurement discipline is the repo's standing one (SURVEY §7 hard part
2): candidates are timed sync-honestly — the caller's ``measure``
callable must end on a real device sync (``instrument.timers.block`` /
``chain_rate``; :func:`feedback_rate` below packages the donated-
feedback loop shape) — and each candidate window is wrapped in a
telemetry ``comm_span`` so ``tpumt-trace`` shows the sweep windows on
the cross-rank timeline when ``--telemetry`` is on.

Budget (``--tune-budget``) is a wall-clock cap across the candidate
list: the prior (first candidate) is ALWAYS measured, later candidates
are dropped when the budget is exhausted, and every drop is emitted as
a ``skipped`` record — a bounded sweep must never read as an exhaustive
one. An erroring candidate (e.g. a hand-ring kernel below its
lane-alignment floor on this shape) records its error and scores NaN
rather than killing the sweep.

JSONL records (rendered by ``tpumt-report``'s tuning table):

* ``{"kind": "tune", knob, candidate, seconds|skipped|error,
  fingerprint}`` — one per candidate;
* ``{"kind": "tune_result", knob, value, seconds, measured, skipped,
  fingerprint}`` — the persisted winner;
* ``{"kind": "tune_hit", knob, value, fingerprint}`` — a resolution
  served from the cache with no sweep (what ``make tune-smoke`` asserts
  on its second invocation).

Multi-process runs measure too (ISSUE 14): every rank runs every
candidate — the candidates dispatch collectives, so all ranks must be
present — but ONLY rank 0's timer decides. The per-candidate
continue/stop (the budget cutoff) and the final winner are replicated
to every rank through :func:`tpu_mpi_tests.tune.fleet.bcast` before any
rank acts on them, so the executed candidate sequence and the applied
schedule are identical on every rank BY CONSTRUCTION — the TPM1301
broadcast-consistency shape, dogfooded (a mutant that drops the winner
broadcast is a lint finding; ``tests/test_lint.py`` seeds it). The
winner is stored by rank 0 alone (the cache has ONE writer — see
:meth:`~tpu_mpi_tests.tune.cache.ScheduleCache.save`), per-candidate
``tune`` records are rank-0-only ("exactly one sweep"), and the
``tune_result`` record every rank emits is built once on rank 0 and
broadcast, so the per-rank JSONL streams carry byte-identical resolved
schedules. A fleet without any broadcast transport keeps the PR-4
contract: record the skip, resolve cached > prior.
"""

from __future__ import annotations

import time
from typing import Callable, Iterable

from tpu_mpi_tests.instrument.telemetry import comm_span
from tpu_mpi_tests.tune import registry
from tpu_mpi_tests.tune.fingerprint import device_fingerprint, fingerprint


def feedback_rate(fn, state, n_short: int = 4, n_long: int = 12):
    """Seconds per call of a donated single-step function, measured by
    feeding its output back as the next input (``state = fn(state)``)
    and differencing two run lengths — the host-loop analog of
    ``chain_rate`` for ops that can't carry a device-side ``fori_loop``
    (e.g. one ``halo_exchange`` dispatch, which donates its operand).
    Returns ``(seconds_per_call, final_state)``; NaN on a non-positive
    delta, like every other invalid measurement in this repo."""
    from tpu_mpi_tests.instrument.timers import block

    state = block(fn(state))  # compile + warm

    def run(state, n):
        for _ in range(n):
            state = fn(state)
        return block(state), None

    t0 = time.perf_counter()
    state, _ = run(state, n_short)
    t_short = time.perf_counter() - t0
    t0 = time.perf_counter()
    state, _ = run(state, n_long)
    t_long = time.perf_counter() - t0
    delta = t_long - t_short
    per = delta / (n_long - n_short) if delta > 0 else float("nan")
    return per, state


def _process_count() -> int:
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


def sweep(
    knob: str,
    measure: Callable[[object], float],
    *,
    candidates: Iterable | None = None,
    budget_s: float | None = None,
    emit: Callable[[dict], None] | None = None,
    persist: bool = True,
    **ctx,
):
    """Measure every candidate within budget, persist and return the
    winner. ``measure(candidate) -> seconds`` (NaN = invalid). The
    winner lands in the configured cache under the full fingerprint AND
    the device-only fingerprint, so context-free resolution sites still
    benefit from a sweep run with full context.

    Multi-process runs take the rank-0-swept, broadcast-applied path
    (:func:`_fleet_sweep` — see the module docstring): every per-rank
    decision that used to make pod sweeps unsafe (budget cutoff, winner
    choice) is made once on rank 0 and broadcast before any rank acts
    on it. Single-process behavior is byte-identical to the PR-4
    engine."""
    if candidates is None:
        candidates = registry.space(knob).candidates
    candidates = list(candidates)
    if budget_s is None:
        budget_s = registry.tune_budget_s()
    emit = emit or registry.default_emit() or (lambda rec: None)
    fp = fingerprint(**ctx)

    if _process_count() > 1:
        return _fleet_sweep(
            knob, measure, candidates, budget_s, emit, persist, ctx, fp
        )

    t_begin = time.perf_counter()
    best = None
    best_sec = float("inf")
    measured = 0
    skipped = 0
    for i, cand in enumerate(candidates):
        if (
            i
            and budget_s is not None
            and time.perf_counter() - t_begin >= budget_s
        ):
            # budget exhausted: report the drop, never truncate silently
            skipped = len(candidates) - i
            for c in candidates[i:]:
                emit({"kind": "tune", "knob": knob, "candidate": c,
                      "skipped": "budget", "fingerprint": fp})
            break
        err = None
        sec = float("nan")
        with comm_span(f"tune:{knob}", candidate=cand):
            try:
                sec = float(measure(cand))
            except Exception as e:  # infeasible candidate, not a dead sweep
                err = f"{type(e).__name__}: {e}"
        rec = {"kind": "tune", "knob": knob, "candidate": cand,
               "seconds": None if sec != sec else sec, "fingerprint": fp}
        if err is not None:
            rec["error"] = err
        emit(rec)
        if sec == sec:  # finite/valid
            measured += 1
            if sec < best_sec:
                best, best_sec = cand, sec

    if best is None:
        # nothing measured validly: the prior stays the schedule, and the
        # non-result is recorded (not persisted — a later run retries)
        emit({"kind": "tune_result", "knob": knob, "value": candidates[0],
              "seconds": None, "measured": 0, "skipped": skipped,
              "fingerprint": fp, "note": "no valid measurement"})
        return candidates[0]

    cache = registry.configured_cache()
    if persist and cache is not None:
        cache.store(knob, fp, best, seconds=best_sec)
        if ctx:
            cache.store(knob, device_fingerprint(), best, seconds=best_sec)
        cache.save()
    emit({"kind": "tune_result", "knob": knob, "value": best,
          "seconds": best_sec, "measured": measured, "skipped": skipped,
          "fingerprint": fp})
    return best


def _fleet_sweep(knob, measure, candidates, budget_s, emit, persist,
                 ctx, fp):
    """The rank-0-swept, broadcast-applied multi-process sweep.

    Every rank measures every candidate (the candidate programs dispatch
    collectives — all ranks must enter them together), but only rank 0's
    clock and timer have authority: the per-candidate go/stop decision
    and the final winner record are computed on rank 0 and replicated
    through :func:`~tpu_mpi_tests.tune.fleet.bcast` before any rank acts
    on them, so budget cutoffs and applied schedules are identical on
    every rank by construction. Per-candidate ``tune`` records and the
    cache write are rank-0-only; the broadcast ``tune_result`` is
    emitted by every rank (identical content — the per-rank JSONL
    streams agree byte for byte on the resolved schedule)."""
    from tpu_mpi_tests.tune import fleet

    try:
        # the opening handshake doubles as the transport probe: a fleet
        # with no broadcast path degrades to the PR-4 skip contract on
        # every rank symmetrically, instead of diverging mid-sweep
        fleet.bcast({"knob": knob, "n": len(candidates)}, f"{knob}:open")
    except fleet.FleetUnavailable as e:
        fallback = registry.lookup(knob, **ctx)
        if fallback is None:
            fallback = candidates[0]
        emit({"kind": "tune_result", "knob": knob, "value": fallback,
              "seconds": None, "measured": 0,
              "skipped": len(candidates), "fingerprint": fp,
              "note": f"sweep skipped: multi-process run with no fleet "
                      f"broadcast transport ({e}); warm the cache "
                      f"single-process or ship a --tune-pack"})
        return fallback

    rank = fleet.process_index()
    t_begin = time.perf_counter()
    best = None
    best_sec = float("inf")
    measured = 0
    skipped = 0
    for i, cand in enumerate(candidates):
        # rank 0's clock is the ONLY budget authority; every rank
        # applies the broadcast decision, so the executed candidate
        # sequence cannot diverge (the prior, candidate 0, is always
        # measured — same contract as the single-process sweep)
        if rank == 0:
            go = bool(
                i == 0
                or budget_s is None
                or time.perf_counter() - t_begin < budget_s
            )
        else:
            go = None
        go = fleet.bcast(go, f"{knob}:go{i}")
        if not go:
            skipped = len(candidates) - i
            if rank == 0:
                for c in candidates[i:]:
                    emit({"kind": "tune", "knob": knob, "candidate": c,
                          "skipped": "budget", "fingerprint": fp})
            break
        err = None
        sec = float("nan")
        with comm_span(f"tune:{knob}", candidate=cand):
            try:
                sec = float(measure(cand))
            except Exception as e:  # infeasible candidate, not fatal
                err = f"{type(e).__name__}: {e}"
        if rank == 0:
            rec = {"kind": "tune", "knob": knob, "candidate": cand,
                   "seconds": None if sec != sec else sec,
                   "fingerprint": fp}
            if err is not None:
                rec["error"] = err
            emit(rec)
            if sec == sec:
                measured += 1
                if sec < best_sec:
                    best, best_sec = cand, sec

    # rank 0 builds the COMPLETE winner record and broadcasts it; every
    # rank emits the broadcast copy and applies its value — the TPM1301
    # shape this protocol exists for (and the seeded-mutant gate strips)
    if rank == 0:
        if best is None:
            result = {"kind": "tune_result", "knob": knob,
                      "value": candidates[0], "seconds": None,
                      "measured": 0, "skipped": skipped,
                      "fingerprint": fp, "note": "no valid measurement"}
        else:
            result = {"kind": "tune_result", "knob": knob, "value": best,
                      "seconds": best_sec, "measured": measured,
                      "skipped": skipped, "fingerprint": fp}
    else:
        result = None
    result = fleet.bcast(result, f"{knob}:result")
    emit(result)

    if rank == 0 and persist and result.get("note") is None:
        # single cache writer: non-zero ranks never touch the file (the
        # cache itself is read-only there — belt and braces), so the
        # merge-on-write save cannot race itself across a shared homedir
        cache = registry.configured_cache()
        if cache is not None:
            cache.store(knob, fp, result["value"],
                        seconds=result["seconds"])
            if ctx:
                cache.store(knob, device_fingerprint(), result["value"],
                            seconds=result["seconds"])
            cache.save()
    return result["value"]


def ensure_tuned(
    knob: str,
    measure: Callable[[object], float],
    *,
    explicit=None,
    prior=None,
    candidates: Iterable | None = None,
    budget_s: float | None = None,
    emit: Callable[[dict], None] | None = None,
    device_fallback: bool = True,
    **ctx,
):
    """The driver-side resolution entry point: explicit > cached (a
    ``tune_hit`` record) > sweep-on-miss when ``--tune`` armed the
    registry > prior. Returns the schedule to run.
    ``device_fallback=False`` for context-sensitive knobs (see
    :func:`~tpu_mpi_tests.tune.registry.lookup`).

    Multi-process runs make the hit-vs-sweep decision on RANK 0's cache
    and broadcast it: per-host caches can diverge (rank 0 is the only
    writer, so a fleet without a shared cache file or a ``--tune-pack``
    holds the winner on rank 0 alone), and a subset of ranks entering
    the collective sweep handshake while the rest took the hit path
    would hang the pod. With no broadcast transport the decision stays
    local — the pre-fleet behavior, where a divergent cache could
    diverge schedules but never deadlock."""
    if explicit is not None:
        return explicit
    cached = registry.lookup(knob, device_fallback=device_fallback, **ctx)
    if _process_count() > 1:
        from tpu_mpi_tests.tune import fleet

        try:
            if fleet.process_index() == 0:
                decision = {"hit": cached is not None, "value": cached}
            else:
                decision = None
            decision = fleet.bcast(decision, f"{knob}:resolve")
            cached = decision["value"] if decision["hit"] else None
        except fleet.FleetUnavailable:
            pass  # no transport: local resolution, skip-record sweeps
    if cached is not None:
        (emit or registry.default_emit() or (lambda rec: None))(
            {"kind": "tune_hit", "knob": knob, "value": cached,
             "fingerprint": fingerprint(**ctx)}
        )
        return cached
    if not registry.tuning_enabled():
        if prior is not None:
            return prior
        return registry.space(knob).prior
    return sweep(
        knob, measure, candidates=candidates, budget_s=budget_s,
        emit=emit, **ctx,
    )
