"""The measured sweep: price candidate schedules on-device, persist the
winner, report every measurement.

Measurement discipline is the repo's standing one (SURVEY §7 hard part
2): candidates are timed sync-honestly — the caller's ``measure``
callable must end on a real device sync (``instrument.timers.block`` /
``chain_rate``; :func:`feedback_rate` below packages the donated-
feedback loop shape) — and each candidate window is wrapped in a
telemetry ``comm_span`` so ``tpumt-trace`` shows the sweep windows on
the cross-rank timeline when ``--telemetry`` is on.

Budget (``--tune-budget``) is a wall-clock cap across the candidate
list: the prior (first candidate) is ALWAYS measured, later candidates
are dropped when the budget is exhausted, and every drop is emitted as
a ``skipped`` record — a bounded sweep must never read as an exhaustive
one. An erroring candidate (e.g. a hand-ring kernel below its
lane-alignment floor on this shape) records its error and scores NaN
rather than killing the sweep.

JSONL records (rendered by ``tpumt-report``'s tuning table):

* ``{"kind": "tune", knob, candidate, seconds|skipped|error,
  fingerprint}`` — one per candidate;
* ``{"kind": "tune_result", knob, value, seconds, measured, skipped,
  fingerprint}`` — the persisted winner;
* ``{"kind": "tune_hit", knob, value, fingerprint}`` — a resolution
  served from the cache with no sweep (what ``make tune-smoke`` asserts
  on its second invocation).
"""

from __future__ import annotations

import time
from typing import Callable, Iterable

from tpu_mpi_tests.instrument.telemetry import comm_span
from tpu_mpi_tests.tune import registry
from tpu_mpi_tests.tune.fingerprint import device_fingerprint, fingerprint


def feedback_rate(fn, state, n_short: int = 4, n_long: int = 12):
    """Seconds per call of a donated single-step function, measured by
    feeding its output back as the next input (``state = fn(state)``)
    and differencing two run lengths — the host-loop analog of
    ``chain_rate`` for ops that can't carry a device-side ``fori_loop``
    (e.g. one ``halo_exchange`` dispatch, which donates its operand).
    Returns ``(seconds_per_call, final_state)``; NaN on a non-positive
    delta, like every other invalid measurement in this repo."""
    from tpu_mpi_tests.instrument.timers import block

    state = block(fn(state))  # compile + warm

    def run(state, n):
        for _ in range(n):
            state = fn(state)
        return block(state), None

    t0 = time.perf_counter()
    state, _ = run(state, n_short)
    t_short = time.perf_counter() - t0
    t0 = time.perf_counter()
    state, _ = run(state, n_long)
    t_long = time.perf_counter() - t0
    delta = t_long - t_short
    per = delta / (n_long - n_short) if delta > 0 else float("nan")
    return per, state


def _process_count() -> int:
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


def sweep(
    knob: str,
    measure: Callable[[object], float],
    *,
    candidates: Iterable | None = None,
    budget_s: float | None = None,
    emit: Callable[[dict], None] | None = None,
    persist: bool = True,
    **ctx,
):
    """Measure every candidate within budget, persist and return the
    winner. ``measure(candidate) -> seconds`` (NaN = invalid). The
    winner lands in the configured cache under the full fingerprint AND
    the device-only fingerprint, so context-free resolution sites still
    benefit from a sweep run with full context.

    Single-process only: candidate measurements dispatch collectives,
    and every per-rank decision in a sweep — the wall-clock budget
    cutoff, an errored candidate, the winner itself — is local, so two
    processes could execute different candidate programs and hang the
    pod on a collective only a subset of ranks entered. A multi-process
    run therefore never measures: it records the skip and resolves
    cached > prior (warm the cache in a single-process run on one host
    of the same topology, or point every process at one shared
    ``--tune-cache`` file)."""
    if candidates is None:
        candidates = registry.space(knob).candidates
    candidates = list(candidates)
    if budget_s is None:
        budget_s = registry.tune_budget_s()
    emit = emit or registry.default_emit() or (lambda rec: None)
    fp = fingerprint(**ctx)

    if _process_count() > 1:
        fallback = registry.lookup(knob, **ctx)
        if fallback is None:
            fallback = candidates[0]
        emit({"kind": "tune_result", "knob": knob, "value": fallback,
              "seconds": None, "measured": 0,
              "skipped": len(candidates), "fingerprint": fp,
              "note": "sweep skipped: multi-process run (per-rank "
                      "budget/winner decisions would diverge across "
                      "ranks mid-collective); warm the cache "
                      "single-process"})
        return fallback

    t_begin = time.perf_counter()
    best = None
    best_sec = float("inf")
    measured = 0
    skipped = 0
    for i, cand in enumerate(candidates):
        if (
            i
            and budget_s is not None
            and time.perf_counter() - t_begin >= budget_s
        ):
            # budget exhausted: report the drop, never truncate silently
            skipped = len(candidates) - i
            for c in candidates[i:]:
                emit({"kind": "tune", "knob": knob, "candidate": c,
                      "skipped": "budget", "fingerprint": fp})
            break
        err = None
        sec = float("nan")
        with comm_span(f"tune:{knob}", candidate=cand):
            try:
                sec = float(measure(cand))
            except Exception as e:  # infeasible candidate, not a dead sweep
                err = f"{type(e).__name__}: {e}"
        rec = {"kind": "tune", "knob": knob, "candidate": cand,
               "seconds": None if sec != sec else sec, "fingerprint": fp}
        if err is not None:
            rec["error"] = err
        emit(rec)
        if sec == sec:  # finite/valid
            measured += 1
            if sec < best_sec:
                best, best_sec = cand, sec

    if best is None:
        # nothing measured validly: the prior stays the schedule, and the
        # non-result is recorded (not persisted — a later run retries)
        emit({"kind": "tune_result", "knob": knob, "value": candidates[0],
              "seconds": None, "measured": 0, "skipped": skipped,
              "fingerprint": fp, "note": "no valid measurement"})
        return candidates[0]

    cache = registry.configured_cache()
    if persist and cache is not None:
        cache.store(knob, fp, best, seconds=best_sec)
        if ctx:
            cache.store(knob, device_fingerprint(), best, seconds=best_sec)
        cache.save()
    emit({"kind": "tune_result", "knob": knob, "value": best,
          "seconds": best_sec, "measured": measured, "skipped": skipped,
          "fingerprint": fp})
    return best


def ensure_tuned(
    knob: str,
    measure: Callable[[object], float],
    *,
    explicit=None,
    prior=None,
    candidates: Iterable | None = None,
    budget_s: float | None = None,
    emit: Callable[[dict], None] | None = None,
    device_fallback: bool = True,
    **ctx,
):
    """The driver-side resolution entry point: explicit > cached (a
    ``tune_hit`` record) > sweep-on-miss when ``--tune`` armed the
    registry > prior. Returns the schedule to run.
    ``device_fallback=False`` for context-sensitive knobs (see
    :func:`~tpu_mpi_tests.tune.registry.lookup`)."""
    if explicit is not None:
        return explicit
    cached = registry.lookup(knob, device_fallback=device_fallback, **ctx)
    if cached is not None:
        (emit or registry.default_emit() or (lambda rec: None))(
            {"kind": "tune_hit", "knob": knob, "value": cached,
             "fingerprint": fingerprint(**ctx)}
        )
        return cached
    if not registry.tuning_enabled():
        if prior is not None:
            return prior
        return registry.space(knob).prior
    return sweep(
        knob, measure, candidates=candidates, budget_s=budget_s,
        emit=emit, **ctx,
    )
