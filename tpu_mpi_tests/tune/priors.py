"""Shipped measured-best schedules, demoted to cold-start priors.

Every constant here came from an on-chip sweep recorded in BASELINE.md;
they used to be pinned at their point of use (``comm/ring.py``,
``kernels/pallas_kernels.py``, ``bench.py``). The autotuner demotes them
to PRIORS: the first candidate a sweep tries, and the value every
resolver returns when tuning is disabled and no cache entry exists — so
a run with no cache and no ``--tune`` resolves every schedule exactly as
the hand-pinned era did (pinned-prior parity, gated by
``tests/test_tune.py`` and the table pin in ``tests/test_ring.py``).

This module (and the rest of ``tpu_mpi_tests/tune/``) is the ONLY
sanctioned home for numeric tile/schedule constants: rule TPM701
(``analysis/rules/schedule_constants.py``) flags any such assignment
elsewhere, so future knobs must route through
:func:`~tpu_mpi_tests.tune.registry.declare_space` /
:func:`~tpu_mpi_tests.tune.registry.resolve` and get cached per
topology instead of re-pinning one machine's optimum for everyone.
"""

from __future__ import annotations

# Flash-attention tile configuration per ring layout (BASELINE.md round-5
# stripebalance, three grids interleaved same-window): wide k_tiles win
# for BOTH layouts, and the causal-skip granularity is LAYOUT-DEPENDENT —
# the striped layout's spread diagonal band wants 256-wide sub-span
# skipping (paced 1.645 vs 1.859 ms coupled, 18% less total work,
# same-window), while the contiguous/self-causal narrow band (only
# q_tile wide) trades within window noise with a slight coupled edge
# (3/5 alternated windows), so contig keeps the simpler homogeneous
# full-width masked loop. Re-exported by ``comm.ring`` under the same
# names; ``k_tile=None`` / ``skip_tile=None`` resolve through the cache
# with these as priors.
MEASURED_BEST_K_TILE = {"contig": 2048, "striped": 2048}
MEASURED_BEST_SKIP_TILE = {"contig": 0, "striped": 256}

# Streaming-path skip_tile prior, MEASURED on chip (BASELINE round-5
# streaming-decoupling note): the self-causal stream A/B reads coupled
# 2.424/2.459 ms vs decoupled 2.637/2.663 at L=32K bf16 (alternated
# min-of-2) — the boundary cell is 1 of ~8 live cells per q tile and
# the sub-span machinery costs more than the ~half-cell waste it saves,
# the same verdict as the resident contiguous diagonal. 0 = coupled
# full-width masking; the striped ring never reaches this path at
# production sizes (its blocks stay VMEM-resident), so no striped entry.
STREAM_SKIP_TILE = 0

# Resident-block schedule priors for the headline stencil loop
# (BASELINE.md): S=2 resident blocks measured 3021 vs 2087 iter/s
# against the single-buffer dim-1 kernel at 8192² f32 k=4 (S≥4 loses to
# per-call launch overhead); bf16 runs best with NO blocks (the dim-1
# single-buffer kernel is the measured-best 16-bit schedule). k=4
# temporal blocking is the shipped default depth. ``bench.py`` resolves
# both through the cache with these priors; ``TPU_MPI_BENCH_BLOCKS`` /
# ``TPU_MPI_BENCH_STEPS`` stay the explicit overrides.
BENCH_BLOCKS = {"float32": 2, "bfloat16": 0}
BENCH_STEPS = 4

# Kernel-tier prior for the headline stencil iterate (ISSUE 15): the
# ppermute hand tier ("blocks", parameterized by stencil/blocks — 0 is
# the dim-1 single buffer, S>=2 the resident-block schedule) is the
# measured-best shipped schedule for both official dtypes; the sweep
# candidates price the chained-RDMA ring ("rdma-chained"), the
# one-launch fused halo+stencil kernel ("rdma-fused"), and the XLA
# formulation ("xla") against it. bench.py / the stencil2d iterate leg
# resolve through the cache with this prior, so an untuned run keeps
# the pre-ISSUE-15 schedule byte-identically.
STENCIL_TIER = "blocks"

# Halo exchange schedule prior: DIRECT (plain ppermute on edge slices,
# XLA packs as needed) is the measured-best default on every topology
# benchmarked so far; DEVICE_STAGED and the hand-written PALLAS_RDMA
# ring are the candidates a ``--tune`` sweep prices against it
# (HOST_STAGED is a measurement mode, never a candidate).
HALO_STAGING = "direct"

# Collective variant prior: the XLA lowering ("xla"), with the
# hand-written RDMA ring twin ("rdma") and the one-shot in-kernel
# burst ("oneshot", ISSUE 19) as the sweep alternatives where twins
# exist (allgather/allreduce). The prior stays "xla": new tiers enter
# as CANDIDATES the sweeper must price, never as default behavior.
COLL_VARIANT = "xla"

# Ring-attention tier prior (ISSUE 19): the host-pipelined ring
# (``ring_scan`` + per-step flash kernel, paced by ``ring/
# pipeline_depth``) is the shipped schedule; the one-launch fused-RDMA
# kernel ("fused", kernels/collectives_pallas.py) is the sweep
# candidate — it collapses w dispatches + w launches into one and is
# expected to win only at latency-bound geometries where the whole
# local block fits VMEM. Untuned runs stay byte-identical to the
# pre-ISSUE-19 schedule.
RING_TIER = "pipelined"

# Overlap-engine depth priors (ISSUE 7). All three ship at 1 — today's
# strictly-serialized schedules — so an untuned run stays byte-identical
# to the pre-overlap era; a ``--tune`` sweep (or an explicit flag) is
# what opens a pipeline. Depth semantics per knob:
#
# * ``halo/overlap`` (comm/halo.py): 1 = blocking exchange then update;
#   2 = the ghost exchange rides in flight while the interior computes
#   (the reference's Irecv/compute/Waitall split, host-scheduled).
# * ``ring/pipeline_depth`` (comm/ring.py): 1 = rotate the K/V block
#   after consuming it; d = the next d−1 rotations are issued before
#   the current block's matmul, so the permute-start precedes the
#   compute in program order and XLA's latency-hiding scheduler can
#   run them together.
# * ``coll/dispatch_depth`` (comm/collectives.py): up to d chained
#   collective dispatches in flight before the window blocks on the
#   oldest — bounds the sync-honesty window instead of syncing per
#   call.
HALO_OVERLAP_DEPTH = 1
RING_PIPELINE_DEPTH = 1
COLL_DISPATCH_DEPTH = 1

# Serving-era pillar priors (ISSUE 8). ``moe/combine``: the inverse
# all_to_all mirrors the dispatch hop byte-for-byte — the symmetric
# default; the allgather+select candidate moves world× the bytes but
# collapses the second variable-occupancy hop. ``embedding/lookup``:
# dynamic ``take`` is the general-case local gather; the one-hot matmul
# candidate trades O(B·V_local) FLOPs for the MXU's streaming access
# pattern and wins only on small vocab shards — which is exactly why
# both knobs resolve with device_fallback=False (payload/shape keyed).
MOE_COMBINE = "alltoall"
EMBED_LOOKUP = "take"

# Host-dispatch chunking for the daxpy pillar (ISSUE 14): how many
# kernel applications one dispatch chains device-side (a fori_loop of
# identical applications — bitwise the same result, since each
# iteration recomputes from the same operands). 1 = the reference's
# dispatch-per-iteration semantics, byte-identical stdout; bigger
# chunks amortize the per-dispatch fixed cost the decode pillar
# measures in µs/op. Deliberately a LOCAL-compute knob: it is the
# fleet-sweep smoke's measurable candidate on backends whose
# cross-process device collectives don't exist (make fleet-smoke).
DAXPY_CHUNK = 1
