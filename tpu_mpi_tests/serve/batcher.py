"""Request batching/coalescing for the serving loop.

Dispatching one device program per request wastes the controller
round-trip that dominates small-op latency on the tunneled backends
(BASELINE's ~106 ms fixed dispatch cost is the extreme case); a serving
loop therefore coalesces queued requests into batches. The rule that
makes coalescing *correct* is compatibility: two requests share a batch
iff they are the same workload class — same handler, same shape, same
dtype (equality on :attr:`WorkloadClass.key <tpu_mpi_tests.serve.
workloads.WorkloadClass.key>`). Crossing dtype or shape class would
silently execute a different program than either request asked for,
which is exactly the kind of aggregation bug the bf16-stripe verdict
taught this repo to fear; the never-coalesce-across-class rule is gated
in ``tests/test_serve.py``.

Scheduling is head-of-queue FIFO: the oldest waiting request picks the
class, then the batch greedily collects *later* same-class requests up
to ``max_batch``. Other classes keep their relative order, so a burst of
one class cannot starve another beyond its own service time.

Pure stdlib; requests are whatever objects carry a ``.cls`` with a
``.key`` (the loop's ``Request``), so the module tests without jax.
"""

from __future__ import annotations


def coalesce(queue: list, max_batch: int) -> tuple[list, list]:
    """Pop one batch off ``queue``: the head request plus up to
    ``max_batch - 1`` later requests of the same class, order preserved
    on both sides. Returns ``(batch, remaining)``; an empty queue
    returns ``([], [])``."""
    if not queue:
        return [], []
    if max_batch < 1:
        max_batch = 1
    key = queue[0].cls.key
    batch: list = []
    rest: list = []
    for i, req in enumerate(queue):
        if req.cls.key == key:
            batch.append(req)
            if len(batch) == max_batch:
                # batch full: the remainder moves wholesale (a C-level
                # slice copy, not a per-item key scan) — the serve loop
                # calls this per batch, so a deep queue must not cost a
                # full Python walk once the batch is decided
                rest.extend(queue[i + 1:])
                break
        else:
            rest.append(req)
    return batch, rest
