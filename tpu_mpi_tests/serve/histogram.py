"""Bounded-memory latency histograms for the serving loop.

A steady-state traffic run completes an unbounded number of requests, so
per-request sample retention (the sorted-list percentile everybody writes
first) grows without bound — exactly the failure mode a loop that is
supposed to run for hours must not have. This is the fixed-footprint
alternative: log-spaced buckets (HdrHistogram's idea at benchmark scale),
one integer counter per bucket, so a million requests and ten requests
occupy the same memory and the percentile read stays O(buckets).

Resolution is the bucket width: with :data:`BUCKETS_PER_DECADE` = 24 a
reported percentile is within ~±5% relative of the true sample value
(geometric-midpoint readout, half a bucket each way) — far inside the
run-to-run noise of any latency measurement this repo makes, and gated
against a sorted-sample reference in ``tests/test_serve.py``.

Pure stdlib: the histogram is also what the SLO records carry through
``tpumt-report``, which must stay importable on login nodes without jax.
"""

from __future__ import annotations

import math

#: smallest resolvable latency (seconds); everything below lands in the
#: underflow bucket and reads back as the recorded minimum
MIN_LATENCY_S = 1e-6

#: decades covered above :data:`MIN_LATENCY_S` (1 us .. 1000 s)
DECADES = 9

#: buckets per decade of latency; 24 → ~10% bucket width, ~±5% readout
BUCKETS_PER_DECADE = 24


class LatencyHistogram:
    """Fixed-size log-bucketed latency accumulator.

    ``record`` is one index computation + two adds; ``percentile`` walks
    the (fixed) bucket array. The memory footprint is independent of the
    number of recorded samples by construction — the bounded-memory
    contract of the serve loop (ISSUE 6 acceptance) hangs off this class.
    """

    __slots__ = ("counts", "count", "total_s", "min_s", "max_s")

    def __init__(self):
        self.counts = [0] * (DECADES * BUCKETS_PER_DECADE + 2)
        self.count = 0
        self.total_s = 0.0
        self.min_s = math.inf
        self.max_s = 0.0

    def _index(self, seconds: float) -> int:
        """Bucket index: 0 = underflow, last = overflow, in between the
        log-spaced ladder starting at :data:`MIN_LATENCY_S`."""
        if seconds < MIN_LATENCY_S:
            return 0
        idx = int(
            math.log10(seconds / MIN_LATENCY_S) * BUCKETS_PER_DECADE
        ) + 1
        return min(idx, len(self.counts) - 1)

    def record(self, seconds: float) -> None:
        if not (seconds >= 0.0):  # NaN / negative: an invalid latency
            return
        # TPM1601 suppressions: the lockset engine merges every
        # LatencyHistogram instance into one abstract location, and the
        # heartbeat/exporter threads do read histograms — but only the
        # MetricsRegistry-owned instances, whose every touch happens
        # under MetricsRegistry._lock (observe_sample/snapshot); the
        # serve loop's own instances never leave its single thread.
        # Per-instance ownership is the documented blind spot of the
        # per-class location abstraction.
        self.counts[self._index(seconds)] += 1  # tpumt: ignore[TPM1601]
        self.count += 1  # tpumt: ignore[TPM1601]
        self.total_s += seconds
        self.min_s = min(self.min_s, seconds)  # tpumt: ignore[TPM1601]
        self.max_s = max(self.max_s, seconds)  # tpumt: ignore[TPM1601]

    def mean(self) -> float | None:
        return self.total_s / self.count if self.count else None

    def percentile(self, q: float) -> float | None:
        """Nearest-rank percentile, read back as the bucket's geometric
        midpoint clamped into the truly observed [min, max] (so the
        under/overflow buckets and bucket quantization can never report
        a latency outside what was actually recorded). None when empty."""
        if not self.count:
            return None
        rank = max(1, math.ceil(q / 100.0 * self.count))
        acc = 0
        for idx, c in enumerate(self.counts):
            acc += c
            if acc >= rank:
                if idx == 0:
                    return self.min_s
                lo = MIN_LATENCY_S * 10 ** ((idx - 1) / BUCKETS_PER_DECADE)
                hi = lo * 10 ** (1 / BUCKETS_PER_DECADE)
                mid = math.sqrt(lo * hi)
                return min(max(mid, self.min_s), self.max_s)
        return self.max_s  # unreachable: acc ends at self.count

    def reset(self) -> None:
        for i in range(len(self.counts)):
            self.counts[i] = 0
        self.count = 0
        self.total_s = 0.0
        self.min_s = math.inf
        self.max_s = 0.0

    def percentiles_ms(self) -> dict[str, float]:
        """The SLO record's percentile fields (milliseconds); empty dict
        when nothing was recorded — absent fields, never fake zeros."""
        if not self.count:
            return {}
        out = {}
        for name, q in (("p50_ms", 50.0), ("p95_ms", 95.0),
                        ("p99_ms", 99.0)):
            v = self.percentile(q)
            if v is not None:
                out[name] = v * 1e3
        mean = self.mean()
        if mean is not None:
            out["mean_ms"] = mean * 1e3
        return out
