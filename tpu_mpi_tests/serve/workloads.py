"""The mixed-workload table: what a serving run actually executes.

A production mesh does not serve one shape — it serves a *mix*, and the
schedule-cache fingerprint space (``tune/fingerprint.py`` keys on op ×
shape bucket × dtype) is only exercised when the traffic really mixes
classes. A workload table is a weighted list of :class:`WorkloadClass`
entries; every request draws its class from the table under a seeded
RNG, so a run's class sequence is reproducible.

Spec grammar (CLI ``--workloads``, comma-separated entries)::

    name[:shape[:dtype[:weight]]]

``shape`` is ``x``-separated dims (``256x64``); ``dtype``/``weight``
default to float32 / 1. Omitted fields fall back to the per-workload
defaults in :data:`DEFAULT_SHAPES`. The default table
(:data:`DEFAULT_TABLE`) exercises four handler families — daxpy step,
stencil1d halo step, ring-attention block, small-payload allreduce —
so the fingerprint space is genuinely mixed out of the box; the
serving-era pillars (``moe`` token routing, ``decode`` collectives,
``embedding`` lookup — registered automatically by their workload
specs, ``tpu_mpi_tests/workloads/``) join a mix by naming them in the
table (``moe:2048x64:float32:2``).

The handlers themselves live with their drivers (the
``drivers/_common.py`` workload registry); this module is the pure
(stdlib-only, jax-free) table layer, shared by the loop and the tests.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

#: the dtypes the driver layer accepts (mirrors ``base_parser --dtype``)
VALID_DTYPES = ("float32", "float64", "bfloat16")

#: per-workload default shapes (elements; attn is (L, head_dim), moe is
#: (tokens, d_model), decode is (batch, heads), embedding is
#: (vocab, batch, d_model))
DEFAULT_SHAPES = {
    "daxpy": (65536,),
    "halo": (65536,),
    "attn": (256, 64),
    "allreduce": (4096,),
    "moe": (2048, 64),
    "decode": (8, 16),
    "embedding": (65536, 256, 64),
}

#: the out-of-the-box mix: all four handler families, small shapes, with
#: weights skewed toward the cheap classes the way decode-heavy serving
#: traffic skews toward small-payload latency-bound ops
DEFAULT_TABLE = (
    "daxpy:65536:float32:4,halo:65536:float32:2,"
    "attn:256x64:float32:1,allreduce:4096:float32:3"
)


@dataclass(frozen=True)
class WorkloadClass:
    """One row of the workload table. ``key`` is the coalescing class:
    requests batch together iff their keys are equal (the batcher's
    never-across-dtype/shape rule is equality on this string)."""

    workload: str
    shape: tuple[int, ...]
    dtype: str = "float32"
    weight: float = 1.0

    @property
    def key(self) -> str:
        dims = "x".join(str(d) for d in self.shape)
        return f"{self.workload}:{dims}:{self.dtype}"

    @property
    def nbytes(self) -> int:
        """Nominal payload bytes of one request (shape × itemsize) — the
        span annotation, not a bandwidth claim."""
        n = 1
        for d in self.shape:
            n *= d
        item = 8 if self.dtype == "float64" else (
            2 if self.dtype == "bfloat16" else 4
        )
        return n * item


def parse_workload_table(spec: str) -> list[WorkloadClass]:
    """Parse a ``--workloads`` spec into classes. Raises ``ValueError``
    with a caller-printable message on malformed entries — the driver
    turns that into an ERROR line + exit 2, never a traceback."""
    classes: list[WorkloadClass] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        name = parts[0]
        if name not in DEFAULT_SHAPES:
            raise ValueError(
                f"unknown workload {name!r}; valid: "
                f"{','.join(sorted(DEFAULT_SHAPES))}"
            )
        shape = DEFAULT_SHAPES[name]
        dtype = "float32"
        weight = 1.0
        try:
            if len(parts) > 1 and parts[1]:
                shape = tuple(int(d) for d in parts[1].split("x"))
            if len(parts) > 2 and parts[2]:
                dtype = parts[2]
            if len(parts) > 3 and parts[3]:
                weight = float(parts[3])
        except ValueError:
            raise ValueError(f"malformed workload entry {entry!r} "
                             f"(want name[:shape[:dtype[:weight]]])")
        if len(parts) > 4:
            raise ValueError(f"malformed workload entry {entry!r}: "
                             f"too many fields")
        if dtype not in VALID_DTYPES:
            raise ValueError(
                f"unknown dtype {dtype!r} in {entry!r}; valid: "
                f"{','.join(VALID_DTYPES)}"
            )
        if not shape or any(d < 1 for d in shape):
            raise ValueError(f"shape must be positive dims in {entry!r}")
        if not weight > 0:
            raise ValueError(f"weight must be positive in {entry!r}")
        classes.append(WorkloadClass(name, shape, dtype, weight))
    if not classes:
        raise ValueError(f"empty workload table {spec!r}")
    keys = [c.key for c in classes]
    dupes = {k for k in keys if keys.count(k) > 1}
    if dupes:
        raise ValueError(
            f"duplicate workload classes: {','.join(sorted(dupes))}"
        )
    return classes


class WorkloadMix:
    """Weighted class drawer under a seeded RNG stream (separate from
    the arrival-process stream, so changing the mix never perturbs the
    arrival schedule and vice versa)."""

    def __init__(self, classes: list[WorkloadClass], seed: int = 0):
        self.classes = list(classes)
        self._weights = [c.weight for c in self.classes]
        self._rng = random.Random(f"mix:{seed}")

    def draw(self) -> WorkloadClass:
        return self._rng.choices(self.classes, self._weights)[0]
