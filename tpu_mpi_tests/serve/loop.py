"""The steady-state serving loop: unbounded time, bounded memory.

Every other driver in this repo is a one-shot benchmark — initialize a
mesh, run N iterations, report, exit. This loop is the repo's first
subsystem where *time is unbounded and the steady state is the
measurement*: one persistent mesh and one set of warmed compiled
handlers stay alive while an arrival process (``serve/arrival.py``)
generates requests drawn from a mixed workload table
(``serve/workloads.py``), the batcher (``serve/batcher.py``) coalesces
compatible requests, and per-request latency lands in fixed-size
histograms (``serve/histogram.py``).

Observability rides the existing spine, not a new one:

* per-class SLO records (``kind: "serve"``, ``event: "window"`` every
  ``window_s`` plus one ``event: "summary"``) flow through the caller's
  sink onto the same JSONL stream every other record uses, wall-clock
  stamped on the PR-2 clock so ``tpumt-trace`` places them and
  ``tpumt-report`` renders the SLO table / ``--diff`` gates it;
* each executed batch is bracketed in a telemetry ``comm_span``
  (``op: "serve:<class>"``), so with ``--telemetry`` the request stream
  appears on the cross-rank timeline as first-class request spans;
* the watchdog integration is idle-aware (``IdleAwareWatchdog``): armed
  only around active dispatch, so an arbitrarily long Poisson gap can
  never fire it while a genuinely wedged batch still does.

The loop itself is single-threaded pure Python with injectable clocks —
deterministic under test (fake clock, fake handlers, no jax import) and
honest in production (handlers block on device completion before
returning, so a latency reading is a completed request, not a dispatch).
"""

from __future__ import annotations

import time
from typing import Any, Callable

from tpu_mpi_tests.serve.batcher import coalesce
from tpu_mpi_tests.serve.histogram import LatencyHistogram
from tpu_mpi_tests.serve.workloads import WorkloadClass, WorkloadMix

#: longest single sleep while idle — keeps the loop responsive to the
#: run deadline and window boundaries without busy-waiting
MAX_IDLE_SLEEP_S = 0.25

#: pause after a failed batch: closed-loop clients re-arm the instant
#: their batch completes, so a persistently failing handler would
#: otherwise busy-spin the loop at CPU speed for the whole run — this
#: bounds it to ~20 error batches/s while leaving transient errors
#: nearly free
FAIL_BACKOFF_S = 0.05

#: chaos arm-point (``tpu_mpi_tests/chaos/inject.py`` rebinds this at
#: arm time; never set by anything else): ``hook(window_index) -> int``
#: synthetic arrivals to flood into the queue at an SLO window
#: boundary. Consulted once per window — a rare branch on an idle-path
#: boundary, and a disarmed run (slot None) takes the same code path
#: as a build without the chaos layer.
_CHAOS_FLOOD = None


class Request:
    """One in-queue request: its workload class and scheduled arrival
    time (the open-loop latency origin — queue wait counts).
    ``synthetic`` marks chaos-flood injections: they are served and
    accounted like any request but never fed back to the arrival
    process — a closed loop's fixed client population must not be
    permanently inflated by a transient burst."""

    __slots__ = ("cls", "arrival", "synthetic")

    def __init__(self, cls: WorkloadClass, arrival: float,
                 synthetic: bool = False):
        self.cls = cls
        self.arrival = arrival
        self.synthetic = synthetic


#: per-(class, window) exemplar budget for shed and error terminal
#: records — the sampler's rate cap (plus exactly one p99-worst
#: completion), so the ``kind:"req"`` stream stays bounded per window
#: no matter how hard a storm sheds
REQ_EXEMPLAR_CAP = 2


class _ClassStats:
    """Per-class accumulators, total + current-window. Fixed size: six
    histograms (e2e + queue-delay + service, total and window), a
    bounded exemplar set, and a handful of counters, regardless of
    request count."""

    __slots__ = ("hist", "win_hist", "qd_hist", "svc_hist",
                 "win_qd_hist", "win_svc_hist", "requests", "errors",
                 "shed", "batches", "arrivals", "queue_max",
                 "win_requests", "win_errors", "win_shed", "win_batches",
                 "win_arrivals", "win_queue_max", "shed_wait_s",
                 "shed_wait_max_s", "win_shed_wait_s",
                 "win_shed_wait_max_s", "win_worst", "win_shed_ex",
                 "win_err_ex", "consec_errors", "quarantines",
                 "quarantine_s", "streak_errors", "quar_errors",
                 "quar_shed")

    def __init__(self):
        self.hist = LatencyHistogram()
        self.win_hist = LatencyHistogram()
        # the latency DECOMPOSITION: e2e = queue delay (arrival ->
        # dispatch) + service (dispatch -> completion), recorded from
        # the same three stamps so qd + svc == e2e per request exactly
        # and the percentile readouts reconcile within bucket tolerance
        self.qd_hist = LatencyHistogram()
        self.svc_hist = LatencyHistogram()
        self.win_qd_hist = LatencyHistogram()
        self.win_svc_hist = LatencyHistogram()
        self.requests = self.errors = self.shed = 0
        self.batches = self.arrivals = self.queue_max = 0
        self.win_requests = self.win_errors = self.win_shed = 0
        self.win_batches = self.win_arrivals = self.win_queue_max = 0
        # terminal accounting for requests that never complete: the
        # queue time a shed request had accumulated when dropped
        # (admission sheds + quarantine backlog drops) — kept OUT of
        # qd_hist so the qd+svc≈e2e reconciliation stays a completions-
        # only identity, but first-class in the window record
        self.shed_wait_s = 0.0
        self.shed_wait_max_s = 0.0
        self.win_shed_wait_s = 0.0
        self.win_shed_wait_max_s = 0.0
        # the bounded per-window request exemplars: the p99-worst
        # completed request (one), plus up to REQ_EXEMPLAR_CAP shed and
        # error terminals — ready-to-sink dicts, wall-stamped at
        # capture time
        self.win_worst: dict | None = None
        self.win_shed_ex: list[dict] = []
        self.win_err_ex: list[dict] = []
        # graceful degradation bookkeeping: consecutive failed batches
        # (reset on any success), completed quarantine episodes, and
        # total seconds the class spent quarantined
        self.consec_errors = 0
        self.quarantines = 0
        self.quarantine_s = 0.0
        # quarantine ATTRIBUTION: request-unit errors in the failure
        # streak that ended in quarantine (streak_errors accumulates,
        # moves to quar_errors on entry) and sheds caused by the
        # quarantine itself (dropped backlog + quarantined-arrival
        # sheds) — so the driver can forgive exactly the degradation
        # the quarantine accounts for, and nothing else
        self.streak_errors = 0
        self.quar_errors = 0
        self.quar_shed = 0

    def window_active(self) -> bool:
        return bool(self.win_arrivals or self.win_requests
                    or self.win_errors or self.win_shed)

    def reset_window(self) -> None:
        self.win_hist.reset()
        self.win_qd_hist.reset()
        self.win_svc_hist.reset()
        self.win_requests = self.win_errors = self.win_shed = 0
        self.win_batches = self.win_arrivals = self.win_queue_max = 0
        self.win_shed_wait_s = 0.0
        self.win_shed_wait_max_s = 0.0
        self.win_worst = None
        self.win_shed_ex = []
        self.win_err_ex = []

    def note_shed_wait(self, wait_s: float) -> None:
        wait_s = max(wait_s, 0.0)
        self.shed_wait_s += wait_s
        self.shed_wait_max_s = max(self.shed_wait_max_s, wait_s)
        self.win_shed_wait_s += wait_s
        self.win_shed_wait_max_s = max(self.win_shed_wait_max_s, wait_s)


class ServeLoop:
    """Drive ``handlers`` under ``arrival`` for ``duration_s`` seconds.

    ``handlers`` maps each class key to a ``step_fn(n)`` executing ``n``
    coalesced requests and returning only after device completion (the
    driver-registry contract, ``drivers/_common.py``). ``sink`` receives
    every ``kind: "serve"`` record; ``watchdog`` (optional) must expose
    the idle-aware ``arm(phase)``/``disarm()`` API. ``clock``/``wall``/
    ``sleep`` are injectable for deterministic tests.
    """

    def __init__(
        self,
        classes: list[WorkloadClass],
        handlers: dict[str, Callable[[int], Any]],
        arrival,
        *,
        duration_s: float,
        max_batch: int = 8,
        window_s: float = 5.0,
        max_queue: int = 10000,
        seed: int = 0,
        sink: Callable[[dict], None] | None = None,
        watchdog=None,
        quarantine_after: int | None = None,
        controller=None,
        recorder=None,
        clock: Callable[[], float] = time.monotonic,
        wall: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
    ):
        missing = [c.key for c in classes if c.key not in handlers]
        if missing:
            raise ValueError(f"no handler for classes: {missing}")
        self.classes = list(classes)
        self.handlers = dict(handlers)
        self.arrival = arrival
        self.duration_s = float(duration_s)
        self.max_batch = max(1, int(max_batch))
        self.window_s = float(window_s)
        self.max_queue = int(max_queue)
        self.mix = WorkloadMix(classes, seed=seed)
        self.sink = sink
        self.watchdog = watchdog
        # graceful degradation: after N consecutive failed batches a
        # class is quarantined — its arrivals shed, the others keep
        # serving — instead of error-spinning an hours-long run; a
        # window-boundary probe re-admits it once the handler recovers
        # (None = off, the pre-quarantine behavior)
        self.quarantine_after = (int(quarantine_after)
                                 if quarantine_after else None)
        # online re-tuning (tune/controller.py, --retune): consulted at
        # window boundaries only — between batches, like the quarantine
        # probes — so a bounded re-sweep is quarantine-style degraded
        # service (arrivals queue through it), never a mid-batch stall.
        # None = off, byte-identical to the pre-controller loop.
        self.controller = controller
        # traffic capture (serve/replay.py TrafficRecorder, --record):
        # fed the OFFERED stream — every admission attempt, before the
        # shed decision, chaos-flood injections included — so a replay
        # re-offers exactly what this run saw, storms and all
        self.recorder = recorder
        self._quarantined: dict[str, float] = {}  # key -> wall t of entry
        self._clock = clock
        self._wall = wall
        self._sleep = sleep
        self.stats: dict[str, _ClassStats] = {
            c.key: _ClassStats() for c in classes
        }
        self._by_key = {c.key: c for c in classes}

    # -- record emission ---------------------------------------------------

    def _emit(self, event: str, cls: WorkloadClass, st: _ClassStats,
              t_start: float, t_end: float, window: bool,
              offered_dur: float | None = None,
              queue_depth: int | None = None) -> dict:
        """``offered_dur`` divides the offered rate when the record's
        span is longer than the window arrivals were generated in: a
        summary covers traffic + drain, and dividing arrivals by the
        drain-inclusive span would make a saturated run (offered ≫
        sustained, everything eventually served) read as offered ==
        achieved — the exact signal the pair exists to expose."""
        dur = max(t_end - t_start, 1e-9)
        if window:
            arrivals, requests = st.win_arrivals, st.win_requests
            errors, shed = st.win_errors, st.win_shed
            batches, qmax = st.win_batches, st.win_queue_max
            hist, qd_hist = st.win_hist, st.win_qd_hist
            svc_hist = st.win_svc_hist
            shed_wait_s = st.win_shed_wait_s
            shed_wait_max_s = st.win_shed_wait_max_s
        else:
            arrivals, requests = st.arrivals, st.requests
            errors, shed = st.errors, st.shed
            batches, qmax = st.batches, st.queue_max
            hist, qd_hist, svc_hist = st.hist, st.qd_hist, st.svc_hist
            shed_wait_s = st.shed_wait_s
            shed_wait_max_s = st.shed_wait_max_s
        rec = {
            "kind": "serve",
            "event": event,
            "class": cls.key,
            "workload": cls.workload,
            "shape": list(cls.shape),
            "dtype": cls.dtype,
            "t_start": t_start,
            "t_end": t_end,
            "duration_s": dur,
            "arrivals": arrivals,
            "requests": requests,
            "errors": errors,
            "shed": shed,
            "batches": batches,
            "offered_hz": arrivals / (offered_dur or dur),
            "achieved_hz": requests / dur,
            "queue_max": qmax,
            **hist.percentiles_ms(),
            # the decomposition columns: e2e ≈ qd + svc per percentile
            # (exact per request; percentiles reconcile within the
            # histogram's readout tolerance)
            **{f"qd_{k}": v
               for k, v in qd_hist.percentiles_ms().items()},
            **{f"svc_{k}": v
               for k, v in svc_hist.percentiles_ms().items()},
        }
        if shed:
            # queue time the shed/dropped requests had accumulated —
            # the coordinated-omission blind spot, measured: a storm's
            # victims carry their wait into the record instead of
            # vanishing from every histogram
            rec["shed_wait_ms_mean"] = shed_wait_s / shed * 1e3
            rec["shed_wait_ms_max"] = shed_wait_max_s * 1e3
        if queue_depth is not None:
            # the STANDING backlog at emission time (queue_max is the
            # window's high-water mark): the live pressure signal the
            # metrics tee turns into the serve queue-depth gauge — this
            # loop knows nothing about metrics, only its own record
            rec["queue_depth"] = queue_depth
        if not window and st.quarantines:
            rec["quarantines"] = st.quarantines
            rec["quarantine_s"] = st.quarantine_s
            rec["quar_errors"] = st.quar_errors
            rec["quar_shed"] = st.quar_shed
        if offered_dur is not None and dur > offered_dur:
            # how long past the deadline the queue took to drain — a
            # saturated run's backlog, first-class in the record
            rec["drain_s"] = dur - offered_dur
        if self.sink is not None:
            self.sink(rec)
        return rec

    def _emit_req_exemplars(self, st: _ClassStats) -> None:
        """Flush the window's bounded request exemplars: the p99-worst
        completion plus the capped shed/error terminals, captured as
        ready-to-sink ``kind:"req"`` dicts. Called at every window
        boundary just after the window record, so a trace reader sees
        the exemplars inside the window they describe."""
        if self.sink is None:
            return
        if st.win_worst is not None:
            self.sink(st.win_worst)
        for rec in st.win_shed_ex:
            self.sink(rec)
        for rec in st.win_err_ex:
            self.sink(rec)

    # -- graceful degradation ----------------------------------------------

    def _enter_quarantine(self, cls: WorkloadClass, st: _ClassStats,
                          t_wall: float, t_mono: float, queue: list,
                          waiting: dict) -> None:
        """A handler class that stayed dead past ``quarantine_after``
        consecutive failed batches stops being served: its backlog is
        shed, future arrivals shed on admission, and the rest of the
        classes keep their SLO — instead of the whole hours-long run
        error-spinning to rc 1. Emits ``kind:"serve"
        event:"quarantine"``; a window-boundary probe re-admits the
        class when the handler recovers."""
        self._quarantined[cls.key] = t_wall
        st.quar_errors += st.streak_errors
        st.streak_errors = 0
        dropped = [r for r in queue if r.cls.key == cls.key]
        if dropped:
            queue[:] = [r for r in queue if r.cls.key != cls.key]
            st.shed += len(dropped)
            st.win_shed += len(dropped)
            st.quar_shed += len(dropped)
            waiting[cls.key] = 0
            # lifecycle terminals for the dropped backlog: each request
            # dies with the queue time it had accumulated (satellite of
            # the coordinated-omission fix — quarantine drops used to
            # vanish without a latency trace)
            for r in dropped:
                wait_s = max(t_mono - r.arrival, 0.0)
                st.note_shed_wait(wait_s)
                if len(st.win_shed_ex) < REQ_EXEMPLAR_CAP:
                    st.win_shed_ex.append({
                        "kind": "req", "event": "shed",
                        "class": cls.key,
                        "sampled": "quarantine_drop",
                        "t_arrival": t_wall - wait_s,
                        "t_done": t_wall,
                        "queue_ms": wait_s * 1e3,
                    })
        if self.sink is not None:
            self.sink({
                "kind": "serve", "event": "quarantine", "class": cls.key,
                "workload": cls.workload, "dtype": cls.dtype,
                "t": t_wall, "consecutive_errors": st.consec_errors,
                "dropped": len(dropped),
            })

    def _probe_quarantined(self, t_wall: float) -> None:
        """One probe batch (n=1, synthetic — no queued request is
        risked) per quarantined class per window boundary; success
        re-admits the class and records the downtime."""
        for key in list(self._quarantined):
            if self.watchdog is not None:
                self.watchdog.arm(f"serve:probe:{key}")
            try:
                self.handlers[key](1)
                ok = True
            except Exception:
                ok = False
            finally:
                if self.watchdog is not None:
                    self.watchdog.disarm()
            if not ok:
                continue
            t_q = self._quarantined.pop(key)
            st = self.stats[key]
            st.consec_errors = 0
            st.quarantines += 1
            st.quarantine_s += max(t_wall - t_q, 0.0)
            if self.sink is not None:
                self.sink({
                    "kind": "serve", "event": "recover", "class": key,
                    "t": t_wall, "downtime_s": max(t_wall - t_q, 0.0),
                })

    # -- the loop ----------------------------------------------------------

    def run(self) -> list[dict]:
        """Serve until the deadline, drain the queue, return the per-class
        ``event: "summary"`` records (also pushed through the sink)."""
        from tpu_mpi_tests.instrument.telemetry import comm_span

        clock, wall = self._clock, self._wall
        t0 = clock()
        wall0 = wall()
        t_end = t0 + self.duration_s
        self.arrival.start(t0)
        queue: list[Request] = []
        # per-class waiting counts, maintained incrementally (+1 on
        # enqueue, -batch on coalesce): the SLO queue-depth column must
        # not cost an O(queue) scan inside the latency-measuring loop
        waiting: dict[str, int] = {}
        window_start = t0
        window_wall = wall0
        window_index = 0

        def wall_at(t_mono: float) -> float:
            return wall0 + (t_mono - t0)

        # replay hook: a ReplayArrivals process carries the recorded
        # class keys and hands them out in admission order, overriding
        # the seeded mix drawer — two replays of one artifact admit the
        # exact same (time, class) sequence
        draw_recorded = getattr(self.arrival, "draw_class", None)

        def admit(t_arr: float, synthetic: bool = False) -> None:
            """One arrival: draw its class, then queue / shed it. A
            quarantined class sheds on arrival — the whole point is
            that its backlog cannot starve the healthy classes."""
            cls = None
            if draw_recorded is not None and not synthetic:
                key = draw_recorded()
                cls = self._by_key.get(key) if key is not None else None
            if cls is None:
                cls = self.mix.draw()
            if self.recorder is not None:
                self.recorder.add(t_arr - t0, cls.key)
            st = self.stats[cls.key]
            st.arrivals += 1
            st.win_arrivals += 1
            if len(queue) >= self.max_queue or cls.key in self._quarantined:
                # shed and gone: a shed request is never fed back
                # through on_complete (re-arming what the full
                # queue just rejected would spin) — closed-loop
                # callers must keep concurrency <= max_queue or
                # the population decays (the driver enforces it)
                st.shed += 1
                st.win_shed += 1
                if cls.key in self._quarantined:
                    st.quar_shed += 1
                # terminal lifecycle accounting: the request dies HERE
                # with the queue time it accumulated between its
                # scheduled arrival and the shed decision (a loop
                # running behind schedule sheds late, and that lateness
                # is real queue delay the victim experienced)
                wait_s = max(now - t_arr, 0.0)
                st.note_shed_wait(wait_s)
                if len(st.win_shed_ex) < REQ_EXEMPLAR_CAP:
                    st.win_shed_ex.append({
                        "kind": "req", "event": "shed",
                        "class": cls.key, "sampled": "shed",
                        "t_arrival": wall_at(t_arr),
                        "t_done": wall_at(now),
                        "queue_ms": wait_s * 1e3,
                    })
                return
            queue.append(Request(cls, t_arr, synthetic))
            d = waiting.get(cls.key, 0) + 1
            waiting[cls.key] = d
            st.queue_max = max(st.queue_max, d)
            st.win_queue_max = max(st.win_queue_max, d)

        while True:
            now = clock()
            # ingest arrivals scheduled up to now (never past the
            # deadline — the post-deadline drain must terminate)
            for t_arr in self.arrival.take_due(now, limit=t_end):
                admit(t_arr)
            # window boundary: emit + reset (drain windows included)
            if now - window_start >= self.window_s:
                w_end = wall_at(now)
                for cls in self.classes:
                    st = self.stats[cls.key]
                    if st.window_active():
                        self._emit("window", cls, st, window_wall,
                                   w_end, window=True,
                                   queue_depth=waiting.get(cls.key, 0))
                        self._emit_req_exemplars(st)
                    st.reset_window()
                    # requests already waiting carry into the new
                    # window's depth — a backlog is not depth zero
                    st.win_queue_max = waiting.get(cls.key, 0)
                window_start = now
                window_wall = w_end
                window_index += 1
                flood = _CHAOS_FLOOD
                if flood is not None:
                    for _ in range(flood(window_index)):
                        admit(now, synthetic=True)
                if self._quarantined:
                    self._probe_quarantined(w_end)
                if self.controller is not None:
                    self.controller.window_boundary(w_end)

            if queue:
                batch, queue = coalesce(queue, self.max_batch)
                cls = batch[0].cls
                waiting[cls.key] -= len(batch)
                st = self.stats[cls.key]
                if self.watchdog is not None:
                    self.watchdog.arm(f"serve:{cls.key}")
                # the dispatch stamp: everything before it is queue
                # delay (arrival -> coalesce -> here), everything after
                # is service — e2e = qd + svc per request by identity
                t_disp = clock()
                failed = False
                try:
                    with comm_span(
                        f"serve:{cls.key}",
                        nbytes=cls.nbytes * len(batch),
                        requests=len(batch),
                    ):
                        # handler blocks on device completion before
                        # returning (registry contract) — the span and
                        # the latency reads below are sync-honest
                        self.handlers[cls.key](len(batch))
                except Exception:
                    failed = True
                finally:
                    if self.watchdog is not None:
                        self.watchdog.disarm()
                done = clock()
                st.batches += 1
                st.win_batches += 1
                svc = max(done - t_disp, 0.0)
                if failed:
                    st.errors += len(batch)
                    st.win_errors += len(batch)
                    st.streak_errors += len(batch)
                    st.consec_errors += 1
                    if len(st.win_err_ex) < REQ_EXEMPLAR_CAP:
                        # one exemplar per failed batch, carrying the
                        # oldest member's queue delay — enough to see
                        # WHERE the failed request spent its life
                        oldest = min(r.arrival for r in batch)
                        st.win_err_ex.append({
                            "kind": "req", "event": "error",
                            "class": cls.key, "sampled": "error",
                            "t_arrival": wall_at(oldest),
                            "t_dispatch": wall_at(t_disp),
                            "t_done": wall_at(done),
                            "queue_ms": max(t_disp - oldest, 0.0) * 1e3,
                            "service_ms": svc * 1e3,
                            "requests": len(batch),
                        })
                    if (self.quarantine_after
                            and st.consec_errors >= self.quarantine_after
                            and cls.key not in self._quarantined):
                        self._enter_quarantine(cls, st, wall_at(done),
                                               done, queue, waiting)
                else:
                    st.consec_errors = 0
                    st.streak_errors = 0
                    for req in batch:
                        qd = max(t_disp - req.arrival, 0.0)
                        lat = qd + svc
                        st.requests += 1
                        st.win_requests += 1
                        st.hist.record(lat)
                        st.win_hist.record(lat)
                        st.qd_hist.record(qd)
                        st.win_qd_hist.record(qd)
                        st.svc_hist.record(svc)
                        st.win_svc_hist.record(svc)
                        worst = st.win_worst
                        if (worst is None
                                or lat * 1e3 > worst["e2e_ms"]):
                            # the window's p99-worst completion — the
                            # one request a trace reader always gets
                            st.win_worst = {
                                "kind": "req", "event": "complete",
                                "class": cls.key,
                                "sampled": "p99_worst",
                                "t_arrival": wall_at(req.arrival),
                                "t_dispatch": wall_at(t_disp),
                                "t_done": wall_at(done),
                                "queue_ms": qd * 1e3,
                                "service_ms": svc * 1e3,
                                "e2e_ms": lat * 1e3,
                            }
                # synthetic (chaos-flood) completions never re-arm the
                # arrival process: a closed loop's population must
                # return to exactly --concurrency once the burst drains
                organic = sum(1 for r in batch if not r.synthetic)
                self.arrival.on_complete(organic, done)
                if failed:
                    self._sleep(FAIL_BACKOFF_S)
                continue

            if now >= t_end:
                break  # deadline passed, queue drained
            nxt = self.arrival.next_event()
            targets = [t_end, window_start + self.window_s]
            if nxt is not None:
                targets.append(nxt)
            gap = min(targets) - now
            if gap > 0:
                self._sleep(min(gap, MAX_IDLE_SLEEP_S))

        end_wall = wall_at(clock())
        # a class still quarantined at run end charges its open episode
        # to the summary's downtime accounting
        for key, t_q in self._quarantined.items():
            st = self.stats[key]
            st.quarantines += 1
            st.quarantine_s += max(end_wall - t_q, 0.0)
        # final partial window, then the run summaries
        for cls in self.classes:
            st = self.stats[cls.key]
            if st.window_active():
                self._emit("window", cls, st, window_wall, end_wall,
                           window=True,
                           queue_depth=waiting.get(cls.key, 0))
                self._emit_req_exemplars(st)
            st.reset_window()
        return [
            self._emit("summary", self._by_key[key], st, wall0,
                       end_wall, window=False,
                       offered_dur=min(self.duration_s,
                                       max(end_wall - wall0, 1e-9)))
            for key, st in self.stats.items()
        ]
