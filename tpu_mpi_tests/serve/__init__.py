"""Serving-mode harness: steady-state traffic against a persistent mesh.

The ROADMAP's north star is a production system serving heavy traffic;
every other driver is a one-shot benchmark. This package models the
missing regime — sustained load, tail latency, throughput-under-load —
as four small pure-Python pieces (arrival processes, a weighted workload
table, a class-compatible batcher, bounded-memory latency histograms)
around one single-threaded loop, with the actual device work supplied by
the workload-handler registry in ``drivers/_common.py`` and all
observability riding the existing telemetry/JSONL spine
(``kind: "serve"`` records → ``tpumt-report`` SLO table, batch spans →
``tpumt-trace`` timelines). Entry point: ``tpumt-serve``
(``drivers/serve.py``).
"""

# lazy re-exports (PEP 562), matching the instrument package: the table/
# histogram/arrival layers are stdlib-only and must stay importable in
# jax-free test and login-node contexts
_EXPORTS = {
    "OpenLoopPoisson": "arrival",
    "ClosedLoop": "arrival",
    "coalesce": "batcher",
    "LatencyHistogram": "histogram",
    "ServeLoop": "loop",
    "Request": "loop",
    "WorkloadClass": "workloads",
    "WorkloadMix": "workloads",
    "parse_workload_table": "workloads",
    "DEFAULT_TABLE": "workloads",
}
__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(
            f"tpu_mpi_tests.serve.{_EXPORTS[name]}"
        )
        return getattr(mod, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
