"""Deterministic traffic record/replay for the serving loop.

A serve run's load is defined by its arrival-time + class-key stream.
Seeding the generators (``serve/arrival.py`` + the mix drawer) makes two
runs *statistically* identical, but ROADMAP item 2 asks for more: the
``tpumt-report --diff`` SLO gate should compare two runs of **the same
traffic**, not two draws from the same distribution — the honest-
measurement discipline the paper's harness applies to its stencil
timings (controlled repeat runs, then aggregate). This module is the
PR-14 ``tpumt-tune pack`` idiom applied to load:

* :class:`TrafficRecorder` — rides the loop's admission path
  (``tpumt-serve --record traffic.json``) and captures every offered
  arrival as ``(relative_time, class_key)``, chaos-flood injections
  included: the artifact is the *offered* stream, whether the system
  served or shed each request is the measured response.
* :func:`save_traffic`/:func:`load_traffic` — the versioned portable
  artifact, fingerprinted over its count / duration / per-class
  composition / microsecond-rounded event stream, so two artifacts
  with the same fingerprint carry the same traffic and a corrupted or
  version-skewed file is refused loudly (:class:`TrafficFormatError`),
  never half-replayed.
* :class:`ReplayArrivals` — an arrival process (the same four-method
  interface the loop drives) that reproduces the recorded stream
  byte-identically: arrivals are re-scheduled at their recorded offsets
  from the loop's own ``t0`` (clock-injectable, so tests replay a
  wall-hours trace instantly) and the recorded class keys override the
  mix drawer via the loop's ``draw_class`` hook. Open- and closed-loop
  recordings replay the same way — a closed loop's completion-gated
  admission times *are* its traffic.

Pure stdlib by design (json + hashlib), importable on login nodes.
"""

from __future__ import annotations

import hashlib
import json

#: artifact format marker — a file without it is not a traffic artifact
TRAFFIC_FORMAT = "tpumt-traffic"

#: artifact schema version; :func:`load_traffic` refuses other versions
#: (forward-compat: an older build must not silently mis-replay a newer
#: artifact's stream)
TRAFFIC_VERSION = 1


class TrafficFormatError(ValueError):
    """A traffic artifact that cannot be trusted: unreadable, not the
    expected format, a version this build does not speak, or contents
    that fail the fingerprint self-check."""


def traffic_fingerprint(events: list, duration_s: float) -> str:
    """Stable identity of one traffic stream: sha256 (truncated) over
    the count, the microsecond-rounded duration, the per-class
    composition, and the microsecond-rounded event stream itself.
    Rounding to 1 us makes the fingerprint robust to float round-trips
    through JSON while still pinning the actual schedule, not just its
    histogram."""
    comp: dict[str, int] = {}
    for _t, key in events:
        comp[key] = comp.get(key, 0) + 1
    payload = {
        "version": TRAFFIC_VERSION,
        "count": len(events),
        "duration_us": int(round(float(duration_s) * 1e6)),
        "classes": {k: comp[k] for k in sorted(comp)},
        "events": [[int(round(float(t) * 1e6)), key]
                   for t, key in events],
    }
    blob = json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class TrafficRecorder:
    """Capture the offered arrival stream of one serve run.

    The loop calls :meth:`add` once per admission attempt (before the
    shed decision — the artifact is the load, not the outcome) with the
    arrival's offset from the run's ``t0`` and the drawn class key.
    :meth:`finalize` freezes the artifact dict."""

    def __init__(self, arrival: str = "?", load: str = ""):
        self.arrival = arrival
        self.load = load
        self.events: list[tuple[float, str]] = []

    def add(self, rel_t: float, class_key: str) -> None:
        self.events.append((float(rel_t), class_key))

    def finalize(self, duration_s: float) -> dict:
        comp: dict[str, int] = {}
        for _t, key in self.events:
            comp[key] = comp.get(key, 0) + 1
        return {
            "format": TRAFFIC_FORMAT,
            "version": TRAFFIC_VERSION,
            "arrival": self.arrival,
            "load": self.load,
            "duration_s": float(duration_s),
            "count": len(self.events),
            "classes": {k: comp[k] for k in sorted(comp)},
            "fingerprint": traffic_fingerprint(self.events, duration_s),
            "events": [[t, key] for t, key in self.events],
        }


def save_traffic(path: str, artifact: dict) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")


def load_traffic(path: str) -> dict:
    """Load + validate a traffic artifact. Raises
    :class:`TrafficFormatError` with a human-readable reason on ANY
    defect — the driver turns it into a visible NOTE + exit 2, never a
    crash and never a silent partial replay."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        raise TrafficFormatError(f"cannot open {path}: {e}") from e
    except json.JSONDecodeError as e:
        raise TrafficFormatError(
            f"{path} is not valid JSON ({e}) — corrupted or not a "
            f"traffic artifact") from e
    if not isinstance(doc, dict) or doc.get("format") != TRAFFIC_FORMAT:
        raise TrafficFormatError(
            f"{path} is not a {TRAFFIC_FORMAT} artifact (format="
            f"{doc.get('format') if isinstance(doc, dict) else type(doc).__name__!r})")
    if doc.get("version") != TRAFFIC_VERSION:
        raise TrafficFormatError(
            f"{path} is traffic schema version {doc.get('version')!r}; "
            f"this build speaks version {TRAFFIC_VERSION} — re-record "
            f"with this build or replay with the one that recorded it")
    events = doc.get("events")
    if not isinstance(events, list) or any(
        not (isinstance(e, list) and len(e) == 2
             and isinstance(e[0], (int, float))
             and isinstance(e[1], str))
        for e in events
    ):
        raise TrafficFormatError(
            f"{path}: malformed event stream — want [[seconds, "
            f"class_key], ...]")
    if doc.get("count") != len(events):
        raise TrafficFormatError(
            f"{path}: count={doc.get('count')} does not match "
            f"{len(events)} events — truncated artifact")
    pairs = [(float(t), key) for t, key in events]
    if any(b[0] < a[0] for a, b in zip(pairs, pairs[1:])):
        raise TrafficFormatError(
            f"{path}: event times are not monotone — corrupted stream")
    want = traffic_fingerprint(pairs, float(doc.get("duration_s") or 0.0))
    if doc.get("fingerprint") != want:
        raise TrafficFormatError(
            f"{path}: fingerprint {doc.get('fingerprint')!r} does not "
            f"match the recomputed stream identity {want!r} — the "
            f"artifact was edited or corrupted")
    return doc


class ReplayArrivals:
    """Arrival process replaying a recorded stream byte-identically.

    Implements the loop's four-method arrival interface (``start`` /
    ``take_due`` / ``next_event`` / ``on_complete``) plus the
    ``draw_class`` hook the loop consults when present: class keys come
    from the recording, in admission order, instead of the seeded mix
    drawer — two replays of one artifact admit the exact same
    ``(time, class)`` sequence. ``on_complete`` is a no-op: replay is
    open-loop by construction even for closed-loop recordings, because
    the recorded admission times already encode the original
    completion gating."""

    def __init__(self, artifact: dict):
        events = artifact.get("events") or []
        self._rel = [float(t) for t, _k in events]
        self._keys = [str(k) for _t, k in events]
        self.duration_s = float(artifact.get("duration_s") or 0.0)
        self.fingerprint = artifact.get("fingerprint")
        self.classes = dict(artifact.get("classes") or {})
        self._t0: float | None = None
        self._i = 0  # next arrival to schedule
        self._j = 0  # next class key to hand out

    def start(self, t0: float) -> None:
        self._t0 = t0
        self._i = self._j = 0

    def take_due(self, now: float, limit: float | None = None) -> list[float]:
        if self._t0 is None:
            return []
        cutoff = now if limit is None else min(now, limit)
        due: list[float] = []
        while (self._i < len(self._rel)
               and self._t0 + self._rel[self._i] <= cutoff):
            due.append(self._t0 + self._rel[self._i])
            self._i += 1
        return due

    def next_event(self) -> float | None:
        if self._t0 is None or self._i >= len(self._rel):
            return None
        return self._t0 + self._rel[self._i]

    def on_complete(self, n: int, now: float) -> None:
        pass  # the recording already encodes any completion gating

    def draw_class(self) -> str | None:
        """The recorded class key for the next admitted arrival; None
        once exhausted (the loop falls back to its mix drawer — only
        reachable if something injects arrivals beyond the recording,
        e.g. chaos armed on top of a replay)."""
        if self._j >= len(self._keys):
            return None
        key = self._keys[self._j]
        self._j += 1
        return key
