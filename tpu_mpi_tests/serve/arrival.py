"""Request arrival processes for the serving loop.

Two canonical load models (the open-vs-closed distinction of Schroeder's
"Open Versus Closed" — conflating them is the classic benchmarking bug):

* **Open loop** (:class:`OpenLoopPoisson`): arrivals are scheduled by an
  external Poisson clock that does NOT care whether the system keeps up.
  A request's latency is measured from its *scheduled* arrival, so a
  stalled server accumulates queue delay instead of silently slowing the
  generator down (coordinated omission is impossible by construction).

* **Closed loop** (:class:`ClosedLoop`): a fixed population of
  ``concurrency`` logical clients, each issuing its next request the
  moment the previous one completes — the throughput-under-load probe.

Both are driven by the single-threaded serve loop through one small
interface: ``start(t0)`` anchors the process, ``take_due(now, limit)``
pops the arrival times that have come due, ``next_event()`` tells the
loop how long it may sleep, ``on_complete(n, now)`` feeds completions
back (a no-op for the open loop). Times are whatever monotonic clock the
loop uses; the processes never read a clock themselves, which is what
makes them deterministic under a seeded RNG and testable without one.

Pure stdlib by design (``random.Random``, no numpy/jax).
"""

from __future__ import annotations

import random


class OpenLoopPoisson:
    """Poisson arrivals at ``rate_hz``: exponential inter-arrival gaps
    from a seeded ``random.Random`` stream — two instances with the same
    (rate, seed) generate the same schedule (gated in tests)."""

    def __init__(self, rate_hz: float, seed: int = 0):
        if not rate_hz > 0:
            raise ValueError(f"rate_hz must be positive, got {rate_hz}")
        self.rate_hz = float(rate_hz)
        self._rng = random.Random(f"poisson:{seed}")
        self._next: float | None = None

    def start(self, t0: float) -> None:
        self._next = t0 + self._rng.expovariate(self.rate_hz)

    def take_due(self, now: float, limit: float | None = None) -> list[float]:
        """Arrival times scheduled at or before ``now`` (and at or before
        ``limit`` — the run deadline: arrivals past it are never
        generated, so a drain after the deadline terminates)."""
        if self._next is None:
            return []
        due: list[float] = []
        cutoff = now if limit is None else min(now, limit)
        while self._next <= cutoff:
            due.append(self._next)
            self._next += self._rng.expovariate(self.rate_hz)
        return due

    def next_event(self) -> float | None:
        return self._next

    def on_complete(self, n: int, now: float) -> None:
        pass  # open loop: completions never gate arrivals


class ClosedLoop:
    """``concurrency`` logical clients, each re-issuing on completion.

    ``start`` schedules the initial population at ``t0``; every
    completion re-arms that many clients at the completion time. The
    offered rate is whatever the system sustains — which is the point.
    """

    def __init__(self, concurrency: int):
        # no RNG here: a fixed population re-issuing on completion is
        # deterministic by construction (the mix drawer has the stream)
        if concurrency < 1:
            raise ValueError(
                f"concurrency must be >= 1, got {concurrency}"
            )
        self.concurrency = int(concurrency)
        self._pending: list[float] = []

    def start(self, t0: float) -> None:
        self._pending = [t0] * self.concurrency

    def take_due(self, now: float, limit: float | None = None) -> list[float]:
        cutoff = now if limit is None else min(now, limit)
        due = [t for t in self._pending if t <= cutoff]
        self._pending = [t for t in self._pending if t > cutoff]
        return due

    def next_event(self) -> float | None:
        return min(self._pending) if self._pending else None

    def on_complete(self, n: int, now: float) -> None:
        self._pending.extend([now] * n)
