"""Compile-cost capture: AOT compile wall-time + the compiler's own
cost/memory model, keyed by the tune-layer fingerprint.

The repo measures achieved seconds and GB/s everywhere, but until now no
span knew what the *compiler* thinks the op costs — so "fast as the
hardware allows" (ROADMAP) was unverifiable: achieved bandwidth had no
denominator. This module supplies it:

* :func:`compile_probe` — the AOT wrap point. Given a (jitted or plain)
  function and example args, it times ``fn.lower(*args).compile()``
  (``kind: "compile"`` JSONL span on the PR-2 wall clock, so
  ``tpumt-trace`` draws a compile track) and captures
  ``compiled.cost_analysis()`` (flops, bytes accessed) and
  ``compiled.memory_analysis()`` (temp/output/argument allocation
  sizes), tagging the record with the tune-layer fingerprint
  (:mod:`tpu_mpi_tests.tune.fingerprint`) and the device's peak HBM
  bandwidth where known. The probe compiles *in addition to* the plain
  execution path (jax's jit dispatch cache is separate from AOT) — it
  runs only under ``--telemetry``, dedupes per (label, arg-avals), and
  the persistent compilation cache (``--compile-cache``) makes the
  second compile nearly free. It never raises and never touches the
  measured fn's buffers (``lower``/``compile`` do not execute).
* a **cost registry + span provider**: the latest probe per label is
  kept in-process and registered as the telemetry layer's cost
  provider, so every later span whose ``op`` matches a probed label
  gets ``cost_bytes``/``cost_flops``/``model_gbps`` and — where a peak
  is known — ``roofline_frac`` (achieved cost-model bytes/s over peak
  bytes/s) attached to its JSONL record. ``tpumt-report`` joins the
  same records into the COMPILE table.

Module import is stdlib-only (jax loads inside the probe), keeping the
login-node CLI closure jax-free.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable

from tpu_mpi_tests.instrument import telemetry as _telemetry

#: published peak HBM bandwidth per device kind, GB/s — the roofline
#: denominator. Override/extend with TPU_MPI_PEAK_GBPS (a float) when
#: the device kind is missing or the pod's effective peak differs.
PEAK_HBM_GBPS = {
    "TPU v2": 700.0,
    "TPU v3": 900.0,
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1640.0,
    "TPU v6e": 1640.0,
}

_LOCK = threading.Lock()
#: label -> latest probe info (flops/bytes/compile seconds/fingerprint).
#: A label probed at MORE THAN ONE shape set (e.g. collbench sweeping an
#: op over payload sizes) is marked ``"ambiguous"``: spans cannot know
#: which shape a given call ran at, so attaching any single shape's cost
#: model would fabricate numbers — ambiguous labels attach nothing.
_REGISTRY: dict[str, dict[str, Any]] = {}
#: (label, aval-key) pairs already probed — one compile per shape set
_PROBED: set = set()


def peak_gbps() -> float | None:
    """Peak HBM GB/s for this process's devices: ``TPU_MPI_PEAK_GBPS``
    env override first, else the :data:`PEAK_HBM_GBPS` table by device
    kind (substring match). ``None`` when unknown (CPU, fake devices) —
    consumers then omit roofline percentages rather than fabricating
    them."""
    env = os.environ.get("TPU_MPI_PEAK_GBPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:
        return None
    for name, gbps in PEAK_HBM_GBPS.items():
        if name in kind or kind in name:
            return gbps
    return None


def _aval_key(a) -> tuple:
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is None and dtype is None:
        return (type(a).__name__,)
    return (tuple(shape or ()), str(dtype))


def _num(v) -> float | None:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if f == f else None


def _cost_analysis(compiled) -> dict[str, Any]:
    """Normalized ``cost_analysis()``: some jax versions return a list
    of per-computation dicts, newer ones a dict."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca if isinstance(ca, dict) else {}


def _memory_analysis(compiled) -> dict[str, int]:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    out = {}
    for rec_key, attr in (
        ("temp_bytes", "temp_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
        ("argument_bytes", "argument_size_in_bytes"),
        ("alias_bytes", "alias_size_in_bytes"),
        ("code_bytes", "generated_code_size_in_bytes"),
    ):
        v = getattr(ma, attr, None)
        if isinstance(v, (int, float)):
            out[rec_key] = int(v)
    return out


def _fingerprint(**ctx) -> str | None:
    try:
        from tpu_mpi_tests.tune.fingerprint import fingerprint

        return fingerprint(**ctx)
    except Exception:
        return None


def compile_probe(
    fn: Callable,
    args: tuple,
    label: str,
    phase: str | None = None,
    emit: Callable[[dict], None] | None = None,
    **ctx,
) -> dict[str, Any] | None:
    """AOT-compile ``fn(*args)``, record the compile span + cost model.

    No-op (returns the existing registry entry, or None) unless span
    telemetry is enabled — the probe costs a real compile, which is
    observability overhead a plain benchmark run must not pay. Dedupes
    per (label, arg shapes/dtypes). ``phase`` names the PhaseTimer
    phase / span op whose measured seconds this fn's runtime lands in,
    so ``tpumt-report`` can join compile cost against achieved time;
    it defaults to ``label``. ``ctx`` feeds the tune-layer fingerprint
    (dtype/shape/world context). Never raises; any failure (un-AOT-able
    fn, analysis unsupported) returns None with nothing emitted."""
    if not _telemetry.registry().enabled:
        return None
    key = (label,) + tuple(_aval_key(a) for a in args)
    with _LOCK:
        if key in _PROBED:
            return _REGISTRY.get(label)
        second_shape = any(k[0] == label for k in _PROBED)
        _PROBED.add(key)
    try:
        import jax

        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        t0_wall = time.time()
        t0 = time.perf_counter()
        compiled = jitted.lower(*args).compile()
        t1 = time.perf_counter()
        dt = t1 - t0
        ca = _cost_analysis(compiled)
        info: dict[str, Any] = {
            "label": label,
            "compile_s": dt,
            "flops": _num(ca.get("flops")),
            "bytes_accessed": _num(ca.get("bytes accessed")),
            "fingerprint": _fingerprint(**ctx),
        }
        info.update(_memory_analysis(compiled))
        peak = peak_gbps()
        if peak:
            info["peak_gbps"] = peak
        if second_shape:
            # the label now covers several shapes with different cost
            # models; no single model can be attributed to its spans
            info["ambiguous"] = True
        with _LOCK:
            _REGISTRY[label] = info
        _telemetry.set_cost_provider(cost_fields)
        record = {
            "kind": "compile",
            "phase": phase or label,
            "seconds": dt,
            "t_start": t0_wall,
            # wall end anchored to the monotonic duration (same
            # NTP-step argument as comm_span)
            "t_end": t0_wall + dt,
            "mono_start": t0,
            "mono_end": t1,
            **info,
        }
        (emit or _telemetry.emit)(record)
        return info
    except Exception:
        return None


def cost_info(label: str) -> dict[str, Any] | None:
    """Latest probe result for ``label`` (None when never probed)."""
    with _LOCK:
        return _REGISTRY.get(label)


def cost_fields(op: str, seconds: float | None) -> dict[str, Any]:
    """Span-attachable roofline fields for a measured execution of the
    probed fn ``op``: the cost model's flops/bytes, the model-implied
    achieved rates over the measured ``seconds``, and the roofline
    utilization where a peak is known. ``{}`` for unknown ops/invalid
    seconds — the telemetry layer merges this into span records.
    Labels probed at several shapes attach nothing (see the registry
    note): the span cannot say which shape it ran at."""
    info = cost_info(op)
    if not info or info.get("ambiguous") or not seconds or seconds <= 0:
        return {}
    out: dict[str, Any] = {}
    cb = info.get("bytes_accessed")
    cf = info.get("flops")
    if cb:
        out["cost_bytes"] = cb
        out["model_gbps"] = cb / seconds / 1e9
        peak = info.get("peak_gbps")
        if peak:
            out["roofline_frac"] = cb / seconds / 1e9 / peak
    if cf:
        out["cost_flops"] = cf
        out["model_gflops"] = cf / seconds / 1e9
    return out


def reset() -> None:
    """Drop all probe state (tests)."""
    with _LOCK:
        _REGISTRY.clear()
        _PROBED.clear()
