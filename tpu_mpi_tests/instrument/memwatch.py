"""HBM watermarks + live-buffer census: the memory axis of observability.

The reference's unified-vs-explicit memory comparison (``daxpy_nvtx.cu``,
PAPER §DAXPY pillar) is memory-side observability the time-domain layers
(PRs 1–2) never reproduced: spans know *when* an op ran but nothing knows
*what the HBM was doing* while it ran. This module is the missing
recorder, three pieces:

* :func:`device_memory_stats` — per-device allocator stats from
  ``device.memory_stats()`` (``bytes_in_use``, ``peak_bytes_in_use``,
  ``bytes_limit``), normalized to plain ints. CPU and fake devices
  return ``None``/``{}`` from the backend; callers get ``{}`` and every
  consumer degrades gracefully (census-only records, absent — never
  zero — result fields).
* :func:`live_array_census` — ``jax.live_arrays()`` bucketed by
  shape·dtype (count/bytes per bucket, top-K offenders by bytes): the
  answer to "what is actually holding the HBM", available on every
  backend including CPU.
* :class:`MemWatch` — the run-long recorder ``--memwatch`` arms: a
  low-rate sampler thread plus :mod:`~tpu_mpi_tests.instrument.timers`
  phase hooks, emitting ``kind: "mem"`` JSONL records stamped with the
  PR-2 wall clock (``t`` / ``t_start``/``t_end``) so they land on the
  shared cross-rank timeline — ``tpumt-trace`` renders them as Perfetto
  counter tracks, ``tpumt-report`` as the MEMORY table, and the
  watchdog dumps the same census when it fires.

Thread discipline: the sampler emits through the Reporter's
``jsonl``-backed sink, which serializes one locked ``write()`` per
record — this module itself never touches a file handle (the TPM601
hazard class). Module import is stdlib-only; jax loads lazily inside
the probe functions so the watchdog (and anything else stdlib-side) can
import this module on jax-less hosts.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

#: memory_stats fields worth recording (allocator dicts carry many more;
#: these are the watermark/capacity trio every consumer reads)
STATS_FIELDS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")

#: default census depth: top-K shape·dtype buckets by bytes (the
#: watchdog fire-dump contract is 8)
CENSUS_TOP_K = 8

#: default sampler period — low-rate by design: the sampler exists to
#: draw a counter track, not to profile allocation churn
SAMPLE_INTERVAL_S = 0.5


def device_memory_stats() -> dict[str, dict[str, int]]:
    """``{device_id: {bytes_in_use, peak_bytes_in_use, bytes_limit}}``
    for every local device whose backend reports allocator stats.

    Returns ``{}`` when jax is unavailable, the backend exposes no
    ``memory_stats()`` (CPU, fake devices return ``None``/``{}``), or
    the probe raises — never raises itself, so it is safe from the
    watchdog's timer thread and the sampler."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return {}
    out: dict[str, dict[str, int]] = {}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        fields = {
            k: int(stats[k])
            for k in STATS_FIELDS
            if isinstance(stats.get(k), (int, float))
        }
        if fields:
            out[str(getattr(d, "id", len(out)))] = fields
    return out


def _live_totals() -> tuple[int, int]:
    """(count, bytes) of live arrays — one walk, no bucketing: the cheap
    growth signal the phase hooks poll on backends without allocator
    stats. (0, 0) when jax is unavailable."""
    count = 0
    total = 0
    try:
        import jax

        for a in jax.live_arrays():
            try:
                if a.is_deleted():
                    continue
                total += int(a.size) * int(a.dtype.itemsize)
                count += 1
            except Exception:
                continue
    except Exception:
        pass
    return count, total


def live_array_census(top_k: int = CENSUS_TOP_K) -> dict[str, Any] | None:
    """Census of ``jax.live_arrays()`` bucketed by shape·dtype.

    Returns ``{"count": N, "bytes": B, "top": [{key, count, bytes}, …]}``
    with ``top`` holding the ``top_k`` buckets by total bytes (key shape
    ``"8192x8192·float32"``); ``bytes`` are logical global sizes
    (``size · itemsize``). ``None`` when jax is unavailable — the only
    case with nothing to report; an empty process reports 0 buffers."""
    try:
        import jax

        arrays = jax.live_arrays()
    except Exception:
        return None
    buckets: dict[str, list[int]] = {}
    count = 0
    total = 0
    for a in arrays:
        try:
            if a.is_deleted():
                continue
            nbytes = int(a.size) * int(a.dtype.itemsize)
            key = "x".join(str(s) for s in a.shape) or "scalar"
            key = f"{key}·{a.dtype.name}"
        except Exception:
            continue
        b = buckets.setdefault(key, [0, 0])
        b[0] += 1
        b[1] += nbytes
        count += 1
        total += nbytes
    top = sorted(buckets.items(), key=lambda kv: -kv[1][1])[: max(top_k, 0)]
    return {
        "count": count,
        "bytes": total,
        "top": [
            {"key": k, "count": c, "bytes": b} for k, (c, b) in top
        ],
    }


def mem_record(
    event: str = "sample",
    phase: str | None = None,
    top_k: int = 0,
    t_start: float | None = None,
    t_end: float | None = None,
) -> dict[str, Any]:
    """One ``kind: "mem"`` JSONL record: wall timestamp ``t`` (the PR-2
    clock the timeline merger offset-corrects), per-device watermarks
    when the backend reports them, and live-array totals (full top-K
    census only when ``top_k`` > 0 — it walks every live buffer).

    Degrades to census-only where ``memory_stats()`` is absent/empty
    (CPU, fake devices): no ``devices``/``bytes_in_use`` keys, never
    zeros that would read as a measured empty HBM."""
    rec: dict[str, Any] = {"kind": "mem", "event": event, "t": time.time()}
    if phase is not None:
        rec["phase"] = phase
    if t_start is not None:
        rec["t_start"] = t_start
    if t_end is not None:
        rec["t_end"] = t_end
    devices = device_memory_stats()
    if devices:
        rec["devices"] = devices
        rec["bytes_in_use"] = sum(
            d.get("bytes_in_use", 0) for d in devices.values()
        )
        rec["peak_bytes_in_use"] = max(
            d.get("peak_bytes_in_use", 0) for d in devices.values()
        )
    census = live_array_census(top_k if top_k > 0 else CENSUS_TOP_K)
    if census is not None:
        rec["live_count"] = census["count"]
        rec["live_bytes"] = census["bytes"]
        if top_k > 0:
            rec["census"] = census
    return rec


def watermark_lines(top_k: int = CENSUS_TOP_K) -> list[str]:
    """Human dump for hang/fire diagnostics: per-device watermarks plus
    the top-K live-array buckets. Best-effort and never raises — the
    caller is the watchdog's timer thread mid-hang."""
    lines: list[str] = []
    try:
        for dev, s in sorted(device_memory_stats().items()):
            parts = [f"HBM dev{dev}:"]
            for k in STATS_FIELDS:
                if k in s:
                    parts.append(f"{k}={s[k]}")
            lines.append(" ".join(parts))
    except Exception:
        pass
    try:
        census = live_array_census(top_k)
    except Exception:
        census = None
    if census is not None:
        lines.append(
            f"LIVE census: {census['count']} arrays, "
            f"{census['bytes']} bytes"
        )
        for e in census["top"]:
            lines.append(
                f"LIVE {e['key']}: n={e['count']} bytes={e['bytes']}"
            )
    return lines


class MemWatch:
    """Run-long memory recorder: a daemon sampler thread plus PhaseTimer
    hooks, both emitting ``kind: "mem"`` records through ``sink``.

    Record stream: one ``event: "start"`` record (with census) on
    :meth:`start`, ``event: "sample"`` records every ``interval_s``
    (watermarks + live totals, no full census — the sampler stays
    cheap), one ``event: "phase"`` record per phase *name* at its first
    exit (census included) and again whenever that phase raises the
    global peak watermark by >1% (hot-loop phases re-enter thousands of
    times; emitting every exit would swamp the JSONL for zero new
    information), and one ``event: "final"`` record (census) on
    :meth:`stop`. Phase records carry ``t_start``/``t_end`` plus the
    in-use delta and peak raise across the phase body. Non-emitting
    exits stay cheap by design — one allocator query (or one
    live-array walk where allocator stats are absent), never a full
    census — because hot-loop phases pay the hook per iteration.

    ``sink`` must serialize its own writes (the Reporter's ``jsonl``
    does: one locked ``write()`` per record) — the sampler thread and
    the main thread's phase hooks emit concurrently."""

    def __init__(
        self,
        sink: Callable[[dict], None],
        interval_s: float = SAMPLE_INTERVAL_S,
        top_k: int = CENSUS_TOP_K,
    ):
        self._sink = sink
        self._interval = max(float(interval_s), 0.02)
        self._top_k = top_k
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        # probed once at start(): whether this backend reports allocator
        # stats at all — phase begins skip the query where it never can
        # return anything (CPU/fake devices)
        self._has_device_stats = False
        # phase name -> {t0, devices at entry, emitted, last peak}
        self._phase_state: dict[str, dict[str, Any]] = {}

    # -- lifecycle ---------------------------------------------------

    def start(self) -> "MemWatch":
        from tpu_mpi_tests.instrument import timers

        self._has_device_stats = bool(device_memory_stats())
        timers.add_phase_hook(self._on_phase)
        self._emit(mem_record(event="start", top_k=self._top_k))
        self._thread = threading.Thread(
            target=self._run, name="tpumt-memwatch", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Idempotent: stop the sampler, detach the phase hooks, emit
        the final census record."""
        if self._stop.is_set():
            return
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        try:
            from tpu_mpi_tests.instrument import timers

            timers.remove_phase_hook(self._on_phase)
        except Exception:
            pass
        self._emit(mem_record(event="final", top_k=self._top_k))

    # -- internals ---------------------------------------------------

    def _emit(self, rec: dict) -> None:
        try:
            self._sink(rec)
        except Exception:
            pass  # observability must never fail the run

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self._emit(mem_record(event="sample"))

    def _on_phase(self, name: str, event: str) -> None:
        if event == "begin":
            with self._lock:
                st = self._phase_state.setdefault(name, {})
                st["t0"] = time.time()
                if self._has_device_stats:
                    st["devices"] = device_memory_stats()
            return
        if event != "end":
            return
        # cheap growth signal FIRST — a hot-loop phase pays this hook
        # every iteration, and most exits emit nothing: one allocator
        # query (or one no-bucketing live-array walk on backends with
        # no allocator stats), never a full census
        now = time.time()
        devices = device_memory_stats() if self._has_device_stats else {}
        if devices:
            peak = max(
                d.get("peak_bytes_in_use", 0) for d in devices.values()
            )
        else:
            live_count, live_bytes = _live_totals()
            peak = live_bytes
        with self._lock:
            st = self._phase_state.setdefault(name, {})
            first = not st.get("emitted")
            grew = peak > st.get("last_peak", 0) * 1.01
            if not (first or grew):
                return
            st["emitted"] = True
            st["last_peak"] = peak
            t_start = st.get("t0", now)
            begin = st.get("devices") or {}
        rec: dict[str, Any] = {
            "kind": "mem", "event": "phase", "phase": name,
            "t": now, "t_start": t_start, "t_end": now,
        }
        if devices:
            rec["devices"] = devices
            rec["bytes_in_use"] = sum(
                d.get("bytes_in_use", 0) for d in devices.values()
            )
            rec["peak_bytes_in_use"] = peak
            if begin:
                rec["delta_bytes"] = rec["bytes_in_use"] - sum(
                    d.get("bytes_in_use", 0) for d in begin.values()
                )
                # peaks are monotonic (current jaxlibs expose no reset
                # hook): the phase's raise is the watermark difference
                # across its body
                rec["peak_delta"] = peak - max(
                    d.get("peak_bytes_in_use", 0) for d in begin.values()
                )
            live_count, live_bytes = _live_totals()
        rec["live_count"] = live_count
        rec["live_bytes"] = live_bytes
        if first:
            census = live_array_census(self._top_k)
            if census is not None:
                rec["census"] = census
        self._emit(rec)
