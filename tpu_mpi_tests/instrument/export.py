"""OpenMetrics exposition + live heartbeat for the metrics registry.

Two small pieces, both stdlib-only and both strictly consumers of
:class:`~tpu_mpi_tests.instrument.metrics.MetricsRegistry`:

* :class:`MetricsExporter` — an ``http.server`` endpoint on a
  background daemon thread serving the registry as OpenMetrics /
  Prometheus text exposition at ``/metrics`` (armed by
  ``--metrics-port``; rank 0 by default, every rank with
  ``--metrics-all-ranks``, each at ``port + process_index``). Counters
  export with the ``_total`` sample suffix, rolling histograms as
  summaries (``quantile="0.5"/"0.99"`` + ``_count``/``_sum``), and the
  body ends with the OpenMetrics ``# EOF`` terminator, so both a
  Prometheus scraper and a plain ``curl`` mid-run read it.

* :class:`Heartbeat` — a daemon thread emitting periodic
  ``kind: "health" event: "heartbeat"`` records through the Reporter's
  sink: sequence number, uptime, record throughput, serve queue depth,
  HBM in-use, and the rolling p50/p99 of the all-ops latency
  histogram. The point is the trail, not the dashboard: a rank that
  dies mid-run leaves its last heartbeat in the JSONL, which is
  exactly the liveness cadence the ONLINE doctor
  (``tpumt-doctor --follow``) needs to tell "slow" from "gone" while
  the run is still executing. ``stop()`` emits one final heartbeat so
  a clean close is distinguishable from a kill.

Neither piece exists on a disarmed run (no ``--metrics-port`` — the
modules are never imported), preserving the PR-9 byte-identity
contract.
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from tpu_mpi_tests.instrument.metrics import MetricsRegistry

#: OpenMetrics content type served on /metrics (readable as plain text
#: by curl, parseable by Prometheus' OpenMetrics parser)
CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                "charset=utf-8")


def _escape_label(v) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _labels_text(labels: tuple, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _num(v) -> str:
    if v is None or v != v:
        return "NaN"
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_openmetrics(registry: MetricsRegistry) -> str:
    """The registry as OpenMetrics text exposition (one string,
    ``# EOF``-terminated). Counter samples carry the ``_total`` suffix,
    histograms export as summaries over their rolling window."""
    lines: list[str] = []
    for name, fam in registry.snapshot().items():
        kind = fam["type"]
        om_type = {"counter": "counter", "gauge": "gauge",
                   "histogram": "summary"}[kind]
        lines.append(f"# TYPE {name} {om_type}")
        for labels, value in fam["samples"]:
            if kind == "counter":
                lines.append(
                    f"{name}_total{_labels_text(labels)} {_num(value)}")
            elif kind == "gauge":
                lines.append(
                    f"{name}{_labels_text(labels)} {_num(value)}")
            else:
                for q, key in (("0.5", "p50"), ("0.99", "p99")):
                    extra = 'quantile="' + q + '"'
                    lines.append(
                        f"{name}{_labels_text(labels, extra)}"
                        f" {_num(value[key])}")
                lines.append(
                    f"{name}_count{_labels_text(labels)} "
                    f"{_num(value['count'])}")
                lines.append(
                    f"{name}_sum{_labels_text(labels)} "
                    f"{_num(value['sum'])}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class MetricsExporter:
    """Background-thread HTTP endpoint serving the registry at
    ``/metrics``. ``port=0`` binds an ephemeral port (tests); the bound
    port is readable as ``.port`` after :meth:`start`."""

    def __init__(self, registry: MetricsRegistry, port: int,
                 host: str = "0.0.0.0"):
        self._registry = registry
        self._host = host
        self.port = int(port)
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> "MetricsExporter":
        registry = self._registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = render_openmetrics(registry).encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                # wfile is this connection's own socket handle — one
                # handler instance per request, so the per-connection
                # threads the race detector pairs here never share it
                # (the ISSUE-13 sanctioned per-connection-wfile case;
                # formerly the same suppression under lexical TPM601)
                self.wfile.write(body)  # tpumt: ignore[TPM1601]

            def log_message(self, *args):  # scrapes must not spam stdout
                pass

        self._server = ThreadingHTTPServer((self._host, self.port),
                                           Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="tpumt-metrics",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()


class Heartbeat:
    """Periodic ``kind: "health" event: "heartbeat"`` records through
    ``sink``. Runs on its own daemon thread so a wedged main thread
    still leaves a trail — which is precisely how the online doctor
    tells a straggling rank (heartbeats keep coming, phases lag) from a
    dead one (heartbeats stop)."""

    def __init__(self, registry: MetricsRegistry,
                 sink: Callable[[dict], None],
                 interval_s: float = 1.0):
        self._registry = registry
        self._sink = sink
        self._interval = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._seq = 0

    def _record(self, final: bool = False) -> dict:
        reg = self._registry
        # GIL-atomic monotonic counter bump, and the only off-thread
        # caller is stop(), which join()s the heartbeat thread BEFORE
        # its final emit — ordered by happens-before, not by a lock
        self._seq += 1  # tpumt: ignore[TPM1601]
        rec = {
            "kind": "health", "event": "heartbeat", "seq": self._seq,
            "t": reg.wall(),
            "uptime_s": round(reg.wall() - reg.started_wall, 3),
        }
        if final:
            rec["final"] = True
        snap = reg.snapshot()

        def total(name):
            fam = snap.get(name)
            return sum(v for _l, v in fam["samples"]) if fam else None

        def gauge_max(name):
            fam = snap.get(name)
            return max((v for _l, v in fam["samples"]), default=None) \
                if fam else None

        records = total("tpumt_records")
        if records is not None:
            rec["records"] = int(records)
        depth = total("tpumt_serve_queue_depth")
        if depth is not None:
            rec["queue_depth"] = int(depth)
        hbm = gauge_max("tpumt_hbm_bytes_in_use")
        if hbm is None:
            hbm = gauge_max("tpumt_live_bytes")
        if hbm is not None:
            rec["hbm_bytes_in_use"] = int(hbm)
        lat = snap.get("tpumt_latency_seconds")
        if lat and lat["samples"]:
            _labels, q = lat["samples"][0]
            if q["count"]:
                rec["p50_ms"] = round(q["p50"] * 1e3, 3)
                rec["p99_ms"] = round(q["p99"] * 1e3, 3)
        return rec

    def _emit(self, final: bool = False) -> None:
        try:
            self._sink(self._record(final=final))
        except Exception:
            pass  # the heartbeat must never hurt the run it watches

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self._emit()

    def start(self) -> "Heartbeat":
        self._thread = threading.Thread(
            target=self._run, name="tpumt-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self._emit(final=True)  # the clean-close marker heartbeat
